"""Benchmark: meta-tasks/sec/chip on the flagship MAML++ train step.

Default workload = the SHIPPED flagship config
``experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json``
(BASELINE.json config #4 at its throughput-optimal documented operating
point: meta-batch 12/chip + bn_fast_math; docs/PERF.md records the
batch sweep): Mini-ImageNet 5-way 5-shot, 4-conv VGG backbone (48
filters), K=5 inner steps, SECOND-ORDER meta gradients, learnable
per-layer-per-step inner LRs, per-step batch-norm — the MAML++ hot path
(SURVEY.md §3.2), jitted as one XLA program with remat over inner steps.
The benched number is therefore reproducible from a shipped config by
construction: ``python bench.py`` ==
``python bench.py --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json``.

The executable is selected per epoch exactly as ``ExperimentBuilder``
does; we bench the STEADY-STATE epoch (the schedule's last): past the
multi-step-loss annealing window (``multi_step_loss_num_epochs=15``) the
step computes the target loss at the final inner step only, matching what
real training runs for epochs 15..100 (85% of the flagship schedule). The
MSL-window step (epochs 0..14, 4 extra per-step target forwards) measures
~17% slower (docs/PERF.md); run-weighted over the full schedule the
throughput is ~3% below the number printed here.

Metric: meta-tasks processed per second per chip (tasks = episodes through
the complete inner-loop adaptation + meta-gradient).

Baseline for ``vs_baseline``: the reference publishes no throughput numbers
(SURVEY.md §6). We use a documented estimate of the reference running its
own flagship config on a single A100: upstream reports ~1 day for a
mini-imagenet run of 100 epochs x 500 iters x meta-batch 2 on a paper-era
GPU (~2.3 tasks/s); scaling ~3x to A100-class hardware gives ~7 tasks/s.
We round UP to 8.0 tasks/s to bias the comparison against ourselves.
BASELINE.json's north-star target is 4x single-A100, i.e. vs_baseline >= 4.

Usage: python bench.py [--steps N] [--batch B] [--quick]
                       [--config experiment_config/<cfg>.json]
                       [--backend-timeout S]
Backend init is retried with bounded backoff (default up to 10 min,
subprocess probes so a wedged/hung tunnel can be escaped) before
failing — one transient tunnel outage must not zero a capture.
Prints the headline JSON line {"metric", "value", "unit",
"vs_baseline"} as soon as it is measured; enriched lines follow (each a
strict superset): the warm-start leg (`time_to_first_step_cold_s` /
`_warm_s` — null on the headline line, measured right after it), then
for the flagship workload the run-weighted whole-schedule throughput
measured across every executable the config's epoch schedule visits,
then the strict paper batch-8 operating point (`strict_b8_*` keys). The
LAST JSON line is authoritative. With
--config, any shipped workload is benched instead of the flagship (batch
and mesh re-shaped to the local device count, everything else as
shipped); "vs_baseline" is then null — the baseline estimate is for the
flagship workload only — and a "workload" key names the config.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.sources import (
    build_source, source_kind)
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, replicated_sharding, shard_batch)
from howtotrainyourmamlpytorch_tpu.meta.inner import Episode

# Documented single-A100 reference-throughput estimate (see module docstring).
BASELINE_TASKS_PER_SEC = 8.0

# The per-device-kind peak-FLOPs + HBM-bandwidth table lives in
# telemetry/profiler.py (DEVICE_PEAKS) — ONE table for bench MFU, the
# cost cards' roofline verdicts and scripts/perf_report.py. The
# MAML_PEAK_FLOPS / MAML_HBM_GBPS env overrides win over it (the r4
# lesson: a "TPU v5 lite" device string sustaining v5p-class matmul
# rates makes the table a default, not an oracle), and the artifact's
# `peak_flops_source` key records which one produced the MFU —
# "table" / "override" / "unknown" — so a quietly-wrong MFU against a
# guessed peak can no longer pass silently.


# Backend bring-up (outage retry, hang watchdog, compile cache) lives in
# the package (howtotrainyourmamlpytorch_tpu/utils/backend.py) — the
# trainer CLI needs the same resilience as the measurement tools.
# Re-exported here because every perf script and the retry unit tests
# import it from bench.
from howtotrainyourmamlpytorch_tpu.telemetry import (  # noqa: E402
    COMPILE_COUNT, COMPILE_SECONDS, MetricsRegistry)
from howtotrainyourmamlpytorch_tpu.utils.backend import (  # noqa: E402,F401
    init_backend, init_devices_with_watchdog,
    maybe_enable_compilation_cache, timed_compile, wait_for_backend)
from howtotrainyourmamlpytorch_tpu.utils.hlo_flops import (  # noqa: E402
    executable_flops)
from howtotrainyourmamlpytorch_tpu.telemetry import (  # noqa: E402
    profiler as profiler_mod)


def _compiled_flops(compiled) -> float:
    """Scan-trip-expanded hardware FLOPs of one execution of the
    compiled train step's PER-DEVICE module (the work one chip does for
    its batch_size/n_devices task shard).

    History (VERDICT r4 weak #1): this used to return
    ``cost_analysis()["flops"]`` raw, which counts every while/scan body
    ONCE — under-counting the shipped flagship ~12x at mb=12 (the
    microbatch scan) on top of the K-step inner scan. It now delegates
    to ``utils.hlo_flops.executable_flops``: the optimized HLO is walked
    with loop bodies multiplied by their trip counts, calibrated against
    XLA's own flat count so elementwise/exotic-conv flops stay priced by
    XLA. The result is invariant to ``task_microbatches``
    (tests/test_perf_tooling.py pins mb=1 vs mb=4 agreement).

    This is HARDWARE flops — it includes the remat recompute the
    executable actually performs — which is the honest numerator for a
    utilization figure ("how busy is the MXU"), unlike a paper
    model-FLOPs count that would credit recomputation as free. Returns
    0.0 when neither HLO text nor cost analysis is available.
    """
    return executable_flops(compiled)["flops"]


def flagship_config(batch_size: int, n_devices: int) -> MAMLConfig:
    return MAMLConfig(
        experiment_name="bench_flagship",
        dataset_name="mini_imagenet_full_size",
        image_height=84, image_width=84, image_channels=3,
        num_classes_per_set=5, num_samples_per_class=5,
        num_target_samples=3,
        batch_size=batch_size,
        cnn_num_filters=48, num_stages=4,
        number_of_training_steps_per_iter=5,
        number_of_evaluation_steps_per_iter=5,
        second_order=True,
        use_multi_step_loss_optimization=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        per_step_bn_statistics=True,
        mesh_shape=(1, n_devices),
        # Perf variants (scripts/perf_variants.py): block_outs remat +
        # folded-stat BN, +13% over the f32/nothing baseline, meta-
        # gradients equal to rtol 5e-3 (tests/test_outer.py).
        remat_policy="block_outs",
        bn_fast_math=True,
    )


def synthetic_batch(cfg: MAMLConfig, seed: int) -> Episode:
    """Device-shaped episode batch from host RNG (content irrelevant to
    throughput; shapes/dtypes match the real pipeline's wire format —
    raw uint8 pixels by default, normalized inside the jitted step)."""
    rng = np.random.RandomState(seed)
    n, k, t, b = (cfg.num_classes_per_set, cfg.num_samples_per_class,
                  cfg.num_target_samples, cfg.batch_size)
    h, w, c = cfg.image_shape
    if cfg.transfer_images_uint8:
        sx = rng.randint(0, 256, (b, n * k, h, w, c)).astype(np.uint8)
        tx = rng.randint(0, 256, (b, n * t, h, w, c)).astype(np.uint8)
    else:
        sx = rng.randn(b, n * k, h, w, c).astype(np.float32)
        tx = rng.randn(b, n * t, h, w, c).astype(np.float32)
    sy = np.tile(np.repeat(np.arange(n), k)[None], (b, 1)).astype(np.int32)
    ty = np.tile(np.repeat(np.arange(n), t)[None], (b, 1)).astype(np.int32)
    return Episode(sx, sy, tx, ty)


def measure_rate(step_fn, state, batch_ep, epoch, *, batch_size: int,
                 n_dev: int, steps: int = 30, warmup: int = 3,
                 windows: int = 3) -> float:
    """Median-of-windows pipelined throughput of a (compiled) train step,
    in tasks/s/chip — THE timing methodology, shared by bench.py,
    scripts/perf_ceiling.py and scripts/perf_resnet12_sweep.py so a fix
    here (warmup, window count, tunnel-latency handling) changes every
    reported number consistently.

    Warmup uses a host fetch as the fence (on the tunneled 'axon'
    backend ``block_until_ready`` has been observed returning without
    waiting). Timed windows do NO per-step sync: steps chain through the
    donated state and fetching each window's final loss forces the whole
    sequence while host dispatch runs ahead of the device. The median of
    3 windows drops the occasional 2-4x-slow window the shared tunnel
    serves under contention. Raises FloatingPointError on a non-finite
    loss.
    """
    for _ in range(warmup):
        state, metrics = step_fn(state, batch_ep, epoch)
        float(jax.device_get(metrics.loss))
    per_window = max(1, steps // windows)
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(per_window):
            state, metrics = step_fn(state, batch_ep, epoch)
        loss = float(jax.device_get(metrics.loss))
        dt = time.perf_counter() - t0
        if not np.isfinite(loss):
            raise FloatingPointError(f"non-finite loss {loss}")
        rates.append(batch_size * per_window / dt)
    return float(np.median(rates)) / n_dev


def load_workload(config_path: str, batch_override: int,
                  n_dev: int) -> MAMLConfig:
    """A shipped config re-shaped to the local device count: per-chip
    batch = the file's global batch over the file's mesh size; every
    execution knob (microbatching, remat, bn_fast_math, toggles) stays
    as shipped so the timed step IS the training step. A --batch
    override clamps task_microbatches to the gcd so the accumulation
    geometry stays as close to shipped as the requested batch allows."""
    base = MAMLConfig.from_json_file(config_path)
    per_chip = max(
        base.batch_size // max(int(np.prod(base.mesh_shape)), 1), 1)
    batch = batch_override or per_chip * n_dev
    cfg = base.replace(batch_size=batch, mesh_shape=(1, n_dev))
    return cfg.replace(
        task_microbatches=cfg.effective_task_microbatches(n_dev))


def quick_shrink(cfg: MAMLConfig, n_dev: int) -> MAMLConfig:
    """Tiny shapes for CI/CPU sanity — applied identically to the
    headline and (in quick mode) the strict-b8 leg, so --quick
    smoke-executes EVERY code path a real capture runs. Module-level
    and SHARED with scripts/tune_parity.py: the autotune parity gate
    must probe numerics at the same geometry ``bench --quick`` trials
    measured at, so the shapes live in exactly one place."""
    quick_batch = max(2 * n_dev, 2)
    cfg = cfg.replace(
        image_height=16, image_width=16,
        cnn_num_filters=8, num_stages=2,
        batch_size=quick_batch)
    # Same clamp as load_workload: the shipped configs'
    # task_microbatches need not divide the shrunken quick batch.
    return cfg.replace(
        task_microbatches=cfg.effective_task_microbatches(n_dev))


class Workload(NamedTuple):
    """A config built + AOT-compiled at its steady-state epoch — THE
    single build path behind the headline, run-weighted and strict-b8
    numbers (one place to fix sharding/epoch-pick rules)."""
    init: Any
    mesh: Any
    plan: Any
    state: Any
    batch_ep: Any
    epoch: Any
    compiled: Any
    bench_epoch: int


# XLA compiler options forwarded to every .compile() in this module
# (set from --compiler-option KEY=VAL; empty = compiler defaults). The
# tunneled backend rejects client-side XLA_FLAGS outright (unknown-flag
# abort in the client parser; TPU flags live in the SERVER compiler),
# but PJRT compiler_options pass through — this is the only working
# channel for per-experiment compiler knobs in this environment.
COMPILER_OPTIONS: dict = {}


# KEY=VAL validation moved to its canonical home in tune/space.py (the
# jax-free autotune driver and MAMLConfig validation share it);
# re-exported here because the perf scripts and the unit tests import
# it from bench. Same rules, same error text.
from howtotrainyourmamlpytorch_tpu.tune.space import (  # noqa: E402
    parse_compiler_options)


def resolve_compiler_options(cli_opts: dict, tuned_path,
                             cfg: MAMLConfig) -> "tuple[dict, dict, str]":
    """The effective (options, config_overrides, source) this capture
    runs — precedence: explicit ``--compiler-option`` CLI pairs
    ("cli"), an adopted autotune record via ``--tuned`` ("tuned" —
    the only source with a non-empty overrides channel: a winner is a
    POINT in the joint space), the benched config's own
    ``xla_compiler_options`` key ("config"), else compiler defaults
    ("none"). The TUNED.json is read exactly ONCE — both channels from
    one snapshot, so a concurrent atomic rewrite of the record can
    never yield a mixed point. CLI + --tuned together is a hard error,
    not a merge: a capture whose artifact says "tuned" must be running
    EXACTLY the adopted set. Raises ValueError on the conflict or an
    unreadable/rejected TUNED.json (record.read_tuned refuses
    adopted=false records)."""
    if cli_opts and tuned_path:
        raise ValueError(
            "--compiler-option and --tuned are mutually exclusive: the "
            "artifact must attribute the flag set to one source")
    if cli_opts:
        return dict(cli_opts), {}, "cli"
    if tuned_path:
        opts, overrides = read_tuned_record(tuned_path)
        return opts, overrides, "tuned"
    if cfg.xla_compiler_options:
        return dict(cfg.xla_compiler_options_dict), {}, "config"
    return {}, {}, "none"


def read_tuned_record(tuned_path: str) -> "tuple[dict, dict]":
    """(xla_compiler_options, config_overrides) of an ADOPTED autotune
    record. A winner is a POINT in the joint space — flag set AND
    structural overrides — so a capture labeled "tuned" must apply
    both; returning only the flags would bench the untuned structural
    config under a "tuned" label (r13 review catch). Raises ValueError
    on a rejected/malformed record (record.read_tuned refuses
    adopted=false)."""
    from howtotrainyourmamlpytorch_tpu.tune.record import read_tuned
    doc = read_tuned(tuned_path)
    opts = doc.get("xla_compiler_options") or {}
    overrides = doc.get("config_overrides") or {}
    if not isinstance(opts, dict) or not isinstance(overrides, dict):
        raise ValueError(
            f"--tuned {tuned_path!r}: xla_compiler_options / "
            f"config_overrides are not mappings")
    return {str(k): str(v) for k, v in opts.items()}, dict(overrides)


def apply_tuned_overrides(cfg: MAMLConfig, overrides: dict,
                          n_dev: int) -> MAMLConfig:
    """The adopted structural overrides applied to a benched workload,
    with ``task_microbatches`` re-clamped at THIS box's geometry (the
    load_workload/quick-shrink batch may differ from the sweep's) so
    the executed config matches what is recorded. Unknown keys raise
    (MAMLConfig.replace is a dataclass replace — a typo'd override
    must not vanish)."""
    if not overrides:
        return cfg
    try:
        cfg = cfg.replace(**overrides)
    except TypeError as e:
        raise ValueError(f"--tuned config_overrides: {e}") from None
    return cfg.replace(
        task_microbatches=cfg.effective_task_microbatches(n_dev))


def build_steady_state(cfg: MAMLConfig, devices,
                       registry: MetricsRegistry = None) -> Workload:
    """Build cfg's steady-state (last-epoch) train step: by definition an
    executable real training runs, past every annealing boundary that is
    ever crossed (DA's switch to second order, MSL's window), selected
    exactly as ExperimentBuilder does per epoch. The compiled executable
    serves warmup, the timed windows AND the FLOPs cost analysis. The
    compile goes through ``timed_compile`` so compile cost lands in the
    artifact's ``compile_seconds``/``compile_count`` keys."""
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, devices)
    plan = make_sharded_steps(cfg, apply, mesh)
    bench_epoch = max(cfg.total_epochs - 1, 0)
    train = plan.train_steps[(cfg.use_second_order(bench_epoch),
                              cfg.use_msl(bench_epoch))]
    state = jax.device_put(init_train_state(cfg, init,
                                            jax.random.PRNGKey(0)),
                           replicated_sharding(mesh))
    batch_ep = shard_batch(synthetic_batch(cfg, 0), mesh)
    epoch = jnp.float32(bench_epoch)
    compiled = timed_compile(train.lower(state, batch_ep, epoch),
                             registry=registry,
                             compiler_options=COMPILER_OPTIONS or None)
    return Workload(init, mesh, plan, state, batch_ep, epoch, compiled,
                    bench_epoch)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30,
                    help="timed outer steps, rounded DOWN to a multiple of "
                         "3 (split into 3 median windows; values <3 still "
                         "run 3 steps, one per window)")
    ap.add_argument("--batch", type=int, default=0,
                    help="meta-batch size (0 = auto: 12 per device)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for CI/CPU sanity (not a real bench)")
    ap.add_argument("--config", default=None, metavar="JSON",
                    help="bench an experiment_config/*.json workload "
                         "instead of the flagship (way/shot/backbone/"
                         "steps/toggles from the file; batch and mesh "
                         "from --batch / the local device count)")
    ap.add_argument("--no-run-weighted", action="store_true",
                    help="skip timing the schedule's other executables "
                         "(MSL window / first-order phases) for the "
                         "vs_baseline_run_weighted key")
    ap.add_argument("--no-strict-b8", action="store_true",
                    help="skip the strict paper batch-8 operating point "
                         "leg (the strict_b8_* keys)")
    ap.add_argument("--compiler-option", action="append", default=[],
                    metavar="KEY=VAL",
                    help="XLA compiler option forwarded via PJRT "
                         "compiler_options to every compile (repeatable; "
                         "e.g. xla_tpu_scoped_vmem_limit_kib=65536). "
                         "Client-side XLA_FLAGS do NOT reach the "
                         "tunneled server compiler — this does.")
    ap.add_argument("--tuned", default=None, metavar="TUNED.json",
                    help="apply an ADOPTED autotune flag set "
                         "(scripts/autotune.py winner record; refuses "
                         "adopted=false records). Mutually exclusive "
                         "with --compiler-option; the artifact's "
                         "compiler_options_source says which applied.")
    ap.add_argument("--no-warm-start", action="store_true",
                    help="skip the AOT warm-start leg (the "
                         "time_to_first_step_cold_s/_warm_s keys); it "
                         "compiles the train step's undonated twin — "
                         "one extra full compile per capture")
    ap.add_argument("--backend-timeout", type=float, default=600.0,
                    help="seconds to poll for JAX backend availability "
                         "before failing (tunnel outages are transient; "
                         "0 = no retry, fail on first init error)")
    args = ap.parse_args()
    try:
        parsed_options = parse_compiler_options(args.compiler_option)
        # Fast-fail resolution of the cli/tuned sources BEFORE backend
        # init (a malformed option or rejected TUNED.json must not
        # cost a backend bring-up); the "config" source can only
        # resolve after the workload config loads, below.
        (effective_options, tuned_overrides,
         options_source) = resolve_compiler_options(
            parsed_options, args.tuned, MAMLConfig())
    except (ValueError, OSError) as e:
        print(json.dumps({"error": str(e)}))
        return 1
    COMPILER_OPTIONS.clear()
    COMPILER_OPTIONS.update(effective_options)

    devices = init_backend(args.backend_timeout)
    # Compile telemetry (docs/PERF.md § Observability): every AOT
    # executable build in this tool goes through timed_compile into this
    # registry, so the artifact separates compile cost from the
    # steady-state rate without depending on the jax.monitoring hook.
    registry = MetricsRegistry()
    n_dev = len(devices)
    # No --config: bench the shipped flagship operating point (see module
    # docstring) so the headline number IS a shipped-config number.
    repo = os.path.dirname(os.path.abspath(__file__))
    config_path = args.config or os.path.join(
        repo, "experiment_config",
        "mini-imagenet_maml++_5-way_5-shot_DA_b12.json")
    cfg = load_workload(config_path, args.batch, n_dev)
    if args.quick:
        cfg = quick_shrink(cfg, n_dev)
        args.steps = min(args.steps, 3)
    if options_source == "none":
        # Re-resolve now that the workload config is loaded — the
        # "config" source (a JSON carrying its own adopted flag set)
        # can only be known here, and the precedence rules must have
        # exactly ONE home (cli/tuned already resolved + fast-failed
        # above, so this can only return "config" or "none").
        effective_options, _, options_source = resolve_compiler_options(
            {}, None, cfg)
        COMPILER_OPTIONS.update(effective_options)
    if options_source == "tuned":
        # The adopted point is flags AND structural overrides; apply
        # both so the "tuned" label means the capture ran the winner.
        try:
            cfg = apply_tuned_overrides(cfg, tuned_overrides, n_dev)
        except ValueError as e:
            print(json.dumps({"error": str(e)}))
            return 1
    # Single-channel discipline: this tool forwards the effective
    # options explicitly at every timed_compile; strip the config copy
    # so the jit level doesn't carry a second (identical) set.
    cfg = cfg.replace(xla_compiler_options=())

    # Dataset open probe (datastore/ subsystem, docs/DATA.md): resolve
    # the TRAIN split's image source exactly as the training loader
    # would, timed. With a packed shard present this is an O(header)
    # mmap open; without one it is the os.walk index (+ eager decode
    # under load_into_memory) or the synthetic fallback — so the packed
    # cold-start win is a number in the bench trajectory, not a claim.
    # Fail-soft: a broken dataset mount must not zero a throughput
    # capture (the timed step uses synthetic batches regardless).
    t0 = time.perf_counter()
    try:
        dataset_source_kind = source_kind(build_source(cfg, "train"))
    except Exception as e:  # noqa: BLE001
        dataset_source_kind = f"error:{type(e).__name__}"
    dataset_open_seconds = round(time.perf_counter() - t0, 6)

    # One build path (build_steady_state) for every number this tool
    # prints; for the flagship (total_epochs 100, DA boundary -1, MSL
    # window 15) the steady state is the second-order, final-step-loss
    # executable of epochs 15..99.
    wl = build_steady_state(cfg, devices, registry)
    init, mesh, plan = wl.init, wl.mesh, wl.plan
    state, batch_ep, epoch, compiled = (wl.state, wl.batch_ep, wl.epoch,
                                        wl.compiled)

    # Timing methodology lives in measure_rate (shared with the perf
    # scripts): pipelined dispatch, 3-window median, fetch-as-fence.
    try:
        per_chip = measure_rate(compiled, state, batch_ep, epoch,
                                batch_size=cfg.batch_size, n_dev=n_dev,
                                steps=args.steps)
    except FloatingPointError as e:
        print(json.dumps({"error": str(e)}))
        return 1
    # Checkpoint keys (ckpt/ subsystem, docs/CHECKPOINT.md): the cost of
    # ONE synchronous epoch save of this workload's state through the
    # real CheckpointManager path (device_get + msgpack + MAMLCKP1
    # framing + fsync'd atomic write + manifest commit, to a temp dir),
    # and the fraction of one epoch a synchronous save would stall the
    # training thread — the number ckpt_async=1 exists to erase
    # (blocking_frac ~ save / (save + epoch) at this measured rate).
    # Fail-soft null: a broken temp mount must not zero the capture.
    ckpt_save_seconds = ckpt_blocking_frac = None
    try:
        import shutil
        import tempfile
        from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
            CheckpointManager)
        ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
        try:
            # Fresh state: the timed loop DONATED the benched one.
            st_ckpt = init_train_state(cfg, init, jax.random.PRNGKey(0))
            t0 = time.perf_counter()
            CheckpointManager(ckpt_dir).save(st_ckpt, 0, 0, 0.0)
            ckpt_save_seconds = round(time.perf_counter() - t0, 6)
            epoch_seconds = (cfg.total_iter_per_epoch * cfg.batch_size
                             / (per_chip * n_dev))
            ckpt_blocking_frac = round(
                ckpt_save_seconds / (ckpt_save_seconds + epoch_seconds),
                6)
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    except Exception:  # noqa: BLE001 — observability key, never fatal
        pass
    # Warm-start keys (parallel/aot.py): measured AFTER the headline
    # print below — the leg costs a full extra compile of the headline
    # program, and the headline must already be on stdout if a kill
    # lands mid-compile (the same discipline as the run-weighted legs).
    # Null at first print; the enriched lines that follow carry the
    # measured values, and the authoritative LAST line is a strict
    # superset of everything measured before any hiccup.
    time_to_first_step_cold_s = time_to_first_step_warm_s = None
    # The baseline estimate is for the FLAGSHIP workload (either batch
    # variant); a ratio against it means nothing for other configs.
    is_flagship = cfg.experiment_name.startswith(
        "mini-imagenet_maml++_5-way_5-shot_DA")
    out = {
        "metric": "meta_tasks_per_sec_per_chip",
        "value": round(per_chip, 3),
        "unit": "tasks/s/chip",
        # Algorithm identity (meta/algos/ registry): echoed from the
        # config, never null — a BENCH_* row must say WHICH algorithm's
        # train step it timed (maml++/fomaml/anil/reptile compile
        # different executables; docs/ALGORITHMS.md).
        "meta_algorithm": cfg.meta_algorithm,
        "vs_baseline": (round(per_chip / BASELINE_TASKS_PER_SEC, 3)
                        if is_flagship else None),
        # Observability keys (additive — the metric contract above is
        # unchanged): AOT compile cost of the headline executable (later
        # legs compile more, but the headline keys are frozen at first
        # print), and the feed-stall fraction of the timed loop —
        # structurally 0.0 here because bench redispatches one
        # device-resident synthetic batch; real-training feed stalls are
        # reported by scripts/telemetry_report.py from events.jsonl.
        "compile_seconds": round(
            registry.counter(COMPILE_SECONDS).value, 3),
        "compile_count": int(registry.counter(COMPILE_COUNT).value),
        # Flag-set attribution (autotune subsystem, docs/PERF.md §
        # Autotune): the PJRT compiler options every compile in this
        # capture ran with, and where they came from — "cli"
        # (--compiler-option), "tuned" (--tuned TUNED.json), "config"
        # (the workload JSON's xla_compiler_options key) or "none".
        # A BENCH_* row is now attributable to its exact flag set.
        "compiler_options": effective_options,
        "compiler_options_source": options_source,
        "feed_stall_frac": 0.0,
        # Serving keys (serve/ subsystem): part of the artifact schema
        # so one consumer reads train and serve captures uniformly, but
        # this tool benches the TRAIN step — always null here. The
        # non-null producer is scripts/serve_bench.py (same key names,
        # same last-JSON-line contract). The meta_tasks_per_sec_per_chip
        # contract above is unchanged.
        "serve_latency_p50_ms": None,
        "serve_latency_p95_ms": None,
        "serve_cache_hit_frac": None,
        # Data-plane keys (datastore/ subsystem): cold-start cost and
        # kind of the config's TRAIN image source, measured above —
        # always non-null (the probe is fail-soft into an error string).
        "dataset_open_seconds": dataset_open_seconds,
        "dataset_source_kind": dataset_source_kind,
        # Health keys (telemetry/health.py): null unless the benched
        # config enables health_metrics_every_n_steps (the serve-field
        # convention — same artifact schema either way, non-null only
        # when the producing subsystem ran). Filled below, before the
        # headline print, when enabled.
        "outer_grad_norm": None,
        "health_overhead_frac": None,
        # Checkpoint keys (ckpt/ subsystem): one measured synchronous
        # save of THIS workload's state + the epoch fraction it would
        # stall (fail-soft null on error, measured above).
        "ckpt_save_seconds": ckpt_save_seconds,
        "ckpt_blocking_frac": ckpt_blocking_frac,
        # Warm-start keys (parallel/aot.py): first-step latency paying
        # the full trace+lower+compile (cold) vs an AOT-store
        # deserialize (warm) of the SAME headline executable — the
        # restart cost the prewarm pipeline erases. Null HERE by design:
        # the leg costs an extra compile and runs after the headline
        # print (kill-resilience); the later enriched lines — and the
        # authoritative LAST line — carry the measured values. Fail-soft
        # null where executable serialization is unavailable.
        "time_to_first_step_cold_s": time_to_first_step_cold_s,
        "time_to_first_step_warm_s": time_to_first_step_warm_s,
        # Perf-lab keys (telemetry/profiler.py, docs/PERF.md § Where
        # the time goes): one jax.profiler-captured window over the
        # headline executable, parsed into the wall-time split and the
        # top device-time executable's roofline verdict. Null at first
        # print (the leg runs after the headline, kill-resilience);
        # the enriched lines carry them measured.
        "mfu_compute_frac": None,
        "dispatch_gap_frac": None,
        "top_executable": None,
        "top_executable_bound": None,
    }
    if cfg.health_metrics_every_n_steps > 0:
        # The headline executable ALREADY computes the diagnostics
        # in-graph (make_train_step keys on the config), so the headline
        # rate IS the health-on rate; one extra step on a fresh state
        # fetches the outer-grad norm, and a brief health-off leg prices
        # the overhead the diagnostics add. Fail-soft: the headline
        # numbers must survive any hiccup here.
        try:
            st_h = jax.device_put(
                init_train_state(cfg, init, jax.random.PRNGKey(0)),
                replicated_sharding(mesh))
            _, m = compiled(st_h, batch_ep, epoch)
            out["outer_grad_norm"] = round(
                float(jax.device_get(m.health["grad_norm"])), 6)
            wl_off = build_steady_state(
                cfg.replace(health_metrics_every_n_steps=0), devices,
                registry)
            rate_off = measure_rate(
                wl_off.compiled, wl_off.state, wl_off.batch_ep,
                wl_off.epoch, batch_size=cfg.batch_size, n_dev=n_dev,
                steps=min(9, args.steps))
            # Negative values are measurement noise, reported honestly.
            out["health_overhead_frac"] = round(1.0 - per_chip / rate_off,
                                                4)
        except Exception as e:  # noqa: BLE001
            out["health_error"] = f"{type(e).__name__}: {e}"
    # Utilization anchor (VERDICT r1): FLOPs of the timed executable vs
    # the chip's peak bf16 rate — makes the throughput claim absolute
    # instead of relative to a self-estimated baseline. Scan-trip-
    # expanded (VERDICT r4 weak #1): invariant to task_microbatches.
    # The count is per-device, covering batch_size/n_dev tasks.
    fl = executable_flops(compiled)
    flops = fl["flops"]
    peaks = profiler_mod.resolve_peaks(
        getattr(devices[0], "device_kind", ""))
    peak = peaks["peak_flops"]
    out["peak_flops_source"] = peaks["source"]
    if flops > 0:
        local_tasks = max(cfg.batch_size // n_dev, 1)
        out["flops_per_task"] = round(flops / local_tasks)
        out["flops_source"] = fl["source"]
        if peak > 0:
            out["mfu"] = round(per_chip * flops / local_tasks / peak, 4)
    if "parse_error" in fl:
        # A failed HLO walk degrades to the loop-flat XLA count — the
        # very under-count r5 fixed — so it must be visible, not silent.
        out["flops_parse_error"] = fl["parse_error"]
    # Trip-count tripwire (ADVICE r5 / VERDICT Next #6): every detected
    # loop bound must be one of the config's known scan extents — the K
    # inner steps (train/eval, and the unroll quotient), the microbatch
    # accumulation count — or the heuristic misread a constant and the
    # flops/mfu keys above are silently wrong. Warnings ride the
    # artifact; they never zero a capture.
    from howtotrainyourmamlpytorch_tpu.utils.hlo_flops import (
        parse_trip_overrides, verify_trip_counts)
    k_train = cfg.number_of_training_steps_per_iter
    expected_trips = {k_train,
                      cfg.number_of_evaluation_steps_per_iter,
                      cfg.effective_task_microbatches(n_dev)}
    if cfg.inner_unroll > 1 and k_train % cfg.inner_unroll == 0:
        expected_trips.add(k_train // cfg.inner_unroll)
    try:
        overridden = parse_trip_overrides(
            os.environ.get("PERF_CEILING_TRIPS", ""))
    except ValueError:
        overridden = {}  # counter init already surfaced the parse error
    trip_warnings = verify_trip_counts(fl.get("trip_counts") or {},
                                       expected_trips,
                                       overridden=overridden)
    if trip_warnings:
        out["flops_trip_warnings"] = trip_warnings
    # Print the headline IMMEDIATELY: the run-weighted legs below cost
    # up to two more executable compiles, and if anything (or anyone)
    # kills the process mid-compile the artifact must already hold the
    # headline. The enriched line printed afterwards is a strict
    # superset; the LAST JSON line on stdout is authoritative.
    print(json.dumps({**out, "workload": cfg.experiment_name}), flush=True)
    # Perf-lab leg (telemetry/profiler.py, docs/PERF.md § Where the
    # time goes): capture ONE profiled window of a few headline-
    # executable steps and split its wall time into device compute vs
    # dispatch gap, then attach the executable's roofline verdict from
    # its cost card. No extra compile (the headline executable is
    # reused on a fresh state — the timed loop donated the benched
    # one), so this runs immediately after the headline print.
    # mfu_compute_frac is the fraction of window wall-clock ANY device
    # spent executing — the occupancy ceiling on MFU: mfu can never
    # exceed mfu_compute_frac x (achieved-FLOPs/s / peak at full
    # occupancy), so a low value says "dispatch/idle", a high value
    # says "the kernels themselves are slow". Fail-soft: a backend
    # that cannot trace leaves the keys null.
    try:
        card = profiler_mod.cost_card_from_compiled(
            "bench_train", compiled,
            device_kind=getattr(devices[0], "device_kind", ""),
            peaks=peaks)
        region_indexes = {}
        try:
            module, index = profiler_mod.region_index_from_hlo(
                compiled.as_text())
            if module:
                region_indexes[module] = index
        except Exception:  # noqa: BLE001
            pass
        st_prof = jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)),
            replicated_sharding(mesh))

        def _profiled_steps(state=st_prof, n=3):
            for _ in range(n):
                state, m = compiled(state, batch_ep, epoch)
            float(jax.device_get(m.loss))

        summary = profiler_mod.capture_window(_profiled_steps,
                                              region_indexes)
        profiler_mod.attach_roofline(
            summary, {"bench_train": card}, steps=3)
        out["mfu_compute_frac"] = round(
            summary["device_compute_frac"], 4)
        out["dispatch_gap_frac"] = round(
            summary["dispatch_gap_frac"], 4)
        out["top_executable"] = summary.get("top_executable")
        out["top_executable_bound"] = card.get("bound", "unknown")
    except Exception as e:  # noqa: BLE001 — observability keys; the
        # headline (already printed) must survive, but the miss stays
        # visible in the artifact.
        out["perf_profile_error"] = f"{type(e).__name__}: {e}"
    out["workload"] = cfg.experiment_name
    print(json.dumps(out), flush=True)
    # Warm-start leg (parallel/aot.py, docs/PERF.md § Cold start & warm
    # restarts): time-to-first-step cold vs warm through a REAL AOT
    # store round trip. The store holds the UNDONATED twin of the train
    # step (parallel/mesh.py § MeshPlan — deserialized donating
    # executables are unsafe on this jaxlib), so the cold leg pays the
    # twin's own trace+lower+compile — exactly what a cold run with the
    # store enabled pays — and the warm leg deserializes it back. One
    # extra compile per capture; --no-warm-start skips it. Fail-soft
    # null: a backend without executable serialization must not zero
    # the capture.
    if not args.no_warm_start:
        try:
            import shutil
            import tempfile
            from howtotrainyourmamlpytorch_tpu.parallel import (
                aot as aot_mod)
            aot_dir = tempfile.mkdtemp(prefix="bench_aot_")
            try:
                store = aot_mod.AOTStore(
                    aot_dir, aot_mod.store_fingerprint(cfg, mesh),
                    doc=aot_mod.fingerprint_doc(cfg, mesh))
                bench_key = (cfg.use_second_order(wl.bench_epoch),
                             cfg.use_msl(wl.bench_epoch))
                twin = plan.aot_train_steps[bench_key]

                def one_step_seconds(step_fn) -> float:
                    st = jax.device_put(
                        init_train_state(cfg, init,
                                         jax.random.PRNGKey(0)),
                        replicated_sharding(mesh))
                    t0 = time.perf_counter()
                    _, m = step_fn(st, batch_ep, epoch)
                    float(jax.device_get(m.loss))
                    return time.perf_counter() - t0

                # Avals, not the live state: the timed loop above
                # DONATED wl.state's buffers.
                savals = aot_mod.state_avals(wl.state, mesh)
                bavals = aot_mod.episode_aval(cfg, mesh, cfg.padded_batch_size)
                t0 = time.perf_counter()
                twin_compiled = timed_compile(
                    twin.lower(savals, bavals, aot_mod.epoch_aval()),
                    registry=registry,
                    compiler_options=COMPILER_OPTIONS or None)
                build_s = time.perf_counter() - t0
                time_to_first_step_cold_s = round(
                    build_s + one_step_seconds(twin_compiled), 6)
                if not store.save("bench_train", twin_compiled):
                    raise RuntimeError(
                        "executable serialization unavailable")
                t0 = time.perf_counter()
                loaded = store.load("bench_train")
                load_seconds = time.perf_counter() - t0
                if loaded is not None:
                    time_to_first_step_warm_s = round(
                        load_seconds + one_step_seconds(loaded), 6)
            finally:
                shutil.rmtree(aot_dir, ignore_errors=True)
        except Exception:  # noqa: BLE001 — observability keys, never
            pass           # fatal
        out["time_to_first_step_cold_s"] = time_to_first_step_cold_s
        out["time_to_first_step_warm_s"] = time_to_first_step_warm_s
        out["workload"] = cfg.experiment_name
        print(json.dumps(out), flush=True)
    # Run-weighted throughput over the config's REAL schedule (VERDICT
    # r2 weak #5: pin the whole-run number in the BENCH artifact, not
    # just PERF.md prose). Epochs group into distinct executables by
    # their (second_order, use_msl) key — for the flagship: 15 MSL
    # first-order epochs, 25 first-order steady, 60 second-order steady.
    # Each non-headline executable is timed briefly; the whole-run rate
    # is the epoch-weighted harmonic mean (equal tasks per epoch).
    # Fail-soft: the headline line must survive any hiccup here.
    bench_epoch = wl.bench_epoch
    # --quick runs this leg too (tiny shapes, minimal steps): every
    # capture path executes in CI or it breaks on capture day.
    if is_flagship and not args.no_run_weighted:
        try:
            keys = {}
            for e in range(cfg.total_epochs):
                k = (cfg.use_second_order(e), cfg.use_msl(e))
                keys[k] = keys.get(k, 0) + 1
            bench_key = (cfg.use_second_order(bench_epoch),
                         cfg.use_msl(bench_epoch))
            inv_sum = keys.get(bench_key, 0) / per_chip
            for k, n_epochs in keys.items():
                if k == bench_key:
                    continue
                # Fresh state per leg: the previous timed loop DONATED
                # its state buffers. Representative epoch = first epoch
                # the schedule runs this executable at.
                st = jax.device_put(
                    init_train_state(cfg, init, jax.random.PRNGKey(0)),
                    replicated_sharding(mesh))
                rep = jnp.float32(next(
                    e for e in range(cfg.total_epochs)
                    if (cfg.use_second_order(e), cfg.use_msl(e)) == k))
                other = timed_compile(
                    plan.train_steps[k].lower(st, batch_ep, rep),
                    registry=registry,
                    compiler_options=COMPILER_OPTIONS or None)
                rate = measure_rate(other, st, batch_ep, rep,
                                    batch_size=cfg.batch_size,
                                    n_dev=n_dev,
                                    steps=min(9, args.steps))
                inv_sum += n_epochs / rate
            rw = cfg.total_epochs / inv_sum
            out["run_weighted_tasks_per_sec_per_chip"] = round(rw, 3)
            out["vs_baseline_run_weighted"] = round(
                rw / BASELINE_TASKS_PER_SEC, 3)
        except Exception as e:  # noqa: BLE001 — headline must survive,
            # but a swallowed divergence (non-finite loss in a shipped
            # executable) must still be visible in the artifact.
            out["run_weighted_error"] = f"{type(e).__name__}: {e}"
        out["workload"] = cfg.experiment_name
        print(json.dumps(out), flush=True)
    # Strict paper batch-8 operating point (VERDICT r3 item 6: the 4x
    # gate has been argued three ways across rounds — emit headline,
    # run-weighted AND strict-b8 in one machine-readable object every
    # default run). This is the shipped ..._DA.json config: meta-batch
    # 8/chip exactly as the paper trains, at ITS shipped microbatching.
    # Fail-soft like run-weighted; the LAST JSON line stays a strict
    # superset of everything measured before the hiccup. Gated on
    # is_flagship (NOT on --config absence) so the docstring's
    # equivalence `python bench.py == python bench.py --config
    # ..._DA_b12.json` holds key-for-key; skipped when the benched
    # workload IS the strict-b8 config (it would re-measure itself).
    # --quick still runs this leg (tiny shapes): a capture path that CI
    # never executes is a capture path that breaks on capture day.
    if (is_flagship and not args.no_strict_b8
            and cfg.experiment_name != "mini-imagenet_maml++_5-way_5-shot_DA"):
        try:
            b8_cfg = load_workload(
                os.path.join(repo, "experiment_config",
                             "mini-imagenet_maml++_5-way_5-shot_DA.json"),
                0, n_dev)
            if args.quick:
                b8_cfg = quick_shrink(b8_cfg, n_dev)
            wl8 = build_steady_state(b8_cfg, devices, registry)
            b8 = measure_rate(wl8.compiled, wl8.state, wl8.batch_ep,
                              wl8.epoch, batch_size=b8_cfg.batch_size,
                              n_dev=n_dev, steps=min(9, args.steps))
            out["strict_b8_tasks_per_sec_per_chip"] = round(b8, 3)
            out["vs_baseline_strict_b8"] = round(
                b8 / BASELINE_TASKS_PER_SEC, 3)
        except Exception as e:  # noqa: BLE001
            out["strict_b8_error"] = f"{type(e).__name__}: {e}"
        out["workload"] = cfg.experiment_name
        print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI entry point — same contract as the reference's
``train_maml_system.py``:

    python train_maml_system.py --name_of_args_json_file \\
        experiment_config/omniglot_maml++_5-way_1-shot.json [--key value ...]

Any config field can be overridden on the command line after the JSON is
applied (reference: argparse defaults → JSON override; here: dataclass
defaults → JSON → CLI overrides).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.resilience import EXIT_PREEMPTED
from howtotrainyourmamlpytorch_tpu.utils.dataset_tools import (
    maybe_unzip_dataset)


def _coerce(parser, field, key: str, raw: str):
    """Parse a CLI override against its dataclass field type.

    JSON literals are accepted for every type; additionally bools take
    true/false in any case ('--second_order False' must not become the
    truthy string 'False'). Non-string fields reject unparseable values
    loudly instead of smuggling strings into the config.
    """
    if field.type in ("bool", bool):
        low = raw.strip().lower()
        if low in ("true", "1", "yes"):
            return True
        if low in ("false", "0", "no"):
            return False
        parser.error(f"--{key} expects a boolean, got {raw!r}")
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        if "Tuple" in str(field.type) or "tuple" in str(field.type):
            # Tuple fields accept bare comma/space-separated values
            # ('--mesh_shape 2,4' or '--mesh_shape 2 4') in addition to
            # JSON ('--mesh_shape [2,4]').
            try:
                return json.loads(f"[{raw}]")
            except json.JSONDecodeError:
                pass
        if "str" in str(field.type):
            return raw  # bare string (e.g. --experiment_name foo)
        parser.error(f"--{key}: could not parse {raw!r} as "
                     f"{field.type}")


def get_args(argv=None) -> MAMLConfig:
    parser = argparse.ArgumentParser(
        description="TPU-native MAML++ few-shot meta-learning")
    parser.add_argument("--name_of_args_json_file", type=str, default=None,
                        help="experiment_config/*.json (reference schema)")
    known, overrides = parser.parse_known_args(argv)

    values = {}
    if known.name_of_args_json_file:
        with open(known.name_of_args_json_file) as f:
            values.update(json.load(f))

    fields = {f.name: f for f in dataclasses.fields(MAMLConfig)}
    i = 0
    while i < len(overrides):
        tok = overrides[i]
        if not tok.startswith("--"):
            parser.error(f"unexpected argument {tok!r}")
        key, eq, inline = tok[2:].partition("=")
        if key not in fields:
            parser.error(f"unknown config field --{key}")
        if eq:
            raw = inline
            i += 1
        else:
            # Greedily take the run of non-flag tokens so tuple fields
            # work naturally: '--mesh_shape 2 4' == '--mesh_shape 2,4'.
            # Negative numbers ('-1') don't start with '--' and are
            # consumed as values.
            j = i + 1
            while j < len(overrides) and not overrides[j].startswith("--"):
                j += 1
            tokens = overrides[i + 1:j]
            if not tokens:
                parser.error(f"--{key} needs a value")
            is_tuple = ("Tuple" in str(fields[key].type)
                        or "tuple" in str(fields[key].type))
            if len(tokens) > 1 and not is_tuple:
                parser.error(
                    f"--{key} takes one value, got {len(tokens)}: "
                    f"{' '.join(tokens)!r}")
            raw = tokens[0] if len(tokens) == 1 else ",".join(tokens)
            i = j
        values[key] = _coerce(parser, fields[key], key, raw)

    return MAMLConfig.from_dict(values)


def main(argv=None) -> int:
    cfg = get_args(argv)
    # Optional platform pin (e.g. MAML_JAX_PLATFORM=cpu): this
    # environment's sitecustomize overrides the JAX_PLATFORMS env var,
    # so CI subprocesses (scripts/parity_run.sh smoke) and CPU-only
    # boxes need an env knob that wins — jax.config.update does, as
    # long as it runs before first backend use.
    platform = os.environ.get("MAML_JAX_PLATFORM")
    if platform:
        import jax as _jax
        _jax.config.update("jax_platforms", platform)
    # Optional bounded wait for a transiently-unavailable backend
    # (MAML_BACKEND_TIMEOUT=<seconds>): on a tunneled device, start-time
    # outages are transient and a bare first device query either fails
    # a restartable job instantly or hangs it forever — the shared
    # preamble (utils/backend.py) turns both into a bounded retry.
    # Off by default: local/CPU runs should fail fast.
    # Subprocess probe ONLY — no in-process device query here: the
    # multi-host bootstrap below must be the first backend touch so
    # jax.devices() is the global pod list.
    backend_timeout = float(os.environ.get("MAML_BACKEND_TIMEOUT", "0"))
    if backend_timeout > 0:
        from howtotrainyourmamlpytorch_tpu.utils.backend import (
            wait_for_backend)
        wait_for_backend(timeout_s=backend_timeout)
    # Elastic startup gate (docs/RESILIENCE.md § Elastic pod): a process
    # launched with the ORIGINAL env while a degraded survivor group is
    # LIVE is a backfill — it must rejoin through the roster file, not
    # stand up a rival full-geometry coordination ring. Runs before the
    # distributed bootstrap below because the verdict changes the JAX_*
    # env the bootstrap reads. Generation-carrying processes (already
    # resharded) and non-elastic configs skip straight through.
    if cfg.elastic_mode:
        from howtotrainyourmamlpytorch_tpu.resilience import (
            cluster as _cluster, elastic as _elastic)
        lease_dir = os.path.join(cfg.experiment_root, cfg.experiment_name,
                                 _cluster.LEASE_DIR)
        if _elastic.parse_roster_env() is None:
            doc = _elastic.read_roster(lease_dir)
            self_host = int(os.environ.get("JAX_PROCESS_ID", "0"))
            stalled = _cluster.stalled_after(cfg)
            n_ranks = len((doc or {}).get("roster", [])) or 1
            ages = _cluster.read_lease_ages(lease_dir,
                                            expected_hosts=n_ranks)
            verdict = _elastic.startup_disposition(self_host, doc, ages,
                                                   stalled)
            if verdict == "backfill_wait":
                print(f"elastic: host {self_host} is a backfill for a "
                      f"live degraded group (roster "
                      f"{(doc or {}).get('roster')}); waiting to rejoin",
                      flush=True)
                joined = _elastic.backfill_wait(lease_dir, self_host,
                                                stalled)
                if joined is not None:
                    # Adopt the re-expanded generation's env in-process
                    # (JAX is not initialized yet — no exec needed;
                    # removed keys like a stale MAML_FAULTS are dropped
                    # too — see elastic.adopt_env).
                    _elastic.adopt_env(joined, self_host)
                    print(f"elastic: rejoining at generation "
                          f"{joined['generation']}", flush=True)
                else:
                    print("elastic: degraded group is gone; launching "
                          "at the original geometry", flush=True)
                    _elastic.archive_roster(lease_dir)
            elif doc is not None:
                # Whole-job restart over a stale roster: retire it so
                # the lost-host budget restarts at zero.
                _elastic.archive_roster(lease_dir)
    # Multi-host bootstrap (no-op single-process); must run before any
    # device query so jax.devices() is the global pod device list.
    from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed
    multihost = initialize_distributed()
    print(f"experiment: {cfg.experiment_name} | dataset: "
          f"{cfg.dataset_name} | {cfg.num_classes_per_set}-way "
          f"{cfg.num_samples_per_class}-shot | mesh {cfg.mesh_shape}"
          + (f" | multihost: {multihost}" if multihost else ""))
    if cfg.compilation_cache_dir:
        # Persistent executable cache: a resumed/restarted run reloads
        # its compiled train/eval steps instead of paying the multi-10s
        # TPU compiles again. Safe to share across hosts (content-keyed).
        import jax as _jax
        _jax.config.update("jax_compilation_cache_dir",
                           cfg.compilation_cache_dir)
        # Cache EVERY executable (default threshold skips sub-second
        # compiles — but a restart replays dozens of those too).
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    # Dataset provisioning: single extractor (process 0), everyone waits —
    # concurrent unzip into a shared dataset dir would corrupt it. The
    # barrier sits in a finally so a provisioning failure on process 0
    # still releases the other hosts (they fail on the missing data)
    # instead of deadlocking them at the barrier.
    import jax

    from howtotrainyourmamlpytorch_tpu.parallel import barrier
    try:
        if jax.process_index() == 0:
            if cfg.download_datasets:
                # Reference behavior: download-then-extract; a failed or
                # wrong download raises instead of silently training on
                # the synthetic fallback.
                from howtotrainyourmamlpytorch_tpu.utils.dataset_tools \
                    import gdrive_fetcher
                maybe_unzip_dataset(cfg, fetcher=gdrive_fetcher,
                                    require=True)
            else:
                maybe_unzip_dataset(cfg)  # synthetic fallback if absent
    finally:
        barrier("dataset_ready")
    builder = ExperimentBuilder(cfg)
    result = builder.run_experiment()
    if isinstance(result, dict) and "preempted_at_iter" in result:
        # Distinct exit code (EX_TEMPFAIL): the run checkpointed cleanly
        # on SIGTERM/SIGINT and wants to be resubmitted with
        # continue_from_epoch='latest' — not a success, not a failure
        # (docs/RESILIENCE.md § Exit codes).
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())

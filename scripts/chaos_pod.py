"""Pod chaos harness: SIGKILL a peer, prove the attributed cluster abort
and the consensus resume — end to end, with real OS processes.

The ISSUE 9 acceptance scenario (docs/RESILIENCE.md § Pod fault domain):

1. **peer_kill** — boot an N-process ``jax.distributed`` training run on
   CPU (each process 4 virtual devices, the
   tests/test_multiprocess_distributed.py topology) with the pod fault
   domain armed (``cluster_collective_timeout_s``). One host carries
   ``kill_peer@I`` (resilience/faults.py): at train iteration I it
   SIGKILLs itself — no handler, no cleanup, exactly what a yanked pod
   node looks like to the survivors. Every survivor must block in its
   next collective, trip the cluster deadline within
   ``cluster_collective_timeout_s`` + slack, write a crash bundle and a
   ``peer_lost`` row *naming the dead host*, and exit
   ``EXIT_PEER_LOST`` (73) so a scheduler restarts the whole job.
2. **restart** — relaunch all N processes with no faults. The cluster
   consensus-resume barrier agrees every host onto the committed
   checkpoint epoch; the run must resume from exactly those bytes (the
   committed epoch file's CRC is pinned before and after) and complete
   through the ensemble test protocol.
3. **parity** — zero-cost-when-disabled, the watchdog standard: three
   single-process runs (cluster off / on / off) must produce
   bitwise-identical final weights, and the two cache-warm runs must
   compile the same number of executables.

Artifact contract (bench.py discipline): the LAST stdout JSON line is
authoritative — ``{"metric": "pod_chaos", "status":
"recovered"|"failed"|"skipped", ...}``. Exit 0 iff recovered (or
skipped: a sandbox that cannot bind localhost sockets cannot run the
multi-process phases, and says so rather than failing).

Usage:
    python scripts/chaos_pod.py --quick
    python scripts/chaos_pod.py --out /tmp/pod --phases peer_kill,restart
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import zlib

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

NUM_PROCESSES = 2
KILL_ITER = 6  # mid-epoch-1 (epoch 0 = iters 1..4, checkpointed at 4)
COLLECTIVE_TIMEOUT_S = 12.0
# Trip-latency slack on top of the collective budget: watchdog poll
# overshoot (<= ~25% of the deadline), the bundle/flush drain, and this
# 1-core box's scheduling jitter.
TRIP_SLACK_S = 60.0


def pod_cfg_dict(out_dir: str, **kw):
    """The tiny-but-real 2-host workload: 3-way 1-shot over a (2, 4)
    mesh, every sync point one iteration apart so the kill lands
    deterministically, cluster deadline tight enough to prove latency."""
    base = dict(
        experiment_name="pod_chaos", experiment_root=out_dir,
        dataset_name="synthetic_pod",
        image_height=10, image_width=10, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=2, batch_size=8,
        cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False, use_multi_step_loss_optimization=False,
        total_epochs=2, total_iter_per_epoch=4,
        num_evaluation_tasks=4, max_models_to_save=2,
        compute_dtype="float32", meta_learning_rate=0.005,
        dispatch_sync_every=1, live_progress=False,
        mesh_shape=[2, 4],
        continue_from_epoch="latest",
        # Pod fault domain armed; generous generic deadlines so ONLY
        # the cluster budget can trip (the attribution under test).
        cluster_collective_timeout_s=COLLECTIVE_TIMEOUT_S,
        cluster_lease_interval_s=0.5,
        watchdog_step_timeout_s=600.0, watchdog_feed_timeout_s=600.0,
        watchdog_collective_timeout_s=600.0,
        watchdog_compile_timeout_s=1200.0,
        watchdog_poll_interval_s=0.25,
        # Fail-loud geometry: this IS a pod profile (satellite pin).
        require_mesh=1)
    base.update(kw)
    return base


def launch_pod(out: str, cfg: dict, fault_host=None, fault_spec=""):
    """Start NUM_PROCESSES train_maml_system.py workers joined through
    jax.distributed; returns (procs, log files). Workers write straight
    to files — SPMD lockstep means an undrained PIPE on one would
    deadlock all."""
    os.makedirs(out, exist_ok=True)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg_path = os.path.join(out, "pod_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    procs, logs = [], []
    for pid in range(NUM_PROCESSES):
        env = dict(os.environ)
        env.pop("MAML_FAULTS", None)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(NUM_PROCESSES),
            "JAX_PROCESS_ID": str(pid),
            "MAML_JAX_PLATFORM": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=4"
                          ).strip(),
        })
        if fault_host is not None and pid == fault_host:
            env["MAML_FAULTS"] = fault_spec
        out_f = open(os.path.join(out, f"worker{pid}.log"), "w+")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(_REPO, "train_maml_system.py"),
             "--name_of_args_json_file", cfg_path],
            env=env, stdout=out_f, stderr=subprocess.STDOUT, text=True))
        logs.append(out_f)
    return procs, logs


def read_events(out: str):
    path = os.path.join(out, "pod_chaos", "logs", "events.jsonl")
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                if line.strip():
                    rows.append(json.loads(line))
    return rows


def committed_view(out: str):
    """(newest committed epoch, its iteration, its file CRC32) from the
    shared manifest + checkpoint file — the consensus resume target."""
    saved = os.path.join(out, "pod_chaos", "saved_models")
    manifest_path = os.path.join(saved, "MANIFEST.json")
    epoch = it = crc = None
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            records = json.load(f).get("records", {})
        epochs = [(int(t), r) for t, r in records.items()
                  if t.isdigit() and r.get("status") == "committed"]
        if epochs:
            epoch, rec = max(epochs)
            it = rec.get("iter")
    if epoch is not None:
        ckpt = os.path.join(saved, f"train_model_{epoch}.ckpt")
        if os.path.exists(ckpt):
            with open(ckpt, "rb") as f:
                crc = zlib.crc32(f.read())
    return epoch, it, crc


def wait_all(procs, logs, timeout_s: float):
    """Wait for every worker; returns return codes (None = timed out,
    then killed)."""
    codes = []
    deadline = time.monotonic() + timeout_s
    for p in procs:
        left = max(deadline - time.monotonic(), 1.0)
        try:
            p.wait(timeout=left)
            codes.append(p.returncode)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            codes.append(None)
    for f in logs:
        f.close()
    return codes


def run_peer_kill(out: str) -> dict:
    """Phase 1: the attributed abort. Returns the phase's facts."""
    from howtotrainyourmamlpytorch_tpu.resilience import EXIT_PEER_LOST
    procs, logs = launch_pod(out, pod_cfg_dict(out), fault_host=1,
                             fault_spec=f"kill_peer@{KILL_ITER}")
    victim, survivor = procs[1], procs[0]
    # The victim SIGKILLs itself mid-epoch-1 (after compiles + epoch 0,
    # which can take minutes on a 1-core box) — generous ceiling.
    try:
        victim.wait(timeout=1200)
    except subprocess.TimeoutExpired:
        pass
    victim_dead_at = time.monotonic()
    # The survivor must exit within the cluster budget + slack FROM THE
    # PEER'S DEATH — the latency claim the exit code makes.
    try:
        survivor.wait(timeout=COLLECTIVE_TIMEOUT_S + TRIP_SLACK_S)
        survivor_latency = time.monotonic() - victim_dead_at
    except subprocess.TimeoutExpired:
        survivor_latency = None
    wait_all(procs, logs, timeout_s=5.0)

    events = read_events(out)
    lost = [e for e in events if e.get("event") == "peer_lost"]
    bundle = os.path.join(out, "pod_chaos", "logs", "crash_bundle_p0")
    crash = {}
    crash_path = os.path.join(bundle, "crash.json")
    if os.path.exists(crash_path):
        with open(crash_path) as f:
            crash = json.load(f)
    epoch, it, crc = committed_view(out)
    tail = ""
    log_path = os.path.join(out, "worker0.log")
    if os.path.exists(log_path):
        with open(log_path) as f:
            tail = f.read()[-1200:]
    facts = {
        "victim_exit_code": victim.returncode,
        "survivor_exit_code": survivor.returncode,
        "survivor_latency_s": (round(survivor_latency, 3)
                               if survivor_latency is not None else None),
        "peer_lost_rows": len(lost),
        "suspect_hosts": (lost[-1].get("suspect_hosts") if lost else None),
        "bundle_reason": crash.get("reason"),
        "bundle_suspects": crash.get("suspect_hosts"),
        "committed_epoch": epoch,
        "committed_iter": it,
        "committed_crc": crc,
    }
    facts["ok"] = bool(
        victim.returncode == -9  # SIGKILL took it, nothing graceful
        and survivor.returncode == EXIT_PEER_LOST
        and survivor_latency is not None
        and facts["peer_lost_rows"] >= 1
        and facts["suspect_hosts"] == [1]
        and facts["bundle_reason"] == "peer_lost"
        and epoch == 0 and it == 4)  # epoch 0's boundary survived
    if not facts["ok"]:
        facts["survivor_log_tail"] = tail
    return facts


def run_restart(out: str, committed_epoch, committed_crc) -> dict:
    """Phase 2: consensus resume. All N relaunch, agree on the committed
    epoch, resume bitwise from its bytes, finish the run."""
    procs, logs = launch_pod(out, pod_cfg_dict(out))
    codes = wait_all(procs, logs, timeout_s=1500)
    with open(os.path.join(out, "worker0.log")) as f:
        w0 = f.read()
    resumed = None
    for line in w0.splitlines():
        if line.startswith("resumed from checkpoint"):
            resumed = line.strip()
    # The committed snapshot's bytes were the resume source and survive
    # the restart untouched — bitwise, not "same epoch number". (The
    # restart retrains LATER epochs; this epoch's file must not move.)
    crc_after = None
    if committed_epoch is not None:
        ckpt = os.path.join(out, "pod_chaos", "saved_models",
                            f"train_model_{committed_epoch}.ckpt")
        if os.path.exists(ckpt):
            with open(ckpt, "rb") as f:
                crc_after = zlib.crc32(f.read())
    facts = {
        "exit_codes": codes,
        "resumed_line": resumed,
        "committed_crc_unchanged": bool(committed_crc is not None
                                        and crc_after == committed_crc),
        "test_protocol_ran": "test:" in w0,
    }
    facts["ok"] = bool(
        all(c == 0 for c in codes)
        and resumed is not None and "at iter 4" in resumed
        and facts["committed_crc_unchanged"]
        and facts["test_protocol_ran"])
    if not facts["ok"]:
        facts["worker0_log_tail"] = w0[-1200:]
    return facts


def _last_json(text: str):
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return {}


def run_elastic(out: str) -> dict:
    """Elastic phases (ISSUE 12 acceptance): kill a peer mid-epoch with
    ``elastic_mode=1`` — the survivor reshards onto the N-1 mesh within
    one collective budget, resumes from the committed epoch with ZERO
    XLA compiles (degraded-prewarmed AOT store), and finishes the run;
    then a COLD run launched directly at the survivor geometry from a
    snapshot of the same committed state must produce bitwise-identical
    final weights."""
    from howtotrainyourmamlpytorch_tpu.resilience import elastic as el

    eout = os.path.join(out, "elastic")
    store = os.path.join(eout, "aot_store")
    os.makedirs(eout, exist_ok=True)
    cfg = pod_cfg_dict(eout, aot_store_dir=store,
                       elastic_mode=1, elastic_max_lost_hosts=1)
    cfg_path = os.path.join(eout, "elastic_cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)

    base_env = dict(os.environ)
    for key in ("MAML_FAULTS", "JAX_COORDINATOR_ADDRESS",
                "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                el.GEN_ENV, el.ROSTER_ENV, el.ORIG_ENV):
        base_env.pop(key, None)
    base_env.update({
        "MAML_JAX_PLATFORM": "cpu", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                      + " --xla_force_host_platform_device_count=4"
                      ).strip(),
    })

    # 1. Prewarm the SURVIVOR topology (N-1 = 1 host x 4 chips) into the
    # shared store — the reshard must pay zero compiles.
    prew = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "aot_prewarm.py"),
         "--config", cfg_path, "--degraded", "1", "--degraded-only"],
        env=base_env, capture_output=True, text=True, timeout=1800)
    prew_art = _last_json(prew.stdout)

    # 2. Kill host 1 mid-epoch-1; host 0 must reshard and keep going.
    procs, logs = launch_pod(eout, cfg, fault_host=1,
                             fault_spec=f"kill_peer@{KILL_ITER}")
    victim, survivor = procs[1], procs[0]
    try:
        victim.wait(timeout=1800)
    except subprocess.TimeoutExpired:
        pass
    victim_dead_at = time.time()

    # 3. Snapshot the committed state for the cold-parity leg while the
    # survivor is still stranded in its collective (the trip needs a
    # full collective budget to fire, the epoch-0 files have been
    # stable since iter 4). Wait for the manifest to show epoch 0
    # committed first — the async writer may still be draining.
    cold_root = os.path.join(out, "elastic_cold")
    for _ in range(40):
        epoch, it, crc0 = committed_view(eout)
        if epoch == 0:
            break
        time.sleep(0.25)
    shutil.rmtree(cold_root, ignore_errors=True)
    shutil.copytree(os.path.join(eout, "pod_chaos"),
                    os.path.join(cold_root, "pod_chaos"))

    try:
        survivor.wait(timeout=1800)
    except subprocess.TimeoutExpired:
        pass
    wait_all(procs, logs, timeout_s=5.0)

    roster_path = os.path.join(eout, "pod_chaos", "cluster",
                               "ROSTER.json")
    roster_doc, reshard_latency = {}, None
    if os.path.exists(roster_path):
        reshard_latency = os.path.getmtime(roster_path) - victim_dead_at
        with open(roster_path) as f:
            roster_doc = json.load(f)
    events = read_events(eout)
    reshards = [e for e in events if e.get("event") == "elastic_reshard"]
    warms = [e for e in events if e.get("event") == "warm_start"]
    last_warm = warms[-1] if warms else {}
    with open(os.path.join(eout, "worker0.log")) as f:
        w0 = f.read()
    final_ckpt = os.path.join(eout, "pod_chaos", "saved_models",
                              "train_model_1.ckpt")
    crc_elastic = None
    if os.path.exists(final_ckpt):
        with open(final_ckpt, "rb") as f:
            crc_elastic = zlib.crc32(f.read())

    facts = {
        "prewarm_ok": bool(prew_art.get("ok")),
        "victim_exit_code": victim.returncode,
        "survivor_exit_code": survivor.returncode,
        "reshard_latency_s": (round(reshard_latency, 3)
                              if reshard_latency is not None else None),
        "reshard_rows": len(reshards),
        "reshard_suspects": (reshards[-1].get("suspects")
                             if reshards else None),
        "roster_generation": roster_doc.get("generation"),
        "roster": roster_doc.get("roster"),
        "warm_compiles_before_first_step": last_warm.get(
            "compiles_before_first_step"),
        "warm_aot_misses": last_warm.get("aot_misses"),
        "resumed_at_iter_4": "at iter 4" in w0.split("elastic:")[-1],
        "test_protocol_ran": "test:" in w0,
        "final_ckpt_crc": crc_elastic,
    }
    facts["kill_ok"] = bool(
        facts["prewarm_ok"]
        and victim.returncode == -9
        and survivor.returncode == 0          # NOT 73: it kept training
        and facts["reshard_rows"] >= 1
        and facts["reshard_suspects"] == [1]
        and facts["roster_generation"] == 1
        and facts["roster"] == [0]
        and reshard_latency is not None
        and reshard_latency <= COLLECTIVE_TIMEOUT_S + TRIP_SLACK_S
        and facts["warm_compiles_before_first_step"] == 0
        and facts["warm_aot_misses"] == 0
        and facts["resumed_at_iter_4"]
        and facts["test_protocol_ran"]
        and crc_elastic is not None)
    if not facts["kill_ok"]:
        facts["survivor_log_tail"] = w0[-1500:]
        facts["prewarm_tail"] = (prew.stdout + prew.stderr)[-800:]
        return facts

    # 4. Cold N-1 parity: launch ONE process directly at the survivor
    # geometry (same roster env, same shared store) from the snapshot;
    # its continued training must be bitwise the survivor's.
    cold_env = dict(base_env)
    cold_env.update({el.GEN_ENV: "1", el.ROSTER_ENV: "0",
                     el.ORIG_ENV: str(NUM_PROCESSES)})
    cold = subprocess.run(
        [sys.executable, os.path.join(_REPO, "train_maml_system.py"),
         "--name_of_args_json_file", cfg_path,
         "--experiment_root", cold_root],
        env=cold_env, capture_output=True, text=True, timeout=1800)
    cold_ckpt = os.path.join(cold_root, "pod_chaos", "saved_models",
                             "train_model_1.ckpt")
    crc_cold = None
    if os.path.exists(cold_ckpt):
        with open(cold_ckpt, "rb") as f:
            crc_cold = zlib.crc32(f.read())
    facts.update({
        "cold_exit_code": cold.returncode,
        "cold_final_ckpt_crc": crc_cold,
        "bitwise_equal_cold_n1": bool(crc_cold is not None
                                      and crc_cold == crc_elastic),
    })
    facts["ok"] = bool(facts["kill_ok"] and cold.returncode == 0
                       and facts["bitwise_equal_cold_n1"])
    if not facts["ok"]:
        facts["cold_log_tail"] = (cold.stdout + cold.stderr)[-1500:]
    return facts


def run_parity(out: str) -> dict:
    """Phase 3: all cluster knobs at 0/off vs armed — bitwise-identical
    weights and cache-warm compile counts (the watchdog standard)."""
    import jax
    import numpy as np
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    def single(name, **kw):
        cfg = pod_cfg_dict(out, experiment_name=name, mesh_shape=[1, 1],
                           batch_size=2, require_mesh=0,
                           continue_from_epoch="from_scratch", **kw)
        builder = ExperimentBuilder(MAMLConfig.from_dict(cfg))
        builder.run_experiment()
        return builder

    on_kw = dict(cluster_collective_timeout_s=300.0,
                 cluster_lease_interval_s=0.1)
    off_kw = dict(cluster_collective_timeout_s=0.0)
    elastic_kw = dict(cluster_collective_timeout_s=300.0,
                      cluster_lease_interval_s=0.1, elastic_mode=1)
    # Run 1 (off) pays the process's cold compiles; the on/off pair is
    # equally cache-warm, so their compile counts isolate the domain.
    single("parity_cold", **off_kw)
    b_on = single("parity_on", **on_kw)
    compiles_on = b_on.registry.counter("compile/count").value
    b_off = single("parity_off", **off_kw)
    compiles_off = b_off.registry.counter("compile/count").value
    # Elastic leg: policy installed (cluster on + elastic_mode=1) but it
    # never fires — weights and compile counts must stay identical (the
    # zero-cost-when-armed half of the elastic_mode=0 parity pin).
    b_el = single("parity_elastic", **elastic_kw)
    compiles_el = b_el.registry.counter("compile/count").value
    weights_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(b_on.state.params),
                        jax.tree.leaves(b_off.state.params)))
    weights_equal_elastic = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(b_el.state.params),
                        jax.tree.leaves(b_off.state.params)))
    facts = {
        "weights_equal": weights_equal,
        "weights_equal_elastic": weights_equal_elastic,
        "compiles_on": int(compiles_on),
        "compiles_off": int(compiles_off),
        "compiles_elastic": int(compiles_el),
    }
    facts["ok"] = bool(weights_equal and weights_equal_elastic
                       and compiles_on == compiles_off == compiles_el)
    return facts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Pod fault-domain chaos: SIGKILL a jax.distributed "
                    "peer, prove attributed exit 73 + consensus resume.")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="experiment root (default: fresh temp dir, "
                         "removed on success)")
    ap.add_argument("--phases", default="peer_kill,restart,parity,elastic",
                    help="comma list of peer_kill,restart,parity,elastic")
    ap.add_argument("--quick", action="store_true",
                    help="accepted for CLI symmetry; the config is "
                         "already CI-sized")
    args = ap.parse_args(argv)

    platform = os.environ.get("MAML_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)

    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    out = args.out or tempfile.mkdtemp(prefix="chaos_pod_")
    cleanup = args.out is None
    artifact = {"metric": "pod_chaos", "phases": phases}

    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
    except OSError:
        # No localhost sockets, no jax.distributed: record the skip
        # loudly instead of failing a box that cannot run the scenario.
        artifact.update({"value": None, "status": "skipped",
                         "skip_reason": "cannot bind localhost sockets"})
        print(json.dumps(artifact), flush=True)
        return 0

    results = {}
    ok = True
    committed_epoch = committed_crc = None
    for phase in phases:
        print(json.dumps({"phase": phase, "status": "running"}),
              flush=True)
        if phase == "peer_kill":
            results.update(
                {f"peer_kill_{k}": v
                 for k, v in run_peer_kill(out).items()})
            committed_epoch = results.get("peer_kill_committed_epoch")
            committed_crc = results.get("peer_kill_committed_crc")
            ok = ok and results["peer_kill_ok"]
        elif phase == "restart":
            results.update(
                {f"restart_{k}": v
                 for k, v in run_restart(out, committed_epoch,
                                         committed_crc).items()})
            ok = ok and results["restart_ok"]
        elif phase == "parity":
            results.update(
                {f"parity_{k}": v for k, v in run_parity(out).items()})
            ok = ok and results["parity_ok"]
        elif phase == "elastic":
            results.update(
                {f"elastic_{k}": v for k, v in run_elastic(out).items()})
            ok = ok and results.get("elastic_ok",
                                    results["elastic_kill_ok"])
        else:
            raise SystemExit(f"unknown phase {phase!r}")

    artifact.update(results)
    artifact.update({
        "value": 1.0 if ok else 0.0,
        "unit": "recovered",
        "status": "recovered" if ok else "failed",
        "out_dir": None if cleanup else out,
    })
    if cleanup and ok:
        shutil.rmtree(out, ignore_errors=True)
    print(json.dumps(artifact), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Fleet chaos suite: kill, crash-loop and overload the serving fleet.

scripts/chaos_pod.py proves the TRAINING loop survives its failure
table; this is the same discipline for the SERVING fleet — the
self-healing stack (supervisor + router failover + admission shedding,
docs/SERVING.md § Self-healing fleet) exercised against the three
failure shapes it exists for, each asserted from the artifact:

1. **kill** — N supervised replicas under load; one is SIGKILLed
   mid-leg. The router's failover policy resubmits the victim's
   orphaned requests to the surviving replicas (``fleet/failovers``),
   the per-replica breaker drops the dead socket from the candidate
   set before its lease ages out, and the supervisor respawns the slot
   (``fleet/restarts``). Asserts: **zero lost requests**, at least one
   restart, and the fleet restored to N live replicas within the
   restoration budget.
2. **crash_loop** — one slot's spawn is poisoned (nonexistent
   checkpoint: the replica exits on boot, every time). The supervisor
   restarts it with backoff until the crash-loop breaker trips
   (``fleet/crash_loops``), marks the slot FAILED, and the fleet
   serves the whole leg at N-1 — no infinite respawn, zero lost
   requests.
3. **burst** — one replica, deadline shed policy on. A trickle of
   distinct tenants seeds the admission controller's service-time
   EWMA with honest miss-adapt cost, then a 10x burst of repeat
   tenants slams the queue. Excess load is refused AT ADMISSION with
   the distinct ``shed`` status (``fleet/sheds`` > 0); every ADMITTED
   request completes inside its deadline (zero ``failed`` statuses —
   the "never a timeout after queued work" contract) and the admitted
   p95 holds the SLO.

Every phase also runs under alert rules (telemetry/alerts.py): kill
proves the lease-absence and restart-rate alerts fire during the fault
and resolve after healing, crash_loop the crash-loop rate alert, burst
the admission-shedding rate alert. The rollup (``alert_fired_kinds``,
``alerts_resolved``) and a post-chaos ``scripts/ops_console.py`` render
(zero alerts still firing) gate the ``recovered`` verdict.

Artifact contract (bench.py discipline): the LAST stdout JSON line is
``{"metric": "chaos_fleet", ...}`` with per-phase verdicts and the
schema-stable fleet robustness keys (``fleet_restarts``,
``fleet_crash_loops``, ``fleet_failover_count``, ``fleet_shed_count``)
plus the alert rollup above. On a box that cannot bind localhost
sockets: ``"status": "skipped"``, exit 0 (the chaos_pod.py rule).

The driver process stays jax-free (fleet_bench's file-path loading
discipline — router, supervisor and load generator shared with
scripts/fleet_bench.py); jax runs only in the prepare child and the
replica workers.

Usage:
    python scripts/chaos_fleet.py --quick          # 2-replica CI smoke
    python scripts/chaos_fleet.py                  # full 3-replica run
    python scripts/chaos_fleet.py --phases kill,burst --out /tmp/cf
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_SCRIPTS)
sys.path.insert(0, _SCRIPTS)
sys.path.insert(0, _REPO)

from fleet_bench import (  # noqa: E402
    ReplicaConn, _MiniMetrics, _can_bind_localhost, _load_module,
    _router_mod, _run_child, bench_bucket, build_schedule, drive_leg,
    fleet_cfg_dict)

_supervisor_mod = _load_module(
    "_chaos_fleet_supervisor_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "fleet",
                 "supervisor.py"))
_alerts_mod = _load_module(
    "_chaos_fleet_alerts_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "telemetry",
                 "alerts.py"))


# ---------------------------------------------------------------------------
# alert instrumentation (telemetry/alerts.py)
# ---------------------------------------------------------------------------

def _make_evaluator(rules: List[dict], *, source: str,
                    snapshot_path: Optional[str] = None):
    """Inline-rules AlertEvaluator for one chaos phase: each phase must
    prove its alerts FIRE during the fault and RESOLVE after healing,
    through the same rule engine production configs drive."""
    return _alerts_mod.AlertEvaluator(
        _alerts_mod.parse_rules({"rules": rules}), source=source,
        snapshot_path=snapshot_path)


def _alert_outcome(evaluators: Dict[str, Any],
                   events_paths: List[str]) -> dict:
    """Per-phase alert verdict: which rules fired (from the ``alert``
    rows the evaluators appended to the phase's events files) plus the
    fire/resolve ledger — the artifact's fire-AND-resolve proof.
    ``resolved_all`` is the recovery gate: every fired instance closed
    and nothing is still active on any evaluator."""
    fired_kinds: set = set()
    for path in events_paths:
        try:
            with open(path) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if (row.get("event") == "alert"
                            and row.get("state") == "firing"):
                        fired_kinds.add(str(row.get("rule")))
        except OSError:
            continue
    fired = sum(ev.fired_total for ev in evaluators.values())
    resolved = sum(ev.resolved_total for ev in evaluators.values())
    active = sum(len(ev.active()) for ev in evaluators.values())
    return {"fired_kinds": sorted(fired_kinds), "fired": fired,
            "resolved": resolved, "active_final": active,
            "resolved_all": bool(fired > 0 and resolved == fired
                                 and active == 0)}


def _console_check(out: str) -> dict:
    """Render post-chaos fleet status via scripts/ops_console.py — the
    operator's real entrypoint, as a subprocess — and keep the artifact
    fields the chaos verdict gates on: the console must agree that
    nothing is still firing after the suite."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(_SCRIPTS, "ops_console.py"),
             out],
            cwd=_REPO, capture_output=True, text=True, timeout=120)
        lines = proc.stdout.strip().splitlines()
        doc = json.loads(lines[-1]) if lines else {}
    except Exception as e:  # noqa: BLE001 — folded into the verdict
        return {"error": f"{type(e).__name__}: {e}"}
    return {"exit_code": proc.returncode,
            "events_rows": doc.get("events_rows"),
            "replicas_live": doc.get("replicas_live"),
            "alerts_firing": doc.get("alerts_firing"),
            "alerts_by_severity": doc.get("alerts_by_severity"),
            "error": doc.get("error")}


def _settle_alerts(evaluators: Dict[str, Any], tick_fns,
                   timeout_s: float = 15.0) -> None:
    """Keep ticking the healing loops until every fired alert has
    resolved (or the budget runs out — the outcome assert then names
    the stuck rule). Rate rules need one more evaluation AFTER the
    counter stops moving; absence rules need the replacement lease."""
    deadline = time.monotonic() + timeout_s
    while (any(ev.active() for ev in evaluators.values())
           and time.monotonic() < deadline):
        for fn in tick_fns:
            fn()
        time.sleep(0.1)


# ---------------------------------------------------------------------------
# replica spawning + connection upkeep
# ---------------------------------------------------------------------------

def make_spawn(out: str, cfg_path: str, ckpt_dir: str, fleet_dir: str,
               poisoned=()):
    """Supervisor ``spawn_fn``: the fleet_bench replica recipe, per
    slot. ``poisoned`` slots get a nonexistent checkpoint dir — the
    crash-loop phase's reproducible boot failure."""
    def spawn(slot: int):
        ckpt = (ckpt_dir if slot not in poisoned
                else os.path.join(out, "no_such_checkpoint"))
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log = open(os.path.join(out, f"replica_{slot}.log"), "a")
        try:
            return subprocess.Popen(
                [sys.executable, "-m",
                 "howtotrainyourmamlpytorch_tpu.serve.fleet.replica",
                 "--config", cfg_path, "--replica-id", str(slot),
                 "--fleet-dir", fleet_dir, "--checkpoint", ckpt,
                 "--events",
                 os.path.join(out, f"events_replica_{slot}.jsonl")],
                cwd=_REPO, env=env, stdout=log,
                stderr=subprocess.STDOUT)
        finally:
            log.close()  # the child holds its own inherited fd
    return spawn


class FleetClient:
    """Keeps one live ReplicaConn per announced replica, reconnecting
    when the lease pid changes (a supervisor restart) or the socket
    dies — the driver-side half of self-healing. ``pump()`` runs on
    drive_leg's refresh cadence via ``on_tick``."""

    def __init__(self, router, fleet_dir: str):
        self.router = router
        self.fleet_dir = fleet_dir
        self.conns: Dict[int, ReplicaConn] = {}
        self._pids: Dict[int, Any] = {}

    def pump(self) -> None:
        members = _router_mod.read_members(self.fleet_dir)
        for rid, rec in members.items():
            payload = rec.get("payload") or {}
            port, pid = payload.get("port"), payload.get("pid")
            if not port:
                continue
            conn = self.conns.get(rid)
            stale = (conn is None or conn._stopped_evt.is_set()
                     or self._pids.get(rid) != pid)
            if not stale:
                continue
            try:
                fresh = ReplicaConn(rid, int(port),
                                    lambda _rid, _msg: None)
            except OSError:
                continue  # announced but not accepting yet; next pump
            if conn is not None:
                conn.close()
            self.conns[rid] = fresh
            self._pids[rid] = pid
            # A reachable socket is the breaker's recovery signal: the
            # restarted replica rejoins the candidate set immediately
            # instead of waiting out a half-open probe cycle.
            self.router.record_success(rid)

    def close(self) -> None:
        for conn in self.conns.values():
            conn.close()


def _boot_fleet(sup, client, router, *, want_live: int,
                want_failed: int = 0, timeout_s: float = 420.0) -> None:
    """Tick the supervisor until ``want_live`` replicas are routable
    and connected (and, for the crash-loop phase, ``want_failed``
    slots have tripped their breaker)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        sup.tick()
        router.refresh()
        client.pump()
        failed = sup.count(_supervisor_mod.FAILED)
        if (len(router.routable) >= want_live
                and sum(1 for r in router.routable
                        if r in client.conns) >= want_live
                and failed >= want_failed):
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"fleet never reached {want_live} live (+{want_failed} failed) "
        f"replicas in {timeout_s:.0f}s: states={sup.states()} "
        f"routable={router.routable}")


def _router_for(fleet_dir: str, cfg_doc: dict, registry) -> Any:
    return _router_mod.FleetRouter(
        fleet_dir, vnodes=int(cfg_doc["fleet_vnodes"]),
        load_factor=float(cfg_doc["fleet_load_factor"]),
        stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
        dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
        breaker_cooldown_s=1.0, registry=registry)


# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------

def phase_kill(out: str, cfg_path: str, cfg_doc: dict, ckpt_dir: str,
               *, replicas: int, requests: int, tenants: int,
               quick: bool, image_shape) -> dict:
    fleet_dir = os.path.join(out, "fleet_kill")
    registry = _MiniMetrics()
    router = _router_for(fleet_dir, cfg_doc, registry)
    sup_events = os.path.join(out, "events_supervisor_kill.jsonl")
    drv_events = os.path.join(out, "events_driver_kill.jsonl")
    # Two evaluators, two vantage points. The SUPERVISOR one rides the
    # wired-in tick hook and watches the restart counter it bumps (a
    # SIGKILLed child is seen by poll() within one tick, so its lease
    # never ages while the slot counts as RUNNING — the supervisor's
    # absence view cannot fire here by design). The DRIVER one watches
    # the raw membership leases the router routes by: the victim's
    # lease vanishes for the whole respawn window, fires, and resolves
    # when the replacement's lease lands.
    sup_alerts = _make_evaluator(
        [{"name": "replica_restarts", "type": "rate",
          "metric": _supervisor_mod.RESTARTS_COUNTER,
          "op": ">", "value": 0, "for_s": 0, "severity": "warn"}],
        source="supervisor",
        snapshot_path=os.path.join(out, "ALERTS_kill_sup.json"))
    drv_alerts = _make_evaluator(
        [{"name": "replica_lease_stale", "type": "absence",
          "signal_prefix": "lease:", "for_s": 0, "severity": "critical",
          "max_age_s": 2.0 * float(cfg_doc["fleet_replica_stalled_s"])}],
        source="driver",
        snapshot_path=os.path.join(out, "ALERTS_kill_driver.json"))
    drv_appender = _supervisor_mod._EventAppender(drv_events)
    seen_rids: set = set()

    def drv_alert_tick() -> None:
        # A replica that has EVER leased is expected to keep leasing:
        # the supervisor reaps the victim's stale lease file within a
        # tick of the kill (so its age never grows on disk), and a
        # vanished-but-expected lease is age inf — the absence rule
        # fires for the whole respawn window and resolves the moment
        # the replacement's lease lands.
        members = _router_mod.read_members(fleet_dir)
        seen_rids.update(members)
        ages = {f"lease:{rid}":
                (float(members[rid].get("age") or 0.0)
                 if rid in members else float("inf"))
                for rid in seen_rids}
        drv_alerts.evaluate(snapshot=registry.snapshot(), ages=ages,
                            jsonl=drv_appender, registry=registry)

    sup = _supervisor_mod.ReplicaSupervisor(
        fleet_dir, make_spawn(out, cfg_path, ckpt_dir, fleet_dir),
        desired=replicas, scale_min=1, scale_max=replicas,
        max_restarts=5, restart_window_s=300.0,
        stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
        dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
        start_timeout_s=420.0, backoff_base_s=0.2, backoff_cap_s=2.0,
        registry=registry, events_path=sup_events,
        alert_evaluator=sup_alerts)
    client = FleetClient(router, fleet_dir)
    try:
        _boot_fleet(sup, client, router, want_live=replicas)
        _, schedule = build_schedule(requests, tenants, 0, image_shape,
                                     bench_bucket(quick))
        victim: Dict[str, Any] = {"slot": None, "pid": None}

        def fire() -> None:
            # SIGKILL the lowest RUNNING slot mid-load — the ungraceful
            # death the whole stack exists for.
            for slot in sorted(sup.slots):
                rec = sup.slots[slot]
                if (rec["state"] == _supervisor_mod.RUNNING
                        and rec["proc"] is not None):
                    victim.update(slot=slot, pid=rec["proc"].pid)
                    os.kill(rec["proc"].pid, signal.SIGKILL)
                    return

        def on_tick(_now: float) -> None:
            sup.tick()
            client.pump()
            drv_alert_tick()

        stats = drive_leg(
            router, client.conns, schedule,
            max_outstanding=4 * replicas,
            swap_trigger={"at_completed": max(requests // 4, 1),
                          "fire": fire},
            # Generous failover budget: until the victim's lease ages
            # out, half-open probes keep testing its dead socket and
            # each probe burns one attempt for some unlucky request.
            failover_max_attempts=10,
            stall_timeout_s=180.0 if quick else 300.0,
            on_tick=on_tick)
        # Restoration budget: the supervisor must put the fleet back at
        # full strength after the leg (the leg itself may complete on
        # N-1 before the restarted replica finishes booting).
        restore_deadline = time.monotonic() + (120.0 if quick else 180.0)
        while time.monotonic() < restore_deadline:
            sup.tick()
            router.refresh()
            client.pump()
            drv_alert_tick()
            if len(router.routable) >= replicas:
                break
            time.sleep(0.1)
        restored = len(router.routable) >= replicas
        evaluators = {"supervisor": sup_alerts, "driver": drv_alerts}
        _settle_alerts(evaluators, [sup.tick, drv_alert_tick])
        alerts = _alert_outcome(evaluators, [sup_events, drv_events])
        sup.flush_metrics()
        snap = registry.snapshot()
        restarts = int(snap.get(_supervisor_mod.RESTARTS_COUNTER, 0))
        failovers = int(snap.get(_router_mod.FAILOVERS_COUNTER, 0))
        ok = bool(stats["responses_ok"] == requests
                  and stats["dropped"] == 0
                  and victim["slot"] is not None
                  and restarts >= 1 and restored
                  # Fire-AND-resolve: both vantage points saw the kill
                  # (restart rate + lease staleness) and every alert
                  # closed once the fleet healed.
                  and "replica_restarts" in alerts["fired_kinds"]
                  and "replica_lease_stale" in alerts["fired_kinds"]
                  and alerts["resolved_all"])
        return {"ok": ok, "stats": stats, "victim_slot": victim["slot"],
                "restarts": restarts, "failovers": failovers,
                "breaker_trips": int(snap.get(
                    _router_mod.BREAKER_TRIPS_COUNTER, 0)),
                "restored": restored, "alerts": alerts, "metrics": snap}
    finally:
        sup.stop()
        client.close()


def phase_crash_loop(out: str, cfg_path: str, cfg_doc: dict,
                     ckpt_dir: str, *, replicas: int, requests: int,
                     tenants: int, quick: bool, image_shape) -> dict:
    fleet_dir = os.path.join(out, "fleet_crash")
    registry = _MiniMetrics()
    router = _router_for(fleet_dir, cfg_doc, registry)
    poisoned_slot = replicas  # one EXTRA slot beyond the healthy fleet
    sup_events = os.path.join(out, "events_supervisor_crash.jsonl")
    # The crash-loop story is entirely supervisor-side: each boot
    # failure bumps restarts (rate alert, warn) until the breaker
    # trips crash_loops (rate alert, critical). Rate rules resolve on
    # the first quiet evaluation — the FAILED slot stops respawning,
    # so a post-leg settle pass must end with zero active alerts.
    sup_alerts = _make_evaluator(
        [{"name": "replica_crash_loop", "type": "rate",
          "metric": _supervisor_mod.CRASH_LOOPS_COUNTER,
          "op": ">", "value": 0, "for_s": 0, "severity": "critical"},
         {"name": "replica_restarts", "type": "rate",
          "metric": _supervisor_mod.RESTARTS_COUNTER,
          "op": ">", "value": 0, "for_s": 0, "severity": "warn"}],
        source="supervisor",
        snapshot_path=os.path.join(out, "ALERTS_crash_sup.json"))
    sup = _supervisor_mod.ReplicaSupervisor(
        fleet_dir, make_spawn(out, cfg_path, ckpt_dir, fleet_dir,
                              poisoned={poisoned_slot}),
        desired=replicas + 1, scale_min=1, scale_max=replicas + 1,
        max_restarts=2, restart_window_s=300.0,
        stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
        dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
        start_timeout_s=420.0, backoff_base_s=0.1, backoff_cap_s=0.5,
        registry=registry, events_path=sup_events,
        alert_evaluator=sup_alerts)
    client = FleetClient(router, fleet_dir)
    try:
        # The poisoned slot crash-loops DURING boot: wait for the
        # healthy N live AND the breaker trip.
        _boot_fleet(sup, client, router, want_live=replicas,
                    want_failed=1)
        _, schedule = build_schedule(requests, tenants, 1, image_shape,
                                     bench_bucket(quick))

        def on_tick(_now: float) -> None:
            sup.tick()
            client.pump()

        stats = drive_leg(router, client.conns, schedule,
                          max_outstanding=4 * replicas,
                          stall_timeout_s=180.0 if quick else 300.0,
                          on_tick=on_tick)
        evaluators = {"supervisor": sup_alerts}
        _settle_alerts(evaluators, [sup.tick])
        alerts = _alert_outcome(evaluators, [sup_events])
        sup.flush_metrics()
        snap = registry.snapshot()
        crash_loops = int(snap.get(
            _supervisor_mod.CRASH_LOOPS_COUNTER, 0))
        failed_state = (sup.states().get(poisoned_slot)
                        == _supervisor_mod.FAILED)
        ok = bool(stats["responses_ok"] == requests
                  and stats["dropped"] == 0
                  and crash_loops >= 1 and failed_state
                  and len(router.routable) == replicas
                  and "replica_crash_loop" in alerts["fired_kinds"]
                  and alerts["resolved_all"])
        return {"ok": ok, "stats": stats,
                "poisoned_slot": poisoned_slot,
                "crash_loops": crash_loops,
                "restarts": int(snap.get(
                    _supervisor_mod.RESTARTS_COUNTER, 0)),
                "slot_failed": failed_state,
                "served_at": len(router.routable),
                "alerts": alerts, "metrics": snap}
    finally:
        sup.stop()
        client.close()


def phase_burst(out: str, cfg_path: str, cfg_doc: dict, ckpt_dir: str,
                *, requests: int, warm_requests: int, quick: bool,
                image_shape) -> dict:
    fleet_dir = os.path.join(out, "fleet_burst")
    registry = _MiniMetrics()
    router = _router_for(fleet_dir, cfg_doc, registry)
    sup = _supervisor_mod.ReplicaSupervisor(
        fleet_dir, make_spawn(out, cfg_path, ckpt_dir, fleet_dir),
        desired=1, scale_min=1, scale_max=1,
        stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
        dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
        start_timeout_s=420.0, registry=registry,
        events_path=os.path.join(out, "events_supervisor_burst.jsonl"))
    client = FleetClient(router, fleet_dir)
    try:
        _boot_fleet(sup, client, router, want_live=1)

        def on_tick(_now: float) -> None:
            sup.tick()
            client.pump()

        # Trickle: distinct tenants at low concurrency — every request
        # pays the full miss-adapt, seeding the admission controller's
        # service-time EWMA with the honest per-batch cost.
        _, warm_sched = build_schedule(warm_requests, warm_requests, 2,
                                       image_shape, bench_bucket(quick))
        warm = drive_leg(router, client.conns, warm_sched,
                         max_outstanding=2,
                         stall_timeout_s=180.0 if quick else 300.0,
                         on_tick=on_tick)
        # Prime: a saturating-but-survivable wave (distinct tenants,
        # concurrency well under the deadline's queue budget) that
        # trains the EWMA on BUSY completion intervals — the drain
        # rate a backlog actually pays, which idle trickle batches
        # understate ~2x. Without this the flood's head is admitted
        # on trickle-rate estimates faster than the EWMA can converge.
        prime_n = 96 if quick else 128
        _, prime_sched = build_schedule(prime_n, prime_n, 5,
                                        image_shape, bench_bucket(quick))
        prime = drive_leg(router, client.conns, prime_sched,
                          max_outstanding=prime_n,
                          stall_timeout_s=180.0 if quick else 300.0,
                          on_tick=on_tick)
        # Burst: ALL-distinct tenants (every request is a real adapt —
        # offered work genuinely exceeds one replica's service rate) at
        # a concurrency whose full-queue wait sits PAST the deadline.
        # The admission controller holds the queue at the depth its
        # deadline math allows and refuses the rest at the door.
        _, burst_sched = build_schedule(requests, requests, 3,
                                        image_shape, bench_bucket(quick))
        burst = drive_leg(router, client.conns, burst_sched,
                          max_outstanding=requests,
                          stall_timeout_s=180.0 if quick else 300.0,
                          on_tick=on_tick)
        per_replica = {}
        for rid, conn in client.conns.items():
            try:
                per_replica[str(rid)] = conn.stats()
            except Exception as e:  # noqa: BLE001
                per_replica[str(rid)] = {"error": str(e)}
        shed = int(burst["shed"])
        replica_sheds = sum(
            int((rec.get("stats") or {}).get("sheds") or 0)
            for rec in per_replica.values())
        failed = int(burst["status_counts"].get("failed", 0))
        slo_ms = float(cfg_doc["fleet_slo_p95_ms"])
        p95 = burst["p95_ms"]
        # Shed-rate alert over the driver's own observation ledger: the
        # replica flushes its shed counter only at exit, so the driver
        # mirrors the refusals it SAW into serve/shed_total and replays
        # the burst timeline through the rate rule — quiet baseline
        # (first observation), the burst's refusals (fires), cooldown
        # with the counter still (resolves). Synthetic timestamps keep
        # the rate math deterministic.
        sh_events = os.path.join(out, "events_driver_burst.jsonl")
        sh_alerts = _make_evaluator(
            [{"name": "admission_shedding", "type": "rate",
              "metric": "serve/shed_total",
              "op": ">", "value": 0, "for_s": 0, "severity": "warn"}],
            source="driver",
            snapshot_path=os.path.join(out, "ALERTS_burst_driver.json"))
        sh_appender = _supervisor_mod._EventAppender(sh_events)
        t0 = time.time()
        # Materialize the counter at 0 BEFORE the baseline pass: a rate
        # rule ignores an absent metric entirely, so without this the
        # post-burst value would itself become the baseline and the
        # alert could never fire.
        registry.counter("serve/shed_total")
        sh_alerts.evaluate(t0, snapshot=registry.snapshot(),
                           jsonl=sh_appender, registry=registry)
        registry.counter("serve/shed_total").inc(shed)
        sh_alerts.evaluate(t0 + 1.0, snapshot=registry.snapshot(),
                           jsonl=sh_appender, registry=registry)
        sh_alerts.evaluate(t0 + 2.0, snapshot=registry.snapshot(),
                           jsonl=sh_appender, registry=registry)
        alerts = _alert_outcome({"driver": sh_alerts}, [sh_events])
        ok = bool(burst["dropped"] == 0 and warm["dropped"] == 0
                  and prime["dropped"] == 0
                  and shed > 0 and failed == 0
                  and replica_sheds >= shed > 0
                  and p95 is not None and p95 <= slo_ms
                  and "admission_shedding" in alerts["fired_kinds"]
                  and alerts["resolved_all"])
        return {"ok": ok, "warm": warm, "prime": prime, "stats": burst,
                "shed": shed, "replica_sheds": replica_sheds,
                "deadline_misses": failed,
                "admitted_p95_ms": p95, "slo_p95_ms": slo_ms,
                "alerts": alerts,
                "per_replica": per_replica, "metrics": registry.snapshot()}
    finally:
        sup.stop()
        client.close()


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="serving-fleet chaos suite (kill / crash_loop / "
                    "burst)")
    ap.add_argument("--quick", action="store_true",
                    help="2-replica CI smoke with a small load")
    ap.add_argument("--out", default=None)
    ap.add_argument("--phases", default="kill,crash_loop,burst",
                    help="comma list from {kill,crash_loop,burst}")
    ap.add_argument("--replicas", type=int, default=None)
    args = ap.parse_args(argv)

    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    bad = set(phases) - {"kill", "crash_loop", "burst"}
    if bad:
        raise SystemExit(f"unknown phases: {sorted(bad)}")
    replicas = args.replicas or (2 if args.quick else 3)
    requests = 48 if args.quick else 150
    tenants = 8 if args.quick else 16
    burst_requests = 240 if args.quick else 400
    warm_requests = 12 if args.quick else 24

    artifact: Dict[str, Any] = {
        "metric": "chaos_fleet", "value": None, "unit": "phases_ok",
        "status": "failed", "quick": bool(args.quick),
        "replicas": replicas, "phases_run": phases,
    }
    if not _can_bind_localhost():
        artifact.update({"status": "skipped",
                         "skip_reason": "cannot bind localhost sockets"})
        print(json.dumps(artifact), flush=True)
        return 0

    out = args.out or tempfile.mkdtemp(prefix="chaos_fleet_")
    made_tmp = args.out is None
    os.makedirs(out, exist_ok=True)
    ckpt_dir = os.path.join(out, "saved_models")
    l2_dir = os.path.join(out, "l2")
    l1_capacity = 4 * tenants

    # One shared serving profile; the burst phase layers the shed
    # policy on top (all overrides are AOT-runtime-only keys, so every
    # phase hits the ONE prewarmed store).
    base_doc = fleet_cfg_dict(out, quick=args.quick,
                              l1_capacity=l1_capacity, l2_dir=l2_dir)
    burst_doc = dict(base_doc)
    burst_doc.update(
        fleet_shed_policy="deadline",
        # 2.5s leaves deliberate margin over the worst honest admit:
        # the flood's head is admitted before the service-time EWMA
        # converges to the loaded drain rate (~0.3s in), and those
        # requests ride the full queue (~2.0s at depth ~200). The
        # deadline must sit above that or the phase asserts on misses
        # the estimator could never have predicted.
        serve_default_deadline_ms=2500.0,
        serve_max_queue_depth=512,
        fleet_slo_p95_ms=3000.0)
    cfg_base = os.path.join(out, "cfg_chaos.json")
    cfg_burst = os.path.join(out, "cfg_burst.json")
    with open(cfg_base, "w") as f:
        json.dump(base_doc, f)
    with open(cfg_burst, "w") as f:
        json.dump(burst_doc, f)

    image_shape = (base_doc["image_height"], base_doc["image_width"],
                   base_doc["image_channels"])
    results: Dict[str, Any] = {}
    try:
        t_prep = time.monotonic()
        _run_child("prepare", cfg_base, ckpt_dir, out)
        artifact["prepare_seconds"] = round(time.monotonic() - t_prep, 1)
        if "kill" in phases:
            results["kill"] = phase_kill(
                out, cfg_base, base_doc, ckpt_dir, replicas=replicas,
                requests=requests, tenants=tenants, quick=args.quick,
                image_shape=image_shape)
        if "crash_loop" in phases:
            results["crash_loop"] = phase_crash_loop(
                out, cfg_base, base_doc, ckpt_dir,
                replicas=max(replicas - 1, 1), requests=requests,
                tenants=tenants, quick=args.quick,
                image_shape=image_shape)
        if "burst" in phases:
            results["burst"] = phase_burst(
                out, cfg_burst, burst_doc, ckpt_dir,
                requests=burst_requests, warm_requests=warm_requests,
                quick=args.quick, image_shape=image_shape)

        n_ok = sum(1 for r in results.values() if r.get("ok"))
        kill = results.get("kill") or {}
        crash = results.get("crash_loop") or {}
        burst = results.get("burst") or {}
        # Fire-AND-resolve rollup across phases: which alert rules the
        # chaos actually tripped, and whether every one of them closed
        # once the fleet healed. Both gate "recovered" — a fleet that
        # serves every request but leaves an alert stuck firing has NOT
        # recovered by the ops plane's definition.
        phase_alerts = [r.get("alerts") or {} for r in results.values()]
        alert_fired_kinds = sorted(
            {k for a in phase_alerts for k in a.get("fired_kinds", [])})
        alerts_resolved = bool(phase_alerts and all(
            a.get("resolved_all") for a in phase_alerts))
        # Post-chaos console render: the SAME status CLI an operator
        # would run, over the phase out-dir exhaust — and it must agree
        # that nothing is still firing.
        console = _console_check(out)
        recovered = bool(n_ok == len(phases)
                         and len(results) == len(phases)
                         and alerts_resolved
                         and console.get("alerts_firing") == 0)
        ok = recovered
        artifact.update({
            "status": "ok" if ok else "failed",
            "value": n_ok,
            "phases": results,
            # Schema-stable robustness keys (serve_bench/fleet_bench
            # carry the same names): null when the phase didn't run.
            "fleet_restarts": kill.get("restarts"),
            "fleet_crash_loops": crash.get("crash_loops"),
            "fleet_failover_count": kill.get("failovers"),
            "fleet_shed_count": burst.get("shed"),
            "alert_fired_kinds": alert_fired_kinds,
            "alerts_fired": sum(int(a.get("fired") or 0)
                                for a in phase_alerts),
            "alerts_resolved": alerts_resolved,
            "alerts_active_final": sum(int(a.get("active_final") or 0)
                                       for a in phase_alerts),
            "recovered": recovered,
            "console": console,
            "out_dir": None if made_tmp else out,
        })
        print(json.dumps(artifact), flush=True)
        if made_tmp and ok:
            shutil.rmtree(out, ignore_errors=True)
        return 0 if ok else 1
    except Exception as e:  # noqa: BLE001 — the artifact IS the report
        artifact.update({"status": "failed",
                         "error": f"{type(e).__name__}: {e}",
                         "phases": results, "out_dir": out})
        print(json.dumps(artifact), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())

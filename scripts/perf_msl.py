"""MSL-window step timing: serial in-scan target forwards vs the batched
out-of-scan form (config ``msl_target_batching``), plus the steady-state
(non-MSL) step for context. VERDICT r1 next-round #4.

The MSL window is epochs 0..multi_step_loss_num_epochs-1 of every MAML++
run (15% of the flagship schedule); its executable computes a target-set
forward after EVERY inner step instead of only the last.

Usage: python scripts/perf_msl.py [--steps N] [--batch B]
Prints one JSON line per variant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, synthetic_batch
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, replicated_sharding, shard_batch)


def time_step(cfg, msl: bool, steps: int, windows: int = 3) -> float:
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices()[:1])
    plan = make_sharded_steps(cfg, apply, mesh)
    # Epoch 0 = inside the MSL window; last epoch = steady state.
    epoch = jnp.float32(0.0 if msl else cfg.total_epochs - 1)
    train = plan.train_steps[(True, msl)]
    state = jax.device_put(
        init_train_state(cfg, init, jax.random.PRNGKey(0)),
        replicated_sharding(mesh))
    ep = shard_batch(synthetic_batch(cfg, 0), mesh)
    for _ in range(3):
        state, m = train(state, ep, epoch)
        float(jax.device_get(m.loss))
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = train(state, ep, epoch)
        loss = float(jax.device_get(m.loss))
        dt = time.perf_counter() - t0
        assert np.isfinite(loss)
        rates.append(cfg.batch_size * steps / dt)
    return float(np.median(rates))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=12)
    args = ap.parse_args()

    base = flagship_config(args.batch, 1)
    variants = [
        ("steady_state_non_msl", base, False),
        ("msl_serial_in_scan", base.replace(msl_target_batching="off"),
         True),
        ("msl_batched_out_of_scan", base.replace(msl_target_batching="on"),
         True),
    ]
    results = {}
    for name, cfg, msl in variants:
        rate = time_step(cfg, msl, args.steps)
        results[name] = rate
        print(json.dumps({"variant": name,
                          "tasks_per_sec_per_chip": round(rate, 3)}),
              flush=True)
    if results.get("msl_serial_in_scan"):
        print(json.dumps({
            "batched_vs_serial_speedup": round(
                results["msl_batched_out_of_scan"]
                / results["msl_serial_in_scan"], 4),
            "msl_penalty_serial": round(
                1 - results["msl_serial_in_scan"]
                / results["steady_state_non_msl"], 4),
            "msl_penalty_batched": round(
                1 - results["msl_batched_out_of_scan"]
                / results["steady_state_non_msl"], 4)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Measured feed-floor decomposition for driven-vs-bench (VERDICT r4
next #5).

bench.py times the step with the batch PRE-STAGED on device; a driven
run must push every fresh episode batch through the axon tunnel. With
the r4 worker-side placement overlap, a driven epoch's throughput floor
is

    tasks/s  <=  batch_size / max(t_transfer, t_step)

where t_transfer = batch_bytes / tunnel_bandwidth (uint8 wire format)
and t_step is the device step time bench measures. This script measures
all three terms in one session on the real chip and prints the
decomposition as JSON lines:

1. tunnel bandwidth: median device_put wall-clock of the exact flagship
   uint8 episode batch (shape and dtype identical to the loader's wire
   format), fresh buffers each rep so nothing is cached;
2. device step time: bench.measure_rate on the shipped flagship
   steady-state executable (pre-staged batch, pipelined dispatch — the
   same methodology as every bench number);
3. the implied driven ceiling max(transfer, step), its ratio to the
   pre-staged bench rate, and which term binds.

If t_transfer > t_step the driven gap is the LINK's, not the code's: no
scheduling change on this host can reach 0.9x bench, and the honest
deliverable is this table (PERF.md § Round 5 data-path floor). On a
real TPU VM (PCIe/DMA attach) t_transfer shrinks ~100x and the floor
becomes t_step.

Usage: python scripts/feed_floor.py [--reps 9] [--steps 12]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

import bench


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=9)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--config", default=None)
    args = ap.parse_args()

    devices = bench.init_backend()
    n_dev = len(devices)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config_path = args.config or os.path.join(
        repo, "experiment_config",
        "mini-imagenet_maml++_5-way_5-shot_DA_b12.json")
    cfg = bench.load_workload(config_path, 0, n_dev)

    # 1. Tunnel bandwidth on the exact wire-format batch. On this
    # backend ``block_until_ready`` has been observed returning without
    # waiting (see bench.measure_rate), so the fence is a host FETCH of
    # a checksum that touches every transferred byte; the fetch+reduce
    # overhead is measured separately on device-resident data and
    # subtracted.
    import jax.numpy as jnp

    ep = bench.synthetic_batch(cfg, 0)
    batch_bytes = sum(np.asarray(f).nbytes for f in ep)

    @jax.jit
    def checksum(e):
        return sum(jnp.sum(f.astype(jnp.float32)) for f in e)

    resident = jax.device_put(ep, devices[0])
    float(jax.device_get(checksum(resident)))  # compile + warm
    fetch_times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        float(jax.device_get(checksum(resident)))
        fetch_times.append(time.perf_counter() - t0)
    t_fetch = float(np.median(fetch_times))

    times = []
    for r in range(args.reps):
        # Fresh host buffers each rep (copy defeats caching by value).
        ep_r = type(ep)(*(np.array(f) + (r % 2) for f in ep))
        t0 = time.perf_counter()
        dev = jax.device_put(ep_r, devices[0])
        float(jax.device_get(checksum(dev)))
        times.append(time.perf_counter() - t0)
        del dev
    t_transfer = max(float(np.median(times)) - t_fetch, 1e-9)
    bw = batch_bytes / t_transfer
    print(json.dumps({
        "probe": "tunnel_bandwidth", "batch_mbytes":
            round(batch_bytes / 1e6, 2),
        "median_put_plus_fence_s": round(float(np.median(times)), 3),
        "fence_overhead_s": round(t_fetch, 3),
        "median_transfer_s": round(t_transfer, 3),
        "mbytes_per_s": round(bw / 1e6, 1),
        "reps": args.reps,
    }), flush=True)

    # 2. Pre-staged device step time (bench methodology).
    wl = bench.build_steady_state(cfg, devices)
    rate = bench.measure_rate(wl.compiled, wl.state, wl.batch_ep, wl.epoch,
                              batch_size=cfg.batch_size, n_dev=n_dev,
                              steps=args.steps)
    t_step = cfg.batch_size / n_dev / rate
    print(json.dumps({
        "probe": "device_step", "tasks_per_sec_per_chip": round(rate, 2),
        "step_s": round(t_step, 3),
    }), flush=True)

    # 3. The floor.
    binding = "transfer" if t_transfer > t_step else "compute"
    ceiling = cfg.batch_size / n_dev / max(t_transfer, t_step)
    print(json.dumps({
        "probe": "driven_floor",
        "workload": cfg.experiment_name,
        "t_transfer_s": round(t_transfer, 3),
        "t_step_s": round(t_step, 3),
        "binding_term": binding,
        "driven_ceiling_tasks_per_sec_per_chip": round(ceiling, 2),
        "bench_rate_tasks_per_sec_per_chip": round(rate, 2),
        "driven_ceiling_over_bench": round(ceiling / rate, 3),
        "note": ("transfer-bound on this tunneled link: no host-side "
                 "scheduling can exceed the ceiling; a PCIe-attached "
                 "TPU VM removes the term" if binding == "transfer"
                 else "compute-bound: driven should approach bench"),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Live fleet status console — the ops plane's one-table view.

Usage:
    python scripts/ops_console.py <out_dir | events.jsonl ...>
        [--fleet-dir DIR] [--watch SECONDS [--refreshes N]] [--json]
        [--stalled-s S] [--dead-s S] [--slo-p95-ms MS]
        [--slo-target-frac F]

Renders the whole fleet in one screen from its on-disk exhaust — no
RPC to any process, so it works on a live fleet, a dead one, and a
finished chaos/bench out dir alike:

* **replicas** — every membership lease with its verdict
  (live/stalled/dead, draining), model version, queue depth, p95 and
  the peer's own ``alerts_firing`` summary from the lease payload;
* **rollout** — ROLLOUT.json state + stage and the last observed
  ``fleet/canary_weight``;
* **SLO** — per-tenant p95 / bad% / burn rate over sampled
  ``request_trace`` roots, plus the fleet burn-rate gauge;
* **alerts** — the active set by severity, from ``ALERTS*.json``
  snapshots when present, else reconstructed from ``alert`` event rows
  (last transition per (source, rule, labels) wins).

``--watch S`` re-renders every S seconds (``--refreshes N`` bounds the
loop; Ctrl-C exits cleanly). The LAST stdout line is always the
machine-readable ``{"metric": "ops_console", ...}`` artifact (bench.py
discipline; schema pinned by tests/test_alerts.py). Exit codes: 0 ok,
1 nothing to render, 2 bad usage.

No JAX import — runs on a login node: alerts.py, aggregate.py,
tracing.py and the fleet router are stdlib-only and loaded by file
path (importing the package would execute ``__init__`` chains that do
import jax).
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import math
import os
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_PKG = "howtotrainyourmamlpytorch_tpu"
_tracing = _load_module("_console_tracing_impl",
                        os.path.join(_PKG, "utils", "tracing.py"))
_alerts = _load_module("_console_alerts_impl",
                       os.path.join(_PKG, "telemetry", "alerts.py"))
_aggregate = _load_module("_console_aggregate_impl",
                          os.path.join(_PKG, "telemetry", "aggregate.py"))
_router = _load_module("_console_router_impl",
                       os.path.join(_PKG, "serve", "fleet", "router.py"))
nearest_rank = _tracing.nearest_rank

_GAUGES = ("fleet/canary_weight", "fleet/slo_burn_rate",
           "fleet/queue_depth_total", "fleet/replicas_live",
           "fleet/replicas_desired")
# Lifetime counters worth totalling fleet-wide (reset-aware). Explicit
# list on purpose: a metrics row does not distinguish counters from
# gauges, and reset-aware accumulation of a gauge is nonsense.
_COUNTERS = ("fleet/restarts", "fleet/crash_loops", "fleet/scale_ups",
             "fleet/scale_downs", "fleet/failovers",
             "fleet/router_spills", "fleet/slo_good_total",
             "fleet/slo_bad_total", "serve/shed_total",
             "serve/requests_total", "serve/responses_total")


def discover_fleet_dir(paths: List[str]) -> Optional[str]:
    """First directory holding membership leases: each input dir
    itself, its ``fleet/`` child, then any immediate subdirectory
    (chaos_fleet keeps one fleet dir per phase)."""
    candidates: List[str] = []
    for path in paths:
        if not os.path.isdir(path):
            continue
        candidates.append(path)
        candidates += sorted(
            d for d in glob.glob(os.path.join(path, "*"))
            if os.path.isdir(d))
    for cand in candidates:
        if glob.glob(os.path.join(
                cand, f"{_router.LEASE_PREFIX}*{_router.LEASE_SUFFIX}")):
            return cand
    return None


def replica_table(fleet_dir: Optional[str], *, stalled_s: float,
                  dead_s: float) -> List[Dict[str, Any]]:
    if not fleet_dir:
        return []
    members = _router.read_members(fleet_dir)
    rows = []
    for rid in sorted(members):
        rec = members[rid]
        payload = rec.get("payload") or {}
        stats = payload.get("stats") or {}
        verdict = _router.classify(rec["age"], stalled_s, dead_s)
        firing = payload.get("alerts_firing") or {}
        rows.append({
            "replica": rid,
            "verdict": verdict,
            "draining": bool(rec.get("draining")),
            "age_s": (round(rec["age"], 2)
                      if math.isfinite(rec["age"]) else None),
            "version": payload.get("version"),
            "queue_depth": stats.get("queue_depth"),
            "p95_ms": stats.get("p95_ms"),
            "alerts_firing": firing.get("count"),
            "alerts_max_severity": firing.get("max_severity"),
        })
    return rows


def slo_table(rows: List[Dict[str, Any]], *, slo_p95_ms: float,
              slo_target_frac: float) -> Dict[str, Any]:
    """Per-tenant burn over sampled request roots (slo_report.py's
    convention, minus the trace assembly — the console only needs the
    root latencies)."""
    per_tenant: Dict[str, List[float]] = {}
    for row in rows:
        if row.get("event") != "request_trace":
            continue
        if row.get("name") != "request" or row.get("parent_id") is not None:
            continue
        dur = row.get("dur_s")
        if isinstance(dur, (int, float)) and math.isfinite(float(dur)):
            per_tenant.setdefault(str(row.get("tenant") or "?"),
                                  []).append(float(dur) * 1e3)
    tenants: Dict[str, Dict[str, Any]] = {}
    for tenant in sorted(per_tenant):
        vals = sorted(per_tenant[tenant])
        bad_frac = sum(1 for v in vals if v > slo_p95_ms) / len(vals)
        tenants[tenant] = {
            "count": len(vals),
            "p95_ms": round(nearest_rank(vals, 0.95), 2),
            "bad_frac": round(bad_frac, 4),
            "burn_rate": round(bad_frac / (1.0 - slo_target_frac), 3),
        }
    return tenants


def active_alerts(paths: List[str],
                  rows: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The firing set: ALERTS*.json snapshots win (they are the
    evaluators' own word); without any, replay the ``alert`` event rows
    — last transition per (source, rule, labels) wins."""
    snap_paths: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            snap_paths += sorted(glob.glob(os.path.join(path,
                                                        "ALERTS*.json")))
            snap_paths += sorted(glob.glob(os.path.join(path, "logs",
                                                        "ALERTS*.json")))
    docs = _alerts.read_snapshots(snap_paths)
    if docs:
        firing = [dict(r) for d in docs for r in d["firing"]]
    else:
        last: Dict[tuple, Dict[str, Any]] = {}
        for row in rows:
            if row.get("event") != _alerts.ALERT_EVENT:
                continue
            key = (row.get("source"), row.get("rule"),
                   json.dumps(row.get("labels") or {}, sort_keys=True))
            last[key] = row
        firing = [dict(r) for r in last.values()
                  if r.get("state") == "firing"]
    firing.sort(key=lambda r: (
        -_alerts.severity_rank(r.get("severity", "info")),
        str(r.get("rule"))))
    return firing


def summarize(paths: List[str], *, fleet_dir: Optional[str],
              stalled_s: float, dead_s: float, slo_p95_ms: float,
              slo_target_frac: float) -> Dict[str, Any]:
    rows = _aggregate.collect_fleet_events(paths)
    fleet_dir = fleet_dir or discover_fleet_dir(paths)
    replicas = replica_table(fleet_dir, stalled_s=stalled_s,
                             dead_s=dead_s)
    gauges = _aggregate.latest_gauges(rows, list(_GAUGES))
    totals = _aggregate.fleet_counter_totals(rows)
    rollout: Dict[str, Any] = {}
    if fleet_dir:
        try:
            with open(os.path.join(fleet_dir,
                                   _router.ROLLOUT_FILE
                                   if hasattr(_router, "ROLLOUT_FILE")
                                   else "ROLLOUT.json")) as f:
                doc = json.load(f)
            if isinstance(doc, dict):
                rollout = {"state": doc.get("state"),
                           "stage": doc.get("stage")}
        except (OSError, ValueError):
            pass
    alerts = active_alerts(paths, rows)
    by_sev = {sev: 0 for sev in _alerts.SEVERITIES}
    for a in alerts:
        if a.get("severity") in by_sev:
            by_sev[a["severity"]] += 1
    return {
        "metric": "ops_console",
        "events_rows": len(rows),
        "sources": sorted({str(r.get("source", "")) for r in rows
                           if r.get("source")}),
        "fleet_dir": fleet_dir,
        "replicas": replicas,
        "replicas_live": sum(1 for r in replicas
                             if r["verdict"] == _router.LIVE
                             and not r["draining"]),
        "rollout_state": rollout.get("state"),
        "rollout_stage": rollout.get("stage"),
        "canary_weight": gauges["fleet/canary_weight"],
        "slo_burn_rate": gauges["fleet/slo_burn_rate"],
        "tenants": slo_table(rows, slo_p95_ms=slo_p95_ms,
                             slo_target_frac=slo_target_frac),
        "counters": {k: totals[k] for k in sorted(totals)
                     if k in _COUNTERS},
        "alerts_firing": len(alerts),
        "alerts_by_severity": by_sev,
        "alerts": alerts,
    }


def format_console(s: Dict[str, Any]) -> str:
    lines = [
        "ops_console",
        f"  sources {len(s['sources'])}  rows {s['events_rows']}"
        + (f"  fleet_dir {s['fleet_dir']}" if s["fleet_dir"] else ""),
        "",
        f"  {'replica':>7} {'verdict':<9} {'age_s':>7} {'version':<22} "
        f"{'queue':>5} {'p95_ms':>8} {'alerts':>6}",
    ]
    for r in s["replicas"]:
        verdict = r["verdict"] + ("*" if r["draining"] else "")
        firing = ("-" if r["alerts_firing"] is None else
                  f"{r['alerts_firing']}"
                  + (f"!{r['alerts_max_severity'][0]}"
                     if r["alerts_max_severity"] else ""))
        lines.append(
            f"  {r['replica']:>7} {verdict:<9} "
            f"{'-' if r['age_s'] is None else format(r['age_s'], '.2f'):>7} "
            f"{str(r['version'] or '-'):<22.22} "
            f"{'-' if r['queue_depth'] is None else r['queue_depth']:>5} "
            f"{'-' if r['p95_ms'] is None else format(r['p95_ms'], '.1f'):>8}"
            f" {firing:>6}")
    if not s["replicas"]:
        lines.append("  (no membership leases found)")
    lines.append("")
    lines.append(
        f"  rollout: state={s['rollout_state'] or '-'} "
        f"stage={'-' if s['rollout_stage'] is None else s['rollout_stage']}"
        f"  canary_weight="
        f"{'-' if s['canary_weight'] is None else s['canary_weight']}"
        f"  slo_burn="
        f"{'-' if s['slo_burn_rate'] is None else s['slo_burn_rate']}")
    if s["tenants"]:
        lines.append("")
        lines.append(f"  {'tenant':<16} {'count':>6} {'p95_ms':>9} "
                     f"{'bad%':>7} {'burn':>7}")
        for tenant, row in s["tenants"].items():
            lines.append(
                f"  {tenant:<16} {row['count']:>6} {row['p95_ms']:>9.1f} "
                f"{row['bad_frac']:>6.1%} {row['burn_rate']:>7.2f}")
    lines.append("")
    if s["alerts"]:
        lines.append(f"  ALERTS FIRING ({s['alerts_firing']}):")
        for a in s["alerts"]:
            labels = a.get("labels") or {}
            label_s = " ".join(f"{k}={v}" for k, v in
                               sorted(labels.items()))
            lines.append(
                f"    [{a.get('severity', '?'):<8}] {a.get('rule')}"
                + (f"  {label_s}" if label_s else "")
                + (f"  value={a.get('value')}"
                   if a.get("value") is not None else ""))
    else:
        lines.append("  alerts: none firing")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="One-table fleet status console over events.jsonl "
                    "exhaust, membership leases and ALERTS.json.")
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl file(s) and/or out/experiment "
                         "directories")
    ap.add_argument("--fleet-dir", default=None,
                    help="membership-lease directory (default: "
                         "auto-discover under the given dirs)")
    ap.add_argument("--watch", type=float, default=0.0, metavar="S",
                    help="re-render every S seconds (0 = one shot)")
    ap.add_argument("--refreshes", type=int, default=0,
                    help="stop --watch after N renders (0 = until ^C)")
    ap.add_argument("--stalled-s", type=float, default=10.0,
                    help="lease age beyond which a replica renders "
                         "stalled")
    ap.add_argument("--dead-s", type=float, default=30.0,
                    help="lease age beyond which a replica renders dead")
    ap.add_argument("--slo-p95-ms", type=float, default=2000.0)
    ap.add_argument("--slo-target-frac", type=float, default=0.95)
    ap.add_argument("--json", action="store_true",
                    help="emit ONLY the JSON artifact line (CI mode)")
    args = ap.parse_args(argv)
    if args.watch < 0 or args.refreshes < 0 \
            or not (args.slo_p95_ms > 0 and 0 < args.slo_target_frac < 1):
        print(json.dumps({"error": "need --watch/--refreshes >= 0, "
                                   "--slo-p95-ms > 0 and 0 < "
                                   "--slo-target-frac < 1"}))
        return 2

    summary: Dict[str, Any] = {}
    renders = 0
    try:
        while True:
            summary = summarize(
                args.paths, fleet_dir=args.fleet_dir,
                stalled_s=args.stalled_s, dead_s=args.dead_s,
                slo_p95_ms=args.slo_p95_ms,
                slo_target_frac=args.slo_target_frac)
            renders += 1
            if not args.json:
                if args.watch > 0 and sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                print(format_console(summary))
            if args.watch <= 0 or (args.refreshes
                                   and renders >= args.refreshes):
                break
            time.sleep(args.watch)
    except KeyboardInterrupt:
        pass

    if not summary:
        print(json.dumps({"error": "nothing rendered"}))
        return 1
    if not (summary["events_rows"] or summary["replicas"]
            or summary["alerts"]):
        print(json.dumps({"error": "no events rows, membership leases "
                                   "or ALERTS.json found under the "
                                   "given paths"}))
        return 1
    # The LAST stdout line is the machine-readable artifact.
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

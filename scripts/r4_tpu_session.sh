#!/bin/bash
# Round-4 hardware session: runs the full VERDICT r3 measurement agenda
# in priority order (most driver-critical first, so a tunnel drop
# mid-session still leaves the most important evidence captured).
# Usage: bash scripts/r4_tpu_session.sh [logdir]   (default /tmp/r4_session)
# Keep the box QUIET while this runs — concurrent compiles contaminate
# every timing (docs/PERF.md § methodology; memory: 1 CPU core).
set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/r4_session}"
mkdir -p "$LOG"
# Persistent XLA compile cache: a session interrupted by a tunnel drop
# resumes without re-paying the multi-minute flagship compiles.
export MAML_COMPILATION_CACHE="${MAML_COMPILATION_CACHE:-/tmp/r4_xla_cache}"
mkdir -p "$MAML_COMPILATION_CACHE"
stamp() { date -u +%H:%M:%S; }
run() { # run <name> <timeout-s> <cmd...>
  local name="$1" to="$2"; shift 2
  echo "[$(stamp)] >>> $name"
  timeout "$to" "$@" > "$LOG/$name.log" 2> "$LOG/$name.err"
  local rc=$?
  echo "[$(stamp)] <<< $name rc=$rc"
  tail -2 "$LOG/$name.log"
  return $rc
}
# bench.py, the sweep, perf_ceiling and perf_eval all run their own
# bench.init_backend (outage retry + watchdog + cache); only the trainer
# leg lacks one — gate it on this bounded wait via `waitb && run ...`.
waitb() {
  timeout 700 python -c \
    "from howtotrainyourmamlpytorch_tpu.utils.backend import wait_for_backend; wait_for_backend(600)" \
    >> "$LOG/backend_wait.log" 2>&1
  local rc=$?
  [ $rc -ne 0 ] && echo "[$(stamp)] backend wait FAILED (leg skipped)"
  return $rc
}

# 1. THE driver artifact: headline + run-weighted + strict-b8 in one
#    JSON object (VERDICT item 1/6). bench.py retries backend init
#    itself for up to 10 min.
run bench_full 3600 python bench.py

# 2. Microbatch sweep over the seven mb=1 configs (item 4).
run mb_sweep 7200 python scripts/perf_microbatch_sweep.py

# 3. Speed-of-light recalibration at the SHIPPED mb=12 executable
#    (item 3): the ceiling model reads the shipped config by default;
#    --cal replays the recorded best-observed envelope (sustained
#    calibration chains understate the time-sliced tunnel's capability
#    — docs/PERF.md § "MFU, corrected by measurement").
run ceiling_cal 3600 python scripts/perf_ceiling.py --cal 3.03,791.5,455.8

# 4. Eval-path throughput at the new operating point (item 7).
run perf_eval 3600 python scripts/perf_eval.py

# 5. Host-feed validation (item 5 done-criterion): a short flagship
#    driven run; compare its synced tasks/s against bench_full's
#    headline — target within ~1.5x after the r4 loader overlap fix.
#    MAML_BACKEND_TIMEOUT gives the trainer the shared bounded retry;
#    the waitb && gate additionally skips the leg outright on a tunnel
#    that stays dead past the wait budget.
export MAML_BACKEND_TIMEOUT=600
waitb && run driven_flagship 5400 python train_maml_system.py \
  --name_of_args_json_file experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json \
  --experiment_name r4_feed_check --dataset_name synthetic_mini_imagenet \
  --total_epochs 2 --total_iter_per_epoch 60 --num_evaluation_tasks 48 \
  --experiment_root /tmp/r4_feed_check \
  --compilation_cache_dir "$MAML_COMPILATION_CACHE"

echo "[$(stamp)] session complete; logs in $LOG"

"""Autotune driver: crash-isolated XLA-flag + structural-knob sweep
with parity-gated winner adoption into the AOT store.

    python scripts/autotune.py \
        --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json \
        --out /path/to/sweep [--space SPACE.json] [--quick] \
        [--accuracy-gate run|skip] [--prove-warm-train]

Drives the tune/ subsystem end to end (docs/PERF.md § Autotune):

1. **Enumerate** the search space (tune/space.py): XLA
   ``compiler_options`` axes x structural config axes (remat policy,
   task microbatching, fast-math BN), validity-pruned, baseline-first.
2. **Sweep**: every trial is its own ``bench.py`` subprocess
   (tune/harness.py) — a bad flag hard-aborts its child and is counted
   (``invalid_flag``/``crashed``/``timeout``/``oom``), never the sweep.
   The ledger (``TUNE.json``, tune/record.py) is atomically rewritten
   around every trial: kill this driver mid-sweep and re-run it, and
   completed trials are NEVER repeated (interrupted ones re-run with
   their attempt count bumped).
3. **Gate**: the best point must beat the baseline, pass the
   bitwise-or-tolerance parity probe against the untuned program
   (scripts/tune_parity.py, subprocess), and pass
   scripts/accuracy_gate.py — or the sweep records an honest
   ``adopted: false`` with the refusing gate. ``--accuracy-gate skip``
   is allowed but RECORDED (boxes without real data cannot run the
   full-schedule gate; the verdict says so).
4. **Adopt**: the winner is written as ``TUNED.json`` — the
   ``xla_compiler_options`` config key (+ structural overrides) a
   launch applies; ``--prove-warm-train`` then prewarns the tuned
   store (scripts/aot_prewarm.py) and launches a real
   ``train_maml_system.py`` run against it, asserting the tuned
   fingerprint dir delivers ``compiles_before_first_step == 0``.

Trial rows and tune/* counters publish through the telemetry registry
into ``<out>/logs/events.jsonl`` (schema v13 "tune" section,
scripts/telemetry_report.py reads it).

Artifact contract: the LAST stdout JSON line is
``{"metric": "autotune", ...}``. Exit 0 iff the sweep completed (a
rejected winner is a completed sweep; a driver error is not).

No JAX import — the driver runs on a login node: tune/* and the config
module are stdlib-only, and the telemetry registry/tracing modules are
loaded by file path (the scripts/telemetry_report.py idiom). The
artifact's ``jax_free`` key proves it per run.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from howtotrainyourmamlpytorch_tpu.tune import harness, record, space  # noqa: E402


def _load_module(name: str, relpath: str, register: str = None):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    if register:
        # Seed sys.modules BEFORE exec so a module whose source says
        # ``from howtotrainyourmamlpytorch_tpu.utils.tracing import …``
        # resolves to this file-path load instead of dragging in the
        # jax-importing package __init__ chain.
        sys.modules[register] = mod
    spec.loader.exec_module(mod)
    return mod


_tracing = _load_module(
    "_tune_tracing", "howtotrainyourmamlpytorch_tpu/utils/tracing.py",
    register="howtotrainyourmamlpytorch_tpu.utils.tracing")
_registry = _load_module(
    "_tune_registry", "howtotrainyourmamlpytorch_tpu/telemetry/registry.py")

TRIALS_RUN = "tune/trials_run"
TRIALS_FAILED = "tune/trials_failed"
TRIALS_RESUMED = "tune/trials_resumed"
INVALID_FLAG = "tune/invalid_flag_failures"


def load_json(path: str):
    with open(path) as f:
        return json.load(f)


def build_proof_config(base: dict, tuned: dict, out_dir: str) -> str:
    """The warm-train proof workload: the winner's knobs at tiny
    shapes + a 2-iteration schedule, store and experiment dirs inside
    the sweep dir. Tiny by design — the proof is about the PLUMBING
    (options -> fingerprint -> prewarmed store -> zero-compile first
    dispatch), which is shape-independent; proving it costs seconds
    instead of the real workload's cold-compile minutes."""
    cfg = dict(base)
    cfg.update(tuned.get("config_overrides") or {})
    cfg.update({
        "experiment_name": str(base.get("experiment_name", "autotune"))
        + "_tuned_proof",
        "xla_compiler_options": tuned.get("xla_compiler_options") or {},
        "image_height": 16, "image_width": 16,
        "cnn_num_filters": 8, "num_stages": 2,
        "batch_size": 2, "mesh_shape": [1, 1],
        "eval_batch_size": 2, "num_evaluation_tasks": 2,
        "total_epochs": 1, "total_iter_per_epoch": 2,
        "max_models_to_save": 1, "live_progress": False,
        "aot_store_dir": os.path.join(out_dir, "aot"),
        "experiment_root": os.path.join(out_dir, "exp"),
    })
    # The winner's microbatch count may not divide the tiny proof
    # batch; clamp like bench's quick path (gcd degradation is
    # bit-equivalent and the proof is not a throughput number).
    mb = int(cfg.get("task_microbatches", 1) or 1)
    if 2 % mb != 0:
        cfg["task_microbatches"] = 1
    path = os.path.join(out_dir, "proof.json")
    with open(path, "w") as f:
        json.dump(cfg, f, indent=2, sort_keys=True)
    return path


def prove_warm_train(proof_cfg_path: str, out_dir: str, env) -> dict:
    """Prewarm the tuned store, launch a REAL training run against it,
    and read the warm_start telemetry row: the acceptance numbers are
    ``compiles_before_first_step == 0`` and the run's fingerprint
    matching the prewarmed (tuned) one."""
    result: dict = {"ok": False}
    prewarm = os.path.join(_REPO, "scripts", "aot_prewarm.py")
    import subprocess
    p = subprocess.run([sys.executable, prewarm, "--config",
                        proof_cfg_path], capture_output=True, text=True,
                       env=env, timeout=1800)
    art = harness.last_json_line(p.stdout)
    if not art or not art.get("ok"):
        result["error"] = ("prewarm failed: "
                           + ((art or {}).get("error")
                              or (p.stdout + p.stderr)[-300:]))
        return result
    result["prewarm_fingerprint"] = art.get("fingerprint")
    result["prewarm_executables"] = art.get("value")
    result["prewarm_options"] = art.get("xla_compiler_options")
    t = subprocess.run([sys.executable,
                        os.path.join(_REPO, "train_maml_system.py"),
                        "--name_of_args_json_file", proof_cfg_path],
                       capture_output=True, text=True, env=env,
                       timeout=1800)
    if t.returncode != 0:
        result["error"] = (f"tuned training run rc {t.returncode}: "
                           + (t.stdout + t.stderr)[-300:])
        return result
    cfg = load_json(proof_cfg_path)
    events_path = os.path.join(cfg["experiment_root"],
                               cfg["experiment_name"], "logs",
                               "events.jsonl")
    warm = None
    try:
        with open(events_path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("event") == "warm_start":
                    warm = row
    except OSError as e:
        result["error"] = f"no warm_start row readable: {e}"
        return result
    if warm is None:
        result["error"] = "training run emitted no warm_start row"
        return result
    result["compiles_before_first_step"] = warm.get(
        "compiles_before_first_step")
    result["fingerprint"] = warm.get("aot_fingerprint")
    fp = str(result.get("prewarm_fingerprint") or "")
    result["fingerprint_match"] = bool(
        fp and str(warm.get("aot_fingerprint") or "") == fp[:16])
    result["ok"] = (warm.get("compiles_before_first_step") == 0
                    and result["fingerprint_match"])
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="XLA-flag + structural-knob autotune sweep with "
                    "parity-gated winner adoption (docs/PERF.md § "
                    "Autotune)")
    ap.add_argument("--config", required=True,
                    help="experiment_config/*.json base workload")
    ap.add_argument("--out", required=True, metavar="DIR",
                    help="sweep directory (ledger, trial configs/logs, "
                         "TUNED.json; re-running against it RESUMES)")
    ap.add_argument("--space", default=None, metavar="SPEC.json",
                    help="search-space spec (tune/space.py § "
                         "space_from_spec); default: the built-in "
                         "in-tree knob space for --platform")
    ap.add_argument("--platform", default=None, choices=("cpu", "tpu"),
                    help="XLA axis family for the default space "
                         "(default: from MAML_JAX_PLATFORM/"
                         "JAX_PLATFORMS, else tpu)")
    ap.add_argument("--trials", type=int, default=0,
                    help="cap enumerated trials (0 = the whole space; "
                         "the baseline always runs)")
    ap.add_argument("--steps", type=int, default=9,
                    help="bench steps per trial leg")
    ap.add_argument("--quick", action="store_true",
                    help="tiny-shape trial legs (bench --quick): CI "
                         "and plumbing proofs, not real captures")
    ap.add_argument("--trial-timeout", type=float, default=900.0,
                    help="seconds per trial subprocess (a wedged "
                         "compile is a counted timeout)")
    ap.add_argument("--parity-steps", type=int, default=2)
    ap.add_argument("--parity-tolerance", type=float, default=5e-3)
    ap.add_argument("--accuracy-gate", choices=("run", "skip"),
                    default="run",
                    help="'skip' records the skip verbatim in the "
                         "verdict (boxes without the real dataset "
                         "cannot run the full-schedule gate)")
    ap.add_argument("--min-accuracy", type=float, default=None,
                    help="forwarded to scripts/accuracy_gate.py")
    ap.add_argument("--accuracy-timeout", type=float, default=0.0,
                    help="seconds for the accuracy gate (0 = none)")
    ap.add_argument("--prove-warm-train", action="store_true",
                    help="after adoption: prewarm the tuned store and "
                         "launch a real training run against it, "
                         "asserting compiles_before_first_step == 0 "
                         "from the tuned fingerprint dir")
    args = ap.parse_args(argv)

    t_start = time.monotonic()
    out_dir = os.path.abspath(args.out)
    os.makedirs(out_dir, exist_ok=True)

    def fail(msg: str) -> int:
        print(json.dumps({"metric": "autotune", "ok": False,
                          "error": msg}), flush=True)
        return 1

    try:
        base_config = load_json(args.config)
    except (OSError, ValueError) as e:
        return fail(f"unreadable --config: {e}")

    platform = (args.platform
                or os.environ.get("MAML_JAX_PLATFORM")
                or os.environ.get("JAX_PLATFORMS") or "tpu").split(",")[0]
    try:
        if args.space:
            sp = space.space_from_spec(load_json(args.space))
        else:
            import math
            mesh_n = max(int(math.prod(base_config.get("mesh_shape",
                                                       [1, 1]))), 1)
            per_dev = (2 if args.quick else max(
                int(base_config.get("batch_size", 1)) // mesh_n, 1))
            sp = space.default_space(platform, per_device_tasks=per_dev)
        trials, pruned = sp.enumerate()
    except (OSError, ValueError, KeyError) as e:
        return fail(f"bad search space: {e}")
    if args.trials > 0:
        trials = trials[:max(args.trials, 1)]

    ledger = record.TrialLedger(out_dir)
    try:
        import hashlib
        ledger.ensure_workload(hashlib.sha256(json.dumps(
            base_config, sort_keys=True, default=str).encode())
            .hexdigest())
    except ValueError as e:
        return fail(str(e))
    registry = _registry.MetricsRegistry()
    jsonl = _tracing.JsonlLogger(os.path.join(out_dir, "logs",
                                              "events.jsonl"))
    for name in (TRIALS_RUN, TRIALS_FAILED, TRIALS_RESUMED,
                 INVALID_FLAG):
        registry.counter(name)

    env = dict(os.environ)
    bench_py = os.path.join(_REPO, "bench.py")
    done = set(ledger.completed_ids())
    interrupted = set(ledger.interrupted_ids())
    ran = resumed = 0
    for trial in trials:
        if trial.trial_id in done:
            resumed += 1
            registry.counter(TRIALS_RESUMED).inc()
            continue
        ledger.begin(trial.trial_id, trial.assignment)
        row = harness.run_trial(
            trial, base_config=base_config, sweep_dir=out_dir,
            bench_py=bench_py, steps=args.steps, quick=args.quick,
            timeout_s=args.trial_timeout, env=env)
        ledger.complete(trial.trial_id, row)
        ran += 1
        registry.counter(TRIALS_RUN).inc()
        if row["outcome"] != "ok":
            registry.counter(TRIALS_FAILED).inc()
            if row["outcome"] == "invalid_flag":
                registry.counter(INVALID_FLAG).inc()
        rec = ledger.record(trial.trial_id)
        jsonl.log("tune_trial", trial_id=trial.trial_id,
                  outcome=row["outcome"],
                  objective=row.get("objective"),
                  objective_key=row.get("objective_key"),
                  assignment=trial.assignment,
                  attempt=rec.get("attempt"),
                  resumed_after_interrupt=(trial.trial_id
                                           in interrupted),
                  seconds=row["seconds"])
        registry.flush_jsonl(jsonl)
        print(json.dumps({"trial": trial.trial_id,
                          "outcome": row["outcome"],
                          "objective": row.get("objective"),
                          "seconds": row["seconds"]}), flush=True)

    counts = ledger.counts()
    baseline = ledger.record(space.BASELINE_TRIAL_ID)
    if baseline is not None:
        baseline = {**baseline, "trial_id": space.BASELINE_TRIAL_ID}
    # Rank in the BASELINE's objective unit only: a trial whose flops
    # walk failed falls back from mfu to tasks/s and a raw max would
    # crown it on unit mismatch alone. No baseline unit (the baseline
    # trial itself failed) -> no ranking at all: an unkeyed cross-unit
    # max would report a bogus 'best' even though adoption refuses.
    base_key = (baseline or {}).get("objective_key")
    best = ledger.best(objective_key=base_key) if base_key else None

    # -- gates ----------------------------------------------------------
    parity = accuracy = None
    candidate = (best if best and baseline
                 and best.get("trial_id") != space.BASELINE_TRIAL_ID
                 and isinstance(baseline.get("objective"), (int, float))
                 and best["objective"] > baseline["objective"] else None)
    gates_reused = False
    if candidate is not None:
        trials_dir = os.path.join(out_dir, "trials")
        winner_cfg = os.path.join(trials_dir,
                                  f"{candidate['trial_id']}.json")
        base_cfg = os.path.join(trials_dir, "baseline.json")
        # Resume contract for the EXPENSIVE legs too: a prior driver
        # segment's gate verdicts for THIS candidate are reused from
        # the ledger (the accuracy gate trains the full schedule —
        # re-paying it on every resume would gut the kill-and-resume
        # story) — but only when produced under the SAME gate
        # parameters (a re-run that tightened the tolerance must
        # re-probe). A stored SKIP never satisfies a --accuracy-gate
        # run request: the operator asked for the real gate this time.
        gate_params = {"parity_steps": args.parity_steps,
                       "parity_tolerance": args.parity_tolerance,
                       "min_accuracy": args.min_accuracy}
        stored = ledger.gates_for(candidate["trial_id"],
                                  params=gate_params)
        if stored is not None:
            parity = stored.get("parity")
            accuracy = stored.get("accuracy")
            if (args.accuracy_gate == "run"
                    and isinstance(accuracy, dict)
                    and accuracy.get("skipped")):
                accuracy = None
            gates_reused = parity is not None and accuracy is not None
        if not (isinstance(parity, dict) and "pass" in parity):
            parity = harness.run_parity(
                winner_cfg, base_cfg,
                parity_py=os.path.join(_REPO, "scripts",
                                       "tune_parity.py"),
                compiler_options=(candidate.get("compiler_options")
                                  or {}),
                steps=args.parity_steps,
                tolerance=args.parity_tolerance,
                timeout_s=args.trial_timeout, env=env)
            jsonl.log("tune_parity", **{k: v for k, v in parity.items()
                                        if k != "metric"})
        if accuracy is None:
            if args.accuracy_gate == "skip":
                accuracy = {"skipped": "--accuracy-gate skip (operator "
                                       "choice; e.g. no real dataset "
                                       "on this box)"}
            else:
                accuracy = harness.run_accuracy_gate(
                    winner_cfg,
                    gate_py=os.path.join(_REPO, "scripts",
                                         "accuracy_gate.py"),
                    min_accuracy=args.min_accuracy,
                    timeout_s=args.accuracy_timeout, env=env)
        ledger.record_gates(candidate["trial_id"], parity, accuracy,
                            params=gate_params)

    verdict = record.decide_adoption(best, baseline, parity, accuracy)

    tuned_doc = {
        "adopted": verdict["adopted"],
        "reason": verdict["reason"],
        "workload": base_config.get("experiment_name"),
        "base_config": os.path.abspath(args.config),
        "objective_key": (best or {}).get("objective_key"),
        "objective": (best or {}).get("objective"),
        "baseline_objective": (baseline or {}).get("objective"),
        "trial_id": (best or {}).get("trial_id"),
        "assignment": (best or {}).get("assignment"),
        "xla_compiler_options": (best or {}).get("compiler_options"),
        "config_overrides": (best or {}).get("config_overrides"),
        "gates": {"parity": parity, "accuracy": accuracy},
    }
    tuned_path = record.write_tuned(out_dir, tuned_doc)

    # -- warm-train proof ----------------------------------------------
    warm_train = None
    if verdict["adopted"] and args.prove_warm_train:
        try:
            proof_cfg = build_proof_config(base_config, tuned_doc,
                                           out_dir)
            warm_train = prove_warm_train(proof_cfg, out_dir, env)
        except Exception as e:  # noqa: BLE001 — the sweep result must
            # survive a proof-leg failure, visibly.
            warm_train = {"ok": False,
                          "error": f"{type(e).__name__}: {e}"}

    jsonl.log("tune_adopt", adopted=verdict["adopted"],
              reason=verdict["reason"],
              trial_id=(best or {}).get("trial_id"),
              objective=(best or {}).get("objective"),
              objective_key=(best or {}).get("objective_key"),
              baseline_objective=(baseline or {}).get("objective"),
              tuned_fingerprint=((warm_train or {})
                                 .get("prewarm_fingerprint")))
    registry.flush_jsonl(jsonl)

    # ok means THIS invocation's enumerated trials all reached a
    # terminal state — judged over the enumeration, not the whole
    # ledger: a trial stranded `running` by an earlier kill that a
    # --trials cap or an edited --space no longer enumerates must not
    # fail every future resume forever.
    ok = all((ledger.record(t.trial_id) or {}).get("status")
             in record.TERMINAL for t in trials)
    artifact = {
        "metric": "autotune",
        "value": (best or {}).get("objective"),
        "unit": (best or {}).get("objective_key"),
        "ok": ok,
        "workload": base_config.get("experiment_name"),
        "trials_total": len(trials),
        "trials_run": ran,
        "trials_resumed": resumed,
        "trials_ok": counts["ok"],
        "trials_failed": counts["failed"],
        "failed_by_outcome": counts["failed_by_outcome"],
        "invalid_flag_failures": counts["failed_by_outcome"].get(
            "invalid_flag", 0),
        "pruned": len(pruned),
        "baseline_objective": (baseline or {}).get("objective"),
        "best": ({k: (best or {}).get(k) for k in
                  ("trial_id", "objective", "objective_key",
                   "assignment", "compiler_options",
                   "config_overrides")} if best else None),
        "gates": {"parity": parity, "accuracy": accuracy},
        "gates_reused": gates_reused,
        "adopted": verdict["adopted"],
        "reason": verdict["reason"],
        "tuned_path": tuned_path,
        "warm_train": warm_train,
        "ledger": ledger.path,
        "events": jsonl.path,
        "seconds": round(time.monotonic() - t_start, 1),
        # The driver's jax-free contract, proven per run rather than
        # promised: trials/gates/proofs all ran as subprocesses.
        "jax_free": "jax" not in sys.modules,
    }
    print(json.dumps(artifact), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# One-command REAL-DATA accuracy gate (VERDICT r4 next #2): full shipped
# schedule -> 600-episode top-5-ensemble test -> JSON pass/fail vs the
# BASELINE.md MAML++ paper table. Refuses synthetic data; a missing
# dataset directory fails onto maybe_unzip_dataset's instructions.
#
#   bash scripts/accuracy_gate.sh \
#       --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA.json
#
# Exit: 0 pass, 2 accuracy below gate, 1 error. See scripts/accuracy_gate.py.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python scripts/accuracy_gate.py "$@"

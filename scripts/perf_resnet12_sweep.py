"""ResNet-12 pod-step tuning sweep (VERDICT r2 weak #3 / next #3).

Gives the tiered-imagenet resnet12 pod workload the same treatment the
VGG flagship got in rounds 1-2: on ONE chip, steady-state executable,
sweep the execution knobs that do not change the science —

  - remat_policy: nothing | dots | conv_outs | block_outs
  - bn_fast_math: off | on
  - compute_dtype: bfloat16 | float32
  - task_microbatches: 1 | 2 | 4 | 8 (at the shipped per-chip batch)
  - per-chip batch at the best combo

Every variant times the REAL sharded second-order train step (the pod
config's own executable re-shaped to the local chip count, exactly as
``bench.py --config`` does). Prints one JSON line per variant; failures
(OOM, compile errors) are recorded, not fatal.

Usage: python scripts/perf_resnet12_sweep.py [--steps N] [--phase base|micro|batch]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_rate, synthetic_batch
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, replicated_sharding, shard_batch)

POD_CONFIG = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "experiment_config", "tiered-imagenet_maml++_5-way_5-shot_resnet12_pod.json")


def pod_cfg(**overrides) -> MAMLConfig:
    base = MAMLConfig.from_json_file(POD_CONFIG)
    n_dev = len(jax.devices())
    per_chip = max(base.batch_size // int(np.prod(base.mesh_shape)), 1)
    cfg = base.replace(batch_size=per_chip * n_dev, mesh_shape=(1, n_dev))
    return cfg.replace(**overrides)


def run_variant(tag: str, steps: int, **overrides) -> None:
    t_start = time.perf_counter()
    try:
        cfg = pod_cfg(**overrides)
        init, apply = make_model(cfg)
        mesh = make_mesh(cfg, jax.devices())
        plan = make_sharded_steps(cfg, apply, mesh)
        # Steady-state epoch, as ExperimentBuilder selects it (second
        # order from epoch 0 for this config: DA boundary is -1).
        ep_idx = max(cfg.total_epochs - 1, 0)
        train = plan.train_steps[(cfg.use_second_order(ep_idx),
                                  cfg.use_msl(ep_idx))]
        state = jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)),
            replicated_sharding(mesh))
        ep = shard_batch(synthetic_batch(cfg, 0), mesh)
        epoch = jnp.float32(ep_idx)
        for _ in range(2):
            state, m = train(state, ep, epoch)
            float(jax.device_get(m.loss))
        compile_s = time.perf_counter() - t_start
        rate = measure_rate(train, state, ep, epoch,
                            batch_size=cfg.batch_size,
                            n_dev=len(jax.devices()),
                            steps=steps, warmup=0)
        print(json.dumps({
            "variant": tag, **overrides,
            "tasks_per_sec_per_chip": round(rate, 3),
            "warmup_s": round(compile_s, 1)}), flush=True)
    except Exception as e:  # noqa: BLE001 — sweep must survive OOMs
        print(json.dumps({
            "variant": tag, **overrides,
            "error": f"{type(e).__name__}: {str(e)[:200]}"}), flush=True)
        traceback.print_exc(file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--phase", default="base",
                    choices=("base", "micro", "batch"))
    args = ap.parse_args()

    if args.phase == "base":
        # remat x bn_fast_math, pinned at mb=2 (the r2 operating point
        # this grid was measured at — the shipped config now carries the
        # winning mb=8, and inheriting it would silently re-measure the
        # grid at a different point than docs/PERF.md documents).
        for policy in ("block_outs", "nothing", "dots", "conv_outs"):
            for fast in (True, False):
                run_variant("remat_x_fastmath", args.steps,
                            remat_policy=policy, bn_fast_math=fast,
                            task_microbatches=2)
        run_variant("compute_f32", args.steps, compute_dtype="float32",
                    task_microbatches=2)
    elif args.phase == "micro":
        # At the base phase's winning point (bn_fast_math on). mb=8 is
        # the measured winner that ships in the pod config.
        for mb in (1, 2, 4, 8):
            run_variant("microbatch", args.steps, task_microbatches=mb,
                        bn_fast_math=True)
    elif args.phase == "batch":
        n_dev = len(jax.devices())
        for b in (1, 2, 4, 8, 12):
            run_variant("per_chip_batch", args.steps,
                        batch_size=b * n_dev, task_microbatches=1,
                        bn_fast_math=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

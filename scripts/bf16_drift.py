"""On-chip bf16-vs-f32 drift bound at the FLAGSHIP geometry (VERDICT r4
next #3).

The torch-oracle parity suite runs on CPU in f32, so the one numerics
risk it cannot see is what the SHIPPED step (compute_dtype=bfloat16 +
bn_fast_math folded statistics) does to logits, meta-gradients and a
training trajectory at the real 84x84x3 / 48-filter / K=5 geometry on
the real chip. This script measures exactly that, against the f32
reference path (compute_dtype=float32, bn_fast_math=False — the
bit-compatible-with-torch configuration the parity tests pin), with
params held in f32 in BOTH variants (param_dtype is always float32; only
conv/matmul compute and the BN statistics path differ).

Measured quantities, each printed as a JSON line:

1. eval-path adapted logits at a fresh init: max/mean abs diff and the
   argmax (prediction) agreement rate over B*N*T predictions — the
   metric accuracy actually depends on;
2. one train step: |loss_bf16 - loss_f32| and per-parameter-group
   relative L2 drift of the POST-UPDATE parameters (meta-gradient drift
   as Adam actually consumes it);
3. a --steps N trajectory (default 50) driven from the same init on the
   same episode stream: per-step loss gap plus final-parameter relative
   drift — how the one-step drift compounds.

Results are recorded in docs/PARITY.md § Flagship-geometry parity, with
the tolerance argument. Usage:

    python scripts/bf16_drift.py [--steps 50] [--batch 12]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import bench
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.meta.outer import (
    make_eval_step, make_train_step)
from howtotrainyourmamlpytorch_tpu.models import make_model


def rel_l2(a, b) -> float:
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    denom = np.linalg.norm(b)
    return float(np.linalg.norm(a - b) / (denom or 1.0))


def group_drift(params_a, params_b) -> dict:
    out = {}
    for name in params_a:
        for leaf in params_a[name]:
            out[f"{name}.{leaf}"] = rel_l2(params_a[name][leaf],
                                           params_b[name][leaf])
    return out


def separable_batch(cfg, seed: int):
    """Learnable episodes (class i pixels ~ N(i/N, 0.3)): both dtype
    variants can actually converge, so END-STATE prediction agreement
    measures accuracy parity rather than chaos on unlearnable noise."""
    rng = np.random.RandomState(seed)
    n, k, t, b = (cfg.num_classes_per_set, cfg.num_samples_per_class,
                  cfg.num_target_samples, cfg.batch_size)
    h, w, c = cfg.image_shape

    def gen(per):
        means = (np.arange(n) / n)[None, :, None, None, None, None]
        x = rng.randn(b, n, per, h, w, c) * 0.3 + means
        x = (np.clip(x, 0, 1) * 255).astype(np.uint8)
        y = np.tile(np.repeat(np.arange(n), per)[None], (b, 1))
        return x.reshape(b, n * per, h, w, c), y.astype(np.int32)

    sx, sy = gen(k)
    tx, ty = gen(t)
    from howtotrainyourmamlpytorch_tpu.meta.inner import Episode
    return Episode(sx, sy, tx, ty)


def build(cfg):
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    train = jax.jit(make_train_step(cfg, apply),
                    static_argnames=("second_order", "use_msl"))
    ev = jax.jit(make_eval_step(cfg, apply))
    return state, train, ev


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--config", default=None)
    args = ap.parse_args()

    devices = bench.init_backend()
    n_dev = len(devices)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config_path = args.config or os.path.join(
        repo, "experiment_config",
        "mini-imagenet_maml++_5-way_5-shot_DA_b12.json")
    cfg_b = bench.load_workload(config_path, args.batch, n_dev)
    # mb is a memory lever, not numerics (accumulation is equivalence-
    # tested); mb=1 keeps the two dtype variants' programs minimal and
    # identical in structure.
    cfg_b = cfg_b.replace(task_microbatches=1, mesh_shape=(1, 1),
                          batch_size=max(cfg_b.batch_size // n_dev, 1))
    cfg_f = cfg_b.replace(compute_dtype="float32", bn_fast_math=False)
    steady = max(cfg_b.total_epochs - 1, 0)
    so, msl = cfg_b.use_second_order(steady), cfg_b.use_msl(steady)

    state_b, train_b, eval_b = build(cfg_b)
    state_f, train_f, eval_f = build(cfg_f)

    # 1. Eval-path adapted logits at the shared init.
    ep = bench.synthetic_batch(cfg_b, 123)
    rb = eval_b(state_b, ep)
    rf = eval_f(state_f, ep)
    lb = np.asarray(jax.device_get(rb.target_logits), np.float64)
    lf = np.asarray(jax.device_get(rf.target_logits), np.float64)
    agree = float((lb.argmax(-1) == lf.argmax(-1)).mean())
    print(json.dumps({
        "probe": "eval_logits", "workload": cfg_b.experiment_name,
        "batch": cfg_b.batch_size,
        "max_abs_diff": round(float(np.abs(lb - lf).max()), 5),
        "mean_abs_diff": round(float(np.abs(lb - lf).mean()), 6),
        "logit_scale_mean_abs": round(float(np.abs(lf).mean()), 4),
        "argmax_agreement": agree,
        "n_predictions": int(lb.shape[0] * lb.shape[1]),
    }), flush=True)

    # 2. One steady-state train step from the shared init.
    sb, mb_ = train_b(state_b, ep, jnp.float32(steady),
                      second_order=so, use_msl=msl)
    sf, mf_ = train_f(state_f, ep, jnp.float32(steady),
                      second_order=so, use_msl=msl)
    drift = group_drift(jax.device_get(sb.params),
                        jax.device_get(sf.params))
    print(json.dumps({
        "probe": "one_step", "second_order": so, "use_msl": msl,
        "loss_bf16": round(float(jax.device_get(mb_.loss)), 6),
        "loss_f32": round(float(jax.device_get(mf_.loss)), 6),
        "post_update_param_rel_l2_max": round(max(drift.values()), 6),
        "post_update_param_rel_l2": {k: round(v, 6)
                                     for k, v in sorted(drift.items())},
    }), flush=True)

    # 3. Trajectories: same stream, both dtypes, from the shared init.
    # Noise stream = worst-case parameter decoherence (unlearnable, so
    # trajectories amplify per-step drift chaotically — true of any two
    # f32 backends as well); separable stream = the accuracy-relevant
    # question (both converge; do they AGREE where it matters?).
    for stream, make_batch in (("noise", bench.synthetic_batch),
                               ("separable", separable_batch)):
        losses_b, losses_f, acc_b, acc_f = [], [], [], []
        state_b2, _, _ = build(cfg_b)
        state_f2, _, _ = build(cfg_f)
        for t in range(args.steps):
            ep_t = make_batch(cfg_b, 1000 + t)
            state_b2, m_b = train_b(state_b2, ep_t, jnp.float32(steady),
                                    second_order=so, use_msl=msl)
            state_f2, m_f = train_f(state_f2, ep_t, jnp.float32(steady),
                                    second_order=so, use_msl=msl)
            losses_b.append(float(jax.device_get(m_b.loss)))
            losses_f.append(float(jax.device_get(m_f.loss)))
            acc_b.append(float(jax.device_get(m_b.accuracy)))
            acc_f.append(float(jax.device_get(m_f.accuracy)))
        gaps = np.abs(np.asarray(losses_b) - np.asarray(losses_f))
        drift_end = group_drift(jax.device_get(state_b2.params),
                                jax.device_get(state_f2.params))
        # End-state eval on a HELD-OUT batch of the same stream.
        ep_h = make_batch(cfg_b, 99)
        re_b = eval_b(state_b2, ep_h)
        re_f = eval_f(state_f2, ep_h)
        lb2 = np.asarray(jax.device_get(re_b.target_logits))
        lf2 = np.asarray(jax.device_get(re_f.target_logits))
        labels = np.asarray(ep_h.target_y)
        print(json.dumps({
            "probe": "trajectory", "stream": stream, "steps": args.steps,
            "loss_gap_max": round(float(gaps.max()), 5),
            "loss_gap_final": round(float(gaps[-1]), 5),
            "loss_final_bf16": round(losses_b[-1], 5),
            "loss_final_f32": round(losses_f[-1], 5),
            "train_acc_final_bf16": round(acc_b[-1], 4),
            "train_acc_final_f32": round(acc_f[-1], 4),
            "final_param_rel_l2_max": round(max(drift_end.values()), 5),
            "final_param_rel_l2_median": round(
                float(np.median(list(drift_end.values()))), 5),
            "final_param_rel_l2": {k: round(v, 5)
                                   for k, v in sorted(drift_end.items())},
            "end_state_argmax_agreement": round(
                float((lb2.argmax(-1) == lf2.argmax(-1)).mean()), 4),
            "end_state_eval_acc_bf16": round(
                float((lb2.argmax(-1) == labels).mean()), 4),
            "end_state_eval_acc_f32": round(
                float((lf2.argmax(-1) == labels).mean()), 4),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Compare a finished run's test protocol output against BASELINE.md.

Usage: python scripts/parity_report.py <logs/test_summary.csv> [--json]

Reads the ensemble test protocol's summary CSV (written by
``ExperimentBuilder.run_test_protocol``), matches the experiment to its
BASELINE.md accuracy row, and prints a pass/gap line per metric. Exits 0
on parity (mean accuracy >= baseline), 3 on a gap, 2 when the baseline
row is unknown (custom config) — so the wrapper script's exit code IS the
parity verdict.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys

# BASELINE.md accuracy table (MAML++ paper numbers the upstream README
# advertises reproducing; mount-unverifiable, see BASELINE.md provenance).
BASELINE_ACCURACY = {
    ("omniglot_dataset", 5, 1): 0.9947,
    ("omniglot_dataset", 5, 5): 0.9993,
    ("omniglot_dataset", 20, 1): 0.9765,
    ("omniglot_dataset", 20, 5): 0.9933,
    ("mini_imagenet_full_size", 5, 1): 0.5215,
    ("mini_imagenet_full_size", 5, 5): 0.6832,
}


def load_summary(path: str) -> dict:
    with open(path) as f:
        rows = list(csv.DictReader(f))
    if not rows:
        raise SystemExit(f"{path}: empty test summary")
    return rows[-1]  # latest protocol run wins


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("summary_csv")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable one-line result")
    args = ap.parse_args(argv)

    row = load_summary(args.summary_csv)
    mean = float(row["test_accuracy_mean"])
    std = float(row["test_accuracy_std"])
    episodes = int(float(row.get("num_episodes", 0)))
    models = int(float(row.get("num_models", 0)))

    # The experiment's config.json lives two levels up from logs/.
    base_dir = os.path.dirname(os.path.dirname(os.path.abspath(
        args.summary_csv)))
    cfg_path = os.path.join(base_dir, "config.json")
    key = None
    if os.path.isfile(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)
        key = (cfg.get("dataset_name"), cfg.get("num_classes_per_set"),
               cfg.get("num_samples_per_class"))
    baseline = BASELINE_ACCURACY.get(key)

    result = {
        "test_accuracy_mean": mean,
        "test_accuracy_std": std,
        "num_episodes": episodes,
        "num_models": models,
        "baseline": baseline,
        "delta": None if baseline is None else mean - baseline,
        "parity": None if baseline is None else bool(mean >= baseline),
    }
    if args.json:
        print(json.dumps(result))
    else:
        proto = f"{models}-model ensemble over {episodes} episodes"
        print(f"test accuracy: {mean:.4f} ± {std:.4f} ({proto})")
        if baseline is None:
            print(f"no BASELINE.md row for config {key} — custom "
                  f"geometry, nothing to compare")
        else:
            verdict = "PARITY" if mean >= baseline else "GAP"
            print(f"baseline (MAML++ paper via BASELINE.md): "
                  f"{baseline:.4f} -> {verdict} "
                  f"({mean - baseline:+.4f})")
        if episodes < 600:
            print(f"note: paper protocol is 600 episodes; this run used "
                  f"{episodes} (scaled/smoke run?)")
    if baseline is None:
        return 2
    return 0 if mean >= baseline else 3


if __name__ == "__main__":
    sys.exit(main())

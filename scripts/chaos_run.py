"""Chaos harness: prove end-to-end fault recovery, don't hope for it.

Runs the ISSUE 3 acceptance scenario on a tiny synthetic config:

1. **baseline** — a fault-free run straight through the ensemble test
   protocol (the accuracy yardstick).
2. **faulted** — the same schedule with a deterministic fault plan
   (resilience/faults.py): one transient checkpoint-write IO error
   (``io_write@1``, recovered by the storage backoff layer), a NaN outer
   loss in epoch 1 (``nan_loss@N``, recovered by the divergence guard's
   rewind to the epoch-0 checkpoint + train-stream re-seed), and a
   mid-epoch SIGTERM (``kill@M``, recovered by the save-on-signal
   snapshot). The phase ends "preempted".
3. **restart** — resume from 'latest' with NO faults; the run completes
   epoch 1 and the test protocol.
4. **ckpt_kill** (ISSUE 8) — a SUBPROCESS run with ``ckpt_async=1`` and
   an injected SIGKILL-equivalent DURING the second epoch-checkpoint
   write (``kill_in_ckpt_write@2`` — after the tmp bytes, before the
   atomic rename; exit 137): the manifest must show epoch 0's entries
   committed and epoch 1's stranded ``pending``, then a clean restart
   must resume from the last COMMITTED entry (epoch 0's iteration),
   sweep the pending record + ``*.tmp``, quarantine NOTHING (every
   surviving file is good) and finish through the test protocol.
5. **hang** (ISSUE 6) — a SUBPROCESS run (the watchdog kills its whole
   process with ``os._exit``) with an injected wedged data feed
   (``hang_feed@N``) and a tight ``watchdog_feed_timeout_s``: the
   watchdog must trip within its deadline, write a crash bundle
   (all-thread ``stacks.txt`` + ``flight.jsonl``) and exit
   ``resilience.EXIT_HUNG`` (74) — then a clean in-process restart from
   'latest' resumes past the hang and finishes.
6. **peer_kill** (ISSUE 9) — shells out to ``scripts/chaos_pod.py``
   where a multi-process ``jax.distributed`` pair can run: one host
   SIGKILLs itself mid-epoch, every survivor must exit
   ``EXIT_PEER_LOST`` (73) with a ``peer_lost`` row naming the dead
   host, and a full restart must consensus-resume from the committed
   epoch. On a box that cannot run the pair (1 core, no localhost
   sockets) the phase is SKIPPED with the reason recorded in the
   artifact — never silently.

The verdict requires `resilience/rewinds >= 1`, `resilience/io_retries
>= 1`, exactly one preemption, the health subsystem's grad-norm early
warning landing strictly BEFORE the rewind in the faulted phase's log
(ISSUE 7 — `health_grad_norm_warn` precedes `rewind`), the ckpt_kill
phase recovering from the last committed manifest entry (ISSUE 8 —
`ckpt_kill_*` keys), hang exit code 74 + bundle present + hang-restart
completion, the faulted run's restart reaching its first train dispatch
with ZERO XLA compiles — every executable an AOT-store hit from the
store the faulted phase populated (ISSUE 10 — `warm_restart_*` keys) —
and final test accuracies (restart, ckpt-kill-restart AND hang-restart)
within ``--tolerance`` of the baseline.

Artifact contract (bench.py discipline): the LAST stdout JSON line is
authoritative — ``{"metric": "chaos_recovery", "status":
"recovered"|"failed", ...}`` with the fault/recovery counters. Exit 0
iff recovered.

Usage:
    python scripts/chaos_run.py --quick          # CI/CPU smoke (~1 min)
    python scripts/chaos_run.py --out /tmp/chaos --tolerance 0.2
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def tiny_cfg(out_dir: str, name: str, **kw):
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    base = dict(
        experiment_name=name, experiment_root=out_dir,
        dataset_name="synthetic_chaos",
        image_height=10, image_width=10, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=2, batch_size=2,
        cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        second_order=False, use_multi_step_loss_optimization=False,
        total_epochs=2, total_iter_per_epoch=4,
        num_evaluation_tasks=4, max_models_to_save=2,
        compute_dtype="float32", meta_learning_rate=0.005,
        # Sync every iteration: the guard/fault hooks live at the
        # dispatch-sync points, and a chaos run wants tight granularity.
        dispatch_sync_every=1, live_progress=False,
        divergence_patience=1,
        # Health introspection ON (telemetry/health.py): the faulted
        # phase must show the guard's grad-norm early warning landing
        # strictly BEFORE the rewind it foreshadows.
        health_metrics_every_n_steps=1)
    base.update(kw)
    return MAMLConfig(**base)


def run_phase(cfg):
    """One ExperimentBuilder run; returns (result, counters snapshot)."""
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    builder = ExperimentBuilder(cfg)
    result = builder.run_experiment()
    return result, builder.registry.snapshot()


def run_hang_phase(out: str, platform: str):
    """The ISSUE 6 hang scenario, in a subprocess (the watchdog ends its
    process with ``os._exit(EXIT_HUNG)`` — it must not end ours).

    Epoch 0 completes and checkpoints; the prefetch worker then sleeps
    past ``watchdog_feed_timeout_s`` while feeding iteration 5
    (``hang_feed@5``), wedging the consumer in the 'feed' phase. Returns
    the phase's result dict (exit code, bundle facts, trip count).
    """
    cfg = tiny_cfg(out, "chaos_hang", fault_spec="hang_feed@5",
                   continue_from_epoch="latest",
                   watchdog_feed_timeout_s=6.0,
                   watchdog_step_timeout_s=300.0,
                   watchdog_collective_timeout_s=300.0,
                   watchdog_compile_timeout_s=900.0,
                   watchdog_poll_interval_s=0.5)
    cfg_path = os.path.join(out, "chaos_hang_config.json")
    os.makedirs(out, exist_ok=True)
    with open(cfg_path, "w") as f:
        json.dump(cfg.to_dict(), f)
    env = dict(os.environ)
    # The fault plan must come from the config ONLY (an inherited
    # MAML_FAULTS would override it), and the subprocess must land on
    # the same backend the harness runs on.
    env.pop("MAML_FAULTS", None)
    # Bound the injected sleep well past the deadline but short of the
    # harness timeout: if the watchdog FAILS to trip, the run finishes
    # normally and the artifact shows the wrong exit code (a diagnosis)
    # instead of this harness dying on a subprocess timeout.
    env.setdefault("MAML_HANG_SECONDS", "120")
    if platform:
        env["MAML_JAX_PLATFORM"] = platform
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "train_maml_system.py"),
         "--name_of_args_json_file", cfg_path],
        env=env, capture_output=True, text=True, timeout=900)

    bundle = os.path.join(out, "chaos_hang", "logs", "crash_bundle")
    stacks = os.path.join(bundle, "stacks.txt")
    flight = os.path.join(bundle, "flight.jsonl")
    flight_rows = []
    if os.path.exists(flight):
        with open(flight) as f:
            flight_rows = [json.loads(line) for line in f if line.strip()]
    trip_rows = 0
    events_path = os.path.join(out, "chaos_hang", "logs", "events.jsonl")
    if os.path.exists(events_path):
        with open(events_path) as f:
            trip_rows = sum(1 for line in f if line.strip()
                            and json.loads(line).get("event")
                            == "watchdog_trip")
    stacks_ok = (os.path.exists(stacks)
                 and os.path.getsize(stacks) > 0)
    return {
        "hang_exit_code": proc.returncode,
        "bundle_dir": bundle,
        "stacks_dumped": stacks_ok,
        "flight_rows": len(flight_rows),
        "flight_has_feed_phase": any(
            r.get("kind") == "phase" and r.get("phase") == "feed"
            for r in flight_rows),
        "watchdog_trips": trip_rows,
        "stderr_tail": proc.stderr[-800:] if proc.returncode != 74
        else None,
    }


def run_ckpt_kill_phase(out: str, platform: str):
    """The ISSUE 8 kill-during-save scenario, in a subprocess (the
    injected fault ends its process with ``os._exit(137)``).

    With ``ckpt_async=1``, epoch 0 saves (committed by the background
    writer), then ``kill_in_ckpt_write@2`` kills the process after epoch
    1's tmp bytes are written but BEFORE the atomic rename — the
    classic torn-save window. Returns the phase's pre-restart facts:
    exit code, the manifest's committed/pending view, tmp leftovers.
    """
    import glob
    cfg = tiny_cfg(out, "chaos_ckpt", fault_spec="kill_in_ckpt_write@2",
                   ckpt_async=1)
    cfg_path = os.path.join(out, "chaos_ckpt_config.json")
    os.makedirs(out, exist_ok=True)
    with open(cfg_path, "w") as f:
        json.dump(cfg.to_dict(), f)
    env = dict(os.environ)
    env.pop("MAML_FAULTS", None)  # the plan must come from the config
    if platform:
        env["MAML_JAX_PLATFORM"] = platform
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "train_maml_system.py"),
         "--name_of_args_json_file", cfg_path],
        env=env, capture_output=True, text=True, timeout=900)

    saved = os.path.join(out, "chaos_ckpt", "saved_models")
    committed_iter = None
    pending = []
    manifest_path = os.path.join(saved, "MANIFEST.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            records = json.load(f).get("records", {})
        pending = [t for t, r in records.items()
                   if r.get("status") != "committed"]
        committed = [r for r in records.values()
                     if r.get("status") == "committed"]
        if committed:
            committed_iter = max(int(r.get("iter") or 0)
                                 for r in committed)
    return {
        "exit_code": proc.returncode,
        "committed_iter": committed_iter,
        "pending_before_restart": len(pending),
        "tmp_before_restart": len(glob.glob(
            os.path.join(saved, "*.tmp"))),
        "stderr_tail": proc.stderr[-800:] if proc.returncode != 137
        else None,
    }


def ckpt_dir_state(out: str):
    """Post-restart checkpoint-directory facts: pending records, tmp
    leftovers, quarantine files — all of which recovery must have left
    at zero."""
    import glob
    saved = os.path.join(out, "chaos_ckpt", "saved_models")
    pending = 0
    manifest_path = os.path.join(saved, "MANIFEST.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            records = json.load(f).get("records", {})
        pending = sum(1 for r in records.values()
                      if r.get("status") != "committed")
    return {
        "pending": pending,
        "tmp": len(glob.glob(os.path.join(saved, "*.tmp"))),
        "corrupt": len(glob.glob(os.path.join(saved, "*.corrupt"))),
    }


def run_peer_kill_phase(out: str):
    """The ISSUE 9 pod fault-domain scenario, by shelling out to
    scripts/chaos_pod.py (SIGKILL one of N ``jax.distributed`` hosts →
    every survivor exits 73 with a ``peer_lost`` row naming it →
    consensus restart) when this box can run a multi-process pair.
    A box that can't (1-core, or no localhost sockets) SKIPS with the
    reason recorded in the artifact — never silently.
    """
    import socket
    reason = None
    if (os.cpu_count() or 1) < 2:
        reason = ("single-core box: a 2-process jax.distributed "
                  "training pair would serialize past the harness "
                  "timeouts")
    else:
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
        except OSError:
            reason = "cannot bind localhost sockets in this sandbox"
    if reason:
        return {"skipped": reason, "recovered": None}
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "chaos_pod.py"),
         "--out", os.path.join(out, "pod"),
         "--phases", "peer_kill,restart"],  # parity: chaos_pod's own
        #   acceptance; this harness already proves its own phases
        capture_output=True, text=True, timeout=3000)
    artifact = {}
    for line in proc.stdout.strip().splitlines()[::-1]:
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if row.get("metric") == "pod_chaos":
            artifact = row
            break
    return {
        "skipped": None,
        "recovered": artifact.get("status") == "recovered",
        "exit_code": proc.returncode,
        "survivor_exit_code": artifact.get("peer_kill_survivor_exit_code"),
        "suspect_hosts": artifact.get("peer_kill_suspect_hosts"),
        "resumed_line": artifact.get("restart_resumed_line"),
        "stderr_tail": (proc.stderr[-800:]
                        if proc.returncode != 0 else None),
    }


def counter_sum(snapshots, key) -> int:
    return int(sum(float(s.get(key) or 0) for s in snapshots))


def last_warm_start_row(events_path: str):
    """The LAST warm_start row of a phase's events.jsonl — the most
    recent session's time-to-first-step / compiles-at-first-dispatch
    facts (experiment.py § _note_first_dispatch). The faulted and
    restart phases share one log; the restart session's row is last."""
    row = {}
    if os.path.exists(events_path):
        with open(events_path) as f:
            for line in f:
                if not line.strip():
                    continue
                parsed = json.loads(line)
                if parsed.get("event") == "warm_start":
                    row = parsed
    return row


def warn_precedes_rewind(events_path: str):
    """(warn_rows, warn_before_rewind) from a phase's events.jsonl: the
    guard's grad-norm early warning (telemetry/health.py) must land in
    log order strictly BEFORE the rewind it foreshadows — the ordering a
    real divergence produces and the acceptance criterion pins."""
    warn_idx = rewind_idx = None
    warns = 0
    if os.path.exists(events_path):
        with open(events_path) as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                event = json.loads(line).get("event")
                if event == "health_grad_norm_warn":
                    warns += 1
                    if warn_idx is None:
                        warn_idx = i
                elif event == "rewind" and rewind_idx is None:
                    rewind_idx = i
    before = (warn_idx is not None and rewind_idx is not None
              and warn_idx < rewind_idx)
    return warns, before


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Deterministic chaos run: inject faults, prove "
                    "recovery, emit a JSON artifact.")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="experiment root (default: a fresh temp dir, "
                         "removed on success)")
    ap.add_argument("--tolerance", type=float, default=0.4,
                    help="max |test_acc(faulted) - test_acc(baseline)| — "
                         "the rewind re-seeds the train stream, so exact "
                         "equality is not expected")
    ap.add_argument("--quick", action="store_true",
                    help="accepted for CLI symmetry with the other "
                         "scripts; the config is already CI-sized")
    args = ap.parse_args(argv)

    # Optional platform pin (repo convention, see train_maml_system.py:
    # the ambient sitecustomize overrides the JAX_PLATFORMS env var, so
    # CPU-only drives need a knob that wins).
    platform = os.environ.get("MAML_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)

    out = args.out or tempfile.mkdtemp(prefix="chaos_run_")
    cleanup = args.out is None

    # Fault schedule against the 2x4-iteration run: epoch 0 is iters
    # 1..4 (checkpoint at 4); nan at iter 5 trips the patience-1 guard →
    # rewind to epoch 0; kill at iter 6 (reached only after the rewind)
    # preempts mid-epoch; io_write@1 hits the very first JSON write
    # (config.json) and is retried.
    faulted_spec = "io_write@1;nan_loss@5;kill@6"

    print(json.dumps({"phase": "baseline", "status": "running"}),
          flush=True)
    baseline_result, baseline_counters = run_phase(
        tiny_cfg(out, "chaos_baseline"))

    # Warm-start store (parallel/aot.py) shared by the faulted run and
    # its restart: the faulted phase populates it cold; the restart
    # must then reach its first train dispatch with ZERO XLA compiles,
    # every executable a counted AOT hit — the fault-domain restart
    # promise (ISSUE 10), gated in `recovered` below.
    aot_store = os.path.join(out, "aot_store")
    print(json.dumps({"phase": "faulted", "spec": faulted_spec,
                      "status": "running"}), flush=True)
    faulted_result, faulted_counters = run_phase(
        tiny_cfg(out, "chaos_faulted", fault_spec=faulted_spec,
                 aot_store_dir=aot_store))
    preempted = (isinstance(faulted_result, dict)
                 and "preempted_at_iter" in faulted_result)

    print(json.dumps({"phase": "restart", "status": "running"}),
          flush=True)
    restart_result, restart_counters = run_phase(
        tiny_cfg(out, "chaos_faulted", continue_from_epoch="latest",
                 aot_store_dir=aot_store))
    warm_start_row = last_warm_start_row(
        os.path.join(out, "chaos_faulted", "logs", "events.jsonl"))

    # Kill-during-save scenario (ISSUE 8): async writer + SIGKILL mid-
    # write -> restart from the last COMMITTED manifest entry.
    print(json.dumps({"phase": "ckpt_kill",
                      "spec": "kill_in_ckpt_write@2",
                      "status": "running"}), flush=True)
    ckpt_kill = run_ckpt_kill_phase(
        out, platform or os.environ.get("JAX_PLATFORMS", ""))
    print(json.dumps({"phase": "ckpt_kill_restart", "status": "running"}),
          flush=True)
    ckpt_restart_result, ckpt_restart_counters = run_phase(
        tiny_cfg(out, "chaos_ckpt", continue_from_epoch="latest",
                 ckpt_async=1))
    ckpt_dir_after = ckpt_dir_state(out)

    # Hang scenario (ISSUE 6): wedged feed -> watchdog trip -> exit 74 +
    # crash bundle, then a clean restart resumes past the hang.
    print(json.dumps({"phase": "hang", "spec": "hang_feed@5",
                      "status": "running"}), flush=True)
    hang = run_hang_phase(out, platform or os.environ.get("JAX_PLATFORMS",
                                                          ""))
    print(json.dumps({"phase": "hang_restart", "status": "running"}),
          flush=True)
    hang_restart_result, _ = run_phase(
        tiny_cfg(out, "chaos_hang", continue_from_epoch="latest"))

    # Pod fault domain (ISSUE 9): peer SIGKILL -> attributed exit 73 ->
    # consensus restart, via scripts/chaos_pod.py where multi-process
    # is available; a clean, RECORDED skip where it is not.
    print(json.dumps({"phase": "peer_kill", "status": "running"}),
          flush=True)
    peer_kill = run_peer_kill_phase(out)
    if peer_kill["skipped"]:
        print(json.dumps({"phase": "peer_kill", "status": "skipped",
                          "reason": peer_kill["skipped"]}), flush=True)

    # Health early warning (ISSUE 7): the injected NaN poisons the
    # observed grad norm too, so the faulted phase's log must read
    # warn -> rewind in that order.
    grad_norm_warns, warn_before_rewind = warn_precedes_rewind(
        os.path.join(out, "chaos_faulted", "logs", "events.jsonl"))

    chaos_phases = [faulted_counters, restart_counters]
    rewinds = counter_sum(chaos_phases, "resilience/rewinds")
    io_retries = counter_sum(chaos_phases, "resilience/io_retries")
    faults_injected = counter_sum(chaos_phases,
                                  "resilience/faults_injected")
    quarantined = counter_sum(chaos_phases, "resilience/quarantined")

    base_acc = (baseline_result or {}).get("test_accuracy_mean")
    chaos_acc = (restart_result or {}).get("test_accuracy_mean")
    delta = (abs(chaos_acc - base_acc)
             if base_acc is not None and chaos_acc is not None else None)
    hang_acc = (hang_restart_result or {}).get("test_accuracy_mean")
    hang_delta = (abs(hang_acc - base_acc)
                  if base_acc is not None and hang_acc is not None
                  else None)

    from howtotrainyourmamlpytorch_tpu.resilience import EXIT_HUNG
    hang_recovered = bool(
        hang["hang_exit_code"] == EXIT_HUNG
        and hang["stacks_dumped"] and hang["flight_rows"] > 0
        and hang["watchdog_trips"] >= 1
        and hang_delta is not None and hang_delta <= args.tolerance)

    # ISSUE 8 gate: the kill landed mid-write (exit 137, a pending
    # record + tmp stranded), the restart resumed from the last
    # COMMITTED manifest entry (epoch 0's boundary — iteration
    # total_iter_per_epoch), finished the run, GC swept the wreckage,
    # and NO good file was quarantined along the way.
    ckpt_acc = (ckpt_restart_result or {}).get("test_accuracy_mean")
    ckpt_delta = (abs(ckpt_acc - base_acc)
                  if base_acc is not None and ckpt_acc is not None
                  else None)
    ckpt_kill_recovered = bool(
        ckpt_kill["exit_code"] == 137
        and ckpt_kill["pending_before_restart"] >= 1
        and ckpt_kill["committed_iter"] == 4  # total_iter_per_epoch:
        #   epoch 0's boundary — the last committed entry
        and ckpt_acc is not None
        and ckpt_delta is not None and ckpt_delta <= args.tolerance
        and ckpt_dir_after["pending"] == 0
        and ckpt_dir_after["tmp"] == 0
        and ckpt_dir_after["corrupt"] == 0
        and counter_sum([ckpt_restart_counters],
                        "resilience/quarantined") == 0)

    # The peer-kill phase gates recovery when it RAN; a recorded skip
    # (1-core box, no sockets) is not a failure — but it is never
    # silent, the artifact says exactly why it didn't run.
    peer_kill_ok = (peer_kill["skipped"] is not None
                    or bool(peer_kill["recovered"]))
    # ISSUE 10 gate: the restart of the faulted run reached its first
    # train dispatch compile-free, every executable loaded from the AOT
    # store the faulted phase populated.
    warm_restart_ok = bool(
        warm_start_row.get("compiles_before_first_step") == 0
        and (warm_start_row.get("aot_hits") or 0) >= 1
        and (warm_start_row.get("aot_misses") or 0) == 0)
    recovered = bool(
        preempted and rewinds >= 1 and io_retries >= 1
        and warn_before_rewind
        and chaos_acc is not None
        and delta is not None and delta <= args.tolerance
        and ckpt_kill_recovered
        and hang_recovered
        and warm_restart_ok
        and peer_kill_ok)
    # Recoveries: one per distinct fault class the run survived.
    recoveries = (int(preempted) + int(rewinds >= 1)
                  + int(io_retries >= 1) + int(ckpt_kill_recovered)
                  + int(hang_recovered) + int(warm_restart_ok)
                  + int(bool(peer_kill["recovered"])))

    artifact = {
        "metric": "chaos_recovery",
        "value": 1.0 if recovered else 0.0,
        "unit": "recovered",
        "status": "recovered" if recovered else "failed",
        "fault_spec": faulted_spec,
        "faults_injected": faults_injected,
        "recoveries": recoveries,
        "rewinds": rewinds,
        "io_retries": io_retries,
        "quarantined": quarantined,
        "grad_norm_warns": grad_norm_warns,
        "grad_norm_warn_before_rewind": warn_before_rewind,
        "preempted": preempted,
        "preempted_at_iter": (faulted_result or {}).get(
            "preempted_at_iter"),
        "baseline_test_accuracy": base_acc,
        "chaos_test_accuracy": chaos_acc,
        "test_accuracy_delta": (round(delta, 6)
                                if delta is not None else None),
        "ckpt_kill_exit_code": ckpt_kill["exit_code"],
        "ckpt_kill_committed_iter": ckpt_kill["committed_iter"],
        "ckpt_kill_pending_before_restart":
            ckpt_kill["pending_before_restart"],
        "ckpt_kill_tmp_before_restart": ckpt_kill["tmp_before_restart"],
        "ckpt_kill_pending_after_restart": ckpt_dir_after["pending"],
        "ckpt_kill_tmp_after_restart": ckpt_dir_after["tmp"],
        "ckpt_kill_quarantined": counter_sum(
            [ckpt_restart_counters], "resilience/quarantined"),
        "ckpt_kill_stderr_tail": ckpt_kill["stderr_tail"],
        "ckpt_kill_test_accuracy": ckpt_acc,
        "ckpt_kill_test_accuracy_delta": (round(ckpt_delta, 6)
                                          if ckpt_delta is not None
                                          else None),
        "ckpt_kill_recovered": ckpt_kill_recovered,
        "warm_restart_compiles_before_first_step": warm_start_row.get(
            "compiles_before_first_step"),
        "warm_restart_aot_hits": warm_start_row.get("aot_hits"),
        "warm_restart_aot_misses": warm_start_row.get("aot_misses"),
        "warm_restart_time_to_first_step_s": warm_start_row.get(
            "time_to_first_step_seconds"),
        "warm_restart_ok": warm_restart_ok,
        "hang_exit_code": hang["hang_exit_code"],
        "hang_stacks_dumped": hang["stacks_dumped"],
        "hang_flight_rows": hang["flight_rows"],
        "hang_watchdog_trips": hang["watchdog_trips"],
        "hang_stderr_tail": hang["stderr_tail"],
        "hang_test_accuracy": hang_acc,
        "hang_test_accuracy_delta": (round(hang_delta, 6)
                                     if hang_delta is not None else None),
        "hang_recovered": hang_recovered,
        "peer_kill_skipped": peer_kill["skipped"],
        "peer_kill_recovered": peer_kill["recovered"],
        "peer_kill_survivor_exit_code": peer_kill.get(
            "survivor_exit_code"),
        "peer_kill_suspect_hosts": peer_kill.get("suspect_hosts"),
        "peer_kill_stderr_tail": peer_kill.get("stderr_tail"),
        "tolerance": args.tolerance,
        "out_dir": None if cleanup else out,
    }
    if cleanup and recovered:
        shutil.rmtree(out, ignore_errors=True)
    print(json.dumps(artifact), flush=True)
    return 0 if recovered else 1


if __name__ == "__main__":
    sys.exit(main())

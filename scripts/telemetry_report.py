"""Run-summary CLI over a run's ``events.jsonl`` telemetry stream.

Usage:
    python scripts/telemetry_report.py <logs/events.jsonl> [--json]
    python scripts/telemetry_report.py <experiment_root/name> [--json]

Reads the structured event log the experiment loop writes (train_epoch,
telemetry, heartbeat rows — docs/PERF.md § Observability) and prints:

* a human table: step-time p50/p95, meta-tasks/sec/chip, XLA compile
  count/seconds, feed-stall fraction, peak device memory, per-host
  step-time skew — each fail-soft metric that never reported prints an
  explicit "unavailable" marker (measured-zero and not-measured are
  different diagnoses);
* one machine-readable JSON line (the LAST stdout line, matching the
  bench.py artifact discipline) for CI consumption, schema pinned by
  tests/test_telemetry_report.py.

Exit codes: 0 ok, 1 unreadable/empty log, 2 bad usage.
No JAX import — the CLI must run on a login node without accelerators:
the two modules it needs (telemetry/report.py, utils/tracing.py) are
stdlib-only, but importing them through the package would execute
``__init__`` chains that do import jax, so they are loaded by file path.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_report = _load_module(
    "_telemetry_report_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "telemetry", "report.py"))
_tracing = _load_module(
    "_telemetry_tracing_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "utils", "tracing.py"))
format_table = _report.format_table
summarize_events = _report.summarize_events
# Rotation-aware (utils/tracing.py § JsonlLogger rotation): the
# capped spare segment (events.jsonl.1) is read first, so a report
# over a rotated log keeps the oldest surviving rows.
read_jsonl = _tracing.read_jsonl_rotated


def resolve_events_path(path: str) -> str:
    """Accept the events.jsonl itself, a logs dir, or an experiment dir."""
    if os.path.isdir(path):
        for candidate in (os.path.join(path, "events.jsonl"),
                          os.path.join(path, "logs", "events.jsonl")):
            if os.path.exists(candidate):
                return candidate
        raise FileNotFoundError(
            f"no events.jsonl under {path!r} (looked in . and logs/)")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a run's telemetry event log.")
    ap.add_argument("events", help="events.jsonl, a logs/ dir, or an "
                                   "experiment dir containing logs/")
    ap.add_argument("--json", action="store_true",
                    help="emit ONLY the JSON summary line (CI mode)")
    args = ap.parse_args(argv)

    try:
        path = resolve_events_path(args.events)
        events = read_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    if not events:
        print(json.dumps({"error": f"{path}: empty event log"}))
        return 1

    summary = summarize_events(events)
    if not args.json:
        print(format_table(summary))
    # The LAST stdout line is the machine-readable artifact (the same
    # contract bench.py establishes for its JSON output).
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

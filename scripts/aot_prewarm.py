"""Pre-launch executable prewarmer: fill the AOT store before the job.

Schedulers run this ONCE per (config, topology, jax version) before
launching — or relaunching — a job:

    python scripts/aot_prewarm.py \
        --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json \
        [--store /shared/aot] [--serve] [--key value ...]

It lowers and compiles every executable the run will need — one train
step per (derivative-order, MSL) phase boundary the epoch schedule
visits, the eval step, and (``--serve``) each serve bucket's
adapt/predict pair — and serializes them into the store
(``parallel/aot.py``) keyed by the run's fingerprint. A job started
afterwards with the same config and ``aot_store_dir`` reaches its first
train dispatch with ZERO XLA compiles; the fault-domain restart path
(exits 73/74/75 → full job restart) reuses the same store, so every
restart is warm too. Re-running against a warm store is cheap and
idempotent (every executable loads, nothing compiles) — safe to put in
front of every launch unconditionally.

State is never materialized (avals only, ``jax.eval_shape``), so the
prewarmer runs fine on a machine that could not fit the training run —
what must match is the config and the device topology the fingerprint
records.

Artifact contract (bench.py discipline): the LAST stdout JSON line is
``{"metric": "aot_prewarm", ...}`` with per-executable dispositions;
exit 0 iff every requested executable is in the store afterwards.

Trailing ``--key value`` pairs are config overrides with the trainer
CLI's exact coercion rules (train_maml_system.get_args).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compile + serialize every executable a run needs "
                    "into its AOT store (parallel/aot.py)")
    ap.add_argument("--config", required=True,
                    help="experiment_config/*.json to prewarm for")
    ap.add_argument("--store", default=None, metavar="DIR",
                    help="AOT store directory (default: the config's "
                         "aot_store_dir; required via one of the two)")
    ap.add_argument("--serve", action="store_true",
                    help="also prewarm the serve buckets' adapt/predict "
                         "executables (ServingEngine.warmup's set)")
    ap.add_argument("--degraded", type=int, default=0, metavar="K",
                    help="also prewarm the N-1..N-K survivor-roster "
                         "topologies (elastic pod, resilience/elastic.py):"
                         " each k derives the degraded config exactly as "
                         "a resharded survivor group would and stores its"
                         " executables under that roster's fingerprint, "
                         "so the reshard pays zero compiles. Multi-host "
                         "survivor topologies must be prewarmed on a "
                         "machine exposing the survivor device count; "
                         "unrealizable ones are recorded as skipped.")
    ap.add_argument("--degraded-only", action="store_true",
                    help="skip the full-roster executables (useful when "
                         "the full topology is prewarmed by the pod "
                         "itself and this box only covers the degraded "
                         "rosters)")
    ap.add_argument("--backend-timeout", type=float, default=600.0,
                    help="seconds to poll for JAX backend availability "
                         "(0 = fail on first init error)")
    try:
        args, overrides = ap.parse_known_args(argv)
    except SystemExit:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": "invalid command line"}))
        return 1

    from train_maml_system import get_args
    try:
        cfg = get_args(["--name_of_args_json_file", args.config]
                       + overrides)
    except (SystemExit, OSError, ValueError) as e:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": f"invalid config/override: {e}"}))
        return 1
    if args.store:
        cfg = cfg.replace(aot_store_dir=args.store)
    if not cfg.aot_store_dir:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": "no store: set --store or the "
                                   "config's aot_store_dir"}))
        return 1

    from howtotrainyourmamlpytorch_tpu.utils.backend import init_backend
    devices = init_backend(args.backend_timeout)

    import jax
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.parallel import aot
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        make_mesh, make_sharded_steps)
    from howtotrainyourmamlpytorch_tpu.serve.adapt import make_serve_steps

    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        derive_degraded_config)

    n_mesh = int(np.prod(cfg.mesh_shape))
    if n_mesh > len(devices) and not args.degraded_only:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": f"mesh_shape {cfg.mesh_shape} needs "
                                   f"{n_mesh} devices, got "
                                   f"{len(devices)} — prewarm must run "
                                   f"on the job's topology (the "
                                   f"fingerprint records it)"}))
        return 1

    executables = []
    hits = misses = failures = 0
    t_start = time.perf_counter()
    stores = []

    def prewarm_topology(tcfg, label, process_count=None):
        """Every executable one (cfg, topology) pair needs, into that
        pair's fingerprint dir of the shared store root."""
        nonlocal hits, misses, failures
        t_mesh = int(np.prod(tcfg.mesh_shape))
        tcfg = tcfg.replace(
            task_microbatches=tcfg.effective_task_microbatches(t_mesh))
        mesh = make_mesh(tcfg, devices[:t_mesh])
        model_init, apply_fn = make_model(tcfg)
        plan = make_sharded_steps(tcfg, apply_fn, mesh)
        store = aot.AOTStore.from_config(tcfg, mesh,
                                         process_count=process_count)
        stores.append(store)

        # Avals only — the prewarmer never allocates a training state.
        template = jax.eval_shape(
            lambda: init_train_state(tcfg, model_init,
                                     jax.random.PRNGKey(tcfg.seed)))
        savals = aot.state_avals(template, mesh)

        phase_keys, seen = [], set()
        for e in range(tcfg.total_epochs):
            key = (tcfg.use_second_order(e), tcfg.use_msl(e))
            if key not in seen:
                seen.add(key)
                phase_keys.append(key)

        def warm_one(name, jit_fn, avals):
            nonlocal hits, misses, failures
            t0 = time.perf_counter()
            _, hit = aot.load_or_compile(store, name, jit_fn, avals)
            ready = store.manifest.get(name) is not None and \
                store.manifest.get(name).get("status") == "committed"
            hits, misses = hits + hit, misses + (not hit)
            if not ready:
                failures += 1
            executables.append({
                "name": (f"{label}:{name}" if label else name),
                "disposition": "hit" if hit else
                               ("compiled" if ready else "failed"),
                "seconds": round(time.perf_counter() - t0, 3)})
            print(json.dumps(executables[-1]), flush=True)

        train_batch = aot.episode_aval(tcfg, mesh,
                                       tcfg.padded_batch_size)
        for key in phase_keys:
            # The store holds the UNDONATED twins (parallel/mesh.py §
            # MeshPlan): deserialized donating executables are unsafe.
            warm_one(aot.train_exec_name(key), plan.aot_train_steps[key],
                     (savals, train_batch, aot.epoch_aval()))
        warm_one("eval", plan.eval_step,
                 (savals, aot.episode_aval(
                     tcfg, mesh, tcfg.effective_eval_batch_size)))

        if args.serve:
            steps = make_serve_steps(tcfg, apply_fn, mesh)
            # Signatures from aot's shared builders — the engine adopts
            # through the SAME ones (serve/engine.py §
            # _adopt_serve_bucket), so a prewarmed name can never carry
            # a signature the engine would demote on first call.
            done_s, done_q = set(), set()
            for s_b, q_b in tcfg.serve_bucket_shapes:
                adapt_avals = aot.serve_adapt_avals(
                    tcfg, mesh, savals.params, savals.lslr,
                    savals.bn_state, s_b)
                if s_b not in done_s:
                    done_s.add(s_b)
                    warm_one(aot.serve_adapt_name(s_b), steps.aot_adapt,
                             adapt_avals)
                if q_b not in done_q:
                    done_q.add(q_b)
                    warm_one(aot.serve_predict_name(q_b),
                             steps.aot_predict,
                             aot.serve_predict_avals(
                                 tcfg, mesh, steps.adapt, adapt_avals,
                                 savals.params, q_b))

    if not args.degraded_only:
        prewarm_topology(cfg, label="")

    # Degraded survivor rosters (elastic pod): derive each N-k config
    # EXACTLY as a resharded survivor group would (parallel/mesh.py §
    # derive_degraded_config) and stamp its fingerprint with the
    # survivor process count, so the restart-in-place reshard resolves
    # this store dir and pays zero compiles. Rosters whose mesh this
    # box cannot realize are recorded as skipped, not failed — a
    # laptop legitimately prewarms only the rosters it can compile.
    orig_processes = int(cfg.mesh_shape[0])
    for k in range(1, max(args.degraded, 0) + 1):
        survivors = orig_processes - k
        if survivors < 1:
            break
        dcfg = derive_degraded_config(cfg, survivors, orig_processes)
        d_mesh = int(np.prod(dcfg.mesh_shape))
        label = f"degraded{survivors}"
        if d_mesh > len(devices):
            executables.append({"name": f"{label}:*",
                                "disposition": "skipped",
                                "reason": f"needs {d_mesh} devices, "
                                          f"have {len(devices)}"})
            print(json.dumps(executables[-1]), flush=True)
            continue
        prewarm_topology(dcfg, label=label, process_count=survivors)

    ok = failures == 0
    print(json.dumps({
        "metric": "aot_prewarm",
        "value": len(executables) - failures,
        "unit": "executables",
        "ok": ok,
        "hits": hits,
        "misses": misses,
        "failures": failures,
        "seconds": round(time.perf_counter() - t_start, 3),
        "store_dir": (stores[-1].dir if stores else None),
        "fingerprint": (stores[-1].fingerprint if stores else None),
        "fingerprints": [s.fingerprint for s in stores],
        # Tuned compiler options, if any (the autotune adoption loop:
        # TUNED.json → xla_compiler_options → this prewarm → the tuned
        # fingerprint dir a training launch then hits warm). Recorded so
        # a store populated with the wrong flag set is diagnosable from
        # the prewarm artifact alone.
        "xla_compiler_options": cfg.xla_compiler_options_dict,
        "workload": cfg.experiment_name,
        "executables": executables,
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Pre-launch executable prewarmer: fill the AOT store before the job.

Schedulers run this ONCE per (config, topology, jax version) before
launching — or relaunching — a job:

    python scripts/aot_prewarm.py \
        --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA_b12.json \
        [--store /shared/aot] [--serve] [--key value ...]

It lowers and compiles every executable the run will need — one train
step per (derivative-order, MSL) phase boundary the epoch schedule
visits, the eval step, and (``--serve``) each serve bucket's
adapt/predict pair — and serializes them into the store
(``parallel/aot.py``) keyed by the run's fingerprint. A job started
afterwards with the same config and ``aot_store_dir`` reaches its first
train dispatch with ZERO XLA compiles; the fault-domain restart path
(exits 73/74/75 → full job restart) reuses the same store, so every
restart is warm too. Re-running against a warm store is cheap and
idempotent (every executable loads, nothing compiles) — safe to put in
front of every launch unconditionally.

State is never materialized (avals only, ``jax.eval_shape``), so the
prewarmer runs fine on a machine that could not fit the training run —
what must match is the config and the device topology the fingerprint
records.

Artifact contract (bench.py discipline): the LAST stdout JSON line is
``{"metric": "aot_prewarm", ...}`` with per-executable dispositions;
exit 0 iff every requested executable is in the store afterwards.

Trailing ``--key value`` pairs are config overrides with the trainer
CLI's exact coercion rules (train_maml_system.get_args).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compile + serialize every executable a run needs "
                    "into its AOT store (parallel/aot.py)")
    ap.add_argument("--config", required=True,
                    help="experiment_config/*.json to prewarm for")
    ap.add_argument("--store", default=None, metavar="DIR",
                    help="AOT store directory (default: the config's "
                         "aot_store_dir; required via one of the two)")
    ap.add_argument("--serve", action="store_true",
                    help="also prewarm the serve buckets' adapt/predict "
                         "executables (ServingEngine.warmup's set)")
    ap.add_argument("--backend-timeout", type=float, default=600.0,
                    help="seconds to poll for JAX backend availability "
                         "(0 = fail on first init error)")
    try:
        args, overrides = ap.parse_known_args(argv)
    except SystemExit:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": "invalid command line"}))
        return 1

    from train_maml_system import get_args
    try:
        cfg = get_args(["--name_of_args_json_file", args.config]
                       + overrides)
    except (SystemExit, OSError, ValueError) as e:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": f"invalid config/override: {e}"}))
        return 1
    if args.store:
        cfg = cfg.replace(aot_store_dir=args.store)
    if not cfg.aot_store_dir:
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": "no store: set --store or the "
                                   "config's aot_store_dir"}))
        return 1

    from howtotrainyourmamlpytorch_tpu.utils.backend import init_backend
    devices = init_backend(args.backend_timeout)

    import jax
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.parallel import aot
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        make_mesh, make_sharded_steps)
    from howtotrainyourmamlpytorch_tpu.serve.adapt import make_serve_steps

    n_mesh = int(np.prod(cfg.mesh_shape))
    if n_mesh > len(devices):
        print(json.dumps({"metric": "aot_prewarm", "ok": False,
                          "error": f"mesh_shape {cfg.mesh_shape} needs "
                                   f"{n_mesh} devices, got "
                                   f"{len(devices)} — prewarm must run "
                                   f"on the job's topology (the "
                                   f"fingerprint records it)"}))
        return 1
    cfg = cfg.replace(
        task_microbatches=cfg.effective_task_microbatches(n_mesh))
    mesh = make_mesh(cfg, devices[:n_mesh])
    model_init, apply_fn = make_model(cfg)
    plan = make_sharded_steps(cfg, apply_fn, mesh)
    store = aot.AOTStore.from_config(cfg, mesh)

    # Avals only — the prewarmer never allocates a training state.
    template = jax.eval_shape(
        lambda: init_train_state(cfg, model_init,
                                 jax.random.PRNGKey(cfg.seed)))
    savals = aot.state_avals(template, mesh)

    phase_keys, seen = [], set()
    for e in range(cfg.total_epochs):
        key = (cfg.use_second_order(e), cfg.use_msl(e))
        if key not in seen:
            seen.add(key)
            phase_keys.append(key)

    executables = []
    hits = misses = failures = 0
    t_start = time.perf_counter()

    def warm_one(name, jit_fn, avals):
        nonlocal hits, misses, failures
        t0 = time.perf_counter()
        _, hit = aot.load_or_compile(store, name, jit_fn, avals)
        ready = store.manifest.get(name) is not None and \
            store.manifest.get(name).get("status") == "committed"
        hits, misses = hits + hit, misses + (not hit)
        if not ready:
            failures += 1
        executables.append({
            "name": name,
            "disposition": "hit" if hit else
                           ("compiled" if ready else "failed"),
            "seconds": round(time.perf_counter() - t0, 3)})
        print(json.dumps(executables[-1]), flush=True)

    train_batch = aot.episode_aval(cfg, mesh, cfg.batch_size)
    for key in phase_keys:
        # The store holds the UNDONATED twins (parallel/mesh.py §
        # MeshPlan): deserialized donating executables are unsafe.
        warm_one(aot.train_exec_name(key), plan.aot_train_steps[key],
                 (savals, train_batch, aot.epoch_aval()))
    warm_one("eval", plan.eval_step,
             (savals, aot.episode_aval(cfg, mesh,
                                       cfg.effective_eval_batch_size)))

    if args.serve:
        steps = make_serve_steps(cfg, apply_fn, mesh)
        # Signatures from aot's shared builders — the engine adopts
        # through the SAME ones (serve/engine.py § _adopt_serve_bucket),
        # so a prewarmed name can never carry a signature the engine
        # would demote on first call.
        done_s, done_q = set(), set()
        for s_b, q_b in cfg.serve_bucket_shapes:
            adapt_avals = aot.serve_adapt_avals(
                cfg, mesh, savals.params, savals.lslr, savals.bn_state,
                s_b)
            if s_b not in done_s:
                done_s.add(s_b)
                warm_one(aot.serve_adapt_name(s_b), steps.aot_adapt,
                         adapt_avals)
            if q_b not in done_q:
                done_q.add(q_b)
                warm_one(aot.serve_predict_name(q_b), steps.aot_predict,
                         aot.serve_predict_avals(
                             cfg, mesh, steps.adapt, adapt_avals,
                             savals.params, q_b))

    ok = failures == 0
    print(json.dumps({
        "metric": "aot_prewarm",
        "value": len(executables) - failures,
        "unit": "executables",
        "ok": ok,
        "hits": hits,
        "misses": misses,
        "failures": failures,
        "seconds": round(time.perf_counter() - t_start, 3),
        "store_dir": store.dir,
        "fingerprint": store.fingerprint,
        "workload": cfg.experiment_name,
        "executables": executables,
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Pack a reference-layout image dataset into MAMLPACK1 shards.

Decode once, mmap forever (docs/DATA.md): this CLI walks a dataset
directory exactly as ``DiskImageSource`` would — same class-key rules,
same deterministic class order, same fail-soft skip of unreadable
files — PIL-decodes every class, and writes one ``<split>.mamlpack``
shard per split (``datastore/format.py``). Training processes then open
the shard O(header) with zero decode (``build_source`` prefers a shard
automatically), so a multi-host pod stops paying per-process
``os.walk`` + decode against shared storage.

Usage (pre-split layout ``<root>/{train,val,test}/<class>/…``):

    python scripts/dataset_pack.py <root> --height 28 --width 28 \\
        --channels 1 [--splits train,val,test] [--out DIR] [--verify]

Flat class pool split by fractions (``sets_are_pre_split=False``):

    python scripts/dataset_pack.py <root> --flat \\
        --fractions 0.64,0.16,0.20 --height 84 --width 84 --channels 3

Or take every layout/geometry knob from a shipped experiment config
(the recommended form — packed episodes are bitwise identical to what
that config's directory source would sample):

    python scripts/dataset_pack.py --config experiment_config/x.json \\
        [--verify]

``--verify`` re-opens each written shard and CRC-checks EVERY class
block against the header (a deliberate full read).

The LAST stdout line is the JSON artifact (the repo's CLI contract):
``{"metric": "dataset_pack", "classes", "images", "bytes",
"verify_ok", ...}``. Exit 0 on success, 1 on any failure.

No JAX import — packing runs on a login node with no accelerator
runtime (``data/sources.py`` is loaded by file path to skip the
package ``__init__`` chain that imports jax).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig  # noqa: E402
from howtotrainyourmamlpytorch_tpu.datastore import (  # noqa: E402
    PACK_SUFFIX, PackedSource, write_shard)


def _load_sources_module():
    """``data/sources.py`` by file path: importing it as a package
    module would execute ``data/__init__`` → loader → jax."""
    spec = importlib.util.spec_from_file_location(
        "_dataset_pack_sources",
        os.path.join(_REPO, "howtotrainyourmamlpytorch_tpu", "data",
                     "sources.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_sources = _load_sources_module()


def _split_sources(args):
    """Yield (split, source) pairs for the requested layout, built with
    the SAME index rules build_source uses for the directory path."""
    disk_kwargs = dict(numeric_sort=args.labels_as_int,
                       class_key_indexes=args.class_indexes)
    image_shape = (args.height, args.width, args.channels)
    if args.flat:
        pool = _sources.DiskImageSource(args.root, image_shape,
                                        **disk_kwargs)
        for split in args.splits:
            names = _sources.split_class_names(
                pool.class_names, args.fractions, split)
            if not names:
                continue  # a zero fraction legitimately empties a split
            yield split, _sources.SubsetSource(pool, names)
    else:
        for split in args.splits:
            root = os.path.join(args.root, split)
            if not os.path.isdir(root):
                continue
            yield split, _sources.DiskImageSource(root, image_shape,
                                                  **disk_kwargs)


def _class_stream(source):
    """Yield (name, full decoded class block) in the source's
    deterministic order — the order PackedSource will replay, so packed
    and directory episodes stay bitwise identical. Each class is
    EVICTED from the source's decode memo after the writer consumes it,
    so peak RSS is one class, not the whole split."""
    for name in source.class_names:
        yield name, source.class_images(name)
        evict = getattr(source, "evict_class", None)
        if evict is not None:
            evict(name)


def _pack_split(split, source, out_dir, root, verify):
    path = os.path.join(out_dir, split + PACK_SUFFIX)
    t0 = time.perf_counter()
    header = write_shard(path, _class_stream(source), provenance={
        "tool": "scripts/dataset_pack.py",
        "source_root": os.path.abspath(root),
        "source_kind": _sources.source_kind(source),
        "split": split,
        "packed_unix": round(time.time(), 3),
    })
    info = {
        "path": path,
        "classes": len(header["classes"]),
        "images": header["total_images"],
        "bytes": os.path.getsize(path),
        "pack_seconds": round(time.perf_counter() - t0, 3),
    }
    if verify:
        t1 = time.perf_counter()
        PackedSource(path).verify()  # raises CorruptShardError on damage
        info["verify_seconds"] = round(time.perf_counter() - t1, 3)
    return info


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description="pack a dataset directory into MAMLPACK1 shards")
    ap.add_argument("root", nargs="?", default=None,
                    help="dataset directory (holding split subdirs, or a "
                         "flat class pool with --flat); with --config, "
                         "defaults to the config's dataset_dir")
    ap.add_argument("--config", default=None, metavar="JSON",
                    help="take geometry/layout knobs (image shape, "
                         "labels_as_int, class-key indexes, pre-split vs "
                         "flat, fractions, pack output dir) from an "
                         "experiment config")
    ap.add_argument("--out", default=None,
                    help="output directory for <split>.mamlpack shards "
                         "(default: the config's dataset_pack_path, else "
                         "the dataset dir itself — where build_source "
                         "looks first)")
    ap.add_argument("--splits", default="train,val,test",
                    help="comma list of splits to pack (missing split "
                         "dirs are skipped)")
    ap.add_argument("--height", type=int, default=None)
    ap.add_argument("--width", type=int, default=None)
    ap.add_argument("--channels", type=int, default=None)
    ap.add_argument("--flat", action="store_true",
                    help="root is one flat class pool; partition it by "
                         "--fractions (sets_are_pre_split=False layout)")
    ap.add_argument("--fractions", default=None,
                    help="train,val,test class fractions for --flat "
                         "(default 0.64,0.16,0.20; an explicit value "
                         "overrides --config)")
    ap.add_argument("--labels-as-int", action="store_true",
                    help="order integer-named classes numerically "
                         "(reference labels_as_int)")
    ap.add_argument("--class-indexes", default=None,
                    help="comma ints: path components forming the class "
                         "key (reference "
                         "indexes_of_folders_indicating_class; default "
                         "-3,-2; an explicit value overrides --config)")
    ap.add_argument("--verify", action="store_true",
                    help="re-read every written shard and CRC-check "
                         "every class block")
    args = ap.parse_args(argv)

    # Explicit CLI values ALWAYS win; --config (then the flag defaults)
    # fill whatever was not given.
    explicit_indexes = (tuple(int(v) for v in args.class_indexes.split(",")
                              if v)
                        if args.class_indexes is not None else None)
    explicit_fractions = (tuple(float(v)
                                for v in args.fractions.split(","))
                          if args.fractions is not None else None)
    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
        args.root = args.root or cfg.dataset_dir
        args.height = args.height or cfg.image_height
        args.width = args.width or cfg.image_width
        args.channels = args.channels or cfg.image_channels
        args.flat = args.flat or not cfg.sets_are_pre_split
        args.labels_as_int = args.labels_as_int or cfg.labels_as_int
        # None = class key is the full relative path (DiskImageSource).
        ks = cfg.indexes_of_folders_indicating_class
        args.class_indexes = (explicit_indexes if explicit_indexes
                              is not None
                              else tuple(ks) if ks is not None else None)
        args.fractions = explicit_fractions or tuple(
            cfg.train_val_test_split)
        args.out = args.out or cfg.dataset_pack_path or args.root
    else:
        if args.root is None:
            ap.error("either a dataset root or --config is required")
        if not (args.height and args.width and args.channels):
            ap.error("--height/--width/--channels are required without "
                     "--config")
        args.class_indexes = (explicit_indexes if explicit_indexes
                              is not None else (-3, -2))
        args.fractions = explicit_fractions or (0.64, 0.16, 0.20)
        args.out = args.out or args.root
    args.splits = tuple(s for s in str(args.splits).split(",") if s)
    for s in args.splits:
        if s not in _sources.SPLITS:
            ap.error(f"unknown split {s!r}")
    return args


def main(argv=None) -> int:
    args = parse_args(argv)
    shards = {}
    verify_ok = True if args.verify else None
    try:
        if not os.path.isdir(args.root):
            raise FileNotFoundError(
                f"dataset root {args.root!r} is not a directory")
        os.makedirs(args.out, exist_ok=True)
        packed_any = False
        for split, source in _split_sources(args):
            print(json.dumps({"split": split, "status": "packing",
                              "classes": len(source.class_names)}),
                  flush=True)
            shards[split] = _pack_split(split, source, args.out,
                                        args.root, args.verify)
            packed_any = True
        if not packed_any:
            raise FileNotFoundError(
                f"no packable splits found under {args.root!r} "
                f"(looked for {', '.join(args.splits)})")
    except Exception as e:  # noqa: BLE001 — the artifact line must exist
        print(json.dumps({
            "metric": "dataset_pack",
            "error": f"{type(e).__name__}: {e}",
            "classes": sum(s["classes"] for s in shards.values()),
            "images": sum(s["images"] for s in shards.values()),
            "bytes": sum(s["bytes"] for s in shards.values()),
            "verify_ok": False if args.verify else None,
            "shards": shards,
        }), flush=True)
        return 1
    artifact = {
        "metric": "dataset_pack",
        "value": float(sum(s["images"] for s in shards.values())),
        "unit": "images",
        "classes": sum(s["classes"] for s in shards.values()),
        "images": sum(s["images"] for s in shards.values()),
        "bytes": sum(s["bytes"] for s in shards.values()),
        "verify_ok": verify_ok,
        "out_dir": os.path.abspath(args.out),
        "shards": shards,
    }
    print(json.dumps(artifact), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

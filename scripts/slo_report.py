"""Per-tenant SLO + request-trace attribution CLI.

Usage:
    python scripts/slo_report.py <fleet_bench_out_dir> [--json]
    python scripts/slo_report.py events_a.jsonl events_b.jsonl [--json]

Reads every ``request_trace`` row (telemetry/reqtrace.py) from the given
``events.jsonl`` files — or from all ``*.jsonl`` under a directory, the
shape a ``fleet_bench --trace-sample-rate`` run leaves behind (one
driver log + one per replica) — assembles them into traces, and prints:

* a per-tenant table: request count, p50/p95/p99 end-to-end latency
  (exact nearest-rank over the sampled roots), SLO-bad fraction against
  ``--slo-p95-ms``, and the burn rate (bad_frac / (1 - target): 1.0 =
  burning the error budget exactly as fast as the SLO allows — the same
  convention fleet/controller.py's ledger feeds the autoscaler);
* tier-split latency attribution (queue vs wire vs adapt vs predict vs
  other) summed across linked traces, with the dominant tier named —
  the answer to "WHERE is the p95";
* worst-trace exemplars: the slowest sampled requests with their
  per-tier breakdown, so the table's tail has concrete trace ids.

One machine-readable JSON line (the LAST stdout line, bench.py artifact
discipline) with ``{"metric": "slo_report", ...}``; schema pinned by
tests/test_reqtrace.py.  Exit codes: 0 ok, 1 missing/empty input, 2 bad
usage.  No JAX import — runs on a login node: reqtrace.py and
utils/tracing.py are stdlib-only and loaded by file path (importing the
package would execute ``__init__`` chains that do import jax).
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_reqtrace = _load_module(
    "_slo_reqtrace_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "telemetry",
                 "reqtrace.py"))
_tracing = _load_module(
    "_slo_tracing_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "utils", "tracing.py"))
# Rotation-aware: the spare segment (events.jsonl.1) reads first.
read_jsonl = _tracing.read_jsonl_rotated
nearest_rank = _tracing.nearest_rank


def resolve_event_files(paths: List[str]) -> List[str]:
    """Expand each arg: a .jsonl file stands for itself; a directory
    stands for every ``*.jsonl`` directly under it (and under logs/)."""
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            found = sorted(glob.glob(os.path.join(path, "*.jsonl")))
            found += sorted(glob.glob(os.path.join(path, "logs",
                                                   "*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"no *.jsonl files under {path!r}")
            files += found
        else:
            files.append(path)
    return files


def summarize_traces(rows: List[Dict[str, Any]], *, slo_p95_ms: float,
                     slo_target_frac: float,
                     worst_n: int = 3) -> Dict[str, Any]:
    """Assemble request_trace rows into the slo_report artifact dict."""
    traces = _reqtrace.assemble(rows)
    n_linked = sum(1 for t in traces.values() if _reqtrace.linked(t))
    tier_seconds = {tier: 0.0 for tier in _reqtrace.TIERS}
    per_tenant: Dict[str, List[float]] = {}
    scored: List[Dict[str, Any]] = []
    for t in traces.values():
        attr = _reqtrace.attribute(t)
        if _reqtrace.linked(t):
            for tier in _reqtrace.TIERS:
                tier_seconds[tier] += attr[tier]
        if t["root"] is not None:
            ms = float(t["root"]["dur_s"]) * 1e3
            per_tenant.setdefault(t["tenant"] or "?", []).append(ms)
            scored.append({
                "trace_id": t["trace_id"], "tenant": t["tenant"],
                "total_ms": ms, "dominant": attr["dominant"],
                "tiers_ms": {tier: attr[tier] * 1e3
                             for tier in _reqtrace.TIERS},
            })
    tenants: Dict[str, Dict[str, Any]] = {}
    for tenant, vals in sorted(per_tenant.items()):
        vals = sorted(vals)
        bad = sum(1 for v in vals if v > slo_p95_ms)
        bad_frac = bad / len(vals)
        tenants[tenant] = {
            "count": len(vals),
            "p50_ms": nearest_rank(vals, 0.50),
            "p95_ms": nearest_rank(vals, 0.95),
            "p99_ms": nearest_rank(vals, 0.99),
            "bad_frac": bad_frac,
            "burn_rate": bad_frac / (1.0 - slo_target_frac),
        }
    scored.sort(key=lambda s: -s["total_ms"])
    dominant = (max(_reqtrace.TIERS, key=lambda k: tier_seconds[k])
                if n_linked else None)
    return {
        "metric": "slo_report",
        "traces": len(traces),
        "linked": n_linked,
        "linked_frac": (n_linked / len(traces)) if traces else 0.0,
        "spans": sum(len(t["spans"]) + (t["root"] is not None)
                     for t in traces.values()),
        "slo_p95_ms": slo_p95_ms,
        "slo_target_frac": slo_target_frac,
        "tenants": tenants,
        "tier_seconds": tier_seconds,
        "dominant_tier": dominant,
        "worst": scored[:worst_n],
    }


def format_table(s: Dict[str, Any]) -> str:
    lines = [
        "slo_report",
        f"  traces {s['traces']}  linked {s['linked']} "
        f"({s['linked_frac']:.1%})  spans {s['spans']}",
        f"  SLO: p95 <= {s['slo_p95_ms']:.0f} ms for "
        f">= {s['slo_target_frac']:.0%} of requests",
        "",
        f"  {'tenant':<16} {'count':>6} {'p50_ms':>9} {'p95_ms':>9} "
        f"{'p99_ms':>9} {'bad%':>7} {'burn':>7}",
    ]
    for tenant, row in s["tenants"].items():
        lines.append(
            f"  {tenant:<16} {row['count']:>6} {row['p50_ms']:>9.1f} "
            f"{row['p95_ms']:>9.1f} {row['p99_ms']:>9.1f} "
            f"{row['bad_frac']:>6.1%} {row['burn_rate']:>7.2f}")
    lines.append("")
    tiers = s["tier_seconds"]
    total = sum(tiers.values()) or 1.0
    lines.append("  latency attribution (linked traces):")
    for tier in _reqtrace.TIERS:
        mark = "  <- dominant" if tier == s["dominant_tier"] else ""
        lines.append(f"    {tier:<8} {tiers[tier] * 1e3:>10.1f} ms "
                     f"({tiers[tier] / total:.1%}){mark}")
    if s["worst"]:
        lines.append("")
        lines.append("  worst traces:")
        for w in s["worst"]:
            tiers_ms = w["tiers_ms"]
            split = " ".join(f"{tier}={tiers_ms[tier]:.1f}"
                             for tier in _reqtrace.TIERS)
            lines.append(
                f"    {w['trace_id']}  tenant={w['tenant']}  "
                f"{w['total_ms']:.1f} ms  dominant={w['dominant']}  "
                f"[{split}]")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Per-tenant SLO + trace-attribution report over "
                    "request_trace events.")
    ap.add_argument("paths", nargs="+",
                    help="events.jsonl file(s) and/or directories "
                         "containing them (a fleet_bench --out dir)")
    ap.add_argument("--slo-p95-ms", type=float, default=2000.0,
                    help="per-request latency SLO threshold (ms)")
    ap.add_argument("--slo-target-frac", type=float, default=0.95,
                    help="fraction of requests that must meet the SLO")
    ap.add_argument("--worst", type=int, default=3,
                    help="number of worst-trace exemplars to show")
    ap.add_argument("--json", action="store_true",
                    help="emit ONLY the JSON artifact line (CI mode)")
    args = ap.parse_args(argv)
    if not (args.slo_p95_ms > 0 and 0 < args.slo_target_frac < 1):
        print(json.dumps({"error": "need --slo-p95-ms > 0 and "
                                   "0 < --slo-target-frac < 1"}))
        return 2

    rows: List[Dict[str, Any]] = []
    try:
        for path in resolve_event_files(args.paths):
            rows += [r for r in read_jsonl(path)
                     if r.get("event") == _reqtrace.REQUEST_TRACE_EVENT]
    except (OSError, ValueError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    if not rows:
        print(json.dumps({"error": "no request_trace rows found (was "
                                   "the run traced? reqtrace_sample_"
                                   "rate=0 writes none)"}))
        return 1

    summary = summarize_traces(rows, slo_p95_ms=args.slo_p95_ms,
                               slo_target_frac=args.slo_target_frac,
                               worst_n=args.worst)
    if not args.json:
        print(format_table(summary))
    # The LAST stdout line is the machine-readable artifact.
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

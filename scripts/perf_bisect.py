"""Op-level microbenchmarks on the flagship forward's real shapes.

Each candidate op is looped R times inside ONE jitted scan (carry keeps the
chain live), so the per-call tunnel latency (~100ms on axon) amortizes away.
All big arrays are explicit arguments (closures would bake them into the
HLO as constants and blow up the remote-compile request). Prints ms per
single op application.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

R = 30
B = 400  # 16 tasks x 25 support images


def timed(name, fn, *args):
    # Reduce to a scalar on device: fetching a big buffer through the axon
    # HTTP tunnel costs ~seconds and would swamp the op being measured.
    looped = jax.jit(lambda *a: jnp.sum(
        jax.tree.leaves(fn(*a))[0].astype(jnp.float32)))
    out = looped(*args)
    _ = float(jax.device_get(out))
    t0 = time.perf_counter()
    out = looped(*args)
    _ = float(jax.device_get(out))
    dt = time.perf_counter() - t0
    print(json.dumps({"op": name, "ms_per_apply": round(dt / R * 1e3, 3)}),
          flush=True)


def main():
    key = jax.random.PRNGKey(0)

    # --- convs, same-shape carry (stages 2-4 have Cin == Cout) ----------
    for h, w, c in ((42, 42, 48), (21, 21, 48), (10, 10, 48)):
        x = jax.random.normal(key, (B, h, w, c), jnp.bfloat16)
        k = jax.random.normal(key, (3, 3, c, c), jnp.bfloat16)

        def run(x, k):
            def step(carry, _):
                y = jax.lax.conv_general_dilated(
                    carry, k, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                return y * jnp.bfloat16(0.01), ()
            out, _ = jax.lax.scan(step, x, None, length=R)
            return out

        timed(f"conv3x3 {h}x{w}x{c} B={B}", run, x, k)

    # --- first conv 3->48 (carry on output, input fixed) -----------------
    x0 = jax.random.normal(key, (B, 84, 84, 3), jnp.bfloat16)
    k0 = jax.random.normal(key, (3, 3, 3, 48), jnp.bfloat16)
    y0 = jnp.zeros((B, 84, 84, 48), jnp.bfloat16)

    def run_first(x, k, y):
        def step(carry, _):
            # Feed a hair of the carry into the conv input so the conv is
            # loop-VARIANT — otherwise XLA hoists it out of the while loop
            # and dt/R measures only the carry mul-add.
            xi = x + carry[..., :3] * jnp.bfloat16(1e-8)
            out = jax.lax.conv_general_dilated(
                xi, k, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return out * jnp.bfloat16(0.01) + carry * jnp.bfloat16(0.5), ()
        out, _ = jax.lax.scan(step, y, None, length=R)
        return out

    timed(f"conv3x3 84x84x3->48 B={B}", run_first, x0, k0, y0)

    # --- BN(batch stats) + relu, f32 math (current layers.py path) -------
    x = jax.random.normal(key, (B, 84, 84, 48), jnp.bfloat16)
    gamma = jnp.ones((48,), jnp.float32)
    beta = jnp.zeros((48,), jnp.float32)

    def run_bn(x, gamma, beta):
        def step(carry, _):
            xf = carry.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
            y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * gamma + beta
            return jnp.maximum(y, 0).astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(step, x, None, length=R)
        return out

    timed(f"bn+relu f32 84x84x48 B={B}", run_bn, x, gamma, beta)

    # --- BN variant: stats f32, normalize in bf16 ------------------------
    def run_bn_bf16(x, gamma, beta):
        def step(carry, _):
            xf = carry.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            var = jnp.var(xf, axis=(0, 1, 2))
            inv = jax.lax.rsqrt(var + 1e-5)
            scale = (inv * gamma).astype(jnp.bfloat16)
            shift = (beta - mean * inv * gamma).astype(jnp.bfloat16)
            return jnp.maximum(carry * scale + shift, 0), ()
        out, _ = jax.lax.scan(step, x, None, length=R)
        return out

    timed(f"bn+relu bf16-norm 84x84x48 B={B}", run_bn_bf16, x, gamma, beta)

    # --- max pool 2x2 (carry = input, pooled output added into a slice) --
    def run_pool2(x):
        def step(carry, _):
            y = jax.lax.reduce_window(
                carry, -jnp.inf, jax.lax.max,
                (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
            nxt = carry.at[:, :42, :42, :].add(y * jnp.bfloat16(0.01))
            return nxt, ()
        out, _ = jax.lax.scan(step, x, None, length=R)
        return out

    timed(f"maxpool2x2 84x84x48 B={B}", run_pool2, x)

    # --- per-step BN state scatter (the .at[idx].set in layers.py) -------
    state = jnp.zeros((5, 48), jnp.float32)
    mean = jnp.ones((48,), jnp.float32)

    def run_scatter(state, mean):
        def step(carry, i):
            idx = jnp.clip(i % 5, 0, 4)
            return carry.at[idx].set(
                carry[idx] * 0.9 + mean * 0.1), ()
        out, _ = jax.lax.scan(step, state, jnp.arange(R))
        return out

    timed("bn-state scatter (5,48)", run_scatter, state, mean)


if __name__ == "__main__":
    main()

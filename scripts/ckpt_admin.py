"""Checkpoint-directory administration: list / verify / gc / publish /
rollback against a run's ``saved_models`` directory.

Usage:
    python scripts/ckpt_admin.py list     <dir>
    python scripts/ckpt_admin.py verify   <dir>
    python scripts/ckpt_admin.py gc       <dir> [--max-to-keep K] [--dry-run]
    python scripts/ckpt_admin.py publish  <dir> --tag TAG
    python scripts/ckpt_admin.py rollback <dir> --version V [--reason TEXT]

* ``list`` — the manifest's records (tag, status, iter, bytes, val acc)
  and the model registry's versions, human table + JSON artifact.
* ``verify`` — full-read CRC32 + length check of every COMMITTED
  manifest record against its file (ckpt/manifest.py § verify_record).
  Exit 1 if anything fails — the CI gate for a checkpoint mirror.
* ``gc`` — sweep ``*.tmp`` leftovers, ``*.corrupt`` quarantine files,
  pending records from a killed writer, records whose files are gone,
  and committed epoch checkpoints outside the top ``--max-to-keep`` by
  val accuracy (``latest`` is never pruned). ``--dry-run`` reports only.
* ``publish`` — register a COMMITTED manifest entry as a servable
  version in ``REGISTRY.json`` (what training does automatically with
  ``ckpt_publish=1``; this is the operator path for promoting an older
  epoch).
* ``rollback`` — withdraw a published version (status ``rolled_back``);
  polling ServingEngines treat it like it never existed and fall back to
  the newest remaining live version on their next swap decision.

Artifact contract (bench.py discipline): the LAST stdout line is the
JSON artifact — ``{"metric": "ckpt_admin", "command": ..., "ok": ...}``
plus per-command keys. Exit 0 iff ok.

No JAX import — admin runs on a login node without accelerators:
``ckpt/manifest.py`` and ``ckpt/registry.py`` are stdlib-only and are
loaded by file path so the package ``__init__`` chains (which do import
jax) never execute (the trace_export.py discipline).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_manifest = _load_module(
    "_ckpt_admin_manifest_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "ckpt", "manifest.py"))
_registry = _load_module(
    "_ckpt_admin_registry_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "ckpt", "registry.py"))


def resolve_dir(path: str) -> str:
    """Accept the saved_models dir itself or an experiment dir
    containing one."""
    candidate = os.path.join(path, "saved_models")
    if (not os.path.isfile(os.path.join(path, _manifest.MANIFEST_FILE))
            and os.path.isdir(candidate)):
        return candidate
    return path


def cmd_list(directory: str, args) -> dict:
    man = _manifest.Manifest(directory)
    reg = _registry.ModelRegistry(directory)
    rows = sorted(man.records.values(),
                  key=lambda r: int(r.get("iter") or 0))
    print(f"{'tag':>8}  {'status':<10}{'iter':>8}{'bytes':>12}"
          f"  val_acc")
    for rec in rows:
        acc = rec.get("val_acc")
        print(f"{rec['tag']:>8}  {rec['status']:<10}"
              f"{rec.get('iter') or 0:>8}{rec.get('bytes') or 0:>12}"
              f"  {'-' if acc is None else f'{acc:.4f}'}")
    for v in reg.versions:
        print(f"registry v{v['version']}: tag {v['tag']} "
              f"({v['status']}) val_acc "
              f"{'-' if v.get('val_acc') is None else v['val_acc']}")
    latest = reg.latest()
    return {"ok": True, "records": len(man.records),
            "committed": len(man.committed()),
            "pending": len(man.pending()),
            "versions": len(reg.versions),
            "live_version": (latest["version"] if latest else None)}


def cmd_verify(directory: str, args) -> dict:
    man = _manifest.Manifest(directory)
    bad = []
    checked = 0
    for tag, rec in sorted(man.records.items()):
        if rec.get("status") != _manifest.COMMITTED:
            continue  # pending records are GC's problem, not verify's
        checked += 1
        res = _manifest.verify_record(directory, rec)
        print(f"{tag}: {'OK' if res['ok'] else 'BAD — ' + res['reason']}")
        if not res["ok"]:
            bad.append({"tag": tag, "reason": res["reason"]})
    if not man.loaded:
        print("no readable MANIFEST.json (pre-manifest directory?)")
    return {"ok": not bad, "verified": checked, "bad": bad,
            "manifest_present": man.loaded}


def cmd_gc(directory: str, args) -> dict:
    man = _manifest.Manifest(directory)
    # Retention: top --max-to-keep committed EPOCH records by val acc
    # (ties to the newer epoch), mirroring CheckpointManager._prune.
    epochs = [r for r in man.committed()
              if str(r["tag"]).isdigit()]
    epochs.sort(key=lambda r: (float(r.get("val_acc") or 0.0),
                               int(r["tag"])), reverse=True)
    keep = [r["tag"] for r in epochs[:args.max_to_keep]]
    swept = _manifest.sweep(man, keep_tags=keep, remove_corrupt=True,
                            dry_run=args.dry_run)
    verb = "would delete" if args.dry_run else "deleted"
    print(f"{verb}: {swept['deleted_files'] or 'nothing'}")
    print(f"{'would drop' if args.dry_run else 'dropped'} records: "
          f"{swept['dropped_records'] or 'none'}")
    return {"ok": True, "deleted_files": len(swept["deleted_files"]),
            "dropped_records": len(swept["dropped_records"]),
            "kept_tags": keep, "dry_run": bool(args.dry_run)}


def cmd_publish(directory: str, args) -> dict:
    man = _manifest.Manifest(directory)
    rec = man.get(args.tag)
    if rec is None or rec.get("status") != _manifest.COMMITTED:
        print(f"tag {args.tag!r} has no COMMITTED manifest record "
              f"(status: {rec and rec.get('status')})")
        return {"ok": False, "tag": args.tag,
                "error": "not a committed manifest entry"}
    check = _manifest.verify_record(directory, rec)
    if not check["ok"]:
        print(f"refusing to publish {args.tag!r}: {check['reason']}")
        return {"ok": False, "tag": args.tag,
                "error": f"verify failed: {check['reason']}"}
    reg = _registry.ModelRegistry(directory)
    path = os.path.join(directory, rec["file"])
    version = reg.publish(
        tag=rec["tag"], epoch=rec.get("epoch"),
        iteration=int(rec.get("iter") or 0), val_acc=rec.get("val_acc"),
        fingerprint=_manifest.file_fingerprint(path))
    print(f"published tag {rec['tag']} as version "
          f"{version['version']}")
    return {"ok": True, "tag": rec["tag"],
            "version": version["version"]}


def cmd_rollback(directory: str, args) -> dict:
    reg = _registry.ModelRegistry(directory)
    try:
        rec = reg.rollback(args.version, reason=args.reason)
    except KeyError as e:
        print(str(e))
        return {"ok": False, "version": args.version,
                "error": "unknown version"}
    latest = reg.latest()
    print(f"rolled back version {rec['version']} (tag {rec['tag']}); "
          f"live is now "
          f"{'v%d' % latest['version'] if latest else 'NOTHING'}")
    return {"ok": True, "version": rec["version"],
            "live_version": (latest["version"] if latest else None)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Administer a run's checkpoint directory (manifest "
                    "+ model registry).")
    sub = ap.add_subparsers(dest="command", required=True)
    for name in ("list", "verify", "gc", "publish", "rollback"):
        p = sub.add_parser(name)
        p.add_argument("directory",
                       help="saved_models dir (or an experiment dir "
                            "containing one)")
        if name == "gc":
            p.add_argument("--max-to-keep", type=int, default=5,
                           help="retention: committed epoch checkpoints "
                                "kept, top-k by val accuracy "
                                "(default 5, the MAMLConfig default)")
            p.add_argument("--dry-run", action="store_true",
                           help="report what would be removed, touch "
                                "nothing")
        elif name == "publish":
            p.add_argument("--tag", required=True,
                           help="manifest tag to publish (an epoch "
                                "number or 'latest')")
        elif name == "rollback":
            p.add_argument("--version", type=int, required=True)
            p.add_argument("--reason", default="operator rollback")
    args = ap.parse_args(argv)

    directory = resolve_dir(args.directory)
    if not os.path.isdir(directory):
        print(json.dumps({"metric": "ckpt_admin",
                          "command": args.command, "ok": False,
                          "error": f"no such directory: {directory}"}))
        return 1
    try:
        result = {"list": cmd_list, "verify": cmd_verify, "gc": cmd_gc,
                  "publish": cmd_publish,
                  "rollback": cmd_rollback}[args.command](directory, args)
    except (OSError, ValueError) as e:
        result = {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # The LAST stdout line is the machine-readable artifact (the
    # bench.py / dataset_pack.py contract).
    print(json.dumps({"metric": "ckpt_admin", "command": args.command,
                      "directory": directory, **result}), flush=True)
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

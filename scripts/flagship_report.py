"""Digest a driven run's logs into the per-phase evidence table.

Reads an experiment directory (events.jsonl + config.json +
summary_statistics.csv + test_summary.csv) and prints, as JSON lines:

- one row per schedule phase — the (second_order, use_msl) executable
  groups the config's epoch schedule visits — with epoch range, median
  synced whole-epoch throughput (includes host sampling + tunnel
  transfer), and median dispatch throughput (the device-side rate,
  robust to this box's host/tunnel bound);
- a boundary-stall check for every phase switch: the first epoch of the
  new phase vs its own phase's median epoch_seconds (a compile stall at
  the swap would make it an outlier; `precompile_phases` exists to
  prevent exactly that);
- the cosine meta-LR endpoints (first/last train_epoch rows);
- checkpoint retention (files on disk vs max_models_to_save);
- the final test protocol line from test_summary.csv, if present.

Usage: python scripts/flagship_report.py /path/to/<experiment_name>
"""

from __future__ import annotations

import csv
import json
import os
import sys

import numpy as np


def load_events(exp_dir: str) -> list[dict]:
    path = os.path.join(exp_dir, "logs", "events.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def phase_key(cfg: dict, epoch: int) -> tuple[bool, bool]:
    """Mirror MAMLConfig.use_second_order/use_msl from the raw config
    dict (so the report needs no package import). Fallback defaults
    MUST equal the MAMLConfig dataclass defaults (config.py:122-125,
    pinned by tests/test_perf_tooling.py) or a config dict that omits a
    field would silently produce a wrong phase table."""
    # Reference semantic (few_shot_learning_system.py § forward, mirrored
    # by MAMLConfig.use_second_order): STRICTLY epoch > boundary — the
    # flagship's boundary-40 config flips at epoch 41.
    da = cfg.get("first_order_to_second_order_epoch", -1)
    so = bool(cfg.get("second_order", True)) and epoch > da
    msl = (bool(cfg.get("use_multi_step_loss_optimization", True))
           and epoch < cfg.get("multi_step_loss_num_epochs", 15))
    return so, msl


def main() -> int:
    exp_dir = sys.argv[1]
    with open(os.path.join(exp_dir, "config.json")) as f:
        cfg = json.load(f)
    events = load_events(exp_dir)
    train = {e["epoch"]: e for e in events if e["event"] == "train_epoch"}
    if not train:
        print(json.dumps({"error": "no train_epoch events"}))
        return 1

    epochs = sorted(train)
    # Group epochs by phase KEY transitions only: a gap in logged epochs
    # (e.g. the epoch a preemption interrupted, re-run after resume)
    # must not fragment a phase into two groups — that would emit a
    # spurious same-key "boundary" row and fragment the medians.
    phases: list[dict] = []
    for e in epochs:
        k = phase_key(cfg, e)
        if phases and phases[-1]["key"] == k:
            phases[-1]["end"] = e
            phases[-1]["epochs"].append(e)
        else:
            phases.append({"key": k, "start": e, "end": e, "epochs": [e]})

    for ph in phases:
        rows = [train[e] for e in ph["epochs"]]
        secs = [r["epoch_seconds"] for r in rows]
        synced = [r["meta_tasks_per_sec_per_chip"] for r in rows]
        disp = [r["dispatch_meta_tasks_per_sec_per_chip"] for r in rows
                if "dispatch_meta_tasks_per_sec_per_chip" in r]
        print(json.dumps({
            "phase": {"second_order": ph["key"][0], "use_msl": ph["key"][1]},
            "epochs": [ph["start"], ph["end"]],
            "n": len(rows),
            "median_epoch_seconds": round(float(np.median(secs)), 1),
            "median_synced_tasks_per_sec_per_chip":
                round(float(np.median(synced)), 2),
            # None (JSON null) when no epoch carried dispatch timings
            # (e.g. preempted epochs) — a NaN would break the JSON-lines
            # contract.
            "median_dispatch_tasks_per_sec_per_chip":
                (round(float(np.median(disp)), 2) if disp else None),
        }))

    # Boundary-stall check: first epoch of each later phase vs that
    # phase's own median.
    for prev, ph in zip(phases, phases[1:]):
        first = train[ph["start"]]["epoch_seconds"]
        med = float(np.median([train[e]["epoch_seconds"]
                               for e in ph["epochs"]]))
        print(json.dumps({
            "boundary": f"epoch {ph['start']} "
                        f"({prev['key']} -> {ph['key']})",
            "first_epoch_seconds": round(first, 1),
            "phase_median_seconds": round(med, 1),
            "stall_ratio": round(first / med, 2) if med else None,
            "stalled": bool(med and first > 1.5 * med),
        }))

    print(json.dumps({
        "meta_lr_first": train[epochs[0]]["meta_lr"],
        "meta_lr_last": train[epochs[-1]]["meta_lr"],
        "train_acc_last": round(train[epochs[-1]]["train_accuracy"], 4),
    }))

    models = os.path.join(exp_dir, "saved_models")
    if os.path.isdir(models):
        names = sorted(os.listdir(models))
        print(json.dumps({"checkpoints": names,
                          "max_models_to_save":
                              cfg.get("max_models_to_save")}))

    test_csv = os.path.join(exp_dir, "logs", "test_summary.csv")
    if os.path.exists(test_csv):
        with open(test_csv) as f:
            for row in csv.DictReader(f):
                print(json.dumps({"test_summary": row}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

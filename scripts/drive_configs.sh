#!/usr/bin/env bash
# Drive every shipped config that hasn't had a real-TPU full-loop run
# through a SHORT but complete ExperimentBuilder cycle (train -> val
# sweeps -> checkpoints -> top-k ensemble test protocol) on the
# deterministic synthetic source. Each config is a distinct compile
# surface (VERDICT r2 next #6); the resnet12 sharded-compile break was
# only ever found by driving.
#
# Usage: scripts/drive_configs.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/drive_configs}
mkdir -p "$OUT"
FAILED=0

drive() {
  cfg=$1; ds=$2; shift 2
  name="drive_$(basename "$cfg" .json)"
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  python train_maml_system.py \
    --name_of_args_json_file "experiment_config/$cfg" \
    --experiment_name "$name" --dataset_name "$ds" \
    --experiment_root "$OUT" \
    --total_epochs 6 --total_iter_per_epoch 40 \
    --num_evaluation_tasks 60 "$@" \
    > "$OUT/$name.log" 2>&1
  rc=$?
  echo "rc=$rc"
  tail -3 "$OUT/$name.log"
  if [ "$rc" -ne 0 ]; then FAILED=$((FAILED + 1)); fi
}

drive omniglot_maml++_5-way_1-shot.json          synthetic_omniglot
drive omniglot_maml++_5-way_5-shot.json          synthetic_omniglot
drive omniglot_maml++_20-way_5-shot.json         synthetic_omniglot
drive mini-imagenet_maml++_5-way_1-shot.json     synthetic_mini_imagenet
drive mini-imagenet_maml++_5-way_5-shot_DA.json  synthetic_mini_imagenet
drive mini-imagenet_maml_5-way_1-shot.json       synthetic_mini_imagenet
drive mini-imagenet_maml_5-way_1-shot_canonical.json synthetic_mini_imagenet

echo "=== done: $FAILED failure(s) ==="
exit "$FAILED"

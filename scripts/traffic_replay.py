#!/usr/bin/env python
"""Traffic-lab proof driver: trace-driven open-loop replay.

Three legs replay the SAME shaped trace — a diurnal raised-cosine ramp
with a 10x base-to-peak swing, sliding tenant churn and a burst
overlay — against a freshly booted serving fleet each time:

* ``fixed``   — continuous batching OFF (the pre-assembler head-of-line
  dispatch), one replica, no rollout: the baseline.
* ``cb``      — continuous batching ON, otherwise identical: the clean
  p95 comparison pair. The gate is STRICT: cb p95 < fixed p95.
* ``rollout`` — continuous batching ON, two replicas with supervisor
  autoscaling (scale-up under the burst), and a weighted canary
  rollout (controller.py § weighted mode) triggered mid-ramp: the gate
  is the SLO held (burn <= 1.0), the rollout reaching DONE on real
  traffic, and at least one autoscale scale-up.

Why continuous batching wins here (and why the workload is shaped the
way it is): partial groups are PADDED to ``serve_batch_tasks`` before
the compiled step (engine.py), so a head-of-line dispatch of one
request costs the same accelerator time as a full group. Near the
ramp's peak the fixed-mode replica therefore runs at ~full utilization
dispatching partial groups, and its queue performs a random walk that
bursts push into long excursions; the assembler holds groups open for
a short linger, consistently dispatches fuller groups, and keeps real
headroom. The driver CALIBRATES that operating point per box instead
of hardcoding rates: it probes the booted replica's per-dispatch cost
(hit and miss paths) and sizes the peak rate so fixed-mode
single-dispatch is past saturation while grouped dispatch is not.

Open-loop discipline (serve/loadlab/replay.py): arrivals fire off the
trace clock, never off responses, and every latency is measured from
the SCHEDULED arrival instant — a fleet that falls behind accumulates
queueing the way production would (no coordinated omission).

Artifact: ``{"metric": "traffic_replay"}`` on the last stdout line
(schema keys ``traffic_p95_ms`` / ``traffic_slo_held`` /
``traffic_canary_weight_final`` / ``traffic_cb_groups`` — the nulls
serve_bench/fleet_bench carry). Prints ``status: skipped`` + rc 0
where localhost sockets cannot bind.

Usage:
    JAX_PLATFORMS=cpu python scripts/traffic_replay.py [--quick]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_SCRIPTS)
sys.path.insert(0, _SCRIPTS)
sys.path.insert(0, _REPO)

from fleet_bench import (  # noqa: E402
    ReplicaConn, _MiniMetrics, _can_bind_localhost, _load_module,
    _controller_mod, _router_mod, _run_child, _tracing_mod, bench_bucket,
    fleet_cfg_dict)
from chaos_fleet import FleetClient, _boot_fleet, make_spawn  # noqa: E402

_supervisor_mod = _load_module(
    "_traffic_replay_supervisor_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "fleet",
                 "supervisor.py"))
_workloads_mod = _load_module(
    "_traffic_replay_workloads_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "loadlab",
                 "workloads.py"))
_replay_mod = _load_module(
    "_traffic_replay_replay_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "loadlab",
                 "replay.py"))
_trace_mod = _workloads_mod.trace_mod()


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    return round(_tracing_mod.nearest_rank(sorted(vals), q), 3)


# ---------------------------------------------------------------------------
# trace + request synthesis
# ---------------------------------------------------------------------------

def build_trace(*, duration_s: float, base_rate: float, peak_rate: float,
                num_tenants: int, active_tenants: int,
                churn_every_s: float, bucket, seed: int
                ) -> List[Dict[str, Any]]:
    """Diurnal ramp (peak at duration/2) + a burst overlay on the
    rising edge — the burst is what trips the autoscaler BEFORE the
    mid-ramp rollout trigger, so the scaled-up replica is live (and in
    the stable cohort) when the canary bake starts."""
    records = _workloads_mod.gen_diurnal_trace(
        duration_s=duration_s, base_rate=base_rate, peak_rate=peak_rate,
        num_tenants=num_tenants, buckets=[bucket],
        active_tenants=active_tenants, churn_every_s=churn_every_s,
        seed=seed)
    # 3x the diurnal peak: under continuous batching a burst at the
    # base rate is absorbed into fuller groups without ever deepening
    # the queue — the overlay must outrun the linger-window drain so
    # per-replica depth actually rises above the diurnal-peak envelope
    # (the signal the scale-up threshold discriminates on).
    return _workloads_mod.overlay_burst(
        records, at_s=0.30 * duration_s, duration_s=0.08 * duration_s,
        rate=3.0 * peak_rate, num_tenants=num_tenants, buckets=[bucket],
        seed=seed)


def build_requests(records, pool, image_shape, num_tenants: int):
    """Pre-materialized wire payloads, one per trace record: the
    tenant's fixed support set + per-record-seed fresh queries (repeat
    tenants ARE the workload). Done before replay starts so array
    synthesis never shows up as replay lag."""
    import numpy as np
    reqs = []
    for i, rec in enumerate(records):
        t = int(rec["tenant"]) % num_tenants
        sx, sy, q_rows = pool[t]
        rq = np.random.RandomState(int(rec["seed"]) & 0x7FFFFFFF)
        _, _, qx = _workloads_mod.synthetic_arrays(
            image_shape, 3, True, rq, (1, q_rows))
        reqs.append({"tenant": t, "sx": sx, "sy": sy, "qx": qx,
                     "key": _router_mod.routing_key(sx, sy)})
    return reqs


def phase_plan(duration_s: float) -> List[Dict[str, Any]]:
    return [{"name": "trough", "until_s": 0.20 * duration_s},
            {"name": "ramp", "until_s": 0.42 * duration_s},
            {"name": "peak", "until_s": 0.70 * duration_s},
            {"name": "fall", "until_s": duration_s}]


# ---------------------------------------------------------------------------
# one leg: boot fleet, replay trace, settle, account
# ---------------------------------------------------------------------------

class TrafficLeg:
    """One fleet lifecycle around one open-loop replay.

    The pump (run from replay wait slices AND the drain/settle loops)
    does the housekeeping a real frontend runs: membership refresh,
    controller tick, signal publication -> autoscale advice ->
    supervisor tick, reconnects, and retry submission. Submission is
    cohort-aware: while the controller reports a weighted bake in
    flight, each request is deterministically assigned via
    ``assign_canary`` and routed ``among`` its cohort, and its
    completion is attributed back through ``observe_cohort``.
    """

    def __init__(self, name: str, out: str, cfg_path: str,
                 cfg_doc: dict, ckpt_dir: str, *, replicas: int,
                 scale_max: Optional[int] = None,
                 autoscale: bool = False,
                 queue_high_per_replica: float = 2.0,
                 max_retries: int = 20):
        self.name = name
        self.out = out
        self.cfg_doc = cfg_doc
        self.autoscale = autoscale
        self.queue_high = queue_high_per_replica
        self.max_retries = max_retries
        self.replicas = replicas
        self.fleet_dir = os.path.join(out, f"fleet_{name}")
        self.registry = _MiniMetrics()
        self.router = _router_mod.FleetRouter(
            self.fleet_dir, vnodes=int(cfg_doc["fleet_vnodes"]),
            load_factor=float(cfg_doc["fleet_load_factor"]),
            stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
            dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
            breaker_cooldown_s=1.0, registry=self.registry)
        self.controller = _controller_mod.FleetController(
            self.fleet_dir, self.router.refresh, registry=self.registry,
            slo_p95_ms=float(cfg_doc["fleet_slo_p95_ms"]),
            slo_target_frac=float(cfg_doc.get("fleet_slo_target_frac")
                                  or 0.95),
            canary_min_requests=int(cfg_doc.get("fleet_canary_min_requests")
                                    or 32),
            canary_burn_factor=float(cfg_doc.get("fleet_canary_burn_factor")
                                     or 2.0))
        self.sup = _supervisor_mod.ReplicaSupervisor(
            self.fleet_dir,
            make_spawn(out, cfg_path, ckpt_dir, self.fleet_dir),
            desired=replicas, scale_min=replicas,
            scale_max=scale_max or replicas,
            max_restarts=5, restart_window_s=300.0,
            stalled_after_s=float(cfg_doc["fleet_replica_stalled_s"]),
            dead_after_s=float(cfg_doc["fleet_replica_dead_s"]),
            start_timeout_s=420.0, backoff_base_s=0.2, backoff_cap_s=2.0,
            registry=self.registry,
            events_path=os.path.join(out, f"events_sup_{name}.jsonl"))
        self.client = FleetClient(self.router, self.fleet_dir)
        # request bookkeeping (cid = trace record index; warmup/topup
        # requests use negative ids so they never collide)
        self.lock = threading.Lock()
        self.results: Dict[int, dict] = {}
        self.sched: Dict[int, float] = {}
        self.tenant_of: Dict[int, int] = {}
        self.cohort_of: Dict[int, str] = {}
        self.rid_of: Dict[int, int] = {}
        self.retry_count: Dict[int, int] = {}
        self.retry_q: deque = deque()
        self.latency_ms: Dict[int, float] = {}
        self.untracked: set = set()  # warmup ids: excluded from stats
        self._stash: Dict[int, dict] = {}  # cid -> payload (for retries)
        self.split = {"weight": None, "canary": [], "stage": None}
        self.suppressed_scale_downs = 0
        self._last_pump = 0.0
        self._fire_rollout: Optional[Any] = None  # set by run_rollout

    # -- lifecycle --------------------------------------------------------
    def boot(self) -> None:
        _boot_fleet(self.sup, self.client, self.router,
                    want_live=self.replicas)
        self._attach()

    def stop(self) -> None:
        self.sup.stop()
        self.client.close()

    def _attach(self) -> None:
        for conn in self.client.conns.values():
            if conn._on_response is not self._on_response:
                conn._on_response = self._on_response

    # -- response path ----------------------------------------------------
    def _on_response(self, rid: int, msg: dict) -> None:
        cid = msg.get("id")
        with self.lock:
            self.router.complete(self.rid_of.get(cid, rid))
            err = msg.get("error")
            if not err:
                self.router.record_success(rid)
            if err and str(err).startswith("rejected") \
                    and self.retry_count.get(cid, 0) < self.max_retries:
                self.retry_count[cid] = self.retry_count.get(cid, 0) + 1
                self.retry_q.append(cid)
                return
            msg["rid"] = rid
            self.results[cid] = msg
            if cid in self.untracked:
                return
            lat = (time.monotonic() - self.sched[cid]) * 1e3
            self.latency_ms[cid] = lat
            tenant = self.tenant_of.get(cid)
            self.controller.slo.observe(tenant, lat)
            cohort = self.cohort_of.get(cid)
            if cohort is not None:
                self.controller.observe_cohort(cohort, tenant, lat)

    # -- submission -------------------------------------------------------
    def _send(self, cid: int, item: dict) -> bool:
        """Route + send one request under the current traffic split.
        Caller holds the lock. False = no route yet (stays queued)."""
        self._stash[cid] = item
        among = None
        w = self.split["weight"]
        if w is not None:
            canary = set(self.split["canary"])
            if _router_mod.assign_canary(item["tenant"], cid, w):
                self.cohort_of[cid] = "canary"
                among = sorted(canary)
                self.registry.counter(
                    _router_mod.CANARY_REQUESTS_COUNTER).inc()
            else:
                self.cohort_of[cid] = "stable"
                among = [r for r in self.router.routable
                         if r not in canary] or None
        rid = self.router.route(item["key"], among=among)
        if rid is None or rid not in self.client.conns:
            if rid is not None:
                self.router.complete(rid)
            return False
        self.rid_of[cid] = rid
        try:
            self.client.conns[rid].send(
                {"op": "serve", "id": cid, "support_x": item["sx"],
                 "support_y": item["sy"], "query_x": item["qx"]})
        except OSError:
            self.router.complete(rid)
            self.router.record_failure(rid)
            return False
        return True

    def submit(self, cid: int, item: dict, scheduled: float) -> None:
        with self.lock:
            self.sched.setdefault(cid, scheduled)
            self.tenant_of[cid] = item["tenant"]
            if not self._send(cid, item):
                self.retry_count[cid] = self.retry_count.get(cid, 0)
                self.retry_q.append(cid)

    # -- housekeeping -----------------------------------------------------
    def pump(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if now - self._last_pump < 0.05:
            return
        self._last_pump = now
        self.router.refresh()
        self.controller.tick()
        if self.autoscale:
            advice = _controller_mod.advise(
                self.controller.publish_signals(),
                live=len(self.router.routable),
                queue_per_replica_high=self.queue_high,
                p95_high_ms=0.6 * float(self.cfg_doc["fleet_slo_p95_ms"]),
                min_replicas=self.replicas)
            if advice == "scale_down":
                # The lab gates on scale-UP under load; culling capacity
                # while a rollout may be in flight is the chaos suite's
                # territory, not this proof's. Counted, not hidden.
                self.suppressed_scale_downs += 1
                advice = "hold"
            self.sup.tick(advice=advice)
        else:
            self.sup.tick()
        self.client.pump()
        self._attach()
        self.split = self.controller.traffic_split()
        if self._fire_rollout is not None:
            self._fire_rollout()
        with self.lock:
            for _ in range(len(self.retry_q)):
                cid = self.retry_q.popleft()
                if not self._send(cid, self._stash[cid]):
                    self.retry_q.append(cid)
                    break

    # -- the replay -------------------------------------------------------
    def replay(self, records, requests, *, warp: float,
               drain_timeout_s: float = 120.0) -> Dict[str, Any]:
        rep = _replay_mod.replay(
            records,
            lambda i, rec, sched: self.submit(i, requests[i], sched),
            warp=warp, pump=self.pump)
        deadline = time.monotonic() + drain_timeout_s
        total = len(records)
        while time.monotonic() < deadline:
            with self.lock:
                done = sum(1 for c in self.results
                           if c >= 0 and c not in self.untracked)
            if done >= total:
                break
            self.pump()
            time.sleep(0.02)
        return rep

    def warmup(self, items, timeout_s: float = 60.0) -> List[float]:
        """Sequential round trips outside the stats (negative ids).
        Returns per-request wall latencies ms — the calibration probe
        reads them; warmup proper ignores them."""
        out: List[float] = []
        for j, item in enumerate(items):
            cid = -(j + 1 + len(self.untracked))
            self.untracked.add(cid)
            evt = threading.Event()
            with self.lock:
                self.sched[cid] = time.monotonic()
                self.tenant_of[cid] = item["tenant"]
            t0 = time.monotonic()
            sent = False
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                with self.lock:
                    if not sent:
                        sent = self._send(cid, item)
                    if cid in self.results:
                        evt.set()
                if evt.is_set():
                    break
                self.pump()
                time.sleep(0.005)
            if not evt.is_set():
                raise TimeoutError(
                    f"{self.name}: warmup request {cid} timed out")
            out.append((time.monotonic() - t0) * 1e3)
        return out

    # -- accounting -------------------------------------------------------
    def leg_stats(self, records, phases, rep: Dict[str, Any]
                  ) -> Dict[str, Any]:
        with self.lock:
            lat = dict(self.latency_ms)
            tracked = {c: r for c, r in self.results.items()
                       if c >= 0 and c not in self.untracked}
        vals = [lat[c] for c in lat if c >= 0]
        failed = sum(1 for r in tracked.values() if r.get("error"))
        per_replica = {}
        for rid, conn in sorted(self.client.conns.items()):
            try:
                per_replica[str(rid)] = conn.stats()
            except Exception as e:  # noqa: BLE001
                per_replica[str(rid)] = {"error": str(e)}
        cb_groups = sum(
            int(((rec.get("stats") or {}).get("cb_groups")) or 0)
            for rec in per_replica.values())
        sheds = sum(int(((rec.get("stats") or {}).get("sheds")) or 0)
                    for rec in per_replica.values())
        burn = self.controller.slo.burn_rate()
        snap = self.registry.snapshot()
        return {
            "offered": len(records),
            "completed": len(tracked) - failed,
            "failed": failed,
            "dropped": len(records) - len(tracked),
            "rejected_retries": sum(self.retry_count.values()),
            "p50_ms": _pct(vals, 0.50), "p95_ms": _pct(vals, 0.95),
            "p99_ms": _pct(vals, 0.99),
            "phases": _replay_mod.phase_stats(
                records, phases, lat,
                lambda v, q: _tracing_mod.nearest_rank(v, q)),
            "max_lag_ms": rep.get("max_lag_ms"),
            "lag_p95_ms": _pct(list(rep.get("lag_ms") or []), 0.95),
            "wall_seconds": rep.get("wall_seconds"),
            "slo_burn_rate": burn,
            "slo_held": bool(burn is not None and burn <= 1.0),
            "cb_groups": cb_groups, "sheds": sheds,
            "cohort_fallbacks": int(snap.get(
                _router_mod.COHORT_FALLBACK_COUNTER, 0)),
            "canary_requests": int(snap.get(
                _router_mod.CANARY_REQUESTS_COUNTER, 0)),
            "scale_ups": int(snap.get(
                _supervisor_mod.SCALE_UPS_COUNTER, 0)),
            "scale_downs": int(snap.get(
                _supervisor_mod.SCALE_DOWNS_COUNTER, 0)),
            "suppressed_scale_downs": self.suppressed_scale_downs,
            "per_replica_responses": {
                str(rid): sum(1 for r in tracked.values()
                              if r.get("rid") == rid)
                for rid in sorted(self.client.conns)},
        }


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def calibrate(leg: TrafficLeg, image_shape, bucket, *, probes: int = 5
              ) -> Dict[str, Any]:
    """Measure this box's per-dispatch serve cost on the booted
    baseline replica: fresh-tenant requests price the padded
    adapt-on-miss dispatch, an immediate repeat prices the cache-hit
    (predict-only) dispatch. Both are flat in group size (partial
    groups are padded), which is exactly the asymmetry the cb leg
    exploits — so the operating point is derived from them."""
    import numpy as np
    rng = np.random.RandomState(0xCA1)
    miss_items, hit_items = [], []
    for j in range(probes):
        sx, sy, _ = _workloads_mod.synthetic_arrays(
            image_shape, 3, True, rng, bucket)
        _, _, qx = _workloads_mod.synthetic_arrays(
            image_shape, 3, True, rng, (1, bucket[1]))
        item = {"tenant": 100000 + j, "sx": sx, "sy": sy, "qx": qx,
                "key": _router_mod.routing_key(sx, sy)}
        miss_items.append(item)
        hit_items.append(dict(item))
    # First round trips pay any residual warm-up; probe on a second set.
    leg.warmup(miss_items[:2])
    miss_ms = leg.warmup(miss_items[2:])
    hit_ms = leg.warmup(hit_items[2:])
    med = lambda v: sorted(v)[len(v) // 2]  # noqa: E731
    return {"probe_miss_ms": round(med(miss_ms), 2),
            "probe_hit_ms": round(med(hit_ms), 2)}


def operating_point(cal: Dict[str, Any], *, miss_frac: float = 0.12
                    ) -> Dict[str, float]:
    """Rates + linger from the probed costs: peak sized so fixed-mode
    SINGLE dispatch runs past saturation (1.5x) while full groups keep
    >= 2x headroom; linger long enough to assemble most of a group at
    peak, capped so it never dominates the SLO."""
    c = (cal["probe_hit_ms"]
         + miss_frac * max(cal["probe_miss_ms"] - cal["probe_hit_ms"],
                           0.0)) / 1e3
    c = max(c, 0.010)
    peak = min(max(1.5 / c, 4.0), 40.0)
    linger_ms = min(max(2.5 * c * 1e3, 40.0), 250.0)
    return {"per_request_cost_ms": round(c * 1e3, 2),
            "peak_rate": round(peak, 2),
            "base_rate": round(peak / 10.0, 3),
            "linger_ms": round(linger_ms, 1)}


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="traffic lab: open-loop trace replay "
                    "(fixed / cb / rollout legs)")
    ap.add_argument("--quick", action="store_true",
                    help="short trace for CI smoke")
    ap.add_argument("--out", default=None)
    ap.add_argument("--duration", type=float, default=None,
                    help="trace duration seconds (default 60, quick 24)")
    ap.add_argument("--warp", type=float, default=1.0)
    ap.add_argument("--peak-rate", type=float, default=0.0,
                    help="peak request rate; 0 = calibrate on this box")
    ap.add_argument("--linger-ms", type=float, default=0.0,
                    help="cb linger; 0 = calibrate")
    ap.add_argument("--tenants", type=int, default=96)
    ap.add_argument("--active-tenants", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    duration = args.duration or (24.0 if args.quick else 60.0)
    artifact: Dict[str, Any] = {
        "metric": "traffic_replay", "value": None, "unit": "p95_ms",
        "status": "failed", "quick": bool(args.quick),
        "duration_s": duration, "warp": args.warp,
        "traffic_p95_ms": None, "traffic_slo_held": None,
        "traffic_canary_weight_final": None, "traffic_cb_groups": None,
    }
    if not _can_bind_localhost():
        artifact.update({"status": "skipped",
                         "skip_reason": "cannot bind localhost sockets"})
        print(json.dumps(artifact), flush=True)
        return 0

    out = args.out or tempfile.mkdtemp(prefix="traffic_replay_")
    made_tmp = args.out is None
    os.makedirs(out, exist_ok=True)
    ckpt_dir = os.path.join(out, "saved_models")
    l2_dir = os.path.join(out, "l2")
    bucket = bench_bucket(True)

    # The traffic lab measures SCHEDULING — batching, traffic split,
    # autoscale — not adaptation FLOPs, so every leg runs the quick
    # serving profile (the calibrated rates carry the load shape; a
    # big model on this box would just scale everything down).
    base_doc = fleet_cfg_dict(out, quick=True, l1_capacity=48,
                              l2_dir=l2_dir)
    base_doc.update(serve_max_queue_depth=512, fleet_slo_p95_ms=2000.0)
    # The full run walks the config-default 1% -> 10% -> 100% ladder.
    # Evidence at 1% of a ~40 req/s peak trickles in at ~0.4/s, so the
    # minimum per-stage count is small and the rollout triggers just
    # BEFORE peak (0.45 * duration) to give stage 0 the whole peak
    # plateau. The quick profile (a 24s trace) can't feed a 1% stage
    # at all — it rides a 2-stage 25% -> 100% ladder instead.
    if args.quick:
        weights, min_requests = [0.25, 1.0], 10
    else:
        weights, min_requests = [0.01, 0.10, 1.0], 5
    docs = {
        "fixed": dict(base_doc, serve_continuous_batching=0),
        "cb": dict(base_doc, serve_continuous_batching=1),
        "rollout": dict(base_doc, serve_continuous_batching=1,
                        fleet_canary_weights=weights,
                        fleet_canary_min_requests=min_requests,
                        fleet_canary_burn_factor=2.0),
    }
    cfg_paths = {}
    for name, doc in docs.items():
        cfg_paths[name] = os.path.join(out, f"cfg_{name}.json")
        with open(cfg_paths[name], "w") as f:
            json.dump(doc, f)

    image_shape = (base_doc["image_height"], base_doc["image_width"],
                   base_doc["image_channels"])
    phases = phase_plan(duration)
    legs: Dict[str, Any] = {}
    try:
        t_prep = time.monotonic()
        _run_child("prepare", cfg_paths["fixed"], ckpt_dir, out)
        artifact["prepare_seconds"] = round(time.monotonic() - t_prep, 1)

        # ---- leg 1: fixed (also hosts the calibration probe) ----------
        leg = TrafficLeg("fixed", out, cfg_paths["fixed"], docs["fixed"],
                         ckpt_dir, replicas=1)
        leg.boot()
        cal = calibrate(leg, image_shape, bucket)
        op = operating_point(cal)
        if args.peak_rate > 0:
            op["peak_rate"] = args.peak_rate
            op["base_rate"] = args.peak_rate / 10.0
        if args.linger_ms > 0:
            op["linger_ms"] = args.linger_ms
        artifact["calibration"] = dict(cal, **op)

        records = build_trace(
            duration_s=duration, base_rate=op["base_rate"],
            peak_rate=op["peak_rate"], num_tenants=args.tenants,
            active_tenants=args.active_tenants,
            churn_every_s=max(duration / 30.0, 1.0), bucket=bucket,
            seed=args.seed)
        trace_path = os.path.join(out, "diurnal.trace")
        _trace_mod.write_trace(trace_path, records, meta={
            "workload": "diurnal+churn+burst",
            "base_rate": op["base_rate"], "peak_rate": op["peak_rate"],
            "duration_s": duration, "tenants": args.tenants})
        uniq = len({r["tenant"] for r in records})
        artifact["trace"] = {
            "records": len(records), "unique_tenants": uniq,
            "miss_frac_est": round(uniq / max(len(records), 1), 3),
            "base_rate": op["base_rate"], "peak_rate": op["peak_rate"],
            "swing": round(op["peak_rate"] / max(op["base_rate"], 1e-9),
                           1)}
        import numpy as np
        pool = _workloads_mod.tenant_pool(
            image_shape, 3, True, np.random.RandomState(args.seed),
            [bucket], args.tenants)
        requests = build_requests(records, pool, image_shape,
                                  args.tenants)

        rep = leg.replay(records, requests, warp=args.warp)
        legs["fixed"] = leg.leg_stats(records, phases, rep)
        leg.stop()

        # ---- leg 2: cb -------------------------------------------------
        docs["cb"]["serve_batch_linger_ms"] = op["linger_ms"]
        docs["rollout"]["serve_batch_linger_ms"] = op["linger_ms"]
        for name in ("cb", "rollout"):
            with open(cfg_paths[name], "w") as f:
                json.dump(docs[name], f)
        leg = TrafficLeg("cb", out, cfg_paths["cb"], docs["cb"],
                         ckpt_dir, replicas=1)
        leg.boot()
        leg.warmup([requests[0], requests[1]])
        rep = leg.replay(records, requests, warp=args.warp)
        legs["cb"] = leg.leg_stats(records, phases, rep)
        leg.stop()

        # ---- leg 3: rollout (cb + autoscale + weighted canary) ---------
        leg = TrafficLeg("rollout", out, cfg_paths["rollout"],
                         docs["rollout"], ckpt_dir, replicas=2,
                         scale_max=3, autoscale=True)
        leg.boot()
        leg.warmup([requests[0], requests[1]])
        # Late-ramp trigger: once the replay crosses the record whose
        # arrival is at 0.45 * duration (just before the crest),
        # publish v2 off-thread and start the WEIGHTED rollout the
        # moment the publish lands — stage 0's thin canary slice gets
        # the whole peak plateau to gather its evidence.
        trigger_idx = next((i for i, r in enumerate(records)
                            if r["t"] >= 0.45 * duration), len(records))
        box: Dict[str, Any] = {}

        def fire_when_due() -> None:
            with leg.lock:
                submitted = len(leg.sched)
            if box.get("fired") or submitted < trigger_idx:
                return
            box["fired"] = True

            def _worker():
                _run_child("publish-v2", cfg_paths["rollout"], ckpt_dir,
                           out)
                with open(os.path.join(out, "publish-v2.log")) as f:
                    last = [ln for ln in f.read().splitlines()
                            if ln.strip()][-1]
                version = int(json.loads(last)["version"])
                leg.controller.start_rollout(version, weights=weights)
                box["version"] = version
            t = threading.Thread(target=_worker, daemon=True)
            box["thread"] = t
            t.start()

        leg._fire_rollout = fire_when_due
        rep = leg.replay(records, requests, warp=args.warp)
        worker = box.get("thread")
        if worker is not None:
            worker.join(timeout=180)
        # Settle: a bake stage needs live traffic for cohort evidence —
        # trickle trace-shaped top-up requests until the rollout exits
        # ROLLING (counted; the bulk of the rollout ran mid-trace).
        topup = 0
        settle_deadline = time.monotonic() + 120.0
        doc = leg.controller.read_rollout()
        next_send = time.monotonic()
        while (doc.get("state") == _controller_mod.ROLLING
               and time.monotonic() < settle_deadline):
            now = time.monotonic()
            if now >= next_send:
                i = topup % len(requests)
                cid = 1_000_000 + topup
                leg.untracked.add(cid)
                leg.submit(cid, requests[i], now)
                topup += 1
                # ~20/s: enough that even a 1% canary slice sees an
                # observation every few seconds if a bake stage is
                # still open when the trace runs out.
                next_send = now + 0.05
            leg.pump()
            time.sleep(0.01)
            doc = leg.controller.read_rollout()
        legs["rollout"] = leg.leg_stats(records, phases, rep)
        legs["rollout"]["topup_requests"] = topup
        legs["rollout"]["rollout"] = {
            k: doc.get(k) for k in
            ("state", "version", "mode", "stage", "phase", "canary",
             "index", "rejected", "halt_reason", "halt_detail",
             "stage_history")}
        leg.stop()

        # ---- gates -----------------------------------------------------
        fixed, cb, roll = legs["fixed"], legs["cb"], legs["rollout"]
        w_final = None
        if doc.get("mode") == "weighted":
            stage = min(int(doc.get("stage") or 0), len(weights) - 1)
            w_final = (weights[-1]
                       if doc.get("state") == _controller_mod.DONE
                       else weights[stage])
        gates = {
            "cb_beats_fixed": bool(
                cb["p95_ms"] is not None and fixed["p95_ms"] is not None
                and cb["p95_ms"] < fixed["p95_ms"]),
            "cb_structural": bool(fixed["cb_groups"] == 0
                                  and cb["cb_groups"] > 0),
            "zero_dropped": bool(fixed["dropped"] == 0
                                 and cb["dropped"] == 0
                                 and roll["dropped"] == 0
                                 and roll["failed"] == 0),
            "slo_held": bool(roll["slo_held"]),
            "rollout_done": bool(
                doc.get("state") == _controller_mod.DONE),
            "autoscaled": bool(roll["scale_ups"] >= 1),
        }
        ok = all(gates.values())
        artifact.update({
            "status": "ok" if ok else "failed",
            "value": roll["p95_ms"],
            "gates": gates, "legs": legs,
            "traffic_p95_ms": roll["p95_ms"],
            "traffic_slo_held": roll["slo_held"],
            "traffic_canary_weight_final": w_final,
            "traffic_cb_groups": roll["cb_groups"],
            "out_dir": None if made_tmp else out,
        })
        print(json.dumps(artifact), flush=True)
        if made_tmp and ok:
            shutil.rmtree(out, ignore_errors=True)
        return 0 if ok else 1
    except Exception as e:  # noqa: BLE001 — the artifact IS the report
        artifact.update({"status": "failed",
                         "error": f"{type(e).__name__}: {e}",
                         "legs": legs, "out_dir": out})
        print(json.dumps(artifact), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Perf sweep over remat/unroll/batch on the flagship bench workload.

Usage: python scripts/perf_sweep.py [--steps N]
Prints one JSON line per variant; used to pick bench.py's defaults.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, synthetic_batch
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, shard_batch)


def run_variant(batch, remat, policy, unroll, steps):
    n_dev = len(jax.devices())
    cfg = flagship_config(batch * n_dev, n_dev).replace(
        remat_inner_steps=remat, remat_policy=policy, inner_unroll=unroll)
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices())
    plan = make_sharded_steps(cfg, apply, mesh)
    train = plan.train_steps[(True, True)]
    state = jax.device_put(
        init_train_state(cfg, init, jax.random.PRNGKey(0)),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    ep = shard_batch(synthetic_batch(cfg, 0), mesh)
    epoch = jnp.float32(20.0)
    for _ in range(3):
        state, m = train(state, ep, epoch)
        float(jax.device_get(m.loss))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = train(state, ep, epoch)
        float(jax.device_get(m.loss))
    dt = time.perf_counter() - t0
    if not np.isfinite(float(jax.device_get(m.loss))):
        raise RuntimeError("non-finite loss")
    return cfg.batch_size * steps / dt / n_dev


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()
    grid = [
        # (batch/chip, remat, policy, unroll)
        (16, True, "nothing", 1),   # current default
        (16, True, "conv_outs", 1),
        (16, True, "dots", 1),
        (16, False, "nothing", 1),  # no remat at all
        (16, True, "nothing", 5),
        (16, False, "nothing", 5),
        (32, True, "nothing", 1),
        (32, False, "nothing", 1),
        (32, True, "conv_outs", 1),
        (64, True, "nothing", 1),
    ]
    for batch, remat, policy, unroll in grid:
        try:
            v = run_variant(batch, remat, policy, unroll, args.steps)
            print(json.dumps({"batch_per_chip": batch, "remat": remat,
                              "policy": policy, "unroll": unroll,
                              "tasks_per_sec_per_chip": round(v, 2)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"batch_per_chip": batch, "remat": remat,
                              "policy": policy, "unroll": unroll,
                              "error": str(e)[:200]}), flush=True)
            traceback.print_exc()


if __name__ == "__main__":
    main()

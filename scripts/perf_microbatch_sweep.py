"""task_microbatches sweep over the non-flagship configs (VERDICT r3
item 4): the lever measured +34-39% on the two flagship configs
(docs/PERF.md § Microbatching); this script asks the same question at
fixed per-chip batch for the rest of the family. The round-4 session ran
it and shipped every winner (docs/PERF.md § Round-4 hardware session
results) — a re-run now sweeps AGAINST those shipped values, which each
config's closing JSON line reports as `shipped_mb`/`shipped_rate`.

For each target config: build the steady-state executable (bench.py's
single build path) at each divisor of the per-chip batch and measure
with the shared 3-window-median methodology. One JSON line per point;
a final line per config names the winner and the shipped value so the
ship-only-with-a-measurement rule has its numbers.

Usage: python scripts/perf_microbatch_sweep.py [--steps N]
           [--configs a.json b.json ...] [--max-mb M]
Run on a QUIET box (any concurrent compile contaminates the timings —
docs/PERF.md § methodology).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (build_steady_state, init_backend, load_workload,  # noqa: E402
                   measure_rate)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The non-flagship family members — the four Omniglot MAML++ configs,
# both mini-ImageNet 1-shot configs, and the canonical plain-MAML point.
# All carry r4-measured winners now (docs/PERF.md § Round-4 results).
DEFAULT_TARGETS = [
    "omniglot_maml++_5-way_1-shot.json",
    "omniglot_maml++_5-way_5-shot.json",
    "omniglot_maml++_20-way_1-shot.json",
    "omniglot_maml++_20-way_5-shot.json",
    "mini-imagenet_maml++_5-way_1-shot.json",
    "mini-imagenet_maml_5-way_1-shot.json",
    "mini-imagenet_maml_5-way_1-shot_canonical.json",
]


def divisors(n: int, cap: int) -> list:
    return [d for d in range(1, min(n, cap) + 1) if n % d == 0]


def sweep_config(name: str, steps: int, max_mb: int, devices) -> dict:
    path = os.path.join(REPO, "experiment_config", name)
    n_dev = len(devices)
    base = load_workload(path, 0, n_dev)
    per_chip = max(base.batch_size // n_dev, 1)
    shipped_mb = base.task_microbatches
    rows = {}
    for mb in divisors(per_chip, max_mb):
        cfg = base.replace(task_microbatches=mb)
        try:
            wl = build_steady_state(cfg, devices)
            rate = measure_rate(wl.compiled, wl.state, wl.batch_ep,
                                wl.epoch, batch_size=cfg.batch_size,
                                n_dev=n_dev, steps=steps)
            rows[mb] = round(rate, 2)
            print(json.dumps({"config": name, "mb": mb,
                              "tasks_per_sec_per_chip": rows[mb]}),
                  flush=True)
        except Exception:
            print(json.dumps({"config": name, "mb": mb,
                              "error": traceback.format_exc(limit=1)}),
                  flush=True)
    verdict = {"config": name, "per_chip_batch": per_chip,
               "shipped_mb": shipped_mb, "rows": rows}
    if rows:
        best_mb = max(rows, key=rows.get)
        verdict.update(
            best_mb=best_mb, best_rate=rows[best_mb],
            shipped_rate=rows.get(shipped_mb),
            gain_vs_shipped=(round(rows[best_mb] / rows[shipped_mb], 3)
                             if rows.get(shipped_mb) else None))
    print(json.dumps(verdict), flush=True)
    return verdict


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--configs", nargs="*", default=DEFAULT_TARGETS)
    ap.add_argument("--max-mb", type=int, default=16)
    ap.add_argument("--backend-timeout", type=float, default=600.0)
    args = ap.parse_args()
    devices = init_backend(args.backend_timeout)
    verdicts = []
    for c in args.configs:
        try:
            verdicts.append(sweep_config(c, args.steps, args.max_mb,
                                         devices))
        except Exception:  # one bad config must not lose the rest of a
            # possibly hours-long sweep; the error verdict keeps the
            # one-JSON-line-per-point crash-resilient record complete.
            print(json.dumps({"config": c, "rows": {},
                              "error": traceback.format_exc(limit=1)}),
                  flush=True)
            verdicts.append({"config": c, "rows": {}})
    print(json.dumps({"summary": {v["config"]: v.get("best_mb")
                                  for v in verdicts}}), flush=True)
    # A sweep where EVERY point errored (backend half-up) must not read
    # as a successful capture to the session driver.
    return 0 if any(v["rows"] for v in verdicts) else 1


if __name__ == "__main__":
    sys.exit(main())

"""Component-level timing: where does a train step's time go?

.. deprecated::
    Superseded by the perf lab (telemetry/profiler.py +
    ``scripts/perf_report.py``, docs/PERF.md § Where the time goes):
    sampled profiler windows attribute REAL device time per executable
    and per named region, and PROFILE.json cost cards carry the one
    trip-expanded flops algorithm (utils/hlo_flops.py) with roofline
    verdicts — this script's hand-built component timings remain only
    as a quick interactive sanity probe. Pass ``--profile-json`` to
    print the cost-card table from a run's PROFILE.json next to the
    timings instead of deriving any cost numbers privately.

Times (a) plain model forward, (b) forward+backward wrt fast weights,
(c) one full inner step chain without outer grad, (d) full train step —
on the flagship bench shapes. Used to target kernel-level optimization.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, synthetic_batch
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.meta.inner import task_forward
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.ops.losses import cross_entropy
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, shard_batch)


def timeit(fn, *args, n=10):
    for _ in range(2):
        out = fn(*args)
        jax.tree.map(lambda x: x.block_until_ready(), out)
        _ = float(np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
        _ = float(np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[0])
    return (time.perf_counter() - t0) / n


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile-json", default=None, metavar="PATH",
                    help="print the cost cards from a run's "
                         "PROFILE.json (telemetry/profiler.py) before "
                         "the component timings — the consolidated "
                         "flops source (scripts/perf_report.py renders "
                         "the full ranked report)")
    args = ap.parse_args()
    if args.profile_json:
        from howtotrainyourmamlpytorch_tpu.telemetry import (
            profiler as profiler_mod)
        doc = profiler_mod.load_profile(args.profile_json)
        if doc is None:
            print(json.dumps({"error": f"unreadable PROFILE.json at "
                                       f"{args.profile_json!r}"}))
        else:
            for name, card in sorted(doc["cards"].items()):
                print(json.dumps({
                    "cost_card": name, "bound": card.get("bound"),
                    "gflops": round((card.get("flops") or 0) / 1e9, 3),
                    "gbytes": round((card.get("bytes_accessed") or 0)
                                    / 1e9, 3)}), flush=True)
    cfg = flagship_config(16, 1)
    init, apply = make_model(cfg)
    params, bn_state = init(jax.random.PRNGKey(0))
    ep = synthetic_batch(cfg, 0)
    b = cfg.batch_size
    # All support images of the meta-batch as one conv batch (what vmap
    # effectively gives the convs).
    xs = jnp.asarray(ep.support_x.reshape(-1, *cfg.image_shape))
    ys = jnp.asarray(ep.support_y.reshape(-1))

    @jax.jit
    def fwd(params, bn_state, x):
        logits, _ = apply(params, bn_state, x, jnp.int32(0), True)
        return logits

    @jax.jit
    def fwd_bwd(params, bn_state, x, y):
        def loss_fn(p):
            logits, _ = apply(p, bn_state, x, jnp.int32(0), True)
            return cross_entropy(logits, y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        # Keep the gradients live (summed into the output) or XLA
        # dead-code-eliminates the whole backward pass.
        gsum = sum(jnp.sum(g) for g in jax.tree.leaves(grads))
        return loss + 0.0 * gsum, None

    t_fwd = timeit(lambda: fwd(params, bn_state, xs), n=20)
    t_fb = timeit(lambda: fwd_bwd(params, bn_state, xs, ys), n=20)
    print(json.dumps({"what": f"forward {xs.shape[0]} imgs",
                      "ms": round(t_fwd * 1e3, 2)}), flush=True)
    print(json.dumps({"what": f"fwd+bwd {xs.shape[0]} imgs",
                      "ms": round(t_fb * 1e3, 2)}), flush=True)

    # Inner adaptation only (no outer grad), vmapped over tasks.
    from howtotrainyourmamlpytorch_tpu.meta.inner import lslr_init, split_fast_slow
    fast0, _ = split_fast_slow(cfg, params)
    lslr = lslr_init(cfg, fast0)
    ep_dev = jax.device_put(ep)

    @jax.jit
    def inner_only(params, lslr, bn_state, batch):
        def one(task_ep):
            return task_forward(cfg, apply, params, lslr, bn_state, task_ep,
                                num_steps=5, second_order=False,
                                use_msl=False, msl_weights=None).loss
        return jnp.mean(jax.vmap(one)(batch))

    t_inner = timeit(lambda: inner_only(params, lslr, bn_state, ep_dev), n=5)
    print(json.dumps({"what": f"inner K=5 x {b} tasks, first-order, no outer",
                      "ms": round(t_inner * 1e3, 2),
                      "tasks_per_s": round(b / t_inner, 1)}), flush=True)

    # Full sharded train step (second-order + MSL).
    mesh = make_mesh(cfg, jax.devices()[:1])
    plan = make_sharded_steps(cfg, apply, mesh)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    state = jax.device_put(
        state, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    epb = shard_batch(synthetic_batch(cfg, 0), mesh)

    def full(state):
        s2, m = plan.train_steps[(True, True)](state, epb, jnp.float32(20.0))
        return s2, m

    # manual timing to thread state
    for _ in range(3):
        state, m = full(state)
        float(jax.device_get(m.loss))
    t0 = time.perf_counter()
    n = 10
    for _ in range(n):
        state, m = full(state)
        float(jax.device_get(m.loss))
    t_full = (time.perf_counter() - t0) / n
    print(json.dumps({"what": f"full train step (2nd order + MSL), {b} tasks",
                      "ms": round(t_full * 1e3, 2),
                      "tasks_per_s": round(b / t_full, 1)}), flush=True)


if __name__ == "__main__":
    main()

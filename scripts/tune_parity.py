"""Autotune parity gate: tuned program vs untuned program, one verdict.

Runs K train steps of the TUNED candidate (a trial config + its XLA
compiler options, applied through the ``xla_compiler_options`` config
key — i.e. the exact plumbing a tuned training launch uses) and of the
UNTUNED base program, from the same seed on the same synthetic batch,
then compares the resulting train states and losses:

* ``bitwise`` — every leaf identical (remat/microbatch points and most
  pure scheduling flags land here: the math is unchanged by
  construction);
* ``tolerance`` — max relative error <= --tolerance (default 5e-3, the
  bn_fast_math / perf-variants precedent in tests/test_outer.py);
* ``fail`` — beyond tolerance, structurally incomparable states, or
  the tuned program refusing to compile (a flag good enough to win the
  sweep can still be a flag the backend rejects at this geometry —
  that MUST refuse adoption, which is why the driver runs this probe
  in a subprocess like any trial).

Artifact contract: the LAST stdout JSON line is
``{"metric": "tune_parity", "pass": ..., "mode": ...}``.
Exit 0 pass, 2 fail, 1 error. Invoked by scripts/autotune.py
(tune/harness.py § run_parity); runnable standalone for forensics.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bitwise-or-tolerance parity: tuned vs untuned "
                    "train program")
    ap.add_argument("--config", required=True,
                    help="the TUNED candidate's config JSON (trial "
                         "structural overrides already applied)")
    ap.add_argument("--base-config", required=True,
                    help="the UNTUNED base config JSON")
    ap.add_argument("--compiler-option", action="append", default=[],
                    metavar="KEY=VAL",
                    help="the candidate's XLA options (repeatable); "
                         "applied via the xla_compiler_options config "
                         "key — the adoption plumbing under test")
    ap.add_argument("--steps", type=int, default=2,
                    help="train steps to run on each side")
    ap.add_argument("--tolerance", type=float, default=5e-3,
                    help="max relative error accepted when not bitwise")
    ap.add_argument("--full-shapes", action="store_true",
                    help="skip the quick shrink (real geometry; slow)")
    args = ap.parse_args(argv)

    def emit(doc, rc):
        print(json.dumps({"metric": "tune_parity", **doc}), flush=True)
        return rc

    from howtotrainyourmamlpytorch_tpu.tune.space import (
        parse_compiler_options)
    try:
        options = parse_compiler_options(args.compiler_option)
    except ValueError as e:
        return emit({"pass": False, "mode": "fail", "error": str(e)}, 1)

    platform = os.environ.get("MAML_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    import jax
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.meta import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.parallel import (
        make_mesh, make_sharded_steps, replicated_sharding, shard_batch)
    # quick_shrink shared with bench.py (one home for the --quick
    # geometry): the parity gate probes numerics at the SAME shapes
    # the sweep's bench --quick trials measured at.
    from bench import quick_shrink, synthetic_batch

    n_dev = len(jax.devices())

    def build(path: str, xla_options: dict):
        cfg = MAMLConfig.from_json_file(path)
        per_chip = max(
            cfg.batch_size // max(int(np.prod(cfg.mesh_shape)), 1), 1)
        cfg = cfg.replace(batch_size=per_chip * n_dev,
                          mesh_shape=(1, n_dev))
        cfg = cfg.replace(
            task_microbatches=cfg.effective_task_microbatches(n_dev))
        if not args.full_shapes:
            cfg = quick_shrink(cfg, n_dev)
        cfg = cfg.replace(xla_compiler_options=tuple(
            f"{k}={v}" for k, v in sorted(xla_options.items())))
        init, apply = make_model(cfg)
        mesh = make_mesh(cfg, jax.devices())
        plan = make_sharded_steps(cfg, apply, mesh)
        epoch = max(cfg.total_epochs - 1, 0)
        key = (cfg.use_second_order(epoch), cfg.use_msl(epoch))
        state = jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)),
            replicated_sharding(mesh))
        batch = shard_batch(synthetic_batch(cfg, 0), mesh)
        return cfg, plan.train_steps[key], key, state, batch, epoch

    try:
        (cfg_t, step_t, key_t, state_t, batch_t,
         epoch_t) = build(args.config, options)
        (cfg_b, step_b, key_b, state_b, batch_b,
         epoch_b) = build(args.base_config, {})
    except Exception as e:  # noqa: BLE001 — a refused flag/config IS
        # the verdict, not a tool crash.
        return emit({"pass": False, "mode": "fail",
                     "error": f"{type(e).__name__}: {e}"}, 2)
    if key_t != key_b:
        return emit({"pass": False, "mode": "fail",
                     "error": f"phase keys differ: {key_t} vs {key_b}"},
                    2)

    def run(step, state, batch, epoch):
        import jax.numpy as jnp
        ep_arr = jnp.float32(epoch)
        loss = None
        for _ in range(max(args.steps, 1)):
            state, metrics = step(state, batch, ep_arr)
            loss = float(jax.device_get(metrics.loss))
        return jax.device_get(state), loss

    try:
        final_t, loss_t = run(step_t, state_t, batch_t, epoch_t)
        final_b, loss_b = run(step_b, state_b, batch_b, epoch_b)
    except Exception as e:  # noqa: BLE001 — compile/execute refusal of
        # the tuned program must land as a parity FAIL verdict.
        return emit({"pass": False, "mode": "fail",
                     "error": f"{type(e).__name__}: {e}"}, 2)

    leaves_t, tdef = jax.tree.flatten(final_t)
    leaves_b, bdef = jax.tree.flatten(final_b)
    if tdef != bdef or len(leaves_t) != len(leaves_b):
        return emit({"pass": False, "mode": "fail",
                     "error": "state trees structurally incomparable"},
                    2)
    bitwise = True
    max_rel = 0.0
    for a, b in zip(leaves_t, leaves_b):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            return emit({"pass": False, "mode": "fail",
                         "error": "leaf shape/dtype mismatch"}, 2)
        if a.tobytes() != b.tobytes():
            bitwise = False
            af = a.astype(np.float64, copy=False)
            bf = b.astype(np.float64, copy=False)
            # Magnitude floor 1e-6: near-zero leaves (fresh Adam
            # moments) would otherwise turn a denormal-sized absolute
            # difference into an unbounded "relative" error and fail
            # every legitimately-tolerance-class point.
            denom = np.maximum(np.maximum(np.abs(af), np.abs(bf)), 1e-6)
            rel = np.max(np.abs(af - bf) / denom)
            if not np.isfinite(rel):
                return emit({"pass": False, "mode": "fail",
                             "error": "non-finite divergence"}, 2)
            max_rel = max(max_rel, float(rel))
    mode = ("bitwise" if bitwise
            else "tolerance" if max_rel <= args.tolerance else "fail")
    ok = mode != "fail"
    return emit({"pass": ok, "mode": mode, "bitwise": bitwise,
                 "max_rel_err": round(max_rel, 9),
                 "tolerance": args.tolerance,
                 "steps": args.steps,
                 "loss_tuned": loss_t, "loss_untuned": loss_b,
                 "compared_leaves": len(leaves_t)},
                0 if ok else 2)


if __name__ == "__main__":
    sys.exit(main())

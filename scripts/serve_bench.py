"""Serving benchmark: open-loop synthetic load against ServingEngine.

Drives the serve/ subsystem the way a latency benchmark must be driven:
OPEN-LOOP — request arrival times are drawn up front from the target
rate and never wait on completions, so queueing delay under overload is
measured instead of hidden (closed-loop generators self-throttle and
report fantasy latencies). Latency is measured from the scheduled
ARRIVAL instant, so coordinated omission cannot flatter the tail.

The request mix cycles over the configured shape buckets with a
configurable task-repeat fraction (repeats exercise the adapted-params
cache exactly like real "adapt once, predict many" tenants).

Artifact contract (bench.py discipline): the LAST stdout JSON line is
authoritative and carries the serve_latency_p50_ms /
serve_latency_p95_ms / serve_cache_hit_frac keys that bench.py emits as
null — one consumer reads train and serve captures uniformly. With
--events PATH the run also writes an events.jsonl stream
scripts/telemetry_report.py renders (its "serving" section).

Usage:
    python scripts/serve_bench.py --quick                 # CI/CPU smoke
    python scripts/serve_bench.py --requests 200 --rate 20
    python scripts/serve_bench.py --config experiment_config/x.json \
        --checkpoint <dir>                                # real weights
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


def build_config(args):
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    if args.config:
        cfg = MAMLConfig.from_json_file(args.config)
    else:
        cfg = MAMLConfig(
            experiment_name="serve_bench",
            dataset_name="synthetic")
    if args.quick:
        cfg = cfg.replace(
            image_height=12, image_width=12, image_channels=1,
            cnn_num_filters=4, num_stages=2,
            num_classes_per_set=3, num_samples_per_class=1,
            num_target_samples=2,
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
            serve_batch_tasks=2,
            serve_buckets=((3, 4), (6, 4)))
    # The bench is single-process; serve on the local device count's
    # natural mesh only when the config asks for more than we have.
    import jax
    n_dev = len(jax.devices())
    if int(np.prod(cfg.mesh_shape)) > n_dev:
        cfg = cfg.replace(mesh_shape=(1, 1))
    # Deadlines off by default here: the artifact measures the latency
    # DISTRIBUTION; a deadline sweep is a separate experiment (pass
    # --deadline-ms to run one). --queue-depth is honored as given —
    # under overload, rejected submits are load-shedding and the
    # artifact counts them.
    return cfg.replace(serve_default_deadline_ms=args.deadline_ms,
                       serve_max_queue_depth=args.queue_depth)


# The synthetic request generators moved to serve/loadlab/workloads.py
# (the traffic lab's ONE definition — stdlib+numpy, file-path loadable
# by the jax-free fleet drivers). Re-exported here so existing callers
# (`from serve_bench import synthetic_arrays, tenant_pool`) keep
# working and every bench draws identical traffic by construction.
def _load_workloads():
    import importlib.util
    path = os.path.join(_REPO, "howtotrainyourmamlpytorch_tpu", "serve",
                        "loadlab", "workloads.py")
    spec = importlib.util.spec_from_file_location(
        "_serve_bench_workloads_impl", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_workloads_mod = _load_workloads()
synthetic_arrays = _workloads_mod.synthetic_arrays
tenant_pool = _workloads_mod.tenant_pool


def synthetic_request(cfg, bucket, rng, fill, arrival):
    """One synthetic request at ``fill <= bucket`` occupancy with wire
    dtype matching the config (uint8 by default, like real traffic)."""
    from howtotrainyourmamlpytorch_tpu.serve import FewShotRequest
    sx, sy, qx = synthetic_arrays(cfg.image_shape,
                                  cfg.num_classes_per_set,
                                  cfg.transfer_images_uint8, rng, fill)
    req = FewShotRequest(support_x=sx, support_y=sy, query_x=qx)
    req.arrival_time = arrival  # open-loop: scheduled arrival, not ctor
    return req


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Open-loop synthetic load benchmark for serve/.")
    ap.add_argument("--requests", type=int, default=100,
                    help="total synthetic requests")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="offered load, requests/s (0 = as fast as the "
                         "engine drains: a throughput measurement)")
    ap.add_argument("--repeat-frac", type=float, default=0.3,
                    help="fraction of requests that repeat an earlier "
                         "support set (exercises the adapted-params "
                         "cache)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (0 = none)")
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--config", default=None, metavar="JSON",
                    help="experiment_config/*.json to serve")
    ap.add_argument("--checkpoint", default=None, metavar="DIR",
                    help="saved_models dir to load weights from "
                         "(default: a fresh meta-init — throughput/"
                         "latency are weight-independent)")
    ap.add_argument("--events", default=None, metavar="PATH",
                    help="also write an events.jsonl telemetry stream "
                         "(input for scripts/telemetry_report.py)")
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for CI/CPU sanity")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax
    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.serve import ServingEngine
    from howtotrainyourmamlpytorch_tpu.utils.tracing import JsonlLogger

    cfg = build_config(args)
    if args.checkpoint:
        engine = ServingEngine.from_checkpoint(cfg, args.checkpoint)
    else:
        model_init, _ = make_model(cfg)
        state = init_train_state(cfg, model_init,
                                 jax.random.PRNGKey(cfg.seed))
        engine = ServingEngine(cfg, state)

    t0 = time.perf_counter()
    engine.warmup()
    warmup_seconds = time.perf_counter() - t0
    compiles_after_warmup = int(
        engine.registry.counter("compile/count").value)

    # Pre-draw the whole arrival schedule + request mix (open loop).
    rng = np.random.RandomState(args.seed)
    buckets = engine.batcher.buckets
    start = time.monotonic() + 0.01
    if args.rate > 0:
        gaps = rng.exponential(1.0 / args.rate, args.requests)
        arrivals = start + np.cumsum(gaps)
    else:
        arrivals = np.full(args.requests, start)
    requests = []
    for i in range(args.requests):
        bucket = buckets[i % len(buckets)]
        fill = (max(1, bucket[0] - (i % 2)), max(1, bucket[1] - (i % 3)))
        if requests and rng.rand() < args.repeat_frac:
            # Repeat an earlier support set with FRESH queries: the
            # cache-hit path (adapt skipped, predict only).
            prev = requests[rng.randint(len(requests))]
            req = synthetic_request(cfg, bucket,
                                    rng, (prev.num_support,
                                          prev.num_query),
                                    arrivals[i])
            req.support_x = prev.support_x
            req.support_y = prev.support_y
        else:
            req = synthetic_request(cfg, bucket, rng, fill, arrivals[i])
        requests.append(req)

    # Drive: submit every request whose arrival instant has passed, pump
    # the engine between arrivals, drain at the end.
    responses = []
    next_idx = 0
    rejected = 0
    while next_idx < len(requests) or engine.batcher.depth:
        now = time.monotonic()
        while next_idx < len(requests) and requests[next_idx].arrival_time <= now:
            try:
                engine.submit(requests[next_idx])
            except Exception:
                if args.rate > 0:
                    # Rated open-loop traffic: a full queue sheds the
                    # request (that IS the backpressure behavior under
                    # overload; the artifact counts it).
                    rejected += 1
                else:
                    # rate=0 is a backlog/throughput measurement: the
                    # queue-depth cap throttles submission, it must not
                    # discard work — retry after the next batch drains.
                    break
            next_idx += 1
        responses.extend(engine.step())
        if next_idx < len(requests) and not engine.batcher.depth:
            time.sleep(min(0.005,
                           max(requests[next_idx].arrival_time
                               - time.monotonic(), 0.0)))
    wall = time.monotonic() - start

    ok = [r for r in responses if r.error is None]
    lat_ms = sorted(r.latency_seconds * 1e3 for r in ok)

    def pct(q, vals=lat_ms):
        # The repo's one pinned quantile definition (PR-1's p95 fix).
        from howtotrainyourmamlpytorch_tpu.utils.tracing import (
            nearest_rank)
        return round(nearest_rank(vals, q), 3) if vals else None

    # Per-cache-tier latency split (mirrors fleet_bench's leg stats):
    # tier "miss" = adapted from scratch, the expensive path.
    tier_lat = {"l1": [], "l2": [], "miss": []}
    for r in ok:
        tier_lat[r.cache_tier or "miss"].append(r.latency_seconds * 1e3)
    tier_latency_ms = {
        tier: ({"count": len(vals), "p50_ms": pct(0.50, sorted(vals)),
                "p95_ms": pct(0.95, sorted(vals)),
                "p99_ms": pct(0.99, sorted(vals))} if vals else None)
        for tier, vals in tier_lat.items()}

    hits = engine.cache.hits
    misses = engine.cache.misses
    out = {
        "metric": "serve_requests_per_sec",
        "value": round(len(ok) / wall, 3) if wall > 0 else None,
        "unit": "requests/s",
        "requests": args.requests,
        "responses": len(ok),
        "deadline_misses": len(responses) - len(ok),
        "rejected": rejected,
        "serve_latency_p50_ms": pct(0.5),
        "serve_latency_p95_ms": pct(0.95),
        "serve_latency_p99_ms": pct(0.99),
        "tier_latency_ms": tier_latency_ms,
        "serve_cache_hit_frac": (round(hits / (hits + misses), 4)
                                 if hits + misses else None),
        "adapt_batches": engine.adapt_invocations,
        # Algorithm identity + adapted-footprint keys (meta/algos/):
        # the ANIL serve proof reads THESE — under the head-only mask
        # the adapted-param count, the mean cache entry and the adapt
        # p50 all shrink vs maml++ on the same checkpoint geometry
        # (docs/PERF.md § Meta-algorithm zoo; tests/test_algos.py pins
        # the structural halves).
        "meta_algorithm": cfg.meta_algorithm,
        "adapted_params": int(
            engine.registry.gauge("algo/adapted_params").value or 0),
        "total_params": int(
            engine.registry.gauge("algo/total_params").value or 0),
        "cache_entries": len(engine.cache),
        "cache_entry_bytes_mean": (
            round(engine.cache.approx_bytes / len(engine.cache), 1)
            if len(engine.cache) else None),
        "adapt_seconds_p50": engine.registry.histogram(
            "serve/adapt_seconds").quantile(0.5),
        "warmup_seconds": round(warmup_seconds, 3),
        "compile_count_warmup": compiles_after_warmup,
        # The steady-state no-recompile guarantee, in the artifact: any
        # nonzero delta means a request shape escaped the buckets.
        "compile_count_steady_delta": int(
            engine.registry.counter("compile/count").value)
            - compiles_after_warmup,
        "offered_rate": args.rate or None,
        "workload": cfg.experiment_name,
        # Fleet keys (scripts/fleet_bench.py fills them): null here so
        # single-engine and fleet captures stay schema-stable — one
        # consumer reads both artifacts uniformly, the bench.py rule.
        "fleet_replicas": None,
        "fleet_qps": None,
        "fleet_speedup_vs_single": None,
        "fleet_l2_hit_frac": None,
        "fleet_rolling_swaps": None,
        "fleet_rolling_swap_halts": None,
        "fleet_router_spills": None,
        "fleet_trace_count": None,
        "fleet_trace_linked_frac": None,
        "fleet_trace_dominant_tier": None,
        "fleet_trace_tier_seconds": None,
        "fleet_slo_burn_rate": None,
        "fleet_slo_tenants": None,
        "fleet_shed_count": None,
        "fleet_failover_count": None,
        "fleet_restarts": None,
        # Traffic-lab keys (scripts/traffic_replay.py fills them):
        # null here, same schema-stability rule as the fleet keys.
        "traffic_p95_ms": None,
        "traffic_slo_held": None,
        "traffic_canary_weight_final": None,
        "traffic_cb_groups": None,
        # Alert keys (scripts/chaos_fleet.py fills them): this bench
        # runs no alert rules — honestly null, same schema rule.
        "alerts_fired": None,
        "alerts_resolved": None,
        "alerts_active_final": None,
    }
    if args.events:
        jsonl = JsonlLogger(args.events)
        engine.flush_metrics(jsonl, phase="serve_bench")
    engine.close()
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

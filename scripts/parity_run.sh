#!/usr/bin/env bash
# One-command accuracy-parity run (VERDICT r2 #7; BASELINE.md north star).
#
# Points the shipped paper configuration at a real dataset directory and
# executes the EXACT paper protocol: strict batch-8 operating point, full
# 100-epoch schedule (MSL->steady at 15, DA first->second order at 40,
# cosine meta-LR), 600 fixed-seed test episodes, top-5-by-val-accuracy
# checkpoint ensemble — then prints the comparison against BASELINE.md's
# accuracy table.
#
# Usage:
#   scripts/parity_run.sh /path/to/datasets [experiment_root] [extra CLI...]
#
# where /path/to/datasets holds mini_imagenet_full_size/{train,val,test}/
# (or mini_imagenet_full_size.zip — provisioning extracts it). Everything
# after the second argument is passed through as CLI overrides, so e.g. a
# resumed run is:  scripts/parity_run.sh /data out --continue_from_epoch latest
#
# Smoke-tested end-to-end on a synthetic source by
# tests/test_experiment.py § test_parity_runner_smoke (the CI stand-in for
# the real-data run this environment cannot execute).
set -euo pipefail

DATASET_ROOT="${1:?usage: parity_run.sh /path/to/datasets [experiment_root] [extra overrides...]}"
EXPERIMENT_ROOT="${2:-parity_runs}"
shift $(( $# > 1 ? 2 : 1 ))

REPO="$(cd "$(dirname "$0")/.." && pwd)"
CONFIG="$REPO/experiment_config/mini-imagenet_maml++_5-way_5-shot_DA.json"

# The shipped DA config IS the paper point (batch 8, 48 filters, K=5,
# DA at 40, 600 evaluation tasks, top-5 retention); only dataset_path and
# bookkeeping are overridden here. compilation cache makes preempt/resume
# cycles cheap on TPU.
PYTHONPATH="$REPO:${PYTHONPATH:-}" python "$REPO/train_maml_system.py" \
  --name_of_args_json_file "$CONFIG" \
  --dataset_path "$DATASET_ROOT/mini_imagenet_full_size" \
  --experiment_root "$EXPERIMENT_ROOT" \
  --experiment_name parity_mini_imagenet_5w5s \
  --precompile_phases true \
  --compilation_cache_dir "$EXPERIMENT_ROOT/jax_cache" \
  --continue_from_epoch latest \
  "$@"

PYTHONPATH="$REPO:${PYTHONPATH:-}" python "$REPO/scripts/parity_report.py" \
  "$EXPERIMENT_ROOT/parity_mini_imagenet_5w5s/logs/test_summary.csv"

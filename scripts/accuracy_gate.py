"""One-command REAL-DATA accuracy gate (VERDICT r4 next #2).

Drives a shipped config through the FULL training schedule and the
reference evaluation protocol (600 fixed-seed test episodes, ensemble of
the top-5 checkpoints by validation accuracy — SURVEY.md §3.3,
`experiment_builder.py` per SURVEY §2.1), then emits ONE JSON verdict
line comparing the ensemble test accuracy against the MAML++ paper table
recorded in BASELINE.md. Exit code: 0 pass, 2 accuracy below gate,
1 error (no real dataset, training incomplete, ...).

This gate REFUSES to run without real data: a missing dataset directory
hard-fails onto ``maybe_unzip_dataset``'s provisioning instructions, and
a ``synthetic`` dataset name is rejected outright — the driven synthetic
runs in docs/E2E.md are protocol evidence, never paper numbers, and this
tool exists to make that distinction mechanical.

Usage (the flagship paper point):

    bash scripts/accuracy_gate.sh \
        --config experiment_config/mini-imagenet_maml++_5-way_5-shot_DA.json

Any trailing ``--key value`` pairs are config overrides with the trainer
CLI's exact coercion rules (train_maml_system.get_args), e.g. a custom
``--dataset_path``. ``--min-accuracy`` overrides the BASELINE.md
threshold (required for configs with no paper row, e.g. the
tiered-imagenet pod config). The environment knobs the trainer honors
(MAML_JAX_PLATFORM, MAML_BACKEND_TIMEOUT) work here too.

The wiring (config -> dataset check -> full schedule -> ensemble test ->
JSON verdict) is itself exercised end-to-end against a small REAL PNG
image tree in tests/test_accuracy_gate.py, so the day Mini-ImageNet
bytes exist the only new variable is the data.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# MAML++ paper test-accuracy table (BASELINE.md; arXiv:1810.09502), keyed
# by (dataset family, way, shot) as (mean, published 95% CI half-width).
# The PASS gate is >= mean - CI (ADVICE r5): the paper numbers carry a
# ±CI, so an implementation at exact statistical parity lands above and
# below the point estimate with roughly equal probability — gating on
# the bare mean fails ~half of at-parity runs. The strict >=mean verdict
# is still REPORTED (``strict_pass``), just not the exit-code gate.
# Omniglot rows: BASELINE.md records no CI, so their margin is 0 (the
# strict gate) rather than an invented one.
PAPER_GATES = {
    ("omniglot", 5, 1): (0.9947, 0.0),
    ("omniglot", 5, 5): (0.9993, 0.0),
    ("omniglot", 20, 1): (0.9765, 0.0),
    ("omniglot", 20, 5): (0.9933, 0.0),
    ("imagenet", 5, 1): (0.5215, 0.0026),
    ("imagenet", 5, 5): (0.6832, 0.0044),
}

# First-order variant rows (BASELINE.md § FOMAML; the MAML paper, Finn
# et al. ICML 2017, arXiv:1703.03400 Table 1): selected when the config
# trains meta_algorithm="fomaml" — gating a deliberately weaker,
# cheaper algorithm against the MAML++ table would fail every at-parity
# run. Mini-ImageNet rows are the paper's explicit "first order
# approx." entries; Omniglot rows reuse the paper's full-MAML numbers
# (with their CIs) as proxies, since the paper reports the first-order
# approximation performs "nearly the same" and publishes no separate
# Omniglot first-order row. The other zoo algorithms (anil, reptile)
# have no BASELINE.md row and demand an explicit --min-accuracy.
FIRST_ORDER_GATES = {
    ("omniglot", 5, 1): (0.987, 0.004),
    ("omniglot", 5, 5): (0.999, 0.001),
    ("omniglot", 20, 1): (0.958, 0.003),
    ("omniglot", 20, 5): (0.989, 0.002),
    ("imagenet", 5, 1): (0.4807, 0.0175),
    ("imagenet", 5, 5): (0.6315, 0.0091),
}


def paper_gate(cfg) -> "tuple[float, float] | None":
    """(paper mean, published CI half-width) for the config's row, or
    None when the paper has no row."""
    # "imagenet" here means MINI-ImageNet only: tiered-ImageNet (the pod
    # config) has no row in the MAML++ paper table and must demand an
    # explicit --min-accuracy instead of borrowing mini's gate.
    name = cfg.dataset_name
    family = ("omniglot" if "omniglot" in name
              else "imagenet" if "mini" in name and "imagenet" in name
              else None)
    if family is None:
        return None
    table = (FIRST_ORDER_GATES if cfg.meta_algorithm == "fomaml"
             else PAPER_GATES if cfg.meta_algorithm == "maml++"
             else {})
    return table.get(
        (family, cfg.num_classes_per_set, cfg.num_samples_per_class))


def fail(reason: str, **extra) -> int:
    print(json.dumps({"gate": "accuracy", "pass": False,
                      "error": reason, **extra}), flush=True)
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="real-data accuracy gate vs the BASELINE.md table")
    ap.add_argument("--config", required=True,
                    help="experiment_config/*.json to gate")
    ap.add_argument("--min-accuracy", type=float, default=None,
                    help="override the BASELINE.md threshold (REQUIRED "
                         "for configs with no paper row)")
    # argparse exits with status 2 on usage errors — which would collide
    # with this tool's documented exit-2 = "ran but below the accuracy
    # gate". Remap every parse failure to the error contract (exit 1,
    # JSON verdict line) so a CLI typo can never masquerade as a failed
    # accuracy run.
    try:
        args, overrides = ap.parse_known_args(argv)
    except SystemExit:
        return fail("invalid command line (usage printed on stderr)")

    # Trainer-CLI config loading + coercion, verbatim (one parser to rule
    # every entry point; overrides behave exactly like the CLI's).
    from train_maml_system import get_args
    try:
        cfg = get_args(["--name_of_args_json_file", args.config]
                       + overrides)
    except (SystemExit, OSError, ValueError) as e:
        return fail(f"invalid config/override "
                    f"({e if not isinstance(e, SystemExit) else 'usage printed on stderr'})",
                    config=args.config)

    if "synthetic" in cfg.dataset_name:
        return fail(
            f"dataset_name {cfg.dataset_name!r} is synthetic — the "
            f"accuracy gate only means something on real data "
            f"(docs/E2E.md synthetic runs are protocol evidence, not "
            f"paper numbers)", config=args.config)

    paper_mean = paper_ci = None
    if args.min_accuracy is not None:
        # Explicit override: an absolute threshold, no CI margin.
        threshold, margin = args.min_accuracy, 0.0
    else:
        row = paper_gate(cfg)
        if row is None:
            return fail(
                f"no BASELINE.md paper row for {cfg.dataset_name!r} "
                f"{cfg.num_classes_per_set}-way "
                f"{cfg.num_samples_per_class}-shot; pass --min-accuracy",
                config=args.config)
        paper_mean, paper_ci = row
        # Gate at mean - CI (ADVICE r5): deterministic for an at-parity
        # run; the margin is recorded in the verdict below.
        threshold, margin = paper_mean - paper_ci, paper_ci

    platform = os.environ.get("MAML_JAX_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    backend_timeout = float(os.environ.get("MAML_BACKEND_TIMEOUT", "0"))
    if backend_timeout > 0:
        from howtotrainyourmamlpytorch_tpu.utils.backend import (
            wait_for_backend)
        wait_for_backend(timeout_s=backend_timeout)

    # Hard real-data requirement: directory -> zip -> (no fetcher) raise
    # with the provisioning instructions.
    from howtotrainyourmamlpytorch_tpu.utils.dataset_tools import (
        maybe_unzip_dataset)
    try:
        maybe_unzip_dataset(cfg, require=True)
    except (FileNotFoundError, RuntimeError, ValueError) as e:
        return fail(f"no real dataset at {cfg.dataset_dir!r}: {e}",
                    config=args.config)

    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    result = ExperimentBuilder(cfg).run_experiment()
    if "test_accuracy_mean" not in result:
        return fail(
            f"training did not reach the test protocol (result: "
            f"{result}); resume with --continue_from_epoch latest",
            config=args.config)

    acc = result["test_accuracy_mean"]
    verdict = {
        "gate": "accuracy",
        "config": args.config,
        "workload": cfg.experiment_name,
        "dataset": cfg.dataset_name,
        "dataset_path": cfg.dataset_dir,
        "way": cfg.num_classes_per_set,
        "shot": cfg.num_samples_per_class,
        "test_accuracy_mean": round(acc, 4),
        "test_accuracy_std": round(result["test_accuracy_std"], 4),
        "num_models": result["num_models"],
        "num_episodes": result["num_episodes"],
        "threshold": round(threshold, 6),
        "meta_algorithm": cfg.meta_algorithm,
        "threshold_source": (
            "--min-accuracy" if args.min_accuracy is not None
            else "BASELINE.md FOMAML (MAML paper) table, mean - CI"
            if cfg.meta_algorithm == "fomaml"
            else "BASELINE.md MAML++ paper table, mean - CI"),
        # The margin the gate granted (the paper's published CI
        # half-width; 0 for --min-accuracy and CI-less rows), plus the
        # strict >=mean verdict as a REPORTED field — the exit code
        # gates on mean - CI, the report still shows both.
        "paper_mean": paper_mean,
        "paper_ci": paper_ci,
        "margin": margin,
        "strict_pass": (bool(acc >= paper_mean)
                        if paper_mean is not None else None),
        "pass": bool(acc >= threshold),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["pass"] else 2


if __name__ == "__main__":
    sys.exit(main())

"""Validation-sweep timing: decoupled (large) eval meta-batch vs the old
train-batch-sized sweeps. VERDICT r1 next-round #5.

Times a full 600-episode evaluation sweep (the per-epoch validation and
the per-model test protocol cost) on the flagship workload at several
eval batch sizes, including the auto default (8x train batch).

Usage: python scripts/perf_eval.py [--episodes N]
Prints one JSON line per batch size.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import synthetic_batch
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, replicated_sharding, shard_batch)


def sweep_time(cfg: MAMLConfig, eval_batch: int, episodes: int,
               repeats: int = 3) -> float:
    cfg = cfg.replace(eval_batch_size=eval_batch)
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices()[:1])
    plan = make_sharded_steps(cfg, apply, mesh)
    state = jax.device_put(
        init_train_state(cfg, init, jax.random.PRNGKey(0)),
        replicated_sharding(mesh))
    num_batches = -(-episodes // eval_batch)
    # Device-resident fixed episodes (cache_eval_episodes default), so the
    # measured cost is the eval computation itself — as in training.
    batches = [shard_batch(synthetic_batch(
        cfg.replace(batch_size=eval_batch), s), mesh)
        for s in range(num_batches)]
    # Warmup/compile.
    res = plan.eval_step(state, batches[0])
    float(jax.device_get(res.loss).mean())
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = []
        for b in batches:
            out.append(plan.eval_step(state, b))
        tot = float(np.concatenate(
            [np.asarray(jax.device_get(r.accuracy)) for r in out]).mean())
        times.append(time.perf_counter() - t0)
        assert np.isfinite(tot)
    return float(np.median(times))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=600)
    args = ap.parse_args()

    import bench
    bench.init_backend()  # outage retry + watchdog + compile cache

    cfg = MAMLConfig.from_json_file(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "experiment_config", "mini-imagenet_maml++_5-way_5-shot_DA_b12.json"))
    base = None
    for eb in (12, 24, 48, 96, 120, 200):
        t = sweep_time(cfg, eb, args.episodes)
        if base is None:
            base = t
        print(json.dumps({
            "eval_batch": eb,
            "sweep_seconds": round(t, 3),
            "speedup_vs_train_batch": round(base / t, 3)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Rebuild a Chrome-trace timeline offline from a run's logs.

Usage:
    python scripts/trace_export.py <experiment_dir | logs_dir | events.jsonl>
        [--flight FLIGHT_JSONL] [--out TRACE_JSON] [--process-index N]

Synthesizes ``telemetry/trace.py``'s Chrome ``trace_event`` JSON —
loadable in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``
— from whichever timeline sources the run left behind:

* ``events.jsonl`` (always written): whole-run epoch spans, per-host
  heartbeat markers, checkpoint/rewind/preemption/trip/warn markers;
* ``flight.jsonl`` (the experiment loop's per-epoch ring dump, or the
  copy inside a crash bundle): fine-grained step/feed/collective/
  compile/serve phase spans for the most recent ring window.

When given a directory, the flight ring is auto-discovered next to the
events log (``flight.jsonl``), falling back to the newest crash
bundle's copy — so ``python scripts/trace_export.py <experiment>``
after a watchdog trip renders the hang's final seconds with zero extra
flags. Either source alone suffices; having neither is an error.

The LAST stdout line is the JSON artifact (the repo's CLI contract):
``{"metric": "trace_export", "spans": N, "instants": I, "hosts": H,
"events_rows": E, "flight_rows": F, "out": PATH}``. Exit 0 on success,
1 on any failure. Schema pinned by tests/test_trace.py through this
real entrypoint.

No JAX import — timelines render on a login node without accelerators:
``telemetry/trace.py`` and ``utils/tracing.py`` are stdlib-only but are
loaded by file path so the package ``__init__`` chains (which do import
jax) never execute.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_trace = _load_module(
    "_trace_export_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "telemetry", "trace.py"))
_tracing = _load_module(
    "_trace_export_tracing_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "utils", "tracing.py"))
# Rotation-aware: the spare segment (events.jsonl.1) reads first.
read_jsonl = _tracing.read_jsonl_rotated


def resolve_paths(path: str):
    """(events_path_or_None, flight_path_or_None, out_dir) for a CLI
    argument that may be an events.jsonl, a logs dir, or an experiment
    dir. Flight auto-discovery: next to the events log, else the newest
    crash bundle's copy (``crash_bundle*/flight.jsonl``)."""
    if os.path.isdir(path):
        logs = path
        for candidate in (path, os.path.join(path, "logs")):
            if os.path.exists(os.path.join(candidate, "events.jsonl")) \
                    or glob.glob(os.path.join(candidate, "crash_bundle*")):
                logs = candidate
                break
        events = os.path.join(logs, "events.jsonl")
        events = events if os.path.exists(events) else None
    else:
        events = path if os.path.exists(path) else None
        logs = os.path.dirname(path) or "."
    flight = os.path.join(logs, "flight.jsonl")
    if not os.path.exists(flight):
        bundles = sorted(
            glob.glob(os.path.join(logs, "crash_bundle*", "flight.jsonl")),
            key=os.path.getmtime)
        flight = bundles[-1] if bundles else None
    return events, flight, logs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Rebuild a Chrome-trace timeline from a run's "
                    "events.jsonl + flight.jsonl.")
    ap.add_argument("path", help="events.jsonl, a logs/ dir, or an "
                                 "experiment dir containing logs/")
    ap.add_argument("--flight", default=None, metavar="JSONL",
                    help="explicit flight.jsonl (default: auto-discover "
                         "next to the events log, then the newest crash "
                         "bundle's copy)")
    ap.add_argument("--out", default=None, metavar="JSON",
                    help="output trace path (default: trace.json next to "
                         "the inputs)")
    ap.add_argument("--process-index", type=int, default=0,
                    help="pid to assign the flight ring's phase spans "
                         "(a per-host crash bundle from host N renders "
                         "on track N)")
    args = ap.parse_args(argv)

    try:
        events_path, flight_path, out_dir = resolve_paths(args.path)
        if args.flight is not None:
            flight_path = args.flight
        events = read_jsonl(events_path) if events_path else None
        flight = read_jsonl(flight_path) if flight_path else None
        if not events and not flight:
            raise FileNotFoundError(
                f"no timeline source under {args.path!r}: need an "
                f"events.jsonl and/or a flight.jsonl")
        out = args.out or os.path.join(out_dir, "trace.json")
        stats = _trace.write_trace(out, events=events, flight=flight,
                                   process_index=args.process_index)
    except (OSError, ValueError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1

    # The LAST stdout line is the machine-readable artifact (the
    # bench.py / dataset_pack.py contract).
    print(json.dumps({
        "metric": "trace_export",
        "spans": stats["spans"],
        "instants": stats["instants"],
        "hosts": stats["hosts"],
        "events_rows": len(events) if events else 0,
        "flight_rows": len(flight) if flight else 0,
        "out": out,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

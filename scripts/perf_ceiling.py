"""Quantitative speed-of-light bound for the flagship train step.

.. deprecated::
    The flops half of this model is consolidated into the perf lab:
    PROFILE.json cost cards (telemetry/profiler.py) carry the ONE
    trip-expanded flops algorithm (utils/hlo_flops.py — this script's
    global compute term now reads
    ``hlo_flops.combine_flops_estimates``, the same combiner behind
    bench.py's flops/mfu keys and every cost card), and
    ``scripts/perf_report.py`` renders measured device time against the
    same cards. Pass ``--profile-json PATH --card NAME`` to take the
    compute term from a recorded cost card instead of re-deriving it
    here. The serial/bandwidth chain model (kernel floor, tile-padded
    traffic) remains unique to this script.

VERDICT r2 weak #1 asked for a *number* behind the "latency-bound chain
of small ops" ceiling story: sum the serial chain into a "max achievable
~= X tasks/s, we are at Y% of it" figure. This script builds that model
from the compiled executable itself:

1. AOT-compile the steady-state flagship train step (exactly as bench.py
   does) and fetch its OPTIMIZED per-device HLO, with layouts.
2. Walk every instruction the device will execute (entry computation;
   while-loop bodies multiplied by their trip counts; fusion internals
   charged only for their boundary traffic, since fused intermediates
   stay in VMEM/registers).
3. Cost each instruction as

       t_op = max(kernel_floor, physical_bytes / HBM_BW, flops / MXU_peak)

   where physical_bytes accounts for the (8,128) tile padding the layout
   string declares (the flagship's NHWC buffers pad 48->128 lanes and
   25->32 sublanes: ~3.4x the logical bytes — charging logical bytes
   would overstate the headroom by that factor), flops are parsed from
   convolution/dot shapes (including inside fusions), and the three
   hardware constants are MEASURED on this chip (dependent-kernel chain,
   big-buffer streaming, big-matmul chain) rather than taken from spec
   sheets.
4. A TPU core executes one kernel at a time, so the sum over executed
   instructions is a lower bound on step wall-clock => an upper bound on
   tasks/s for THIS program on THIS chip. Report bound, measured, and
   Z = measured/bound.

The bound is per-executable, so the model also says WHERE the floor is:
the per-category table shows how much of it is conv compute vs padded
elementwise traffic vs kernel-count floor.

Usage: python scripts/perf_ceiling.py [--batch 12] [--steps 12]
                                      [--config experiment_config/x.json]
Prints JSON lines; the last line is the summary.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import bench
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, shard_batch)
# The HLO parsing machinery (shape/layout byte accounting, instruction
# parser, conv/dot FLOP pricing, trip-count extraction) moved into the
# package in r5 so bench.py's flops_per_task/mfu keys could share the
# scan-trip expansion (VERDICT r4 weak #1). Re-exported here under the
# historical names — tests/test_perf_ceiling.py pins them.
from howtotrainyourmamlpytorch_tpu.utils.hlo_flops import (  # noqa: F401
    _FREE_OPS, _NAME_RE, _SHAPE_RE, HloFlopsCounter, _conv_flops,
    _dot_flops, _parse_instr, _shape_bytes, _split_computations)


class HloCostModel(HloFlopsCounter):
    """Serial/bandwidth/flop cost model on top of the shared HLO walk.

    The parse machinery (computation split, symbol table, operand-shape
    resolution, conv/dot flop pricing inside fusions, while-loop
    trip-count extraction incl. the PERF_CEILING_TRIPS override) is
    INHERITED from ``utils.hlo_flops.HloFlopsCounter`` — the same code
    path behind bench.py's ``flops_per_task``/``mfu`` keys, so a fix to
    e.g. the trip-count heuristic changes both tools consistently. This
    subclass adds only what the ceiling model needs: physical
    (tile-padded) byte accounting and the per-kernel time model.
    """

    def __init__(self, hlo: str, floor_s: float, hbm_bps: float,
                 mxu_fps: float):
        super().__init__(hlo)
        self.floor = floor_s
        self.bw = hbm_bps
        self.peak = mxu_fps
        self.by_cat: dict[str, dict] = {}
        self.kernels = 0
        self.total_bytes = 0.0   # every op incl. async DMA (BW is shared)
        self.total_flops = 0.0
        self.async_bytes = 0.0

    def _operand_bytes(self, comp: str, ops_t: str) -> int:
        """Bytes read: resolve operand names through the computation's
        symbol table; inline shapes (older dump styles) also count."""
        total, _ = _shape_bytes(ops_t, physical=True)
        if total:
            return total
        tab = self.symtab.get(comp, {})
        for name in _NAME_RE.findall(ops_t):
            shape = tab.get(name)
            if shape:
                b, _ = _shape_bytes(shape, physical=True)
                total += b
        return total

    # Historical names used by comp_cost below and pinned by the unit
    # tests; both delegate to the shared machinery.
    def _comp_flops(self, name: str, seen=None) -> float:
        return self._fusion_flops(name, seen)

    def _trip_count(self, cond_name: str) -> int:
        return self.trip_count(cond_name)

    # -- per-computation serial cost -----------------------------------
    def comp_cost(self, name: str, mult: float = 1.0) -> float:
        total = 0.0
        for line in self.comps.get(name, []):
            p = _parse_instr(line)
            if not p:
                continue
            opcode, out_t, ops_t, attrs = p
            if opcode in _FREE_OPS:
                continue
            if opcode == "while":
                m_b = re.search(r"body=%?([\w.\-]+)", attrs)
                m_c = re.search(r"condition=%?([\w.\-]+)", attrs)
                if m_b and m_c:
                    trips = self._trip_count(m_c.group(1))
                    total += self.comp_cost(m_b.group(1), mult * trips)
                    total += self.comp_cost(m_c.group(1), mult * trips)
                continue
            if opcode in ("call", "conditional"):
                # conditionals name their branches via true_computation=/
                # false_computation=/branch_computations={...}; calls use
                # to_apply=. Cost every referenced branch (upper bound:
                # one branch executes, but which one is data-dependent).
                for c in re.findall(
                        r"(?:to_apply|calls|true_computation|"
                        r"false_computation)=%?([\w.\-]+)", attrs):
                    total += self.comp_cost(c, mult)
                m = re.search(r"branch_computations=\{([^}]*)\}", attrs)
                if m:
                    for c in _NAME_RE.findall(m.group(1)):
                        total += self.comp_cost(c, mult)
                continue
            # Async pairs (copy-start/-done, async-start/-done): the DMA
            # overlaps the main kernel stream, so a speed-of-light bound
            # charges no serial time — but the bytes still ride the
            # shared HBM bus and enter the global bandwidth bound below.
            if opcode.endswith("-done"):
                continue
            if opcode.endswith("-start"):
                a_b = self._operand_bytes(name, ops_t)
                self.async_bytes += a_b * mult
                self.total_bytes += a_b * mult
                continue
            out_b, _ = _shape_bytes(out_t, physical=True)
            in_b = self._operand_bytes(name, ops_t)
            flops = 0.0
            resolved = " ".join(self._operand_shapes(name, ops_t))
            if opcode == "convolution":
                flops = _conv_flops(out_t, resolved, attrs)
            elif opcode == "dot":
                flops = _dot_flops(out_t, resolved, attrs)
            elif opcode == "fusion":
                m = re.search(r"calls=%?([\w.\-]+)", attrs)
                if m:
                    flops = self._comp_flops(m.group(1))
            self.total_bytes += (out_b + in_b) * mult
            self.total_flops += flops * mult
            t = max(self.floor, (out_b + in_b) / self.bw, flops / self.peak)
            cat = opcode
            d = self.by_cat.setdefault(
                cat, {"n": 0, "time_s": 0.0, "bytes": 0, "flops": 0.0})
            d["n"] += mult
            d["time_s"] += t * mult
            d["bytes"] += (out_b + in_b) * mult
            d["flops"] += flops * mult
            self.kernels += mult
            total += t * mult
        return total

    def step_bound_s(self) -> float:
        """max(serial kernel chain, global HBM bytes, global FLOPs) —
        each term is an independent lower bound on step wall-clock."""
        # Re-entrant: reset the accumulators so a second call (e.g.
        # after tweaking the hardware constants) doesn't double-count.
        self.by_cat = {}
        self.kernels = 0
        self.total_bytes = self.total_flops = self.async_bytes = 0.0
        serial = self.comp_cost(self.entry)
        self.serial_s = serial
        self.bw_bound_s = self.total_bytes / self.bw
        self.flop_bound_s = self.total_flops / self.peak
        return max(serial, self.bw_bound_s, self.flop_bound_s)


# -- on-chip calibration ---------------------------------------------------

def _time_chain(fn, *args, reps: int = 3) -> float:
    out = fn(*args)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    _ = float(jax.device_get(jax.tree.leaves(out)[0]).ravel()[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(jax.device_get(jax.tree.leaves(out)[0]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best


def _slope(make_fn, args_fn, n_lo: int, n_hi: int) -> float:
    """Per-unit device time via two chain lengths: (t_hi - t_lo) /
    (n_hi - n_lo). The axon tunnel adds ~100ms of per-call dispatch +
    fetch latency that swamps any single absolute measurement (a naive
    calibration here read the SAME ~95ms wall-clock for all three
    constants); the slope cancels it exactly."""
    # The BW and matmul chain lengths are chosen so the REAL work delta
    # is ~1 s of device time: the tunnel adds tens of ms of per-call
    # jitter, and a slope whose true delta is comparable to that jitter
    # swings wildly (observed: 593-2815 GB/s for the same chip across
    # runs before the lengths were scaled up); at ~1 s deltas that
    # jitter is <5%. The kernel-floor chain cannot reach ~1 s (its ops
    # must stay at module top level, and a ~300k-op HLO won't compile
    # in reasonable time), so its ~24 ms delta stays jitter-exposed —
    # acceptable because the floor term is ~2% of the modeled bound,
    # and min-of-reps timing plus the plausibility bounds below cap the
    # damage.
    t_lo = t_hi = float("nan")
    for attempt in range(3):
        t_lo = _time_chain(make_fn(n_lo), *args_fn())
        t_hi = _time_chain(make_fn(n_hi), *args_fn())
        if t_hi > t_lo:
            return (t_hi - t_lo) / (n_hi - n_lo)
    raise RuntimeError(
        f"calibration slope non-positive after 3 attempts "
        f"(t_lo={t_lo:.4f}s, t_hi={t_hi:.4f}s) — the tunnel is too "
        f"contended to calibrate; rerun on a quiet box")


def calibrate() -> dict:
    """Measure the three model constants on this chip (slope method,
    ~1 s work deltas — see _slope). Results are sanity-bounded: a value
    outside physical plausibility for any current TPU means the
    measurement was corrupted and the model must not run on it."""
    # Kernel floor: dependent TOP-LEVEL kernels, fusion broken by
    # optimization_barrier. The ops must be at module top level — inside
    # a scan body they execute within one compiled loop region and
    # measure ~0.02us/op, which is not the entry-computation per-kernel
    # overhead this constant represents (a sanity-bound catch).
    x0 = jnp.ones((8, 128), jnp.float32)

    def make_chain(n):
        @jax.jit
        def chain(x):
            for _ in range(n):
                x = jax.lax.optimization_barrier(x * 1.0000001)
            return jnp.sum(x)
        return chain

    floor = _slope(make_chain, lambda: (x0,), 400, 8400)

    # Streaming bandwidth: chained big-buffer add (reads+writes 2*size).
    size = 192 * 1024 * 1024  # 192 MB, comfortably inside HBM
    big = jnp.ones((size // 4,), jnp.float32)

    def make_stream(n):
        @jax.jit
        def stream(x):
            def body(c, _):
                return c + 1.0, ()
            c, _ = jax.lax.scan(body, x, None, length=n)
            return jnp.sum(c[:1])
        return stream

    per_iter = _slope(make_stream, lambda: (big,), 10, 2010)
    bw = 2.0 * size / per_iter

    # Matmul peak: chained 2048^3 bf16 matmuls (~17.2 GFLOP each).
    a = jnp.ones((2048, 2048), jnp.bfloat16)

    def make_mm(n):
        @jax.jit
        def mm(a):
            def body(c, _):
                return (c @ c) * jnp.bfloat16(1e-4), ()
            c, _ = jax.lax.scan(body, a, None, length=n)
            return jnp.sum(c[:1, :1].astype(jnp.float32))
        return mm

    per_mm = _slope(make_mm, lambda: (a,), 10, 25010)
    peak = 2.0 * 2048 ** 3 / per_mm
    cal = {"kernel_floor_us": floor * 1e6, "hbm_gbps": bw / 1e9,
           "matmul_tflops": peak / 1e12}
    _check_cal_bounds(cal)
    return cal


# Physical plausibility for any current TPU generation: HBM3e tops out
# under 2 TB/s/chip and no chip exceeds ~1 PFLOP/s dense bf16 — the
# observed corrupted readings (2815 GB/s, 3755 TFLOP/s) must fail.
_CAL_BOUNDS = {"kernel_floor_us": (0.2, 100.0), "hbm_gbps": (50, 2000),
               "matmul_tflops": (10, 1000)}


def _check_cal_bounds(cal: dict) -> None:
    for k, (lo, hi) in _CAL_BOUNDS.items():
        if not lo <= cal[k] <= hi:
            raise RuntimeError(
                f"calibration {k}={cal[k]:.3g} outside plausible "
                f"range [{lo}, {hi}] — measurement corrupted (tunnel "
                f"contention?) or --cal fields out of order; expected "
                f"FLOOR_US,BW_GBPS,MM_TFLOPS")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--config", default=None)
    ap.add_argument("--skip-measure", action="store_true",
                    help="model only: skip the measurement leg (pct_of_"
                         "bound is then null; compare against bench.py's "
                         "recorded rate by hand)")
    ap.add_argument("--dump", default=None, metavar="PATH",
                    help="write the optimized HLO text to PATH")
    ap.add_argument("--profile-json", default=None, metavar="PATH",
                    help="take the global compute term from a recorded "
                         "PROFILE.json cost card (telemetry/profiler.py"
                         ") instead of re-deriving it from this "
                         "compile's HLO — with --card naming the "
                         "executable (default: the steady-state train "
                         "slot)")
    ap.add_argument("--card", default=None, metavar="NAME",
                    help="cost-card name inside --profile-json")
    ap.add_argument("--cal", default=None,
                    metavar="FLOOR_US,BW_GBPS,MM_TFLOPS",
                    help="reuse recorded calibration constants instead "
                         "of measuring (the shared tunnel time-slices "
                         "long bursts, so sustained calibrations can "
                         "understate capability — see docs/PERF.md; "
                         "pass the best-observed envelope for a true "
                         "ceiling)")
    args = ap.parse_args()

    devices = bench.init_backend()
    n_dev = len(devices)
    config_path = args.config or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "experiment_config", "mini-imagenet_maml++_5-way_5-shot_DA_b12.json")
    # Same reshape-to-local-devices rule as every bench capture: the
    # modeled geometry must be the measured one.
    cfg = bench.load_workload(config_path, args.batch or 0, n_dev)

    if args.cal:
        parts = args.cal.split(",")
        if len(parts) != 3:
            print(json.dumps({"error": "--cal needs exactly 3 comma-"
                              "separated values: FLOOR_US,BW_GBPS,"
                              "MM_TFLOPS"}))
            return 1
        cal = {"kernel_floor_us": float(parts[0]),
               "hbm_gbps": float(parts[1]),
               "matmul_tflops": float(parts[2]), "recorded": True}
        _check_cal_bounds(cal)
    else:
        cal = calibrate()
    print(json.dumps({"calibration": cal}), flush=True)

    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, devices)
    plan = make_sharded_steps(cfg, apply, mesh)
    bench_epoch = max(cfg.total_epochs - 1, 0)
    train = plan.train_steps[(cfg.use_second_order(bench_epoch),
                              cfg.use_msl(bench_epoch))]
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    state = jax.device_put(state, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec()))
    batch_ep = shard_batch(bench.synthetic_batch(cfg, 0), mesh)
    epoch = jnp.float32(bench_epoch)
    compiled = train.lower(state, batch_ep, epoch).compile()
    hlo = compiled.as_text()
    print(json.dumps({"hlo_chars": len(hlo)}), flush=True)
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(hlo)

    model = HloCostModel(
        hlo,
        floor_s=cal["kernel_floor_us"] / 1e6,
        hbm_bps=cal["hbm_gbps"] * 1e9,
        mxu_fps=cal["matmul_tflops"] * 1e12)
    bound_s = model.step_bound_s()
    # Global compute term from the shared scan-trip-expanded counter
    # (hardware FLOPs incl. remat recompute, XLA-calibrated so the
    # dilated-conv encoding of the vmapped grouped convs — which defeats
    # exact label-based parsing — stays priced by XLA's own analysis).
    # Computed on the ALREADY-PARSED model (HloCostModel subclasses
    # HloFlopsCounter) rather than re-parsing the multi-MB HLO through
    # executable_flops; the calibration follows the same recipe, and the
    # estimator provenance is emitted in the summary JSON so a degraded
    # count can never pass silently.
    from howtotrainyourmamlpytorch_tpu.utils.hlo_flops import (
        combine_flops_estimates, xla_flat_flops)
    xla_flops = 0.0
    flops_source = "unavailable"
    if args.profile_json:
        # Consolidated path: the recorded cost card IS the compute
        # term — one flops algorithm (hlo_flops via the card), no
        # private re-derivation. Falls through to the live computation
        # when the card is missing (recorded in flops_source).
        from howtotrainyourmamlpytorch_tpu.telemetry import (
            profiler as profiler_mod)
        doc = profiler_mod.load_profile(args.profile_json)
        bench_key = (cfg.use_second_order(bench_epoch),
                     cfg.use_msl(bench_epoch))
        card_name = args.card or (
            f"train_so{int(bench_key[0])}_msl{int(bench_key[1])}")
        card = (doc or {"cards": {}})["cards"].get(card_name)
        if card and card.get("flops"):
            xla_flops = float(card["flops"])
            flops_source = f"cost_card:{card_name}"
        else:
            print(json.dumps({"warning": f"no cost card {card_name!r} "
                              f"in {args.profile_json!r}; deriving "
                              f"from this compile's HLO"}), flush=True)
    if not xla_flops:
        xla_flops, flops_source = combine_flops_estimates(
            model.total(expand_trips=True),
            model.total(expand_trips=False),
            xla_flat_flops(compiled))
    if xla_flops:
        model.flop_bound_s = max(model.flop_bound_s,
                                 xla_flops / (cal["matmul_tflops"] * 1e12))
        bound_s = max(bound_s, model.flop_bound_s)
    local_tasks = max(cfg.batch_size // n_dev, 1)
    bound_rate = local_tasks / bound_s

    cats = sorted(model.by_cat.items(), key=lambda kv: -kv[1]["time_s"])
    for name, d in cats[:12]:
        print(json.dumps({
            "category": name, "kernels": round(d["n"], 1),
            "model_ms": round(d["time_s"] * 1e3, 3),
            "gbytes": round(d["bytes"] / 1e9, 3),
            "gflops": round(d["flops"] / 1e9, 2)}), flush=True)
    print(json.dumps({"trip_counts": model.trip_counts}), flush=True)

    measured = None
    if not args.skip_measure:
        measured = bench.measure_rate(
            compiled, state, batch_ep, epoch,
            batch_size=cfg.batch_size, n_dev=n_dev, steps=args.steps)

    out = {
        "metric": "ceiling_model",
        "workload": cfg.experiment_name,
        "batch_per_chip": local_tasks,
        "kernels_per_step": round(model.kernels, 1),
        "serial_ms": round(model.serial_s * 1e3, 2),
        "bw_bound_ms": round(model.bw_bound_s * 1e3, 2),
        "flop_bound_ms": round(model.flop_bound_s * 1e3, 2),
        "async_gbytes": round(model.async_bytes / 1e9, 3),
        "total_gbytes": round(model.total_bytes / 1e9, 3),
        "total_gflops": round(model.total_flops / 1e9, 1),
        "flops_source": flops_source,
        "expanded_gflops": round(xla_flops / 1e9, 1),
        "bound_step_ms": round(bound_s * 1e3, 2),
        "bound_tasks_per_sec_per_chip": round(bound_rate, 2),
        "measured_tasks_per_sec_per_chip": (round(measured, 2)
                                            if measured else None),
        "pct_of_bound": (round(100 * measured / bound_rate, 1)
                         if measured else None),
    }
    if measured:
        # Bounds sandwich: where does the measured step sit between the
        # model's optimistic floor (every kernel at the calibrated launch
        # floor, all traffic free) and its pessimistic serial sum? When
        # the measured step BEATS even the pure-bandwidth leg — observed
        # at the shipped mb=12 point — the padded-traffic accounting
        # itself overstates real HBM residency (fusion keeps more
        # intermediates in VMEM than the per-fusion operand/output byte
        # sum admits). implied_max_hbm_gbytes converts the measured step
        # time into the largest traffic consistent with the calibrated
        # bandwidth: the gap to total_gbytes is a measured lower bound on
        # how much of the modeled traffic never touched HBM.
        step_s = local_tasks / measured
        floor_s = model.kernels * cal["kernel_floor_us"] * 1e-6
        implied = step_s * cal["hbm_gbps"] * 1e9
        out.update({
            "floor_bound_ms": round(floor_s * 1e3, 2),
            "measured_step_ms": round(step_s * 1e3, 2),
            "implied_max_hbm_gbytes": round(implied / 1e9, 3),
            "modeled_traffic_overstatement_pct": (
                round(100 * (1 - implied / model.total_bytes), 1)
                if model.total_bytes > implied else 0.0),
        })
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Where does the device time go? Ranked perf report over a run's
PROFILE.json cost cards + perf_profile telemetry rows.

Usage:
    python scripts/perf_report.py <PROFILE.json | logs dir | experiment dir>
                                  [--events PATH] [--json]

Reads the two artifacts the perf lab (telemetry/profiler.py,
docs/PERF.md § Where the time goes) produces:

* ``PROFILE.json`` — one roofline cost card per compiled executable
  (trip-expanded FLOPs, bytes accessed, arithmetic intensity,
  compute-vs-memory-bound verdict against the device peak table);
* ``events.jsonl`` ``perf_profile`` rows — sampled device-time
  attribution windows (per-executable / per-named-region seconds,
  device-compute vs dispatch-gap wall split).

and prints the ranked table the MFU campaign reads: executables by
measured device time (cards-by-FLOPs when the run never sampled), each
with its bound verdict and achieved-vs-ceiling FLOP/s, plus the window
split and the per-region ranking. This CLI supersedes the private
flops/ceiling math in scripts/perf_breakdown.py / perf_ceiling.py —
one flops algorithm (utils/hlo_flops.py via the cost cards),
everywhere.

Artifact contract (bench.py discipline): the LAST stdout line is the
JSON artifact ``{"metric": "perf_report", ...}``. Exit 0 ok, 1 when
neither a PROFILE.json nor any perf_profile rows are readable, 2 bad
usage.

No JAX import — the report must run on a login node: profiler.py and
tracing.py are stdlib-only at import time and are loaded by file path
so the package ``__init__`` chains (which do import jax) never execute
(the ckpt_admin.py discipline).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REPORT_SCHEMA = "maml_perf_report_v1"


def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_profiler = _load_module(
    "_perf_report_profiler",
    os.path.join("howtotrainyourmamlpytorch_tpu", "telemetry",
                 "profiler.py"))
_tracing = _load_module(
    "_perf_report_tracing",
    os.path.join("howtotrainyourmamlpytorch_tpu", "utils", "tracing.py"))


def resolve_profile_path(path: str) -> Optional[str]:
    """Accept PROFILE.json itself, a logs dir, or an experiment dir."""
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        for candidate in (
                os.path.join(path, _profiler.PROFILE_FILE),
                os.path.join(path, "logs", _profiler.PROFILE_FILE)):
            if os.path.exists(candidate):
                return candidate
    return None


def resolve_events_path(path: str) -> Optional[str]:
    if os.path.isfile(path) and path.endswith(".jsonl"):
        return path
    base = os.path.dirname(path) if os.path.isfile(path) else path
    for candidate in (os.path.join(base, "events.jsonl"),
                      os.path.join(base, "logs", "events.jsonl")):
        if os.path.exists(candidate):
            return candidate
    return None


def accumulate_rows(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a log's perf_profile rows: per-executable/region seconds
    SUM across samples (total observed device time — more samples in an
    executable means more weight, which is the ranking the MFU campaign
    wants); the window-split fractions take the most recent row (the
    current shape of the step)."""
    per_exec: Dict[str, float] = {}
    per_region: Dict[str, float] = {}
    roofline: Dict[str, Dict[str, Any]] = {}
    last: Dict[str, Any] = {}
    samples = 0
    for e in events:
        if e.get("event") != "perf_profile":
            continue
        samples += 1
        last = e
        for k, v in (e.get("per_executable_seconds") or {}).items():
            if isinstance(v, (int, float)):
                per_exec[k] = per_exec.get(k, 0.0) + float(v)
        for k, v in (e.get("per_region_seconds") or {}).items():
            if isinstance(v, (int, float)):
                per_region[k] = per_region.get(k, 0.0) + float(v)
        # Achieved-vs-ceiling was computed live per sample
        # (profiler.attach_roofline); the newest row's rates win —
        # the current shape of each executable.
        for k, v in (e.get("roofline") or {}).items():
            if isinstance(v, dict):
                roofline[k] = v
    return {"samples": samples, "per_executable_seconds": per_exec,
            "per_region_seconds": per_region, "roofline": roofline,
            "last": last}


def build_report(profile: Optional[Dict[str, Any]],
                 acc: Dict[str, Any]) -> Dict[str, Any]:
    cards: Dict[str, Dict[str, Any]] = dict(
        (profile or {}).get("cards") or {})
    per_exec = acc["per_executable_seconds"]
    ranked: List[Dict[str, Any]] = []
    if per_exec:
        order = sorted(per_exec.items(), key=lambda kv: -kv[1])
        total = sum(per_exec.values()) or 1.0
        for module, secs in order:
            card = cards.get(module) or _profiler._match_card(module,
                                                             cards)
            row = {"executable": module,
                   "device_seconds": round(secs, 6),
                   "share": round(secs / total, 4),
                   "bound": (card or {}).get("bound"),
                   "flops": (card or {}).get("flops"),
                   "arithmetic_intensity":
                       (card or {}).get("arithmetic_intensity")}
            ceiling = (card or {}).get("ceiling_flops_per_s")
            if ceiling:
                row["ceiling_flops_per_s"] = ceiling
            # Achieved FLOP/s vs ceiling, from the newest sample's
            # live attach_roofline computation.
            rl = acc.get("roofline", {}).get(module) or {}
            if rl.get("achieved_flops_per_s") is not None:
                row["achieved_flops_per_s"] = rl["achieved_flops_per_s"]
            if rl.get("frac_of_ceiling") is not None:
                row["frac_of_ceiling"] = round(rl["frac_of_ceiling"], 4)
            ranked.append(row)
    else:
        # Never-sampled run: rank the cost cards by FLOPs — the static
        # half of the story still names the heaviest executable.
        for name, card in sorted(cards.items(),
                                 key=lambda kv: -(kv[1].get("flops")
                                                  or 0.0)):
            ranked.append({
                "executable": name, "device_seconds": None,
                "share": None, "bound": card.get("bound"),
                "flops": card.get("flops"),
                "arithmetic_intensity":
                    card.get("arithmetic_intensity")})
    last = acc["last"]
    top = ranked[0] if ranked else None
    return {
        "schema": REPORT_SCHEMA,
        "peak_flops": (profile or {}).get("peak_flops"),
        "hbm_bytes_per_s": (profile or {}).get("hbm_bytes_per_s"),
        "peak_flops_source": (profile or {}).get("peak_flops_source"),
        "device_kind": (profile or {}).get("device_kind"),
        "cards": len(cards),
        "samples": acc["samples"],
        "ranked": ranked,
        "per_region_seconds": {
            k: round(v, 6)
            for k, v in sorted(acc["per_region_seconds"].items(),
                               key=lambda kv: -kv[1])},
        "device_compute_frac": last.get("device_compute_frac"),
        "dispatch_gap_frac": last.get("dispatch_gap_frac"),
        "top_executable": top["executable"] if top else None,
        "top_executable_bound": top["bound"] if top else None,
    }


def format_report(report: Dict[str, Any]) -> str:
    lines = [f"perf report ({report['cards']} cost card(s), "
             f"{report['samples']} profile sample(s); device "
             f"{report['device_kind'] or '?'}, peaks "
             f"{report['peak_flops_source'] or 'unknown'})"]
    if report.get("device_compute_frac") is not None:
        lines.append(
            f"  window split: device compute "
            f"{report['device_compute_frac']:.1%}, dispatch gap "
            f"{report['dispatch_gap_frac']:.1%}")
    if report["ranked"]:
        lines.append(f"  {'executable':<28} {'device s':>10} "
                     f"{'share':>7} {'bound':>8} {'GFLOP':>10} "
                     f"{'%ceil':>7}")
        for row in report["ranked"][:12]:
            secs = (f"{row['device_seconds']:.4f}"
                    if row["device_seconds"] is not None else "-")
            share = (f"{row['share']:.1%}"
                     if row["share"] is not None else "-")
            gflop = (f"{row['flops'] / 1e9:.2f}"
                     if row.get("flops") else "-")
            ceil = (f"{row['frac_of_ceiling']:.1%}"
                    if row.get("frac_of_ceiling") is not None else "-")
            lines.append(f"  {row['executable']:<28} {secs:>10} "
                         f"{share:>7} {str(row['bound'] or '-'):>8} "
                         f"{gflop:>10} {ceil:>7}")
    regions = report["per_region_seconds"]
    if regions:
        lines.append("  named regions (device s): " + ", ".join(
            f"{k}={v:.4f}" for k, v in list(regions.items())[:8]))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Ranked device-time / roofline report over "
                    "PROFILE.json + events.jsonl.")
    ap.add_argument("path", help="PROFILE.json, a logs/ dir, or an "
                                 "experiment dir")
    ap.add_argument("--events", default=None,
                    help="events.jsonl override (default: discovered "
                         "next to the profile)")
    ap.add_argument("--json", action="store_true",
                    help="emit ONLY the JSON artifact line (CI mode)")
    args = ap.parse_args(argv)

    profile_path = resolve_profile_path(args.path)
    profile = (_profiler.load_profile(profile_path)
               if profile_path else None)
    events_path = (args.events if args.events
                   else resolve_events_path(args.path))
    if args.events and not os.path.exists(args.events):
        # An EXPLICIT events override that doesn't exist is an error,
        # not a silent cards-only report — "samples: 0" must mean the
        # run never sampled, never a typo'd path.
        print(json.dumps({"error": f"--events {args.events!r} does "
                                   f"not exist"}))
        return 1
    events: List[Dict[str, Any]] = []
    if events_path and os.path.exists(events_path):
        try:
            events = _tracing.read_jsonl_rotated(events_path)
        except (OSError, ValueError) as e:
            print(json.dumps(
                {"error": f"{type(e).__name__}: {e}",
                 "events": events_path}))
            return 1
    acc = accumulate_rows(events)
    if profile is None and acc["samples"] == 0:
        print(json.dumps({
            "error": f"no readable {_profiler.PROFILE_FILE} under "
                     f"{args.path!r} and no perf_profile rows "
                     f"(profile_every_n_steps=0 run, or wrong path?)"}))
        return 1
    report = build_report(profile, acc)
    if not args.json:
        print(format_report(report))
    artifact = {"metric": "perf_report", **{
        k: report[k] for k in (
            "schema", "cards", "samples", "top_executable",
            "top_executable_bound", "device_compute_frac",
            "dispatch_gap_frac", "peak_flops_source")},
        "profile_path": profile_path, "events_path": events_path,
        "ok": True}
    print(json.dumps(artifact), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Fleet benchmark: N replica subprocesses + jax-free router on one box.

The proof harness for the serving fleet (docs/SERVING.md § Fleet): real
OS processes, real sockets, the real router/controller/L2 — no mocks.
The driver process stays **jax-free** (router + controller loaded by
file path, the ckpt_admin.py discipline; the load generator is shared
with scripts/serve_bench.py); everything that needs jax runs in child
processes (``--mode prepare`` / ``--mode publish-v2`` and the replica
workers themselves).

Legs:

1. **single** — ONE replica, no L2, driven through the same router and
   sockets: the pre-fleet architecture (PR 2's engine) under this
   workload, the honest baseline.
2. **fleet** — N replicas (default 3) with consistent-hash routing and
   the shared L2 tier, same workload, same tenant population.
   Mid-load, a perturbed checkpoint is published as a new version and
   the controller runs a ROLLING hot-swap through it — replicas swap
   one at a time behind the router, so the leg proves zero dropped
   requests through the swap.
3. **migration** — after the rollout: serve one tenant on its primary
   replica A, tombstone-drain A, route the tenant again (it lands on
   the next ring position B) and assert the response came from the
   **L2 tier with zero adapt dispatches on B** — the cross-replica
   "adapt once, predict many" guarantee.

What makes the fleet faster *on one core*: the workload has more
tenants than one replica's L1 (``--tenants`` > ``--l1-capacity``), so
the single engine thrashes its LRU and re-adapts repeat tenants, while
consistent hashing partitions the tenant space so each replica's share
FITS — the fleet scales the cached working set, not raw FLOPs, which
is exactly the router's design claim (and the only scaling axis a
1-core box can demonstrate honestly; on real parallel hardware the
compute axis multiplies on top).

Artifact contract (bench.py discipline): the LAST stdout JSON line is
``{"metric": "fleet_bench", ...}`` with per-replica and fleet-aggregate
QPS/p50/p95/hit fractions, rolling-swap counts and the migration
verdict. On a box that cannot bind localhost sockets the artifact says
``"status": "skipped"`` (exit 0) — the chaos_pod.py rule.

Usage:
    python scripts/fleet_bench.py --quick            # 2-replica CI smoke
    python scripts/fleet_bench.py                    # full 3-replica proof
    python scripts/fleet_bench.py --replicas 4 --requests 600 --out /tmp/fb
    python scripts/fleet_bench.py --quick --trace-sample-rate 1.0 \
        --out /tmp/fb   # request tracing on; then scripts/slo_report.py /tmp/fb
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _SCRIPTS)
sys.path.insert(0, _REPO)

def _load_module(name: str, relpath: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# The ONE definition of the synthetic request generators lives in
# serve/loadlab/workloads.py (file-path loaded — stdlib+numpy only, no
# jax); serve_bench re-exports the same functions, so every bench
# synthesizes identical traffic by construction.
_workloads_mod = _load_module(
    "_fleet_bench_workloads_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "loadlab",
                 "workloads.py"))
synthetic_arrays = _workloads_mod.synthetic_arrays
tenant_pool = _workloads_mod.tenant_pool


_router_mod = _load_module(
    "_fleet_bench_router_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "fleet",
                 "router.py"))
_controller_mod = _load_module(
    "_fleet_bench_controller_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "serve", "fleet",
                 "controller.py"))
_tracing_mod = _load_module(
    "_fleet_bench_tracing_impl",
    os.path.join("howtotrainyourmamlpytorch_tpu", "utils", "tracing.py"))


def bench_bucket(quick: bool):
    """(support, query) bucket: the full profile serves 3-way 5-shot
    (15 support rows — the MAML++ flagship shot count) with a small
    query set, which is what prices adaptation honestly ABOVE the
    per-request fixed costs (K inner fwd+bwd passes over 15 rows vs
    one forward over 2); --quick shrinks to a 1-shot toy."""
    return (3, 4) if quick else (15, 2)


class _MiniMetrics:
    """Duck-typed stand-in for the telemetry MetricsRegistry (whose
    import chain pulls jax — this driver must not): counters, gauges
    and exact-value histograms, snapshot()-able into the artifact."""

    class _C:
        def __init__(self):
            self.value = 0.0

        def inc(self, amount: float = 1.0):
            self.value += amount

    class _G:
        def __init__(self):
            self.value = None

        def set(self, v):
            self.value = float(v)

    class _H:
        # Exact values (the driver sees hundreds of requests, not
        # millions), nearest-rank quantiles — no bucket error.
        def __init__(self):
            self.values: List[float] = []

        def observe(self, v):
            self.values.append(float(v))

        def quantile(self, q):
            if not self.values:
                return None
            return _tracing_mod.nearest_rank(sorted(self.values), q)

        @property
        def value(self):
            return {"count": len(self.values),
                    "sum": round(sum(self.values), 6),
                    "p50": self.quantile(0.50),
                    "p95": self.quantile(0.95)}

    def __init__(self):
        self._m: Dict[str, Any] = {}

    def counter(self, name):
        return self._m.setdefault(name, self._C())

    def gauge(self, name):
        return self._m.setdefault(name, self._G())

    def histogram(self, name):
        return self._m.setdefault(name, self._H())

    def snapshot(self):
        return {k: v.value for k, v in sorted(self._m.items())}


def _can_bind_localhost() -> bool:
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
        return True
    except OSError:
        return False


def fleet_cfg_dict(out_dir: str, *, quick: bool, l1_capacity: int,
                   l2_dir: str, trace_sample_rate: float = 0.0) -> dict:
    """The serving workload every process in the bench shares.

    The full profile runs a REALISTICALLY-priced adaptation (20x20
    images, 16 filters, 3 stages, the MAML++ 5-step evaluation
    protocol): the fleet's claim is that routing affinity + the L2
    tier remove adapt WORK, so the adapt must dominate per-request
    cost the way it does in production — a toy adapt would measure
    socket overhead instead of the architecture. --quick shrinks
    everything (tiny model, 2 steps) because the CI smoke asserts
    plumbing (zero drops, migration), not throughput."""
    return dict(
        experiment_name="fleet_bench", experiment_root=out_dir,
        dataset_name="synthetic_fleet",
        image_height=(12 if quick else 24),
        image_width=(12 if quick else 24), image_channels=1,
        num_classes_per_set=3,
        num_samples_per_class=(1 if quick else 5),
        num_target_samples=2, batch_size=4,
        cnn_num_filters=(4 if quick else 32),
        num_stages=(2 if quick else 4),
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=(2 if quick else 5),
        second_order=False, use_multi_step_loss_optimization=False,
        compute_dtype="float32", mesh_shape=[1, 1],
        serve_buckets=[list(bench_bucket(quick))], serve_batch_tasks=4,
        serve_cache_capacity=int(l1_capacity),
        serve_default_deadline_ms=0.0,
        serve_max_queue_depth=256,
        serve_registry_poll_s=0.0,
        # Canary gates on NOISE probes are luck, not signal, and this
        # bench's v2 is v1 with a 1e-3 weight perturbation: (a) the
        # latency gate compares candidate vs live adapt wall time —
        # scheduling noise when N replicas share one oversubscribed
        # box; (b) with 2 probes x 2 queries the live engine "beats
        # chance" on noise pixels often enough to arm the accuracy
        # gate, turning every swap into a coin flip. Widen both so the
        # gate that decides this bench's rollout is the one that can
        # actually fire on bad bytes: finiteness.
        serve_canary_latency_factor=20.0,
        serve_canary_acc_drop=1.0,
        serve_l2_dir=l2_dir,
        # Fleet knobs — the driver reads THESE (one source of truth
        # for replicas and router): tight lease cadence for fast
        # membership, a generous dead threshold (a swap canary on an
        # oversubscribed box can starve even the side-thread
        # heartbeat), high vnodes for smooth tenant shares, and a
        # permissive load factor so affinity — the thing this bench
        # measures — yields to spill only under real imbalance.
        fleet_lease_interval_s=0.25,
        fleet_replica_stalled_s=0.75,
        fleet_replica_dead_s=5.0,
        fleet_vnodes=128,
        fleet_load_factor=2.5,
        # Request tracing + SLO ledger (telemetry/reqtrace.py): the
        # replicas read the sample rate from this same json, so driver
        # and engines make the identical head-based sampling decision.
        reqtrace_sample_rate=float(trace_sample_rate),
        fleet_slo_p95_ms=2000.0,
        fleet_slo_target_frac=0.95,
        aot_store_dir=os.path.join(out_dir, "aot_store"),
        watchdog_serve_timeout_s=600.0)


# ---------------------------------------------------------------------------
# jax-side child modes (the driver process never imports jax)
# ---------------------------------------------------------------------------

def _mode_prepare(cfg_path: str, ckpt_dir: str) -> int:
    """Save + publish the v1 checkpoint and prewarm the shared AOT
    store (one warmed engine) so every replica boots warm instead of
    paying its own compile — the PR 9 warm-start story doing real work."""
    import jax
    from howtotrainyourmamlpytorch_tpu.ckpt.registry import ModelRegistry
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.serve import ServingEngine
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)

    cfg = MAMLConfig.from_json_file(cfg_path)
    model_init, _ = make_model(cfg)
    state = init_train_state(cfg, model_init, jax.random.PRNGKey(cfg.seed))
    manager = CheckpointManager(ckpt_dir, max_to_keep=4)
    manager.save(state, epoch=0, current_iter=1, val_acc=0.5)
    registry = ModelRegistry(ckpt_dir)
    rec = registry.publish(tag="0", epoch=0, iteration=1, val_acc=0.5,
                           fingerprint=manager.fingerprint(0))
    engine = ServingEngine.from_checkpoint(cfg, ckpt_dir)
    try:
        engine.warmup()  # populates the AOT store for the whole fleet
    finally:
        engine.close()
    print(json.dumps({"prepared": True, "version": rec["version"]}),
          flush=True)
    return 0


def _mode_publish_v2(cfg_path: str, ckpt_dir: str) -> int:
    """Publish a REAL new version (perturbed weights — different bytes,
    different fingerprint, still finite so the canary passes): the
    rolling-swap target."""
    import jax
    from howtotrainyourmamlpytorch_tpu.ckpt.registry import ModelRegistry
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)

    cfg = MAMLConfig.from_json_file(cfg_path)
    model_init, _ = make_model(cfg)
    template = init_train_state(cfg, model_init,
                                jax.random.PRNGKey(cfg.seed))
    manager = CheckpointManager(ckpt_dir, max_to_keep=4)
    state, _meta = manager.load(template, 0)
    state = state.replace(params=jax.tree.map(
        lambda x: x * (1.0 + 1e-3), state.params))
    manager.save(state, epoch=1, current_iter=2, val_acc=0.6)
    registry = ModelRegistry(ckpt_dir)
    rec = registry.publish(tag="1", epoch=1, iteration=2, val_acc=0.6,
                           fingerprint=manager.fingerprint(1))
    print(json.dumps({"published": True, "version": rec["version"]}),
          flush=True)
    return 0


def _run_child(mode: str, cfg_path: str, ckpt_dir: str, out: str,
               wait: bool = True):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    log = open(os.path.join(out, f"{mode}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--mode", mode,
         "--config-path", cfg_path, "--ckpt-dir", ckpt_dir, "--out", out],
        cwd=_REPO, env=env, stdout=log, stderr=subprocess.STDOUT)
    if not wait:
        return proc
    rc = proc.wait()
    log.close()
    if rc != 0:
        with open(log.name) as f:
            raise RuntimeError(f"child --mode {mode} failed rc={rc}:\n"
                               + f.read()[-2000:])
    return proc


# ---------------------------------------------------------------------------
# replica management (driver side)
# ---------------------------------------------------------------------------

class ReplicaConn:
    """One persistent full-duplex connection to a replica: a sender
    (the driver loop) and a reader thread that dispatches response
    frames to the bench's completion callback."""

    def __init__(self, rid: int, port: int, on_response):
        self.rid = rid
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=60)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._on_response = on_response
        self._send_lock = threading.Lock()
        self._stats: Optional[dict] = None
        self._stats_evt = threading.Event()
        self._stopped_evt = threading.Event()
        threading.Thread(target=self._reader, daemon=True).start()

    def _reader(self) -> None:
        try:
            while True:
                msg = _router_mod.recv_msg(self.sock)
                op = msg.get("op")
                if op == "response":
                    self._on_response(self.rid, msg)
                elif op == "stats":
                    self._stats = msg
                    self._stats_evt.set()
                elif op == "stopped":
                    self._stopped_evt.set()
                    return
        except (ConnectionError, OSError, EOFError):
            self._stopped_evt.set()

    def send(self, msg: dict) -> None:
        with self._send_lock:
            _router_mod.send_msg(self.sock, msg)

    def stats(self, timeout: float = 30.0) -> dict:
        self._stats_evt.clear()
        self.send({"op": "stats"})
        if not self._stats_evt.wait(timeout):
            raise TimeoutError(f"replica {self.rid} stats timed out")
        return self._stats or {}

    def stop(self, timeout: float = 30.0) -> None:
        try:
            self.send({"op": "stop"})
            self._stopped_evt.wait(timeout)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def start_replicas(out: str, cfg_path: str, ckpt_dir: str,
                   fleet_dir: str, ids: List[int]) -> Dict[int, Any]:
    procs = {}
    for rid in ids:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log = open(os.path.join(out, f"replica_{rid}.log"), "w")
        procs[rid] = (subprocess.Popen(
            [sys.executable, "-m",
             "howtotrainyourmamlpytorch_tpu.serve.fleet.replica",
             "--config", cfg_path, "--replica-id", str(rid),
             "--fleet-dir", fleet_dir, "--checkpoint", ckpt_dir,
             "--events", os.path.join(out, f"events_replica_{rid}.jsonl")],
            cwd=_REPO, env=env, stdout=log, stderr=subprocess.STDOUT),
            log)
    return procs


def wait_for_replicas(fleet_dir: str, ids: List[int], procs,
                      timeout_s: float) -> Dict[int, int]:
    """Block until every replica's lease payload carries its port."""
    deadline = time.monotonic() + timeout_s
    ports: Dict[int, int] = {}
    while time.monotonic() < deadline:
        members = _router_mod.read_members(fleet_dir)
        for rid in ids:
            payload = (members.get(rid) or {}).get("payload") or {}
            if payload.get("port"):
                ports[rid] = int(payload["port"])
        if len(ports) == len(ids):
            return ports
        for rid, (proc, _log) in procs.items():
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rid} exited rc={proc.returncode} before "
                    f"announcing (see replica_{rid}.log)")
        time.sleep(0.1)
    raise TimeoutError(f"replicas {sorted(set(ids) - set(ports))} never "
                       f"announced within {timeout_s:.0f}s")


def stop_replicas(conns: Dict[int, ReplicaConn], procs) -> None:
    for conn in conns.values():
        conn.stop()
    for rid, (proc, log) in procs.items():
        try:
            # A replica that never got a stop frame (no conn — startup
            # failed) won't exit on its own: terminate it directly.
            proc.wait(timeout=30 if rid in conns else 0.1)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        log.close()
    for conn in conns.values():
        conn.close()


# ---------------------------------------------------------------------------
# load generation + the drive loop
# ---------------------------------------------------------------------------

def build_schedule(num_requests: int, num_tenants: int, seed: int,
                   image_shape, bucket):
    """Mixed-tenant request schedule over a fixed tenant population
    (serve_bench's shared generators): every request is some tenant's
    fixed support set + fresh queries — repeat tenants ARE the
    workload, exactly the traffic the router's affinity exists for."""
    import numpy as np
    rng = np.random.RandomState(seed)
    pool = tenant_pool(image_shape, 3, True, rng, [bucket], num_tenants)
    schedule = []
    for i in range(num_requests):
        t = int(rng.randint(num_tenants))
        sx, sy, q_rows = pool[t]
        _, _, qx = synthetic_arrays(image_shape, 3, True, rng,
                                    (1, q_rows))
        schedule.append({"cid": i, "tenant": t, "sx": sx, "sy": sy,
                         "qx": qx,
                         "key": _router_mod.routing_key(sx, sy)})
    return pool, schedule


def drive_leg(router, conns: Dict[int, ReplicaConn], schedule,
              *, max_outstanding: int, controller=None,
              swap_trigger=None, max_retries: int = 20,
              failover_max_attempts: int = 3,
              stall_timeout_s: float = 300.0, reqtrace=None,
              sample_rate: float = 0.0, slo=None, on_tick=None) -> dict:
    """Push the whole schedule through the fleet as fast as the window
    allows (backlog/throughput mode — the serve_bench rate=0 rule),
    pumping membership refresh, rollout ticks and the optional mid-load
    swap trigger from the same loop a real frontend would run.

    ``reqtrace`` (the module ``_router_mod.reqtrace_mod()`` returns —
    same object the wire protocol records into) + ``sample_rate`` turn
    on request tracing: each request mints its context ONCE (retries
    keep the original trace — the root span covers the whole e2e
    including rejection round-trips) and the root ``request`` span is
    recorded when the final response lands.  ``slo`` is an optional
    SLOLedger fed every completed request's e2e latency.

    Failover is the ROUTER's job now (router.py § FailoverPolicy): a
    dead connection's orphans are resubmitted through the policy
    (bounded ``failover_max_attempts``, counted ``fleet/failovers``,
    breaker-fed so the dead replica leaves the candidate set before
    its lease ages out); a request that exhausts its attempts lands as
    a terminal ``failover_exhausted`` result instead of orbiting the
    ring. A ``shed:`` error is TERMINAL by construction — admission
    refused it at the door, retrying would defeat overload protection.
    ``on_tick(now)`` (optional) runs on the refresh cadence — the
    chaos driver pumps its supervisor and reconnects from it."""
    lock = threading.Lock()
    cond = threading.Condition(lock)
    results: Dict[int, dict] = {}
    rid_of: Dict[int, int] = {}
    send_ts: Dict[int, float] = {}
    ctx_of: Dict[int, Any] = {}
    retry_q: deque = deque()
    retry_count: Dict[int, int] = {}
    state = {"outstanding": 0, "retries": 0, "gave_up": 0}
    failover = _router_mod.FailoverPolicy(
        router, max_attempts=failover_max_attempts)

    def on_response(rid: int, msg: dict) -> None:
        cid = msg.get("id")
        with cond:
            router.complete(rid_of.get(cid, rid))
            err = msg.get("error")
            if not err:
                # A served answer closes the replica's breaker (the
                # half-open probe success path included).
                router.record_success(rid)
            if err and str(err).startswith("rejected") \
                    and retry_count.get(cid, 0) < max_retries:
                retry_count[cid] = retry_count.get(cid, 0) + 1
                state["retries"] += 1
                retry_q.append(cid)
            else:
                latency = time.monotonic() - send_ts[cid]
                msg["latency_s_e2e"] = latency
                msg["rid"] = rid
                results[cid] = msg
                failover.request_done(cid)
                if slo is not None:
                    slo.observe(by_cid[cid]["tenant"], latency * 1e3)
                ctx = ctx_of.get(cid)
                if reqtrace is not None and ctx is not None:
                    reqtrace.record_root(ctx, send_ts[cid], latency,
                                         replica=rid,
                                         error=bool(err))
            state["outstanding"] -= 1
            cond.notify()

    def give_up(cid: int) -> None:
        # Caller holds ``cond``. Terminal synthetic result: the request
        # chased failovers past the bound; surface the error rather
        # than stall the window (zero-dropped accounting still sees
        # it — "dropped" counts non-ok results).
        latency = time.monotonic() - send_ts.get(cid, time.monotonic())
        results[cid] = {"id": cid, "error": "failover_exhausted",
                        "status": "failed", "latency_s_e2e": latency,
                        "rid": None}
        state["gave_up"] += 1
        ctx = ctx_of.get(cid)
        if reqtrace is not None and ctx is not None:
            reqtrace.record_root(ctx, send_ts.get(cid, 0.0), latency,
                                 replica=None, error=True)

    for conn in conns.values():
        conn._on_response = on_response

    by_cid = {item["cid"]: item for item in schedule}
    pending = deque(item["cid"] for item in schedule)
    swap_fired = False
    dead_conns: set = set()  # conn OBJECT ids — a restarted replica's
    #                          fresh conn under the same rid is new.
    t0 = time.monotonic()
    last_progress = time.monotonic()
    last_refresh = 0.0
    completed_prev = 0
    while len(results) < len(schedule):
        now = time.monotonic()
        if now - last_refresh > 0.05:
            router.refresh()
            if controller is not None:
                controller.tick()
            if on_tick is not None:
                on_tick(now)
            last_refresh = now
            # Dead-socket recovery (the failure-table contract): a
            # replica whose connection died mid-flight never answers
            # its outstanding requests — hand them to the failover
            # policy, which settles the router's books, feeds the
            # breaker, and bounds per-request attempts.
            for rid, conn in list(conns.items()):
                if conn._on_response is not on_response:
                    # A conn swapped in mid-leg (chaos reconnect after
                    # a supervisor restart) joins the response path.
                    conn._on_response = on_response
                if id(conn) in dead_conns \
                        or not conn._stopped_evt.is_set():
                    continue
                dead_conns.add(id(conn))
                with cond:
                    orphans = [cid for cid, r in rid_of.items()
                               if r == rid and cid not in results
                               and cid not in retry_q
                               and cid not in pending]
                    requeue, gave_up = failover.replica_failed(
                        rid, orphans)
                    for cid in requeue:
                        retry_count[cid] = retry_count.get(cid, 0) + 1
                        state["retries"] += 1
                        retry_q.append(cid)
                        state["outstanding"] -= 1
                    for cid in gave_up:
                        give_up(cid)
                        state["outstanding"] -= 1
                    cond.notify()
        if (swap_trigger is not None and not swap_fired
                and len(results) >= swap_trigger["at_completed"]):
            swap_trigger["fire"]()
            swap_fired = True
        sent_any = False
        with cond:
            while (retry_q or pending) \
                    and state["outstanding"] < max_outstanding:
                cid = retry_q.popleft() if retry_q else pending.popleft()
                item = by_cid[cid]
                if reqtrace is not None and cid not in ctx_of:
                    # Mint ONCE per request id: the head-based decision
                    # and the trace id survive retries unchanged.
                    ctx_of[cid] = reqtrace.mint(item["tenant"], cid,
                                                sample_rate)
                ctx = ctx_of.get(cid)
                rid = router.route(item["key"], ctx)
                if rid is None or rid not in conns:
                    if rid is not None:
                        router.complete(rid)
                    (retry_q if retry_count.get(cid) else pending
                     ).appendleft(cid)
                    break
                rid_of[cid] = rid
                send_ts.setdefault(cid, time.monotonic())
                state["outstanding"] += 1
                sent_any = True
                conn = conns[rid]
                try:
                    msg = {"op": "serve", "id": cid,
                           "support_x": item["sx"],
                           "support_y": item["sy"],
                           "query_x": item["qx"]}
                    if ctx is not None:
                        # Unsampled requests carry NO trace key at all
                        # (rate=0 wire bytes identical to pre-trace).
                        msg["trace"] = ctx
                    conn.send(msg)
                except OSError:
                    # Replica vanished mid-send (SIGKILL class): the
                    # failover policy settles the books (complete +
                    # breaker failure) and decides requeue vs give-up.
                    state["outstanding"] -= 1
                    requeue, gave_up = failover.replica_failed(
                        rid, [cid])
                    if requeue:
                        retry_count[cid] = retry_count.get(cid, 0) + 1
                        state["retries"] += 1
                        retry_q.append(cid)
                    else:
                        give_up(cid)
                    break
            completed = len(results)
            if completed > completed_prev:
                last_progress = time.monotonic()
                completed_prev = completed
            if not sent_any:
                cond.wait(timeout=0.02)
        if time.monotonic() - last_progress > stall_timeout_s:
            raise TimeoutError(
                f"fleet made no progress for {stall_timeout_s:.0f}s "
                f"({len(results)}/{len(schedule)} done)")
    wall = time.monotonic() - t0
    ok = [r for r in results.values() if not r.get("error")]
    lat_ms = sorted(r["latency_s_e2e"] * 1e3 for r in ok)

    def pct(q, vals=lat_ms):
        # Nearest-rank, the repo's one pinned quantile definition
        # (utils/tracing.py § nearest_rank — file-path loaded, the
        # jax-free driver rule).
        if not vals:
            return None
        return round(_tracing_mod.nearest_rank(vals, q), 3)

    # Per-cache-tier latency split: WHERE a request's latency came from
    # is tier-shaped (an L1 hit skips adapt entirely, a miss pays it).
    tier_lat: Dict[str, List[float]] = {"l1": [], "l2": [], "miss": []}
    for r in ok:
        tier_lat[r.get("cache_tier") or "miss"].append(
            r["latency_s_e2e"] * 1e3)
    tier_latency_ms = {
        tier: ({"count": len(vals), "p50_ms": pct(0.50, sorted(vals)),
                "p95_ms": pct(0.95, sorted(vals)),
                "p99_ms": pct(0.99, sorted(vals))} if vals else None)
        for tier, vals in tier_lat.items()}

    tiers = [r.get("cache_tier") for r in ok]
    shed = sum(1 for r in results.values()
               if r.get("status") == "shed"
               or str(r.get("error") or "").startswith("shed"))
    status_counts: Dict[str, int] = {}
    for r in results.values():
        st = r.get("status") or ("ok" if not r.get("error") else "failed")
        status_counts[st] = status_counts.get(st, 0) + 1
    return {
        "status_counts": status_counts,
        "wall_seconds": round(wall, 3),
        "qps": round(len(ok) / wall, 3) if wall > 0 else None,
        "responses_ok": len(ok),
        "dropped": len(schedule) - len(ok) - shed,
        "shed": shed,
        "failover_gave_up": state["gave_up"],
        "rejected_retries": state["retries"],
        "p50_ms": pct(0.50), "p95_ms": pct(0.95), "p99_ms": pct(0.99),
        "tier_latency_ms": tier_latency_ms,
        "l1_hit_frac": (round(tiers.count("l1") / len(ok), 4)
                        if ok else None),
        "l2_hit_frac": (round(tiers.count("l2") / len(ok), 4)
                        if ok else None),
        "adapt_frac": (round(tiers.count(None) / len(ok), 4)
                       if ok else None),
        "per_replica_responses": {
            str(rid): sum(1 for r in ok if r.get("rid") == rid)
            for rid in sorted(conns)},
    }


# ---------------------------------------------------------------------------
# the migration leg
# ---------------------------------------------------------------------------

def migration_check(router, controller, conns: Dict[int, ReplicaConn],
                    pool, seed: int, image_shape) -> dict:
    """Prove the L2 tier across a drain: serve one tenant on its ring
    primary A, drain A (lease tombstone), serve the SAME tenant again —
    it must land on a different replica AND come back from the l2 tier
    with zero new adapt dispatches on the target."""
    import numpy as np
    rng = np.random.RandomState(seed + 999)
    router.refresh()
    sx, sy, q_rows = pool[0]
    key = _router_mod.routing_key(sx, sy)
    primary = router.ring.primary(key)
    if primary is None or primary not in conns:
        return {"ok": False, "reason": "no primary for tenant"}

    done = threading.Event()
    box: Dict[str, Any] = {}

    def on_response(rid, msg):
        router.complete(rid)
        box["resp"] = msg
        box["rid"] = rid
        done.set()

    def ask(rid: int, cid: int) -> dict:
        _, _, qx = synthetic_arrays(image_shape, 3, True, rng,
                                    (1, q_rows))
        for conn in conns.values():
            conn._on_response = on_response
        done.clear()
        conns[rid].send({"op": "serve", "id": cid, "support_x": sx,
                         "support_y": sy, "query_x": qx})
        if not done.wait(120):
            raise TimeoutError("migration request timed out")
        return dict(box["resp"], rid=box["rid"])

    # Warm the tenant on its primary (adapts or hits there; publishes
    # the adaptation to L2 either way — a fresh adapt publishes, a hit
    # means an earlier adapt already did).
    first = ask(primary, 10_000_000)
    controller.drain(primary, reason="migration_check")
    router.refresh()
    target = router.ring.primary(key)
    if target is None or target == primary:
        controller.undrain(primary)
        return {"ok": False, "reason": f"drain did not move the tenant "
                                       f"(target={target})"}
    before = conns[target].stats()["stats"]["adapt_invocations"]
    second = ask(target, 10_000_001)
    after = conns[target].stats()["stats"]["adapt_invocations"]
    controller.undrain(primary)
    router.refresh()
    return {
        "ok": bool(second.get("cache_tier") == "l2"
                   and after == before and not second.get("error")),
        "tenant_key": key[:16],
        "from_replica": primary, "to_replica": target,
        "first_tier": first.get("cache_tier"),
        "second_tier": second.get("cache_tier"),
        "target_adapt_delta": int(after - before),
    }


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def run_leg(out, cfg_path, ckpt_dir, fleet_dir, ids, schedule, registry,
            *, image_shape,
            swap_spec=None, pool=None, migration=False,
            startup_timeout_s=420.0):
    """Boot a replica set, drive the schedule, optionally swap/migrate,
    tear down. Returns (leg stats, per-replica stats, extras). The
    router's ring/threshold knobs come from the SAME config json the
    replicas run (the fleet_* knobs; defaults mirror
    config.effective_fleet_* — re-derived here because this driver is
    jax-free and cannot build a MAMLConfig)."""
    with open(cfg_path) as f:
        cfg_doc = json.load(f)
    interval = float(cfg_doc.get("fleet_lease_interval_s") or 0.5)
    stalled = float(cfg_doc.get("fleet_replica_stalled_s") or 0.0) \
        or 3.0 * interval
    dead = max(float(cfg_doc.get("fleet_replica_dead_s") or 0.0)
               or 6.0 * interval, stalled)
    os.makedirs(fleet_dir, exist_ok=True)
    # Request tracing (telemetry/reqtrace.py): the driver's spans must
    # land in the SAME module object the wire protocol records into, so
    # the ring installs into _router_mod.reqtrace_mod() — never a
    # second file-path copy.
    rate = float(cfg_doc.get("reqtrace_sample_rate") or 0.0)
    rt = _router_mod.reqtrace_mod() if rate > 0 else None
    ring = prev_ring = None
    if rt is not None:
        ring = rt.SpanRing(capacity=16384, registry=registry)
        prev_ring = rt.install(ring)
    procs = start_replicas(out, cfg_path, ckpt_dir, fleet_dir, ids)
    extras: Dict[str, Any] = {}
    conns: Dict[int, ReplicaConn] = {}
    try:
        ports = wait_for_replicas(fleet_dir, ids, procs,
                                  startup_timeout_s)
        for rid, port in ports.items():
            conns[rid] = ReplicaConn(rid, port, lambda *_: None)
        router = _router_mod.FleetRouter(
            fleet_dir, vnodes=int(cfg_doc.get("fleet_vnodes") or 64),
            load_factor=float(cfg_doc.get("fleet_load_factor") or 1.25),
            stalled_after_s=stalled, dead_after_s=dead,
            registry=registry)
        controller = _controller_mod.FleetController(
            fleet_dir, router.refresh, registry=registry,
            slo_p95_ms=float(cfg_doc.get("fleet_slo_p95_ms")
                             or 2000.0),
            slo_target_frac=float(cfg_doc.get("fleet_slo_target_frac")
                                  or 0.95))
        router.refresh()

        swap_trigger = None
        if swap_spec is not None:
            child_box: Dict[str, Any] = {}

            def fire():
                # Publish v2 OFF the driver's critical path (a jax
                # child takes seconds to boot); the rollout starts as
                # soon as the publish lands, while load keeps flowing.
                def _worker():
                    _run_child("publish-v2", cfg_path, ckpt_dir, out)
                    with open(os.path.join(out, "publish-v2.log")) as f:
                        last = [ln for ln in f.read().splitlines()
                                if ln.strip()][-1]
                    version = int(json.loads(last)["version"])
                    controller.start_rollout(version)
                    child_box["version"] = version
                t = threading.Thread(target=_worker, daemon=True)
                child_box["thread"] = t
                t.start()
            swap_trigger = {"at_completed": swap_spec["at_completed"],
                            "fire": fire}
        stats = drive_leg(router, conns, schedule,
                          max_outstanding=swap_spec["max_outstanding"]
                          if swap_spec else 4 * len(ids),
                          controller=controller,
                          swap_trigger=swap_trigger,
                          reqtrace=rt, sample_rate=rate,
                          slo=controller.slo)
        if swap_spec is not None:
            # The publish child may still be landing when the load
            # drains (mid-load means it STARTED under load): wait for
            # it, then tick the rollout to completion.
            worker = child_box.get("thread")
            if worker is not None:
                worker.join(timeout=180)
            deadline = time.monotonic() + 180
            doc = controller.read_rollout()
            while doc["state"] == _controller_mod.ROLLING \
                    and time.monotonic() < deadline:
                router.refresh()
                doc = controller.tick()
                time.sleep(0.1)
            extras["rollout"] = {k: doc.get(k) for k in
                                 ("state", "version", "index", "rejected",
                                  "halt_reason", "halt_detail",
                                  "halt_replica")}
            extras["swap_version"] = child_box.get("version")
        if migration and pool is not None:
            extras["migration"] = migration_check(
                router, controller, conns, pool, seed=0,
                image_shape=image_shape)
        controller.publish_signals()
        per_replica = {}
        for rid, conn in conns.items():
            try:
                per_replica[str(rid)] = conn.stats()
            except Exception as e:  # noqa: BLE001
                per_replica[str(rid)] = {"error": str(e)}
        extras["advice"] = _controller_mod.advise(
            controller.publish_signals(), live=len(router.routable))
        extras["slo"] = controller.slo.snapshot()
        extras["slo_burn_rate"] = controller.slo.burn_rate()
        return stats, per_replica, extras
    finally:
        stop_replicas(conns, procs)
        if rt is not None:
            # Driver-side spans (route, wire both directions, roots)
            # land next to the replicas' events files — slo_report.py
            # and the linked-trace gate read the whole set.
            ring.flush(_tracing_mod.JsonlLogger(
                os.path.join(out, "events_driver.jsonl")),
                phase="fleet_driver", replica="driver")
            rt.install(prev_ring)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-replica serving fleet benchmark")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--tenants", type=int, default=24)
    ap.add_argument("--l1-capacity", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--quick", action="store_true",
                    help="2-replica CI smoke: no single leg, no "
                         "hot-swap leg, small load")
    ap.add_argument("--trace-sample-rate", type=float, default=0.0,
                    help="head-based request-trace sampling rate in "
                         "[0, 1]; 0 (default) = tracing off, bitwise-"
                         "identical serving")
    ap.add_argument("--skip-single", action="store_true")
    ap.add_argument("--no-swap", action="store_true")
    # jax-side child plumbing (internal)
    ap.add_argument("--mode", default="bench",
                    choices=["bench", "prepare", "publish-v2"])
    ap.add_argument("--config-path", default=None)
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args(argv)

    if args.mode == "prepare":
        return _mode_prepare(args.config_path, args.ckpt_dir)
    if args.mode == "publish-v2":
        return _mode_publish_v2(args.config_path, args.ckpt_dir)

    if args.quick:
        args.replicas = min(args.replicas, 2)
        args.requests = min(args.requests, 36)
        args.tenants = min(args.tenants, 8)
        args.skip_single = True
        args.no_swap = True

    artifact: Dict[str, Any] = {
        "metric": "fleet_bench", "value": None, "unit": "requests/s",
        "status": "failed", "replicas": args.replicas,
        "requests": args.requests, "tenants": args.tenants,
        "l1_capacity": args.l1_capacity, "quick": bool(args.quick),
        "trace_sample_rate": float(args.trace_sample_rate),
    }
    if not _can_bind_localhost():
        # No localhost sockets, no fleet: record the skip honestly
        # (the chaos_pod.py rule) instead of failing the harness.
        artifact.update({"status": "skipped",
                         "skip_reason": "cannot bind localhost sockets"})
        print(json.dumps(artifact), flush=True)
        return 0

    out = args.out or tempfile.mkdtemp(prefix="fleet_bench_")
    made_tmp = args.out is None
    os.makedirs(out, exist_ok=True)
    ckpt_dir = os.path.join(out, "saved_models")
    l2_dir = os.path.join(out, "l2")
    cfg_fleet = os.path.join(out, "cfg_fleet.json")
    cfg_single = os.path.join(out, "cfg_single.json")
    with open(cfg_fleet, "w") as f:
        json.dump(fleet_cfg_dict(
            out, quick=args.quick, l1_capacity=args.l1_capacity,
            l2_dir=l2_dir,
            trace_sample_rate=args.trace_sample_rate), f)
    # The single leg stays untraced: it is the BASELINE — its wire
    # bytes and engine behavior must match the pre-fleet architecture.
    with open(cfg_single, "w") as f:
        json.dump(fleet_cfg_dict(out, quick=args.quick,
                                 l1_capacity=args.l1_capacity,
                                 l2_dir=""), f)

    registry = _MiniMetrics()
    try:
        t_prep = time.monotonic()
        _run_child("prepare", cfg_fleet, ckpt_dir, out)
        artifact["prepare_seconds"] = round(time.monotonic() - t_prep, 1)
        cfg_doc = fleet_cfg_dict(out, quick=args.quick,
                                 l1_capacity=args.l1_capacity,
                                 l2_dir=l2_dir)
        image_shape = (cfg_doc["image_height"], cfg_doc["image_width"],
                       cfg_doc["image_channels"])
        pool, schedule = build_schedule(args.requests, args.tenants,
                                        args.seed, image_shape,
                                        bench_bucket(args.quick))

        single = None
        if not args.skip_single:
            single, _, _ = run_leg(
                out, cfg_single, ckpt_dir,
                os.path.join(out, "fleet_single"), [0], schedule,
                _MiniMetrics(), image_shape=image_shape)

        ids = list(range(args.replicas))
        swap_spec = None
        if not args.no_swap:
            # Fire early: the publish child needs seconds to boot jax,
            # and the rolling swap must run UNDER load to prove the
            # zero-drop claim.
            swap_spec = {"at_completed": max(args.requests // 6, 1),
                         "max_outstanding": 4 * len(ids)}
        fleet, per_replica, extras = run_leg(
            out, cfg_fleet, ckpt_dir, os.path.join(out, "fleet"),
            ids, schedule, registry, image_shape=image_shape,
            swap_spec=swap_spec, pool=pool, migration=True)

        reg_snap = registry.snapshot()
        speedup = (round(fleet["qps"] / single["qps"], 2)
                   if single and single.get("qps") else None)
        migration = extras.get("migration") or {}
        rollout = extras.get("rollout") or {}
        zero_dropped = (fleet["dropped"] == 0
                        and (single is None or single["dropped"] == 0))

        # Linked-trace verdict (the FLEET-style proof): every sampled
        # request must have left a causally-complete span set across
        # driver + replica events files, and the tier sums name WHERE
        # the latency went.
        trace_summary = None
        if args.trace_sample_rate > 0:
            rt = _router_mod.reqtrace_mod()
            rows = []
            for name in sorted(os.listdir(out)):
                if name.endswith(".jsonl"):
                    rows += [r for r in _tracing_mod.read_jsonl(
                                 os.path.join(out, name))
                             if r.get("event")
                             == rt.REQUEST_TRACE_EVENT]
            traces = rt.assemble(rows)
            n_linked = sum(1 for t in traces.values() if rt.linked(t))
            tier_seconds = {tier: 0.0 for tier in rt.TIERS}
            for t in traces.values():
                if rt.linked(t):
                    attr = rt.attribute(t)
                    for tier in rt.TIERS:
                        tier_seconds[tier] += attr[tier]
            trace_summary = {
                "count": len(traces),
                "linked": n_linked,
                "linked_frac": (round(n_linked / len(traces), 4)
                                if traces else 0.0),
                "dominant_tier": (max(rt.TIERS,
                                      key=lambda k: tier_seconds[k])
                                  if n_linked else None),
                "tier_seconds": {k: round(v, 4)
                                 for k, v in tier_seconds.items()},
            }
        trace_ok = (trace_summary is None
                    or (trace_summary["count"] > 0
                        and trace_summary["linked_frac"] >= 0.95))

        ok = bool(fleet["responses_ok"] == args.requests
                  and zero_dropped
                  and migration.get("ok", args.quick)
                  and (args.no_swap or rollout.get("state") == "done")
                  and trace_ok)
        artifact.update({
            "status": "ok" if ok else "failed",
            "value": fleet["qps"],
            "single": single, "fleet": fleet,
            "single_qps": single["qps"] if single else None,
            "fleet_qps": fleet["qps"],
            "fleet_speedup_vs_single": speedup,
            "fleet_l2_hit_frac": fleet["l2_hit_frac"],
            "fleet_rolling_swaps": int(
                reg_snap.get(_controller_mod.SWAPS_COUNTER, 0)),
            "fleet_rolling_swap_halts": int(
                reg_snap.get(_controller_mod.HALTS_COUNTER, 0)),
            "fleet_router_spills": int(
                reg_snap.get(_router_mod.SPILLS_COUNTER, 0)),
            # Schema-stable robustness keys (chaos_fleet.py fills the
            # same names from its own legs): failovers come from the
            # router's counter; this bench runs no supervisor and no
            # shed policy, so those two are honestly null, not 0.
            "fleet_failover_count": int(
                reg_snap.get(_router_mod.FAILOVERS_COUNTER, 0)),
            "fleet_shed_count": None,
            "fleet_restarts": None,
            "fleet_trace_count": (trace_summary["count"]
                                  if trace_summary else None),
            "fleet_trace_linked_frac": (trace_summary["linked_frac"]
                                        if trace_summary else None),
            "fleet_trace_dominant_tier": (trace_summary["dominant_tier"]
                                          if trace_summary else None),
            "fleet_trace_tier_seconds": (trace_summary["tier_seconds"]
                                         if trace_summary else None),
            "fleet_slo_burn_rate": extras.get("slo_burn_rate"),
            "fleet_slo_tenants": extras.get("slo"),
            # Traffic-lab keys (scripts/traffic_replay.py fills them):
            # this bench drives closed-loop load with no replayer, no
            # continuous batching and no weighted split — honestly null.
            "traffic_p95_ms": None,
            "traffic_slo_held": None,
            "traffic_canary_weight_final": None,
            "traffic_cb_groups": None,
            # Alert keys (scripts/chaos_fleet.py fills them): this
            # bench installs no alert evaluator — honestly null.
            "alerts_fired": None,
            "alerts_resolved": None,
            "alerts_active_final": None,
            "rollout": rollout or None,
            "migration": migration or None,
            "zero_dropped": zero_dropped,
            "per_replica": per_replica,
            "autoscale_advice": extras.get("advice"),
            "fleet_metrics": reg_snap,
            "out_dir": None if made_tmp else out,
        })
        print(json.dumps(artifact), flush=True)
        if made_tmp:
            shutil.rmtree(out, ignore_errors=True)
        return 0 if ok else 1
    except Exception as e:  # noqa: BLE001 — the artifact IS the report
        artifact.update({"status": "failed",
                         "error": f"{type(e).__name__}: {e}",
                         "out_dir": out})
        print(json.dumps(artifact), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main())

"""Sharded full-pyramid test runner: the whole suite in one session.

The full pyramid (tier-1 quick profile + the `slow` system tests) is
~30+ min of wall-clock on a 1-core box — past what a single pytest
invocation survives inside CI session budgets, and a single process
also accumulates jit-cache/thread state across 200+ tests. This runner
splits the suite into per-file shards, runs each as a FRESH pytest
subprocess (bounded memory, independent timeouts, a hang kills one
shard not the session), streams everything into one archived log, and
emits the bench.py-style last-JSON-line artifact:

    {"metric": "pyramid", "passed": N, "failed": N, ...}

Usage:

    python scripts/run_pyramid.py                      # full pyramid
    python scripts/run_pyramid.py --profile quick      # -m 'not slow'
    python scripts/run_pyramid.py --shard 2/4          # this shard only
    python scripts/run_pyramid.py --archive docs/measurements/r6

With ``pytest-xdist`` installed, ``--xdist N`` forwards ``-n N`` to
each shard instead (process-parallel within the shard); the subprocess
sharding needs no extra dependency and is the default — this container
ships no xdist (VERDICT Next #5: the 234-test suite must complete in
one session, with the round's full-run log archived under
docs/measurements/).

Exit 0 iff every shard ran and nothing failed or errored.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Counts pytest prints on its summary line, e.g.
# "== 12 passed, 2 skipped, 1 xfailed in 34.56s ==".
_SUMMARY_RE = re.compile(
    r"(\d+) (passed|failed|errors?|skipped|xfailed|xpassed|deselected|"
    r"warnings?)")

PROFILES = {
    "full": None,            # the whole pyramid, slow tests included
    "quick": "not slow",     # the tier-1 profile
    "core": "core",          # the <5-minute pre-commit gate
}

# Tier-1 time-budget tripwire: the driver runs the quick profile under
# `timeout -k 10 870` (ROADMAP.md § Tier-1 verify). Past this floor the
# suite is one slow new test away from a silent timeout-kill, so a
# quick-profile run whose summed shard wall-clock crosses it warns
# loudly and stamps the artifact — new tests must fit the headroom or
# ride the slow profile.
TIER1_DRIVER_BUDGET_S = 870.0
TIER1_WARN_S = 800.0


# Shard-size table: byte size is the balance heuristic, but wall-clock
# does not track bytes for files dominated by SUBPROCESS system tests
# (each spawns jax-importing children; the file itself stays small).
# Entries here override the on-disk size with an effective byte weight
# so round-robin keeps two subprocess-heavy files out of one shard.
# Weights are relative to the big unit-test files (~40-60 KB).
SHARD_SIZE_OVERRIDES = {
    "tests/test_fleet.py": 120_000,        # 2-replica fleet smoke + the
    #                                        slow 3-replica swap proof
    "tests/test_pod_e2e.py": 120_000,      # multi-process chaos runs
    "tests/test_multiprocess_distributed.py": 90_000,
    "tests/test_perf_profiler.py": 60_000,  # tiny profiled runs + the
    #                                         perf_report CLI subprocess
    "tests/test_tune.py": 120_000,          # the slow sweep smoke runs
    #                                         real bench --quick children
    #                                         (~80s each) + a resume leg
    "tests/test_reqtrace.py": 120_000,      # traced 2-replica fleet
    #                                         smoke + slo_report CLI
    #                                         subprocesses
    "tests/test_fleet_supervisor.py": 120_000,  # slow chaos_fleet
    #                                         --quick proof: three
    #                                         real-replica phases,
    #                                         several minutes
    "tests/test_algos.py": 60_000,          # slow half compiles the
    #                                         flagship train step twice
    #                                         (bitwise pin) + two
    #                                         serving engines (ANIL
    #                                         serve comparison)
    "tests/test_traffic_lab.py": 120_000,   # batcher/canary units plus
    #                                         a jax-free subprocess
    #                                         booby-trap proof
    "tests/test_alerts.py": 120_000,        # rule-engine units + the
    #                                         ops_console CLI subprocess
    #                                         + the slow bitwise
    #                                         alerts-on/off parity run
}


def collect_shards(n_shards: int) -> list:
    """Per-file shards, round-robin over the size-sorted file list so
    the heavy system-test files spread across shards instead of
    stacking in one (sizes from disk, overridden by the table above
    for subprocess-heavy files)."""
    files = sorted(glob.glob(os.path.join(_REPO, "tests", "test_*.py")))

    def weight(f: str) -> int:
        return SHARD_SIZE_OVERRIDES.get(
            os.path.relpath(f, _REPO).replace(os.sep, "/"),
            os.path.getsize(f))

    files.sort(key=weight, reverse=True)
    shards = [[] for _ in range(max(n_shards, 1))]
    for i, f in enumerate(files):
        shards[i % len(shards)].append(os.path.relpath(f, _REPO))
    return [sorted(s) for s in shards if s]


def run_shard(index: int, files: list, marker, timeout_s: float,
              xdist: int, log_fh) -> dict:
    cmd = [sys.executable, "-m", "pytest", "-q",
           "--continue-on-collection-errors", "-p", "no:cacheprovider",
           "-p", "no:randomly"] + files
    if marker:
        cmd += ["-m", marker]
    if xdist:
        cmd += ["-n", str(xdist)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.monotonic()
    header = (f"\n===== shard {index}: {len(files)} file(s) =====\n"
              f"$ {' '.join(cmd)}\n")
    log_fh.write(header)
    log_fh.flush()
    counts = {"passed": 0, "failed": 0, "errors": 0, "skipped": 0,
              "xfailed": 0, "xpassed": 0, "deselected": 0}
    try:
        proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                              capture_output=True, timeout=timeout_s)
        out = proc.stdout + proc.stderr
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries BYTES on Python < 3.12 even under
        # text=True; concatenating raw would TypeError and kill the
        # whole runner exactly when a shard hangs.
        def _txt(s):
            return s.decode(errors="replace") if isinstance(s, bytes) \
                else (s or "")
        out = (_txt(e.stdout) + _txt(e.stderr)
               + f"\n[pyramid] shard {index} TIMED OUT after "
                 f"{timeout_s:.0f}s\n")
        rc = -1
        counts["errors"] += 1
    log_fh.write(out)
    log_fh.flush()
    for m in _SUMMARY_RE.finditer(out):
        key = m.group(2).rstrip("s") if m.group(2).startswith("error") \
            else m.group(2).rstrip()
        key = "errors" if key == "error" else key
        if key in counts:
            counts[key] += int(m.group(1))
    # pytest exit 5 = "no tests collected" (a fully-deselected shard
    # under -m) — not a failure.
    ok = rc in (0, 5) and counts["failed"] == 0 and counts["errors"] == 0
    return {"shard": index, "files": len(files), "rc": rc, "ok": ok,
            "seconds": round(time.monotonic() - t0, 1), **counts}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sharded full-pyramid pytest runner with archived "
                    "log + JSON artifact")
    ap.add_argument("--profile", choices=sorted(PROFILES), default="full",
                    help="marker filter: full (default, everything), "
                         "quick (-m 'not slow'), core")
    ap.add_argument("--shards", type=int, default=6,
                    help="number of per-file shard subprocesses")
    ap.add_argument("--shard", default=None, metavar="I/N",
                    help="run only shard I of N (1-based; CI fan-out)")
    ap.add_argument("--shard-timeout", type=float, default=2400.0,
                    help="seconds per shard subprocess")
    ap.add_argument("--xdist", type=int, default=0,
                    help="forward -n N to pytest (requires pytest-xdist; "
                         "0 = off, the no-dependency default)")
    ap.add_argument("--archive", default=None, metavar="DIR",
                    help="directory to archive the full run log under "
                         "(e.g. docs/measurements/r6); default: "
                         "/tmp, not archived")
    args = ap.parse_args(argv)

    if args.xdist:
        try:
            import xdist  # noqa: F401
        except ImportError:
            print(json.dumps({"metric": "pyramid", "ok": False,
                              "error": "--xdist requested but "
                                       "pytest-xdist is not installed"}))
            return 1

    n_shards = args.shards
    only = None
    if args.shard:
        try:
            i_s, n_s = args.shard.split("/")
            only, n_shards = int(i_s), int(n_s)
            if not 1 <= only <= n_shards:
                raise ValueError
        except ValueError:
            print(json.dumps({"metric": "pyramid", "ok": False,
                              "error": f"--shard expects I/N with "
                                       f"1<=I<=N, got {args.shard!r}"}))
            return 1

    shards = collect_shards(n_shards)
    if only is not None and only > len(shards):
        # Empty shards are dropped, so with more requested shards than
        # test files a high index enumerates nothing — that must be an
        # explicit error, not a zero-tests "ok": false with no cause.
        print(json.dumps({"metric": "pyramid", "ok": False,
                          "error": f"--shard {args.shard}: only "
                                   f"{len(shards)} non-empty shard(s) "
                                   f"exist at this shard count"}))
        return 1
    log_dir = args.archive or "/tmp"
    os.makedirs(log_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%d_%H%M%S")
    log_path = os.path.join(
        log_dir, f"pyramid_{args.profile}_{stamp}.log")

    t0 = time.monotonic()
    results = []
    with open(log_path, "w") as log_fh:
        log_fh.write(f"full-pyramid run: profile={args.profile} "
                     f"shards={len(shards)} "
                     f"{time.strftime('%Y-%m-%d %H:%M:%S')}\n")
        for i, files in enumerate(shards, start=1):
            if only is not None and i != only:
                continue
            res = run_shard(i, files, PROFILES[args.profile],
                            args.shard_timeout, args.xdist, log_fh)
            results.append(res)
            print(json.dumps(res), flush=True)

    total = {k: sum(r[k] for r in results)
             for k in ("passed", "failed", "errors", "skipped",
                       "xfailed", "xpassed", "deselected")}
    ok = bool(results) and all(r["ok"] for r in results)
    # Tier-1 budget tripwire: the quick profile's summed shard seconds
    # approximate one sequential `pytest -m 'not slow'` run — the thing
    # the 870s driver timeout actually kills. Recorded for every
    # profile; warned only for quick (the full profile legitimately
    # runs for an hour+).
    shard_seconds = {str(r["shard"]): r["seconds"] for r in results}
    tier1_seconds = round(sum(r["seconds"] for r in results), 1)
    tier1_exceeded = (args.profile == "quick"
                      and only is None
                      and tier1_seconds > TIER1_WARN_S)
    if tier1_exceeded:
        print(f"[pyramid] WARNING: tier-1 profile took {tier1_seconds:.0f}s"
              f" > {TIER1_WARN_S:.0f}s of the {TIER1_DRIVER_BUDGET_S:.0f}s"
              f" driver budget — trim or slow-mark tests before the "
              f"driver timeout starts killing the suite",
              file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "pyramid",
        "value": total["passed"],
        "unit": "tests_passed",
        "ok": ok,
        "profile": args.profile,
        "shards_run": len(results),
        "shards_total": len(shards),
        **total,
        "seconds": round(time.monotonic() - t0, 1),
        "shard_seconds": shard_seconds,
        "tier1_budget_warn_s": TIER1_WARN_S,
        "tier1_budget_exceeded": tier1_exceeded,
        "log": log_path,
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Mount burn-down: the moment `/root/reference/` stops being empty,
turn every round's accumulated uncertainty into a ranked TODO in minutes.

Context (VERDICT r3 item 8): the reference mount has been empty every
round, so 14 behavioral assumptions live in MOUNT-AUDIT.md and the
mechanical copy-check has been vacuous. This script, run against a
populated mount (or any fixture tree):

1. re-runs a local copy-similarity check of this repo's non-test sources
   against same-named / similar-sized reference files (difflib ratio,
   >60% flags — the same thresholds the driver's detector documents),
2. parses MOUNT-AUDIT.md's assumption table and checks which reference
   files each open item needs, and whether they now exist in the mount,
3. prints a ranked TODO: verifiable-now items first (their reference
   files are present), then blocked items, then resolved ones skipped.

Usage: python scripts/mount_burndown.py [--ref /root/reference]
           [--repo /root/repo] [--json]
Exit 0 with "mount still empty" when there is nothing to do.
"""

from __future__ import annotations

import argparse
import difflib
import json
import os
import re
import sys

SIMILARITY_FLAG = 0.60       # driver detector's documented threshold
SIZE_RATIO_WINDOW = (0.5, 2.0)  # "similar-sized" candidate window
_SOURCE_EXTS = (".py", ".cc", ".cpp", ".h", ".json", ".sh")
# Repo walk: our tests/ are not candidate copies. Reference walk: its
# tests/ ARE files to verify against, but VCS/cache junk still is not
# (an rsynced clone's .git objects must not flip the emptiness check).
_SKIP_DIRS = {"tests", ".git", "__pycache__", ".claude"}
_REF_SKIP_DIRS = {".git", "__pycache__", ".claude"}


def find_files(root: str, exts=None, skip_dirs=frozenset()) -> list:
    """Walk ``root`` for files; callers pass ``_SKIP_DIRS`` for the repo
    (our tests/ are not candidate copies) and ``_REF_SKIP_DIRS`` for the
    mount (its tests/ count, VCS/cache junk never does)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in skip_dirs]
        for f in filenames:
            if exts is None or f.endswith(exts):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def _norm_lines(path: str) -> list:
    """Comparison form: stripped non-blank lines (whitespace/reflow noise
    removed so renamed-copy similarity still registers)."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            return [ln.strip() for ln in fh if ln.strip()]
    except OSError:
        return []


def copy_check(repo: str, ref: str) -> list:
    """Flag repo sources >SIMILARITY_FLAG similar to a same-named or
    similar-sized reference file. Returns [{repo_file, ref_file, ratio}]."""
    # Source files only on BOTH sides: a mount shipping its datasets
    # (thousands of images/checkpoints) must not enter the candidate
    # pool or the line cache.
    ref_files = find_files(ref, _SOURCE_EXTS,
                           skip_dirs=_REF_SKIP_DIRS)
    ref_by_name = {}
    for p in ref_files:
        ref_by_name.setdefault(os.path.basename(p), []).append(p)
    ref_sizes = [(p, os.path.getsize(p)) for p in ref_files]

    ref_lines = {}  # decoded-once cache: most ref files are candidates
                    # for many repo files under the size window

    flags = []
    for rp in find_files(repo, _SOURCE_EXTS, skip_dirs=_SKIP_DIRS):
        size = os.path.getsize(rp)
        cands = set(ref_by_name.get(os.path.basename(rp), []))
        for p, s in ref_sizes:
            lo, hi = SIZE_RATIO_WINDOW
            if size and lo <= s / size <= hi:
                cands.add(p)
        if not cands:
            continue
        mine = _norm_lines(rp)
        if not mine:
            continue
        # One matcher per repo file: set_seq2 precomputes the line index
        # once; the quick_ratio gates skip the quadratic ratio() for the
        # (vast majority of) pairs that cannot clear the flag threshold.
        matcher = difflib.SequenceMatcher(None, autojunk=False)
        matcher.set_seq2(mine)
        best, best_ratio = None, 0.0
        for cand in cands:
            if cand not in ref_lines:
                ref_lines[cand] = _norm_lines(cand)
            theirs = ref_lines[cand]
            if not theirs:
                continue
            matcher.set_seq1(theirs)
            if (matcher.real_quick_ratio() <= SIMILARITY_FLAG
                    or matcher.quick_ratio() <= SIMILARITY_FLAG):
                continue
            ratio = matcher.ratio()
            if ratio > best_ratio:
                best, best_ratio = cand, ratio
        if best is not None and best_ratio > SIMILARITY_FLAG:
            flags.append({"repo_file": os.path.relpath(rp, repo),
                          "ref_file": os.path.relpath(best, ref),
                          "ratio": round(best_ratio, 3)})
    return sorted(flags, key=lambda d: -d["ratio"])


_ROW = re.compile(r"^\|\s*(\d+)\s*\|(.+)\|(.+)\|(.+)\|\s*$")
_REF_FILE = re.compile(r"([\w./-]+\.(?:py|json|sh|md))")


def parse_audit(audit_path: str, repo: str = None) -> list:
    """MOUNT-AUDIT.md table rows -> [{num, assumption, where, verify,
    resolved, ref_files}]. ``ref_files`` are file names mentioned in the
    what-to-verify column (the files to open in the mount); paths that
    exist in THIS repo (e.g. a ``docs/PARITY.md`` or ``bench.py``
    cross-reference) are excluded — they are repo citations, not mount
    files, and counting them would misrank the TODO."""
    if repo is None:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    items = []
    with open(audit_path, "r", encoding="utf-8") as fh:
        for line in fh:
            m = _ROW.match(line.strip())
            if not m:
                continue
            num, assumption, where, verify = (g.strip()
                                              for g in m.groups())
            files = sorted(
                f for f in set(_REF_FILE.findall(verify))
                if not os.path.exists(os.path.join(repo, f)))
            items.append({
                "num": int(num),
                "assumption": assumption,
                "where": where,
                "verify": verify,
                "resolved": assumption.startswith("~~"),
                "ref_files": files,
            })
    return items


def rank_items(items: list, ref: str) -> list:
    """Attach mount availability to each open item and rank: items whose
    reference files are ALL present first, then partially present, then
    blocked (none present); resolved items dropped."""
    present = {os.path.basename(p)
               for p in find_files(ref, skip_dirs=_REF_SKIP_DIRS)}
    ranked = []
    for it in items:
        if it["resolved"]:
            continue
        need = [os.path.basename(f) for f in it["ref_files"]]
        have = [f for f in need if f in present]
        it = dict(it, files_present=have,
                  files_missing=[f for f in need if f not in present])
        # availability: 2 = all files present (verify NOW), 1 = some,
        # 0 = none (or the item names no file — e.g. the baseline row).
        it["availability"] = (0 if not have
                              else 2 if len(have) == len(need) else 1)
        ranked.append(it)
    return sorted(ranked, key=lambda d: (-d["availability"], d["num"]))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args()

    ref_files = (find_files(args.ref, skip_dirs=_REF_SKIP_DIRS)
                 if os.path.isdir(args.ref) else [])
    if not ref_files:
        msg = {"mount": args.ref, "files": 0,
               "status": "mount still empty — nothing to burn down"}
        print(json.dumps(msg) if args.json else msg["status"])
        return 0

    flags = copy_check(args.repo, args.ref)
    audit = os.path.join(args.repo, "MOUNT-AUDIT.md")
    items = rank_items(parse_audit(audit, args.repo), args.ref) \
        if os.path.isfile(audit) else []

    if args.json:
        print(json.dumps({"mount": args.ref, "files": len(ref_files),
                          "copy_flags": flags, "todo": items}))
        return 0

    print(f"Mount {args.ref} holds {len(ref_files)} files — burn-down:\n")
    print(f"== Copy check ({len(flags)} flagged >"
          f"{SIMILARITY_FLAG:.0%} similarity) ==")
    for f in flags:
        print(f"  {f['ratio']:.0%}  {f['repo_file']}  ~  {f['ref_file']}")
    if not flags:
        print("  none flagged")
    print(f"\n== Ranked TODO ({len(items)} open MOUNT-AUDIT items) ==")
    tags = {2: "VERIFY NOW", 1: "PARTIAL", 0: "blocked"}
    for it in items:
        files = ", ".join(it["files_present"]) or "-"
        print(f"  [{tags[it['availability']]}] #{it['num']}: "
              f"{it['assumption'][:70]}")
        print(f"      open: {files}"
              + (f"  (missing: {', '.join(it['files_missing'])})"
                 if it["files_missing"] else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())

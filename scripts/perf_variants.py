"""Full-train-step timing across optimization variants (pipelined timing).

Variants: bn_fast_math on/off x remat policy. Used to pick shipped defaults.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import flagship_config, synthetic_batch
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, replicated_sharding, shard_batch)


def run_variant(cfg, steps):
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices()[:1])
    plan = make_sharded_steps(cfg, apply, mesh)
    train = plan.train_steps[(True, True)]
    state = jax.device_put(
        init_train_state(cfg, init, jax.random.PRNGKey(0)),
        replicated_sharding(mesh))
    ep = shard_batch(synthetic_batch(cfg, 0), mesh)
    epoch = jnp.float32(20.0)
    for _ in range(3):
        state, m = train(state, ep, epoch)
        float(jax.device_get(m.loss))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = train(state, ep, epoch)
    loss = float(jax.device_get(m.loss))
    dt = time.perf_counter() - t0
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}")
    return cfg.batch_size * steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()
    grid = [
        dict(bn_fast_math=False, remat_policy="nothing"),   # shipped today
        dict(bn_fast_math=True, remat_policy="nothing"),
        dict(bn_fast_math=False, remat_policy="block_outs"),
        dict(bn_fast_math=True, remat_policy="block_outs"),
    ]
    for over in grid:
        cfg = flagship_config(args.batch, 1).replace(**over)
        try:
            v = run_variant(cfg, args.steps)
            print(json.dumps({**over, "tasks_per_sec_per_chip": round(v, 2)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({**over, "error": str(e)[:160]}), flush=True)


if __name__ == "__main__":
    main()

"""Pallas fused BN+ReLU kernel (ops/pallas_fused.py), interpret mode.

The kernel's contract: identical numerics to the ``bn_fast_math`` composite
(f32 stats via E[x²]−E[x]², normalize in x.dtype, fused ReLU) and full
differentiability through ``jax.custom_jvp`` — including second order,
which the MAML++ meta-gradient requires.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.models import layers, make_model
from howtotrainyourmamlpytorch_tpu.ops.pallas_fused import (
    _bn_relu_reference, fused_bn_relu, supported)


@pytest.fixture(scope="module")
def data():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 4, 8, 48), jnp.float32) * 2 + 0.3
    gamma = jnp.linspace(0.5, 1.5, 48)
    beta = jnp.linspace(-0.2, 0.2, 48)
    return x, gamma, beta


def test_supported_shapes():
    assert supported(4 * 4 * 8, 48)      # 128 rows x 48 folds into 384
    assert not supported(5, 48)          # 240 flat elements % 384 != 0
    assert supported(2, 128)             # c multiple of lanes: always


def test_kernel_matches_composite(data):
    x, gamma, beta = data
    y_k, m_k, v_k = fused_bn_relu(x, gamma, beta, 1e-5, True)
    y_r, m_r, v_r = _bn_relu_reference(x, gamma, beta, 1e-5)
    np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(m_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_k), np.asarray(v_r), atol=1e-4)


def test_kernel_gradients_match_composite(data):
    x, gamma, beta = data

    def loss_k(x, g, b):
        return jnp.sum(fused_bn_relu(x, g, b, 1e-5, True)[0] ** 2)

    def loss_r(x, g, b):
        return jnp.sum(_bn_relu_reference(x, g, b, 1e-5)[0] ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_kernel_second_order_matches_composite(data):
    """grad-of-grad — what differentiating through the inner loop does."""
    x, gamma, beta = data

    def gn(loss):
        return jax.grad(
            lambda x: jnp.sum(jax.grad(loss)(x, gamma, beta) ** 2))(x)

    h_k = gn(lambda x, g, b: jnp.sum(fused_bn_relu(x, g, b, 1e-5, True)[0]
                                     ** 2))
    h_r = gn(lambda x, g, b: jnp.sum(_bn_relu_reference(x, g, b, 1e-5)[0]
                                     ** 2))
    np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_r),
                               rtol=1e-3, atol=1e-3)


def test_layer_level_matches_fast_math_plus_relu(data):
    x, _, _ = data
    params, state = layers.batch_norm_init(48, 3)
    y_ref, st_ref = layers.batch_norm_apply(params, state, x, jnp.int32(1),
                                            training=True, fast_math=True)
    y_ref = jax.nn.relu(y_ref)
    y_f, st_f = layers.fused_batch_norm_relu_apply(
        params, state, x, jnp.int32(1), training=True, interpret=True)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_ref),
                               atol=1e-5)
    for k in ("mean", "var"):
        np.testing.assert_allclose(np.asarray(st_f[k]),
                                   np.asarray(st_ref[k]), atol=1e-4)


def test_vgg_with_pallas_backend_runs_and_matches():
    """Full model forward with bn_backend='pallas' stays close to the
    fast_math composite model (same math, kernel execution)."""
    cfg = MAMLConfig(image_height=16, image_width=16, image_channels=1,
                     num_classes_per_set=3, num_samples_per_class=1,
                     num_target_samples=1, cnn_num_filters=16, num_stages=2,
                     compute_dtype="float32", bn_fast_math=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 1))

    init, apply = make_model(cfg)
    params, state = init(jax.random.PRNGKey(0))
    logits_ref, _ = apply(params, state, x, jnp.int32(0), True)

    cfg_p = cfg.replace(bn_backend="pallas")
    _, apply_p = make_model(cfg_p)
    logits_p, _ = apply_p(params, state, x, jnp.int32(0), True)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_layer_level_matches_fast_math_bf16():
    """The backend-equivalence contract in the dtype the flagship runs:
    bf16 inputs, scale/shift rounded to bf16, normalize in bf16."""
    key = jax.random.PRNGKey(5)
    x = (jax.random.normal(key, (8, 4, 4, 48)) * 2).astype(jnp.bfloat16)
    params, state = layers.batch_norm_init(48, 2)
    y_ref, _ = layers.batch_norm_apply(params, state, x, jnp.int32(0),
                                       training=True, fast_math=True)
    y_ref = jax.nn.relu(y_ref)
    y_f, _ = layers.fused_batch_norm_relu_apply(
        params, state, x, jnp.int32(0), training=True, interpret=True)
    assert y_f.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(y_f, np.float32),
                                  np.asarray(y_ref, np.float32))


@pytest.mark.parametrize("slope", [0.1, 1.0])
def test_kernel_leaky_and_identity_slopes(data, slope):
    """negative_slope generalization: 0.1 = resnet12's leaky-relu, 1.0 =
    no activation (pre-residual / skip-branch norms)."""
    x, gamma, beta = data
    y_k, m_k, v_k = fused_bn_relu(x, gamma, beta, 1e-5, True, slope)
    y_r, m_r, v_r = _bn_relu_reference(x, gamma, beta, 1e-5, slope)
    np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_r), atol=1e-5)
    if slope == 1.0:
        assert float(jnp.min(y_k)) < 0  # activation really absent

    def gn(loss):
        return jax.grad(
            lambda x: jnp.sum(jax.grad(loss)(x) ** 2))(x)

    h_k = gn(lambda x: jnp.sum(
        fused_bn_relu(x, gamma, beta, 1e-5, True, slope)[0] ** 2))
    h_r = gn(lambda x: jnp.sum(
        _bn_relu_reference(x, gamma, beta, 1e-5, slope)[0] ** 2))
    np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_r),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.slow  # two deep-backbone compiles (~20s, 1 core)
def test_resnet12_pallas_backend_matches_composite():
    """resnet12 with bn_backend='pallas' (fused leaky/identity norms) must
    match the fast_math composite model."""
    cfg = MAMLConfig(backbone="resnet12", image_height=16, image_width=16,
                     image_channels=3, num_classes_per_set=3,
                     cnn_num_filters=8, compute_dtype="float32",
                     bn_fast_math=True)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 16, 16, 3))
    init, apply = make_model(cfg)
    params, state = init(jax.random.PRNGKey(0))
    logits_ref, _ = apply(params, state, x, jnp.int32(0), True)

    _, apply_p = make_model(cfg.replace(bn_backend="pallas"))
    logits_p, _ = apply_p(params, state, x, jnp.int32(0), True)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_jvp_gated_by_variance_clamp():
    """Constant channels round E[x²]−E[x]² to ≤0; the primal clamps var to
    0 and the tangent rule must propagate zero there (not blow up through
    rsqrt(eps)³), matching the composite's jnp.maximum gradient."""
    x = jnp.ones((8, 4, 4, 48), jnp.float32) * 3.0  # zero variance
    gamma = jnp.ones((48,))
    beta = jnp.zeros((48,))

    def loss_k(x):
        return jnp.sum(fused_bn_relu(x, gamma, beta, 1e-5, True)[0])

    def loss_r(x):
        return jnp.sum(_bn_relu_reference(x, gamma, beta, 1e-5)[0])

    g_k = jax.grad(loss_k)(x)
    g_r = jax.grad(loss_r)(x)
    assert np.isfinite(np.asarray(g_k)).all()
    np.testing.assert_allclose(np.asarray(g_k), np.asarray(g_r),
                               rtol=1e-4, atol=1e-5)


def test_config_rejects_pallas_with_layer_norm():
    with pytest.raises(ValueError, match="pallas"):
        MAMLConfig(bn_backend="pallas", norm_layer="layer_norm")

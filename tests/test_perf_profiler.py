"""Perf lab contract (telemetry/profiler.py + scripts/perf_report.py).

Pure units (roofline math, cost-card schema round trip, trace
attribution, region indexing), the structural zero-cost pin
(``profile_every_n_steps=0`` installs NOTHING), the tier-1 bitwise
weight/compile-count parity proof (profiler on vs off over one tiny
store-armed run each — riding the test_health-style tiny fixture, no
new training geometry), cost cards landing in both the AOT store dir
and ``logs/PROFILE.json``, and the perf_report.py CLI artifact schema
through the real entrypoint (over the SAME tiny run — no extra
training)."""

import json
import os
import subprocess
import sys
import warnings

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.telemetry import profiler
from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# pure units


def test_resolve_peaks_table_and_source():
    pk = profiler.resolve_peaks("TPU v5 lite", env={})
    assert pk["source"] == "table"
    assert pk["peak_flops"] == 197e12
    assert pk["hbm_bytes_per_s"] == 819e9
    # Bare v5 reads as v5p (the bench.py ordering, preserved).
    assert profiler.resolve_peaks("TPU v5", env={})["peak_flops"] == 459e12


def test_resolve_peaks_override_wins_over_table():
    pk = profiler.resolve_peaks(
        "TPU v5 lite", env={profiler.PEAK_FLOPS_ENV: "4.56e14"})
    assert pk["source"] == "override"
    assert pk["peak_flops"] == 4.56e14
    # The table's bandwidth survives a flops-only override.
    assert pk["hbm_bytes_per_s"] == 819e9
    pk = profiler.resolve_peaks(
        "nonsense_chip_a", env={profiler.HBM_GBPS_ENV: "100"})
    assert pk["source"] == "override"
    assert pk["hbm_bytes_per_s"] == 100e9


def test_resolve_peaks_unknown_warns_once():
    kind = "never_seen_chip_xyz"
    with pytest.warns(UserWarning, match="matches no entry"):
        pk = profiler.resolve_peaks(kind, env={})
    assert pk == {"peak_flops": 0.0, "hbm_bytes_per_s": 0.0,
                  "source": "unknown"}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        profiler.resolve_peaks(kind, env={})
    assert not caught  # warn-once per kind per process


def test_roofline_verdict_boundaries():
    # ridge = 100e12 / 1e12 = 100 flops/byte.
    peak, bw = 100e12, 1e12
    at_ridge = profiler.roofline_verdict(100e9, 1e9, peak, bw)
    assert at_ridge["bound"] == "compute"  # AI == ridge: MXU-bound
    assert at_ridge["arithmetic_intensity"] == 100.0
    assert at_ridge["ceiling_flops_per_s"] == peak
    below = profiler.roofline_verdict(99e9, 1e9, peak, bw)
    assert below["bound"] == "memory"
    assert below["ceiling_flops_per_s"] == pytest.approx(99e12)
    # Unknown peaks / missing measurements never guess.
    assert profiler.roofline_verdict(1e9, 1e6, 0.0, bw)["bound"] == \
        "unknown"
    assert profiler.roofline_verdict(1e9, 0.0, peak, bw)["bound"] == \
        "unknown"
    assert profiler.roofline_verdict(0.0, 1e6, peak, bw)["bound"] == \
        "unknown"


def test_cost_card_schema_roundtrip(tmp_path):
    path = str(tmp_path / "PROFILE.json")
    peaks = profiler.resolve_peaks("TPU v4", env={})
    card = profiler.build_cost_card(
        "train_so1_msl0",
        flops_info={"flops": 1e12, "source": "hlo_trip_expanded",
                    "trip_counts": {"cond": 5}},
        bytes_accessed=1e9, memory={"peak_bytes": 123},
        fingerprint="abcd", device_kind="TPU v4", peaks=peaks)
    assert card["bound"] == "compute"  # AI 1000 >> v4 ridge ~224
    profiler.merge_profile(path, [card], device_kind="TPU v4",
                           peaks=peaks, fingerprint="abcd" * 16)
    doc = profiler.load_profile(path)
    assert doc["schema"] == profiler.PROFILE_SCHEMA
    assert doc["peak_flops_source"] == "table"
    assert doc["cards"]["train_so1_msl0"] == card
    # Merge semantics: same name updates, other names survive.
    other = dict(card, name="eval", flops=2.0)
    updated = dict(card, flops=3.0)
    profiler.merge_profile(path, [other, updated],
                           device_kind="TPU v4", peaks=peaks)
    doc = profiler.load_profile(path)
    assert set(doc["cards"]) == {"train_so1_msl0", "eval"}
    assert doc["cards"]["train_so1_msl0"]["flops"] == 3.0
    # Unreadable / foreign files degrade to None, never raise.
    assert profiler.load_profile(str(tmp_path / "missing.json")) is None
    (tmp_path / "foreign.json").write_text('{"schema": "other"}')
    assert profiler.load_profile(str(tmp_path / "foreign.json")) is None


def test_trace_window_attribution():
    idx = {"dot.3": "inner_support_grad", "add.4": "other"}
    events = [
        # two overlapping spans of the train module: union 90us
        {"ph": "X", "ts": 100.0, "dur": 50.0, "name": "dot.3",
         "args": {"hlo_module": "jit_step", "hlo_op": "dot.3"}},
        {"ph": "X", "ts": 140.0, "dur": 50.0, "name": "add.4",
         "args": {"hlo_module": "jit_step", "hlo_op": "add.4"}},
        # an unindexed module
        {"ph": "X", "ts": 200.0, "dur": 10.0, "name": "mul",
         "args": {"hlo_module": "jit_other", "hlo_op": "mul"}},
        # host spans without hlo_module are NOT device time
        {"ph": "X", "ts": 0.0, "dur": 500.0, "name": "PjitFunction(f)"},
    ]
    s = profiler.summarize_trace_events(
        events, wall_seconds=400e-6, region_indexes={"jit_step": idx})
    assert s["device_compute_seconds"] == pytest.approx(100e-6)
    # envelope [100, 210] = 110us -> idle 10us; gap = 400 - 110 = 290us
    assert s["device_idle_seconds"] == pytest.approx(10e-6)
    assert s["host_gap_seconds"] == pytest.approx(290e-6)
    assert s["device_compute_frac"] == pytest.approx(0.25)
    assert s["top_executable"] == "jit_step"
    assert s["per_executable_seconds"]["jit_step"] == \
        pytest.approx(100e-6)
    assert s["per_region_seconds"]["inner_support_grad"] == \
        pytest.approx(50e-6)
    assert s["per_region_seconds"][profiler.UNATTRIBUTED] == \
        pytest.approx(10e-6)
    # Empty window: everything is host gap, no crash.
    empty = profiler.summarize_trace_events([], wall_seconds=1e-3)
    assert empty["device_compute_seconds"] == 0.0
    assert empty["dispatch_gap_frac"] == pytest.approx(1.0)
    assert empty["top_executable"] is None


def test_trace_window_marker_clips_stale_spans():
    """Ops of the PREVIOUS step still in flight when the capture began
    lie outside the WINDOW_MARKER host span and must not attribute into
    this window (observed live: device_compute > wall without the
    clip). Straddling spans clip to their in-window part."""
    events = [
        {"ph": "X", "ts": 1000.0, "dur": 500.0,
         "name": profiler.WINDOW_MARKER},
        # entirely before the window: previous step's tail
        {"ph": "X", "ts": 0.0, "dur": 900.0, "name": "dot.1",
         "args": {"hlo_module": "jit_step", "hlo_op": "dot.1"}},
        # straddles the start: only the inside 100us counts
        {"ph": "X", "ts": 900.0, "dur": 200.0, "name": "dot.2",
         "args": {"hlo_module": "jit_step", "hlo_op": "dot.2"}},
        # fully inside
        {"ph": "X", "ts": 1200.0, "dur": 100.0, "name": "dot.3",
         "args": {"hlo_module": "jit_step", "hlo_op": "dot.3"}},
    ]
    s = profiler.summarize_trace_events(events, wall_seconds=500e-6)
    assert s["per_executable_seconds"]["jit_step"] == \
        pytest.approx(200e-6)
    assert s["device_compute_seconds"] == pytest.approx(200e-6)
    assert 0 <= s["device_compute_frac"] <= 1


def test_region_index_from_hlo():
    hlo = (
        'HloModule jit_train_so1_msl0, is_scheduled=true\n'
        '  %dot.3 = f32[4]{0} dot(a, b), '
        'op_name="jit(step)/jit(main)/inner_support_grad/dot_general"\n'
        '  %f.4 = f32[4]{0} add(a, b), '
        'op_name="jit(step)/jit(main)/transpose"\n'
        '  %g.5 = f32[4]{0} add(a, b), '
        'op_name="jit(step)/task_adapt/inner_lslr_update/mul"\n')
    module, idx = profiler.region_index_from_hlo(hlo)
    assert module == "jit_train_so1_msl0"
    assert idx == {"dot.3": "inner_support_grad",
                   "f.4": profiler.OTHER_REGION,
                   "g.5": "inner_lslr_update"}  # innermost label wins


def test_match_card_trace_module_to_store_slot():
    cards = {"train_so1_msl0": {"name": "train_so1_msl0"},
             "eval": {"name": "eval"}}
    assert profiler._match_card("jit_train_so1_msl0", cards) \
        is cards["train_so1_msl0"]
    assert profiler._match_card("jit_eval_step", cards) is cards["eval"]
    assert profiler._match_card("jit_unrelated", cards) is None


def test_attach_roofline_rates():
    summary = {"per_executable_seconds": {"jit_train": 0.5}}
    card = {"name": "train", "flops": 1e9, "bound": "memory",
            "ceiling_flops_per_s": 4e9}
    profiler.attach_roofline(summary, {"train": card}, steps=2)
    entry = summary["roofline"]["jit_train"]
    assert entry["achieved_flops_per_s"] == pytest.approx(4e9)
    assert entry["frac_of_ceiling"] == pytest.approx(1.0)
    assert entry["bound"] == "memory"


def test_crash_bundle_carries_profile(tmp_path):
    from howtotrainyourmamlpytorch_tpu.resilience import flightrec
    profile = tmp_path / "PROFILE.json"
    profile.write_text(json.dumps(
        {"schema": profiler.PROFILE_SCHEMA, "cards": {}}))
    prev = flightrec.register_profile(str(profile))
    try:
        bundle = str(tmp_path / "bundle")
        flightrec.write_crash_bundle(bundle, reason="test")
        copied = os.path.join(bundle, flightrec.PROFILE_FILE)
        assert os.path.exists(copied)
        assert json.load(open(copied))["schema"] == \
            profiler.PROFILE_SCHEMA
    finally:
        flightrec.register_profile(prev)
    # Unregistered: bundles simply omit the file (best-effort).
    bundle2 = str(tmp_path / "bundle2")
    flightrec.write_crash_bundle(bundle2, reason="test")
    assert not os.path.exists(os.path.join(bundle2,
                                           flightrec.PROFILE_FILE))


def test_trace_exporter_perf_lane():
    from howtotrainyourmamlpytorch_tpu.telemetry import trace as trace_mod
    events = [
        {"ts": 100.0, "event": "perf_profile", "wall_seconds": 0.25,
         "device_compute_frac": 0.1, "top_executable": "jit_step"},
        {"ts": 101.0, "event": "checkpoint", "epoch": 0},
    ]
    trace = trace_mod.build_trace(events=events)
    trace_mod.validate_trace(trace)
    perf = [e for e in trace["traceEvents"]
            if e["tid"] == trace_mod.PROFILE_TID]
    assert len(perf) == 1
    span = perf[0]
    assert span["ph"] == "X" and span["name"] == "perf_sample"
    assert span["dur"] == 250_000  # 0.25 s in us
    assert span["args"]["top_executable"] == "jit_step"


def test_failed_start_window_consumes_cadence(monkeypatch):
    """A backend that cannot trace must fail once per cadence period,
    not once per train step: the ATTEMPT records the iteration, so
    due() goes quiet for the next N iterations."""
    import jax

    sampler = profiler.PerfSampler(every_n=5)

    def boom(*a, **k):
        raise RuntimeError("cannot trace")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    assert sampler.due(3)
    with pytest.warns(UserWarning, match="sample failed"):
        assert sampler.start_window(3) is False
    assert not sampler.due(7)   # slot consumed by the failed attempt
    assert sampler.due(8)


def test_abort_window_releases_the_profiler():
    """An exception between start and end (dispatch error, Ctrl-C)
    aborts the capture: the process-wide trace is stopped, so the NEXT
    sample's start_trace succeeds instead of failing 'already
    started'."""
    import jax.numpy as jnp

    sampler = profiler.PerfSampler(every_n=1)
    assert sampler.start_window(0)
    sampler.abort_window()
    assert sampler._window is None
    # A fresh capture works — the aborted one released the profiler.
    assert sampler.start_window(1)
    row = sampler.end_window(jnp.zeros(()), iteration=1)
    assert row is not None and row["wall_seconds"] >= 0
    # Aborting with no live window is a no-op.
    sampler.abort_window()


def _load_perf_report_module():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_perf_report_under_test",
        os.path.join(REPO, "scripts", "perf_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_report_ranked_carries_achieved_vs_ceiling():
    """The ranked table's key MFU-campaign signal: achieved FLOP/s vs
    the roofline ceiling, taken from the newest sample's live
    computation."""
    pr = _load_perf_report_module()
    profile = {"schema": profiler.PROFILE_SCHEMA,
               "cards": {"train_so1_msl0": {
                   "name": "train_so1_msl0", "flops": 1e9,
                   "bound": "memory", "ceiling_flops_per_s": 4e9,
                   "arithmetic_intensity": 4.0}}}
    events = [{"event": "perf_profile", "wall_seconds": 1.0,
               "device_compute_frac": 0.5, "dispatch_gap_frac": 0.4,
               "top_executable": "jit_train_so1_msl0",
               "per_executable_seconds": {"jit_train_so1_msl0": 0.5},
               "roofline": {"jit_train_so1_msl0": {
                   "achieved_flops_per_s": 2e9, "bound": "memory",
                   "ceiling_flops_per_s": 4e9,
                   "frac_of_ceiling": 0.5}}}]
    report = pr.build_report(profile, pr.accumulate_rows(events))
    top = report["ranked"][0]
    assert top["achieved_flops_per_s"] == pytest.approx(2e9)
    assert top["frac_of_ceiling"] == pytest.approx(0.5)
    assert top["bound"] == "memory"
    assert "%ceil" in pr.format_report(report)


# ---------------------------------------------------------------------------
# tiny runs: structural pin, bitwise parity, cost cards, CLI


def _tiny_cfg(root, name, **kw):
    base = dict(
        experiment_name=name, experiment_root=str(root),
        dataset_name="synthetic_perf",
        image_height=8, image_width=8, image_channels=1,
        num_classes_per_set=2, num_samples_per_class=1,
        num_target_samples=1, batch_size=2,
        cnn_num_filters=4, num_stages=1,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=1, total_iter_per_epoch=3,
        num_evaluation_tasks=2, max_models_to_save=1,
        second_order=False, use_multi_step_loss_optimization=False,
        compute_dtype="float32", dispatch_sync_every=1,
        live_progress=False)
    base.update(kw)
    return MAMLConfig(**base)


def _run(cfg):
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    builder = ExperimentBuilder(cfg)
    builder.run_experiment()
    return builder


@pytest.fixture(scope="module")
def _process_warm(tmp_path_factory):
    """One throwaway tiny run so the PROCESS-scoped jit caches (the
    convert_element_type-sized utility programs a first run compiles)
    are warm before either parity leg — compile-count parity must
    compare the runs' OWN executables, not who ran first in the
    pytest process."""
    root = tmp_path_factory.mktemp("perf_warm")
    _run(_tiny_cfg(root, "perf_warm"))


@pytest.fixture(scope="module")
def profiled_run(tmp_path_factory, _process_warm):
    """ONE profiled tiny store-armed run shared by the parity, cost-card,
    report-section and CLI tests below (the tier-1 budget rule: the
    satellite checks ride this fixture instead of each paying its own
    training run). Peak overrides supply MEASURED-style device peaks
    (the CPU kind has no table entry) so the cost cards carry a real
    compute/memory verdict — the acceptance criterion — not
    "unknown"."""
    root = tmp_path_factory.mktemp("perf_on")
    cfg = _tiny_cfg(root, "perf_on", profile_every_n_steps=1,
                    aot_store_dir=str(root / "aot"))
    overrides = {profiler.PEAK_FLOPS_ENV: "1e11",
                 profiler.HBM_GBPS_ENV: "10"}
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        return _run(cfg)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_profile_off_installs_nothing_and_parity(tmp_path_factory,
                                                 profiled_run):
    """THE acceptance pin: with the knob at 0 nothing is installed (no
    sampler, no perf rows, no perf/* metrics) AND the run is bitwise
    identical — final weights and cache-warm compile counts — to the
    profiled run (same config modulo the knob and runtime-only
    paths)."""
    root = tmp_path_factory.mktemp("perf_off")
    cfg = _tiny_cfg(root, "perf_off", aot_store_dir=str(root / "aot"))
    off = _run(cfg)
    assert off._perf is None  # structural pin
    events = read_jsonl(os.path.join(off.paths["logs"], "events.jsonl"))
    assert not [e for e in events if e.get("event") == "perf_profile"]
    assert not any(k.startswith("perf/")
                   for k in off.registry.snapshot())
    on = profiled_run
    # Bitwise weight parity: the profiler is pure host-side observation.
    leaves_off = jax.tree.leaves(jax.device_get(off.state.params))
    leaves_on = jax.tree.leaves(jax.device_get(on.state.params))
    assert len(leaves_off) == len(leaves_on)
    for a, b in zip(leaves_off, leaves_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Compile-count parity: capture adds zero compiles.
    assert (off.registry.counter("compile/count").value
            == on.registry.counter("compile/count").value)


def test_profiled_run_rows_gauges_and_report_section(profiled_run):
    b = profiled_run
    events = read_jsonl(os.path.join(b.paths["logs"], "events.jsonl"))
    rows = [e for e in events if e.get("event") == "perf_profile"]
    assert rows  # sampled on the knob's cadence
    for row in rows:
        assert 0 < row["wall_seconds"]
        assert 0 <= row["device_compute_frac"] <= 1
        assert 0 <= row["dispatch_gap_frac"] <= 1
        assert row["per_executable_seconds"]
        assert isinstance(row["top_executable"], str)
        # named_scope regions attribute real device time
        assert row["per_region_seconds"]
    assert b.registry.counter(profiler.SAMPLES_COUNTER).value == \
        len(rows)
    assert b.registry.gauge(profiler.COMPUTE_FRAC_GAUGE).value > 0
    from howtotrainyourmamlpytorch_tpu.telemetry import summarize_events
    s = summarize_events(events)
    assert s["perf"]["samples"] == len(rows)
    assert isinstance(s["perf"]["top_executable"], str)
    assert 0 <= s["perf"]["device_compute_frac"] <= 1


def test_cost_cards_in_store_and_logs(profiled_run):
    """The AOT store doubles as the cost database: compiling-and-
    populating records one roofline card per executable in the
    fingerprint dir's PROFILE.json, and the run merges them into
    logs/PROFILE.json."""
    b = profiled_run
    store_doc = profiler.load_profile(b._aot_store.profile_path())
    assert store_doc is not None
    assert {"train_so0_msl0", "eval"} <= set(store_doc["cards"])
    logs_doc = profiler.load_profile(
        os.path.join(b.paths["logs"], profiler.PROFILE_FILE))
    assert logs_doc is not None
    assert {"train_so0_msl0", "eval"} <= set(logs_doc["cards"])
    card = logs_doc["cards"]["train_so0_msl0"]
    assert card["flops"] > 0
    assert card["bytes_accessed"] > 0
    assert card["fingerprint"] == b._aot_store.fingerprint[:16]
    # The fixture's measured-peak overrides give a REAL roofline
    # verdict (the acceptance criterion), recorded as such.
    assert card["bound"] in ("compute", "memory")
    assert card["arithmetic_intensity"] > 0
    assert card["ceiling_flops_per_s"] > 0
    assert logs_doc["peak_flops_source"] == "override"


def test_perf_report_cli_artifact_schema(profiled_run):
    """The real entrypoint over the real run: jax-free, human table +
    last-JSON-line artifact."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         profiled_run.paths["logs"]],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    artifact = json.loads(lines[-1])
    assert artifact["metric"] == "perf_report"
    assert artifact["ok"] is True
    assert artifact["cards"] >= 2
    assert artifact["samples"] >= 1
    assert isinstance(artifact["top_executable"], str)
    # The fixture's peak overrides give real verdicts, and the train
    # step dominates the tiny window's device time by orders of
    # magnitude — the report names it WITH its roofline verdict (the
    # acceptance criterion).
    assert "train" in artifact["top_executable"]
    assert artifact["top_executable_bound"] in ("compute", "memory")
    assert 0 <= artifact["device_compute_frac"] <= 1
    assert 0 <= artifact["dispatch_gap_frac"] <= 1
    # Human half renders the ranked table before the artifact.
    assert "perf report" in r.stdout


def test_perf_report_cli_errors_are_json(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r.returncode == 1
    err = json.loads(r.stdout.strip().splitlines()[-1])
    assert "error" in err


def test_perf_report_cli_explicit_events_typo_errors(tmp_path):
    """An EXPLICIT --events path that doesn't exist exits 1 — samples=0
    must mean 'never sampled', not 'typo'd the path'."""
    profile = tmp_path / "PROFILE.json"
    profile.write_text(json.dumps(
        {"schema": profiler.PROFILE_SCHEMA, "cards": {}}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "perf_report.py"),
         str(profile), "--events", str(tmp_path / "nope.jsonl")],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert r.returncode == 1
    err = json.loads(r.stdout.strip().splitlines()[-1])
    assert "does not exist" in err["error"]

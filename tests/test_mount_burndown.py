"""scripts/mount_burndown.py against fixture trees (VERDICT r3 item 8).

The real mount has been empty every round; these tests prove the
burn-down machinery works the day it is not: empty-mount no-op, the
copy-similarity flagging (a planted near-copy must flag, an independent
implementation must not), MOUNT-AUDIT table parsing including resolved
strikethrough rows, and the availability ranking.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import mount_burndown  # noqa: E402


COPY_BODY = "\n".join(
    [f"def layer_{i}(x):\n    return x * {i} + {i}" for i in range(40)])


@pytest.fixture
def fixture_trees(tmp_path):
    ref = tmp_path / "reference"
    repo = tmp_path / "repo"
    (ref / "pkg").mkdir(parents=True)
    repo.mkdir()
    # A reference file and a ~verbatim repo copy of it (must flag).
    (ref / "pkg" / "losses.py").write_text(COPY_BODY)
    (repo / "stolen.py").write_text(COPY_BODY + "\n# extra line\n")
    # An independent file with no counterpart shape (must not flag).
    (repo / "original.py").write_text(
        "\n".join(f"x{i} = compute_{i}(y, z, w)" for i in range(60)))
    # Reference files named by audit items.
    (ref / "data.py").write_text("class Loader: pass\n" * 30)
    audit = repo / "MOUNT-AUDIT.md"
    audit.write_text(
        "# MOUNT-AUDIT\n"
        "| # | Assumption | Where (this repo) | What to verify |\n"
        "|---|---|---|---|\n"
        "| 1 | **Normalization** constants | `sampler.py` | "
        "`data.py` image loading |\n"
        "| 2 | **Vote form** | `experiment.py` | "
        "`experiment_builder.py` protocol |\n"
        "| 3 | ~~resolved thing~~ | `layers.py` | `arch.py` check |\n")
    return ref, repo


def test_empty_mount_is_a_noop(tmp_path, capsys):
    empty = tmp_path / "nothing"
    empty.mkdir()
    rc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "mount_burndown.py"),
         "--ref", str(empty), "--json"],
        capture_output=True, text=True)
    assert rc.returncode == 0
    out = json.loads(rc.stdout)
    assert out["files"] == 0
    assert "empty" in out["status"]


def test_copy_check_flags_near_copy_only(fixture_trees):
    ref, repo = fixture_trees
    flags = mount_burndown.copy_check(str(repo), str(ref))
    flagged = {f["repo_file"] for f in flags}
    assert "stolen.py" in flagged
    assert "original.py" not in flagged
    stolen = next(f for f in flags if f["repo_file"] == "stolen.py")
    assert stolen["ratio"] > 0.9
    assert stolen["ref_file"].endswith("losses.py")


def test_audit_parse_and_ranking(fixture_trees):
    ref, repo = fixture_trees
    items = mount_burndown.parse_audit(str(repo / "MOUNT-AUDIT.md"),
                                       repo=str(repo))
    assert [it["num"] for it in items] == [1, 2, 3]
    assert items[2]["resolved"] is True
    assert items[0]["ref_files"] == ["data.py"]

    ranked = mount_burndown.rank_items(items, str(ref))
    # Resolved item dropped; item 1 verifiable now (data.py present in
    # the mount), item 2 blocked (experiment_builder.py absent).
    assert [it["num"] for it in ranked] == [1, 2]
    assert ranked[0]["availability"] == 2
    assert ranked[1]["availability"] == 0
    assert ranked[1]["files_missing"] == ["experiment_builder.py"]


def test_cli_end_to_end_json(fixture_trees):
    ref, repo = fixture_trees
    rc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "mount_burndown.py"),
         "--ref", str(ref), "--repo", str(repo), "--json"],
        capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    out = json.loads(rc.stdout)
    assert out["files"] == 2  # pkg/losses.py + data.py
    assert any(f["repo_file"] == "stolen.py" for f in out["copy_flags"])
    assert [t["num"] for t in out["todo"]] == [1, 2]


def test_real_audit_table_parses():
    """The ACTUAL MOUNT-AUDIT.md must parse: 15 rows, the resolved row
    detected, every open row naming at least one thing to check."""
    items = mount_burndown.parse_audit(os.path.join(REPO,
                                                    "MOUNT-AUDIT.md"))
    assert len(items) == 15
    nums = [it["num"] for it in items]
    assert nums == list(range(1, 16))
    resolved = [it["num"] for it in items if it["resolved"]]
    assert resolved == [12]
    # This-repo cross-references (docs/PARITY.md in #11, bench.py in
    # #14) must NOT be extracted as mount files.
    by_num = {it["num"]: it for it in items}
    assert by_num[14]["ref_files"] == []
    assert "docs/PARITY.md" not in by_num[11]["ref_files"]
    assert "bench.py" not in by_num[14]["ref_files"]
    # Every open item except the two whose checks need no mount FILE
    # (#11 compares shipped config families, #14 has nothing to read)
    # names at least one reference file to open.
    for it in items:
        if it["resolved"] or it["num"] in (11, 14):
            continue
        assert it["ref_files"], f"item {it['num']} names no files"

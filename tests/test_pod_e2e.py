"""Pod-scale end-to-end proof (VERDICT r1 next-round #1).

Runs the shipped resnet12 pod config through the FULL ``ExperimentBuilder``
loop over multiple OS processes joined by ``jax.distributed``, scaled down
in schedule, tensor sizes, and the microbatch count (mb=2 preserves the
shipped mb=8's 1-task-per-chunk geometry at the test's 2-tasks/chip
batch; backbone family, accumulation scan, second-order+MSL executable,
per-step BN all as shipped):

  phase A: fresh run, train epoch 0 → val sweep → checkpoint → pause
  phase B: resume 'latest', PREEMPT mid-epoch-1 on process 0 only (the
           stop must propagate through the multi-host OR-agreement so all
           hosts break at the same iteration) → mid-epoch snapshot
  phase C: resume 'latest' again (exercises the cross-host tag/iteration/
           fingerprint agreement), finish training, run the top-k ensemble
           test protocol

and asserts: every process sees the same resume iterations; all phases'
metrics are bit-identical across processes (SPMD really ran one program);
and the final parameters + ensemble test accuracy match an UNINTERRUPTED
single-process same-mesh run of the same config (resume-exactness across
two interruptions).

Default in-suite size: mesh (2,4) over 2 processes x 4 devices — the
largest size this box's single CPU core compiles in suite-friendly time.
The shipped config's EXACT (4,8)=32-device topology over 4 processes is
the same code path and is exercised by the driven run recorded in
docs/E2E.md; to reproduce it, set POD_E2E_MESH=4,8 POD_E2E_NPROC=4
(optionally POD_E2E_CACHE=<warm cache dir>, POD_E2E_TIMEOUT=7200) and run
this test — the (4,8) sharded resnet12 compile alone is ~30 min cold on
one core.

Skipped when the sandbox forbids binding a localhost socket. One shared
XLA compilation cache keeps the processes and phases from recompiling.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from helpers import gloo_multiprocess_quarantine

# Multi-process full-loop proof: ~minutes on this 1-core box.
# Excluded from the quick profile (`pytest -m 'not slow'`).
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MESH = tuple(int(x) for x in
              os.environ.get("POD_E2E_MESH", "2,4").split(","))
_NPROC = int(os.environ.get("POD_E2E_NPROC", "2"))
_NDEV = _MESH[0] * _MESH[1]
_TIMEOUT = int(os.environ.get("POD_E2E_TIMEOUT", "2700"))

# The shipped pod config, scaled down in schedule/tensor sizes only.
_POD_OVERRIDES = dict(
    experiment_name="pod_e2e",
    dataset_name="synthetic_tiered_imagenet",
    image_height=16, image_width=16, image_channels=3,
    cnn_num_filters=4,
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    mesh_shape=list(_MESH),
    batch_size=2 * _NDEV,       # 2 tasks/chip; microbatch chunks = 1/chip
    task_microbatches=2,        # shipped value is 8 (= the pod's full
                                # per-chip batch, measured fastest); the
                                # test's scaled 2/chip keeps the same
                                # 1-task-per-chunk geometry via mb=2
    total_epochs=2, total_iter_per_epoch=3,
    num_evaluation_tasks=16,
    dispatch_sync_every=1,      # agree on the preemption stop every iter
    prefetch_batches=1,
    live_progress=False,
)

_WORKER = r"""
import json, os, sys
REPO, CFG_PATH, OUT_DIR = sys.argv[1], sys.argv[2], sys.argv[3]
sys.path.insert(0, REPO)
import jax
jax.config.update("jax_platforms", "cpu")
from howtotrainyourmamlpytorch_tpu.parallel import initialize_distributed
initialize_distributed()
import numpy as np
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

with open(CFG_PATH) as f:
    cfg = MAMLConfig.from_dict(json.load(f))
# Shared persistent XLA cache: phase A compiles each program once; the
# rebuilt builders of phases B/C (and the solo comparison run) hit the
# cache instead of re-compiling the pod-mesh executables.
jax.config.update("jax_compilation_cache_dir", cfg.compilation_cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

def digest(builder):
    import jax
    tot = 0.0
    for leaf in jax.tree.leaves(jax.device_get(builder.state.params)):
        tot += float(np.abs(np.asarray(leaf, np.float64)).sum())
    return tot

out = {"pid": jax.process_index(), "nproc": jax.process_count(),
       "ndev": len(jax.devices())}

# -- phase A: fresh run, one epoch, pause --------------------------------
a = ExperimentBuilder(cfg.replace(total_epochs_before_pause=1))
res_a = a.run_experiment()
out["pauseA"] = res_a.get("paused_at_iter")

# -- phase B: resume + preempt mid-epoch on process 0 only ---------------
b = ExperimentBuilder(cfg.replace(continue_from_epoch="latest"))
out["resumeB_iter"] = b.current_iter
if jax.process_index() == 0:
    orig = b.plan.train_steps
    count = {"n": 0}
    class Preempting(dict):
        def __getitem__(self, key):
            fn = orig[key]
            def wrapped(*args, **kw):
                count["n"] += 1
                if count["n"] == 2:
                    b._preempted = True
                return fn(*args, **kw)
            return wrapped
    b.plan = b.plan._replace(train_steps=Preempting())
res_b = b.run_experiment()
out["preemptB"] = res_b.get("preempted_at_iter")

# -- phase C: resume again, finish, ensemble test ------------------------
c = ExperimentBuilder(cfg.replace(continue_from_epoch="latest"))
out["resumeC_iter"] = c.current_iter
res_c = c.run_experiment()
out["digest"] = digest(c)
out["test"] = {k: v for k, v in res_c.items() if k != "per_model_accuracy"}
with open(os.path.join(OUT_DIR, f"result{jax.process_index()}.json"),
          "w") as f:
    json.dump(out, f)
"""

_SOLO = r"""
import json, os, sys
REPO, CFG_PATH, OUT_PATH = sys.argv[1], sys.argv[2], sys.argv[3]
sys.path.insert(0, REPO)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

with open(CFG_PATH) as f:
    cfg = MAMLConfig.from_dict(json.load(f))
jax.config.update("jax_compilation_cache_dir", cfg.compilation_cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
b = ExperimentBuilder(cfg)
res = b.run_experiment()
tot = 0.0
for leaf in jax.tree.leaves(jax.device_get(b.state.params)):
    tot += float(np.abs(np.asarray(leaf, np.float64)).sum())
with open(OUT_PATH, "w") as f:
    json.dump({"ndev": len(jax.devices()), "digest": tot,
               "test": {k: v for k, v in res.items()
                        if k != "per_model_accuracy"}}, f)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pod_cfg_dict(tmp_path, experiment_root):
    with open(os.path.join(
            REPO, "experiment_config",
            "tiered-imagenet_maml++_5-way_5-shot_resnet12_pod.json")) as f:
        cfg = json.load(f)
    cfg.update(_POD_OVERRIDES)
    cfg["experiment_root"] = str(experiment_root)
    cfg["compilation_cache_dir"] = os.environ.get(
        "POD_E2E_CACHE", str(tmp_path / "xla_cache"))
    return cfg


@gloo_multiprocess_quarantine
def test_pod_config_full_loop_at_virtual_scale(tmp_path):
    # Quarantined on <2-core boxes (helpers.py): the N-process gloo CPU
    # ring intermittently aborts/segfaults there — an environment
    # limitation, skipped with provenance instead of failing the
    # pyramid (docs/measurements/r6/pyramid_notes.md).
    try:
        port = _free_port()
    except OSError:
        pytest.skip("cannot bind localhost sockets in this sandbox")

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(_pod_cfg_dict(tmp_path,
                                                 tmp_path / "exp")))

    nproc = _NPROC
    dev_per_proc = _NDEV // nproc
    procs, logs = [], []
    for pid in range(nproc):
        env = dict(os.environ)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(nproc),
            "JAX_PROCESS_ID": str(pid),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (f"--xla_force_host_platform_device_count="
                          f"{dev_per_proc}"),
        })
        log = open(tmp_path / f"log{pid}.txt", "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), REPO, str(cfg_path),
             str(tmp_path)],
            env=env, stdout=log, stderr=log, text=True))

    results = {}
    try:
        for pid, p in enumerate(procs):
            try:
                # Generous: the phase-A compile of the sharded
                # second-order resnet12 step is minutes on a small shared
                # CPU; later phases hit the persistent cache.
                p.wait(timeout=_TIMEOUT)
            except subprocess.TimeoutExpired:
                pytest.fail(f"pod worker {pid} timed out")
            logs[pid].seek(0)
            tail = logs[pid].read()[-4000:]
            assert p.returncode == 0, f"pod worker {pid} failed:\n{tail}"
            with open(tmp_path / f"result{pid}.json") as f:
                results[pid] = json.load(f)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()

    iters = _POD_OVERRIDES["total_iter_per_epoch"]
    for pid, r in results.items():
        assert r["nproc"] == nproc and r["ndev"] == _NDEV, r
        assert r["pauseA"] == iters                 # paused after epoch 0
        assert r["resumeB_iter"] == iters           # resumed at its end
        assert r["preemptB"] == iters + 2           # preempted mid-epoch 1
        assert r["resumeC_iter"] == iters + 2       # exact mid-epoch resume
        assert r["test"]["num_models"] == 2         # both epochs ensembled
        assert (r["test"]["num_episodes"]
                == _POD_OVERRIDES["num_evaluation_tasks"])
        assert np.isfinite(r["test"]["test_accuracy_mean"])
    # SPMD agreement: every process computed the same program.
    for pid in range(1, nproc):
        assert results[pid]["digest"] == results[0]["digest"]
        assert results[pid]["test"] == results[0]["test"]

    # Artifacts written once (process 0) with the reference filenames.
    logs_dir = tmp_path / "exp" / "pod_e2e" / "logs"
    stats = (logs_dir / "summary_statistics.csv").read_text().splitlines()
    assert len(stats) == 1 + 2                      # header + 2 epochs
    assert (logs_dir / "test_summary.csv").exists()

    # Uninterrupted single-process same-mesh run: the twice-interrupted
    # pod run must land on the SAME final parameters and test accuracy
    # (resume-exactness at pod mesh shape).
    solo = tmp_path / "solo.py"
    solo.write_text(_SOLO)
    solo_cfg = tmp_path / "solo_cfg.json"
    solo_cfg.write_text(json.dumps(_pod_cfg_dict(tmp_path,
                                                 tmp_path / "solo_exp")))
    env = dict(os.environ)
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (f"--xla_force_host_platform_device_count="
                              f"{_NDEV}")})
    out_path = tmp_path / "solo.json"
    r = subprocess.run(
        [sys.executable, str(solo), REPO, str(solo_cfg), str(out_path)],
        env=env, capture_output=True, text=True, timeout=_TIMEOUT)
    assert r.returncode == 0, r.stderr[-4000:]
    with open(out_path) as f:
        solo_res = json.load(f)
    assert solo_res["ndev"] == _NDEV
    # Multi-process feeding assembles per-device shards where solo
    # device_puts one global array; the resulting accumulation-order noise
    # measures ~4e-6 relative on this digest after 6 second-order bf16
    # steps (the r1 two-process test bounded the same effect at 1e-5
    # after 2 steps). Anything beyond noise — a real resume/feeding bug —
    # is orders of magnitude larger.
    np.testing.assert_allclose(results[0]["digest"], solo_res["digest"],
                               rtol=1e-4)
    np.testing.assert_allclose(
        results[0]["test"]["test_accuracy_mean"],
        solo_res["test"]["test_accuracy_mean"], atol=0.02)


def test_pod_config_own_geometry_dryrun():
    """VERDICT r4 next #4: the shipped pod config declares a (4,8) =
    32-device mesh that the in-suite (2,4) e2e above never builds. This
    runs ``__graft_entry__.dryrun_pod_config`` in a fresh 32-virtual-
    CPU-device process: mesh shape, global batch 256, task_microbatches
    8, resnet12 backbone, and the epoch-0 second-order+MSL executable
    all come FROM the shipped JSON (tensor sizes shrunk); one train +
    one eval step must execute finite. The committed POD_DRYRUN_r05.json
    artifact is a capture of exactly this invocation."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=32"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_pod_config()"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=_TIMEOUT)
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["ok"] and out["mesh_shape"] == [4, 8]
    assert out["n_devices"] == 32 and out["global_batch"] == 256
    assert out["task_microbatches"] == 8
    assert out["backbone"] == "resnet12"
    assert out["executable"] == {"second_order": True, "use_msl": True}
    assert np.isfinite(out["train_loss"])
    assert np.isfinite(out["eval_loss_mean"])

"""Shared test fixtures for on-disk / in-archive PNG dataset trees.

Three test modules exercise the reference dataset layout
(``<dataset>/<split>/<class>/*.png`` and the Omniglot nested
``<alphabet>/<character>`` variant). They build their trees through
these helpers so the on-disk contract (grayscale PNG, uint8, extension)
lives in one place.
"""

import io

import numpy as np


def write_png(path, rng, size=(12, 12)):
    """Write one random grayscale PNG to ``path``."""
    from PIL import Image
    Image.fromarray(rng.integers(0, 255, size, np.uint8), "L").save(path)


def png_bytes(rng, size):
    """Random grayscale PNG as bytes (for writing into zip archives)."""
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, size, np.uint8), "L").save(
        buf, "PNG")
    return buf.getvalue()


def make_png_split_tree(root, splits, rng, size=(12, 12),
                        images_per_class=4):
    """Reference flat layout: ``root/<split>/<class>/<i>.png``.

    ``splits`` maps split name -> class-name iterable (or an int for
    ``class_0..class_{n-1}``).
    """
    for split, classes in splits.items():
        if isinstance(classes, int):
            classes = [f"class_{c}" for c in range(classes)]
        for cls in classes:
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(images_per_class):
                write_png(d / f"{i}.png", rng, size)

"""Shared test fixtures for on-disk / in-archive PNG dataset trees.

Three test modules exercise the reference dataset layout
(``<dataset>/<split>/<class>/*.png`` and the Omniglot nested
``<alphabet>/<character>`` variant). They build their trees through
these helpers so the on-disk contract (grayscale PNG, uint8, extension)
lives in one place.
"""

import io
import os
import socket

import numpy as np
import pytest


def _can_bind_localhost() -> bool:
    try:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
        return True
    except OSError:
        return False


# Formal quarantine for the multi-process gloo CPU transport race
# (docs/measurements/r6/pyramid_notes.md): 2-process `jax.distributed`
# training on a single-core box intermittently aborts inside gloo with
# `op.preamble.length <= op.nbytes` (and the occasional worker
# segfault) — an environment limitation of oversubscribed gloo CPU
# rings, not a product defect; the same scenarios pass on >=2-core
# boxes. Tests carrying this marker report an attributed skip instead
# of an environmental failure. Socket availability is probed here too
# so a sandbox without localhost binds skips for the honest reason.
GLOO_MIN_CORES = 2
_cores = os.cpu_count() or 1
gloo_multiprocess_quarantine = pytest.mark.skipif(
    _cores < GLOO_MIN_CORES or not _can_bind_localhost(),
    reason=(f"multi-process gloo CPU transport is flaky below "
            f"{GLOO_MIN_CORES} cores (op.preamble.length abort class, "
            f"docs/measurements/r6/pyramid_notes.md): "
            f"{_cores} core(s), localhost sockets "
            f"{'available' if _can_bind_localhost() else 'unavailable'}"))


def write_png(path, rng, size=(12, 12)):
    """Write one random grayscale PNG to ``path``."""
    from PIL import Image
    Image.fromarray(rng.integers(0, 255, size, np.uint8), "L").save(path)


def png_bytes(rng, size):
    """Random grayscale PNG as bytes (for writing into zip archives)."""
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(rng.integers(0, 255, size, np.uint8), "L").save(
        buf, "PNG")
    return buf.getvalue()


def make_png_split_tree(root, splits, rng, size=(12, 12),
                        images_per_class=4):
    """Reference flat layout: ``root/<split>/<class>/<i>.png``.

    ``splits`` maps split name -> class-name iterable (or an int for
    ``class_0..class_{n-1}``).
    """
    for split, classes in splits.items():
        if isinstance(classes, int):
            classes = [f"class_{c}" for c in range(classes)]
        for cls in classes:
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(images_per_class):
                write_png(d / f"{i}.png", rng, size)

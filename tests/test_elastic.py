"""Elastic pod units (ISSUE 12).

Tier-1 keeps the cheap layers: the pure roster-consensus fixpoint,
ElasticPolicy routing (attributed + within-budget -> reshard; anything
else -> the unchanged exit-73 path), the degraded MeshPlan derivation
and its pad-and-mask partitioning determinism, AOT fingerprint
distinctness across rosters, the backfill startup gate, and the
structural elastic_mode=0-installs-nothing pin (the cluster/watchdog
zero-config discipline). The real 2-process SIGKILL -> reshard ->
bitwise-cold-N-1 proof lives in scripts/chaos_pod.py's elastic phase.
"""

import os
import threading
import time

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu import resilience
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.resilience import (
    cluster, elastic, faults, flightrec, watchdog)
from howtotrainyourmamlpytorch_tpu.resilience.cluster import (
    ClusterFaultDomain)
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry
from howtotrainyourmamlpytorch_tpu.utils.tracing import (
    JsonlLogger, read_jsonl)


@pytest.fixture(autouse=True)
def _clean_process_state():
    faults.configure("")
    prev_reg = resilience.set_registry(None)
    prev_beacon = watchdog.install_beacon(None)
    prev_rec = flightrec.install(None)
    prev_dom = cluster.install(None)
    yield
    faults.configure("")
    resilience.set_registry(prev_reg)
    watchdog.install_beacon(prev_beacon)
    flightrec.install(prev_rec)
    cluster.install(prev_dom)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_config_elastic_validation():
    with pytest.raises(ValueError, match="elastic_mode"):
        MAMLConfig(elastic_mode=2)
    # elastic without the pod fault domain is a contradiction: the
    # policy is routed from the attributed trip.
    with pytest.raises(ValueError, match="cluster_collective_timeout_s"):
        MAMLConfig(elastic_mode=1)
    with pytest.raises(ValueError, match="elastic_max_lost_hosts"):
        MAMLConfig(elastic_max_lost_hosts=0)
    with pytest.raises(ValueError, match="elastic_reshard_timeout_s"):
        MAMLConfig(elastic_reshard_timeout_s=-1.0)
    with pytest.raises(ValueError, match="elastic_pad_tasks"):
        MAMLConfig(elastic_pad_tasks=-1)
    # A pad that does not make the batch divisible is refused.
    with pytest.raises(ValueError, match="elastic_pad_tasks"):
        MAMLConfig(batch_size=6, mesh_shape=(1, 4), elastic_pad_tasks=1)
    cfg = MAMLConfig(elastic_mode=1, cluster_collective_timeout_s=12.0)
    assert elastic.elastic_enabled(cfg)
    assert not elastic.elastic_enabled(MAMLConfig())
    # Auto reshard timeout = one collective budget.
    assert elastic.reshard_timeout(cfg) == pytest.approx(12.0)
    assert elastic.reshard_timeout(
        cfg.replace(elastic_reshard_timeout_s=5.0)) == pytest.approx(5.0)
    # Pad participates in the padded batch the executables see.
    padded = MAMLConfig(batch_size=6, mesh_shape=(1, 4),
                        elastic_pad_tasks=2)
    assert padded.padded_batch_size == 8


# ---------------------------------------------------------------------------
# pure roster math
# ---------------------------------------------------------------------------

def test_roster_consensus_fixpoint():
    # Lone survivor convicting the dead peer agrees with itself.
    assert elastic.roster_consensus({0: [1]}, [0, 1]) == ([0], [1], True)
    # Incomplete until every non-convicted member proposes.
    roster, dead, complete = elastic.roster_consensus(
        {0: [3]}, [0, 1, 2, 3])
    assert roster == [0, 1, 2] and dead == [3] and not complete
    roster, dead, complete = elastic.roster_consensus(
        {0: [3], 1: [3], 2: [3]}, [0, 1, 2, 3])
    assert (roster, dead, complete) == ([0, 1, 2], [3], True)
    # Double loss during the reshard: host 2 dies before proposing and
    # nobody has convicted it yet — the consensus stays incomplete (the
    # caller times out into exit 73).
    roster, dead, complete = elastic.roster_consensus(
        {0: [3], 1: [3]}, [0, 1, 2, 3])
    assert roster == [0, 1, 2] and not complete
    # ...unless a survivor's leases convict it too.
    roster, dead, complete = elastic.roster_consensus(
        {0: [2, 3], 1: [3]}, [0, 1, 2, 3])
    assert (roster, dead, complete) == ([0, 1], [2, 3], True)
    # Mutual accusation: the union removes both; no split-brain is
    # representable because there is exactly one union.
    roster, dead, complete = elastic.roster_consensus(
        {0: [1], 1: [0]}, [0, 1])
    assert roster == [] and dead == [0, 1] and not complete


def test_rerank_and_exec_env():
    assert elastic.rerank([0, 2, 3], 2) == 1
    doc = {"generation": 2, "roster": [0, 2, 3], "orig_processes": 4,
           "coordinator": "10.0.0.1:7777"}
    env = elastic.exec_env(doc, 3, environ={"MAML_FAULTS": "kill@3",
                                            "OTHER": "kept"})
    assert env[elastic.GEN_ENV] == "2"
    assert env[elastic.ROSTER_ENV] == "0,2,3"
    assert env[elastic.ORIG_ENV] == "4"
    assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:7777"
    assert env["JAX_NUM_PROCESSES"] == "3"
    assert env["JAX_PROCESS_ID"] == "2"
    assert env["OTHER"] == "kept"
    # A fault plan is per-launch: the resharded segment must not replay
    # the injection that killed the peer.
    assert "MAML_FAULTS" not in env
    # Lone survivor drops the distributed trio entirely — bitwise the
    # same environment a cold single-process run at the degraded
    # geometry uses.
    solo = elastic.exec_env(
        {"generation": 1, "roster": [1], "orig_processes": 2,
         "coordinator": "x:1"}, 1,
        environ={"JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": "1",
                 "JAX_COORDINATOR_ADDRESS": "x:0"})
    for key in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        assert key not in solo
    # Round trip through the parser the restarted image runs.
    state = elastic.parse_roster_env(env)
    assert state == elastic.RosterState(2, (0, 2, 3), 4)
    assert state.degraded
    assert elastic.parse_roster_env({}) is None


def test_adopt_env_drops_removed_keys():
    """The backfill gate's in-process adoption must DELETE keys the
    roster env removes — a stale MAML_FAULTS would re-arm the fault
    plan that killed the rejoined host's predecessor."""
    env = {"MAML_FAULTS": "kill_peer@6", "JAX_COORDINATOR_ADDRESS": "a:1",
           "JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": "1", "KEEP": "x"}
    elastic.adopt_env({"generation": 2, "roster": [0, 1],
                       "orig_processes": 2, "coordinator": "b:2"},
                      1, environ=env)
    assert "MAML_FAULTS" not in env
    assert env["JAX_COORDINATOR_ADDRESS"] == "b:2"
    assert env["JAX_PROCESS_ID"] == "1" and env["KEEP"] == "x"
    # Lone roster drops the distributed trio entirely.
    env2 = {"JAX_NUM_PROCESSES": "2", "JAX_PROCESS_ID": "0",
            "JAX_COORDINATOR_ADDRESS": "a:1"}
    elastic.adopt_env({"generation": 1, "roster": [0],
                       "orig_processes": 2, "coordinator": "b:2"},
                      0, environ=env2)
    assert not any(k.startswith("JAX_") for k in env2)


def test_apply_roster_derives_and_forces_resume():
    cfg = MAMLConfig(batch_size=8, mesh_shape=(2, 4),
                     continue_from_epoch="from_scratch",
                     elastic_mode=1, cluster_collective_timeout_s=12.0)
    # No roster env: untouched (the generation-0 structural pin).
    out, state = elastic.apply_roster(cfg, environ={})
    assert out is cfg and state is None
    env = {elastic.GEN_ENV: "1", elastic.ROSTER_ENV: "0",
           elastic.ORIG_ENV: "2"}
    out, state = elastic.apply_roster(cfg, environ=env)
    assert state == elastic.RosterState(1, (0,), 2)
    assert out.mesh_shape == (1, 4)
    # A resharded segment is by definition a resume.
    assert out.continue_from_epoch == "latest"


# ---------------------------------------------------------------------------
# degraded MeshPlan derivation
# ---------------------------------------------------------------------------

def test_derive_degraded_config_partitioning():
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        degraded_mesh_shape, derive_degraded_config)

    cfg = MAMLConfig(batch_size=8, mesh_shape=(2, 4),
                     task_microbatches=2, num_classes_per_set=3)
    # 2 -> 1 hosts: mesh (1, 4), batch 8 still divisible -> no pad.
    d1 = derive_degraded_config(cfg, 1, 2)
    assert d1.mesh_shape == (1, 4) and d1.elastic_pad_tasks == 0
    assert d1.batch_size == 8
    assert d1.effective_eval_batch_size % 4 == 0
    # 4 -> 3 hosts with batch 8: 8 % 12 != 0 is impossible (3 hosts x 4
    # chips > batch) — use a batch that genuinely needs the pad.
    cfg4 = MAMLConfig(batch_size=16, mesh_shape=(4, 3),
                      task_microbatches=4, num_classes_per_set=3)
    d3 = derive_degraded_config(cfg4, 3, 4)
    assert d3.mesh_shape == (3, 3)
    # 16 real tasks over 9 devices -> pad 2 to 18.
    assert d3.elastic_pad_tasks == 2 and d3.padded_batch_size == 18
    assert d3.padded_batch_size % 9 == 0
    # Microbatches pre-resolved at the degraded geometry (gcd with the
    # per-device padded task count 18/9 = 2).
    assert d3.task_microbatches == d3.effective_task_microbatches(9)
    # Determinism: the derivation is a pure function of (cfg, roster).
    assert derive_degraded_config(cfg4, 3, 4) == d3
    # Full roster: untouched (re-expansion resumes the original
    # geometry bit-for-bit).
    assert derive_degraded_config(cfg, 2, 2) is cfg
    # A mesh whose dcn axis does not track processes is refused.
    with pytest.raises(ValueError, match="dcn"):
        degraded_mesh_shape((2, 4), 1, 3)
    with pytest.raises(ValueError, match="survivor count"):
        degraded_mesh_shape((2, 4), 0, 2)


def test_degraded_pad_and_mask_step_exactness():
    """The padded-masked train step over the degraded mesh computes the
    EXACT masked mean: allclose to the unpadded single-device step on
    the same 6 real tasks, and bitwise-deterministic for a given
    roster."""
    import jax
    import jax.numpy as jnp
    from howtotrainyourmamlpytorch_tpu.meta import (
        Episode, init_train_state)
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        derive_degraded_config, make_mesh, make_sharded_steps,
        replicate_state, shard_batch)

    base = dict(dataset_name="syn", image_height=6, image_width=6,
                image_channels=1, num_classes_per_set=2,
                num_samples_per_class=1, num_target_samples=1,
                cnn_num_filters=2, num_stages=2,
                number_of_training_steps_per_iter=1,
                number_of_evaluation_steps_per_iter=1,
                second_order=False,
                use_multi_step_loss_optimization=False,
                batch_size=6, cluster_collective_timeout_s=5.0)
    cfg1 = MAMLConfig(**base, mesh_shape=(1, 1))
    cfgd = derive_degraded_config(
        MAMLConfig(**base, mesh_shape=(2, 4)), 1, 2)
    assert cfgd.elastic_pad_tasks == 2

    rng = np.random.default_rng(0)

    def episodes(n):
        return Episode(
            rng.standard_normal((n, 2, 6, 6, 1)).astype(np.float32),
            np.tile(np.arange(2), (n, 1)).astype(np.int32),
            rng.standard_normal((n, 2, 6, 6, 1)).astype(np.float32),
            np.tile(np.arange(2), (n, 1)).astype(np.int32))

    real = episodes(6)
    padded = Episode(*(np.concatenate(
        [f, np.zeros((2,) + f.shape[1:], f.dtype)]) for f in real))

    init, apply = make_model(cfg1)
    dv = jax.devices()
    key = (False, False)

    mesh1 = make_mesh(cfg1, dv[:1])
    plan1 = make_sharded_steps(cfg1, apply, mesh1)
    s1 = replicate_state(init_train_state(cfg1, init,
                                          jax.random.PRNGKey(0)), mesh1)
    s1, m1 = plan1.train_steps[key](s1, shard_batch(real, mesh1),
                                    jnp.float32(0.0))

    meshd = make_mesh(cfgd, dv[:4])
    pland = make_sharded_steps(cfgd, apply, meshd)

    def run_degraded():
        s = replicate_state(init_train_state(cfgd, init,
                                             jax.random.PRNGKey(0)),
                            meshd)
        return pland.train_steps[key](s, shard_batch(padded, meshd),
                                      jnp.float32(0.0))

    sd, md = run_degraded()
    # The pads contribute exactly zero: loss/accuracy/weights match the
    # unpadded reference up to cross-mesh reduction reassociation.
    np.testing.assert_allclose(float(m1.loss), float(md.loss),
                               rtol=2e-5)
    np.testing.assert_allclose(float(m1.accuracy), float(md.accuracy),
                               rtol=2e-5)
    for a, b in zip(jax.tree.leaves(s1.params),
                    jax.tree.leaves(sd.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)
    # Bitwise determinism for a given roster — the property the
    # chaos proof's cold-N-1 parity gate rests on.
    sd2, _ = run_degraded()
    for a, b in zip(jax.tree.leaves(sd.params),
                    jax.tree.leaves(sd2.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_loader_pads_train_batches_with_zero_tail(tmp_path):
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader)

    cfg = MAMLConfig(
        dataset_name="synthetic_padtest", image_height=6, image_width=6,
        image_channels=1, num_classes_per_set=2, num_samples_per_class=1,
        num_target_samples=1, batch_size=6, mesh_shape=(1, 4),
        elastic_pad_tasks=2, prefetch_batches=1)
    loader = MetaLearningDataLoader(cfg)  # mesh None: host batches
    ref = MetaLearningDataLoader(cfg.replace(elastic_pad_tasks=0))
    batch = next(iter(loader.get_train_batches(3, 1)))
    unpadded = next(iter(ref.get_train_batches(3, 1)))
    assert batch.support_x.shape[0] == 8
    # Real positions are the SAME episode stream (indexed by the real
    # batch size), pads are zeros.
    np.testing.assert_array_equal(np.asarray(batch.support_x[:6]),
                                  np.asarray(unpadded.support_x))
    assert not np.asarray(batch.support_x[6:]).any()
    assert not np.asarray(batch.target_y[6:]).any()


# ---------------------------------------------------------------------------
# policy routing
# ---------------------------------------------------------------------------

def test_should_reshard_routing():
    policy = elastic.ElasticPolicy(
        lease_dir="/nonexistent", process_index=0, roster=[0, 1, 2, 3],
        generation=0, orig_processes=4, max_lost_hosts=2, timeout_s=1.0,
        mesh_dcn=4)
    # Attributed within budget -> reshard.
    assert policy.should_reshard([1])
    assert policy.should_reshard([1, 2])
    # Unattributed -> exit 73 (never blame nobody).
    assert not policy.should_reshard([])
    # Over budget -> exit 73.
    assert not policy.should_reshard([1, 2, 3])
    # Budget is CUMULATIVE across generations: one host already lost.
    degraded = elastic.ElasticPolicy(
        lease_dir="/nonexistent", process_index=0, roster=[0, 1, 2],
        generation=1, orig_processes=4, max_lost_hosts=2, timeout_s=1.0,
        mesh_dcn=3)
    assert degraded.should_reshard([1])
    assert not degraded.should_reshard([1, 2])
    # A mesh whose dcn axis does not track the roster cannot be
    # degraded — exit 73.
    wrong_mesh = elastic.ElasticPolicy(
        lease_dir="/nonexistent", process_index=0, roster=[0, 1],
        generation=0, orig_processes=2, max_lost_hosts=1, timeout_s=1.0,
        mesh_dcn=1)
    assert not wrong_mesh.should_reshard([1])


def _stale_peer(lease_dir, host, age_s=120.0):
    os.makedirs(lease_dir, exist_ok=True)
    path = cluster.lease_path(lease_dir, host)
    with open(path, "w") as f:
        f.write("{}")
    past = time.time() - age_s
    os.utime(path, (past, past))


def test_trip_routes_to_reshard_with_exec_env(tmp_path):
    """The full attributed-trip -> consensus -> exec pipeline with an
    injected exec: proposal and roster files land, the elastic_reshard
    row and counters land, and the exec env is the survivor's."""
    reg = MetricsRegistry()
    jsonl = JsonlLogger(str(tmp_path / "events.jsonl"))
    domain = ClusterFaultDomain(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=2, collective_timeout_s=2.0, stalled_after_s=1.0,
        dead_after_s=2.0, lease_interval_s=0.1, registry=reg,
        jsonl=jsonl, prom_path=str(tmp_path / "metrics.prom"))
    execs = []
    policy = elastic.ElasticPolicy(
        lease_dir=domain.lease.lease_dir, process_index=0,
        roster=[0, 1], generation=0, orig_processes=2,
        max_lost_hosts=1, timeout_s=2.0, mesh_dcn=2,
        lease=domain.lease, registry=reg, jsonl=jsonl,
        argv=["train_maml_system.py", "--x", "1"])
    policy._exec = lambda exe, argv, env: execs.append((exe, argv, env))
    domain.elastic = policy
    rec = flightrec.FlightRecorder(32)
    flightrec.install(rec)

    domain.heartbeat(force=True)
    _stale_peer(domain.lease.lease_dir, 1)
    domain.trip_peer_lost({"phase": "collective", "detail": "gather",
                           "age_seconds": 2.5,
                           "deadline_seconds": 2.0})
    domain.close()

    assert len(execs) == 1
    _, argv, env = execs[0]
    assert argv[1:] == ["train_maml_system.py", "--x", "1"]
    assert env[elastic.GEN_ENV] == "1"
    assert env[elastic.ROSTER_ENV] == "0"
    # Lone survivor: the distributed trio is dropped.
    assert "JAX_NUM_PROCESSES" not in env
    # Consensus artifacts on disk: our proposal + the agreed roster.
    props = elastic.read_proposals(policy.lease_dir, 1)
    assert props[0]["dead"] == [1]
    doc = elastic.read_roster(policy.lease_dir)
    assert doc["generation"] == 1 and doc["roster"] == [0]
    assert doc["dead"] == [1] and doc["orig_processes"] == 2
    # Telemetry: reshard row + counter; peer loss still counted.
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    rows = [e for e in events if e["event"] == elastic.RESHARD_EVENT]
    assert len(rows) == 1 and rows[0]["roster"] == [0]
    assert rows[0]["suspects"] == [1]
    assert reg.counter(elastic.RESHARDS_COUNTER).value == 1
    assert reg.counter(cluster.PEER_LOSSES_COUNTER).value == 1
    assert any(e["kind"] == elastic.RESHARD_EVENT for e in rec.events())


def test_unattributed_or_over_budget_trip_still_exits_73(tmp_path):
    """The exit-73 contract survives elastic: over-budget and
    unattributed losses take the unchanged whole-job-restart path."""
    trips = []
    reg = MetricsRegistry()
    jsonl = JsonlLogger(str(tmp_path / "events.jsonl"))
    domain = ClusterFaultDomain(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=3, collective_timeout_s=1.0, stalled_after_s=1.0,
        dead_after_s=1.5, lease_interval_s=0.1, registry=reg,
        jsonl=jsonl, on_trip=trips.append)
    execs = []
    policy = elastic.ElasticPolicy(
        lease_dir=domain.lease.lease_dir, process_index=0,
        roster=[0, 1, 2], generation=0, orig_processes=3,
        max_lost_hosts=1, timeout_s=1.0, mesh_dcn=3, registry=reg)
    policy._exec = lambda *a: execs.append(a)
    domain.elastic = policy
    domain.heartbeat(force=True)
    _stale_peer(domain.lease.lease_dir, 1)
    _stale_peer(domain.lease.lease_dir, 2)
    # TWO dead peers > max_lost_hosts 1: the policy refuses, the trip
    # completes as the ordinary attributed exit (on_trip injected).
    domain.trip_peer_lost({"phase": "collective", "detail": "gather",
                           "age_seconds": 1.6, "deadline_seconds": 1.0})
    domain.close()
    assert not execs
    assert len(trips) == 1 and sorted(trips[0]["suspect_hosts"]) == [1, 2]
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    assert [e for e in events if e["event"] == "peer_lost"]


def test_consensus_timeout_falls_back_to_exit(tmp_path):
    """A second survivor that never proposes (double loss mid-reshard,
    wedged storage) times the consensus out -> False -> exit 73."""
    trips = []
    reg = MetricsRegistry()
    domain = ClusterFaultDomain(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=3, collective_timeout_s=1.0, stalled_after_s=1.0,
        dead_after_s=1.5, lease_interval_s=0.1, registry=reg,
        on_trip=trips.append)
    execs = []
    policy = elastic.ElasticPolicy(
        lease_dir=domain.lease.lease_dir, process_index=0,
        roster=[0, 1, 2], generation=0, orig_processes=3,
        max_lost_hosts=1, timeout_s=1.0, mesh_dcn=3, registry=reg)
    policy._exec = lambda *a: execs.append(a)
    domain.elastic = policy
    domain.heartbeat(force=True)
    _stale_peer(domain.lease.lease_dir, 2)
    # Host 1 is LIVE (fresh lease) but never writes a proposal: the
    # fixpoint stays incomplete and the deadline fires.
    peer1 = cluster.lease_path(domain.lease.lease_dir, 1)
    with open(peer1, "w") as f:
        f.write("{}")
    domain.trip_peer_lost({"phase": "collective", "detail": "gather",
                           "age_seconds": 1.6, "deadline_seconds": 1.0})
    domain.close()
    assert not execs
    assert len(trips) == 1
    assert reg.counter(elastic.REFUSALS_COUNTER).value == 1


def test_mutual_accusation_refuses_own_reshard(tmp_path):
    """Peers convicted US while we convicted them: the union excludes
    both; each refuses its own reshard and exits 73 (no split-brain)."""
    trips = []
    reg = MetricsRegistry()
    domain = ClusterFaultDomain(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=3, collective_timeout_s=1.0, stalled_after_s=1.0,
        dead_after_s=1.5, lease_interval_s=0.1, registry=reg,
        on_trip=trips.append)
    execs = []
    policy = elastic.ElasticPolicy(
        lease_dir=domain.lease.lease_dir, process_index=0,
        roster=[0, 1, 2], generation=0, orig_processes=3,
        max_lost_hosts=1, timeout_s=2.0, mesh_dcn=3, registry=reg)
    policy._exec = lambda *a: execs.append(a)
    domain.elastic = policy
    domain.heartbeat(force=True)
    _stale_peer(domain.lease.lease_dir, 1)
    _stale_peer(domain.lease.lease_dir, 2, age_s=0.0)  # host 2 is live
    # Host 2 already proposed gen 1 convicting US (and not host 1).
    elastic.write_proposal(domain.lease.lease_dir, 1, 2,
                           {"host": 2, "dead": [0], "coordinator": "c"})
    domain.trip_peer_lost({"phase": "collective", "detail": "gather",
                           "age_seconds": 1.6, "deadline_seconds": 1.0})
    domain.close()
    assert not execs
    assert len(trips) == 1
    assert reg.counter(elastic.REFUSALS_COUNTER).value == 1


def test_stale_newer_roster_refuses(tmp_path):
    """A roster generation newer than ours already on disk means the
    peers resharded past this (wedged) host: exit 73, never a rival
    reshard."""
    execs, trips = [], []
    reg = MetricsRegistry()
    domain = ClusterFaultDomain(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=2, collective_timeout_s=1.0, stalled_after_s=1.0,
        dead_after_s=1.5, lease_interval_s=0.1, registry=reg,
        on_trip=trips.append)
    policy = elastic.ElasticPolicy(
        lease_dir=domain.lease.lease_dir, process_index=0,
        roster=[0, 1], generation=0, orig_processes=2,
        max_lost_hosts=1, timeout_s=1.0, mesh_dcn=2, registry=reg)
    policy._exec = lambda *a: execs.append(a)
    domain.elastic = policy
    domain.heartbeat(force=True)
    _stale_peer(domain.lease.lease_dir, 1)
    elastic.write_roster(domain.lease.lease_dir,
                         {"generation": 1, "roster": [1],
                          "orig_processes": 2, "coordinator": "c"})
    domain.trip_peer_lost({"phase": "collective", "detail": "gather",
                           "age_seconds": 1.6, "deadline_seconds": 1.0})
    domain.close()
    assert not execs and len(trips) == 1


# ---------------------------------------------------------------------------
# AOT fingerprints across rosters
# ---------------------------------------------------------------------------

def test_aot_fingerprint_distinct_across_rosters():
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import aot
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        derive_degraded_config, make_mesh)

    cfg = MAMLConfig(batch_size=8, mesh_shape=(2, 4),
                     aot_store_dir="/tmp/unused",
                     cluster_collective_timeout_s=12.0, elastic_mode=1)
    dv = jax.devices()
    full_mesh = make_mesh(cfg, dv[:8])
    dcfg = derive_degraded_config(cfg, 1, 2)
    deg_mesh = make_mesh(dcfg, dv[:4])
    fp_full = aot.store_fingerprint(cfg, full_mesh, process_count=2)
    fp_deg = aot.store_fingerprint(dcfg, deg_mesh, process_count=1)
    # Survivor topology resolves its OWN fingerprint dir.
    assert fp_full != fp_deg
    # The process-count override alone separates rosters that share a
    # mesh shape (prewarming FOR a pod from a single-process box).
    assert aot.store_fingerprint(cfg, full_mesh, process_count=2) \
        != aot.store_fingerprint(cfg, full_mesh, process_count=1)
    # Elastic POLICY knobs are runtime-only: toggling them must not
    # re-fingerprint (the survivor must hit a store prewarmed without
    # them).
    assert aot.store_fingerprint(
        cfg.replace(elastic_mode=0, elastic_max_lost_hosts=1),
        full_mesh, process_count=2) == fp_full
    # The derived PAD is structural: it changes the compiled program.
    padded = dcfg.replace(elastic_pad_tasks=4, batch_size=4)
    assert aot.store_fingerprint(padded, deg_mesh, process_count=1) \
        != aot.store_fingerprint(dcfg, deg_mesh, process_count=1)


# ---------------------------------------------------------------------------
# backfill gate + re-expansion
# ---------------------------------------------------------------------------

def test_startup_disposition_and_backfill_wait(tmp_path):
    lease_dir = str(tmp_path / "cluster")
    doc = {"generation": 1, "roster": [0], "orig_processes": 2,
           "coordinator": "127.0.0.1:1"}
    # Live degraded group (fresh rank-0 lease): the excluded host must
    # wait; a member of the roster (or a full roster) proceeds.
    assert elastic.startup_disposition(1, doc, {0: 0.2}, 1.5) \
        == "backfill_wait"
    assert elastic.startup_disposition(0, doc, {0: 0.2}, 1.5) == "full"
    assert elastic.startup_disposition(1, doc, {0: 99.0}, 1.5) == "full"
    assert elastic.startup_disposition(1, None, {}, 1.5) == "full"
    full = {"generation": 2, "roster": [0, 1], "orig_processes": 2}
    assert elastic.startup_disposition(1, full, {0: 0.2}, 1.5) == "full"

    # backfill_wait returns the generation that includes us.
    elastic.write_roster(lease_dir, doc)
    lease = cluster.HeartbeatLease(lease_dir, 0, 0.05)
    lease.touch(force=True)

    def promote():
        time.sleep(0.4)
        lease.touch(force=True)
        elastic.write_roster(lease_dir, {
            "generation": 2, "roster": [0, 1], "orig_processes": 2,
            "coordinator": "127.0.0.1:2"})

    t = threading.Thread(target=promote)
    t.start()
    joined = elastic.backfill_wait(lease_dir, 1, stalled_after_s=5.0,
                                   poll_s=0.1, timeout_s=10.0)
    t.join()
    assert joined is not None and joined["generation"] == 2
    # The rejoin file is cleaned up on exit.
    assert elastic.read_rejoins(lease_dir) == []

    # A dead group (stale leases) releases the backfill to launch full.
    past = time.time() - 120.0
    os.utime(lease.path, (past, past))
    assert elastic.backfill_wait(lease_dir, 1, stalled_after_s=1.5,
                                 poll_s=0.1, timeout_s=10.0) is None


def test_maybe_re_expand_writes_full_roster_and_execs(tmp_path):
    """Epoch-boundary re-expansion: with every missing host's rejoin
    file present, the survivor writes the next-generation FULL roster
    and restarts in place (injected exec observes the env)."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    builder = ExperimentBuilder(_cfg(
        tmp_path, cluster_collective_timeout_s=300.0, elastic_mode=1))
    execs = []
    policy = elastic.ElasticPolicy(
        lease_dir=os.path.join(builder.paths["base"], cluster.LEASE_DIR),
        process_index=0, roster=[0], generation=1, orig_processes=2,
        max_lost_hosts=1, timeout_s=1.0, mesh_dcn=1,
        registry=builder.registry, jsonl=builder.jsonl)
    policy._exec = lambda exe, argv, env: execs.append(env)
    builder._elastic = policy

    # No rejoin file yet: nothing happens.
    builder._maybe_re_expand()
    assert not execs
    # The missing host announces itself.
    elastic.write_rejoin(policy.lease_dir, 1)
    builder._maybe_re_expand()
    assert len(execs) == 1
    env = execs[0]
    assert env[elastic.GEN_ENV] == "2"
    assert env[elastic.ROSTER_ENV] == "0,1"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["JAX_PROCESS_ID"] == "0"
    doc = elastic.read_roster(policy.lease_dir)
    assert doc["generation"] == 2 and doc["roster"] == [0, 1]
    events = read_jsonl(os.path.join(builder.paths["logs"],
                                     "events.jsonl"))
    rows = [e for e in events if e["event"] == elastic.RE_EXPAND_EVENT]
    assert len(rows) == 1 and rows[0]["generation"] == 2
    assert builder.registry.counter(
        elastic.RE_EXPANSIONS_COUNTER).value == 1


# ---------------------------------------------------------------------------
# structural pin: elastic_mode=0 installs nothing
# ---------------------------------------------------------------------------

def test_run_installs_elastic_iff_enabled(tmp_path, monkeypatch):
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    seen = {}

    def probe(builder):
        def stub():
            seen["cluster"] = builder._cluster
            seen["elastic"] = builder._elastic
            seen["attached"] = (builder._cluster.elastic
                                if builder._cluster is not None else None)
            return {"paused_at_iter": builder.current_iter}
        return stub

    # Cluster armed, elastic OFF (the default): no policy anywhere —
    # the exit-73 path is byte-for-byte the PR 8 one.
    builder = ExperimentBuilder(_cfg(tmp_path / "off",
                                     cluster_collective_timeout_s=30.0))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert seen["cluster"] is not None
    assert seen["elastic"] is None and seen["attached"] is None

    # Elastic ON: the policy is attached to the domain with the
    # generation-0 identity, and restored after the run.
    builder = ExperimentBuilder(_cfg(tmp_path / "on",
                                     cluster_collective_timeout_s=30.0,
                                     elastic_mode=1))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert isinstance(seen["elastic"], elastic.ElasticPolicy)
    assert seen["attached"] is seen["elastic"]
    assert seen["elastic"].roster == (0,)
    assert seen["elastic"].generation == 0
    assert not seen["elastic"].degraded
    assert builder._elastic is None  # scoped lifetime


def test_elastic_armed_run_end_to_end_report(tmp_path):
    """One tiny real run with elastic armed (nothing trips): completes,
    and the telemetry report renders the v10 elastic section with
    measured zeros and generation 0."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.telemetry import summarize_events

    builder = ExperimentBuilder(_cfg(
        tmp_path, cluster_collective_timeout_s=300.0,
        cluster_lease_interval_s=0.05, elastic_mode=1,
        dispatch_sync_every=1))
    result = builder.run_experiment()
    assert "test_accuracy_mean" in result
    events = read_jsonl(os.path.join(builder.paths["logs"],
                                     "events.jsonl"))
    sec = summarize_events(events)["elastic"]
    assert sec["reshards"] == 0 and sec["re_expansions"] == 0
    assert sec["degraded_epochs"] == 0
    assert sec["generation"] == 0
    assert not [e for e in events
                if e.get("event") == elastic.RESHARD_EVENT]

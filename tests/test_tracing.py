"""Tests for profiling/structured-logging (utils/tracing.py) and its
ExperimentBuilder integration (events.jsonl, profiler fail-soft)."""

import json
import os
import time

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.utils.tracing import (
    JsonlLogger, StepTimer, nearest_rank, profile_trace, read_jsonl)


def test_jsonl_logger_roundtrip(tmp_path):
    log = JsonlLogger(str(tmp_path / "events.jsonl"))
    log.log("train_epoch", epoch=0, loss=1.5)
    log.log("checkpoint", epoch=0, path="x.ckpt")
    rows = read_jsonl(log.path)
    assert [r["event"] for r in rows] == ["train_epoch", "checkpoint"]
    assert rows[0]["loss"] == 1.5
    assert all("ts" in r for r in rows)


def test_jsonl_logger_coerces_numpy_and_objects(tmp_path):
    log = JsonlLogger(str(tmp_path / "e.jsonl"))
    row = log.log("m", acc=np.float32(0.5), n=np.int64(3),
                  nested={"a": np.float64(1.0)}, seq=(np.int32(1), 2),
                  obj=object())
    # written line must be valid JSON
    parsed = read_jsonl(log.path)[0]
    assert parsed["acc"] == 0.5
    assert parsed["n"] == 3
    assert parsed["nested"]["a"] == 1.0
    assert parsed["seq"] == [1, 2]
    assert isinstance(parsed["obj"], str)
    assert row["acc"] == 0.5


def test_step_timer_summary():
    t = StepTimer()
    t.start()
    for _ in range(5):
        time.sleep(0.01)
        t.tick()
    s = t.summary(tasks_per_step=4, n_chips=2)
    assert s["steps"] == 5
    assert s["mean_step_seconds"] >= 0.009
    assert s["p50_step_seconds"] <= s["p95_step_seconds"] * 1.5
    assert s["meta_tasks_per_sec_per_chip"] == pytest.approx(
        s["meta_tasks_per_sec"] / 2)
    t.reset()
    assert t.summary(1) == {}


def test_jsonl_logger_nonfinite_floats_stay_parseable(tmp_path):
    """A NaN loss must not corrupt the log: json.dumps would write bare
    NaN/Infinity tokens (invalid JSON); the logger coerces them to null
    and the stream round-trips through read_jsonl (ISSUE 1 satellite)."""
    log = JsonlLogger(str(tmp_path / "e.jsonl"))
    row = log.log("train_epoch", loss=float("nan"), lr=float("inf"),
                  acc=np.float32("nan"), neg=float("-inf"),
                  nested={"a": float("nan")}, seq=[1.0, float("inf")],
                  fine=0.5)
    parsed = read_jsonl(log.path)  # must parse under strict JSON rules
    assert parsed[0]["loss"] is None
    assert parsed[0]["lr"] is None
    assert parsed[0]["acc"] is None
    assert parsed[0]["neg"] is None
    assert parsed[0]["nested"]["a"] is None
    assert parsed[0]["seq"] == [1.0, None]
    assert parsed[0]["fine"] == 0.5
    assert row["loss"] is None  # returned row matches what was written


def test_step_timer_quantiles_nearest_rank():
    """Quantiles pinned on known sequences: nearest-rank, i.e. the
    ceil(q*n)-th smallest (ISSUE 1 satellite — the old p95 indexed
    int(0.95*n), off by one whole rank when 0.95*n is integral)."""
    t = StepTimer()
    t._durations = [float(v) for v in range(1, 21)]  # 1..20
    s = t.summary(tasks_per_step=1)
    assert s["p95_step_seconds"] == 19.0  # ceil(19)=19th; old code said 20
    assert s["p50_step_seconds"] == 10.0
    t._durations = [5.0, 1.0, 3.0, 2.0, 4.0]  # unsorted on purpose
    s = t.summary(tasks_per_step=1)
    assert s["p95_step_seconds"] == 5.0  # ceil(4.75)=5th smallest
    assert s["p50_step_seconds"] == 3.0
    t._durations = [7.5]
    s = t.summary(tasks_per_step=1)
    assert s["p95_step_seconds"] == 7.5
    assert s["p50_step_seconds"] == 7.5


def test_nearest_rank_helper_contract():
    assert nearest_rank([1.0, 2.0, 3.0, 4.0], 1.0) == 4.0
    assert nearest_rank([1.0, 2.0, 3.0, 4.0], 0.25) == 1.0
    assert nearest_rank([1.0, 2.0], 0.5) == 1.0
    with pytest.raises(ValueError):
        nearest_rank([], 0.5)
    with pytest.raises(ValueError):
        nearest_rank([1.0], 0.0)


def test_profile_trace_noop_without_dir():
    with profile_trace(None):
        pass  # must not touch jax at all


def test_profile_trace_fail_soft(tmp_path, monkeypatch):
    import jax
    def boom(*a, **k):
        raise RuntimeError("backend cannot trace")
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.warns(UserWarning, match="profiling unavailable"):
        with profile_trace(str(tmp_path), "t"):
            ran = True
    assert ran


def test_experiment_writes_events_jsonl(tmp_path):
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    cfg = MAMLConfig(
        experiment_name="trace_smoke",
        experiment_root=str(tmp_path),
        dataset_name="synthetic",
        image_height=12, image_width=12, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=1, batch_size=2,
        cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        second_order=False, use_multi_step_loss_optimization=False,
        total_epochs=1, total_iter_per_epoch=2,
        num_evaluation_tasks=2, max_models_to_save=2)
    result = ExperimentBuilder(cfg).run_experiment()
    events = read_jsonl(os.path.join(
        str(tmp_path), "trace_smoke", "logs", "events.jsonl"))
    kinds = [e["event"] for e in events]
    assert "train_epoch" in kinds
    assert "validation" in kinds
    assert "checkpoint" in kinds
    assert "test_protocol" in kinds
    tp = [e for e in events if e["event"] == "train_epoch"][0]
    assert tp["meta_tasks_per_sec"] > 0
    assert "test_accuracy_mean" in [
        e for e in events if e["event"] == "test_protocol"][0]
    assert 0.0 <= result["test_accuracy_mean"] <= 1.0

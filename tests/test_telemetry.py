"""Telemetry subsystem unit tests: registry, instruments, aggregation.

Covers the multi-host single-writer contract (ISSUE 1 satellite): a
disabled logger still returns coerced rows but writes nothing, and the
process-0 aggregation path produces ONE line per heartbeat fleet-wide,
not one per host.
"""

import json
import os
import threading

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.telemetry import (
    COMPILE_COUNT, COMPILE_SECONDS, CompileWatcher, FeedStallMeter,
    MetricsRegistry, device_memory_stats, emit_heartbeat,
    exponential_buckets, host_step_skew)
from howtotrainyourmamlpytorch_tpu.utils.tracing import (
    JsonlLogger, read_jsonl)


# -- registry -------------------------------------------------------------

def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("compile/count")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    assert reg.counter("compile/count") is c  # get-or-create
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("val/accuracy")
    assert g.value is None
    g.set(0.5)
    g.set(0.25)  # gauges overwrite
    assert g.value == 0.25


def test_registry_rejects_type_confusion():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_exponential_buckets_spacing():
    b = exponential_buckets(0.001, 2.0, 5)
    assert b == (0.001, 0.002, 0.004, 0.008, 0.016)
    with pytest.raises(ValueError):
        exponential_buckets(0, 2, 5)


def test_histogram_observe_and_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("t", buckets=[1.0, 2.0, 4.0, 8.0])
    for v in [0.5, 1.5, 1.5, 3.0, 9.0]:
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(15.5)
    # nearest-rank(0.5) of 5 obs = 3rd smallest (1.5) -> bucket bound 2.0
    assert h.quantile(0.5) == 2.0
    # nearest-rank(0.95) = 5th smallest (9.0) -> overflow reports last bound
    assert h.quantile(0.95) == 8.0
    h.observe(float("nan"))  # dropped, never corrupts the sum
    assert h.count == 5
    snap = h.snapshot()
    assert snap["count"] == 5 and snap["p50"] == 2.0


def test_registry_snapshot_and_jsonl_flush(tmp_path):
    reg = MetricsRegistry()
    reg.counter("compile/count").inc(3)
    reg.gauge("val/accuracy").set(0.5)
    reg.histogram("step_seconds", buckets=[0.1, 1.0]).observe(0.05)
    log = JsonlLogger(str(tmp_path / "e.jsonl"))
    reg.flush_jsonl(log, epoch=4)
    row = read_jsonl(log.path)[0]
    assert row["event"] == "metrics" and row["epoch"] == 4
    m = row["metrics"]
    assert m["compile/count"] == 3.0
    assert m["val/accuracy"] == 0.5
    assert m["step_seconds"]["count"] == 1


def test_write_prometheus_textfile(tmp_path):
    reg = MetricsRegistry()
    reg.counter("compile/seconds").inc(1.25)
    reg.gauge("val/accuracy").set(0.5)
    reg.gauge("never/set")  # valueless gauges are omitted
    h = reg.histogram("step_seconds", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    path = str(tmp_path / "metrics.prom")
    reg.write_prometheus(path)
    text = open(path).read()
    assert "# TYPE compile_seconds counter" in text
    assert "compile_seconds 1.25" in text
    assert "val_accuracy 0.5" in text
    assert "never_set" not in text
    assert 'step_seconds_bucket{le="0.1"} 1' in text
    assert 'step_seconds_bucket{le="+Inf"} 2' in text
    assert "step_seconds_count 2" in text
    assert not [p for p in os.listdir(tmp_path)
                if p.startswith("metrics.prom.tmp")]  # atomic rename


def test_registry_thread_safety_smoke():
    reg = MetricsRegistry()
    c = reg.counter("n")
    h = reg.histogram("h", buckets=[1.0])

    def work():
        for _ in range(500):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 2000
    assert h.count == 2000


# -- instruments ----------------------------------------------------------

def test_compile_watcher_counts_fresh_jit():
    import jax
    import jax.numpy as jnp
    reg = MetricsRegistry()
    watch = CompileWatcher.install(reg)
    assert watch.installed, "jax.monitoring hook unavailable on this jax"
    try:
        # A never-before-seen shape forces a real backend compile.
        @jax.jit
        def f(x):
            return x * 3 + 1
        f(jnp.zeros((3, 7, 11)))
        assert watch.count >= 1
        assert watch.seconds > 0
        assert watch.saw_compile  # event-key liveness flag (consumers
        #            treat installed-but-never-seen as "unavailable")
        before = watch.count
    finally:
        watch.uninstall()

    @jax.jit
    def g(x):
        return x - 2
    g(jnp.zeros((5, 13)))
    assert reg.counter(COMPILE_COUNT).value == before  # detached
    assert reg.counter(COMPILE_SECONDS).value > 0


def test_device_memory_stats_fail_soft():
    # The CPU backend reports no allocator stats: the telemetry layer
    # must yield None (-> "unavailable"), never a fake zero.
    assert device_memory_stats() is None
    class Boom:
        def memory_stats(self):
            raise RuntimeError("no stats RPC")
    assert device_memory_stats([Boom()]) is None


def test_device_memory_stats_aggregates_fakes():
    class Dev:
        def __init__(self, live, peak):
            self._s = {"bytes_in_use": live, "peak_bytes_in_use": peak}
        def memory_stats(self):
            return self._s
    out = device_memory_stats([Dev(100, 150), Dev(300, 400)])
    assert out == {"live_bytes_total": 400,
                   "live_bytes_max_device": 300,
                   "peak_bytes_max_device": 400}


def test_feed_stall_meter_delta():
    m = FeedStallMeter()
    m.record_wait(3.0)
    m.record_dispatch(1.0)
    snap1 = m.snapshot()
    d1 = FeedStallMeter.delta(snap1, None)
    assert d1["feed_stall_frac"] == pytest.approx(0.75)
    m.record_wait(0.0)
    m.record_dispatch(4.0)
    d2 = FeedStallMeter.delta(m.snapshot(), snap1)
    assert d2["feed_wait_seconds"] == pytest.approx(0.0)
    assert d2["feed_stall_frac"] == pytest.approx(0.0)
    # No elapsed time -> 0.0, not a ZeroDivisionError
    empty = FeedStallMeter()
    assert FeedStallMeter.delta(empty.snapshot(),
                                None)["feed_stall_frac"] == 0.0


def test_loader_meters_train_feed():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader)
    cfg = MAMLConfig(
        dataset_name="synthetic", image_height=8, image_width=8,
        image_channels=1, num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=1, batch_size=2, num_stages=2)
    loader = MetaLearningDataLoader(cfg)
    for _ in loader.get_train_batches(0, 3):
        pass
    snap = loader.feed.snapshot()
    assert snap["feed_batches"] >= 3
    assert snap["feed_wait_seconds"] > 0
    # Eval sweeps are not metered (feed_stall_frac diagnoses training).
    before = loader.feed.snapshot()
    for _ in loader.get_val_batches():
        break
    assert loader.feed.snapshot() == before


# -- single-writer + aggregation (ISSUE 1 satellite) ----------------------

def test_disabled_logger_writes_nothing_but_returns_coerced_rows(tmp_path):
    path = str(tmp_path / "sub" / "events.jsonl")
    log = JsonlLogger(path, enabled=False)
    row = log.log("train_epoch", loss=np.float32(0.5),
                  bad=float("nan"), obj=object())
    # Row is fully coerced — non-main processes can still compute with it.
    assert row["loss"] == 0.5
    assert row["bad"] is None
    assert isinstance(row["obj"], str)
    assert not os.path.exists(path)
    assert not os.path.exists(os.path.dirname(path))  # no dir scaffolding


def test_heartbeat_single_line_per_beat_not_per_host(tmp_path):
    # Two simulated hosts run the same program point: host 0 owns the
    # enabled logger, host 1 the disabled one. The fleet must emit ONE
    # line per heartbeat, while every host computes the identical row.
    path = str(tmp_path / "events.jsonl")
    loggers = [JsonlLogger(path, enabled=True),
               JsonlLogger(path, enabled=False)]
    for beat in range(3):
        rows = [emit_heartbeat(lg, epoch=0, iteration=beat,
                               local_mean_step_seconds=0.125,
                               process_index=i)
                for i, lg in enumerate(loggers)]
        assert rows[0]["hosts"] == rows[1]["hosts"] == 1
        assert rows[0]["skew_frac"] == rows[1]["skew_frac"]
    lines = read_jsonl(path)
    assert len(lines) == 3  # one per heartbeat, NOT one per host
    assert all(e["event"] == "heartbeat" for e in lines)
    assert lines[-1]["iter"] == 2


def test_host_step_skew_single_process():
    skew = host_step_skew(0.25)
    assert skew["hosts"] == 1
    assert skew["host_mean_step_seconds"] == [0.25]
    assert skew["skew_frac"] == 0.0
    assert skew["slowest_host"] == 0
    # Degenerate (no positive step time yet) stays well-defined.
    zero = host_step_skew(0.0)
    assert zero["skew_frac"] == 0.0


def test_heartbeat_payload_round_trips_json(tmp_path):
    log = JsonlLogger(str(tmp_path / "e.jsonl"))
    emit_heartbeat(log, epoch=2, iteration=10,
                   local_mean_step_seconds=0.5, process_index=0,
                   memory=None, feed_stall_frac=0.1)
    row = read_jsonl(log.path)[0]
    assert row["epoch"] == 2 and row["iter"] == 10
    assert row["memory"] is None
    assert row["feed_stall_frac"] == 0.1
    json.dumps(row)  # strictly serializable

"""Watchdog & flight recorder units (ISSUE 6).

Tier-1 keeps the cheap layers — ring-buffer bounds/thread-safety/dump
ordering, beacon/deadline math (disabled never trips; the compile
budget is separate from the step budget), hang fault-kind parsing,
trip-writes-bundle with an injected trip action, config validation,
structural install/uninstall around a (stubbed) run. The system proofs
(hang_feed → stacks → exit 74 → restart, watchdog-on/off parity) live
in tests/test_resilience.py's slow profile and scripts/chaos_run.py.
"""

import json
import os
import threading
import time

import pytest

from howtotrainyourmamlpytorch_tpu import resilience
from howtotrainyourmamlpytorch_tpu.resilience import (
    faults, flightrec, watchdog)
from howtotrainyourmamlpytorch_tpu.resilience.faults import FaultPlan
from howtotrainyourmamlpytorch_tpu.resilience.flightrec import (
    FlightRecorder, write_crash_bundle)
from howtotrainyourmamlpytorch_tpu.resilience.watchdog import (
    ProgressBeacon, Watchdog)
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry
from howtotrainyourmamlpytorch_tpu.utils.tracing import JsonlLogger


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Every test starts/ends with no beacon, recorder, fault plan or
    resilience registry installed (runs/engines install their own)."""
    faults.configure("")
    prev_reg = resilience.set_registry(None)
    prev_beacon = watchdog.install_beacon(None)
    prev_rec = flightrec.install(None)
    yield
    faults.configure("")
    resilience.set_registry(prev_reg)
    watchdog.install_beacon(prev_beacon)
    flightrec.install(prev_rec)


# ---------------------------------------------------------------------------
# flight recorder ring
# ---------------------------------------------------------------------------

def test_ring_bounded_and_ordered():
    rec = FlightRecorder(capacity=8)
    for i in range(30):
        rec.record("phase", phase="step", i=i)
    assert len(rec) == 8
    # Oldest dropped; survivors in append order.
    assert [e["i"] for e in rec.events()] == list(range(22, 30))
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_ring_thread_safe_append():
    rec = FlightRecorder(capacity=64)
    n_threads, per_thread = 8, 400

    def hammer(tid):
        for i in range(per_thread):
            rec.record("phase", tid=tid, i=i)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.events()
    assert len(rec) == 64
    # Monotone timestamps prove snapshot consistency under concurrency.
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)
    # Per-thread suborder preserved (each thread's i strictly increases).
    for tid in range(n_threads):
        own = [e["i"] for e in events if e["tid"] == tid]
        assert own == sorted(own)


def test_ring_dump_jsonl_ordering(tmp_path):
    rec = FlightRecorder(capacity=4)
    for i in range(6):
        rec.record("phase", phase=f"p{i}")
    path = tmp_path / "flight.jsonl"
    assert rec.dump_jsonl(str(path)) == 4
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["phase"] for r in rows] == ["p2", "p3", "p4", "p5"]
    assert all(r["kind"] == "phase" and "t" in r and "ts" in r
               for r in rows)


def test_module_record_is_noop_without_recorder():
    assert flightrec.get() is None
    flightrec.record("phase", phase="step")  # must not raise
    rec = FlightRecorder(4)
    assert flightrec.install(rec) is None
    flightrec.record("phase", phase="step")
    assert len(rec) == 1
    assert flightrec.install(None) is rec


# ---------------------------------------------------------------------------
# beacon + deadline math
# ---------------------------------------------------------------------------

def test_beacon_stamp_age_and_flight_record():
    rec = FlightRecorder(16)
    flightrec.install(rec)
    b = ProgressBeacon()
    b.stamp("step", detail=7)
    phase, stamp, detail = b.current()
    assert phase == "step" and detail == 7
    assert b.age(now=stamp + 2.5) == pytest.approx(2.5)
    # Every stamp feeds the flight ring (the ring IS the phase record).
    last = rec.events()[-1]
    assert last["kind"] == "phase"
    assert last["phase"] == "step" and last["detail"] == 7


def test_beacon_phase_scope_restores_with_fresh_stamp():
    b = ProgressBeacon()
    b.stamp("step", detail=3)
    _, t0, _ = b.current()
    with b.phase("collective", detail="barrier"):
        assert b.current()[0] == "collective"
    phase, t1, detail = b.current()
    assert phase == "step" and detail == 3
    assert t1 >= t0  # restored with a FRESH stamp: scoped work counts
                     # as progress


def test_module_stamp_and_phase_noop_without_beacon():
    watchdog.stamp("step", detail=1)  # must not raise
    with watchdog.phase("collective"):
        pass
    b = ProgressBeacon()
    watchdog.install_beacon(b)
    watchdog.stamp("feed")
    assert b.current()[0] == "feed"
    with watchdog.phase("collective"):
        assert b.current()[0] == "collective"
    assert b.current()[0] == "feed"


def test_deadline_disabled_never_trips():
    b = ProgressBeacon()
    b.stamp("step")
    # Per-phase zero: no deadline for that phase.
    wd = Watchdog(b, {"step": 0.0, "feed": 5.0}, bundle_dir="/nonexistent")
    _, stamp, _ = b.current()
    assert wd.check(now=stamp + 1e9) is None
    # All-zero: the watchdog is disabled outright (start() is a no-op).
    wd0 = Watchdog(b, {"step": 0.0, "feed": 0.0},
                   bundle_dir="/nonexistent")
    assert not wd0.enabled
    assert wd0.check(now=stamp + 1e9) is None
    wd0.start()
    assert wd0._thread is None
    # Unknown/bookkeeping phases ('idle') never trip even when enabled.
    b.stamp("idle")
    _, stamp, _ = b.current()
    assert wd.check(now=stamp + 1e9) is None


def test_deadline_compile_budget_separate_from_step():
    b = ProgressBeacon()
    wd = Watchdog(b, {"step": 1.0, "compile": 100.0},
                  bundle_dir="/nonexistent")
    b.stamp("compile")
    _, stamp, _ = b.current()
    assert wd.check(now=stamp + 50.0) is None       # within compile budget
    info = wd.check(now=stamp + 101.0)
    assert info["phase"] == "compile"
    b.stamp("step", detail=12)
    _, stamp, _ = b.current()
    assert wd.check(now=stamp + 0.5) is None
    info = wd.check(now=stamp + 2.0)                # step budget is its own
    assert info["phase"] == "step" and info["detail"] == 12
    assert info["age_seconds"] == pytest.approx(2.0)
    assert info["deadline_seconds"] == pytest.approx(1.0)


def test_watchdog_poll_interval_auto_and_override():
    b = ProgressBeacon()
    assert Watchdog(b, {"step": 2.0}, bundle_dir="x").poll_interval_s \
        == pytest.approx(0.5)
    assert Watchdog(b, {"step": 1e6}, bundle_dir="x").poll_interval_s \
        == pytest.approx(5.0)
    assert Watchdog(b, {"step": 0.01}, bundle_dir="x").poll_interval_s \
        == pytest.approx(0.05)
    assert Watchdog(b, {"step": 2.0}, bundle_dir="x",
                    poll_interval_s=1.25).poll_interval_s \
        == pytest.approx(1.25)


# ---------------------------------------------------------------------------
# trip path
# ---------------------------------------------------------------------------

def test_trip_writes_bundle_counts_and_flushes(tmp_path):
    reg = MetricsRegistry()
    rec = FlightRecorder(16)
    flightrec.install(rec)
    b = ProgressBeacon()
    watchdog.install_beacon(b)
    b.stamp("feed", detail="train")
    b.stamp("step", detail=41)
    jsonl = JsonlLogger(str(tmp_path / "events.jsonl"))
    bundle = str(tmp_path / "crash_bundle")
    trips = []
    wd = Watchdog(b, {"step": 0.5}, bundle_dir=bundle, registry=reg,
                  jsonl=jsonl, prom_path=str(tmp_path / "metrics.prom"),
                  on_trip=trips.append)
    info = wd.check(now=b.current()[1] + 1.0)
    assert info is not None
    wd.trip(info)
    assert trips == [info]  # injected action ran INSTEAD of os._exit
    # Bundle layout: all-thread stacks, the flight ring, crash context.
    stacks = open(os.path.join(bundle, "stacks.txt")).read()
    assert "Thread" in stacks or "File" in stacks
    rows = [json.loads(line) for line in
            open(os.path.join(bundle, "flight.jsonl"))]
    phases = [r for r in rows if r["kind"] == "phase"]
    assert [p["phase"] for p in phases] == ["feed", "step"]
    assert rows[-1]["kind"] == "watchdog_trip"
    crash = json.load(open(os.path.join(bundle, "crash.json")))
    assert crash["reason"] == "hung_step"
    assert crash["phase"] == "step" and crash["detail"] == 41
    assert crash["metrics"]["watchdog/trips"] == 1
    # Telemetry flushed: trip row + registry snapshot row + Prometheus.
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    trip_rows = [e for e in events if e["event"] == "watchdog_trip"]
    assert len(trip_rows) == 1 and trip_rows[0]["phase"] == "step"
    metric_rows = [e for e in events if e["event"] == "metrics"]
    assert metric_rows[-1]["metrics"]["watchdog/trips"] == 1
    assert "watchdog_trips 1" in open(
        str(tmp_path / "metrics.prom")).read()
    assert reg.counter(watchdog.TRIPS_COUNTER).value == 1


def test_watchdog_thread_trips_on_real_stall(tmp_path):
    """The daemon-thread path end-to-end (with an injected trip action
    instead of os._exit): a stamped phase left to age past a tight
    deadline trips within ~2 poll intervals."""
    b = ProgressBeacon()
    b.stamp("feed")
    tripped = threading.Event()
    wd = Watchdog(b, {"feed": 0.15}, bundle_dir=str(tmp_path / "b"),
                  poll_interval_s=0.05,
                  on_trip=lambda info: tripped.set())
    wd.start()
    try:
        assert tripped.wait(timeout=5.0)
        assert wd.tripped["phase"] == "feed"
    finally:
        wd.stop()
    assert os.path.exists(tmp_path / "b" / "stacks.txt")


def test_watchdog_thread_quiet_while_progressing(tmp_path):
    """Fresh stamps keep the watchdog silent; stop() joins the thread."""
    b = ProgressBeacon()
    tripped = threading.Event()
    # Deadline far above the stamp cadence so a loaded CI box's
    # scheduling jitter can't fake a stall.
    wd = Watchdog(b, {"step": 2.0}, bundle_dir=str(tmp_path / "b"),
                  poll_interval_s=0.05,
                  on_trip=lambda info: tripped.set())
    wd.start()
    for i in range(12):
        b.stamp("step", detail=i)
        time.sleep(0.05)
    wd.stop()
    assert not tripped.is_set()
    assert wd._thread is None


# ---------------------------------------------------------------------------
# fault kinds + crash-bundle helper
# ---------------------------------------------------------------------------

def test_hang_fault_kinds_parse_and_fire():
    plan = FaultPlan.parse("hang_feed@5; hang_collective@2, hang_step@3")
    assert {s.kind for s in plan.specs} == {"hang_feed", "hang_collective",
                                            "hang_step"}
    assert plan.maybe_fire("hang_feed", step=5)
    assert not plan.maybe_fire("hang_feed", step=5)  # at most once
    # hang_collective is call-counted: fires on the 2nd collective.
    assert [plan.maybe_fire("hang_collective") for _ in range(3)] \
        == [False, True, False]
    with pytest.raises(ValueError):
        FaultPlan.parse("hang_nope@1")


def test_hang_sleep_is_bounded_and_env_tunable(monkeypatch):
    t0 = time.monotonic()
    faults.hang(seconds=0.05)
    assert 0.04 <= time.monotonic() - t0 < 2.0
    monkeypatch.setenv(faults.HANG_SECONDS_ENV, "0.05")
    t0 = time.monotonic()
    faults.hang()
    assert time.monotonic() - t0 < 2.0
    monkeypatch.setenv(faults.HANG_SECONDS_ENV, "not-a-number")
    t0 = time.monotonic()
    faults.hang(seconds=0.0)  # explicit arg still wins
    assert time.monotonic() - t0 < 1.0


def test_injected_collective_hang_is_single_process_simulable(monkeypatch):
    """hang_collective must fire on this (single-process) box — the
    chaos hook sits before the collective's early return."""
    from howtotrainyourmamlpytorch_tpu.parallel import multihost
    monkeypatch.setenv(faults.HANG_SECONDS_ENV, "0.01")
    faults.configure("hang_collective@1")
    rec = FlightRecorder(8)
    flightrec.install(rec)
    t0 = time.monotonic()
    assert multihost.any_process_true(False) is False
    assert time.monotonic() - t0 < 2.0
    assert any(e["kind"] == "fault" and e["fault"] == "hang_collective"
               for e in rec.events())


def test_write_crash_bundle_without_recorder(tmp_path):
    """The bundle degrades gracefully: no recorder -> no flight.jsonl,
    stacks + crash.json still written (signal escalation can run before
    any watchdog is installed)."""
    bundle = write_crash_bundle(str(tmp_path / "b"), reason="test",
                                info={"iter": 3})
    assert os.path.getsize(os.path.join(bundle, "stacks.txt")) > 0
    assert not os.path.exists(os.path.join(bundle, "flight.jsonl"))
    crash = json.load(open(os.path.join(bundle, "crash.json")))
    assert crash["reason"] == "test" and crash["iter"] == 3


# ---------------------------------------------------------------------------
# config + wiring structure
# ---------------------------------------------------------------------------

def test_config_watchdog_validation():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    for field in ("watchdog_step_timeout_s", "watchdog_feed_timeout_s",
                  "watchdog_collective_timeout_s",
                  "watchdog_compile_timeout_s",
                  "watchdog_serve_timeout_s",
                  "watchdog_ckpt_timeout_s",
                  "watchdog_poll_interval_s"):
        with pytest.raises(ValueError, match=field):
            MAMLConfig(**{field: -1.0})
    with pytest.raises(ValueError, match="flight_recorder_events"):
        MAMLConfig(flight_recorder_events=0)
    cfg = MAMLConfig()
    assert watchdog.watchdog_enabled(cfg)  # generous defaults are ON
    # The compile budget defaults far above the step budget (a cold pod
    # compile must not false-trip).
    d = watchdog.deadlines_from_config(cfg)
    assert d["compile"] > d["step"]
    off = cfg.replace(**{f: 0.0 for f in (
        "watchdog_step_timeout_s", "watchdog_feed_timeout_s",
        "watchdog_collective_timeout_s", "watchdog_compile_timeout_s",
        "watchdog_serve_timeout_s", "watchdog_ckpt_timeout_s")})
    assert not watchdog.watchdog_enabled(off)


_ALL_TIMEOUTS = ("watchdog_step_timeout_s", "watchdog_feed_timeout_s",
                 "watchdog_collective_timeout_s",
                 "watchdog_compile_timeout_s", "watchdog_serve_timeout_s",
                 "watchdog_ckpt_timeout_s")


def test_run_installs_watchdog_iff_enabled(tmp_path, monkeypatch):
    """Structural half of the acceptance pin: with every timeout 0 a run
    installs NO beacon/recorder/watchdog (each site stays a single None
    check); with the defaults it installs all three for the run's
    duration and restores the process state after. The training-parity
    half is the slow test in test_resilience.py."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    seen = {}

    def probe(builder):
        def stub():
            seen["beacon"] = watchdog.get_beacon()
            seen["recorder"] = flightrec.get()
            seen["watchdog"] = builder._watchdog
            return {"paused_at_iter": builder.current_iter}
        return stub

    off = {f: 0.0 for f in _ALL_TIMEOUTS}
    builder = ExperimentBuilder(_cfg(tmp_path / "off", **off))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert seen == {"beacon": None, "recorder": None, "watchdog": None}

    builder = ExperimentBuilder(_cfg(tmp_path / "on"))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert isinstance(seen["beacon"], ProgressBeacon)
    assert isinstance(seen["recorder"], FlightRecorder)
    assert seen["watchdog"].enabled
    # Scoped lifetime: everything restored/stopped after the run.
    assert watchdog.get_beacon() is None
    assert flightrec.get() is None
    assert builder._watchdog is None


def test_unhandled_exception_dumps_flight_bundle(tmp_path, monkeypatch):
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    builder = ExperimentBuilder(_cfg(tmp_path))

    def boom():
        raise RuntimeError("kaboom")

    monkeypatch.setattr(builder, "_run_experiment", boom)
    with pytest.raises(RuntimeError, match="kaboom"):
        builder.run_experiment()
    bundle = builder._bundle_dir()
    assert os.path.exists(os.path.join(bundle, "flight.jsonl"))
    crash = json.load(open(os.path.join(bundle, "crash.json")))
    assert crash["reason"] == "exception:RuntimeError"
    assert "kaboom" in crash["error"]

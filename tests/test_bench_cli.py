"""bench.py end-to-end CLI contract (slow profile).

The driver's round artifact is `python bench.py`'s LAST stdout JSON
line; VERDICT r3 item 6 requires it to carry the headline, run-weighted
and strict-b8 numbers in ONE object. --quick executes every leg of that
capture path at tiny shapes, so this test pins the whole contract
mechanically — argparse wiring, backend preamble, all three legs, the
strict-superset line discipline — the way capture day exercises it.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_quick_emits_full_capture_contract():
    env = dict(os.environ, MAML_JAX_PLATFORM="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick",
         "--steps", "3"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, r.stdout
    first, last = json.loads(lines[0]), json.loads(lines[-1])
    # Headline keys, printed immediately (fail-soft discipline).
    for key in ("metric", "value", "unit", "vs_baseline", "workload"):
        assert key in first, key
    assert first["metric"] == "meta_tasks_per_sec_per_chip"
    assert first["value"] > 0
    # Observability keys (ISSUE 1): additive to the artifact, frozen at
    # first print like every headline key. bench routes every AOT build
    # through timed_compile into its registry, so compile stats are
    # always measured (never null) — a wiring regression must fail here.
    assert first["compile_count"] > 0
    assert first["compile_seconds"] > 0
    # Flag-set attribution (ISSUE 15): every BENCH_* row names the
    # compiler options it ran with and their source — compiler
    # defaults here.
    assert first["compiler_options"] == {}
    assert first["compiler_options_source"] == "none"
    assert first["feed_stall_frac"] == 0.0  # synthetic device-resident
    #                                         batch: no host feed to stall
    # Data-plane keys (ISSUE 4): the dataset open probe is always
    # measured and non-null — with no dataset installed the flagship
    # config resolves to the synthetic fallback.
    assert first["dataset_open_seconds"] > 0
    assert first["dataset_source_kind"] == "synthetic"
    # Health keys (ISSUE 7): fail-soft null when the benched config
    # leaves health_metrics_every_n_steps at 0 (the flagship default) —
    # the serve-field convention. The non-null producer is the
    # health-enabled --config leg (test below).
    assert first["outer_grad_norm"] is None
    assert first["health_overhead_frac"] is None
    # Checkpoint keys (ISSUE 8): one real synchronous save is timed
    # against a temp dir — always measured (fail-soft null only on a
    # broken temp mount), and the epoch-stall fraction is a proper
    # fraction.
    assert first["ckpt_save_seconds"] > 0
    assert 0 <= first["ckpt_blocking_frac"] < 1
    # Warm-start keys (ISSUE 10): cold (trace+lower+compile+step) vs
    # warm (AOT-store deserialize+step) first-step latency through a
    # REAL serialize/deserialize round trip of the headline executable.
    # Null at FIRST print (the leg costs an extra compile and runs
    # after the headline, the kill-resilience discipline); the LAST
    # line carries them non-null, with warm strictly smaller (the
    # restart win the subsystem exists to deliver).
    assert first["time_to_first_step_cold_s"] is None
    assert first["time_to_first_step_warm_s"] is None
    assert last["time_to_first_step_cold_s"] > 0
    assert last["time_to_first_step_warm_s"] > 0
    assert (last["time_to_first_step_warm_s"]
            < last["time_to_first_step_cold_s"])
    # Perf-lab keys (ISSUE 14): peak_flops_source is known at headline
    # time ("unknown" on CPU — honest, not a guessed peak); the
    # profiled-window keys are null at first print and measured on the
    # enriched/LAST lines (fail-soft non-null: a CPU backend traces).
    assert first["peak_flops_source"] in ("table", "override", "unknown")
    assert first["mfu_compute_frac"] is None
    assert first["dispatch_gap_frac"] is None
    assert "perf_profile_error" not in last, last
    assert 0 < last["mfu_compute_frac"] <= 1
    assert 0 < last["dispatch_gap_frac"] <= 1
    assert isinstance(last["top_executable"], str)
    assert last["top_executable_bound"] in ("compute", "memory",
                                            "unknown")
    # The authoritative LAST line is a strict superset with all three
    # measurement groups.
    for key in ("value", "run_weighted_tasks_per_sec_per_chip",
                "vs_baseline_run_weighted",
                "strict_b8_tasks_per_sec_per_chip",
                "vs_baseline_strict_b8"):
        assert key in last, (key, last)
    assert last["strict_b8_tasks_per_sec_per_chip"] > 0
    measured_after_first = {"time_to_first_step_cold_s",
                            "time_to_first_step_warm_s",
                            "mfu_compute_frac", "dispatch_gap_frac",
                            "top_executable", "top_executable_bound"}
    for key, val in first.items():
        if key in measured_after_first:
            continue
        assert last.get(key) == val, f"superset violated at {key}"


@pytest.mark.slow
def test_bench_health_enabled_config_fills_health_keys(tmp_path):
    """A --config workload with health_metrics_every_n_steps > 0 benches
    the health-on executable and fills outer_grad_norm (one fetched
    step) + health_overhead_frac (a brief health-off leg) — the non-null
    half of the fail-soft convention."""
    cfg_path = os.path.join(REPO, "experiment_config",
                            "mini-imagenet_maml++_5-way_5-shot_DA.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["experiment_name"] = "bench_health_probe"  # not flagship-named:
    #                          skips the run-weighted / strict-b8 legs
    cfg["health_metrics_every_n_steps"] = 1
    probe = tmp_path / "health_cfg.json"
    probe.write_text(json.dumps(cfg))
    env = dict(os.environ, MAML_JAX_PLATFORM="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick",
         "--steps", "3", "--config", str(probe)],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    last = json.loads([ln for ln in r.stdout.splitlines()
                       if ln.startswith("{")][-1])
    assert "health_error" not in last, last
    assert isinstance(last["outer_grad_norm"], float)
    assert last["outer_grad_norm"] > 0
    assert isinstance(last["health_overhead_frac"], float)
    # Non-flagship --config: baseline ratio stays null, headline real.
    assert last["vs_baseline"] is None
    assert last["value"] > 0


def test_bench_rejects_malformed_compiler_option():
    """--compiler-option must be KEY=VAL; malformed input fails fast
    (before backend init) with a JSON error line and rc=1."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--compiler-option", "no_equals_sign"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, MAML_JAX_PLATFORM="cpu"), cwd=REPO)
    assert r.returncode == 1, (r.returncode, r.stdout, r.stderr[-500:])
    err = json.loads([ln for ln in r.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert "compiler-option" in err["error"]


def _bench_error(args):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")] + args,
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, MAML_JAX_PLATFORM="cpu"), cwd=REPO)
    assert r.returncode == 1, (r.returncode, r.stdout, r.stderr[-500:])
    return json.loads([ln for ln in r.stdout.splitlines()
                       if ln.startswith("{")][-1])


def test_bench_tuned_flag_fast_fails_before_backend():
    """--tuned fast-fails on an unreadable record, a rejected
    (adopted=false) record, and the --compiler-option conflict — all
    BEFORE backend init, with the JSON error-line contract."""
    err = _bench_error(["--tuned", "/nonexistent/TUNED.json"])
    assert "TUNED.json" in err["error"] or "No such file" in err["error"]
    err = _bench_error(["--tuned", "/tmp/x.json",
                        "--compiler-option", "a=1"])
    assert "mutually exclusive" in err["error"]


def test_bench_tuned_rejected_record_refused(tmp_path):
    from howtotrainyourmamlpytorch_tpu.tune import record
    p = record.write_tuned(str(tmp_path), {"adopted": False,
                                           "reason": "parity"})
    err = _bench_error(["--tuned", p])
    assert "adopted=false" in err["error"]


def test_bench_resolution_precedence_unit(tmp_path):
    """resolve_compiler_options: cli > tuned > config > none, with the
    artifact source naming the applied channel and the tuned record
    read ONCE (both channels from one snapshot — no mixed point under
    a concurrent rewrite)."""
    import bench
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.tune import record
    cfg_opts = MAMLConfig(xla_compiler_options=("b=2",))
    assert bench.resolve_compiler_options({"a": "1"}, None, cfg_opts) \
        == ({"a": "1"}, {}, "cli")
    assert bench.resolve_compiler_options({}, None, cfg_opts) \
        == ({"b": "2"}, {}, "config")
    assert bench.resolve_compiler_options({}, None, MAMLConfig()) \
        == ({}, {}, "none")
    p = record.write_tuned(str(tmp_path), {
        "adopted": True, "xla_compiler_options": {"k": "v"},
        "config_overrides": {"remat_policy": "dots"}})
    assert bench.resolve_compiler_options({}, p, MAMLConfig()) \
        == ({"k": "v"}, {"remat_policy": "dots"}, "tuned")

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.models import make_model

CFG = MAMLConfig(image_height=28, image_width=28, image_channels=1,
                 num_classes_per_set=5, cnn_num_filters=16, num_stages=4,
                 compute_dtype="float32")


def test_vgg_shapes_and_state():
    init, apply = make_model(CFG)
    params, state = init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (7, 28, 28, 1))
    logits, new_state = apply(params, state, x, jnp.int32(0), True)
    assert logits.shape == (7, 5)
    assert params["norm0"]["gamma"].shape == (CFG.bn_num_steps, 16)
    assert state["norm0"]["mean"].shape == (CFG.bn_num_steps, 16)
    # Only step-0 rows of the running stats moved.
    changed = np.asarray(new_state["norm0"]["mean"]) != np.asarray(
        state["norm0"]["mean"])
    assert changed[0].any() and not changed[1:].any()


def test_vgg_flatten_dim_inference():
    # 28x28 with 4 stages of SAME conv + 2x2 pool -> 1x1 spatial.
    init, _ = make_model(CFG)
    params, _ = init(jax.random.PRNGKey(0))
    assert params["linear"]["w"].shape == (16, 5)
    # Mini-ImageNet geometry: 84 -> 42 -> 21 -> 10 -> 5 => 5*5*filters.
    cfg = CFG.replace(image_height=84, image_width=84, image_channels=3,
                      cnn_num_filters=48)
    init2, _ = make_model(cfg)
    params2, _ = init2(jax.random.PRNGKey(0))
    assert params2["linear"]["w"].shape == (5 * 5 * 48, 5)


def test_vgg_no_pooling_stride2():
    cfg = CFG.replace(max_pooling=False)
    init, apply = make_model(cfg)
    params, state = init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 28, 28, 1))
    logits, _ = apply(params, state, x, jnp.int32(0), True)
    assert logits.shape == (3, 5)


def test_vgg_jit_and_traced_step():
    init, apply = make_model(CFG)
    params, state = init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 28, 28, 1))

    @jax.jit
    def run(p, s, x, step):
        return apply(p, s, x, step, True)

    l0, _ = run(params, state, x, jnp.int32(0))
    l1, _ = run(params, state, x, jnp.int32(1))  # same trace, dynamic index
    assert l0.shape == l1.shape


def test_layer_norm_backbone():
    cfg = CFG.replace(norm_layer="layer_norm")
    init, apply = make_model(cfg)
    params, state = init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
    logits, new_state = apply(params, state, x, jnp.int32(0), True)
    assert logits.shape == (2, 5)
    # Full elementwise affine over the stage's post-conv feature shape
    # (reference MetaLayerNormLayer semantics).
    assert params["norm0"]["gamma"].shape == (1, 28, 28, 16)

"""Resilience subsystem tests (ISSUE 3).

Tier-1 keeps the cheap unit layers — fault-injection registry, backoff
math, storage retry, CRC framing, quarantine, divergence guard, loader
corrupt-episode skip, config knobs — inside the 870s budget. The system
proofs (mid-epoch-kill resume equivalence, the full chaos acceptance
scenario) are ``slow``.
"""

import json
import math
import os
import warnings

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu import resilience
from howtotrainyourmamlpytorch_tpu.resilience import (
    DivergenceGuard, backoff_delay, faults, retry_io)
from howtotrainyourmamlpytorch_tpu.resilience.faults import FaultPlan
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Every test starts and ends with no fault plan and no process-wide
    resilience registry (builders/engines install their own)."""
    faults.configure("")
    prev = resilience.set_registry(None)
    yield
    faults.configure("")
    resilience.set_registry(prev)


# ---------------------------------------------------------------------------
# fault-injection registry
# ---------------------------------------------------------------------------

def test_fault_plan_parse_and_fire():
    plan = FaultPlan.parse("io_write@2:2; nan_loss@7 , kill@9")
    assert {s.kind for s in plan.specs} == {"io_write", "nan_loss", "kill"}
    # call-counted: fires on calls 2 and 3 only
    assert [plan.maybe_fire("io_write") for _ in range(4)] == [
        False, True, True, False]
    # step-keyed
    assert not plan.maybe_fire("nan_loss", step=6)
    assert plan.maybe_fire("nan_loss", step=7)
    assert plan.fired == [("io_write", 2), ("io_write", 3),
                          ("nan_loss", 7)]


def test_fault_fires_at_most_once_per_step():
    """A rewind revisits the poisoned iteration; re-injecting there would
    make recovery impossible by construction."""
    plan = FaultPlan.parse("nan_loss@5")
    assert plan.maybe_fire("nan_loss", step=5)
    assert not plan.maybe_fire("nan_loss", step=5)


def test_fault_plan_rejects_bad_specs():
    for bad in ("nan_loss", "nope@3", "nan_loss@x", "nan_loss@-1",
                "io_write@1:0"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_disabled_injection_is_inert():
    assert not faults.active()
    assert not faults.maybe_fire("nan_loss", step=1)
    faults.configure("nan_loss@1")
    assert faults.active() and faults.maybe_fire("nan_loss", step=1)
    faults.configure("")
    assert not faults.active()


def test_fired_faults_count_into_registry():
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    faults.configure("io_read@1")
    assert faults.maybe_fire("io_read")
    assert reg.counter("resilience/faults_injected").value == 1


# ---------------------------------------------------------------------------
# backoff / retry
# ---------------------------------------------------------------------------

def test_backoff_delay_math():
    import random
    # Exponential growth, capped.
    assert backoff_delay(0, base=0.1, factor=2, cap=10, jitter_frac=0) \
        == pytest.approx(0.1)
    assert backoff_delay(3, base=0.1, factor=2, cap=10, jitter_frac=0) \
        == pytest.approx(0.8)
    assert backoff_delay(30, base=0.1, factor=2, cap=10, jitter_frac=0) \
        == pytest.approx(10)
    # Jitter multiplies after the cap: bounded by cap * (1 + frac).
    rng = random.Random(1)
    for attempt in range(8):
        d = backoff_delay(attempt, base=0.1, factor=2, cap=1.0,
                          jitter_frac=0.5, rng=rng)
        lo = min(0.1 * 2 ** attempt, 1.0)
        assert lo <= d <= lo * 1.5
    with pytest.raises(ValueError):
        backoff_delay(-1)
    with pytest.raises(ValueError):
        backoff_delay(0, base=0)


def test_retry_recovers_and_counts():
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    sleeps = []
    calls = {"n": 0}

    @retry_io("unit io", retries=3, base=1e-4, sleep=sleeps.append)
    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient")
        return 42

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert flaky() == 42
    assert calls["n"] == 3 and len(sleeps) == 2
    assert reg.counter("resilience/io_retries").value == 2
    assert any("retry 1/3" in str(r.message) for r in rec)


def test_retry_bounded_and_giveup_counted():
    reg = MetricsRegistry()
    resilience.set_registry(reg)

    @retry_io("unit io", retries=2, base=1e-4, sleep=lambda s: None)
    def always_fails():
        raise OSError("permanent")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(OSError, match="permanent"):
            always_fails()
    assert reg.counter("resilience/io_retries").value == 2
    assert reg.counter("resilience/io_giveups").value == 1


def test_retry_env_knobs_invalid_values_fall_back(monkeypatch):
    """Satellite: a typo'd MAML_IO_RETRIES/_RETRY_BASE_S/_CAP_S must
    warn once and fall back to the defaults — never raise at import or
    call time (the resilience layer cannot itself be the brittle
    part)."""
    from howtotrainyourmamlpytorch_tpu.resilience import retry

    retry._warned_env.clear()
    cases = [
        ("MAML_IO_RETRIES", "three", 3, int, 0),
        ("MAML_IO_RETRIES", "-2", 3, int, 0),
        ("MAML_IO_RETRY_BASE_S", "fast", 0.02, float, 1e-6),
        ("MAML_IO_RETRY_BASE_S", "-0.5", 0.02, float, 1e-6),
        ("MAML_IO_RETRY_BASE_S", "0", 0.02, float, 1e-6),  # backoff
        # rejects base<=0: the fallback must stay usable
        ("MAML_IO_RETRY_CAP_S", "nan", 2.0, float, 1e-6),
        ("MAML_IO_RETRY_CAP_S", "-1", 2.0, float, 1e-6),
    ]
    for name, raw, default, cast, minimum in cases:
        retry._warned_env.clear()
        monkeypatch.setenv(name, raw)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert retry._env_number(name, default, cast,
                                     minimum=minimum) == default
            # Warn ONCE per knob per process.
            assert retry._env_number(name, default, cast,
                                     minimum=minimum) == default
        assert sum(name in str(r.message) for r in rec) == 1
    # Valid values still parse; unset uses the default silently.
    monkeypatch.setenv("MAML_IO_RETRIES", "5")
    assert retry._env_number("MAML_IO_RETRIES", 3, int) == 5
    monkeypatch.delenv("MAML_IO_RETRIES")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert retry._env_number("MAML_IO_RETRIES", 3, int) == 3
    assert not rec


def test_retry_module_import_survives_bad_env():
    """The module-level defaults are read at import time: importing with
    a hostile environment must succeed with the documented defaults
    (pre-fix, `int('three')` raised at import)."""
    import subprocess
    import sys
    code = (
        "from howtotrainyourmamlpytorch_tpu.resilience import retry;"
        "assert retry.DEFAULT_RETRIES == 3, retry.DEFAULT_RETRIES;"
        "assert retry.DEFAULT_BASE_S == 0.02;"
        "assert retry.DEFAULT_CAP_S == 2.0;"
        "print('ok')")
    env = dict(os.environ, MAML_IO_RETRIES="three",
               MAML_IO_RETRY_BASE_S="-1", MAML_IO_RETRY_CAP_S="oops",
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-W", "ignore", "-c", code],
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-1000:]
    assert "ok" in r.stdout


def test_retry_gives_up_immediately_on_missing_file():
    calls = {"n": 0}

    @retry_io("unit io", retries=5, base=1e-4, sleep=lambda s: None)
    def missing():
        calls["n"] += 1
        raise FileNotFoundError("nope")

    with pytest.raises(FileNotFoundError):
        missing()
    assert calls["n"] == 1  # a missing file is control flow, not a fault


def test_storage_json_injected_write_fault_recovers(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.storage import (
        load_from_json, save_to_json)
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    faults.configure("io_write@1;io_read@1")
    path = str(tmp_path / "x.json")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        save_to_json(path, {"a": 1})
        assert load_from_json(path) == {"a": 1}
    assert reg.counter("resilience/io_retries").value == 2
    assert reg.counter("resilience/faults_injected").value == 2


# ---------------------------------------------------------------------------
# checkpoint CRC framing + quarantine
# ---------------------------------------------------------------------------

def _tiny_state():
    import jax
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.meta import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    cfg = MAMLConfig(image_height=8, image_width=8, image_channels=1,
                     num_classes_per_set=2, cnn_num_filters=4,
                     num_stages=1, number_of_training_steps_per_iter=2,
                     number_of_evaluation_steps_per_iter=2,
                     compute_dtype="float32")
    init, _ = make_model(cfg)
    return init_train_state(cfg, init, jax.random.PRNGKey(0))


def test_checkpoint_crc_header_roundtrip_and_detection(tmp_path):
    import jax
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        _MAGIC, CheckpointManager, CorruptCheckpointError)
    mgr = CheckpointManager(str(tmp_path))
    state = _tiny_state()
    mgr.save(state, epoch=0, current_iter=3, val_acc=0.5)
    path = tmp_path / "train_model_0.ckpt"
    blob = path.read_bytes()
    assert blob.startswith(_MAGIC)
    loaded, meta = mgr.load(_tiny_state(), 0)
    assert meta["current_iter"] == 3
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Flip one payload byte: the CRC must catch what msgpack might not.
    mid = len(blob) // 2
    path.write_bytes(blob[:mid] + bytes([blob[mid] ^ 0xFF])
                     + blob[mid + 1:])
    with pytest.raises(CorruptCheckpointError, match="CRC"):
        mgr.load(_tiny_state(), 0)
    # Truncation is caught by the length field.
    path.write_bytes(blob[:-10])
    with pytest.raises(CorruptCheckpointError, match="length"):
        mgr.load(_tiny_state(), 0)


def test_legacy_headerless_checkpoint_still_loads(tmp_path):
    import jax
    from flax import serialization
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)
    mgr = CheckpointManager(str(tmp_path))
    state = _tiny_state()
    mgr.save(state, epoch=0, current_iter=3, val_acc=0.5)
    # Rewrite the file as a pre-framing checkpoint: raw msgpack payload.
    raw = serialization.to_bytes(jax.device_get(state))
    (tmp_path / "train_model_0.ckpt").write_bytes(raw)
    loaded, _ = mgr.load(_tiny_state(), 0)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fallback_quarantines_corrupt_checkpoint(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    mgr = CheckpointManager(str(tmp_path))
    state = _tiny_state()
    mgr.save(state, epoch=0, current_iter=2, val_acc=0.4)
    mgr.save(state, epoch=1, current_iter=4, val_acc=0.6)
    latest = tmp_path / "train_model_latest.ckpt"
    # Replace 'latest' with garbage (new inode: the epoch files survive).
    os.remove(latest)
    latest.write_bytes(b"garbage")
    # A resume constructs a FRESH manager (reads state.json from disk).
    mgr = CheckpointManager(str(tmp_path))
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        _, meta, tag = mgr.load_latest_or_fallback(_tiny_state())
    assert tag == 1 and meta["current_iter"] == 4
    # Quarantined: renamed aside, never re-attempted on the next resume.
    assert not latest.exists()
    assert (tmp_path / "train_model_latest.ckpt.corrupt").exists()
    assert any("quarantined" in str(r.message) for r in rec)
    assert reg.counter("resilience/quarantined").value == 1

    # An EPOCH checkpoint that rots is also dropped from the bookkeeping
    # (the ensemble protocol must not try to load it later).
    p1 = tmp_path / "train_model_1.ckpt"
    blob = p1.read_bytes()
    mid = len(blob) // 2
    p1.write_bytes(blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:])
    mgr2 = CheckpointManager(str(tmp_path))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, meta2, tag2 = mgr2.load_latest_or_fallback(_tiny_state())
    assert tag2 == 0 and meta2["current_iter"] == 2
    assert "1" not in mgr2.meta["iter_at_epoch"]
    # Epoch 1 was the best (0.6): the best-val bookkeeping must fall
    # back to the best REMAINING checkpoint, or no later epoch could
    # ever reclaim best_val_acc from a *.corrupt file.
    assert mgr2.meta["best_val_epoch"] == 0
    assert mgr2.meta["best_val_acc"] == pytest.approx(0.4)
    mgr3 = CheckpointManager(str(tmp_path))
    assert "1" not in mgr3.meta["iter_at_epoch"]  # persisted
    assert mgr3.meta["best_val_epoch"] == 0


def test_quarantine_disabled_for_non_writer(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)
    mgr = CheckpointManager(str(tmp_path))
    state = _tiny_state()
    mgr.save(state, epoch=0, current_iter=2, val_acc=0.4)
    latest = tmp_path / "train_model_latest.ckpt"
    os.remove(latest)
    latest.write_bytes(b"garbage")
    ro = CheckpointManager(str(tmp_path), quarantine=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, _, tag = ro.load_latest_or_fallback(_tiny_state())
    assert tag == 0
    assert latest.exists()  # a non-writer process must not touch the FS


def test_injected_ckpt_corruption_recovered_on_resume(tmp_path):
    """End-to-end through the manager: a fault-injected corrupt save is
    caught by the CRC on load and the fallback recovers."""
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager, CorruptCheckpointError)
    mgr = CheckpointManager(str(tmp_path))
    state = _tiny_state()
    mgr.save(state, epoch=0, current_iter=2, val_acc=0.4)
    # Corrupt the NEXT checkpoint write (epoch 1 file + its hard-linked
    # 'latest' share the damaged inode).
    faults.configure("ckpt_corrupt@1")
    mgr.save(state, epoch=1, current_iter=4, val_acc=0.6)
    with pytest.raises(CorruptCheckpointError):
        mgr.load(_tiny_state(), 1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _, meta, tag = mgr.load_latest_or_fallback(_tiny_state())
    assert tag == 0 and meta["current_iter"] == 2


# ---------------------------------------------------------------------------
# divergence guard
# ---------------------------------------------------------------------------

def test_guard_patience_and_reset():
    g = DivergenceGuard(patience=3)
    assert not g.observe(1.0, 0)
    assert not g.observe(float("nan"), 1)
    assert not g.observe(float("inf"), 2)
    assert g.observe(float("nan"), 3)          # third consecutive bad
    assert not g.observe(float("nan"), 4)      # streak reset by trigger
    # A good loss in between resets the streak.
    g2 = DivergenceGuard(patience=2)
    assert not g2.observe(float("nan"), 0)
    assert not g2.observe(1.0, 1)
    assert not g2.observe(float("nan"), 2)
    assert g2.observe(float("nan"), 3)


def test_guard_spike_detection():
    g = DivergenceGuard(patience=1, spike_factor=10.0)
    for i in range(6):
        assert not g.observe(1.0 + 0.01 * i, i)
    assert not g.observe(5.0, 10)   # 5x median: not a spike at 10x
    assert g.observe(50.0, 11)      # 50x median: spike, patience 1
    # Spike detection needs history; a fresh guard ignores early spikes.
    g2 = DivergenceGuard(patience=1, spike_factor=10.0)
    assert not g2.observe(1e9, 0)


def test_guard_counts_into_registry():
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    g = DivergenceGuard(patience=2)
    g.observe(float("nan"), 0)
    g.observe(float("nan"), 1)
    assert reg.counter("resilience/nan_steps").value == 2


def test_guard_rejects_bad_params():
    with pytest.raises(ValueError):
        DivergenceGuard(patience=0)
    with pytest.raises(ValueError):
        DivergenceGuard(patience=1, spike_factor=0.5)


# ---------------------------------------------------------------------------
# loader corrupt-episode skip
# ---------------------------------------------------------------------------

def _loader(registry=None):
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader)
    cfg = MAMLConfig(dataset_name="synthetic_resilience",
                     image_height=10, image_width=10, image_channels=1,
                     num_classes_per_set=3, num_samples_per_class=1,
                     num_target_samples=2, batch_size=4)
    return MetaLearningDataLoader(cfg, registry=registry)


def test_corrupt_episode_skipped_with_counter_and_replacement():
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        _REPLACEMENT_STRIDE)
    reg = MetricsRegistry()
    resilience.set_registry(reg)
    faults.configure("episode_corrupt@2")
    loader = _loader(registry=reg)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        batches = list(loader.get_train_batches(0, 2))
    # Epoch step count preserved: both batches arrive, full-size.
    assert len(batches) == 2
    assert batches[0].support_x.shape[0] == 4
    assert reg.counter("data/corrupt_episodes").value == 1
    assert sum("replacement" in str(r.message) for r in rec) == 1
    # The replacement is the DETERMINISTIC alternate episode, and the
    # other positions are untouched.
    sampler = loader.sampler("train")
    np.testing.assert_array_equal(
        batches[0].support_x[2],
        sampler.sample(2 + _REPLACEMENT_STRIDE).support_x)
    np.testing.assert_array_equal(batches[0].support_x[1],
                                  sampler.sample(1).support_x)


def test_persistently_broken_split_still_raises():
    loader = _loader()
    sampler = loader.sampler("train")
    sampler.sample = lambda idx: (_ for _ in ()).throw(
        RuntimeError("decode failed"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(RuntimeError, match="decode failed"):
            list(loader.get_train_batches(0, 1))


def test_train_salt_shifts_train_stream_only():
    loader_a, loader_b = _loader(), _loader()
    loader_b.set_train_salt(1)
    a = next(iter(loader_a.get_train_batches(0, 1)))
    b = next(iter(loader_b.get_train_batches(0, 1)))
    assert not np.array_equal(a.support_x, b.support_x)
    # Fixed eval streams are rewind-invariant.
    va = next(iter(loader_a.get_val_batches()))
    vb = next(iter(loader_b.get_val_batches()))
    np.testing.assert_array_equal(np.asarray(va.support_x),
                                  np.asarray(vb.support_x))


# ---------------------------------------------------------------------------
# config knobs
# ---------------------------------------------------------------------------

def test_config_resilience_validation():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    with pytest.raises(ValueError, match="divergence_patience"):
        MAMLConfig(divergence_patience=-1)
    with pytest.raises(ValueError, match="divergence_spike_factor"):
        MAMLConfig(divergence_spike_factor=0.5)
    with pytest.raises(ValueError, match="divergence_max_rewinds"):
        MAMLConfig(divergence_max_rewinds=-1)
    with pytest.raises(ValueError, match="fault spec"):
        MAMLConfig(fault_spec="nonsense")
    cfg = MAMLConfig.from_dict({"divergence_patience": 5,
                                "fault_spec": "nan_loss@3"})
    assert cfg.divergence_patience == 5 and cfg.fault_spec == "nan_loss@3"


def test_preemption_at_epoch_boundary_reports_preempted(tmp_path):
    """A signal that lands outside _train_epoch (epoch-boundary val
    sweep, or before the loop starts) exits via the while condition —
    it must still report preemption so the CLI exits EXIT_PREEMPTED and
    the scheduler resubmits, never 'paused' (exit 0 = success)."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    builder = ExperimentBuilder(_cfg(tmp_path))
    builder._preempted = True
    assert builder.run_experiment() == {"preempted_at_iter": 0}


def test_second_signal_escalates_to_immediate_exit(tmp_path, monkeypatch):
    """Satellite: a SECOND SIGTERM/SIGINT while the first is still
    draining the in-flight step must dump forensics and _exit(75) NOW —
    a hung step would otherwise make the graceful save-on-signal path
    un-interruptible."""
    import signal as _signal
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.resilience import EXIT_PREEMPTED

    builder = ExperimentBuilder(_cfg(tmp_path))
    exits = []

    class _Exited(BaseException):
        pass

    def fake_exit(code):
        exits.append(code)
        raise _Exited()

    monkeypatch.setattr(os, "_exit", fake_exit)
    # First signal: graceful — just sets the drain flag.
    builder._handle_signal(_signal.SIGTERM, None)
    assert builder._preempted and not exits
    # Second signal while draining: immediate forensic exit.
    with pytest.raises(_Exited):
        builder._handle_signal(_signal.SIGTERM, None)
    assert exits == [EXIT_PREEMPTED]
    bundle = builder._bundle_dir()
    assert os.path.getsize(os.path.join(bundle, "stacks.txt")) > 0
    crash = json.load(open(os.path.join(bundle, "crash.json")))
    assert crash["reason"] == "signal_escalation"


# ---------------------------------------------------------------------------
# system proofs (slow profile)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # full run + killed-and-resumed run (~60s), 1-core box
def test_injected_midepoch_kill_resume_matches_uninterrupted(tmp_path):
    """Satellite 3: a fault-injected mid-epoch SIGTERM (the REAL signal
    path: handler -> quiesce -> latest snapshot) followed by a restart
    must reproduce the uninterrupted run's post-resume trajectory
    exactly (the episode stream is a pure function of the iteration)."""
    import jax
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    cfg_a = _cfg(tmp_path / "a", dispatch_sync_every=1)
    builder_a = ExperimentBuilder(cfg_a)
    builder_a.run_experiment()

    cfg_b = _cfg(tmp_path / "b", dispatch_sync_every=1,
                 fault_spec="kill@3")
    builder_b = ExperimentBuilder(cfg_b)
    result = builder_b.run_experiment()
    assert result == {"preempted_at_iter": 3}
    assert builder_b.ckpt.has_checkpoint("latest")

    cfg_b2 = _cfg(tmp_path / "b", dispatch_sync_every=1,
                  continue_from_epoch="latest")
    builder_b2 = ExperimentBuilder(cfg_b2)
    assert builder_b2.current_iter == 3
    builder_b2.run_experiment()

    for a, b in zip(jax.tree.leaves(builder_a.state.params),
                    jax.tree.leaves(builder_b2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # NaN -> rewind -> recover run (~45s), 1-core box
def test_nan_loss_triggers_rewind_and_run_recovers(tmp_path):
    """Divergence guard end-to-end: an injected NaN outer loss in epoch 1
    rewinds to the epoch-0 checkpoint, re-seeds the train stream, and
    the run still completes the full schedule + test protocol."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    cfg = _cfg(tmp_path, dispatch_sync_every=1, divergence_patience=1,
               health_metrics_every_n_steps=1,  # ISSUE 7 early warning
               fault_spec="nan_loss@6")  # epoch 1 (iters 6..10)
    builder = ExperimentBuilder(cfg)
    result = builder.run_experiment()
    assert result["num_models"] == 2  # completed despite the NaN
    assert builder.registry.counter("resilience/rewinds").value == 1
    assert builder.ckpt.meta["rewinds"] == 1
    # The rewind row landed in the event stream, and the health
    # subsystem's grad-norm warning preceded it STRICTLY in log order
    # (the ISSUE 7 acceptance ordering) without changing any recovery
    # semantics (the rewind happened exactly as before).
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    events = read_jsonl(os.path.join(builder.paths["logs"],
                                     "events.jsonl"))
    rewinds = [e for e in events if e.get("event") == "rewind"]
    assert len(rewinds) == 1 and rewinds[0]["epoch"] == 0
    kinds = [e.get("event") for e in events]
    assert "health_grad_norm_warn" in kinds
    assert kinds.index("health_grad_norm_warn") < kinds.index("rewind")
    assert builder.registry.counter(
        "health/grad_norm_warn").value == 1


@pytest.mark.slow  # divergence with no checkpoint must fail loudly (~20s)
def test_nan_before_any_checkpoint_fails_loudly(tmp_path):
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    cfg = _cfg(tmp_path, dispatch_sync_every=1, divergence_patience=1,
               fault_spec="nan_loss@2")  # epoch 0: nothing to rewind to
    with pytest.raises(RuntimeError, match="nothing to rewind"):
        ExperimentBuilder(cfg).run_experiment()


@pytest.mark.slow  # subprocess hang run + in-process restart (~60s)
def test_hang_feed_watchdog_end_to_end(tmp_path):
    """THE ISSUE 6 system proof: an injected wedged data feed
    (hang_feed) trips the watchdog within its deadline in a REAL
    training process — all-thread stack dump + flight.jsonl written,
    exit code 74 — and a clean restart from 'latest' resumes past the
    hang and completes the schedule + test protocol."""
    import subprocess
    import sys
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.resilience import EXIT_HUNG

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Epoch 0 (iters 0..4) checkpoints at iter 5... total_iter_per_epoch
    # is 5 in _cfg: epoch-0 batches are 0..4, epoch-1 batches 5..9;
    # hang the feed of iteration 6, after the epoch-0 checkpoint.
    cfg = _cfg(tmp_path, dispatch_sync_every=1,
               continue_from_epoch="latest",
               fault_spec="hang_feed@6",
               watchdog_feed_timeout_s=6.0,
               watchdog_step_timeout_s=300.0,
               watchdog_compile_timeout_s=900.0,
               watchdog_poll_interval_s=0.5)
    cfg_path = tmp_path / "hang_config.json"
    cfg_path.write_text(json.dumps(cfg.to_dict()))
    env = dict(os.environ, MAML_JAX_PLATFORM="cpu",
               MAML_HANG_SECONDS="120")
    env.pop("MAML_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "train_maml_system.py"),
         "--name_of_args_json_file", str(cfg_path)],
        env=env, capture_output=True, text=True, timeout=600, cwd=repo)
    assert proc.returncode == EXIT_HUNG, (proc.returncode,
                                          proc.stderr[-1500:])

    bundle = tmp_path / "smoke" / "logs" / "crash_bundle"
    stacks = (bundle / "stacks.txt").read_text()
    assert "Thread" in stacks  # all-thread dump, not just the main one
    flight = [json.loads(line) for line in
              (bundle / "flight.jsonl").read_text().splitlines()]
    # The ring holds the hang's context: the injected fault and the
    # final stuck 'feed' phase, ending in the trip record.
    assert any(r["kind"] == "fault" and r["fault"] == "hang_feed"
               for r in flight)
    assert flight[-1]["kind"] == "watchdog_trip"
    assert flight[-1]["phase"] == "feed"
    crash = json.loads((bundle / "crash.json").read_text())
    assert crash["reason"] == "hung_feed"
    assert crash["age_seconds"] >= 6.0
    # The trip row + final registry flush landed in the event stream.
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    events = read_jsonl(str(tmp_path / "smoke" / "logs" / "events.jsonl"))
    assert sum(e.get("event") == "watchdog_trip" for e in events) == 1
    # ... and the telemetry report renders the v5 watchdog section.
    from howtotrainyourmamlpytorch_tpu.telemetry import summarize_events
    wd = summarize_events(events)["watchdog"]
    assert wd["trips"] == 1 and wd["last_phase"] == "feed"

    # Restart with no faults: resumes at the snapshot and completes.
    builder = ExperimentBuilder(_cfg(tmp_path, dispatch_sync_every=1,
                                     continue_from_epoch="latest"))
    assert builder.current_iter >= 5  # epoch-0 checkpoint was kept
    result = builder.run_experiment()
    assert result["num_models"] == 2  # full schedule + test protocol


@pytest.mark.slow  # three tiny end-to-end runs (~60s), 1-core box
def test_watchdog_disabled_is_parity_with_enabled(tmp_path):
    """Acceptance pin: with all watchdog_*_timeout_s = 0 the training
    path behaves byte-identically to the (non-tripping) enabled default
    — same final weights bitwise, and the beacon adds ZERO compiles
    (everything lives in host Python outside compiled code)."""
    import jax
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    off = {f: 0.0 for f in (
        "watchdog_step_timeout_s", "watchdog_feed_timeout_s",
        "watchdog_collective_timeout_s", "watchdog_compile_timeout_s",
        "watchdog_serve_timeout_s", "watchdog_ckpt_timeout_s")}
    # Run 1 (disabled) pays the process's cold compiles; runs 2 and 3
    # are equally cache-warm, so comparing THEIR counts isolates the
    # watchdog: if the beacon injected anything into traced code, the
    # enabled run's HLO would differ and miss the executable cache.
    builder_cold = ExperimentBuilder(_cfg(tmp_path / "cold", **off))
    builder_cold.run_experiment()

    builder_on = ExperimentBuilder(_cfg(tmp_path / "on"))
    builder_on.run_experiment()
    compiles_on = builder_on.registry.counter("compile/count").value

    builder_off = ExperimentBuilder(_cfg(tmp_path / "off", **off))
    builder_off.run_experiment()
    compiles_off = builder_off.registry.counter("compile/count").value

    for a, b, c in zip(jax.tree.leaves(builder_cold.state.params),
                       jax.tree.leaves(builder_on.state.params),
                       jax.tree.leaves(builder_off.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(b), np.asarray(c))
    assert compiles_on == compiles_off
    # The enabled run's heartbeat rows carry the liveness gauge.
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    events = read_jsonl(os.path.join(builder_on.paths["logs"],
                                     "events.jsonl"))
    beats = [e for e in events if e.get("event") == "heartbeat"]
    assert beats and all(
        e.get("progress_age_seconds") is not None for e in beats)
    off_events = read_jsonl(os.path.join(builder_off.paths["logs"],
                                         "events.jsonl"))
    off_beats = [e for e in off_events if e.get("event") == "heartbeat"]
    assert off_beats and all(
        e.get("progress_age_seconds") is None for e in off_beats)


@pytest.mark.slow  # four tiny end-to-end runs (~80s), 1-core box
def test_health_disabled_is_parity_with_enabled(tmp_path):
    """ISSUE 7 acceptance pin (the watchdog parity pattern): health
    metrics change NOTHING about training numerics — enabled and
    disabled runs produce bitwise-identical final weights — and the
    diagnostics-off build is structurally the seed build: a warm off-run
    AFTER the health-on run compiles exactly as many executables as a
    warm off-run before it (the on-run's different executables neither
    polluted nor invalidated the off cache)."""
    import jax
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    on = dict(dispatch_sync_every=1, health_metrics_every_n_steps=1)

    # Run 1 (off) pays the process's cold compiles; the off-warm runs
    # bracketing the on-run are the isolated comparison.
    builder_cold = ExperimentBuilder(_cfg(tmp_path / "cold"))
    builder_cold.run_experiment()

    builder_off_a = ExperimentBuilder(_cfg(tmp_path / "off_a"))
    builder_off_a.run_experiment()
    compiles_off_a = builder_off_a.registry.counter("compile/count").value

    builder_on = ExperimentBuilder(_cfg(tmp_path / "on", **on))
    builder_on.run_experiment()

    builder_off_b = ExperimentBuilder(_cfg(tmp_path / "off_b"))
    builder_off_b.run_experiment()
    compiles_off_b = builder_off_b.registry.counter("compile/count").value

    for a, b, c, d in zip(jax.tree.leaves(builder_cold.state.params),
                          jax.tree.leaves(builder_off_a.state.params),
                          jax.tree.leaves(builder_on.state.params),
                          jax.tree.leaves(builder_off_b.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(b), np.asarray(c))
        np.testing.assert_array_equal(np.asarray(c), np.asarray(d))
    assert compiles_off_a == compiles_off_b
    # The enabled run emitted health rows; the disabled runs none.
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    on_events = read_jsonl(os.path.join(builder_on.paths["logs"],
                                        "events.jsonl"))
    assert any(e.get("event") == "health" for e in on_events)
    off_events = read_jsonl(os.path.join(builder_off_b.paths["logs"],
                                         "events.jsonl"))
    assert not any(e.get("event") == "health" for e in off_events)


@pytest.mark.slow  # 5 tiny runs through the chaos harness (~3min), 1-core
def test_chaos_acceptance(tmp_path, capsys):
    """THE ISSUE 3 acceptance scenario: injected NaN loss + one injected
    checkpoint-write IO error + one mid-epoch SIGTERM; the restarted run
    completes with rewinds >= 1, io_retries >= 1, and a final accuracy
    within tolerance of the fault-free run."""
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    try:
        import chaos_run
    finally:
        sys.path.pop(0)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = chaos_run.main(["--out", str(tmp_path)])
    last = capsys.readouterr().out.strip().splitlines()[-1]
    artifact = json.loads(last)
    assert rc == 0, artifact
    assert artifact["status"] == "recovered"
    assert artifact["rewinds"] >= 1
    assert artifact["io_retries"] >= 1
    assert artifact["preempted"] is True
    assert artifact["faults_injected"] >= 3
    assert artifact["test_accuracy_delta"] <= artifact["tolerance"]
    # Health early warning (ISSUE 7): the faulted phase's log shows the
    # grad-norm warn row strictly before the rewind row.
    assert artifact["grad_norm_warns"] >= 1
    assert artifact["grad_norm_warn_before_rewind"] is True
    # Hang phase (ISSUE 6): wedged feed -> watchdog -> exit 74 + bundle
    # (stacks + flight ring) -> restart recovered within tolerance.
    assert artifact["hang_exit_code"] == 74
    assert artifact["hang_stacks_dumped"] is True
    assert artifact["hang_flight_rows"] > 0
    assert artifact["hang_watchdog_trips"] >= 1
    assert artifact["hang_recovered"] is True
    assert artifact["hang_test_accuracy_delta"] <= artifact["tolerance"]

"""telemetry_report CLI contract: fixture-driven schema smoke (tier-1,
so the CLI can't silently rot) plus a real 2-epoch CPU training run
driven through the full pipeline (the ISSUE 1 acceptance scenario).
"""

import json
import os
import subprocess
import sys

import pytest

from howtotrainyourmamlpytorch_tpu.telemetry.report import (
    SCHEMA, UNAVAILABLE, format_table, summarize_events)
from howtotrainyourmamlpytorch_tpu.utils.tracing import JsonlLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "scripts", "telemetry_report.py")

# Every key the CI consumer may rely on (the acceptance list: step-time
# percentiles, tasks/sec/chip, compile count/seconds, feed-stall
# fraction, peak memory, per-host skew; v2 adds the serving section,
# v3 the resilience section, v4 the data-plane section, v5 the
# watchdog section, v6 the optimization-health section, v7 the
# checkpoint-lifecycle section, v8 the pod-fault-domain cluster
# section, v9 the AOT warm-start section, v10 the elastic-pod section,
# v11 the serving-fleet section, v12 the perf-lab section, v13 the
# autotune section, v14 the request-tracing + SLO section, v15 the
# meta-algorithm zoo section, v16 the fleet-health section, v17 the
# traffic-lab section, v18 the alerts section).
SCHEMA_KEYS = {
    "schema", "events", "epochs", "steps", "step_seconds_p50",
    "step_seconds_p95", "meta_tasks_per_sec_per_chip", "compile_count",
    "compile_seconds", "feed_stall_frac", "peak_memory_bytes",
    "live_memory_bytes", "host_skew", "serving", "resilience", "data",
    "watchdog", "health", "checkpoint", "cluster", "warm_start",
    "elastic", "fleet", "fleet_health", "traffic", "perf", "tune",
    "requests", "algo", "alerts",
}


def write_fixture_events(path, *, with_failsoft=True, with_serving=False,
                         with_resilience=False, with_data=False,
                         with_watchdog=False, with_health=False,
                         with_checkpoint=False, with_cluster=False):
    """A synthetic 2-epoch run's event stream, as the experiment loop
    writes it (train_epoch + telemetry + heartbeat per epoch); with
    ``with_serving``, a trailing serve/ registry-flush row as
    ServingEngine.flush_metrics writes it; with ``with_resilience``,
    registry-flush rows carrying resilience/* counters as the
    experiment loop's per-epoch flush writes them."""
    log = JsonlLogger(str(path))
    for epoch, (p50, p95, rate) in enumerate([(0.10, 0.50, 40.0),
                                              (0.08, 0.12, 50.0)]):
        log.log("train_epoch", epoch=epoch, iter=(epoch + 1) * 10,
                train_loss=1.0, meta_tasks_per_sec_per_chip=rate,
                dispatch_steps=10, dispatch_p50_step_seconds=p50,
                dispatch_p95_step_seconds=p95)
        log.log("telemetry", epoch=epoch, iter=(epoch + 1) * 10,
                step_seconds_p50=p50, step_seconds_p95=p95,
                meta_tasks_per_sec_per_chip=rate,
                compile_count_total=(4 if with_failsoft else None),
                compile_seconds_total=(12.5 if with_failsoft else None),
                feed_wait_seconds=1.0, feed_dispatch_seconds=9.0,
                feed_stall_frac=0.1,
                memory=({"live_bytes_total": 1000,
                         "live_bytes_max_device": 800,
                         "peak_bytes_max_device": 2000 + epoch}
                        if with_failsoft else None))
        log.log("heartbeat", epoch=epoch, iter=(epoch + 1) * 10,
                process_index=0, hosts=4,
                host_mean_step_seconds=[0.1, 0.1, 0.1, 0.14],
                skew_frac=0.05 * (epoch + 1), slowest_host=3)
    if with_serving:
        # Two rows: counters are cumulative, the LAST serve row wins.
        log.log("metrics", metrics={"serve/requests_total": 10.0,
                                    "serve/responses_total": 9.0})
        log.log("metrics", metrics={
            "serve/requests_total": 40.0,
            "serve/responses_total": 38.0,
            "serve/rejected_total": 1.0,
            "serve/deadline_misses": 1.0,
            "serve/cache_hits": 12.0,
            "serve/cache_misses": 28.0,
            "serve/queue_depth": 0.0,
            "serve/latency_seconds": {"count": 38, "sum": 3.8,
                                      "p50": 0.1, "p95": 0.4},
        })
    if with_resilience:
        # Two rows: counters are cumulative, the LAST row wins.
        log.log("metrics", metrics={"resilience/rewinds": 0.0,
                                    "resilience/io_retries": 1.0})
        log.log("metrics", metrics={
            "resilience/rewinds": 1.0,
            "resilience/nan_steps": 2.0,
            "resilience/io_retries": 3.0,
            "resilience/io_giveups": 0.0,
            "resilience/quarantined": 1.0,
            "resilience/faults_injected": 4.0,
            "resilience/cache_errors": 1.0,
            "data/corrupt_episodes": 2.0,
        })
    if with_data:
        # Registry flushes carrying the data-plane keys build_source
        # records (datastore subsystem); cumulative counters, so the
        # accumulated view must total across the rows.
        log.log("metrics", metrics={"data/source_kind/packed": 1.0,
                                    "data/pack_open_seconds": 0.002,
                                    "data/pack_bytes_mapped": 4096.0})
        log.log("metrics", metrics={"data/source_kind/packed": 3.0,
                                    "data/source_kind/synthetic": 1.0,
                                    "data/pack_open_seconds": 0.006,
                                    "data/pack_bytes_mapped": 4096.0,
                                    "data/corrupt_images": 2.0})
    if with_watchdog:
        # A watchdog-enabled run: heartbeats carry the liveness age, a
        # registry row carries the trips counter (reset after the trip
        # kills the process — the restart's row reads 0), and the trip
        # itself lands as an explicit watchdog_trip event row.
        log.log("heartbeat", epoch=2, iter=30, process_index=0,
                hosts=4, host_mean_step_seconds=[0.1] * 4,
                skew_frac=0.0, slowest_host=0,
                host_progress_age_seconds=[0.5, 0.4, 0.6, 9.5],
                progress_age_seconds=9.5, progress_phase="step")
        log.log("metrics", metrics={"watchdog/trips": 1.0})
        log.log("watchdog_trip", phase="feed", detail="train",
                age_seconds=12.25, deadline_seconds=6.0,
                process_index=0)
        # Restarted segment: fresh registry — reset-aware accumulation
        # must not double or drop the killed segment's trip.
        log.log("metrics", metrics={"watchdog/trips": 0.0})
    if with_health:
        # A health-enabled run (telemetry/health.py): per-fetch "health"
        # rows (last grad norm + msl vector win; lslr bounds and the
        # ratio report run-wide extremes), one guard warning row, and a
        # counter row — followed by a restarted segment's reset-to-zero
        # row the reset-aware accumulation must absorb.
        log.log("health", iter=5, epoch=0, grad_norm=2.0,
                update_ratio_max=0.05, lslr_min=0.08, lslr_max=0.12,
                msl_importance=[0.6, 0.4],
                per_step_support_loss=[1.0, 0.5],
                per_step_target_loss=[0.9, 0.4])
        log.log("health", iter=10, epoch=1, grad_norm=3.5,
                update_ratio_max=0.02, lslr_min=0.09, lslr_max=0.4,
                msl_importance=[0.7, 0.3],
                per_step_support_loss=[0.8, 0.4],
                per_step_target_loss=[0.7, 0.3])
        log.log("health_grad_norm_warn", iter=11, grad_norm=99.0)
        log.log("metrics", metrics={"health/grad_norm_warn": 1.0})
        log.log("metrics", metrics={"health/grad_norm_warn": 0.0})
    if with_checkpoint:
        # A killed-and-restarted run (counter reset between segments) +
        # a serving process's flush carrying the hot-swap counters: the
        # v7 checkpoint section must total across all of it.
        log.log("metrics", metrics={"ckpt/saves": 2.0,
                                    "ckpt/save_seconds": 0.5,
                                    "ckpt/blocked_seconds": 0.1,
                                    "ckpt/skipped_saves": 1.0,
                                    "ckpt/gc_deletes": 0.0})
        log.log("metrics", metrics={"ckpt/saves": 1.0,  # restart: reset
                                    "ckpt/save_seconds": 0.25,
                                    "ckpt/blocked_seconds": 0.0,
                                    "ckpt/skipped_saves": 0.0,
                                    "ckpt/gc_deletes": 2.0})
        log.log("metrics", metrics={"serve/hot_swaps": 2.0,
                                    "serve/hot_swap_rollbacks": 1.0})
    if with_cluster:
        # A pod fault domain run: heartbeats carry the per-host lease
        # ages, the survivor's peer_lost row names the suspect, its
        # registry flush carries the counter, and the restarted
        # segment's consensus_resume row + reset-to-zero counter row
        # must be absorbed reset-aware.
        log.log("heartbeat", epoch=2, iter=30, process_index=0,
                hosts=2, host_mean_step_seconds=[0.1, 0.1],
                skew_frac=0.0, slowest_host=0,
                peer_lease_age_seconds={"0": 0.4, "1": 7.5})
        log.log("peer_lost", phase="collective",
                detail="any_process_true_each", age_seconds=12.0,
                deadline_seconds=10.0, process_index=0,
                suspect_hosts=[1],
                peer_verdicts={"0": "live", "1": "dead"},
                peer_lease_age_seconds={"0": 0.6, "1": 13.0})
        log.log("metrics", metrics={"cluster/peer_losses": 1.0})
        # Restarted segment: fresh registry + consensus adoption.
        log.log("consensus_resume", consensus_epoch=3, local_view=-1)
        log.log("metrics", metrics={"cluster/peer_losses": 0.0,
                                    "cluster/consensus_epoch": 3.0})
    return log.path


def test_summarize_events_fixture(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl")
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    assert s["schema"] == SCHEMA
    assert s["epochs"] == 2 and s["steps"] == 20
    assert s["step_seconds_p50"] == pytest.approx(0.09)  # median of epochs
    assert s["step_seconds_p95"] == pytest.approx(0.31)
    assert s["meta_tasks_per_sec_per_chip"] == pytest.approx(45.0)
    assert s["compile_count"] == 4
    assert s["compile_seconds"] == 12.5
    # Feed stall re-derived from second totals (2.0 wait / 20.0 busy).
    assert s["feed_stall_frac"] == pytest.approx(0.1)
    assert s["peak_memory_bytes"] == 2001
    assert s["host_skew"]["hosts"] == 4
    assert s["host_skew"]["max_skew_frac"] == pytest.approx(0.1)
    # No serve/, resilience/, data/, watchdog or health rows -> the
    # sections say so explicitly.
    assert s["serving"] == UNAVAILABLE
    assert s["resilience"] == UNAVAILABLE
    assert s["data"] == UNAVAILABLE
    assert s["watchdog"] == UNAVAILABLE
    assert s["health"] == UNAVAILABLE
    assert s["checkpoint"] == UNAVAILABLE
    assert s["cluster"] == UNAVAILABLE
    assert s["warm_start"] == UNAVAILABLE
    assert s["fleet"] == UNAVAILABLE
    # The table renders every row without raising.
    table = format_table(s)
    assert "feed stall fraction" in table and "0.1" in table


def test_summarize_events_serving_section(tmp_path):
    """serve/ metric rows (ServingEngine.flush_metrics) render the
    serving section; cumulative counters mean the LAST row wins."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_serving=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    serving = s["serving"]
    assert serving["requests"] == 40 and serving["responses"] == 38
    assert serving["rejected"] == 1 and serving["deadline_misses"] == 1
    assert serving["cache_hit_frac"] == pytest.approx(0.3)
    assert serving["latency_p50_ms"] == pytest.approx(100.0)
    assert serving["latency_p95_ms"] == pytest.approx(400.0)
    assert serving["queue_depth"] == 0
    assert "serving" in format_table(s)
    # Training metrics are untouched by the serve rows.
    assert s["epochs"] == 2 and s["compile_count"] == 4


def test_summarize_events_resilience_section(tmp_path):
    """resilience/* metric rows (the experiment loop's per-epoch registry
    flush) render the v3 resilience section; cumulative counters mean
    the LAST row wins."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_resilience=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    res = s["resilience"]
    assert res["rewinds"] == 1
    assert res["nan_steps"] == 2
    assert res["io_retries"] == 3
    assert res["io_giveups"] == 0
    assert res["quarantined"] == 1
    assert res["faults_injected"] == 4
    assert res["cache_errors"] == 1
    assert res["corrupt_episodes"] == 2
    assert "resilience" in format_table(s)
    # Training + serving metrics untouched by the resilience rows.
    assert s["epochs"] == 2 and s["serving"] == UNAVAILABLE


def test_resilience_counters_survive_process_restarts():
    """A preempted-and-restarted run logs a fresh (reset-to-zero)
    registry into the SAME events.jsonl. Counter-reset accumulation must
    total across segments — last-row-wins would report the restarted
    segment's zeros and hide the killed segment's rewind."""
    events = [
        # killed segment: epoch flush, then the preempt-path flush
        {"event": "metrics", "metrics": {"resilience/rewinds": 0.0,
                                         "resilience/io_retries": 1.0}},
        {"event": "metrics", "metrics": {"resilience/rewinds": 1.0,
                                         "resilience/io_retries": 1.0}},
        # restarted segment: fresh registry, counters reset
        {"event": "metrics", "metrics": {"resilience/rewinds": 0.0,
                                         "resilience/io_retries": 0.0}},
        {"event": "metrics", "metrics": {"resilience/rewinds": 0.0,
                                         "resilience/io_retries": 2.0}},
    ]
    res = summarize_events(events)["resilience"]
    assert res["rewinds"] == 1     # killed segment's rewind kept
    assert res["io_retries"] == 3  # 1 (segment 1) + 2 (segment 2)


def test_summarize_events_data_section(tmp_path):
    """data/* metric rows (build_source's source-kind counters + pack
    open telemetry) render the v4 data-plane section; counters total
    with reset detection, the bytes gauge is last-wins."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_data=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    data = s["data"]
    # Kinds seen across the run, comma-joined deterministically.
    assert data["source_kind"] == "packed,synthetic"
    assert data["pack_open_seconds"] == pytest.approx(0.006)
    assert data["pack_bytes_mapped"] == 4096
    assert data["corrupt_images"] == 2
    assert "data plane" in format_table(s)
    # Training metrics untouched by the data rows.
    assert s["epochs"] == 2 and s["serving"] == UNAVAILABLE


def test_summarize_events_watchdog_section(tmp_path):
    """watchdog rows (heartbeat liveness, watchdog/trips counter,
    watchdog_trip event) render the v5 watchdog section; the trip row
    (always the segment's last word) wins last_phase/progress_age, and
    the post-restart counter reset must not drop the trip."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_watchdog=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    wd = s["watchdog"]
    assert wd["trips"] == 1
    assert wd["last_phase"] == "feed"
    assert wd["progress_age_seconds"] == pytest.approx(12.25)
    assert "watchdog" in format_table(s)
    # Training metrics untouched by the watchdog rows.
    assert s["epochs"] == 2 and s["serving"] == UNAVAILABLE


def test_watchdog_section_from_heartbeats_alone():
    """A healthy watchdog-enabled run (no trips) still reports the
    section: 0 trips, the last heartbeat's phase and liveness age —
    'watchdog on, nothing tripped' and 'no watchdog' are different
    facts."""
    events = [
        {"event": "metrics", "metrics": {"watchdog/trips": 0.0}},
        {"event": "heartbeat", "progress_age_seconds": 0.4,
         "progress_phase": "step", "skew_frac": 0.0, "hosts": 1},
        {"event": "heartbeat", "progress_age_seconds": 0.7,
         "progress_phase": "feed", "skew_frac": 0.0, "hosts": 1},
    ]
    wd = summarize_events(events)["watchdog"]
    assert wd == {"trips": 0, "last_phase": "feed",
                  "progress_age_seconds": 0.7}


def test_summarize_events_health_section(tmp_path):
    """health rows (the experiment loop's per-fetch publish) render the
    v6 health section: last grad norm and msl vector, run-wide ratio
    max / lslr bounds, and reset-aware warning accumulation cross-
    checked against explicit warn rows."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_health=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    h = s["health"]
    assert h["grad_norm"] == pytest.approx(3.5)        # last row wins
    assert h["update_ratio_max"] == pytest.approx(0.05)  # run-wide max
    assert h["lslr_min"] == pytest.approx(0.08)        # run-wide min
    assert h["lslr_max"] == pytest.approx(0.4)         # run-wide max
    assert h["msl_importance"] == [0.7, 0.3]           # last row wins
    # 1 from the counter (reset row absorbed) == 1 explicit warn row.
    assert h["grad_norm_warns"] == 1
    assert "health" in format_table(s)
    # Training metrics untouched by the health rows.
    assert s["epochs"] == 2 and s["serving"] == UNAVAILABLE


def test_summarize_events_checkpoint_section(tmp_path):
    """ckpt/* + hot-swap metric rows (the experiment loop's per-epoch
    flush and a serving process's flush) render the v7 checkpoint
    section; counters accumulate reset-aware across preempt/restart
    segments like the resilience section's."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_checkpoint=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    ck = s["checkpoint"]
    assert ck["saves"] == 3            # 2 (killed segment) + 1 (restart)
    assert ck["save_seconds"] == pytest.approx(0.75)
    assert ck["blocked_seconds"] == pytest.approx(0.1)
    assert ck["skipped_saves"] == 1
    assert ck["gc_deletes"] == 2
    assert ck["hot_swaps"] == 2
    assert ck["rollbacks"] == 1
    assert "checkpoint" in format_table(s)
    # Training metrics untouched by the checkpoint rows. (The hot-swap
    # flush is a serve/* row, so the serving section renders too — a
    # hot-swapping process IS a serving process.)
    assert s["epochs"] == 2 and s["serving"] != UNAVAILABLE


def test_summarize_events_cluster_section(tmp_path):
    """peer_lost / consensus_resume rows + cluster/* metric rows (the
    pod fault domain, resilience/cluster.py) render the v8 cluster
    section: losses accumulate reset-aware across the killed survivor's
    segment and the restart (cross-checked against explicit peer_lost
    rows), the last suspect and the consensus epoch follow log order,
    and the lease-age picture comes from the newest row carrying one."""
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_cluster=True)
    s = summarize_events(read_jsonl(path))
    assert set(s) == SCHEMA_KEYS
    cl = s["cluster"]
    assert cl["peer_losses"] == 1        # counter and row agree
    assert cl["last_suspect_host"] == 1  # the peer_lost row named it
    assert cl["consensus_epoch"] == 3    # the restart's adoption wins
    # The peer_lost row's lease picture is newer than the heartbeat's.
    assert cl["max_peer_lease_age_seconds"] == pytest.approx(13.0)
    assert "cluster" in format_table(s)
    # Training metrics untouched by the cluster rows.
    assert s["epochs"] == 2 and s["watchdog"] == UNAVAILABLE


def test_cluster_section_from_heartbeats_alone():
    """Lease ages on ordinary heartbeat rows alone (a healthy armed run
    that never tripped) render the section with zero losses — a
    measured zero, not an omission."""
    events = [{"event": "heartbeat", "epoch": 0, "iter": 5,
               "peer_lease_age_seconds": {"0": 0.2, "1": 0.9}},
              {"event": "metrics",
               "metrics": {"cluster/peer_losses": 0.0}}]
    cl = summarize_events(events)["cluster"]
    assert cl["peer_losses"] == 0
    assert cl["last_suspect_host"] == UNAVAILABLE
    assert cl["consensus_epoch"] == UNAVAILABLE
    assert cl["max_peer_lease_age_seconds"] == pytest.approx(0.9)


def test_summarize_events_warm_start_section():
    """v9: aot/* counters accumulate reset-aware across process
    segments (a restart resets them to 0 — the very event warm-start
    exists for) and the LAST warm_start row — the most recent restart —
    wins the per-session numbers."""
    events = [
        # Cold session: 2 misses, a compile-paying first dispatch.
        {"event": "warm_start", "iter": 0,
         "time_to_first_step_seconds": 31.5,
         "compiles_before_first_step": 2, "aot_hits": 0, "aot_misses": 2},
        {"event": "metrics",
         "metrics": {"aot/hits": 0.0, "aot/misses": 2.0,
                     "aot/load_seconds": 0.01}},
        # Restart (counters reset): everything loads, zero compiles.
        {"event": "warm_start", "iter": 8,
         "time_to_first_step_seconds": 0.4,
         "compiles_before_first_step": 0, "aot_hits": 2, "aot_misses": 0},
        {"event": "metrics",
         "metrics": {"aot/hits": 2.0, "aot/misses": 0.0,
                     "aot/load_seconds": 0.2}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    ws = s["warm_start"]
    assert ws["aot_hits"] == 2
    assert ws["aot_misses"] == 2          # both segments counted
    # Reset detection sees 0.2 > 0.01 as a continuation (the known
    # cross-section limitation of the Prometheus rate() rule when a new
    # segment immediately exceeds the old): delta-accumulates to 0.2.
    assert ws["aot_load_seconds"] == pytest.approx(0.2)
    assert ws["time_to_first_step_seconds"] == pytest.approx(0.4)
    assert ws["compiles_before_first_step"] == 0
    assert ws["sessions"] == 2
    assert "warm start" in format_table(s)


def test_summarize_events_elastic_section():
    """v10: elastic counters accumulate reset-aware across the
    restart-in-place segments the subsystem creates by design
    (reshard/re-expand EXEC the process), cross-checked against the
    explicit event rows; generation/roster/lost track the last signal
    in log order."""
    events = [
        # Generation 0 (2 hosts), armed and healthy.
        {"event": "metrics",
         "metrics": {"elastic/reshards": 0.0,
                     "elastic/degraded_epochs": 0.0,
                     "elastic/re_expansions": 0.0,
                     "elastic/generation": 0.0,
                     "elastic/lost_hosts": 0.0}},
        # Host 1 dies: reshard row lands, then the exec resets counters.
        {"event": "elastic_reshard", "generation": 1, "roster": [0],
         "dead": [1], "orig_processes": 2, "suspects": [1]},
        # Generation 1 (degraded): two degraded epochs, then the
        # backfill arrives and the survivor re-expands.
        {"event": "metrics",
         "metrics": {"elastic/reshards": 0.0,
                     "elastic/degraded_epochs": 2.0,
                     "elastic/re_expansions": 0.0,
                     "elastic/generation": 1.0,
                     "elastic/lost_hosts": 1.0}},
        {"event": "elastic_re_expand", "generation": 2,
         "roster": [0, 1], "dead": [], "orig_processes": 2},
        # Generation 2 (full again): fresh counters.
        {"event": "metrics",
         "metrics": {"elastic/reshards": 0.0,
                     "elastic/degraded_epochs": 0.0,
                     "elastic/re_expansions": 0.0,
                     "elastic/generation": 2.0,
                     "elastic/lost_hosts": 0.0}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    el = s["elastic"]
    # Rows win over the exec-reset counters.
    assert el["reshards"] == 1
    assert el["re_expansions"] == 1
    assert el["degraded_epochs"] == 2   # reset-aware accumulation
    assert el["generation"] == 2        # last signal in log order
    assert el["roster"] == [0, 1]
    assert el["lost_hosts"] == 0
    assert "elastic" in format_table(s)


def test_elastic_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["elastic"] == UNAVAILABLE


def test_summarize_events_perf_section():
    """v12: perf/samples accumulates reset-aware across process
    segments (a preempted profiled run restarts at 0), cross-checked
    against the explicit perf_profile rows; the window-split fractions
    and top executable take the most recent row — the current shape of
    the step, which is what the MFU campaign reads."""
    events = [
        {"event": "perf_profile", "iter": 2, "wall_seconds": 0.5,
         "device_compute_frac": 0.10, "dispatch_gap_frac": 0.85,
         "top_executable": "jit_train_so1_msl0",
         "per_executable_seconds": {"jit_train_so1_msl0": 0.05}},
        {"event": "metrics",
         "metrics": {"perf/samples": 1.0, "perf/sample_seconds": 0.5}},
        # Restart: counters reset, a new sample shows the step after an
        # optimization landed.
        {"event": "perf_profile", "iter": 12, "wall_seconds": 0.25,
         "device_compute_frac": 0.40, "dispatch_gap_frac": 0.55,
         "top_executable": "jit_train_so1_msl0",
         "per_executable_seconds": {"jit_train_so1_msl0": 0.10}},
        {"event": "metrics",
         "metrics": {"perf/samples": 1.0, "perf/sample_seconds": 0.25}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    pf = s["perf"]
    assert pf["samples"] == 2               # both segments counted
    assert pf["device_compute_frac"] == pytest.approx(0.40)
    assert pf["dispatch_gap_frac"] == pytest.approx(0.55)
    assert pf["top_executable"] == "jit_train_so1_msl0"
    assert pf["top_executable_seconds"] == pytest.approx(0.10)
    assert "perf" in format_table(s)


def test_perf_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["perf"] == UNAVAILABLE


def test_summarize_events_fleet_section():
    """v11: fleet counters accumulate reset-aware across REPLICA
    restarts (a replica's flushed l2 counters drop to 0 when its
    process restarts — its contribution must still count) AND per
    source: one fleet log interleaves several replicas' flush rows
    (each carries its `replica` id), so replica 1's smaller counters
    must not read as a reset of replica 0's stream. Gauges take the
    last signal in log order; the controller's fleet/agg_* aggregates
    are distinct names, never double-counted into l2_*."""
    events = [
        # Replica 0's first life: flushes its l2 counters.
        {"event": "metrics", "replica": 0,
         "metrics": {"fleet/l2_hits": 10.0, "fleet/l2_misses": 4.0,
                     "fleet/l2_errors": 1.0,
                     "fleet/l2_publishes": 4.0}},
        # Replica 1 interleaves with SMALLER values: per-source
        # tracking must add them, not treat them as replica 0
        # resetting.
        {"event": "metrics", "replica": 1,
         "metrics": {"fleet/l2_hits": 3.0, "fleet/l2_misses": 2.0,
                     "fleet/l2_errors": 0.0,
                     "fleet/l2_publishes": 2.0}},
        # The controller process (no replica id): membership gauges,
        # one rolling swap that halted on a canary fail, and its
        # fleet-wide aggregates under the distinct agg_* names (must
        # NOT double into the l2_* sums).
        {"event": "metrics",
         "metrics": {"fleet/replicas_live": 3.0,
                     "fleet/replicas_draining": 1.0,
                     "fleet/rolling_swaps": 1.0,
                     "fleet/rolling_swap_halts": 1.0,
                     "fleet/router_spills": 7.0,
                     "fleet/agg_l2_hits": 13.0}},
        # Replica 0 restarted: counters reset below its own previous
        # value — the reset rule contributes the new segment whole.
        {"event": "metrics", "replica": 0,
         "metrics": {"fleet/l2_hits": 5.0, "fleet/l2_misses": 1.0,
                     "fleet/l2_errors": 0.0,
                     "fleet/l2_publishes": 1.0}},
        # Final controller flush: membership gauges last-wins.
        {"event": "metrics",
         "metrics": {"fleet/replicas_live": 2.0,
                     "fleet/replicas_draining": 0.0}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    fl = s["fleet"]
    assert fl["l2_hits"] == 18        # r0: 10 + 5 (restart); r1: 3
    assert fl["l2_misses"] == 7
    assert fl["l2_errors"] == 1
    assert fl["l2_publishes"] == 7
    assert fl["l2_hit_frac"] == pytest.approx(0.72)
    assert fl["rolling_swaps"] == 1
    assert fl["rolling_swap_halts"] == 1
    assert fl["router_spills"] == 7
    assert fl["replicas_live"] == 2   # last signal wins
    assert fl["replicas_draining"] == 0
    assert "fleet" in format_table(s)


def test_fleet_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["fleet"] == UNAVAILABLE


def test_summarize_events_fleet_health_section():
    """v16: self-healing counters (supervisor restarts/crash-loops/
    scaling, router failovers/breaker trips, replica sheds) accumulate
    reset-aware PER SOURCE — the supervisor flushes under
    replica="supervisor", each replica under its id — and the
    supervisor's lifecycle rows tally by kind, so the report names
    WHICH healing paths fired. replicas_desired is a gauge
    (last signal wins)."""
    events = [
        {"event": "fleet_supervisor", "kind": "spawn", "slot": 0},
        {"event": "fleet_supervisor", "kind": "running", "slot": 0},
        {"event": "fleet_supervisor", "kind": "restart_scheduled",
         "slot": 0},
        {"event": "fleet_supervisor", "kind": "crash_loop", "slot": 0},
        # Supervisor flush: its own counters + the desired gauge.
        {"event": "metrics", "replica": "supervisor",
         "metrics": {"fleet/restarts": 2.0, "fleet/crash_loops": 1.0,
                     "fleet/scale_ups": 1.0, "fleet/scale_downs": 0.0,
                     "fleet/replicas_desired": 3.0}},
        # A replica's engine flush carries its shed counter; a SECOND
        # replica's smaller value must add, not read as a reset.
        {"event": "metrics", "replica": 0,
         "metrics": {"serve/shed_total": 7.0}},
        {"event": "metrics", "replica": 1,
         "metrics": {"serve/shed_total": 2.0}},
        # The router driver's flush (no replica id): failovers +
        # breaker trips.
        {"event": "metrics",
         "metrics": {"fleet/failovers": 4.0,
                     "fleet/breaker_trips": 1.0}},
        # Replica 0 restarted: its shed counter resets below its own
        # previous value — the new segment contributes whole.
        {"event": "metrics", "replica": 0,
         "metrics": {"serve/shed_total": 3.0}},
        # Final supervisor flush: gauge last-wins, counters monotone.
        {"event": "metrics", "replica": "supervisor",
         "metrics": {"fleet/restarts": 2.0, "fleet/crash_loops": 1.0,
                     "fleet/scale_ups": 1.0, "fleet/scale_downs": 1.0,
                     "fleet/replicas_desired": 2.0}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    fh = s["fleet_health"]
    assert fh["restarts"] == 2
    assert fh["crash_loops"] == 1
    assert fh["scale_ups"] == 1 and fh["scale_downs"] == 1
    assert fh["failovers"] == 4
    assert fh["breaker_trips"] == 1
    assert fh["sheds"] == 12          # r0: 7 + 3 (restart); r1: 2
    assert fh["replicas_desired"] == 2  # last signal wins
    assert fh["supervisor_events"] == {
        "spawn": 1, "running": 1, "restart_scheduled": 1,
        "crash_loop": 1}
    assert "fleet health" in format_table(s)
    # The healing counters must not leak into the v11 fleet section's
    # l2/router tallies (distinct key sets over the same rows).
    assert s["fleet"]["l2_hits"] == 0
    assert s["fleet"]["router_spills"] == 0


def test_fleet_health_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["fleet_health"] == UNAVAILABLE


def test_summarize_events_traffic_section():
    """v17: continuous-batching dispatch counters (replica flushes) and
    weighted-canary split counters (router+controller driver flushes)
    accumulate reset-aware per source; the canary weight — the rollout
    ladder's current stage — is a gauge (last signal wins)."""
    events = [
        # Replica 0's engine flush mirrors its assembler counters.
        {"event": "metrics", "replica": 0,
         "metrics": {"serve/cb_groups": 10.0,
                     "serve/cb_fill_dispatch": 6.0,
                     "serve/cb_linger_dispatch": 4.0}},
        # Replica 1 flushes smaller values: a second SOURCE, not a
        # counter reset — totals must add.
        {"event": "metrics", "replica": 1,
         "metrics": {"serve/cb_groups": 3.0,
                     "serve/cb_fill_dispatch": 1.0,
                     "serve/cb_linger_dispatch": 2.0}},
        # The driver's flush: split counters + the stage-weight gauge.
        {"event": "metrics",
         "metrics": {"fleet/canary_requests": 25.0,
                     "fleet/cohort_fallbacks": 1.0,
                     "fleet/canary_stage_promotions": 1.0,
                     "fleet/canary_weight": 0.01}},
        # Replica 0 restarted: counters reset below their own previous
        # values — the new segment contributes whole.
        {"event": "metrics", "replica": 0,
         "metrics": {"serve/cb_groups": 2.0,
                     "serve/cb_fill_dispatch": 1.0,
                     "serve/cb_linger_dispatch": 1.0}},
        # Later driver flush: promoted to the 10% stage.
        {"event": "metrics",
         "metrics": {"fleet/canary_requests": 60.0,
                     "fleet/cohort_fallbacks": 1.0,
                     "fleet/canary_stage_promotions": 2.0,
                     "fleet/canary_weight": 0.10}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    tr = s["traffic"]
    assert tr["cb_groups"] == 15            # r0: 10 + 2 (restart); r1: 3
    assert tr["cb_fill_dispatches"] == 8
    assert tr["cb_linger_dispatches"] == 7
    assert tr["canary_requests"] == 60
    assert tr["cohort_fallbacks"] == 1
    assert tr["stage_promotions"] == 2
    assert tr["canary_weight"] == 0.10      # gauge: last signal wins
    assert "traffic" in format_table(s)


def test_traffic_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["traffic"] == UNAVAILABLE


def test_summarize_events_alerts_section():
    """v18: fired/resolved tallies from the explicit ``alert``
    transition rows; still_firing replays transitions last-wins per
    (source, rule, labels) — a fired-then-resolved instance reads
    closed, the same rule on a DIFFERENT source is its own instance."""
    events = [
        {"event": "alert", "rule": "replica_restarts", "type": "rate",
         "severity": "warn", "state": "firing", "labels": {},
         "source": "supervisor"},
        {"event": "alert", "rule": "replica_restarts", "type": "rate",
         "severity": "warn", "state": "resolved", "labels": {},
         "source": "supervisor"},
        {"event": "alert", "rule": "heartbeat_stale", "type": "absence",
         "severity": "critical", "state": "firing",
         "labels": {"signal": "heartbeat"}, "source": "train"},
        # Same rule name, different source: a distinct instance that is
        # STILL firing at the end of the log.
        {"event": "alert", "rule": "replica_restarts", "type": "rate",
         "severity": "warn", "state": "firing", "labels": {},
         "source": "driver"},
        {"event": "alert", "rule": "replica_restarts", "type": "rate",
         "severity": "warn", "state": "firing", "labels": {},
         "source": "supervisor"},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    al = s["alerts"]
    assert al["fired"] == 4
    assert al["resolved"] == 1
    # Still firing: train/heartbeat_stale, driver/replica_restarts and
    # the supervisor's re-fired replica_restarts.
    assert al["still_firing"] == 3
    assert al["fired_by_severity"] == {"warn": 3, "critical": 1}
    assert al["most_fired_rule"] == "replica_restarts"
    assert "alerts" in format_table(s)


def test_alerts_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["alerts"] == UNAVAILABLE
    assert s["schema"] == "maml_tpu_telemetry_report_v18"


def test_tune_section_reset_aware_across_sweep_segments():
    """Autotune section (schema v13): one sweep log legitimately spans
    several DRIVER lifetimes — the ledger's kill-and-resume contract —
    so tune/* counters must accumulate reset-aware across the
    segments, cross-checked against the explicit tune_trial rows; the
    best objective is the max over ok rows; the adoption verdict and
    tuned fingerprint ride the tune_adopt row."""
    events = [
        # Segment 1: three trials (one invalid-flag failure), then the
        # driver is killed — its final flush carries the counters.
        {"event": "tune_trial", "trial_id": "baseline", "outcome": "ok",
         "objective": 6.9, "objective_key": "tasks_per_sec_per_chip"},
        {"event": "tune_trial", "trial_id": "aaa", "outcome":
         "invalid_flag", "objective": None},
        {"event": "tune_trial", "trial_id": "bbb", "outcome": "ok",
         "objective": 7.4, "objective_key": "tasks_per_sec_per_chip"},
        {"event": "metrics",
         "metrics": {"tune/trials_run": 3.0, "tune/trials_failed": 1.0,
                     "tune/invalid_flag_failures": 1.0}},
        # Segment 2 (resumed driver): counters RESET to a smaller
        # value — the new segment contributes whole, not as a delta.
        {"event": "tune_trial", "trial_id": "ccc", "outcome": "ok",
         "objective": 8.1, "objective_key": "tasks_per_sec_per_chip"},
        # A row scored in a DIFFERENT unit (failed flops walk degraded
        # mfu->tasks/s, or vice versa) must not win best_objective on
        # raw magnitude — the unit anchors on the first scored row.
        {"event": "tune_trial", "trial_id": "ddd", "outcome": "ok",
         "objective": 999.0, "objective_key": "mfu"},
        {"event": "metrics",
         "metrics": {"tune/trials_run": 1.0, "tune/trials_failed": 0.0,
                     "tune/invalid_flag_failures": 0.0}},
        {"event": "tune_adopt", "adopted": True,
         "reason": "parity passed (bitwise)", "trial_id": "ccc",
         "tuned_fingerprint": "deadbeefdeadbeefcafe"},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    tn = s["tune"]
    assert tn["trials_run"] == 5          # row fallback beats counters
    assert tn["trials_failed"] == 1
    assert tn["invalid_flag_failures"] == 1
    assert tn["best_objective"] == 8.1
    assert tn["objective"] == "tasks_per_sec_per_chip"
    assert tn["adopted"] is True
    assert tn["tuned_fingerprint"] == "deadbeefdeadbeef"  # 16-char key
    assert "tune" in format_table(s)


def test_tune_section_rejected_sweep_and_row_fallback():
    """A rejected winner reads as adopted=False (the honest verdict is
    a first-class signal), and a log whose registry flush was lost
    still counts trials from the explicit rows."""
    events = [
        {"event": "tune_trial", "trial_id": "baseline", "outcome": "ok",
         "objective": 6.9, "objective_key": "mfu"},
        {"event": "tune_trial", "trial_id": "aaa", "outcome": "crashed"},
        {"event": "tune_adopt", "adopted": False,
         "reason": "parity gate: fail"},
    ]
    tn = summarize_events(events)["tune"]
    assert tn["trials_run"] == 2          # row fallback, no metrics row
    assert tn["trials_failed"] == 1
    assert tn["best_objective"] == 6.9
    assert tn["adopted"] is False
    assert tn["tuned_fingerprint"] == UNAVAILABLE


def test_tune_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["tune"] == UNAVAILABLE


def test_summarize_events_requests_section():
    """Request-tracing section (schema v14): reqtrace/SLO counters
    accumulate reset-aware and PER SOURCE (one fleet log interleaves
    the driver's flush with every replica's, each keyed by its
    `replica` id — a replica's smaller counter must not read as a
    reset of another's stream); request_trace rows assemble through
    the SAME linked/attribute definitions as fleet_bench's gate and
    slo_report; the burn-rate gauge takes the last signal."""
    events = [
        # Replica 0's first life, then the driver ring interleaving
        # with SMALLER counters (a different source, not a reset), then
        # replica 0 restarted below its own previous value (a reset —
        # the new segment contributes whole).
        {"event": "metrics", "replica": 0,
         "metrics": {"reqtrace/spans": 5.0, "reqtrace/dropped": 0.0,
                     "fleet/slo_good_total": 4.0,
                     "fleet/slo_bad_total": 1.0,
                     "fleet/slo_burn_rate": 4.0}},
        {"event": "metrics", "replica": "driver",
         "metrics": {"reqtrace/spans": 3.0,
                     "fleet/slo_burn_rate": 0.5}},
        {"event": "metrics", "replica": 0,
         "metrics": {"reqtrace/spans": 2.0}},
        # One fully-linked trace (root + hops, queue-dominant) ...
        {"event": "request_trace", "trace_id": "t1", "span_id": "r.1",
         "parent_id": None, "name": "request", "dur_s": 1.0,
         "tenant": "a"},
        {"event": "request_trace", "trace_id": "t1", "span_id": "r.2",
         "parent_id": "r.1", "name": "socket_queue", "dur_s": 0.6,
         "tenant": "a"},
        {"event": "request_trace", "trace_id": "t1", "span_id": "r.3",
         "parent_id": "r.1", "name": "predict", "dur_s": 0.1,
         "tenant": "a"},
        # ... and one orphan hop whose root never flushed (unlinked).
        {"event": "request_trace", "trace_id": "t2", "span_id": "x.2",
         "parent_id": "zzz", "name": "predict", "dur_s": 0.2,
         "tenant": "b"},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    rq = s["requests"]
    assert rq["spans_recorded"] == 10   # r0: 5 + 2 (restart); driver: 3
    assert rq["spans_dropped"] == 0
    assert rq["trace_rows"] == 4
    assert rq["traces"] == 2
    assert rq["linked"] == 1
    assert rq["linked_frac"] == pytest.approx(0.5)
    assert rq["dominant_tier"] == "queue"   # over LINKED traces only
    assert rq["tenants"] == 2
    assert rq["slo_good"] == 4 and rq["slo_bad"] == 1
    assert rq["slo_bad_frac"] == pytest.approx(0.2)
    assert rq["slo_burn_rate"] == 0.5       # gauge: last signal wins
    assert "requests" in format_table(s)


def test_requests_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["requests"] == UNAVAILABLE


def test_summarize_events_algo_section():
    """Algo section (schema v15): identity/counts are last-signal (an
    ANIL hot-swap legitimately changes the adapted count mid-log),
    adapt p50 is tracked PER VARIANT from the meta_algorithm-stamped
    serving rows, and adapt-batch counters accumulate reset-aware per
    (replica, variant)."""
    events = [
        {"event": "algo", "meta_algorithm": "maml++",
         "task_type": "classification", "adapted_params": 1000,
         "total_params": 1000},
        {"event": "metrics", "meta_algorithm": "maml++",
         "replica": "r1",
         "metrics": {"serve/adapt_seconds": {"count": 4, "sum": 0.8,
                                             "p50": 0.2, "p95": 0.3},
                     "serve/adapt_batches": 10.0}},
        # Replica restart: the counter RESETS to 4 — accumulated total
        # must read 14, not max(10, 4).
        {"event": "metrics", "meta_algorithm": "maml++",
         "replica": "r1",
         "metrics": {"serve/adapt_batches": 4.0}},
        # Hot-swap onto the ANIL variant: last signal wins for identity
        # and counts; its adapt p50 lands under its own variant key.
        {"event": "algo", "meta_algorithm": "anil",
         "task_type": "classification", "adapted_params": 100,
         "total_params": 1000},
        {"event": "metrics", "meta_algorithm": "anil",
         "replica": "r2",
         "metrics": {"serve/adapt_seconds": {"count": 2, "sum": 0.1,
                                             "p50": 0.05, "p95": 0.06},
                     "serve/adapt_batches": 6.0}},
    ]
    s = summarize_events(events)
    assert set(s) == SCHEMA_KEYS
    al = s["algo"]
    assert al["meta_algorithm"] == "anil"
    assert al["task_type"] == "classification"
    assert al["adapted_params"] == 100
    assert al["total_params"] == 1000
    assert al["adapted_frac"] == pytest.approx(0.1)
    assert al["adapt_seconds_p50"] == {"maml++": 0.2, "anil": 0.05}
    assert al["adapt_batches"] == {"maml++": 14, "anil": 6}
    assert "algo" in format_table(s)


def test_algo_section_gauge_rows_without_algo_event():
    """A serving-only log (no trainer 'algo' row) still summarizes from
    the algo/* gauges ServingEngine mirrors into its flushes."""
    events = [{"event": "metrics", "meta_algorithm": "anil",
               "metrics": {"algo/adapted_params": 55.0,
                           "algo/total_params": 550.0}}]
    al = summarize_events(events)["algo"]
    assert al["meta_algorithm"] == "anil"
    assert al["adapted_params"] == 55 and al["total_params"] == 550
    assert al["adapted_frac"] == pytest.approx(0.1)
    assert al["adapt_seconds_p50"] == UNAVAILABLE
    assert al["adapt_batches"] == UNAVAILABLE


def test_algo_section_unavailable_without_subsystem():
    s = summarize_events([{"event": "train_epoch", "epoch": 0}])
    assert s["algo"] == UNAVAILABLE


def test_health_section_nonfinite_grad_norm_visible():
    """A NaN grad norm is nulled by the JSONL writer; the report must
    show 'non-finite' — the diagnosis itself — not hide the row."""
    events = [{"event": "health", "iter": 5, "grad_norm": None,
               "update_ratio_max": 0.1}]
    h = summarize_events(events)["health"]
    assert h["grad_norm"] == "non-finite"
    assert h["grad_norm_warns"] == 0


def test_summarize_events_failsoft_markers(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.tracing import read_jsonl
    path = write_fixture_events(tmp_path / "events.jsonl",
                                with_failsoft=False)
    s = summarize_events(read_jsonl(path))
    # Metrics that never reported say so EXPLICITLY — "unavailable", not 0.
    assert s["compile_count"] == UNAVAILABLE
    assert s["compile_seconds"] == UNAVAILABLE
    assert s["peak_memory_bytes"] == UNAVAILABLE
    assert UNAVAILABLE in format_table(s)


def test_cli_smoke_fixture_schema(tmp_path):
    """Tier-1 CLI rot guard: subprocess run over a fixture, JSON schema
    asserted on the LAST stdout line (the bench.py artifact contract)."""
    write_fixture_events(tmp_path / "events.jsonl")
    r = subprocess.run([sys.executable, CLI, str(tmp_path)],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-1000:]
    lines = r.stdout.strip().splitlines()
    summary = json.loads(lines[-1])
    assert set(summary) == SCHEMA_KEYS
    assert summary["epochs"] == 2
    assert "telemetry report" in lines[0]  # human table precedes JSON
    # --json mode: machine line only.
    rj = subprocess.run([sys.executable, CLI, "--json",
                        str(tmp_path / "events.jsonl")],
                        capture_output=True, text=True, timeout=120,
                        cwd=REPO)
    assert rj.returncode == 0
    assert json.loads(rj.stdout.strip()) == summary


def test_cli_errors_are_json(tmp_path):
    r = subprocess.run([sys.executable, CLI,
                        str(tmp_path / "missing.jsonl")],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == 1
    assert "error" in json.loads(r.stdout.strip().splitlines()[-1])
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    r2 = subprocess.run([sys.executable, CLI, str(empty)],
                        capture_output=True, text=True, timeout=120,
                        cwd=REPO)
    assert r2.returncode == 1
    assert "empty" in json.loads(r2.stdout.strip().splitlines()[-1])["error"]


@pytest.mark.slow  # real 2-epoch training run (~20s, 1 core); the
#                    fixture-driven CLI smoke above stays tier-1
def test_report_on_real_two_epoch_cpu_run(tmp_path):
    """THE acceptance scenario: a 2-epoch CPU smoke run, then the CLI
    reports step-time percentiles, compile count/seconds, feed-stall
    fraction and peak memory (explicitly 'unavailable' on CPU)."""
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    cfg = MAMLConfig(
        experiment_name="telemetry_e2e",
        experiment_root=str(tmp_path),
        dataset_name="synthetic",
        image_height=12, image_width=12, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=1, batch_size=2,
        cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        second_order=False, use_multi_step_loss_optimization=False,
        total_epochs=2, total_iter_per_epoch=2,
        num_evaluation_tasks=2, max_models_to_save=2,
        # Health introspection on, fetched at every sync (ISSUE 7): the
        # report's v6 section must render from a REAL pipeline.
        dispatch_sync_every=1, health_metrics_every_n_steps=1)
    ExperimentBuilder(cfg).run_experiment()

    exp_dir = os.path.join(str(tmp_path), "telemetry_e2e")
    r = subprocess.run([sys.executable, CLI, "--json", exp_dir],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-1000:]
    s = json.loads(r.stdout.strip())
    assert s["epochs"] == 2
    assert s["steps"] == 4
    assert s["step_seconds_p50"] > 0
    assert s["step_seconds_p95"] >= s["step_seconds_p50"]
    assert s["meta_tasks_per_sec_per_chip"] > 0
    # In-process jit compiles were counted by the monitoring listener.
    assert isinstance(s["compile_count"], int) and s["compile_count"] > 0
    assert s["compile_seconds"] > 0
    assert isinstance(s["feed_stall_frac"], float)
    # CPU backend has no allocator stats: explicit marker, never fake 0.
    assert s["peak_memory_bytes"] == UNAVAILABLE
    assert s["host_skew"]["hosts"] == 1
    # v4 data-plane section: build_source counted what fed the run.
    assert s["data"]["source_kind"] == "synthetic"
    # v5 watchdog section: the default-enabled watchdog reported
    # liveness (0 trips on a healthy run — a measured zero, not absent).
    assert s["watchdog"]["trips"] == 0
    assert s["watchdog"]["last_phase"] in (
        "step", "feed", "collective", "compile", "idle")
    assert isinstance(s["watchdog"]["progress_age_seconds"], float)
    # v6 health section: in-graph diagnostics fetched at the sync points
    # (0 warnings on a healthy run — measured zero, not absent).
    assert s["health"]["grad_norm"] > 0
    assert s["health"]["update_ratio_max"] > 0
    assert s["health"]["lslr_min"] > 0
    assert s["health"]["grad_norm_warns"] == 0
    # v7 checkpoint section: every epoch saved synchronously through
    # the writer (0 skips/blocks on the sync path — measured zeros).
    assert s["checkpoint"]["saves"] == 2
    assert s["checkpoint"]["save_seconds"] > 0
    assert s["checkpoint"]["skipped_saves"] == 0
    assert s["checkpoint"]["blocked_seconds"] == 0
    # The Prometheus textfile snapshot landed next to the JSONL stream.
    prom = open(os.path.join(exp_dir, "logs", "metrics.prom")).read()
    assert "# TYPE compile_count counter" in prom
    assert "test_accuracy_mean" in prom

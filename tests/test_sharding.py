"""Mesh sharding tests on the virtual 8-device CPU platform: sharded
training must match single-device numerics, both mesh factorizations must
work, and the driver dry-run must pass."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import Episode, init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, shard_batch)

CFG = MAMLConfig(
    image_height=10, image_width=10, image_channels=1,
    num_classes_per_set=3, num_samples_per_class=2, num_target_samples=2,
    cnn_num_filters=8, num_stages=2, batch_size=8,
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    compute_dtype="float32", meta_learning_rate=0.01)


def _batch(key, cfg):
    n, k, t, b = (cfg.num_classes_per_set, cfg.num_samples_per_class,
                  cfg.num_target_samples, cfg.batch_size)
    h, w, c = cfg.image_shape
    ks = jax.random.split(key, 3)
    protos = jax.random.normal(ks[0], (b, n, h, w, c))

    def mk(key, per):
        noise = jax.random.normal(key, (b, n, per, h, w, c)) * 0.4
        x = (protos[:, :, None] + noise).reshape(b, n * per, h, w, c)
        y = jnp.tile(jnp.repeat(jnp.arange(n), per)[None], (b, 1))
        return x, y.astype(jnp.int32)

    sx, sy = mk(ks[1], k)
    tx, ty = mk(ks[2], t)
    return Episode(sx, sy, tx, ty)


def _run_steps(cfg, mesh_shape, devices, n_iters=3):
    cfg = cfg.replace(mesh_shape=mesh_shape)
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, devices)
    plan = make_sharded_steps(cfg, apply, mesh)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    state = jax.device_put(
        state, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    losses = []
    for i in range(n_iters):
        batch = shard_batch(_batch(jax.random.PRNGKey(10 + i), cfg), mesh)
        state, m = plan.train_steps[(True, True)](state, batch,
                                                 jnp.float32(0))
        losses.append(float(m.loss))
    return state, losses


def test_sharded_matches_single_device():
    """8-way task sharding must reproduce single-device numerics: the psum
    over the tasks axis is exactly the unsharded mean."""
    state1, losses1 = _run_steps(CFG, (1, 1), jax.devices()[:1])
    state8, losses8 = _run_steps(CFG, (1, 8), jax.devices())
    np.testing.assert_allclose(losses1, losses8, rtol=2e-4)
    for name, sub in state1.params.items():
        for leaf, a in sub.items():
            if name.startswith("conv") and leaf == "b":
                # Conv biases are BN-shadowed: their true gradient is zero
                # (batch norm subtracts the mean), so Adam amplifies pure
                # reduction-order noise into a random walk — excluded.
                continue
            b = state8.params[name][leaf]
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-5,
                                       err_msg=f"{name}.{leaf}")


def test_dcn_by_tasks_mesh():
    """(dcn=2, tasks=4) factorization: collectives ride both axes."""
    _, losses24 = _run_steps(CFG, (2, 4), jax.devices())
    _, losses18 = _run_steps(CFG, (1, 8), jax.devices())
    np.testing.assert_allclose(losses24, losses18, rtol=2e-4)


def test_eval_step_sharded_outputs():
    cfg = CFG.replace(mesh_shape=(1, 8))
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices())
    plan = make_sharded_steps(cfg, apply, mesh)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    state = jax.device_put(
        state, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    res = plan.eval_step(state, shard_batch(_batch(jax.random.PRNGKey(0),
                                                   cfg), mesh))
    assert np.asarray(res.loss).shape == (8,)
    assert np.asarray(res.target_logits).shape == (8, 6, 3)


def test_mesh_validation_errors():
    with pytest.raises(ValueError, match="devices"):
        make_mesh(CFG.replace(mesh_shape=(1, 3)), jax.devices())
    cfg = CFG.replace(mesh_shape=(1, 8), batch_size=6)
    init, apply = make_model(cfg)
    with pytest.raises(ValueError, match="divisible"):
        make_sharded_steps(cfg, apply, make_mesh(cfg, jax.devices()))


@pytest.mark.slow  # pod-scale system dry run (~100s on the 1-core box)
def test_graft_dryrun_multichip():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_graft_entry_compiles():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (25, 5)


def test_sharded_microbatch_accumulation():
    """task_microbatches composes with the (dcn, tasks) mesh: the reshape
    to (M, B/M) chunks re-annotates sharding without host round-trips and
    the step still produces finite, matching results."""
    devices = jax.devices()[:8]
    cfg = CFG.replace(mesh_shape=(2, 4), task_microbatches=2,
                  batch_size=16)  # 2 tasks/device -> local chunks of 1
                                  # (microbatching is per-device under
                                  # shard_map; it must divide the local
                                  # shard, not the global batch)
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, devices)
    plan = make_sharded_steps(cfg, apply, mesh)
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def fresh_state():
        # The train step donates its state argument, and device_put with
        # an identical sharding aliases rather than copies — build an
        # independent state per call.
        return jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)), repl)

    batch = shard_batch(_batch(jax.random.PRNGKey(1), cfg), mesh)
    new_state, metrics = plan.train_steps[(True, True)](
        fresh_state(), batch, jnp.float32(0))
    assert np.isfinite(float(metrics.loss))

    # Single-shot on the same mesh gives the same loss and gradients
    # (first-moment check, linear in grads).
    cfg1 = CFG.replace(mesh_shape=(2, 4), batch_size=16)
    _, apply1 = make_model(cfg1)
    plan1 = make_sharded_steps(cfg1, apply1, mesh)
    s1, m1 = plan1.train_steps[(True, True)](
        fresh_state(), batch, jnp.float32(0))
    np.testing.assert_allclose(float(m1.loss), float(metrics.loss),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s1.opt_state),
                    jax.tree.leaves(new_state.opt_state)):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b, np.float64),
                                   rtol=2e-4, atol=1e-7)


def test_microbatch_clamped_to_local_shard():
    """The shipped single-chip sweep winners set task_microbatches as
    high as the full batch (e.g. omniglot 5w1s: mb=16, batch=16). On a
    multi-chip mesh the per-device shard shrinks below that; the plan
    must degrade to gcd(mb, local) with a warning rather than abort,
    and the clamped step must reproduce single-shot numerics (the
    accumulation chunking is bit-equivalent)."""
    devices = jax.devices()[:8]
    cfg = CFG.replace(mesh_shape=(1, 8), batch_size=16,
                      task_microbatches=16)  # local shard = 2 < mb
    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, devices)
    with pytest.warns(UserWarning, match="clamping to gcd 2"):
        plan = make_sharded_steps(cfg, apply, mesh)
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def fresh_state():
        return jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)), repl)

    batch = shard_batch(_batch(jax.random.PRNGKey(1), cfg), mesh)
    _, m = plan.train_steps[(True, True)](fresh_state(), batch,
                                          jnp.float32(0))
    assert np.isfinite(float(m.loss))

    cfg1 = cfg.replace(task_microbatches=1)
    _, apply1 = make_model(cfg1)
    plan1 = make_sharded_steps(cfg1, apply1, mesh)
    _, m1 = plan1.train_steps[(True, True)](fresh_state(), batch,
                                            jnp.float32(0))
    np.testing.assert_allclose(float(m1.loss), float(m.loss), rtol=1e-6)

    # A --batch_size downscale of a shipped config (mb now > the new
    # batch) must also clamp, not abort: gcd(16, 8) = 8 keeps one task
    # per chunk on the shrunken single-chip geometry too.
    cfg_small = CFG.replace(mesh_shape=(1, 1), batch_size=8,
                            task_microbatches=16)
    with pytest.warns(UserWarning, match="clamping to gcd 8"):
        make_sharded_steps(cfg_small, apply,
                           make_mesh(cfg_small, jax.devices()[:1]))

    # ADVICE r4: a value sharing NO factor with a multi-task shard
    # (mb=7 against local 16) was never legal at any geometry this
    # config describes — clamping would silently run mb=1 and lose all
    # accumulation benefit, so the plan must refuse instead. Callers
    # that want the degradation pre-resolve via
    # effective_task_microbatches (as bench.load_workload and
    # ExperimentBuilder do).
    cfg_bad = CFG.replace(mesh_shape=(1, 1), batch_size=16,
                          task_microbatches=7)
    with pytest.raises(ValueError, match="shares no factor"):
        make_sharded_steps(cfg_bad, apply,
                           make_mesh(cfg_bad, jax.devices()[:1]))
    # ...but a 1-task-per-device shard (local == 1) keeps clamping:
    # mb accumulation is meaningless there, not misconfigured.
    cfg_dp = CFG.replace(mesh_shape=(1, 8), batch_size=8,
                         task_microbatches=4)
    with pytest.warns(UserWarning, match="clamping to gcd 1"):
        make_sharded_steps(cfg_dp, apply,
                           make_mesh(cfg_dp, jax.devices()[:8]))


@pytest.mark.slow  # pod-workload backbone on an 8-way mesh (~70s, 1 core)
def test_resnet12_trains_on_sharded_mesh():
    """Regression (r2): resnet12's 1x1 skip projections, vmapped over
    per-task fast kernels, used to lower to feature-grouped convs that the
    SPMD partitioner cannot partition (INVALID_ARGUMENT on any >1-chip
    mesh) — every multi-chip resnet12/pod run was broken. 1x1/stride-1
    convs now lower as per-pixel matmuls (layers.conv2d_apply)."""
    cfg = CFG.replace(backbone="resnet12", cnn_num_filters=4,
                      image_channels=3, task_microbatches=2,
                      batch_size=16,  # keeps local chunks >= 1 task
                      image_height=16, image_width=16)  # 4 pool stages
    _, losses = _run_steps(cfg, (2, 4), jax.devices())
    assert np.isfinite(losses).all()


def test_conv1x1_dot_matches_conv_lowering():
    """The 1x1-as-dot lowering must be numerically equivalent to the
    general conv lowering (f32)."""
    from howtotrainyourmamlpytorch_tpu.models import layers

    key = jax.random.PRNGKey(0)
    params = layers.conv2d_init(key, 6, 10, kernel_size=1)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8, 6))
    got = layers.conv2d_apply(params, x, compute_dtype=jnp.float32)
    want = jax.lax.conv_general_dilated(
        x, params["w"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + params["b"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_msl_batched_on_multichip_mesh_matches_serial():
    """'on' (out-of-scan batched MSL target forwards) on a >1-chip mesh:
    legal under the shard_map formulation (the r2 GSPMD form could not
    compile this), and numerically identical to the serial path."""
    cfg_on = CFG.replace(mesh_shape=(2, 4), msl_target_batching="on",
                         second_order=True,
                         use_multi_step_loss_optimization=True)
    cfg_ser = cfg_on.replace(msl_target_batching="off")
    losses = {}
    for name, cfg in (("on", cfg_on), ("off", cfg_ser)):
        init, apply = make_model(cfg)
        mesh = make_mesh(cfg, jax.devices()[:8])
        plan = make_sharded_steps(cfg, apply, mesh)
        state = jax.device_put(
            init_train_state(cfg, init, jax.random.PRNGKey(0)),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
        batch = shard_batch(_batch(jax.random.PRNGKey(5), cfg), mesh)
        _, m = plan.train_steps[(True, True)](state, batch, jnp.float32(0))
        losses[name] = float(m.loss)
    np.testing.assert_allclose(losses["on"], losses["off"], rtol=1e-6)

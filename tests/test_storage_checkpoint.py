import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.utils import (
    CheckpointManager, build_experiment_folder, load_statistics,
    save_statistics)

CFG = MAMLConfig(image_height=8, image_width=8, image_channels=1,
                 num_classes_per_set=2, cnn_num_filters=4, num_stages=1,
                 number_of_training_steps_per_iter=2,
                 number_of_evaluation_steps_per_iter=2,
                 compute_dtype="float32")

pytestmark = pytest.mark.core  # <5-min pre-commit gate tier



def test_experiment_folder_layout(tmp_path):
    paths = build_experiment_folder(str(tmp_path), "exp1")
    assert os.path.isdir(paths["saved_models"])
    assert os.path.isdir(paths["logs"])


def test_statistics_roundtrip(tmp_path):
    logs = str(tmp_path)
    save_statistics(logs, {"epoch": 0, "loss": 1.5})
    save_statistics(logs, {"epoch": 1, "loss": 1.2})
    stats = load_statistics(logs)
    assert stats["epoch"] == ["0", "1"]
    assert stats["loss"] == ["1.5", "1.2"]
    with pytest.raises(ValueError, match="columns"):
        save_statistics(logs, {"epoch": 2, "other": 1})


def _state():
    init, _ = make_model(CFG)
    return init_train_state(CFG, init, jax.random.PRNGKey(0))


def test_checkpoint_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    state = _state()
    mgr.save(state, epoch=0, current_iter=10, val_acc=0.5)
    loaded, meta = mgr.load(_state(), 0)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert meta["current_iter"] == 10


def test_checkpoint_retention_top_k(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    state = _state()
    accs = {0: 0.1, 1: 0.9, 2: 0.3, 3: 0.7, 4: 0.5, 5: 0.6}
    for epoch, acc in accs.items():
        mgr.save(state, epoch, current_iter=epoch * 10, val_acc=acc)
    assert mgr.top_epochs() == [1, 3, 5]  # by val acc desc
    kept = {f for f in os.listdir(tmp_path) if f.endswith(".ckpt")}
    assert kept == {"train_model_1.ckpt", "train_model_3.ckpt",
                    "train_model_5.ckpt", "train_model_latest.ckpt"}
    assert mgr.meta["best_val_acc"] == 0.9
    assert mgr.meta["best_val_epoch"] == 1


def test_checkpoint_manager_reloads_meta(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(), 0, current_iter=7, val_acc=0.4)
    mgr2 = CheckpointManager(str(tmp_path))
    assert mgr2.meta["current_iter"] == 7
    assert mgr2.has_checkpoint("latest")
    loaded, meta = mgr2.load(_state(), "latest")
    assert meta["val_acc_per_epoch"]["0"] == 0.4


def test_epoch_tag_load_returns_epoch_iter(tmp_path):
    """Loading a specific epoch must return that epoch's iteration, and
    rewinding must drop later epochs from the ensemble bookkeeping."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=5)
    state = _state()
    for epoch in range(4):
        mgr.save(state, epoch, current_iter=(epoch + 1) * 10,
                 val_acc=0.1 * (epoch + 1))
    _, meta = mgr.load(_state(), 1)
    assert meta["current_iter"] == 20
    assert meta["current_epoch"] == 1
    # latest still reports the global position
    _, meta_l = mgr.load(_state(), "latest")
    assert meta_l["current_iter"] == 40

    mgr.rewind_to(1)
    assert set(mgr.meta["val_acc_per_epoch"]) == {"0", "1"}
    assert mgr.meta["best_val_epoch"] == 1
    assert mgr.top_epochs() == [1, 0]
    with pytest.raises(KeyError):
        mgr.rewind_to(77)


def test_missing_checkpoint_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        mgr.load(_state(), 99)


# ---------------------------------------------------------------------------
# loaded-shape reconciliation (ADVICE r2: pre-full-affine layer-norm ckpts)
# ---------------------------------------------------------------------------

LN_CFG = MAMLConfig(image_height=8, image_width=8, image_channels=1,
                    num_classes_per_set=2, cnn_num_filters=4, num_stages=1,
                    number_of_training_steps_per_iter=2,
                    number_of_evaluation_steps_per_iter=2,
                    norm_layer="layer_norm", per_step_bn_statistics=False,
                    compute_dtype="float32")


def _ln_state():
    init, _ = make_model(LN_CFG)
    return init_train_state(LN_CFG, init, jax.random.PRNGKey(0))


def _shrink_ln_affine(state):
    """Rewrite every 4D layer-norm γ/β leaf (and its Adam moments) to the
    pre-change per-channel (1, C) shape, as an old checkpoint held."""
    def shrink(path, leaf):
        name = jax.tree_util.keystr(path)
        if (name.endswith("['gamma']") or name.endswith("['beta']")) \
                and jnp.ndim(leaf) == 4:
            return leaf[:, 0, 0, :]
        return leaf
    return jax.tree_util.tree_map_with_path(shrink, state)


def test_old_layer_norm_checkpoint_migrates(tmp_path):
    from howtotrainyourmamlpytorch_tpu.meta.outer import (
        reconcile_loaded_shapes, state_leaf_shapes)
    fresh = _ln_state()
    template_shapes = state_leaf_shapes(fresh)
    old = _shrink_ln_affine(fresh)
    assert any(jnp.shape(a) != jnp.shape(b) for a, b in
               zip(jax.tree.leaves(old), jax.tree.leaves(fresh)))

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(old, epoch=0, current_iter=5, val_acc=0.4)
    # from_bytes restores the old per-channel leaves without validation...
    loaded, _ = mgr.load(_ln_state(), 0)
    assert any(jnp.ndim(l) == 2 for l in jax.tree.leaves(loaded.params))
    # ...and reconciliation broadcasts them back to the full affine.
    migrated = reconcile_loaded_shapes(LN_CFG, loaded, template_shapes)
    for leaf, want in zip(jax.tree.leaves(migrated), template_shapes):
        assert jnp.shape(leaf) == tuple(want)
    # Broadcast semantics: every (h, w) position holds the channel value.
    def check(path, leaf):
        name = jax.tree_util.keystr(path)
        if (name.endswith("['gamma']") or name.endswith("['beta']")) \
                and jnp.ndim(leaf) == 4:
            np.testing.assert_array_equal(
                np.asarray(leaf),
                np.broadcast_to(np.asarray(leaf)[:, :1, :1, :],
                                leaf.shape))
    jax.tree_util.tree_map_with_path(check, migrated.params)


def test_unknown_shape_mismatch_refuses(tmp_path):
    from howtotrainyourmamlpytorch_tpu.meta.outer import (
        reconcile_loaded_shapes, state_leaf_shapes)
    fresh = _ln_state()
    template_shapes = state_leaf_shapes(fresh)

    def corrupt(path, leaf):
        name = jax.tree_util.keystr(path)
        if name.endswith("['w']") and jnp.ndim(leaf) == 4:
            return leaf[:-1]  # chop a conv kernel: no legal migration
        return leaf
    bad = jax.tree_util.tree_map_with_path(corrupt, fresh)
    with pytest.raises(ValueError, match="refusing to resume"):
        reconcile_loaded_shapes(LN_CFG, bad, template_shapes)

"""End-to-end smoke: the full experiment pipeline on a tiny synthetic
config (SURVEY.md §7 minimum slice), plus resume determinism and the CLI
arg contract."""

import json
import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.utils.storage import load_statistics

import train_maml_system


def _cfg(tmp_path, **kw):
    base = dict(
        experiment_name="smoke", experiment_root=str(tmp_path),
        dataset_name="synthetic_smoke",
        image_height=10, image_width=10, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=2, batch_size=4,
        cnn_num_filters=8, num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=5,
        num_evaluation_tasks=6, max_models_to_save=2,
        second_order=True, use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=1,  # epoch 0 MSL, epoch 1 final-only
        compute_dtype="float32", meta_learning_rate=0.005)
    base.update(kw)
    return MAMLConfig(**base)


def test_full_experiment_end_to_end(tmp_path):
    builder = ExperimentBuilder(_cfg(tmp_path))
    result = builder.run_experiment()
    # Trains both epochs (crossing the MSL->final-only boundary), then runs
    # the ensemble test protocol.
    assert result["num_models"] == 2
    assert result["num_episodes"] == 6
    assert 0.0 <= result["test_accuracy_mean"] <= 1.0
    stats = load_statistics(builder.paths["logs"])
    assert stats["epoch"] == ["0", "1"]
    assert all(float(x) > 0 for x in stats["meta_tasks_per_sec"])
    test_stats = load_statistics(builder.paths["logs"], "test_summary.csv")
    assert "test_accuracy_mean" in test_stats
    assert os.path.isfile(os.path.join(builder.paths["base"],
                                       "config.json"))


def test_regression_experiment_ensemble_reports_mse(tmp_path):
    """The test protocol must score a regression workload by the MSE of
    the ensemble-averaged predictions: the classification softmax/argmax
    vote over a 1-unit head would report accuracy 1.0 unconditionally
    (found driving the sinusoid config end-to-end)."""
    cfg = _cfg(tmp_path, dataset_name="sinusoid_synthetic",
               backbone="mlp", task_type="regression",
               image_height=1, image_width=1, image_channels=1,
               num_classes_per_set=1, num_samples_per_class=5,
               num_target_samples=5, cnn_num_filters=16,
               use_multi_step_loss_optimization=False,
               transfer_images_uint8=False)
    result = ExperimentBuilder(cfg).run_experiment()
    assert result["num_models"] == 2
    # −MSE, the epoch loop's "accuracy" convention — strictly negative on
    # noise-fit sinusoids, never the degenerate argmax 1.0.
    assert result["test_accuracy_mean"] < 0.0
    assert result["test_mse_mean"] == pytest.approx(
        -result["test_accuracy_mean"])
    assert np.isfinite(result["test_mse_mean"])
    test_stats = load_statistics(
        ExperimentBuilder(cfg).paths["logs"], "test_summary.csv")
    assert "test_mse_mean" in test_stats


def test_full_experiment_from_disk_dataset(tmp_path):
    """The real-data user's first path: a reference-layout on-disk PNG
    tree (datasets/<name>/{train,val,test}/<class>/*.png) must drive the
    FULL loop — train epochs, val sweeps, checkpointing, ensemble test —
    through DiskImageSource, not the synthetic fallback."""
    from helpers import make_png_split_tree
    from howtotrainyourmamlpytorch_tpu.data.sources import DiskImageSource

    rng = np.random.default_rng(7)
    data_root = tmp_path / "datasets"
    make_png_split_tree(data_root / "pngset",
                        {"train": 6, "val": 4, "test": 4}, rng,
                        size=(10, 10))

    cfg = _cfg(tmp_path / "exp", dataset_name="pngset",
               dataset_path=str(data_root), total_iter_per_epoch=3,
               num_evaluation_tasks=4, batch_size=2)
    builder = ExperimentBuilder(cfg)
    # No synthetic fallback: every split must resolve to the disk tree.
    for split in ("train", "val", "test"):
        assert isinstance(builder.data.sampler(split).source,
                          DiskImageSource), split
    result = builder.run_experiment()
    assert result["num_models"] == 2
    assert 0.0 <= result["test_accuracy_mean"] <= 1.0
    stats = load_statistics(builder.paths["logs"])
    assert stats["epoch"] == ["0", "1"]


@pytest.mark.slow  # full run + resumed run (~30s), 1-core box
def test_resume_matches_uninterrupted(tmp_path):
    """Checkpoint/resume determinism: pause after epoch 0, resume, and the
    final params must match a straight-through run exactly (the data
    stream is a pure function of the iteration index)."""
    cfg_a = _cfg(tmp_path / "a")
    builder_a = ExperimentBuilder(cfg_a)
    builder_a.run_experiment()

    cfg_b1 = _cfg(tmp_path / "b", total_epochs_before_pause=1,
                  continue_from_epoch="latest")
    ExperimentBuilder(cfg_b1).run_experiment()
    cfg_b2 = _cfg(tmp_path / "b", continue_from_epoch="latest")
    builder_b = ExperimentBuilder(cfg_b2)
    builder_b.run_experiment()

    import jax
    for a, b in zip(jax.tree.leaves(builder_a.state.params),
                    jax.tree.leaves(builder_b.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # builds + tests a run (~20s), 1-core box
def test_evaluate_on_test_set_only(tmp_path):
    cfg = _cfg(tmp_path)
    ExperimentBuilder(cfg).run_experiment()
    cfg2 = _cfg(tmp_path, evaluate_on_test_set_only=True,
                continue_from_epoch="latest")
    result = ExperimentBuilder(cfg2).run_experiment()
    assert result["num_models"] == 2


def test_cli_get_args_json_and_overrides(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"num_classes_per_set": 7, "batch_size": 3,
                             "gpu_to_use": 0}))
    cfg = train_maml_system.get_args(
        ["--name_of_args_json_file", str(p),
         "--batch_size", "9", "--experiment_name=cli_test",
         "--second_order", "false"])
    assert cfg.num_classes_per_set == 7   # from JSON
    assert cfg.batch_size == 9            # CLI overrides JSON
    assert cfg.experiment_name == "cli_test"
    assert cfg.second_order is False


def test_cli_rejects_unknown_field():
    with pytest.raises(SystemExit):
        train_maml_system.get_args(["--not_a_field", "3"])


def test_cli_type_coercion():
    cfg = train_maml_system.get_args(["--second_order", "False",
                                      "--continue_from_epoch", "latest",
                                      "--batch_size", "12"])
    assert cfg.second_order is False     # python-style bool accepted
    assert cfg.continue_from_epoch == "latest"
    assert cfg.batch_size == 12
    with pytest.raises(SystemExit):      # not smuggled in as a string
        train_maml_system.get_args(["--second_order", "Flase"])
    with pytest.raises(SystemExit):
        train_maml_system.get_args(["--batch_size", "many"])


@pytest.mark.slow  # rewind retrain (~25s), 1-core box
def test_resume_from_specific_epoch_retrains(tmp_path):
    """continue_from_epoch=<int> must rewind and retrain, not skip to the
    test protocol with the global latest iteration."""
    cfg = _cfg(tmp_path)
    ExperimentBuilder(cfg).run_experiment()          # trains epochs 0,1
    cfg2 = _cfg(tmp_path, continue_from_epoch=0)
    builder = ExperimentBuilder(cfg2)
    assert builder.current_iter == cfg.total_iter_per_epoch  # epoch 0 end
    result = builder.run_experiment()                # retrains epoch 1
    assert result["num_models"] == 2


@pytest.mark.slow  # run + damaged-resume (~20s), 1-core box
def test_corrupt_latest_falls_back_to_epoch_checkpoint(tmp_path):
    """External damage to train_model_latest.ckpt (our own writes are
    atomic) must not kill the run: resume falls back to the newest
    readable epoch checkpoint and retrains from its boundary."""
    import os
    import warnings

    cfg = _cfg(tmp_path)
    ExperimentBuilder(cfg).run_experiment()          # epochs 0,1 complete
    latest = os.path.join(tmp_path, "smoke", "saved_models",
                          "train_model_latest.ckpt")

    # Damage mode 1: the file is REPLACED (unlink + new inode — e.g. a
    # partial rsync). The hard-linked epoch-1 checkpoint is untouched, so
    # fallback resumes from epoch 1's boundary — and the damaged 'latest'
    # is QUARANTINED (renamed *.corrupt) so it is never re-attempted.
    os.remove(latest)
    with open(latest, "wb") as f:
        f.write(b"truncated garbage")
    cfg2 = _cfg(tmp_path, continue_from_epoch="latest", total_epochs=2)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        builder = ExperimentBuilder(cfg2)
    assert any("unreadable" in str(r.message) for r in rec)
    assert builder.current_iter == 2 * cfg.total_iter_per_epoch
    assert not os.path.exists(latest)            # quarantined...
    assert os.path.exists(latest + ".corrupt")   # ...not deleted

    # Damage mode 1b: 'latest' missing outright (here: the quarantine
    # above; equivalently a partial copy that missed it). Must still fall
    # back — the pre-fix behavior silently restarted from scratch because
    # the has_checkpoint('latest') guard hit first.
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        builder = ExperimentBuilder(cfg2)
    assert any("unreadable" in str(r.message) for r in rec)
    assert builder.current_iter == 2 * cfg.total_iter_per_epoch

    # Damage mode 2: in-place bit-rot. 'latest' is a hard link to the
    # newest epoch checkpoint (one write per save), so the shared inode
    # takes out BOTH and fallback must reach back to epoch 0 —
    # quarantining latest AND epoch 1 (whose bookkeeping is dropped so
    # the ensemble protocol can never load the rotten file). (Mode 1b
    # left no 'latest'; recreate the production hard-link layout first.)
    models_dir = os.path.join(tmp_path, "smoke", "saved_models")
    os.link(os.path.join(models_dir, "train_model_1.ckpt"), latest)
    with open(latest, "r+b") as f:
        f.write(b"bit rot")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        builder = ExperimentBuilder(cfg2)
    assert any("unreadable" in str(r.message) for r in rec)
    assert builder.current_iter == 1 * cfg.total_iter_per_epoch
    assert not os.path.exists(os.path.join(models_dir,
                                           "train_model_1.ckpt"))
    assert "1" not in builder.ckpt.meta["iter_at_epoch"]

    # Damage mode 3: partial copy that dropped state.json but kept a
    # READABLE latest. Loading it would silently restart the iteration
    # counter and schedules at 0 under trained weights — must raise.
    os.link(os.path.join(models_dir, "train_model_0.ckpt"), latest)
    os.remove(os.path.join(models_dir, "state.json"))
    with pytest.raises(RuntimeError, match="state.json missing"):
        ExperimentBuilder(_cfg(tmp_path, continue_from_epoch="latest"))

    # Damage mode 3b: no state.json and no latest, epoch files only. The
    # iteration they represent is unknowable, so this must fail loudly
    # (naming the unbookkept files) — not silently restart a run whose
    # checkpoints are sitting right there.
    os.remove(latest)
    with pytest.raises(RuntimeError, match="no iteration bookkeeping"):
        ExperimentBuilder(_cfg(tmp_path, continue_from_epoch="latest"))

    # With EVERY checkpoint damaged too, resuming must also fail loudly.
    for name in os.listdir(models_dir):
        if name.endswith(".ckpt"):
            with open(os.path.join(models_dir, name), "wb") as f:
                f.write(b"x")
    with pytest.raises(RuntimeError, match="no readable checkpoint"):
        ExperimentBuilder(_cfg(tmp_path, continue_from_epoch="latest"))


@pytest.mark.slow  # preempt + exact-resume system test (~55s), 1-core box
def test_preemption_saves_latest_and_resume_is_exact(tmp_path):
    """Save-on-signal: preempt mid-epoch, resume from 'latest', and the
    final params must equal an uninterrupted run bit-for-bit (same
    deterministic episode stream, same iteration count)."""
    import jax

    cfg_a = _cfg(tmp_path / "a")
    builder_a = ExperimentBuilder(cfg_a)
    builder_a.run_experiment()

    cfg_b = _cfg(tmp_path / "b")
    builder_b = ExperimentBuilder(cfg_b)
    # Preempt after 3 of 5 iterations of epoch 0: flip the flag via the
    # same path the SIGTERM handler uses, from a step-counting hook.
    orig = builder_b.plan.train_steps
    count = {"n": 0}

    class CountingSteps(dict):
        def __getitem__(self, key):
            fn = orig[key]
            def wrapped(*a, **k):
                count["n"] += 1
                if count["n"] == 3:
                    builder_b._preempted = True
                return fn(*a, **k)
            return wrapped

    builder_b.plan = builder_b.plan._replace(train_steps=CountingSteps())
    result = builder_b.run_experiment()
    assert result == {"preempted_at_iter": 3}
    assert builder_b.ckpt.has_checkpoint("latest")

    # Resume: must do the REMAINDER of epoch 0 (2 iters), then epoch 1.
    cfg_b2 = _cfg(tmp_path / "b", continue_from_epoch="latest")
    builder_b2 = ExperimentBuilder(cfg_b2)
    assert builder_b2.current_iter == 3
    builder_b2.run_experiment()

    for a, b in zip(jax.tree.leaves(builder_a.state.params),
                    jax.tree.leaves(builder_b2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # The mid-epoch snapshot must not have entered the ensemble set.
    stats = load_statistics(builder_b2.paths["logs"])
    assert stats["epoch"] == ["0", "1"]


@pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.37: the persistent compilation cache writes no "
           "entries on the CPU backend (ROADMAP.md PR 1 note); "
           "passes again once the installed jax supports CPU cache "
           "persistence")
def test_compilation_cache_dir_populated(tmp_path):
    """compilation_cache_dir wires up JAX's persistent executable cache so
    restarts skip recompilation."""
    import json
    import os

    import train_maml_system

    import jax

    cache = tmp_path / "xla_cache"
    cfg = _cfg(tmp_path, total_epochs=1, total_iter_per_epoch=2,
               num_evaluation_tasks=4)
    cfg_path = tmp_path / "cfg.json"
    payload = {k: v for k, v in cfg.to_dict().items() if v is not None}
    cfg_path.write_text(json.dumps(payload))
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        train_maml_system.main([
            "--name_of_args_json_file", str(cfg_path),
            "--compilation_cache_dir", str(cache)])
        assert cache.is_dir() and os.listdir(cache), (
            "no compiled executables were persisted")
    finally:
        # main() mutates global jax.config; don't leak a tmp cache dir
        # into every later test in this process.
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)


@pytest.mark.slow  # full tiny run (~25s), 1-core box
def test_tensorboard_scalars_written(tmp_path):
    """use_tensorboard adds event files without disturbing the CSV path."""
    pytest.importorskip("tensorboardX")
    cfg = _cfg(tmp_path, use_tensorboard=True, total_epochs=1,
               total_iter_per_epoch=2, num_evaluation_tasks=4)
    builder = ExperimentBuilder(cfg)
    builder.run_experiment()
    tb_dir = os.path.join(builder.paths["logs"], "tensorboard")
    assert os.path.isdir(tb_dir) and os.listdir(tb_dir)
    stats = load_statistics(builder.paths["logs"])  # CSV still written
    assert stats["epoch"] == ["0"]


@pytest.mark.slow  # run + damaged-dir resume (~30s), 1-core box
def test_state_json_only_remnant_aborts_loudly(tmp_path):
    """Damage mode 4 (ADVICE r1): every .ckpt file removed but state.json
    survives. Pre-fix this was treated as a fresh run while the manager
    kept stale top-epoch bookkeeping (the final test protocol would later
    die on nonexistent checkpoint files); it must abort loudly instead."""
    import os

    cfg = _cfg(tmp_path)
    ExperimentBuilder(cfg).run_experiment()
    models_dir = os.path.join(tmp_path, "smoke", "saved_models")
    for name in os.listdir(models_dir):
        if name.endswith(".ckpt"):
            os.remove(os.path.join(models_dir, name))
    assert os.path.isfile(os.path.join(models_dir, "state.json"))
    with pytest.raises(RuntimeError, match="no readable checkpoint"):
        ExperimentBuilder(_cfg(tmp_path, continue_from_epoch="latest"))


@pytest.mark.slow  # two full runs (~35s), 1-core box
def test_checkpoint_fingerprint_changes_with_content(tmp_path):
    """Cheap content fingerprint used for cross-host resume agreement."""
    import os
    from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
        CheckpointManager)

    cfg = _cfg(tmp_path)
    builder = ExperimentBuilder(cfg)
    builder.run_experiment()
    mgr = CheckpointManager(os.path.join(tmp_path, "smoke", "saved_models"))
    fp = mgr.fingerprint("latest")
    assert fp >= 0
    assert fp == mgr.fingerprint("latest")          # stable
    path = os.path.join(tmp_path, "smoke", "saved_models",
                        "train_model_latest.ckpt")
    with open(path, "r+b") as f:
        f.write(b"\xff\xff\xff\xff")                # different head bytes
    assert mgr.fingerprint("latest") != fp
    assert mgr.fingerprint("nonexistent") == -1


def test_cli_tuple_fields_accept_multi_token_and_comma_forms():
    """Tuple-typed config fields work in all three spellings:
    '--mesh_shape 2 4', '--mesh_shape 2,4', '--mesh_shape [2,4]'."""
    for argv in (["--mesh_shape", "2", "4"],
                 ["--mesh_shape", "2,4"],
                 ["--mesh_shape", "[2,4]"],
                 ["--mesh_shape=[2, 4]"]):
        cfg = train_maml_system.get_args(argv + ["--batch_size", "8"])
        assert cfg.mesh_shape == (2, 4), argv
        assert cfg.batch_size == 8
    cfg = train_maml_system.get_args(
        ["--train_val_test_split", "0.6", "0.2", "0.2",
         "--indexes_of_folders_indicating_class", "-3", "-2"])
    assert cfg.train_val_test_split == (0.6, 0.2, 0.2)
    assert cfg.indexes_of_folders_indicating_class == (-3, -2)


def test_cli_flag_followed_by_flag_errors():
    """'--mesh_shape --quick' must error 'needs a value', not silently
    coerce to an empty tuple (ADVICE r2 low)."""
    with pytest.raises(SystemExit):
        train_maml_system.get_args(["--mesh_shape", "--batch_size", "4"])


def test_cli_multi_token_value_only_for_tuple_fields():
    """Multi-token values are the tuple-field convenience form; for scalar
    and string fields they are a user error, not a silent comma-join."""
    cfg = train_maml_system.get_args(["--mesh_shape", "2", "4"])
    assert cfg.mesh_shape == (2, 4)
    with pytest.raises(SystemExit):
        train_maml_system.get_args(["--experiment_name", "two", "words"])
    with pytest.raises(SystemExit):
        train_maml_system.get_args(["--batch_size", "4", "8"])


@pytest.mark.slow  # two full runs across phase boundaries (~65s), 1-core box
def test_precompile_phases_is_bit_identical(tmp_path):
    """The background phase warmup must not change training: it runs on
    throwaway state copies, so a warmed run's parameters match an
    unwarmed run bit-for-bit (and the warmup covers the DA boundary the
    schedule crosses)."""
    import jax
    cfg_a = _cfg(tmp_path / "a", first_order_to_second_order_epoch=0,
                 second_order=True)
    builder_a = ExperimentBuilder(cfg_a)
    builder_a.run_experiment()

    cfg_b = _cfg(tmp_path / "b", first_order_to_second_order_epoch=0,
                 second_order=True, precompile_phases=True)
    builder_b = ExperimentBuilder(cfg_b)
    # Three phase keys visited: (False, True) epoch 0, (True, False)
    # epoch 1 — warmup list holds everything after the first.
    assert len(builder_b._phase_order()) == 2
    builder_b.run_experiment()

    for a, b in zip(jax.tree.leaves(builder_a.state.params),
                    jax.tree.leaves(builder_b.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # train + parity protocol (~40s), 1-core box
def test_parity_runner_smoke(tmp_path):
    """scripts/parity_run.sh end-to-end on a synthetic source (the CI
    stand-in for the real-data parity run): the wrapper must drive the
    shipped DA config through train -> 600-episode-protocol-shaped test ->
    parity_report, and the report must classify a custom/synthetic
    geometry as no-baseline (exit 2) while printing the measured
    accuracy."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # Pin the subprocess to CPU: the ambient sitecustomize overrides
    # JAX_PLATFORMS, and a TPU-tunnel outage would otherwise hang the
    # smoke in backend init (observed 2026-07-31).
    env["MAML_JAX_PLATFORM"] = "cpu"
    proc = subprocess.run(
        ["bash", os.path.join(repo, "scripts", "parity_run.sh"),
         str(tmp_path / "datasets"), str(tmp_path / "out"),
         # scale the schedule and tensors down for CI; the protocol shape
         # (top-k ensemble over the fixed test stream) stays live
         "--dataset_name", "synthetic_mini_imagenet",
         "--image_height", "28", "--image_width", "28",
         "--cnn_num_filters", "8", "--batch_size", "4",
         # The shipped config's task_microbatches=8 cannot divide the
         # scaled batch; mb=4 keeps the one-task-per-chunk geometry.
         "--task_microbatches", "4",
         "--num_samples_per_class", "1", "--num_target_samples", "1",
         "--total_epochs", "2", "--total_iter_per_epoch", "4",
         "--num_evaluation_tasks", "8", "--max_models_to_save", "2",
         "--number_of_training_steps_per_iter", "2",
         "--number_of_evaluation_steps_per_iter", "2",
         "--second_order", "false", "--precompile_phases", "false"],
        env=env, capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "test accuracy:" in proc.stdout
    assert "nothing to compare" in proc.stdout
    assert os.path.isfile(tmp_path / "out" / "parity_mini_imagenet_5w5s"
                          / "logs" / "test_summary.csv")


def test_parity_report_against_baseline(tmp_path):
    """parity_report's verdict logic on synthetic CSVs: PARITY (exit 0)
    when mean >= the BASELINE.md row, GAP (exit 3) below it."""
    import sys
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    try:
        import parity_report
    finally:
        sys.path.pop(0)

    base = tmp_path / "exp"
    logs = base / "logs"
    os.makedirs(logs)
    with open(base / "config.json", "w") as f:
        json.dump({"dataset_name": "mini_imagenet_full_size",
                   "num_classes_per_set": 5,
                   "num_samples_per_class": 5}, f)
    with open(logs / "test_summary.csv", "w") as f:
        f.write("test_accuracy_mean,test_accuracy_std,num_models,"
                "num_episodes\n0.6900,0.0040,5,600\n")
    assert parity_report.main([str(logs / "test_summary.csv")]) == 0
    with open(logs / "test_summary.csv", "w") as f:
        f.write("test_accuracy_mean,test_accuracy_std,num_models,"
                "num_episodes\n0.6500,0.0040,5,600\n")
    assert parity_report.main([str(logs / "test_summary.csv"),
                               "--json"]) == 3

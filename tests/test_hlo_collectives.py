"""Collective-inventory audit of the compiled sharded steps (VERDICT r2 #4).

The pod-scaling story rests on a structural claim (SURVEY §5, README): per-
task adaptation is device-local, and the ONLY cross-device traffic is one
fused grad/metric reduction per train step plus one tiny result gather per
eval step. Round 2 proved the claim is fragile — GSPMD mis-partitioned the
task-vmapped grouped convs and silently all-gathered episode activations
and adapted kernels inside the inner scan (the discovery that motivated the
shard_map formulation in parallel/mesh.py). This test walks the OPTIMIZED
HLO of every sharded executable on the virtual 8-device mesh and fails
loudly on any regression:

  * train steps: psum-family ops only (all-reduce), at least one (a missing
    grad pmean would train per-device-divergent models silently, since
    shard_map is compiled with check_vma=False), none inside any loop body
    (the inner-adaptation scan and the microbatch accumulation scan must
    stay collective-free);
  * eval steps: all-gathers of the per-task results only, each small
    (metrics + logits — never episode- or parameter-sized), none inside
    loop bodies, no reductions at all;
  * nowhere: all-to-all, collective-permute, reduce-scatter.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel.mesh import (make_mesh,
                                                         make_sharded_steps)

_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter", "all-to-all",
                "collective-permute")
_DTYPE_BYTES = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4, "u32": 4,
                "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1}


def _shape_bytes(shape_str: str) -> int:
    """Total bytes of every dtype[dims] literal in an HLO shape string
    (handles variadic-collective tuple shapes)."""
    total = 0
    for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _parse_collectives(hlo_text: str):
    """-> list of (computation, op, bytes); plus the set of computations
    transitively reachable from any while-loop body/condition."""
    comps = {}  # name -> list of instruction lines
    cur = None
    for line in hlo_text.splitlines():
        if line and not line[0].isspace():  # computation header or '}'
            m = re.match(r"(?:ENTRY\s+)?%?([\w.-]+)\s*\(", line)
            cur = m.group(1) if m else None
        elif cur is not None:
            comps.setdefault(cur, []).append(line)

    refs = {}       # comp -> referenced comps (calls, loop bodies, branches)
    loop_roots = set()
    for name, lines in comps.items():
        out = set()
        for line in lines:
            for kw in ("body", "condition", "to_apply", "called_computations"):
                for r in re.findall(rf"{kw}=\{{?%?([\w.-]+)", line):
                    out.add(r)
            for r in re.findall(r"branch_computations=\{([^}]*)\}", line):
                out.update(x.strip().lstrip("%") for x in r.split(","))
            for kw in ("body", "condition"):
                for r in re.findall(rf"{kw}=%?([\w.-]+)", line):
                    loop_roots.add(r)
        refs[name] = out

    in_loop = set()
    frontier = set(loop_roots)
    while frontier:
        nxt = set()
        for c in frontier:
            if c in in_loop:
                continue
            in_loop.add(c)
            nxt |= refs.get(c, set())
        frontier = nxt - in_loop

    found = []
    for name, lines in comps.items():
        for line in lines:
            m = re.search(
                r"=\s*(\([^)]*\)|[\w]+\[[^\]]*\](?:\{[^}]*\})?)\s*"
                r"(" + "|".join(_COLLECTIVES) + r")\b", line)
            if m:
                found.append((name, m.group(2), _shape_bytes(m.group(1))))
    return found, in_loop


import functools


@functools.lru_cache(maxsize=None)   # compiles are minutes on this box;
def _audit(cfg: MAMLConfig):         # each config audits once per session
    init, apply_fn = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    mesh = make_mesh(cfg, jax.devices()[:8])
    plan = make_sharded_steps(cfg, apply_fn, mesh)
    data = MetaLearningDataLoader(cfg, mesh)
    batch = next(iter(data.get_train_batches(0, 1)))

    results = {}
    # Both derivative orders: the first-order executable runs every epoch
    # before the DA boundary and has its own grad path (stop_gradient),
    # so a collective regression there must fail the audit too. Each
    # executable is audited separately (the >= 1-reduction check must
    # hold per phase, not merely in aggregate).
    results["train"] = {}
    for key in [(cfg.second_order, cfg.use_multi_step_loss_optimization),
                (False, False)]:
        txt = (plan.train_steps[key]
               .lower(state, batch, jnp.float32(0)).compile().as_text())
        results["train"][key] = _parse_collectives(txt)
    ebatch = next(iter(data.get_val_batches()))
    txt = plan.eval_step.lower(state, ebatch).compile().as_text()
    results["eval"] = _parse_collectives(txt)
    return results


_VGG_CFG = MAMLConfig(
    dataset_name="synthetic_audit", image_height=28, image_width=28,
    image_channels=3, num_classes_per_set=3, num_samples_per_class=2,
    num_target_samples=2, batch_size=8, cnn_num_filters=8, num_stages=2,
    number_of_training_steps_per_iter=3,
    number_of_evaluation_steps_per_iter=3, mesh_shape=(2, 4),
    second_order=True, use_multi_step_loss_optimization=True,
    num_evaluation_tasks=16)

_RESNET_CFG = _VGG_CFG.replace(
    backbone="resnet12", num_stages=4, cnn_num_filters=4, batch_size=16,
    task_microbatches=2, use_multi_step_loss_optimization=False,
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2)

# Episode tensors in these configs are >= batch*images*H*W*C bytes; the
# legitimate eval gather moves per-task scalars + (tasks, N*T, N) logits.
# 1 MiB cleanly separates the two for every shipped geometry.
_EVAL_GATHER_MAX_BYTES = 1 << 20


@pytest.mark.parametrize(
    "cfg",
    [pytest.param(_VGG_CFG, marks=pytest.mark.core),
     # ResNet-12 audit compiles the deep backbone 3x (~2.5 min on
     # the 1-core box): slow profile (full CI keeps it).
     pytest.param(_RESNET_CFG, marks=pytest.mark.slow)],
    ids=["vgg_msl", "resnet12_micro"])
def test_collective_inventory(cfg):
    results = _audit(cfg)

    for key, (t_found, t_loop) in results["train"].items():
        assert all(op == "all-reduce" for _, op, _ in t_found), (
            f"train step {key} must use psum-family collectives only, "
            f"found: {t_found}")
        assert t_found, (
            f"train step {key} compiled with NO cross-device reduction — "
            f"the grad pmean is missing and each device would train its "
            f"own model")
        in_loop = [f for f in t_found if f[0] in t_loop]
        assert not in_loop, (
            f"train step {key}: collectives inside a loop body (inner "
            f"scan / microbatch accumulation must be device-local): "
            f"{in_loop}")

    e_found, e_loop = results["eval"]
    assert all(op == "all-gather" for _, op, _ in e_found), (
        f"eval step: result gather only, found: {e_found}")
    big = [f for f in e_found if f[2] > _EVAL_GATHER_MAX_BYTES]
    assert not big, (
        f"eval all-gather larger than any per-task result can be "
        f"(episode/parameter-sized gather => GSPMD-style fallback): {big}")
    assert not [f for f in e_found if f[0] in e_loop], (
        "collectives inside an eval loop body")


@pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.37 / XLA CPU: the grad pmean lowers to per-leaf "
           "all-reduces that the combiner does not re-fuse (fails "
           "with seed sources too — ROADMAP.md PR 1 note); the "
           "inventory/placement audits above still gate collectives")
def test_train_allreduce_count_is_bounded():
    """The pmean must stay FUSED (XLA's combiner keeps the reduction count
    independent of parameter-tree size); a per-leaf all-reduce explosion
    is a perf regression even when each op is individually legal."""
    for key, (t_found, _) in _audit(_VGG_CFG)["train"].items():
        assert len(t_found) <= 8, (
            f"{len(t_found)} all-reduces in train step {key} — the grad "
            f"reduction has unfused into per-leaf collectives: {t_found}")

"""Numerical parity of the functional layers against a torch CPU oracle.

The oracle re-implements the reference's layer math via torch.nn.functional
(SURVEY.md §4: parity tests against a tiny CPU oracle, not copied code).
All comparisons run in float32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from howtotrainyourmamlpytorch_tpu.models import layers

F32 = jnp.float32


def _rand(key, shape):
    return jax.random.normal(key, shape, F32)


def test_conv2d_matches_torch():
    key = jax.random.PRNGKey(0)
    x = _rand(key, (2, 9, 9, 3))
    params = layers.conv2d_init(jax.random.PRNGKey(1), 3, 8)
    y = layers.conv2d_apply(params, x, compute_dtype=F32)

    xt = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)  # NHWC->NCHW
    wt = torch.tensor(np.asarray(params["w"])).permute(3, 2, 0, 1)  # HWIO->OIHW
    bt = torch.tensor(np.asarray(params["b"]))
    yt = F.conv2d(xt, wt, bt, stride=1, padding=1)  # SAME for 3x3
    np.testing.assert_allclose(np.asarray(y),
                               yt.permute(0, 2, 3, 1).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_conv2d_stride2_valid_matches_torch():
    x = _rand(jax.random.PRNGKey(2), (2, 10, 10, 4))
    params = layers.conv2d_init(jax.random.PRNGKey(3), 4, 6)
    y = layers.conv2d_apply(params, x, stride=2, padding="VALID",
                            compute_dtype=F32)
    xt = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
    wt = torch.tensor(np.asarray(params["w"])).permute(3, 2, 0, 1)
    bt = torch.tensor(np.asarray(params["b"]))
    yt = F.conv2d(xt, wt, bt, stride=2, padding=0)
    np.testing.assert_allclose(np.asarray(y),
                               yt.permute(0, 2, 3, 1).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_linear_matches_torch():
    x = _rand(jax.random.PRNGKey(4), (5, 11))
    params = layers.linear_init(jax.random.PRNGKey(5), 11, 7)
    y = layers.linear_apply(params, x, compute_dtype=F32)
    yt = F.linear(torch.tensor(np.asarray(x)),
                  torch.tensor(np.asarray(params["w"])).T,
                  torch.tensor(np.asarray(params["b"])))
    np.testing.assert_allclose(np.asarray(y), yt.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_max_pool_matches_torch():
    x = _rand(jax.random.PRNGKey(6), (2, 7, 7, 3))  # odd size: floor mode
    y = layers.max_pool2d(x)
    xt = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
    yt = F.max_pool2d(xt, 2).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), yt.numpy(), rtol=1e-6,
                               atol=1e-6)


def test_max_pool_rejects_empty_output():
    # A 1x1 input pooled 2x2/VALID would be spatially empty; downstream
    # reductions would then turn it into NaN (or, worse, a flatten into
    # an all-zero feature vector with a finite loss). Torch raises; so
    # do we.
    x = _rand(jax.random.PRNGKey(7), (2, 1, 1, 3))
    with pytest.raises(ValueError, match="too small"):
        layers.max_pool2d(x)


def test_batch_norm_matches_torch_training_mode():
    """Normalization = batch stats; running stats updated with torch's
    momentum convention (biased var to normalize, unbiased in the running
    update) — the reference always calls F.batch_norm(training=True)."""
    num_steps, feats = 4, 5
    params, state = layers.batch_norm_init(feats, num_steps)
    # Distinct initial stats so the per-step indexing is observable.
    state = {"mean": state["mean"] + jnp.arange(num_steps)[:, None] * 0.5,
             "var": state["var"] * (1 + jnp.arange(num_steps)[:, None])}
    x = _rand(jax.random.PRNGKey(7), (6, 3, 3, feats))
    step = 2
    y, new_state = layers.batch_norm_apply(params, state, x,
                                           jnp.int32(step), training=True)

    xt = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2).contiguous()
    rm = torch.tensor(np.asarray(state["mean"][step]))
    rv = torch.tensor(np.asarray(state["var"][step]))
    yt = F.batch_norm(xt, rm, rv,
                      torch.tensor(np.asarray(params["gamma"][step])),
                      torch.tensor(np.asarray(params["beta"][step])),
                      training=True, momentum=0.1, eps=1e-5)
    np.testing.assert_allclose(np.asarray(y),
                               yt.permute(0, 2, 3, 1).numpy(),
                               rtol=1e-4, atol=1e-4)
    # Running-stat update matches torch's in-place update, at row `step` only.
    np.testing.assert_allclose(np.asarray(new_state["mean"][step]),
                               rm.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["var"][step]),
                               rv.numpy(), rtol=1e-4, atol=1e-4)
    for other in (0, 1, 3):
        np.testing.assert_array_equal(np.asarray(new_state["mean"][other]),
                                      np.asarray(state["mean"][other]))


def test_batch_norm_step_index_clipped():
    params, state = layers.batch_norm_init(3, 2)
    x = _rand(jax.random.PRNGKey(8), (4, 2, 2, 3))
    y_hi, _ = layers.batch_norm_apply(params, state, x, jnp.int32(99),
                                      training=True)
    y_last, _ = layers.batch_norm_apply(params, state, x, jnp.int32(1),
                                        training=True)
    np.testing.assert_array_equal(np.asarray(y_hi), np.asarray(y_last))


def test_layer_norm_normalizes():
    params, state = layers.layer_norm_init(4)
    x = _rand(jax.random.PRNGKey(9), (3, 5, 5, 4))
    y, _ = layers.layer_norm_apply(params, state, x, jnp.int32(0),
                                   training=True)
    flat = np.asarray(y).reshape(3, -1)
    np.testing.assert_allclose(flat.mean(axis=1), 0.0, atol=1e-5)
    np.testing.assert_allclose(flat.std(axis=1), 1.0, atol=1e-3)


def test_xavier_uniform_bounds():
    params = layers.conv2d_init(jax.random.PRNGKey(10), 16, 32)
    w = np.asarray(params["w"])
    limit = np.sqrt(6.0 / (16 * 9 + 32 * 9))
    assert np.all(np.abs(w) <= limit)
    assert np.abs(w).max() > 0.8 * limit  # actually fills the range
    assert np.all(np.asarray(params["b"]) == 0)


def test_batch_norm_fast_math_close_to_f32_path():
    """fast_math folds stats into scale/shift applied in x.dtype; on f32
    inputs it must agree with the reference path to float tolerance, and
    running-stat updates must be identical math."""
    params, state = layers.batch_norm_init(4, 3)
    x = _rand(jax.random.PRNGKey(3), (8, 5, 5, 4)) * 3.0 + 1.5
    y_ref, st_ref = layers.batch_norm_apply(params, state, x, jnp.int32(1),
                                            training=True)
    y_fast, st_fast = layers.batch_norm_apply(params, state, x, jnp.int32(1),
                                              training=True, fast_math=True)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(st_fast["mean"]),
                               np.asarray(st_ref["mean"]), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_fast["var"]),
                               np.asarray(st_ref["var"]), rtol=1e-4,
                               atol=1e-4)


def test_batch_norm_fast_math_grads_close():
    """Second-order-relevant: gradients through the fast_math path agree
    with the f32 path (both are plain jnp ops, differentiable twice)."""
    params, state = layers.batch_norm_init(4, 2)
    x = _rand(jax.random.PRNGKey(4), (6, 3, 3, 4))

    def loss(x, fast):
        y, _ = layers.batch_norm_apply(params, state, x, jnp.int32(0),
                                       training=True, fast_math=fast)
        return jnp.sum(y ** 2)

    g_ref = jax.grad(lambda x: loss(x, False))(x)
    g_fast = jax.grad(lambda x: loss(x, True))(x)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


def test_layer_norm_full_shape_affine_matches_torch():
    """The LN affine covers the full (H, W, C) feature shape (reference
    MetaLayerNormLayer: elementwise nn.LayerNorm((C, H, W)) affine) and
    matches torch's layer_norm with elementwise weights."""
    import torch
    import torch.nn.functional as F

    rng = np.random.default_rng(0)
    params, state = layers.layer_norm_init((5, 6, 4))
    assert params["gamma"].shape == (1, 5, 6, 4)
    params = {
        "gamma": jnp.asarray(rng.normal(1.0, 0.2, (1, 5, 6, 4)),
                             jnp.float32),
        "beta": jnp.asarray(rng.normal(0.0, 0.2, (1, 5, 6, 4)),
                            jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(3, 5, 6, 4)), jnp.float32)
    y, _ = layers.layer_norm_apply(params, state, x, jnp.int32(0),
                                   training=True)
    xt = torch.tensor(np.asarray(x).transpose(0, 3, 1, 2))
    w = torch.tensor(np.asarray(params["gamma"][0]).transpose(2, 0, 1))
    b = torch.tensor(np.asarray(params["beta"][0]).transpose(2, 0, 1))
    want = F.layer_norm(xt, (4, 5, 6), weight=w, bias=b, eps=1e-5)
    np.testing.assert_allclose(
        np.asarray(y).transpose(0, 3, 1, 2), want.numpy(),
        rtol=1e-5, atol=1e-5)


def test_vgg_layer_norm_params_cover_stage_shapes():
    """Each VGG stage's LN affine matches that stage's post-conv feature
    shape (28x28 grayscale, SAME convs, 2x2 pools: 28, 14, 7, 3)."""
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.models import make_model

    cfg = MAMLConfig(norm_layer="layer_norm", image_height=28,
                     image_width=28, image_channels=1, cnn_num_filters=6,
                     num_stages=4, compute_dtype="float32")
    init, apply = make_model(cfg)
    params, state = init(jax.random.PRNGKey(0))
    got = [params[f"norm{i}"]["gamma"].shape for i in range(4)]
    assert got == [(1, 28, 28, 6), (1, 14, 14, 6), (1, 7, 7, 6),
                   (1, 3, 3, 6)]
    # And the backbone still runs end to end.
    x = jnp.zeros((10, 28, 28, 1), jnp.float32)
    logits, _ = apply(params, state, x, jnp.int32(0), True)
    assert logits.shape == (10, cfg.num_classes_per_set)

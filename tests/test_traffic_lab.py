"""Traffic-lab tests (ISSUE 19): trace format + generators, open-loop
replay, continuous batching (GroupAssembler wiring), and the
weighted-canary traffic split.

Tier-1 keeps to pure/host-side units — the trace modules are loaded by
FILE PATH (they are jax-free by contract, proven by the booby-trap
subprocess test below), the batcher units run in-process, and the
weighted-rollout machine is driven against a fake membership snapshot
(the test_fleet.py idiom). The full three-leg replay proof lives in
scripts/traffic_replay.py, not here.
"""

import importlib.util
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.serve.batcher import (
    FewShotRequest, GroupAssembler, QueueFullError, RequestBatcher,
    pad_group)
from howtotrainyourmamlpytorch_tpu.serve.fleet import (
    FleetController, FleetRouter, ReplicaLease, assign_canary,
    canary_fraction)
from howtotrainyourmamlpytorch_tpu.serve.fleet import controller as fc
from howtotrainyourmamlpytorch_tpu.serve.fleet import router as fr
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOADLAB = os.path.join(REPO, "howtotrainyourmamlpytorch_tpu", "serve",
                       "loadlab")


def _load(name, filename):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(LOADLAB, filename))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace = _load("_tl_trace", "trace.py")
workloads = _load("_tl_workloads", "workloads.py")
replay = _load("_tl_replay", "replay.py")


# ---------------------------------------------------------------------------
# trace format
# ---------------------------------------------------------------------------

def _records(n=20):
    return [trace.trace_record(i * 0.5, i % 3, (4, 3),
                               deadline_ms=250.0 if i % 2 else None,
                               seed=i)
            for i in range(n)]


def test_trace_roundtrip_and_meta(tmp_path):
    path = str(tmp_path / "t.trace")
    recs = _records()
    n = trace.write_trace(path, recs, meta={"workload": "diurnal",
                                            "peak_rate": 12.5})
    assert n == os.path.getsize(path)
    header, out = trace.read_trace(path)
    assert out == recs
    assert header["records"] == len(recs)
    assert header["workload"] == "diurnal"
    assert header["peak_rate"] == 12.5


def test_trace_refuses_every_kind_of_damage(tmp_path):
    """The framing contract: a trace either replays exactly or refuses
    to replay at all — no silently-shortened replay flattering every
    latency number downstream."""
    path = str(tmp_path / "t.trace")
    trace.write_trace(path, _records())
    blob = open(path, "rb").read()
    # Bit flip in the payload -> CRC.
    flipped = bytearray(blob)
    flipped[-10] ^= 0x40
    with pytest.raises(ValueError, match="CRC"):
        trace.decode_trace(bytes(flipped))
    # Truncation -> framed length.
    with pytest.raises(ValueError, match="length"):
        trace.decode_trace(blob[:-7])
    # Foreign file -> magic.
    with pytest.raises(ValueError, match="magic"):
        trace.decode_trace(b"NOTATRACE" + blob)
    # Header/record-count mismatch survives reframing -> loud.
    head, recs = trace.decode_trace(blob)
    doctored = trace.encode_trace(recs[:-1])
    import json as _json
    lines = doctored[trace._HEAD.size + len(trace.TRACE_MAGIC):].decode(
        ).splitlines()
    hdr = _json.loads(lines[0])
    hdr["records"] = len(recs)  # lie
    payload = ("\n".join([_json.dumps(hdr, sort_keys=True)] + lines[1:])
               + "\n").encode()
    import zlib as _zlib
    reframed = (trace.TRACE_MAGIC
                + trace._HEAD.pack(len(payload),
                                   _zlib.crc32(payload) & 0xFFFFFFFF)
                + payload)
    with pytest.raises(ValueError, match="header says"):
        trace.decode_trace(reframed)


def test_trace_encode_rejects_unsorted_and_negative():
    recs = [trace.trace_record(1.0, 0, (4, 3)),
            trace.trace_record(0.5, 0, (4, 3))]
    with pytest.raises(ValueError, match="sorted"):
        trace.encode_trace(recs)
    with pytest.raises(ValueError, match=">= 0"):
        trace.trace_record(-0.1, 0, (4, 3))


def test_gen_diurnal_trace_is_deterministic_and_shaped():
    kw = dict(duration_s=60.0, base_rate=2.0, peak_rate=20.0,
              num_tenants=24, buckets=[(4, 3), (8, 6)],
              active_tenants=6, churn_every_s=5.0, seed=7)
    a = workloads.gen_diurnal_trace(**kw)
    assert a == workloads.gen_diurnal_trace(**kw)  # same seed, same trace
    assert a and all(a[i]["t"] <= a[i + 1]["t"] for i in range(len(a) - 1))
    # The diurnal shape: the middle third (around peak) offers several
    # times the rate of the edges (base:peak is 1:10).
    third = 60.0 / 3.0
    edge = sum(1 for r in a if r["t"] < third or r["t"] >= 2 * third)
    mid = sum(1 for r in a if third <= r["t"] < 2 * third)
    assert mid > edge
    # Every record's bucket matches the shared tenant->bucket rule, so
    # generators and tenant_pool agree by construction.
    for r in a:
        assert r["bucket"] == list(
            workloads.tenant_bucket(r["tenant"], kw["buckets"]))


def test_overlay_burst_merges_sorted_and_adds_rate():
    base = workloads.gen_diurnal_trace(
        duration_s=30.0, base_rate=5.0, peak_rate=5.0, num_tenants=8,
        buckets=[(4, 3)], seed=3)
    merged = workloads.overlay_burst(
        base, at_s=10.0, duration_s=5.0, rate=40.0, num_tenants=8,
        buckets=[(4, 3)], seed=3)
    assert all(merged[i]["t"] <= merged[i + 1]["t"]
               for i in range(len(merged) - 1))
    added = len(merged) - len(base)
    assert 100 < added < 300  # ~40/s for 5s
    assert all(10.0 <= r["t"] < 15.0
               for r in merged if r not in base)


# ---------------------------------------------------------------------------
# open-loop replay
# ---------------------------------------------------------------------------

class _Clock:
    """Deterministic injectable clock: sleep() advances it exactly."""

    def __init__(self):
        self.t = 100.0

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def test_replay_fires_on_the_trace_clock_not_the_response_clock():
    clk = _Clock()
    recs = [trace.trace_record(t, 0, (4, 3)) for t in (0.0, 1.0, 1.0, 4.0)]
    fired = []

    def submit(i, rec, sched):
        # An open-loop replayer never waits on this "response": make
        # each submit artificially slow and check later arrivals are
        # still scheduled off the TRACE clock, not pushed back.
        fired.append((i, sched))
        clk.t += 0.3

    log = replay.replay(recs, submit, warp=2.0, now=clk.now,
                        sleep=clk.sleep)
    start = log["start"]
    assert [s - start for _, s in fired] == [0.0, 0.5, 0.5, 2.0]
    assert log["scheduled"] == [s for _, s in fired]
    # Record 2 fires 0.3s behind schedule (record 1's slow submit ate
    # its slot) and its own submit adds 0.3s more; the lag is REPORTED
    # — the replayer measures its own under-offering.
    assert log["lag_ms"][2] == pytest.approx(600.0, abs=1.0)
    assert log["max_lag_ms"] == pytest.approx(600.0, abs=1.0)


def test_replay_pumps_housekeeping_only_while_waiting():
    clk = _Clock()
    recs = [trace.trace_record(t, 0, (4, 3)) for t in (0.0, 0.5)]
    pumped = []
    log = replay.replay(recs, lambda *a: None, pump=pumped.append,
                        now=clk.now, sleep=clk.sleep)
    assert pumped  # ran during the 0.5s gap
    assert all(log["start"] <= t <= log["start"] + 0.5 for t in pumped)
    with pytest.raises(ValueError, match="warp"):
        replay.replay(recs, lambda *a: None, warp=0.0)


def test_phase_stats_attributes_by_arrival_and_keeps_empty_phases():
    recs = [trace.trace_record(t, 0, (4, 3))
            for t in (0.1, 0.2, 5.0, 11.0)]
    phases = [{"name": "trough", "until_s": 1.0},
              {"name": "peak", "until_s": 10.0},
              {"name": "fall", "until_s": 12.0},
              {"name": "never", "until_s": 12.0}]
    lat = {0: 10.0, 1: 20.0, 3: 40.0}  # record 2 never completed
    out = replay.phase_stats(recs, phases, lat,
                             lambda v, q: v[round(q * (len(v) - 1))])
    assert out["trough"] == {"offered": 2, "completed": 2,
                             "p50_ms": 10.0, "p95_ms": 20.0}
    assert out["peak"]["offered"] == 1 and out["peak"]["completed"] == 0
    assert out["peak"]["p95_ms"] is None
    assert out["fall"]["completed"] == 1
    assert out["never"] == {"offered": 0, "completed": 0, "p50_ms": None,
                            "p95_ms": None}
    # Past-the-end arrivals belong to the LAST phase, not nowhere.
    assert replay.phase_of(phases, 99.0) == "never"


# ---------------------------------------------------------------------------
# continuous batching: GroupAssembler + batcher wiring
# ---------------------------------------------------------------------------

def _req(s=2, q=2, deadline=None, tenant=None):
    return FewShotRequest(
        support_x=np.zeros((s, 4, 4, 1), np.uint8),
        support_y=(np.arange(s) % 3).astype(np.int32),
        query_x=np.zeros((q, 4, 4, 1), np.uint8),
        deadline=deadline, tenant=tenant)


def test_assembler_fill_dispatch_fires_without_lingering():
    asm = GroupAssembler(batch_tasks=3, linger_ms=10_000.0)
    now = 50.0
    for _ in range(3):
        r = _req()
        r.enqueue_time = now
        asm.admit(r, (4, 4))
    bucket, group = asm.pop_ready(now, max_tasks=3)
    assert bucket == (4, 4) and len(group) == 3
    assert asm.fill_dispatches == 1 and asm.linger_dispatches == 0
    assert asm.pending == 0 and asm.pop_ready(now, 3) is None


def test_assembler_linger_dispatch_charges_at_most_the_budget():
    asm = GroupAssembler(batch_tasks=4, linger_ms=50.0)
    r = _req()
    r.enqueue_time = 10.0
    asm.admit(r, (4, 4))
    # Within the linger budget: hold for company.
    assert asm.pop_ready(10.049, max_tasks=4) is None
    assert asm.pending == 1
    # Past it: the lone request dispatches rather than keep paying.
    bucket, group = asm.pop_ready(10.051, max_tasks=4)
    assert len(group) == 1
    assert asm.linger_dispatches == 1 and asm.fill_dispatches == 0


def test_assembler_dispatches_oldest_group_first_and_keeps_fifo():
    asm = GroupAssembler(batch_tasks=2, linger_ms=0.0)  # always ready
    first, second = _req(), _req()
    first.enqueue_time, second.enqueue_time = 1.0, 2.0
    asm.admit(first, (8, 8))
    asm.admit(second, (4, 4))
    bucket, group = asm.pop_ready(3.0, max_tasks=2)
    assert bucket == (8, 8) and group == [first]  # oldest admit wins
    a, b = _req(), _req()
    a.enqueue_time = b.enqueue_time = 4.0
    asm.admit(a, (4, 4))
    asm.admit(b, (4, 4))
    _, group = asm.pop_ready(5.0, max_tasks=2)
    assert group == [second, a]  # same-bucket order is strict FIFO


def test_assembler_sweeps_expired_from_forming_groups():
    asm = GroupAssembler(batch_tasks=4, linger_ms=1000.0)
    live, dead = _req(deadline=100.0), _req(deadline=1.0)
    live.enqueue_time = dead.enqueue_time = 0.5
    asm.admit(live, (4, 4))
    asm.admit(dead, (4, 4))
    assert asm.sweep_expired(2.0) == [dead]
    assert asm.pending == 1
    # An emptied bucket drops entirely so its linger clock dies.
    only = _req(deadline=1.0)
    only.enqueue_time = 0.5
    asm2 = GroupAssembler(batch_tasks=4, linger_ms=1000.0)
    asm2.admit(only, (4, 4))
    asm2.sweep_expired(2.0)
    assert asm2._groups == {}


def _batcher(cb=True, depth=16, linger_ms=1000.0):
    b = RequestBatcher(buckets=[(4, 4), (8, 8)], max_queue_depth=depth)
    if cb:
        b.assembler = GroupAssembler(4, linger_ms)
    return b


def test_batcher_default_off_is_structurally_unchanged():
    """The zero-cost pin: serve_continuous_batching off leaves
    ``assembler`` None and dispatch IS the head-of-line queue path."""
    b = _batcher(cb=False)
    assert b.assembler is None
    b.submit(_req())
    bucket, group, expired = b.next_group(4)
    assert len(group) == 1 and expired == [] and b.depth == 0


def test_batcher_cb_holds_partial_groups_then_dispatches():
    b = _batcher(linger_ms=1000.0)
    t0 = time.monotonic()
    b.submit(_req(), now=t0)
    b.submit(_req(), now=t0)
    assert b.depth == 2  # forming members count as queued
    bucket, group, expired = b.next_group(4, now=t0 + 0.1)
    assert group == [] and b.depth == 2  # still lingering for company
    bucket, group, _ = b.next_group(4, now=t0 + 1.1)
    assert len(group) == 2 and bucket == (4, 4)
    assert b.depth == 0


def test_batcher_cb_forming_groups_count_against_backpressure():
    b = _batcher(depth=2)
    b.submit(_req())
    b.submit(_req())
    with pytest.raises(QueueFullError):
        b.submit(_req())


def test_pad_group_replicates_task0_for_missing_tasks():
    """Padding exactness under partial groups: a 2-of-4 dispatch pads
    the missing tasks by REPLICATING task 0 (an all-zero weight row
    would divide by zero in the weighted adapt loss); real rows carry
    weight 1 on real support only."""
    a, b = _req(s=2, q=1), _req(s=3, q=2)
    out = pad_group([a, b], bucket=(4, 4), batch_tasks=4,
                    image_shape=(4, 4, 1))
    assert out["support_x"].shape == (4, 4, 4, 4, 1)
    assert out["occupancy"] == 0.5
    np.testing.assert_array_equal(out["support_w"][0], [1, 1, 0, 0])
    np.testing.assert_array_equal(out["support_w"][1], [1, 1, 1, 0])
    for pad in (2, 3):
        np.testing.assert_array_equal(out["support_x"][pad],
                                      out["support_x"][0])
        np.testing.assert_array_equal(out["support_w"][pad],
                                      out["support_w"][0])


# ---------------------------------------------------------------------------
# weighted canary split
# ---------------------------------------------------------------------------

def test_canary_assignment_is_deterministic_and_rate_monotone():
    ids = [(t, s) for t in range(8) for s in range(200)]
    f = [canary_fraction(t, s) for t, s in ids]
    assert f == [canary_fraction(t, s) for t, s in ids]
    assert 0.4 < sum(f) / len(f) < 0.6  # roughly uniform on [0, 1)
    # Growing the weight only ADDS requests to the canary cohort: every
    # stage's cohort is a strict superset of the previous stage's (the
    # property the stage-over-stage SLO comparison rests on).
    cohorts = {w: {i for i in ids if assign_canary(i[0], i[1], w)}
               for w in (0.0, 0.1, 0.25, 1.0)}
    assert cohorts[0.0] == set()
    assert cohorts[1.0] == set(ids)
    assert cohorts[0.1] < cohorts[0.25] < cohorts[1.0]
    assert len(cohorts[0.25]) / len(ids) == pytest.approx(0.25, abs=0.06)


def _announce(fleet_dir, rid):
    lease = ReplicaLease(str(fleet_dir), rid, interval_s=0.0)
    lease.touch({"version": 1, "pid": 1000 + rid})
    return lease


def test_router_route_among_restricts_to_cohort_with_loud_fallback(
        tmp_path):
    reg = MetricsRegistry()
    for rid in (0, 1, 2):
        _announce(tmp_path, rid)
    router = FleetRouter(str(tmp_path), registry=reg)
    router.refresh()
    keys = [f"key-{i}" for i in range(40)]
    for k in keys:
        rid = router.route(k, among=[1])
        assert rid == 1
        router.complete(rid)
    assert reg.counter(fr.COHORT_FALLBACK_COUNTER).value == 0
    # Empty intersection: serving on the wrong cohort beats dropping
    # the request — but the fallback is COUNTED, never silent.
    rid = router.route(keys[0], among=[99])
    assert rid in (0, 1, 2)
    router.complete(rid)
    assert reg.counter(fr.COHORT_FALLBACK_COUNTER).value == 1


# ---------------------------------------------------------------------------
# weighted rollout state machine (fake membership, the test_fleet idiom)
# ---------------------------------------------------------------------------

class _FakeFleet:
    def __init__(self, rids):
        self.members = {r: {"state": "live", "age": 0.0,
                            "draining": False,
                            "payload": {"version": 1, "stats": {}}}
                        for r in rids}

    def __call__(self):
        return {r: dict(rec) for r, rec in self.members.items()}


def _feed(ctl, cohort, n, latency_ms):
    for i in range(n):
        ctl.observe_cohort(cohort, f"t{i}", latency_ms)


def test_weighted_rollout_bakes_stage_by_stage_to_done(tmp_path):
    reg = MetricsRegistry()
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet, registry=reg,
                          slo_p95_ms=100.0, canary_min_requests=5,
                          canary_burn_factor=2.0)
    doc = ctl.start_rollout(2, weights=[0.25, 1.0])
    assert doc["mode"] == "weighted" and doc["phase"] == "swap"
    # No weighted bake in flight yet -> split off.
    assert ctl.traffic_split() == {"weight": None, "canary": [],
                                   "stage": None}
    # Replica 0 acks the swap: it becomes the canary cohort and the
    # rollout holds at weight 0.25 instead of draining replica 1.
    fleet.members[0]["payload"] = {"version": 2}
    doc = ctl.tick()
    assert doc["phase"] == "bake" and doc["canary"] == [0]
    assert ctl.traffic_split() == {"weight": 0.25, "canary": [0],
                                   "stage": 0}
    # Too little evidence: the stage holds.
    _feed(ctl, "canary", 3, 10.0)
    assert ctl.tick()["phase"] == "bake"
    # Enough healthy canary evidence vs stable -> promote. The ladder
    # hits 1.0, so the machine returns to swap for the rest of the
    # fleet and the split opens up (weight None, cohort kept).
    _feed(ctl, "canary", 2, 10.0)
    _feed(ctl, "stable", 8, 10.0)
    doc = ctl.tick()
    assert doc["stage"] == 1 and doc["phase"] == "swap"
    assert doc["stage_history"][0]["stage"] == 0
    assert doc["stage_history"][0]["canary"]["count"] == 5
    split = ctl.traffic_split()
    assert split["weight"] is None and split["canary"] == [0]
    assert os.path.exists(ctl._drain_path(1))
    fleet.members[1]["payload"] = {"version": 2}
    doc = ctl.tick()
    assert doc["state"] == fc.DONE and doc["canary"] == [0, 1]
    assert reg.counter(fc.CANARY_STAGE_COUNTER).value == 1
    assert reg.counter(fc.SWAPS_COUNTER).value == 1
    assert ctl.traffic_split()["weight"] is None


def test_weighted_rollout_halts_and_pins_on_canary_regression(tmp_path):
    reg = MetricsRegistry()
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet, registry=reg,
                          slo_p95_ms=100.0, canary_min_requests=5,
                          canary_burn_factor=2.0)
    ctl.start_rollout(2, weights=[0.25, 1.0])
    fleet.members[0]["payload"] = {"version": 2}
    ctl.tick()
    # The canary cohort blows its SLO while stable is healthy.
    _feed(ctl, "canary", 6, 500.0)
    _feed(ctl, "stable", 6, 10.0)
    doc = ctl.tick()
    assert doc["state"] == fc.HALTED
    assert doc["halt_reason"] == "canary slo regression"
    assert doc["halt_stage"] == 0 and 2 in doc["rejected"]
    assert reg.counter(fc.HALTS_COUNTER).value == 1
    # Split is off after the halt; the version is pinned fleet-wide.
    assert ctl.traffic_split()["weight"] is None
    assert ctl.start_rollout(2)["state"] == fc.HALTED


def test_weighted_rollout_fresh_cohort_ledgers_per_stage(tmp_path):
    """Each stage's verdict rests on its OWN evidence: observations a
    lighter weight already judged must not leak into the next stage."""
    fleet = _FakeFleet([0, 1, 2])
    ctl = FleetController(str(tmp_path), fleet, slo_p95_ms=100.0,
                          canary_min_requests=4, canary_burn_factor=2.0)
    ctl.start_rollout(2, weights=[0.1, 0.5, 1.0])
    fleet.members[0]["payload"] = {"version": 2}
    ctl.tick()
    _feed(ctl, "canary", 4, 10.0)
    doc = ctl.tick()
    assert doc["stage"] == 1 and doc["phase"] == "bake"
    assert ctl.traffic_split()["weight"] == 0.5
    # The promoted stage starts from zero observations.
    assert ctl._cohorts["canary"].count() == 0
    assert ctl.tick()["stage"] == 1  # holds without fresh evidence


# ---------------------------------------------------------------------------
# jax-free contract
# ---------------------------------------------------------------------------

def test_loadlab_modules_load_jax_free(tmp_path):
    """PYTHONPATH booby trap (the reqtrace idiom): the trace, workload
    and replay modules are file-path-loadable by jax-free driver
    processes; any jax import explodes."""
    trap = tmp_path / "trap"
    trap.mkdir()
    (trap / "jax.py").write_text(
        "raise ImportError('loadlab must not import jax')\n")
    prog = (
        "import importlib.util, os\n"
        f"base = {LOADLAB!r}\n"
        "mods = {}\n"
        "for name in ('trace', 'workloads', 'replay'):\n"
        "    spec = importlib.util.spec_from_file_location(\n"
        "        name, os.path.join(base, name + '.py'))\n"
        "    mods[name] = importlib.util.module_from_spec(spec)\n"
        "    spec.loader.exec_module(mods[name])\n"
        "recs = mods['workloads'].gen_diurnal_trace(\n"
        "    duration_s=5.0, base_rate=2.0, peak_rate=8.0,\n"
        "    num_tenants=4, buckets=[(4, 3)], seed=1)\n"
        "blob = mods['trace'].encode_trace(recs)\n"
        "_, out = mods['trace'].decode_trace(blob)\n"
        "assert out == recs\n"
        "log = mods['replay'].replay(out[:3], lambda *a: None, warp=1e9)\n"
        "assert len(log['scheduled']) == 3\n"
        "print('OK')\n")
    proc = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        env=dict(os.environ, PYTHONPATH=str(trap)), timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout

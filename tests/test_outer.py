"""Outer-step tests: train_step learns, schedules behave, eval protocol
returns per-task outputs."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import (
    Episode, init_train_state, make_eval_step, make_train_step,
    meta_lr_schedule)
from howtotrainyourmamlpytorch_tpu.models import make_model

CFG = MAMLConfig(
    image_height=12, image_width=12, image_channels=1,
    num_classes_per_set=3, num_samples_per_class=2, num_target_samples=2,
    cnn_num_filters=8, num_stages=2, batch_size=4,
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    task_learning_rate=0.1, meta_learning_rate=0.01,
    min_learning_rate=0.001, total_epochs=4, total_iter_per_epoch=10,
    compute_dtype="float32")


def _synthetic_batch(key, cfg, batch_size):
    """Trivially separable episodes: class i images have mean i."""
    n, k, t = (cfg.num_classes_per_set, cfg.num_samples_per_class,
               cfg.num_target_samples)
    h, w, c = cfg.image_shape
    keys = jax.random.split(key, 2)
    means = jnp.arange(n, dtype=jnp.float32)[:, None, None, None, None]

    def gen(key, per_class):
        noise = jax.random.normal(key,
                                  (n, per_class * batch_size, h, w, c)) * 0.3
        x = (noise + means).reshape(n, batch_size, per_class, h, w, c)
        x = jnp.moveaxis(x, 1, 0).reshape(batch_size, n * per_class, h, w, c)
        y = jnp.tile(jnp.repeat(jnp.arange(n), per_class)[None],
                     (batch_size, 1))
        return x, y

    sx, sy = gen(keys[0], k)
    tx, ty = gen(keys[1], t)
    return Episode(sx, sy.astype(jnp.int32), tx, ty.astype(jnp.int32))


def test_train_step_decreases_loss():
    init, apply = make_model(CFG)
    state = init_train_state(CFG, init, jax.random.PRNGKey(0))
    train_step = jax.jit(
        functools.partial(make_train_step(CFG, apply),
                          second_order=True, use_msl=True))
    losses = []
    for i in range(20):
        batch = _synthetic_batch(jax.random.PRNGKey(100 + i), CFG, 4)
        state, metrics = train_step(state, batch, jnp.float32(0))
        losses.append(float(metrics.loss))
    assert losses[-1] < losses[0] * 0.7, losses
    assert int(state.step) == 20


def test_eval_step_outputs():
    init, apply = make_model(CFG)
    state = init_train_state(CFG, init, jax.random.PRNGKey(0))
    eval_step = jax.jit(make_eval_step(CFG, apply))
    batch = _synthetic_batch(jax.random.PRNGKey(0), CFG, 4)
    res = eval_step(state, batch)
    assert res.loss.shape == (4,)
    assert res.accuracy.shape == (4,)
    assert res.target_logits.shape == (4, 6, 3)
    # Eval must not mutate training state (functional: nothing to assert on
    # state, but logits must be finite).
    assert np.isfinite(np.asarray(res.target_logits)).all()


def test_first_order_step_runs_and_differs():
    init, apply = make_model(CFG)
    state = init_train_state(CFG, init, jax.random.PRNGKey(0))
    ts = make_train_step(CFG, apply)
    batch = _synthetic_batch(jax.random.PRNGKey(1), CFG, 4)
    s_fo, m_fo = jax.jit(functools.partial(ts, second_order=False,
                                           use_msl=False))(
        state, batch, jnp.float32(0))
    s_so, m_so = jax.jit(functools.partial(ts, second_order=True,
                                           use_msl=False))(
        state, batch, jnp.float32(0))
    # Same forward loss (the loss is computed before the update)...
    np.testing.assert_allclose(float(m_fo.loss), float(m_so.loss),
                               rtol=1e-5)
    # ...but different resulting parameters (different meta-gradients).
    d = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                     s_fo.params, s_so.params)
    assert max(jax.tree.leaves(d)) > 1e-7


@pytest.mark.core
def test_lslr_frozen_when_not_learnable():
    cfg = CFG.replace(
        learnable_per_layer_per_step_inner_loop_learning_rate=False)
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    train_step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                           second_order=True, use_msl=False))
    batch = _synthetic_batch(jax.random.PRNGKey(2), cfg, 4)
    new_state, _ = train_step(state, batch, jnp.float32(0))
    for a, b in zip(jax.tree.leaves(state.lslr),
                    jax.tree.leaves(new_state.lslr)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.core
def test_lslr_updates_when_learnable():
    init, apply = make_model(CFG)
    state = init_train_state(CFG, init, jax.random.PRNGKey(0))
    train_step = jax.jit(functools.partial(make_train_step(CFG, apply),
                                           second_order=True, use_msl=False))
    batch = _synthetic_batch(jax.random.PRNGKey(2), CFG, 4)
    new_state, _ = train_step(state, batch, jnp.float32(0))
    diffs = [float(jnp.abs(a - b).max())
             for a, b in zip(jax.tree.leaves(state.lslr),
                             jax.tree.leaves(new_state.lslr))]
    assert max(diffs) > 0


@pytest.mark.core
def test_bnwb_flags_freeze_gamma_beta():
    """learnable_bn_gamma/beta=False must leave γ/β at their 1/0 init
    (reference: requires_grad flags on MetaBatchNormLayer weight/bias)."""
    cfg = CFG.replace(learnable_bn_gamma=False, learnable_bn_beta=False)
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    train_step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                           second_order=True, use_msl=False))
    batch = _synthetic_batch(jax.random.PRNGKey(5), cfg, 4)
    new_state, _ = train_step(state, batch, jnp.float32(0))
    for name in new_state.params:
        if "norm" in name:
            np.testing.assert_array_equal(
                np.asarray(new_state.params[name]["gamma"]),
                np.asarray(state.params[name]["gamma"]))
            np.testing.assert_array_equal(
                np.asarray(new_state.params[name]["beta"]),
                np.asarray(state.params[name]["beta"]))
    # Conv weights still train.
    assert float(jnp.abs(new_state.params["conv0"]["w"]
                         - state.params["conv0"]["w"]).max()) > 0


def test_eval_steps_exceed_train_steps():
    cfg = CFG.replace(number_of_evaluation_steps_per_iter=4)
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    res = jax.jit(make_eval_step(cfg, apply))(
        state, _synthetic_batch(jax.random.PRNGKey(6), cfg, 4))
    assert np.isfinite(np.asarray(res.loss)).all()
    assert state.lslr["conv0"]["w"].shape == (5,)  # max(train,eval)+1


@pytest.mark.core
def test_cosine_schedule_endpoints():
    sched = meta_lr_schedule(CFG)
    assert abs(float(sched(0)) - CFG.meta_learning_rate) < 1e-9
    last = float(sched(CFG.total_epochs * CFG.total_iter_per_epoch))
    assert abs(last - CFG.min_learning_rate) < 1e-6
    # Epoch-granular: constant within an epoch.
    assert float(sched(0)) == float(sched(CFG.total_iter_per_epoch - 1))
    assert float(sched(0)) > float(sched(CFG.total_iter_per_epoch))


@pytest.mark.slow  # two full train-step compiles (~25s, 1 core);
#                    the clamped trajectory-parity variant also
#                    covers clamp semantics in the full pyramid
def test_grad_clamp_applied():
    """A huge clamp is a no-op; a tight clamp changes the update (the
    reference clamps per-parameter grads to ±10 for *ImageNet runs)."""
    batch = _synthetic_batch(jax.random.PRNGKey(3), CFG, 4)

    def run(clamp):
        cfg = CFG.replace(clamp_meta_grad_value=clamp)
        init, apply = make_model(cfg)
        state = init_train_state(cfg, init, jax.random.PRNGKey(0))
        train_step = jax.jit(functools.partial(
            make_train_step(cfg, apply), second_order=True, use_msl=False))
        new_state, _ = train_step(state, batch, jnp.float32(0))
        return new_state.params

    p_none, p_huge, p_tight = run(None), run(1e6), run(1e-5)
    for a, b in zip(jax.tree.leaves(p_none), jax.tree.leaves(p_huge)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    diffs = [float(jnp.abs(a - b).max())
             for a, b in zip(jax.tree.leaves(p_none),
                             jax.tree.leaves(p_tight))]
    assert max(diffs) > 0


def test_block_outs_remat_and_fast_bn_match_default_grads():
    """The perf variants (remat_policy='block_outs', bn_fast_math) must not
    change the meta-gradient. Gradients are compared directly — comparing
    post-Adam params would amount to a sign test (Adam's first update is
    ±lr for any nonzero grad), infinitely sensitive at near-zero grads."""
    from howtotrainyourmamlpytorch_tpu.meta.inner import (
        lslr_init, per_step_loss_importance, split_fast_slow, task_forward)

    batch = _synthetic_batch(jax.random.PRNGKey(9), CFG, 4)

    def meta_grads(cfg):
        init, apply = make_model(cfg)
        params, bn_state = init(jax.random.PRNGKey(0))
        fast0, _ = split_fast_slow(cfg, params)
        lslr = lslr_init(cfg, fast0)
        msl_w = per_step_loss_importance(cfg, jnp.float32(0))

        def loss_fn(params):
            def one(ep):
                return task_forward(
                    cfg, apply, params, lslr, bn_state, ep,
                    num_steps=cfg.number_of_training_steps_per_iter,
                    second_order=True, use_msl=True,
                    msl_weights=msl_w).loss
            return jnp.mean(jax.vmap(one)(batch))

        return jax.jit(jax.grad(loss_fn))(params)

    g_ref = meta_grads(CFG)
    g_var = meta_grads(CFG.replace(remat_policy="block_outs",
                                   bn_fast_math=True))
    for (p1, p2) in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_var)):
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=5e-3, atol=1e-5)


@pytest.mark.core
def test_task_microbatch_accumulation_matches_single_shot():
    """Grad accumulation over task micro-batches reproduces the one-shot
    step exactly: same loss/metrics and same post-step state."""
    batch = _synthetic_batch(jax.random.PRNGKey(11), CFG, 4)

    def one_step(cfg):
        init, apply = make_model(cfg)
        state = init_train_state(cfg, init, jax.random.PRNGKey(0))
        step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                         second_order=True, use_msl=True))
        return step(state, batch, jnp.float32(0))

    s1, m1 = one_step(CFG)
    s2, m2 = one_step(CFG.replace(task_microbatches=2))
    np.testing.assert_allclose(float(m1.loss), float(m2.loss), rtol=1e-6)
    np.testing.assert_allclose(float(m1.accuracy), float(m2.accuracy),
                               rtol=1e-6)
    # Gradient equality via Adam's first moment (mu = (1-b1)·g — LINEAR in
    # the grad); comparing post-Adam params would be a sign test at
    # near-zero grads (update ≈ ±lr regardless of |g|).
    for a, b in zip(jax.tree.leaves(s1.opt_state),
                    jax.tree.leaves(s2.opt_state)):
        np.testing.assert_allclose(np.asarray(a, np.float64),
                                   np.asarray(b, np.float64),
                                   rtol=2e-4, atol=1e-7)
    for a, b in zip(jax.tree.leaves(s1.bn_state),
                    jax.tree.leaves(s2.bn_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.core
def test_task_microbatches_must_divide_batch():
    import pytest
    init, apply = make_model(CFG.replace(task_microbatches=3))
    with pytest.raises(ValueError, match="divide"):
        make_train_step(CFG.replace(task_microbatches=3), apply)


@pytest.mark.slow  # multi-step-count eval compiles (~25s, 1 core)
def test_eval_adaptation_gain_on_permuted_tasks():
    """The few-shot mechanism itself: with a random per-episode class-label
    permutation the initialization alone cannot classify (the mapping
    changes every episode) — accuracy must come from inner-loop adaptation
    on the support set, and must increase with more adaptation steps.
    Deterministic (fixed seeds, CPU), so the inequalities are exact
    regression checks, not statistical ones."""
    cfg = CFG.replace(number_of_training_steps_per_iter=3,
                      number_of_evaluation_steps_per_iter=3)

    def permuted_batch(key, batch_size):
        n, k, t = (cfg.num_classes_per_set, cfg.num_samples_per_class,
                   cfg.num_target_samples)
        h, w, c = cfg.image_shape
        ks = jax.random.split(key, 3)
        perms = jnp.stack([jax.random.permutation(kk, n)
                           for kk in jax.random.split(ks[0], batch_size)])

        def gen(key, per):
            noise = jax.random.normal(
                key, (batch_size, n, per, h, w, c)) * 0.3
            means = perms[:, :, None, None, None, None].astype(jnp.float32)
            x = (noise + means).reshape(batch_size, n * per, h, w, c)
            y = jnp.tile(jnp.repeat(jnp.arange(n), per)[None],
                         (batch_size, 1)).astype(jnp.int32)
            return x, y

        sx, sy = gen(ks[1], k)
        tx, ty = gen(ks[2], t)
        return Episode(sx, sy, tx, ty)

    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                     second_order=True, use_msl=False))
    for i in range(60):
        state, metrics = step(state, permuted_batch(
            jax.random.PRNGKey(1000 + i), 8), jnp.float32(20))
    assert float(metrics.accuracy) > 0.95

    def eval_acc(num_steps):
        ecfg = cfg.replace(number_of_evaluation_steps_per_iter=num_steps)
        ev = jax.jit(make_eval_step(ecfg, apply))
        accs = [np.asarray(ev(state, permuted_batch(
            jax.random.PRNGKey(5000 + j), 8)).accuracy).mean()
            for j in range(4)]
        return float(np.mean(accs))

    acc1, acc3 = eval_acc(1), eval_acc(3)
    assert acc3 > acc1, (acc1, acc3)      # more adaptation -> better
    assert acc3 > 0.99, acc3              # full adaptation solves the task


@pytest.mark.core
def test_pre_k_plus_1_lslr_checkpoint_migrates():
    """A checkpoint holding the pre-r2 (K,)-row LSLR format must resume:
    migrate_lslr_rows pads the init row + zero Adam moments, and the
    result trains (meta/outer.py § migrate_lslr_rows)."""
    from flax import serialization
    from howtotrainyourmamlpytorch_tpu.meta.outer import migrate_lslr_rows

    init, apply = make_model(CFG)
    state = init_train_state(CFG, init, jax.random.PRNGKey(0))
    chop = lambda leaf: leaf[:-1]

    def chop_entry(entry):
        mu = getattr(entry, "mu", None)
        if isinstance(mu, dict) and "lslr" in mu:
            return entry._replace(
                mu={**mu, "lslr": jax.tree.map(chop, mu["lslr"])},
                nu={**entry.nu, "lslr": jax.tree.map(chop, entry.nu["lslr"])})
        return entry

    old_state = state.replace(
        lslr=jax.tree.map(chop, state.lslr),
        opt_state=tuple(chop_entry(e) for e in state.opt_state))
    # Round-trip through the serialized wire format like a real resume.
    restored = serialization.from_bytes(
        state, serialization.to_bytes(jax.device_get(old_state)))
    migrated = migrate_lslr_rows(CFG, restored)
    for a, b in zip(jax.tree.leaves(migrated.lslr),
                    jax.tree.leaves(state.lslr)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # Shapes line up with the optimizer again: one step runs.
    train_step = jax.jit(functools.partial(make_train_step(CFG, apply),
                                           second_order=False,
                                           use_msl=False))
    new_state, m = train_step(migrated, _synthetic_batch(
        jax.random.PRNGKey(1), CFG, 4), jnp.float32(0))
    assert np.isfinite(float(m.loss))
    # Current-format states pass through untouched.
    assert migrate_lslr_rows(CFG, state) is state


@pytest.mark.core
def test_train_step_persists_task_mean_bn_state():
    """KNOWN DEVIATION from the reference, asserted here so the shipped
    semantics cannot drift silently (VERDICT r4 weak #4; MOUNT-AUDIT
    #15; docs/PARITY.md § Known deviations): the reference backs up and
    RESTORES BN running stats around every TRAINING task
    (few_shot_learning_system.py § forward -> restore_backup_stats per
    SURVEY.md §3.2), i.e. running stats never evolve during training.
    This build instead persists the task-MEAN of the post-task stats
    (meta/outer.py § batch_loss). Behaviorally inert — stats are
    tracked but never normalize (models/layers.py § batch_norm_apply
    always uses batch statistics, train AND eval, exactly like the
    reference) — but checkpoint bytes differ from a faithful port's."""
    cfg = CFG.replace(batch_size=2, per_step_bn_statistics=True)
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    train_step = jax.jit(
        functools.partial(make_train_step(cfg, apply),
                          second_order=False, use_msl=False))
    batch = _synthetic_batch(jax.random.PRNGKey(7), cfg, 2)
    new_state, _ = train_step(state, batch, jnp.float32(0))

    # Expected: the mean over tasks of each task's own post-adaptation
    # bn_state, computed directly through task_forward.
    from howtotrainyourmamlpytorch_tpu.meta.inner import task_forward
    res = jax.vmap(lambda ep: task_forward(
        cfg, apply, state.params, state.lslr, state.bn_state, ep,
        num_steps=cfg.number_of_training_steps_per_iter,
        second_order=False, use_msl=False, msl_weights=None))(batch)
    expected = jax.tree.map(lambda a: jnp.mean(a, axis=0), res.bn_state)

    changed = False
    for got, exp, old in zip(jax.tree.leaves(new_state.bn_state),
                             jax.tree.leaves(expected),
                             jax.tree.leaves(state.bn_state)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=1e-5, atol=1e-6)
        changed = changed or not np.allclose(np.asarray(got),
                                             np.asarray(old))
    # The stats genuinely evolve (the reference's restore semantics
    # would leave them at init) — this is the observable deviation.
    assert changed

    # Eval, by contrast, matches the reference: state untouched.
    eval_step = jax.jit(make_eval_step(cfg, apply))
    eval_step(new_state, batch)  # returns results only; nothing persisted

"""Multi-host feeding path (parallel/multihost.py).

Single-process CPU stand-in: with process_count()==1 every device is
addressable, so ``assemble_global_batch`` must reproduce exactly what
whole-batch sampling + ``shard_batch`` produces — same values, same
per-device shards. The position math (one contiguous run per device,
disjoint cover of the batch axis) is what multi-host correctness rests on.
"""

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.sampler import EpisodeSampler
from howtotrainyourmamlpytorch_tpu.data.sources import SyntheticSource
from howtotrainyourmamlpytorch_tpu.parallel import (
    assemble_global_batch, batch_sharding, local_batch_positions,
    make_mesh, shard_batch)


@pytest.fixture(scope="module")
def cfg():
    return MAMLConfig(
        dataset_name="synthetic", image_height=8, image_width=8,
        image_channels=1, num_classes_per_set=3, num_samples_per_class=2,
        num_target_samples=2, batch_size=16, mesh_shape=(2, 4),
        # 8px supports two pooling stages (8->4->2); with the default
        # four, max_pool2d now (correctly) rejects the empty 4th pool —
        # before that guard this config silently ran on empty features.
        num_stages=2)


@pytest.fixture(scope="module")
def mesh(cfg):
    return make_mesh(cfg)


def _sampler(cfg):
    src = SyntheticSource(num_classes=10, images_per_class=8,
                          image_size=cfg.image_shape, seed=0)
    return EpisodeSampler(src, cfg, split_seed=7)


def test_local_positions_cover_batch_disjointly(cfg, mesh):
    slices = local_batch_positions(batch_sharding(mesh), cfg.batch_size)
    assert len(slices) == 8  # one run per addressable device
    covered = []
    for _, start, stop in slices:
        assert stop - start == cfg.batch_size // 8
        covered.extend(range(start, stop))
    assert sorted(covered) == list(range(cfg.batch_size))


def test_assemble_matches_whole_batch_shard(cfg, mesh):
    sampler = _sampler(cfg)
    sharding = batch_sharding(mesh)

    whole = shard_batch(
        sampler.sample_batch(range(100, 100 + cfg.batch_size)), mesh)
    assembled = assemble_global_batch(
        lambda s, e: sampler.sample_batch(range(100 + s, 100 + e)),
        cfg.batch_size, sharding)

    for a, b in zip(assembled, whole):
        assert a.shape == b.shape
        assert a.sharding.is_equivalent_to(b.sharding, a.ndim)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_assembled_batch_feeds_sharded_step(cfg, mesh):
    """The assembled global batch must be consumable by the jitted sharded
    eval step exactly like a shard_batch-placed one."""
    from howtotrainyourmamlpytorch_tpu.meta import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.parallel import (
        make_sharded_steps, replicated_sharding)

    small = cfg.replace(number_of_training_steps_per_iter=1,
                        number_of_evaluation_steps_per_iter=1)
    init, apply = make_model(small)
    plan = make_sharded_steps(small, apply, mesh)
    state = jax.device_put(
        init_train_state(small, init, jax.random.PRNGKey(0)),
        replicated_sharding(mesh))
    sampler = _sampler(small)
    batch = assemble_global_batch(
        lambda s, e: sampler.sample_batch(range(s, e)),
        small.batch_size, batch_sharding(mesh))
    res = plan.eval_step(state, batch)
    assert np.isfinite(np.asarray(jax.device_get(res.loss))).all()


def test_agreement_helpers_single_process_noop():
    from howtotrainyourmamlpytorch_tpu.parallel import (
        agree_int_from_main, any_process_true)
    assert agree_int_from_main(7) == 7
    assert agree_int_from_main(-1) == -1
    assert any_process_true(True) is True
    assert any_process_true(False) is False

"""Every shipped experiment_config/*.json must train, not just parse.

Loads each config verbatim (the reference JSON schema), shrinks ONLY the
geometry/compute knobs that don't change which code paths run (image
size, filter count, batch, iteration counts), and executes one real
jitted train step + eval step with the config's own feature set — MAML++
toggles, way/shot, backbone, inner-step counts all as shipped. Catches
config/model incompatibilities that a parse-only test cannot (e.g. a
backbone name typo, a way-count the head mishandles, a feature combo
whose executable fails to trace).
"""

import glob
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.sampler import EpisodeSampler
from howtotrainyourmamlpytorch_tpu.data.sources import (
    SinusoidSource, SyntheticSource)
from howtotrainyourmamlpytorch_tpu.meta import init_train_state
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.parallel import (
    make_mesh, make_sharded_steps, shard_batch)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = sorted(glob.glob(os.path.join(REPO, "experiment_config", "*.json")))


@pytest.mark.parametrize(
    "path",
    # The pod-scale ResNet-12 config compiles a much deeper backbone
    # (~2 min on the 1-core CI box) and the 20-way Omniglot configs are
    # the widest episode compiles (~25s each vs ~17s): slow profile,
    # like the other long-compile system tests (full CI keeps them;
    # every way/shot/backbone family keeps a tier-1 representative).
    [pytest.param(p, marks=pytest.mark.slow)
     if ("resnet12_pod" in p or "20-way" in p) else p
     for p in CONFIGS],
    ids=[os.path.basename(p) for p in CONFIGS])
def test_shipped_config_trains_one_step(path):
    cfg = MAMLConfig.from_json_file(path)
    # Shrink compute only; keep way/shot/steps/toggles/backbone as shipped.
    # 16px: the smallest size whose four pooling stages (both backbones)
    # all stay non-empty — max_pool2d raises on anything smaller, and
    # before that check a 12px VGG silently trained on EMPTY feature maps
    # (flatten of a 0-sized spatial dim -> all-zero logits, finite loss).
    shrink = dict(
        cnn_num_filters=4, batch_size=2,
        mesh_shape=(1, 1),
        total_epochs=2, total_iter_per_epoch=2,
        # Keep the shipped accumulation path ACTIVE where possible: the
        # flagship configs ship task_microbatches 12/8, and clamping to
        # the gcd with the scaled batch (2) still exercises mb=2
        # chunked accumulation with each config's exact toggle set.
        task_microbatches=math.gcd(2, cfg.task_microbatches))
    if cfg.task_type != "regression":
        # 16px: the smallest size whose four pooling stages stay
        # non-empty (see module comment above). Regression ships 1x1x1
        # scalar "images" already — nothing to shrink, and resizing
        # would change the MLP's input contract.
        shrink.update(image_height=16, image_width=16)
    cfg = cfg.replace(**shrink)

    if cfg.task_type == "regression":
        src = SinusoidSource(
            num_tasks=max(2 * cfg.num_classes_per_set, 8),
            points_per_task=2 * (cfg.num_samples_per_class
                                 + cfg.num_target_samples),
            seed=5)
    else:
        src = SyntheticSource(
            num_classes=max(2 * cfg.num_classes_per_set, 8),
            images_per_class=2 * (cfg.num_samples_per_class
                                  + cfg.num_target_samples),
            image_size=cfg.image_shape, seed=5)
    sampler = EpisodeSampler(src, cfg, split_seed=1)

    init, apply = make_model(cfg)
    mesh = make_mesh(cfg, jax.devices()[:1])
    plan = make_sharded_steps(cfg, apply, mesh)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    state = jax.device_put(
        state,
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
    batch = shard_batch(sampler.sample_batch(range(cfg.batch_size)), mesh)

    # The executable pair real training would select at epoch 0.
    step = plan.train_steps[(cfg.use_second_order(0), cfg.use_msl(0))]
    state, metrics = step(state, batch, jnp.float32(0.0))
    assert np.isfinite(float(jax.device_get(metrics.loss)))

    ev = plan.eval_step(state, batch)
    losses = np.asarray(jax.device_get(ev.loss))
    assert losses.shape == (cfg.batch_size,)
    assert np.isfinite(losses).all()

"""Pod fault domain units (ISSUE 9).

Tier-1 keeps the cheap layers — the pure ClusterMonitor deadline math
(live/stalled/dead boundaries, clock-skew tolerance, missing leases),
lease write/read round-trip, consensus-epoch agreement with a
deliberately stale local manifest, peer_lost-row + exit-73 plumbing via
an injectable trip action, the double-trip escalation, and the
structural zero-config-installs-nothing pin (the watchdog pattern). The
N-process SIGKILL → exit-73 → consensus-resume proof lives in
tests/test_pod_cluster.py's slow profile and scripts/chaos_pod.py.
"""

import json
import math
import os
import time

import pytest

from howtotrainyourmamlpytorch_tpu import resilience
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.resilience import (
    cluster, faults, flightrec, watchdog)
from howtotrainyourmamlpytorch_tpu.resilience.cluster import (
    ClusterFaultDomain, ClusterMonitor, HeartbeatLease)
from howtotrainyourmamlpytorch_tpu.resilience.faults import FaultPlan
from howtotrainyourmamlpytorch_tpu.resilience.watchdog import (
    ProgressBeacon, Watchdog)
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry
from howtotrainyourmamlpytorch_tpu.utils.tracing import (
    JsonlLogger, read_jsonl)


@pytest.fixture(autouse=True)
def _clean_process_state():
    """Every test starts/ends with no domain, beacon, recorder, fault
    plan or resilience registry installed (runs install their own)."""
    faults.configure("")
    prev_reg = resilience.set_registry(None)
    prev_beacon = watchdog.install_beacon(None)
    prev_rec = flightrec.install(None)
    prev_dom = cluster.install(None)
    yield
    faults.configure("")
    resilience.set_registry(prev_reg)
    watchdog.install_beacon(prev_beacon)
    flightrec.install(prev_rec)
    cluster.install(prev_dom)


# ---------------------------------------------------------------------------
# exit code + config surface
# ---------------------------------------------------------------------------

def test_exit_code_distinct():
    assert resilience.EXIT_PEER_LOST == 73
    assert len({resilience.EXIT_PEER_LOST, resilience.EXIT_HUNG,
                resilience.EXIT_PREEMPTED}) == 3


def test_config_cluster_validation():
    for field in ("cluster_collective_timeout_s",
                  "cluster_peer_stalled_s", "cluster_peer_dead_s"):
        with pytest.raises(ValueError, match=field):
            MAMLConfig(**{field: -1.0})
    with pytest.raises(ValueError, match="cluster_lease_interval_s"):
        MAMLConfig(cluster_lease_interval_s=0.0)
    with pytest.raises(ValueError, match="cluster_peer_dead_s"):
        MAMLConfig(cluster_peer_stalled_s=10.0, cluster_peer_dead_s=5.0)
    with pytest.raises(ValueError, match="require_mesh"):
        MAMLConfig(require_mesh=2)
    # Defaults: the subsystem is OFF.
    cfg = MAMLConfig()
    assert not cluster.cluster_enabled(cfg)
    on = cfg.replace(cluster_collective_timeout_s=30.0)
    assert cluster.cluster_enabled(on)
    # Auto thresholds: stalled = 3 lease intervals; dead = the
    # collective budget, never below stalled.
    assert cluster.stalled_after(on) == pytest.approx(15.0)
    assert cluster.dead_after(on) == pytest.approx(30.0)
    tight = on.replace(cluster_collective_timeout_s=2.0)
    assert cluster.dead_after(tight) >= cluster.stalled_after(tight)


def test_arm_deadlines_merge():
    base = {"collective": 1800.0, "step": 300.0}
    off = MAMLConfig()
    assert cluster.arm_deadlines(off, base) == base
    on = off.replace(cluster_collective_timeout_s=10.0)
    armed = cluster.arm_deadlines(on, base)
    assert armed["collective"] == pytest.approx(10.0)
    assert armed["step"] == pytest.approx(300.0)  # untouched
    # A watchdog collective deadline of 0 (disabled) still gets armed —
    # the cluster budget is what turns the phase on.
    assert cluster.arm_deadlines(on, {"collective": 0.0})["collective"] \
        == pytest.approx(10.0)
    # A TIGHTER generic deadline is kept (the cluster path then never
    # claims the earlier generic trip — owns_trip below).
    assert cluster.arm_deadlines(on, {"collective": 5.0})["collective"] \
        == pytest.approx(5.0)


def test_kill_peer_fault_kind_parses():
    plan = FaultPlan.parse("kill_peer@6")
    assert "kill_peer" in faults.KINDS
    assert plan.maybe_fire("kill_peer", step=6)
    assert not plan.maybe_fire("kill_peer", step=6)  # at most once


# ---------------------------------------------------------------------------
# monitor (pure deadline math)
# ---------------------------------------------------------------------------

def test_monitor_classification_boundaries():
    mon = ClusterMonitor(stalled_after_s=2.0, dead_after_s=10.0)
    assert mon.classify(0.0) == cluster.LIVE
    assert mon.classify(2.0) == cluster.LIVE       # inclusive boundary
    assert mon.classify(2.01) == cluster.STALLED
    assert mon.classify(10.0) == cluster.STALLED   # inclusive boundary
    assert mon.classify(10.01) == cluster.DEAD
    assert mon.classify(math.inf) == cluster.DEAD  # missing lease
    # Clock skew: a lease "from the future" reads as fresh, never dead.
    assert mon.classify(-5.0) == cluster.LIVE
    with pytest.raises(ValueError):
        ClusterMonitor(stalled_after_s=0.0, dead_after_s=10.0)
    with pytest.raises(ValueError):
        ClusterMonitor(stalled_after_s=10.0, dead_after_s=2.0)


def test_monitor_suspects_exclude_self_and_prefer_dead():
    mon = ClusterMonitor(stalled_after_s=2.0, dead_after_s=10.0,
                         self_index=0)
    # Self is stalled too (it is blocked in the stranded collective) —
    # it must never blame itself.
    ages = {0: 5.0, 1: 12.0, 2: 4.0, 3: 30.0}
    assert mon.check(ages)[0] == cluster.STALLED
    assert mon.suspects(ages) == [3, 1]  # dead peers only, oldest first
    # No dead peers: the stalled ones are the suspects.
    assert mon.suspects({0: 5.0, 1: 4.0, 2: 0.1}) == [1]
    # Every peer live: the leases exonerate them (a genuine hang).
    assert mon.suspects({0: 50.0, 1: 0.1, 2: 0.2}) == []


# ---------------------------------------------------------------------------
# heartbeat leases
# ---------------------------------------------------------------------------

def test_lease_write_read_roundtrip(tmp_path):
    lease_dir = str(tmp_path / "cluster")
    lease = HeartbeatLease(lease_dir, process_index=0, interval_s=60.0)
    assert lease.touch(detail="epoch_0") is True
    assert os.path.isfile(lease.path)
    # Advisory payload is readable JSON naming the host.
    assert json.load(open(lease.path))["host"] == 0
    # Rate-limited: an immediate second touch is a no-op...
    assert lease.touch() is False
    # ...unless forced (the per-epoch heartbeat path).
    assert lease.touch(force=True) is True
    assert lease.touches == 2

    ages = cluster.read_lease_ages(lease_dir)
    assert set(ages) == {0} and ages[0] < 30.0
    # A stale peer lease reads as old; an expected-but-absent host
    # reads as inf (dead) — absence on shared storage IS the signal.
    peer = cluster.lease_path(lease_dir, 1)
    with open(peer, "w") as f:
        f.write("{}")
    past = time.time() - 120.0
    os.utime(peer, (past, past))
    # A FAILED write must not consume the rate-limit window: with the
    # lease "dir" shadowed by a file, touch fails — and the very next
    # call (not one interval later) retries.
    broken = HeartbeatLease(str(tmp_path / "shadow"), 0, interval_s=60.0)
    with open(str(tmp_path / "shadow"), "w") as f:
        f.write("not a directory")
    assert broken.touch() is False and broken.errors == 1
    assert broken.touch() is False and broken.errors == 2  # retried NOW

    ages = cluster.read_lease_ages(lease_dir, expected_hosts=3)
    assert 100.0 < ages[1] < 200.0
    assert ages[2] == math.inf
    # An orphan lease from a previous LARGER pod geometry is dropped
    # when the pod size is known — it must not top every suspect list
    # as a permanently-dead host.
    orphan = cluster.lease_path(lease_dir, 7)
    with open(orphan, "w") as f:
        f.write("{}")
    os.utime(orphan, (past, past))
    assert 7 not in cluster.read_lease_ages(lease_dir, expected_hosts=2)
    assert 7 in cluster.read_lease_ages(lease_dir)  # size unknown: kept
    # Fail-soft: a missing directory degrades to expected-hosts-only.
    assert cluster.read_lease_ages(str(tmp_path / "nope")) == {}


# ---------------------------------------------------------------------------
# consensus resume
# ---------------------------------------------------------------------------

def test_host_int_lanes_roundtrip_exactly():
    """The agreement collectives ship ints as two int32 lanes: without
    x64, an int64 array is canonicalized to int32 and any value past
    2^31 — half of all checkpoint fingerprints — silently wraps, making
    every host 'disagree' with its own broadcast (found live by
    chaos_pod's restart phase)."""
    from howtotrainyourmamlpytorch_tpu.parallel.multihost import (
        _decode_i64, _encode_i64)
    values = [0, -1, 1, 2**31 - 1, 2**31, 3562112061, 2**63 - 1,
              -(2**63)]
    encoded = _encode_i64(values)
    assert encoded.dtype.name == "int32"  # survives canonicalization
    assert list(_decode_i64(encoded)) == values
    # The gathered form (one row per host) decodes the same way.
    import numpy as np
    stacked = np.stack([_encode_i64([v]) for v in values])
    assert list(_decode_i64(stacked)) == values


def test_consensus_epoch_math():
    assert cluster.consensus_epoch([5, 3, 4]) == 3
    # A stale/damaged view (-1) adopts the peers' verdict instead of
    # dragging the cluster to a fresh start.
    assert cluster.consensus_epoch([5, -1, 3]) == 3
    assert cluster.consensus_epoch([-1, -1]) == -1
    assert cluster.consensus_epoch([]) == -1
    assert cluster.consensus_epoch([0]) == 0


def test_latest_committed_epoch_with_stale_manifest(tmp_path):
    from howtotrainyourmamlpytorch_tpu.ckpt import manifest as manifest_mod
    # "Fresh" host: epochs 0 and 1 committed, epoch 2 stranded pending,
    # plus the 'latest' link record (which must NOT count — consensus
    # is over epoch snapshots every host can load by tag).
    fresh_dir = str(tmp_path / "fresh")
    os.makedirs(fresh_dir)
    fresh = manifest_mod.Manifest(fresh_dir)
    for epoch in (0, 1):
        fresh.begin(str(epoch), epoch=epoch, iteration=4 * (epoch + 1))
        fresh.commit(str(epoch), nbytes=10, crc=1)
    fresh.begin("latest", iteration=8)
    fresh.commit("latest", nbytes=10, crc=1)
    fresh.begin("2", epoch=2, iteration=12)  # torn write: never commits
    assert cluster.latest_committed_epoch(fresh) == 1

    # Stale host: its MANIFEST.json view predates epoch 1's commit.
    stale_dir = str(tmp_path / "stale")
    os.makedirs(stale_dir)
    stale = manifest_mod.Manifest(stale_dir)
    stale.begin("0", epoch=0, iteration=4)
    stale.commit("0", nbytes=10, crc=1)
    assert cluster.latest_committed_epoch(stale) == 0

    # Damaged host: no readable manifest at all.
    empty = manifest_mod.Manifest(str(tmp_path / "empty"))
    assert cluster.latest_committed_epoch(empty) == -1

    # The cluster agrees on the minimum committed view — the one every
    # host can provably load; the damaged host adopts it.
    views = [cluster.latest_committed_epoch(m)
             for m in (fresh, stale, empty)]
    assert cluster.consensus_epoch(views) == 0


# ---------------------------------------------------------------------------
# trip path (peer_lost row + exit-73 plumbing, injectable on_trip)
# ---------------------------------------------------------------------------

def _domain(tmp_path, **kw):
    reg = MetricsRegistry()
    jsonl = JsonlLogger(str(tmp_path / "events.jsonl"))
    base = dict(
        lease_dir=str(tmp_path / "cluster"), process_index=0,
        num_processes=2, collective_timeout_s=10.0,
        stalled_after_s=2.0, dead_after_s=10.0, lease_interval_s=0.1,
        registry=reg, jsonl=jsonl,
        bundle_dir=str(tmp_path / "crash_bundle"),
        prom_path=str(tmp_path / "metrics.prom"))
    base.update(kw)
    return ClusterFaultDomain(**base), reg, jsonl


def test_watchdog_trip_delegates_to_peer_lost(tmp_path):
    trips = []
    domain, reg, jsonl = _domain(tmp_path, on_trip=trips.append)
    rec = flightrec.FlightRecorder(32)
    flightrec.install(rec)
    # Fresh own lease; peer 1's lease is 2 minutes stale — dead.
    domain.heartbeat(force=True)
    peer = cluster.lease_path(domain.lease.lease_dir, 1)
    with open(peer, "w") as f:
        f.write("{}")
    past = time.time() - 120.0
    os.utime(peer, (past, past))

    b = ProgressBeacon()
    b.stamp("collective", detail="any_process_true_each")
    wd = Watchdog(b, {"collective": 10.0},
                  bundle_dir=str(tmp_path / "wd_bundle"),
                  registry=reg, jsonl=jsonl, cluster=domain)
    info = wd.check(now=b.current()[1] + 12.0)
    assert info is not None and info["phase"] == "collective"
    assert domain.owns_trip(info)
    wd.trip(info)

    # The injected action ran INSTEAD of os._exit, with attribution.
    assert len(trips) == 1
    row = trips[0]
    assert row["suspect_hosts"] == [1]
    assert row["peer_verdicts"]["1"] == cluster.DEAD
    # peer_lost row in events.jsonl + counter + registry flush.
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    lost = [e for e in events if e["event"] == "peer_lost"]
    assert len(lost) == 1 and lost[0]["suspect_hosts"] == [1]
    assert lost[0]["peer_lease_age_seconds"]["1"] > 100.0
    assert reg.counter(cluster.PEER_LOSSES_COUNTER).value == 1
    metric_rows = [e for e in events if e["event"] == "metrics"]
    assert metric_rows[-1]["metrics"]["cluster/peer_losses"] == 1
    # No generic watchdog_trip row: the cluster path OWNED the trip.
    assert not [e for e in events if e["event"] == "watchdog_trip"]
    # Crash bundle written with the peer_lost reason + the flight ring
    # carrying the peer_lost record.
    crash = json.load(open(os.path.join(str(tmp_path / "crash_bundle"),
                                        "crash.json")))
    assert crash["reason"] == "peer_lost"
    assert crash["suspect_hosts"] == [1]
    assert any(e["kind"] == "peer_lost" for e in rec.events())
    assert "peer_losses 1" in open(str(tmp_path / "metrics.prom")).read()


def test_generic_collective_trip_below_cluster_budget_stays_hung(tmp_path):
    """A tighter generic collective deadline tripping EARLIER than the
    cluster budget is a plain hang (74-path forensics): no peer gets
    blamed below the cluster's bar."""
    domain, reg, jsonl = _domain(tmp_path, collective_timeout_s=100.0)
    b = ProgressBeacon()
    b.stamp("collective", detail="barrier:x")
    wd_trips = []
    wd = Watchdog(b, {"collective": 5.0},
                  bundle_dir=str(tmp_path / "wd_bundle"),
                  registry=reg, jsonl=jsonl, cluster=domain,
                  on_trip=wd_trips.append)
    info = wd.check(now=b.current()[1] + 6.0)
    assert not domain.owns_trip(info)
    # Ownership is decided by the BINDING deadline, not the observed
    # age: poll overshoot can first observe a generic-deadline trip at
    # an age past the cluster budget, and that must stay a hang.
    late = dict(info, age_seconds=domain.collective_timeout_s + 5.0)
    assert not domain.owns_trip(late)
    assert domain.owns_trip(dict(late,
                                 deadline_seconds=domain
                                 .collective_timeout_s))
    wd.trip(info)
    assert wd_trips == [info]  # the ORDINARY watchdog action ran
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    assert [e["event"] for e in events if e["event"] in
            ("watchdog_trip", "peer_lost")] == ["watchdog_trip"]


def test_second_trip_escalates_straight_to_exit(tmp_path):
    """The ISSUE 9 bugfix pin: a second trip of the collective deadline
    while the first is still draining (or the armed backstop firing)
    must escalate straight to os._exit(EXIT_PEER_LOST) — no second
    bundle, no second row, nothing that can wedge."""
    exits = []
    domain, reg, jsonl = _domain(tmp_path)
    domain._exit = exits.append  # record instead of dying
    info = {"phase": "collective", "detail": "gather_host_floats",
            "age_seconds": 12.0, "deadline_seconds": 10.0,
            "process_index": 0}
    domain.trip_peer_lost(info)
    # First trip: full drain, then the (injected) exit with 73.
    assert exits == [resilience.EXIT_PEER_LOST]
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    assert sum(e["event"] == "peer_lost" for e in events) == 1

    domain.trip_peer_lost(info)  # the drain-window re-entry
    assert exits == [resilience.EXIT_PEER_LOST] * 2
    # Straight to exit: no second row, no second flush, counted.
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    assert sum(e["event"] == "peer_lost" for e in events) == 1
    assert reg.counter(cluster.ESCALATIONS_COUNTER).value == 1
    domain.close()


def test_backstop_timer_escalates_a_wedged_drain(tmp_path):
    """The first trip arms a backstop timer sized to the collective
    budget; if the drain wedges, the timer re-enters and takes the
    escalation branch — the survivor can never hang forever."""
    exits = []
    domain, _, _ = _domain(tmp_path, collective_timeout_s=0.2,
                           jsonl=None, bundle_dir=None, prom_path=None)
    domain._exit = exits.append
    domain.trip_peer_lost({"phase": "collective", "age_seconds": 1.0})
    assert domain._backstop is not None
    deadline = time.monotonic() + 5.0
    while len(exits) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    # First exit: the trip's own (injected, returned); second: the
    # backstop's escalation.
    assert len(exits) >= 2
    domain.close()


def test_collective_error_converts_to_peer_lost(tmp_path):
    """A transport error escaping a collective scope (a dead peer on a
    transport that detects the closed connection) routes through the
    SAME attributed abort, then re-raises for the injected-action
    case."""
    from howtotrainyourmamlpytorch_tpu.parallel import multihost
    trips = []
    domain, _, jsonl = _domain(tmp_path, on_trip=trips.append)
    cluster.install(domain)
    with pytest.raises(RuntimeError, match="connection reset"):
        with multihost._collective("gather_host_floats"):
            raise RuntimeError("connection reset by peer")
    assert len(trips) == 1
    assert trips[0]["detail"] == "gather_host_floats"
    assert "connection reset" in trips[0]["error"]
    events = read_jsonl(str(tmp_path / "events.jsonl"))
    assert sum(e["event"] == "peer_lost" for e in events) == 1

    # Single-process domains never claim an error (no peer to lose).
    solo, _, _ = _domain(tmp_path / "solo", num_processes=1,
                         on_trip=trips.append)
    cluster.install(solo)
    with pytest.raises(ValueError):
        with multihost._collective("x"):
            raise ValueError("not a transport error")
    assert len(trips) == 1  # unchanged

    # No domain installed: plain raise, no side effects (one None check).
    cluster.install(None)
    with pytest.raises(ValueError):
        with multihost._collective("x"):
            raise ValueError("boom")


def test_unattributed_collective_error_propagates(tmp_path):
    """When the (grace-re-read) leases exonerate every peer, an error
    inside a collective is an APPLICATION failure: it must propagate as
    itself — converting it to exit 73 would loop a deterministic bug
    through infinite whole-job restarts. Counted, never silent."""
    from howtotrainyourmamlpytorch_tpu.parallel import multihost
    trips = []
    # Tight collective budget keeps the grace re-read sub-second.
    domain, reg, jsonl = _domain(tmp_path, on_trip=trips.append,
                                 collective_timeout_s=1.0)
    # BOTH hosts' leases fresh: nobody is dead or stalled.
    domain.heartbeat(force=True)
    with open(cluster.lease_path(domain.lease.lease_dir, 1), "w") as f:
        f.write("{}")
    cluster.install(domain)
    with pytest.raises(RuntimeError, match="app bug"):
        with multihost._collective("agree_int_from_main"):
            raise RuntimeError("app bug, not a dead peer")
    assert trips == []  # no peer-lost conversion
    assert domain.tripped is None
    # Nothing was logged at all: the lazily-created events.jsonl never
    # came into existence because no peer_lost row was written.
    assert not os.path.exists(tmp_path / "events.jsonl")
    assert reg.counter(
        "cluster/unattributed_collective_errors").value == 1


# ---------------------------------------------------------------------------
# wiring structure (the watchdog install-iff-enabled pattern)
# ---------------------------------------------------------------------------

def test_run_installs_cluster_iff_enabled(tmp_path, monkeypatch):
    """Structural half of the acceptance pin: with every cluster knob at
    its 0/off default a run installs NO fault domain (each hook site
    stays a single None check); with the deadline set it installs the
    domain + lease for the run's duration, arms the watchdog's
    collective budget, and restores process state after. The training-
    parity half is the slow bitwise test in test_pod_cluster.py."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    seen = {}

    def probe(builder):
        def stub():
            seen["domain"] = cluster.get()
            seen["builder_domain"] = builder._cluster
            seen["watchdog"] = builder._watchdog
            return {"paused_at_iter": builder.current_iter}
        return stub

    builder = ExperimentBuilder(_cfg(tmp_path / "off"))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert seen["domain"] is None and seen["builder_domain"] is None

    builder = ExperimentBuilder(_cfg(tmp_path / "on",
                                     cluster_collective_timeout_s=30.0))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert isinstance(seen["builder_domain"], ClusterFaultDomain)
    assert seen["domain"] is seen["builder_domain"]
    assert seen["watchdog"].cluster is seen["builder_domain"]
    # The watchdog's collective budget was tightened to the cluster's.
    assert seen["watchdog"].deadlines["collective"] == pytest.approx(30.0)
    # The lease exists from t0 under <experiment>/cluster/.
    lease = os.path.join(str(tmp_path / "on"), "smoke", "cluster",
                         "host_0.lease")
    assert os.path.isfile(lease)
    # Scoped lifetime: restored after the run.
    assert cluster.get() is None and builder._cluster is None

    # Cluster deadline alone (all watchdog knobs 0) still arms the
    # watchdog thread — it is what enforces the collective budget.
    off = {f: 0.0 for f in (
        "watchdog_step_timeout_s", "watchdog_feed_timeout_s",
        "watchdog_collective_timeout_s", "watchdog_compile_timeout_s",
        "watchdog_serve_timeout_s", "watchdog_ckpt_timeout_s")}
    builder = ExperimentBuilder(_cfg(tmp_path / "armed",
                                     cluster_collective_timeout_s=30.0,
                                     **off))
    monkeypatch.setattr(builder, "_run_experiment", probe(builder))
    builder.run_experiment()
    assert seen["watchdog"] is not None and seen["watchdog"].enabled
    assert seen["watchdog"].deadlines["collective"] == pytest.approx(30.0)


def test_require_mesh_makes_geometry_fallback_fatal(tmp_path):
    """VERDICT weakness #6 pin: a pod profile must fail loudly when its
    mesh cannot be realized, not silently train on one device."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder

    # 16 devices do not exist on this 8-device test mesh.
    with pytest.raises(ValueError, match="require_mesh"):
        ExperimentBuilder(_cfg(tmp_path / "strict", mesh_shape=(1, 16),
                               require_mesh=1))
    # Default keeps the documented warn-and-fallback behavior.
    with pytest.warns(UserWarning, match="falling back"):
        builder = ExperimentBuilder(_cfg(tmp_path / "lax",
                                         mesh_shape=(1, 16)))
    assert builder.cfg.mesh_shape == (1, 1)


def test_cluster_run_end_to_end_heartbeats_and_report(tmp_path):
    """One tiny real run with the fault domain armed (nothing trips):
    heartbeat rows carry the per-host lease ages, the lease file is
    maintained, and the telemetry report renders the v8 cluster section
    with measured zeros."""
    from test_experiment import _cfg
    from howtotrainyourmamlpytorch_tpu.experiment import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.telemetry import summarize_events

    builder = ExperimentBuilder(_cfg(
        tmp_path, cluster_collective_timeout_s=300.0,
        cluster_lease_interval_s=0.05, dispatch_sync_every=1))
    result = builder.run_experiment()
    assert "test_accuracy_mean" in result  # ran to completion
    lease = os.path.join(str(tmp_path), "smoke", "cluster",
                         "host_0.lease")
    assert os.path.isfile(lease)
    events = read_jsonl(os.path.join(builder.paths["logs"],
                                     "events.jsonl"))
    beats = [e for e in events if e.get("event") == "heartbeat"]
    assert beats
    for beat in beats:
        ages = beat["peer_lease_age_seconds"]
        assert set(ages) == {"0"} and ages["0"] < 60.0
    cl = summarize_events(events)["cluster"]
    assert cl["peer_losses"] == 0  # measured zero, not omitted
    assert cl["max_peer_lease_age_seconds"] < 60.0
    assert not [e for e in events if e.get("event") == "peer_lost"]

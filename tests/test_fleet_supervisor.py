"""Self-healing fleet units: supervisor, breakers, shedding, failover.

Everything the chaos suite (scripts/chaos_fleet.py) proves end-to-end
is pinned here at unit granularity, clock-in and process-free: the
crash-loop window math, every supervisor slot transition (spawn ->
running -> crash/backoff -> FAILED -> spare backfill, scale up/down
drain -> reap, lease-dead kill, start-timeout kill), the per-replica
circuit breaker's three-state cycle, bounded failover, and the
admission controller's shed policies (deadline + liveness floor,
fair share). ``spawn_fn`` injection means no sockets and no real
processes — the whole file runs in milliseconds, so it is tier-1.

The two exceptions: a subprocess proof that supervisor.py stays
loadable with ZERO third-party imports (the jax-free driver
discipline), and the ``slow``-marked chaos --quick acceptance run
(real replicas over localhost, several minutes — tier-1 sits at ~660s
of the 870s driver budget and must not grow past it).
"""

import itertools
import json
import os
import random
import subprocess
import sys
import time

import pytest

from howtotrainyourmamlpytorch_tpu.serve.batcher import (
    AdmissionController, ShedError, estimate_queue_wait)
from howtotrainyourmamlpytorch_tpu.serve.fleet import router as fr
from howtotrainyourmamlpytorch_tpu.serve.fleet import (
    supervisor as fsup)
from howtotrainyourmamlpytorch_tpu.serve.fleet.router import (
    FailoverPolicy, FleetRouter, ReplicaBreaker, ReplicaLease)
from howtotrainyourmamlpytorch_tpu.serve.fleet.supervisor import (
    CrashLoopBreaker, ReplicaSupervisor)
from helpers import _can_bind_localhost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHAOS_FLEET = os.path.join(REPO, "scripts", "chaos_fleet.py")
SUPERVISOR_PY = os.path.join(
    REPO, "howtotrainyourmamlpytorch_tpu", "serve", "fleet",
    "supervisor.py")


# ---------------------------------------------------------------------------
# test doubles
# ---------------------------------------------------------------------------

class _Counter:
    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n


class _Gauge:
    def __init__(self):
        self.value = 0

    def set(self, v):
        self.value = v


class _Reg:
    """Duck-typed MetricsRegistry (counter/gauge get-or-create) — the
    supervisor/router contract, without importing telemetry."""

    def __init__(self):
        self.counters = {}
        self.gauges = {}

    def counter(self, name):
        return self.counters.setdefault(name, _Counter())

    def gauge(self, name):
        return self.gauges.setdefault(name, _Gauge())


class FakeProc:
    """The injectable spawn_fn contract: poll/pid/terminate/kill."""

    _pids = itertools.count(4000)

    def __init__(self):
        self.pid = next(FakeProc._pids)
        self.exit_code = None
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.exit_code

    def terminate(self):
        self.terminated = True
        self.exit_code = 0

    def kill(self):
        self.killed = True
        self.exit_code = -9

    def exit(self, code):
        self.exit_code = code


def _touch_lease(fleet_dir, slot, *, queue_depth=0, age_s=0.0, pid=None):
    """Write slot's lease as a live replica would, optionally aged."""
    lease = ReplicaLease(str(fleet_dir), slot, 0.0)
    assert lease.touch(payload={
        "port": 7000 + slot, "pid": pid if pid is not None else 4000,
        "stats": {"queue_depth": queue_depth}}, force=True)
    if age_s:
        past = time.time() - age_s
        os.utime(lease.path, (past, past))
    return lease.path


def _mk_sup(fleet_dir, spawned, registry=None, events_path=None, **kw):
    def spawn(slot):
        proc = FakeProc()
        spawned.setdefault(slot, []).append(proc)
        return proc
    kw.setdefault("rng", random.Random(0))
    return ReplicaSupervisor(str(fleet_dir), spawn, registry=registry,
                             events_path=events_path, **kw)


# ---------------------------------------------------------------------------
# CrashLoopBreaker
# ---------------------------------------------------------------------------

def test_crash_loop_breaker_window_math():
    br = CrashLoopBreaker(max_restarts=3, window_s=10.0)
    assert br.record_restart(0, 0.0) is False
    assert br.record_restart(0, 1.0) is False
    # Third restart inside the window exhausts the budget.
    assert br.record_restart(0, 2.0) is True
    assert br.restarts_in_window(0, 2.0) == 3
    # The deque prunes itself: 11s later only the t=2 entry survives,
    # so a fresh restart is the second in window — no trip.
    assert br.restarts_in_window(0, 11.5) == 1
    assert br.record_restart(0, 11.5) is False
    # Slots are independent; reset clears one slot's history only.
    assert br.record_restart(1, 11.5) is False
    br.reset(0)
    assert br.restarts_in_window(0, 11.5) == 0
    assert br.restarts_in_window(1, 11.5) == 1


def test_crash_loop_breaker_validation():
    with pytest.raises(ValueError):
        CrashLoopBreaker(max_restarts=0)
    with pytest.raises(ValueError):
        CrashLoopBreaker(window_s=0.0)


# ---------------------------------------------------------------------------
# ReplicaSupervisor slot lifecycle
# ---------------------------------------------------------------------------

def test_supervisor_spawn_to_running(tmp_path):
    spawned, reg = {}, _Reg()
    events = tmp_path / "events.jsonl"
    sup = _mk_sup(tmp_path / "fleet", spawned, registry=reg,
                  events_path=str(events), desired=2, scale_max=4)
    t0 = time.time()
    states = sup.tick(t0)
    assert states[0] == fsup.STARTING and states[1] == fsup.STARTING
    assert states[2] == fsup.EMPTY and states[3] == fsup.EMPTY
    assert set(spawned) == {0, 1}
    # Replicas announce (live lease with a port) -> RUNNING.
    _touch_lease(tmp_path / "fleet", 0)
    _touch_lease(tmp_path / "fleet", 1)
    states = sup.tick(t0 + 0.1)
    assert states[0] == fsup.RUNNING and states[1] == fsup.RUNNING
    assert sup.count(fsup.RUNNING) == 2
    assert reg.gauges[fsup.DESIRED_GAUGE].value == 2
    kinds = [json.loads(ln)["kind"]
             for ln in events.read_text().splitlines()]
    assert kinds.count("spawn") == 2 and kinds.count("running") == 2


def test_supervisor_crash_restarts_same_slot_after_backoff(tmp_path):
    spawned, reg = {}, _Reg()
    sup = _mk_sup(tmp_path / "fleet", spawned, registry=reg,
                  desired=2, scale_max=4, backoff_base_s=0.05,
                  backoff_cap_s=2.0)
    t0 = time.time()
    sup.tick(t0)
    lease0 = _touch_lease(tmp_path / "fleet", 0)
    _touch_lease(tmp_path / "fleet", 1)
    sup.tick(t0 + 0.1)
    spawned[0][0].exit(1)
    states = sup.tick(t0 + 0.2)
    assert states[0] == fsup.EMPTY
    assert reg.counters[fsup.RESTARTS_COUNTER].value == 1
    # The stale lease is removed NOW (the router must stop routing to
    # the dead port immediately, not when the lease ages out).
    assert not os.path.exists(lease0)
    delay = sup.slots[0]["next_spawn_at"] - (t0 + 0.2)
    assert 0.05 <= delay <= 0.075  # base * U[1, 1.5] jitter, attempt 0
    # Inside the backoff the slot is RESERVED capacity: no spare slot
    # is spawned over it (identity churn on every crash otherwise).
    states = sup.tick(t0 + 0.21)
    assert states[0] == fsup.EMPTY and states[2] == fsup.EMPTY
    assert len(spawned[0]) == 1 and 2 not in spawned
    # Past the backoff the SAME slot respawns.
    states = sup.tick(t0 + 0.2 + delay + 0.01)
    assert states[0] == fsup.STARTING
    assert len(spawned[0]) == 2 and 2 not in spawned


def test_supervisor_crash_loop_fails_slot_and_backfills_spare(tmp_path):
    spawned, reg = {}, _Reg()
    events = tmp_path / "events.jsonl"
    sup = _mk_sup(tmp_path / "fleet", spawned, registry=reg,
                  events_path=str(events), desired=1, scale_max=2,
                  max_restarts=2, restart_window_s=60.0,
                  backoff_base_s=0.01, backoff_cap_s=0.02)
    t0 = time.time()
    sup.tick(t0)
    spawned[0][0].exit(1)
    sup.tick(t0 + 1.0)  # restart scheduled (1st in window)
    sup.tick(t0 + 2.0)  # past backoff: respawn slot 0
    assert len(spawned[0]) == 2
    spawned[0][1].exit(1)
    # Second crash in window == max_restarts: the slot trips FAILED,
    # and — same tick — the spare slot backfills (FAILED is not
    # reserved capacity; a poisoned slot earns a replacement).
    states = sup.tick(t0 + 3.0)
    assert states[0] == fsup.FAILED
    assert states[1] == fsup.STARTING
    assert reg.counters[fsup.CRASH_LOOPS_COUNTER].value == 1
    assert reg.counters[fsup.RESTARTS_COUNTER].value == 1
    # FAILED is sticky across ticks until an operator re-arms it.
    assert sup.tick(t0 + 4.0)[0] == fsup.FAILED
    sup.reset_slot(0)
    assert sup.slots[0]["state"] == fsup.EMPTY
    kinds = [json.loads(ln)["kind"]
             for ln in events.read_text().splitlines()]
    assert "crash_loop" in kinds


def test_supervisor_scale_up_then_drain_scale_down(tmp_path):
    spawned, reg = {}, _Reg()
    fleet = tmp_path / "fleet"
    sup = _mk_sup(fleet, spawned, registry=reg, desired=1,
                  scale_max=3, drain_grace_s=0.0)
    t0 = time.time()
    sup.tick(t0)
    _touch_lease(fleet, 0)
    sup.tick(t0 + 0.1)
    # advise() says scale_up: desired moves, the next slot spawns.
    states = sup.tick(t0 + 0.2, advice="scale_up")
    assert sup.desired == 2 and states[1] == fsup.STARTING
    assert reg.counters[fsup.SCALE_UPS_COUNTER].value == 1
    _touch_lease(fleet, 1)
    sup.tick(t0 + 0.3)
    # scale_down drains the HIGHEST running slot: tombstone written,
    # slot leaves active immediately (the router stops routing to it).
    states = sup.tick(t0 + 0.4, advice="scale_down")
    assert sup.desired == 1 and states[1] == fsup.DRAINING
    assert reg.counters[fsup.SCALE_DOWNS_COUNTER].value == 1
    drain = fr.drain_path(str(fleet), 1)
    assert os.path.exists(drain)
    # Queue empty + grace over -> SIGTERM -> reaped (files removed).
    _touch_lease(fleet, 1, queue_depth=0)
    sup.tick(t0 + 0.5)
    assert spawned[1][0].terminated
    states = sup.tick(t0 + 0.6)
    assert states[1] == fsup.EMPTY
    assert not os.path.exists(drain)
    assert not os.path.exists(fr.lease_path(str(fleet), 1))
    # Desired is clamped: scale_down at scale_min is a no-op.
    sup.tick(t0 + 0.7, advice="scale_down")
    assert sup.desired == 1
    assert reg.counters[fsup.SCALE_DOWNS_COUNTER].value == 1


def test_supervisor_kills_lease_dead_replica(tmp_path):
    spawned, reg = {}, _Reg()
    fleet = tmp_path / "fleet"
    sup = _mk_sup(fleet, spawned, registry=reg, desired=1, scale_max=2,
                  stalled_after_s=1.5, dead_after_s=3.0)
    t0 = time.time()
    sup.tick(t0)
    _touch_lease(fleet, 0)
    assert sup.tick(t0 + 0.1)[0] == fsup.RUNNING
    # Process alive, heartbeat gone 10s: the one failure poll() cannot
    # see. The supervisor kills it; the exit surfaces as a crash.
    _touch_lease(fleet, 0, age_s=10.0)
    sup.tick(t0 + 0.2)
    assert spawned[0][0].killed
    states = sup.tick(t0 + 0.3)
    assert states[0] == fsup.EMPTY
    assert reg.counters[fsup.RESTARTS_COUNTER].value == 1


def test_supervisor_start_timeout_kill(tmp_path):
    spawned = {}
    sup = _mk_sup(tmp_path / "fleet", spawned, desired=1, scale_max=2,
                  start_timeout_s=0.5)
    t0 = time.time()
    sup.tick(t0)
    # Never announces a lease: wedged before serving.
    sup.tick(t0 + 1.0)
    assert spawned[0][0].killed
    assert sup.tick(t0 + 1.1)[0] == fsup.EMPTY


def test_supervisor_spawn_failure_counts_as_crash(tmp_path):
    calls = []

    def bad_spawn(slot):
        calls.append(slot)
        raise OSError("fork bomb averted")

    reg = _Reg()
    sup = ReplicaSupervisor(str(tmp_path / "fleet"), bad_spawn,
                            registry=reg, desired=1, scale_max=2,
                            max_restarts=2, restart_window_s=60.0,
                            rng=random.Random(0))
    t0 = time.time()
    states = sup.tick(t0)
    assert calls == [0]
    assert states[0] == fsup.EMPTY
    assert sup.slots[0]["next_spawn_at"] > t0
    assert reg.counters[fsup.RESTARTS_COUNTER].value == 1


def test_supervisor_flush_metrics_row_shape(tmp_path):
    spawned, reg = {}, _Reg()
    events = tmp_path / "events.jsonl"
    sup = _mk_sup(tmp_path / "fleet", spawned, registry=reg,
                  events_path=str(events), desired=1, scale_max=2)
    t0 = time.time()
    sup.tick(t0)
    sup.flush_metrics(t0 + 1.0)
    rows = [json.loads(ln) for ln in events.read_text().splitlines()]
    metrics = [r for r in rows if r["event"] == "metrics"]
    assert len(metrics) == 1
    row = metrics[0]
    # The registry.flush_jsonl shape: snapshot nested under "metrics",
    # source identity under "replica" — telemetry/report.py's
    # fleet-health section folds this row like any replica's flush.
    assert row["replica"] == "supervisor"
    snap = row["metrics"]
    for name in (fsup.RESTARTS_COUNTER, fsup.CRASH_LOOPS_COUNTER,
                 fsup.SCALE_UPS_COUNTER, fsup.SCALE_DOWNS_COUNTER,
                 fsup.DESIRED_GAUGE):
        assert name in snap
    assert snap[fsup.RESTARTS_COUNTER] == 0
    assert snap[fsup.DESIRED_GAUGE] == 1


def test_supervisor_stop_terminates_and_cleans(tmp_path):
    spawned = {}
    fleet = tmp_path / "fleet"
    sup = _mk_sup(fleet, spawned, desired=2, scale_max=2)
    t0 = time.time()
    sup.tick(t0)
    lease0 = _touch_lease(fleet, 0)
    lease1 = _touch_lease(fleet, 1)
    sup.tick(t0 + 0.1)
    sup.stop(kill_after_s=1.0)
    assert spawned[0][0].terminated and spawned[1][0].terminated
    assert sup.count(fsup.EMPTY) == 2
    assert not os.path.exists(lease0) and not os.path.exists(lease1)


def test_supervisor_validation():
    with pytest.raises(ValueError):
        ReplicaSupervisor("/tmp/x", lambda s: None, scale_min=0)
    with pytest.raises(ValueError):
        ReplicaSupervisor("/tmp/x", lambda s: None, scale_min=2,
                          scale_max=1)
    # desired clamps into [scale_min, scale_max] rather than raising.
    sup = ReplicaSupervisor("/tmp/x", lambda s: None, desired=9,
                            scale_min=1, scale_max=3)
    assert sup.desired == 3


# ---------------------------------------------------------------------------
# ReplicaBreaker + router integration + failover
# ---------------------------------------------------------------------------

def test_replica_breaker_full_cycle():
    br = ReplicaBreaker(threshold=2, cooldown_s=1.0)
    assert br.state(7, 0.0) == fr.BREAKER_CLOSED
    assert br.record_failure(7, 0.0) is False
    assert br.record_failure(7, 0.1) is True  # the countable trip
    assert br.state(7, 0.5) == fr.BREAKER_OPEN
    assert not br.allows(7, 0.5)
    # Cooldown elapsed: OPEN reads HALF_OPEN, ONE probe allowed.
    assert br.state(7, 1.2) == fr.BREAKER_HALF_OPEN
    assert br.allows(7, 1.2)
    br.begin_probe(7)
    assert not br.allows(7, 1.2)  # probe outstanding
    # Probe fails: re-open with a fresh cooldown, NOT a new trip.
    assert br.record_failure(7, 1.3) is False
    assert br.state(7, 1.5) == fr.BREAKER_OPEN
    # Next half-open probe succeeds: record cleared, fully CLOSED.
    br.begin_probe(7)
    assert br.state(7, 2.4) == fr.BREAKER_HALF_OPEN
    br.begin_probe(7)
    br.record_success(7)
    assert br.snapshot() == {}
    assert br.state(7, 2.5) == fr.BREAKER_CLOSED


def test_replica_breaker_validation():
    with pytest.raises(ValueError):
        ReplicaBreaker(threshold=0)
    with pytest.raises(ValueError):
        ReplicaBreaker(cooldown_s=0.0)


def _routable_router(tmp_path, reg, **kw):
    fleet = str(tmp_path / "fleet")
    for slot in (0, 1):
        _touch_lease(tmp_path / "fleet", slot, pid=5000 + slot)
    router = FleetRouter(fleet, registry=reg, **kw)
    router.refresh()
    return router


def test_router_excludes_tripped_replica_until_success(tmp_path):
    reg = _Reg()
    router = _routable_router(tmp_path, reg, breaker_threshold=1,
                              breaker_cooldown_s=60.0)
    assert sorted(router.routable) == [0, 1]
    assert router.record_failure(0) is True
    assert reg.counters[fr.BREAKER_TRIPS_COUNTER].value == 1
    # With replica 0 OPEN, every key lands on 1 (failover routing).
    picks = set()
    for i in range(20):
        r = router.route(f"key-{i}")
        picks.add(r)
        router.complete(r)
    assert picks == {1}
    # A served response closes the breaker; 0 becomes routable again.
    router.record_success(0)
    picks = set()
    for i in range(50):
        r = router.route(f"key-{i}")
        picks.add(r)
        router.complete(r)
    assert picks == {0, 1}


def test_failover_policy_bounded_attempts_and_books(tmp_path):
    reg = _Reg()
    router = _routable_router(tmp_path, reg, breaker_threshold=3,
                              breaker_cooldown_s=60.0)
    policy = FailoverPolicy(router, max_attempts=2)
    with pytest.raises(ValueError):
        FailoverPolicy(router, max_attempts=0)
    # Route two requests onto replica 0's books, then it dies.
    routed = [router.route("k0"), router.route("k0")]
    victim = routed[0]
    assert router.in_flight(victim) >= 1
    requeue, gave_up = policy.replica_failed(victim, [101, 102])
    assert requeue == [101, 102] and gave_up == []
    assert reg.counters[fr.FAILOVERS_COUNTER].value == 2
    # The dead replica's books are settled: one complete() per orphan.
    assert router.in_flight(victim) == 0
    # Second failover for 101 still inside the budget...
    requeue, gave_up = policy.replica_failed(victim, [101])
    assert requeue == [101] and gave_up == []
    # ...the third exceeds max_attempts=2: surface the error upward.
    requeue, gave_up = policy.replica_failed(victim, [101])
    assert requeue == [] and gave_up == [101]
    assert reg.counters[fr.FAILOVERS_COUNTER].value == 3
    # Completion forgets history — a reused id starts a fresh budget.
    policy.request_done(102)
    requeue, _ = policy.replica_failed(victim, [102])
    assert requeue == [102]


# ---------------------------------------------------------------------------
# AdmissionController (shed-at-admission)
# ---------------------------------------------------------------------------

def test_estimate_queue_wait_math_and_validation():
    # A request with < batch_tasks ahead rides the very next batch.
    assert estimate_queue_wait(0, 4, 0.2) == pytest.approx(0.2)
    assert estimate_queue_wait(3, 4, 0.2) == pytest.approx(0.2)
    # A full batch ahead means waiting out that batch first.
    assert estimate_queue_wait(4, 4, 0.2) == pytest.approx(0.4)
    assert estimate_queue_wait(9, 2, 0.5) == pytest.approx(2.5)
    with pytest.raises(ValueError):
        estimate_queue_wait(-1, 4, 0.2)
    with pytest.raises(ValueError):
        estimate_queue_wait(0, 0, 0.2)
    with pytest.raises(ValueError):
        estimate_queue_wait(0, 4, -0.1)


def test_admission_deadline_shed_and_liveness_floor():
    adm = AdmissionController(2, 16, policy="deadline", headroom=1.5)
    bucket = (5, 10)
    now = 100.0
    # No service sample yet: permissive (never guess).
    adm.admit(bucket, now + 0.001, now, depth=10)
    adm.record_service(bucket, 1.0)
    # Liveness floor: below one full batch queued, NEVER deadline-shed
    # — serving is the only way the EWMA refreshes, so shedding at
    # depth 0 on a stale-high estimate would starve the estimator.
    adm.admit(bucket, now + 0.001, now, depth=1)
    # At depth >= batch_tasks the estimate applies: 2 ahead -> own
    # batch completes at 2.0s, x1.5 headroom = 3.0s.
    with pytest.raises(ShedError):
        adm.admit(bucket, now + 1.0, now, depth=2)
    assert adm.sheds == 1
    adm.admit(bucket, now + 10.0, now, depth=2)  # generous deadline
    adm.admit(bucket, float("inf"), now, depth=2)  # no deadline
    adm.admit(bucket, None, now, depth=2)
    assert adm.sheds == 1


def test_admission_fair_share_under_pressure():
    adm = AdmissionController(1, 8, policy="fair", pressure_frac=0.5)
    assert adm.pressure_depth == 4
    now = 0.0
    # Tenant A fills the queue below the pressure line unchallenged.
    for depth in range(4):
        adm.admit((5, 10), None, now, depth=depth, tenant="A")
        adm.note_enqueued("A")
    # Past pressure, a NEW tenant still gets in (share is computed
    # over distinct queued tenants including the newcomer)...
    adm.admit((5, 10), None, now, depth=4, tenant="B")
    adm.note_enqueued("B")
    # ...but A, already holding 4 of 5, is over ceil(6/2)=3: shed.
    with pytest.raises(ShedError):
        adm.admit((5, 10), None, now, depth=5, tenant="A")
    assert adm.sheds == 1
    # B under its share admits; tenant=None opts out of fairness.
    adm.admit((5, 10), None, now, depth=5, tenant="B")
    adm.admit((5, 10), None, now, depth=5, tenant=None)
    # Dequeues release A's held count and re-admit it.
    for _ in range(3):
        adm.note_removed("A")
    adm.admit((5, 10), None, now, depth=2, tenant="A")


def test_admission_ewma_and_validation():
    adm = AdmissionController(4, 16, policy="deadline", ewma_alpha=0.5)
    b = (5, 10)
    assert adm.service_time_s(b) is None
    adm.record_service(b, 1.0)
    assert adm.service_time_s(b) == pytest.approx(1.0)
    adm.record_service(b, 2.0)
    assert adm.service_time_s(b) == pytest.approx(1.5)
    adm.record_service(b, -5.0)  # clock anomaly: ignored
    assert adm.service_time_s(b) == pytest.approx(1.5)
    with pytest.raises(ValueError):
        AdmissionController(4, 16, policy="off")
    with pytest.raises(ValueError):
        AdmissionController(4, 16, ewma_alpha=0.0)
    with pytest.raises(ValueError):
        AdmissionController(4, 16, headroom=0.9)


# ---------------------------------------------------------------------------
# import discipline + chaos acceptance
# ---------------------------------------------------------------------------

def test_supervisor_module_is_dependency_free(tmp_path):
    """The supervisor must survive exactly the failures it supervises:
    file-path loadable and fully operable with ZERO third-party
    imports (not even numpy) — the chaos/fleet driver discipline."""
    code = f"""
import importlib.util, sys
spec = importlib.util.spec_from_file_location(
    "_sup_probe", {SUPERVISOR_PY!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
class P:
    pid = 1
    def poll(self): return None
    def terminate(self): pass
    def kill(self): pass
sup = mod.ReplicaSupervisor({str(tmp_path / "fleet")!r}, lambda s: P(),
                            desired=1, scale_max=2)
states = sup.tick(1000.0)
assert states[0] == mod.STARTING, states
assert mod.backoff_delay(0, base=0.05, cap=2.0) == 0.05
for name in ("jax", "numpy"):
    assert name not in sys.modules, f"{{name}} leaked into the driver"
print("DEP_FREE_OK")
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "DEP_FREE_OK" in proc.stdout


needs_sockets = pytest.mark.skipif(
    not _can_bind_localhost(),
    reason="chaos phases drive real replicas over localhost sockets "
           "(the chaos_fleet skip-artifact path covers the CLI side)")


@pytest.mark.slow
@needs_sockets
def test_chaos_fleet_quick_proof(tmp_path):
    """The ISSUE 18 acceptance run (slow: several minutes): all three
    chaos phases — replica SIGKILL with zero lost requests, crash
    loop tripping the breaker while serving at N-1, and an overload
    burst shed at admission with zero deadline misses — green from
    one real ``chaos_fleet.py --quick`` invocation."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, CHAOS_FLEET, "--quick",
         "--out", str(tmp_path / "chaos")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=570)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines, f"no artifact line\n{proc.stdout}\n{proc.stderr}"
    art = json.loads(lines[-1])
    assert art["metric"] == "chaos_fleet"
    assert art["status"] == "ok", art
    assert proc.returncode == 0
    assert art["value"] == 3 and art["unit"] == "phases_ok"
    phases = art["phases"]
    assert phases["kill"]["ok"] and phases["kill"]["restarts"] >= 1
    assert phases["kill"]["stats"]["dropped"] == 0
    assert phases["crash_loop"]["ok"]
    assert phases["crash_loop"]["crash_loops"] >= 1
    assert phases["burst"]["ok"] and phases["burst"]["shed"] > 0
    assert phases["burst"]["deadline_misses"] == 0
    # Schema-stable robustness keys (serve_bench/fleet_bench parity).
    for key in ("fleet_restarts", "fleet_crash_loops",
                "fleet_failover_count", "fleet_shed_count"):
        assert art[key] is not None

"""Unit tests for the HLO cost model behind scripts/perf_ceiling.py.

The ceiling number (docs/PERF.md) is only as good as the parser: these
pin shape/layout byte accounting (tile padding), conv/dot FLOP parsing,
while-loop trip multiplication, and fusion boundary-traffic costing on a
small hand-written optimized-HLO module.
"""

import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

from perf_ceiling import (  # noqa: E402
    HloCostModel, _conv_flops, _dot_flops, _parse_instr, _shape_bytes)


def test_shape_bytes_logical():
    b, elems = _shape_bytes("f32[2,3]{1,0}", physical=False)
    assert b == 24 and elems == 6
    b, _ = _shape_bytes("bf16[4]{0}", physical=False)
    assert b == 8
    # Tuples: all components summed.
    b, _ = _shape_bytes("(f32[2]{0}, s32[2]{0})", physical=False)
    assert b == 16


def test_shape_bytes_tile_padding():
    # Minor-to-major {4,1,0,3,2} with T(8,128): dim4 (48) pads to 128,
    # dim1 (25) pads to 32 — the flagship's documented ~3.4x padding.
    text = "bf16[12,25,84,84,48]{4,1,0,3,2:T(8,128)(2,1)}"
    logical, _ = _shape_bytes(text, physical=False)
    physical, _ = _shape_bytes(text, physical=True)
    assert logical == 12 * 25 * 84 * 84 * 48 * 2
    assert physical == 12 * 32 * 84 * 84 * 128 * 2
    # No layout string -> no padding.
    p2, _ = _shape_bytes("bf16[12,25,84,84,48]", physical=True)
    assert p2 == logical


def test_parse_instr_tuple_output():
    line = ("%fusion.1 = (f32[2]{0}, f32[3]{0}) fusion(f32[4]{0} %p.1), "
            "kind=kLoop, calls=%fused_computation.1")
    opcode, out_t, ops_t, attrs = _parse_instr(line)
    assert opcode == "fusion"
    assert out_t.startswith("(") and "f32[3]" in out_t
    assert "f32[4]" in ops_t
    assert "fused_computation.1" in attrs


def test_conv_flops_grouped():
    # Grouped conv (the task-vmapped form): kernel i-dim is already
    # Cin/groups, so flops = 2 * out_elems * kh * kw * i.
    out_t = "f32[12,25,84,84,48]{4,3,2,1,0}"
    ops_t = ("f32[12,25,84,84,48]{4,3,2,1,0} %a, "
             "f32[3,3,4,48]{3,2,1,0} %k")
    attrs = (", window={size=3x3 pad=1_1x1_1}, "
             "dim_labels=b01f_01io->b01f, feature_group_count=12")
    out_elems = 12 * 25 * 84 * 84 * 48
    assert _conv_flops(out_t, ops_t, attrs) == 2.0 * out_elems * 3 * 3 * 4


def test_dot_flops():
    out_t = "f32[8,16]{1,0}"
    ops_t = "f32[8,32]{1,0} %a, f32[32,16]{1,0} %b"
    attrs = ", lhs_contracting_dims={1}, rhs_contracting_dims={0}"
    assert _dot_flops(out_t, ops_t, attrs) == 2.0 * 8 * 16 * 32


_TINY_HLO = """\
HloModule tiny

%body.1 (p.0: (s32[], f32[128,128])) -> (s32[], f32[128,128]) {
  %p.0 = (s32[]{:T(128)}, f32[128,128]{1,0}) parameter(0)
  %gte.0 = s32[]{:T(128)} get-tuple-element(%p.0), index=0
  %c.1 = s32[]{:T(128)} constant(1)
  %add.0 = s32[]{:T(128)} add(s32[] %gte.0, s32[] %c.1)
  %gte.1 = f32[128,128]{1,0} get-tuple-element(%p.0), index=1
  %mul.0 = f32[128,128]{1,0} multiply(f32[128,128]{1,0} %gte.1, f32[128,128]{1,0} %gte.1)
  ROOT %tuple.0 = (s32[]{:T(128)}, f32[128,128]{1,0}) tuple(%add.0, %mul.0)
}

%cond.1 (p.1: (s32[], f32[128,128])) -> pred[] {
  %p.1 = (s32[]{:T(128)}, f32[128,128]{1,0}) parameter(0)
  %gte.2 = s32[]{:T(128)} get-tuple-element(%p.1), index=0
  %c.5 = s32[]{:T(128)} constant(5)
  ROOT %lt.0 = pred[]{:T(512)} compare(s32[] %gte.2, s32[] %c.5), direction=LT
}

%fused_computation.1 (fp.0: f32[64,64], fp.1: f32[64,64]) -> f32[64,64] {
  %fp.0 = f32[64,64]{1,0} parameter(0)
  %fp.1 = f32[64,64]{1,0} parameter(1)
  %d.0 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %fp.0, f32[64,64]{1,0} %fp.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %r.0 = f32[64,64]{1,0} negate(f32[64,64]{1,0} %d.0)
}

ENTRY %main.1 (a.0: f32[128,128], b.0: f32[64,64]) -> f32[64,64] {
  %a.0 = f32[128,128]{1,0} parameter(0)
  %b.0 = f32[64,64]{1,0} parameter(1)
  %c.0 = s32[]{:T(128)} constant(0)
  %t.0 = (s32[]{:T(128)}, f32[128,128]{1,0}) tuple(%c.0, %a.0)
  %w.0 = (s32[]{:T(128)}, f32[128,128]{1,0}) while(%t.0), condition=%cond.1, body=%body.1
  ROOT %f.0 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %b.0, f32[64,64]{1,0} %b.0), kind=kOutput, calls=%fused_computation.1
}
"""


def test_cost_model_tiny_module():
    floor = 1e-6
    bw = 1e9  # 1 GB/s so byte terms are visible
    model = HloCostModel(_TINY_HLO, floor_s=floor, hbm_bps=bw,
                         mxu_fps=1e12)
    total = model.step_bound_s()
    # While loop found with trip count 5 from the condition constant.
    assert model.trip_counts == {"cond.1": 5}
    # Body multiply runs 5x: each costs bytes/bw = 3*128*128*4 / 1e9.
    mul = model.by_cat["multiply"]
    assert mul["n"] == 5
    assert abs(mul["time_s"] - 5 * 3 * 128 * 128 * 4 / bw) < 1e-9
    # Fusion charged boundary bytes AND the internal dot's flops.
    fus = model.by_cat["fusion"]
    assert fus["flops"] == 2.0 * 64 * 64 * 64
    # Free ops (parameter/constant/tuple/gte) contribute no kernels.
    assert "parameter" not in model.by_cat
    assert "tuple" not in model.by_cat
    # Total >= the multiply chain alone.
    assert total > mul["time_s"]


def test_free_ops_and_kernel_count():
    model = HloCostModel(_TINY_HLO, floor_s=1e-6, hbm_bps=1e12,
                         mxu_fps=1e15)
    model.step_bound_s()
    # Executed kernels: 5x (add + multiply) in body, 5x compare in cond,
    # 1 fusion. add/compare are tiny -> floor-bound.
    assert model.kernels == 5 * 2 + 5 * 1 + 1

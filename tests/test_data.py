"""Data pipeline tests: seeding contract, rotation augmentation, disk
layout, loader resume alignment (SURVEY.md §4 plan)."""

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data import (
    DiskImageSource, EpisodeSampler, MetaLearningDataLoader,
    SyntheticSource, build_source)

CFG = MAMLConfig(dataset_name="synthetic_test",
                 image_height=12, image_width=12, image_channels=1,
                 num_classes_per_set=5, num_samples_per_class=2,
                 num_target_samples=3, batch_size=4,
                 num_evaluation_tasks=10)


def _sampler(cfg=CFG, seed=0, **kw):
    src = SyntheticSource(num_classes=20, images_per_class=10,
                          image_size=cfg.image_shape, seed=7)
    return EpisodeSampler(src, cfg, seed, **kw)


def test_same_index_same_episode():
    s = _sampler()
    a, b = s.sample(42), s.sample(42)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # A fresh sampler over the same source reproduces it too (no hidden
    # state): this is the resume-correctness property.
    c = _sampler().sample(42)
    np.testing.assert_array_equal(a.support_x, c.support_x)


def test_different_indices_differ():
    s = _sampler()
    assert not np.array_equal(s.sample(1).support_x, s.sample(2).support_x)


def test_different_split_seeds_differ():
    a = _sampler(seed=0).sample(5)
    b = _sampler(seed=1).sample(5)
    assert not np.array_equal(a.support_x, b.support_x)


def test_episode_shapes_and_labels():
    ep = _sampler().sample(0)
    assert ep.support_x.shape == (10, 12, 12, 1)
    assert ep.target_x.shape == (15, 12, 12, 1)
    np.testing.assert_array_equal(ep.support_y,
                                  np.repeat(np.arange(5), 2))
    np.testing.assert_array_equal(ep.target_y,
                                  np.repeat(np.arange(5), 3))
    # Default wire format: raw uint8 (device normalizes — see
    # test_uint8_wire_format_matches_host_normalization).
    assert ep.support_x.dtype == np.uint8


def test_host_f32_path_shapes_and_range():
    ep = _sampler(cfg=CFG.replace(transfer_images_uint8=False)).sample(0)
    assert ep.support_x.dtype == np.float32
    assert 0.0 <= ep.support_x.min() and ep.support_x.max() <= 1.0


def test_rgb_normalization_range():
    cfg = CFG.replace(image_channels=3, transfer_images_uint8=False)
    src = SyntheticSource(20, 10, cfg.image_shape, seed=7)
    ep = EpisodeSampler(src, cfg, 0).sample(0)
    assert ep.support_x.min() < -0.2 and ep.support_x.max() > 0.2
    assert -1.0 <= ep.support_x.min() and ep.support_x.max() <= 1.0


@pytest.mark.parametrize("channels,reverse", [(1, False), (3, False),
                                              (3, True)])
def test_uint8_wire_format_matches_host_normalization(channels, reverse):
    """uint8 episode + device normalize == f32 host path, bit-exact."""
    from howtotrainyourmamlpytorch_tpu.ops.episode import normalize_episode

    cfg = CFG.replace(image_channels=channels, reverse_channels=reverse)
    src = SyntheticSource(20, 10, cfg.image_shape, seed=7)
    ep_u8 = EpisodeSampler(src, cfg, 0).sample(3)
    assert ep_u8.support_x.dtype == np.uint8
    cfg_f = cfg.replace(transfer_images_uint8=False)
    ep_f32 = EpisodeSampler(src, cfg_f, 0).sample(3)

    import jax
    norm = jax.jit(lambda e: normalize_episode(cfg, e))
    ep_dev = norm(ep_u8)
    # Equal to ~1 ulp, not bitwise: XLA rewrites /255 as a reciprocal
    # multiply and fuses 2·(x/255)−1 into one multiply — different
    # rounding than numpy's step-by-step host path.
    np.testing.assert_allclose(np.asarray(ep_dev.support_x),
                               ep_f32.support_x, atol=2e-7)
    np.testing.assert_allclose(np.asarray(ep_dev.target_x),
                               ep_f32.target_x, atol=2e-7)
    # Labels and episode composition identical across wire formats.
    np.testing.assert_array_equal(ep_u8.support_y, ep_f32.support_y)
    np.testing.assert_array_equal(ep_u8.target_y, ep_f32.target_y)


def test_rotation_augmentation_classes():
    cfg = CFG.replace(augment_images=True)
    s = _sampler(cfg=cfg)
    assert len(s.classes) == 80  # 20 physical x 4 rotations
    s_plain = _sampler()
    assert len(s_plain.classes) == 20


def test_rotation_actually_rotates():
    src = SyntheticSource(2, 6, CFG.image_shape, seed=3)
    cfg = CFG.replace(num_classes_per_set=8, num_samples_per_class=1,
                      num_target_samples=1, augment_images=True)
    s = EpisodeSampler(src, cfg, 0)
    # All 8 virtual classes (2 physical x 4 rots) appear in an 8-way
    # episode; collect one image per class and check rotation relations.
    ep = s.sample(0)
    imgs = ep.support_x[:, :, :, 0]
    # At least one pair of images must be exact 90-degree rotations.
    found = any(
        np.array_equal(np.rot90(imgs[i], kk), imgs[j])
        for i in range(8) for j in range(8) if i != j
        for kk in (1, 2, 3))
    assert found


def test_way_exceeds_classes_raises():
    src = SyntheticSource(3, 5, CFG.image_shape, seed=0)
    with pytest.raises(ValueError, match="classes"):
        EpisodeSampler(src, CFG, 0)


def test_disk_source_roundtrip(tmp_path):
    from helpers import make_png_split_tree
    rng = np.random.default_rng(0)
    # Reference layout: <dataset_path>/<dataset_name>/<split>/<class>/…
    make_png_split_tree(
        tmp_path / CFG.dataset_name,
        {"train": ("alpha", "beta", "gamma", "delta", "eps", "zeta")},
        rng, images_per_class=6)
    cfg = CFG.replace(dataset_path=str(tmp_path))
    src = build_source(cfg, "train")
    assert isinstance(src, DiskImageSource)
    assert len(src.class_names) == 6
    ep = EpisodeSampler(src, cfg, 0).sample(3)
    assert ep.support_x.shape == (10, 12, 12, 1)
    # Deterministic across fresh indexes (fresh cache).
    src2 = build_source(cfg, "train")
    ep2 = EpisodeSampler(src2, cfg, 0).sample(3)
    np.testing.assert_array_equal(ep.support_x, ep2.support_x)


def test_build_source_synthetic_fallback_warns():
    cfg = CFG.replace(dataset_name="omniglot_dataset",
                      dataset_path="/nonexistent/path")
    with pytest.warns(UserWarning, match="synthetic"):
        src = build_source(cfg, "train")
    assert isinstance(src, SyntheticSource)


def test_loader_resume_alignment():
    loader = MetaLearningDataLoader(CFG)
    full = list(loader.get_train_batches(0, 7))
    tail = list(MetaLearningDataLoader(CFG).get_train_batches(5, 2))
    np.testing.assert_array_equal(full[5].support_x, tail[0].support_x)
    np.testing.assert_array_equal(full[6].target_x, tail[1].target_x)


def test_loader_val_batches_fixed():
    loader = MetaLearningDataLoader(CFG)
    a = [b.support_x for b in loader.get_val_batches()]
    b = [b.support_x for b in loader.get_val_batches()]
    # Eval batch is decoupled from the train batch (auto: 2x train batch,
    # the measured v5e optimum) — ceil(10/8) = 2 batches.
    assert CFG.effective_eval_batch_size == 8
    assert len(a) == 2
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_loader_eval_batch_decoupled_from_train_batch():
    """Same fixed eval episodes regardless of eval batch size — batching
    changes wall-clock only (VERDICT r1 #5)."""
    small = MetaLearningDataLoader(CFG.replace(eval_batch_size=2))
    big = MetaLearningDataLoader(CFG.replace(eval_batch_size=5))
    eps_small = np.concatenate(
        [b.support_x for b in small.get_val_batches()])
    eps_big = np.concatenate([b.support_x for b in big.get_val_batches()])
    n = CFG.num_evaluation_tasks
    np.testing.assert_array_equal(eps_small[:n], eps_big[:n])


def test_loader_val_and_test_streams_differ():
    loader = MetaLearningDataLoader(CFG)
    v = next(iter(loader.get_val_batches()))
    t = next(iter(loader.get_test_batches()))
    assert not np.array_equal(v.support_x, t.support_x)


def test_loader_abandoned_consumer_stops_worker():
    """Breaking out of the batch iterator early must stop the prefetch
    worker instead of letting it sample the rest of the epoch."""
    import time
    loader = MetaLearningDataLoader(CFG)
    sampler = loader.sampler("train")
    calls = []
    orig = sampler.sample

    def counting(idx):
        calls.append(idx)
        return orig(idx)

    sampler.sample = counting
    gen = loader.get_train_batches(0, 500)
    next(gen)
    gen.close()  # triggers the generator's finally
    time.sleep(0.3)
    n_after_close = len(calls)
    time.sleep(0.3)
    assert len(calls) == n_after_close  # worker stopped producing
    assert len(calls) < 500 * CFG.batch_size


def test_loader_places_batches_on_mesh_in_worker():
    """With a mesh, yielded batches must arrive ALREADY device-placed and
    task-sharded — placement happens in the prefetch worker so the
    host->device transfer overlaps the previous step's compute (the
    dominant per-batch cost on a tunneled device; r4). A regression to
    consumer-side placement would yield numpy here."""
    import jax
    from howtotrainyourmamlpytorch_tpu.parallel import (batch_sharding,
                                                        make_mesh)
    cfg = CFG.replace(batch_size=jax.device_count() * 2,
                      mesh_shape=(1, jax.device_count()))
    mesh = make_mesh(cfg, jax.devices())
    loader = MetaLearningDataLoader(cfg, mesh=mesh)
    batch = next(iter(loader.get_train_batches(0, 1)))
    want = batch_sharding(mesh)
    for leaf in batch:
        assert isinstance(leaf, jax.Array), type(leaf)
        assert leaf.sharding.is_equivalent_to(want, leaf.ndim)
    # Content identical to the host-side sampler output (placement must
    # not reorder or renormalize anything).
    ref = loader.sampler("train").sample_batch(range(cfg.batch_size))
    np.testing.assert_array_equal(np.asarray(batch.support_x),
                                  ref.support_x)


def test_loader_propagates_worker_errors():
    loader = MetaLearningDataLoader(CFG)
    sampler = loader.sampler("train")

    def boom(idx):
        raise RuntimeError("decode failed")

    sampler.sample = boom
    with pytest.raises(RuntimeError, match="decode failed"):
        list(loader.get_train_batches(0, 1))


# ---------------------------------------------------------------------------
# reference config knobs wired into the disk index (VERDICT r1 missing #5)
# ---------------------------------------------------------------------------

from helpers import write_png as _write_png  # noqa: E402  (shared fixture)


def test_nested_disk_layout_uses_folder_indexes(tmp_path):
    """Omniglot-style <root>/<alphabet>/<character>/<imgs> layout: the
    class identity is alphabet/character (reference
    ``indexes_of_folders_indicating_class=(-3, -2)``)."""
    rng = np.random.default_rng(0)
    for alpha in ("Greek", "Latin"):
        for char in ("char1", "char2", "char3"):
            d = tmp_path / "train" / alpha / char
            d.mkdir(parents=True)
            for i in range(4):
                _write_png(d / f"{i}.png", rng)
    src = DiskImageSource(str(tmp_path / "train"), (12, 12, 1))
    assert src.class_names == [
        "Greek/char1", "Greek/char2", "Greek/char3",
        "Latin/char1", "Latin/char2", "Latin/char3"]
    assert src.num_images("Greek/char2") == 4
    # Same result via the config default indexes (flat layouts ignore the
    # out-of-range -3 component; nested ones pick alphabet+character).
    src2 = DiskImageSource(str(tmp_path / "train"), (12, 12, 1),
                           class_key_indexes=(-3, -2))
    assert src2.class_names == src.class_names


def test_flat_layout_with_default_indexes(tmp_path):
    rng = np.random.default_rng(1)
    for cls in ("a", "b"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            _write_png(d / f"{i}.png", rng)
    src = DiskImageSource(str(tmp_path), (12, 12, 1),
                          class_key_indexes=(-3, -2))
    assert src.class_names == ["a", "b"]


def test_labels_as_int_sorts_numerically(tmp_path):
    rng = np.random.default_rng(2)
    for cls in ("2", "10", "1"):
        d = tmp_path / cls
        d.mkdir()
        _write_png(d / "0.png", rng)
    lex = DiskImageSource(str(tmp_path), (12, 12, 1))
    num = DiskImageSource(str(tmp_path), (12, 12, 1), numeric_sort=True)
    assert lex.class_names == ["1", "10", "2"]
    assert num.class_names == ["1", "2", "10"]


def test_load_into_memory_preloads(tmp_path):
    rng = np.random.default_rng(3)
    d = tmp_path / "cls"
    d.mkdir()
    for i in range(3):
        _write_png(d / f"{i}.png", rng)
    lazy = DiskImageSource(str(tmp_path), (12, 12, 1))
    eager = DiskImageSource(str(tmp_path), (12, 12, 1), preload=True)
    assert not lazy._cache and set(eager._cache) == {"cls"}
    np.testing.assert_array_equal(
        lazy.get_images_raw("cls", np.array([0, 2])),
        eager.get_images_raw("cls", np.array([0, 2])))


def test_sets_are_pre_split_false_partitions_flat_pool(tmp_path):
    """One flat class pool split class-disjointly by train_val_test_split
    (reference ``data.py § load_dataset`` with sets_are_pre_split=False)."""
    rng = np.random.default_rng(4)
    root = tmp_path / "flat_pool"
    for i in range(10):
        d = root / f"class_{i:02d}"
        d.mkdir(parents=True)
        for j in range(4):
            _write_png(d / f"{j}.png", rng)
    cfg = CFG.replace(dataset_name="flat_pool", dataset_path=str(tmp_path),
                      sets_are_pre_split=False,
                      train_val_test_split=(0.6, 0.2, 0.2))
    splits = {s: build_source(cfg, s).class_names
              for s in ("train", "val", "test")}
    assert len(splits["train"]) == 6
    assert len(splits["val"]) == 2 and len(splits["test"]) == 2
    all_names = splits["train"] + splits["val"] + splits["test"]
    assert sorted(all_names) == sorted(set(all_names))  # disjoint
    assert len(all_names) == 10                         # complete
    # And the subset source actually samples.
    ep = EpisodeSampler(build_source(cfg, "val"), cfg.replace(
        num_classes_per_set=2), 0).sample(0)
    assert ep.support_x.shape[0] == 2 * cfg.num_samples_per_class


# ---------------------------------------------------------------------------
# configurable normalization constants (VERDICT r1 next-round #3)
# ---------------------------------------------------------------------------

def test_custom_norm_constants_host_path():
    cfg = CFG.replace(image_channels=3, transfer_images_uint8=False,
                      image_norm_mean=(0.2, 0.4, 0.6),
                      image_norm_std=(0.5, 0.25, 0.125))
    src = SyntheticSource(20, 10, cfg.image_shape, seed=7)
    ep = EpisodeSampler(src, cfg, 0).sample(0)
    # Recover the raw [0,1] pixels and re-apply manually.
    base = EpisodeSampler(
        src, cfg.replace(image_norm_mean=(0.0,), image_norm_std=(1.0,)),
        0).sample(0)
    mean = np.array([0.2, 0.4, 0.6], np.float32)
    inv = np.array([2.0, 4.0, 8.0], np.float32)
    np.testing.assert_allclose(ep.support_x,
                               (base.support_x - mean) * inv, rtol=1e-6)


def test_custom_norm_constants_device_matches_host():
    from howtotrainyourmamlpytorch_tpu.ops.episode import normalize_episode
    import jax
    cfg = CFG.replace(image_channels=3,
                      image_norm_mean=(0.485, 0.456, 0.406),
                      image_norm_std=(0.229, 0.224, 0.225))
    src = SyntheticSource(20, 10, cfg.image_shape, seed=7)
    ep_u8 = EpisodeSampler(src, cfg, 0).sample(3)
    assert ep_u8.support_x.dtype == np.uint8
    ep_f32 = EpisodeSampler(
        src, cfg.replace(transfer_images_uint8=False), 0).sample(3)
    ep_dev = jax.jit(lambda e: normalize_episode(cfg, e))(ep_u8)
    np.testing.assert_allclose(np.asarray(ep_dev.support_x),
                               ep_f32.support_x, rtol=2e-5, atol=2e-5)


def test_split_fractions_respect_empty_splits():
    """Cumulative rounding: a zero fraction yields an empty split even
    when the other fractions round awkwardly."""
    from howtotrainyourmamlpytorch_tpu.data.sources import split_class_names
    names = [f"c{i}" for i in range(5)]
    assert split_class_names(names, (0.5, 0.5, 0.0), "test") == []
    train = split_class_names(names, (0.5, 0.5, 0.0), "train")
    val = split_class_names(names, (0.5, 0.5, 0.0), "val")
    assert train + val == names
    assert split_class_names(names, (0.7, 0.3, 0.0), "val") != []

"""Meta-algorithm zoo tests (ISSUE 17).

Tier-1 (no/tiny compiles): registry resolution + did-you-mean, config
validation for the new ``meta_algorithm`` / ``task_type`` keys, the
capability gates each spec imposes, the DEFAULT-PATH STRUCTURAL PIN
(absent key and explicit ``maml++`` trace to the identical jaxpr and
``task_loss_fns`` returns the exact pre-registry function objects), the
ANIL head-only split and its smaller adapted-params footprint, MSE
zero-weight padding exactness, sinusoid sampler determinism, AOT-store
fingerprint distinctness per algorithm, and Reptile's frozen slow/LSLR
leaves.

Slow: the BITWISE default-path pin — 3 optimizer steps of the flagship
(second-order + MSL) trajectory must reproduce the weight digest
recorded BEFORE the registry existed — and the ANIL-vs-MAML++ serving
comparison (smaller cache entries, faster adapt p50 on the same
checkpoint geometry; the same quantities scripts/serve_bench.py
reports).
"""

import functools
import hashlib
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta import algos
from howtotrainyourmamlpytorch_tpu.meta.algos import (
    AlgoSpec, HEAD_PARAM_KEYS)
from howtotrainyourmamlpytorch_tpu.meta.inner import (
    adapted_param_counts, split_fast_slow)
from howtotrainyourmamlpytorch_tpu.meta.outer import (
    init_train_state, make_train_step)
from howtotrainyourmamlpytorch_tpu.models import make_model
from howtotrainyourmamlpytorch_tpu.ops import losses
from tests.test_outer import CFG as OUTER_CFG, _synthetic_batch

ZOO = ("anil", "fomaml", "maml++", "reptile")


def _tiny(**kw):
    """The test_outer geometry, algorithm-parameterizable."""
    base = dict(
        image_height=12, image_width=12, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=2,
        num_target_samples=2, cnn_num_filters=8, num_stages=2,
        batch_size=4, number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2, task_learning_rate=0.1,
        meta_learning_rate=0.01, min_learning_rate=0.001,
        total_epochs=4, total_iter_per_epoch=10,
        compute_dtype="float32")
    base.update(kw)
    return MAMLConfig(**base)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_ships_the_zoo():
    assert algos.names() == ZOO
    for name in ZOO:
        spec = algos.get(name)
        assert spec.name == name and spec.description


def test_registry_did_you_mean_and_duplicate():
    with pytest.raises(ValueError, match="did you mean 'maml\\+\\+'"):
        algos.get("maml")
    with pytest.raises(ValueError, match="did you mean 'reptile'"):
        algos.get("reptil")
    with pytest.raises(ValueError, match="registered"):
        algos.register(AlgoSpec(name="maml++", description="dupe"))
    with pytest.raises(ValueError, match="outer"):
        algos.register(AlgoSpec(name="x", description="x", outer="sgd"))


def test_config_validates_meta_algorithm():
    with pytest.raises(ValueError, match="did you mean 'fomaml'"):
        _tiny(meta_algorithm="fo-maml")
    # The key participates in to_dict (and therefore the AOT structural
    # fingerprint + JSON round-trip).
    assert _tiny().to_dict()["meta_algorithm"] == "maml++"
    assert MAMLConfig.from_dict(
        {"meta_algorithm": "anil"}).meta_algorithm == "anil"


def test_config_validates_regression():
    with pytest.raises(ValueError, match="transfer_images_uint8"):
        _tiny(task_type="regression", backbone="mlp",
              num_classes_per_set=1, transfer_images_uint8=True)
    with pytest.raises(ValueError, match="task_type"):
        _tiny(task_type="ranking")
    cfg = _tiny(task_type="regression", backbone="mlp",
                num_classes_per_set=1, image_height=1, image_width=1,
                transfer_images_uint8=False)
    assert cfg.num_output_units == 1
    assert cfg.label_dtype == "float32"
    clf = _tiny()
    assert clf.num_output_units == clf.num_classes_per_set == 3
    assert clf.label_dtype == "int32"


# ---------------------------------------------------------------------------
# capability gates
# ---------------------------------------------------------------------------

def test_fomaml_forces_first_order():
    cfg = _tiny(meta_algorithm="fomaml", second_order=True,
                first_order_to_second_order_epoch=-1)
    # The config schedule says second order from epoch 0; the spec wins.
    assert cfg.use_second_order(epoch=5) is False
    # Everything else stays config-driven.
    assert cfg.use_msl(0) == _tiny().use_msl(0)
    assert cfg.effective_learnable_lslr == _tiny().effective_learnable_lslr


def test_reptile_gates_msl_lslr_and_order():
    cfg = _tiny(meta_algorithm="reptile", second_order=True,
                first_order_to_second_order_epoch=-1,
                use_multi_step_loss_optimization=True,
                learnable_per_layer_per_step_inner_loop_learning_rate=True)
    assert cfg.use_second_order(5) is False
    assert cfg.use_msl(0) is False
    assert cfg.effective_learnable_lslr is False
    assert cfg.algo.outer == "interpolate"


def test_anil_is_head_only_second_order():
    cfg = _tiny(meta_algorithm="anil", second_order=True,
                first_order_to_second_order_epoch=-1)
    assert cfg.algo.trainable == "head"
    # ANIL keeps the full MAML++ schedule machinery — only the fast set
    # shrinks.
    assert cfg.use_second_order(5) is True


# ---------------------------------------------------------------------------
# default-path structural pin (tier-1 half of satellite 4)
# ---------------------------------------------------------------------------

def test_default_path_loss_fns_are_the_original_objects():
    """maml++ (and the absent key) must dispatch to the EXACT original
    classification loss functions — identical function objects mean
    identical traces, which is how the registry refactor keeps the
    flagship jaxprs untouched."""
    for cfg in (_tiny(), _tiny(meta_algorithm="maml++")):
        loss_fn, weighted_fn, metric_fn = losses.task_loss_fns(cfg)
        assert loss_fn is losses.cross_entropy
        assert weighted_fn is losses.weighted_cross_entropy
        assert metric_fn is losses.accuracy


def test_default_path_jaxpr_identical_absent_vs_explicit():
    """Tracing the full train step under the key-absent config and the
    explicit ``maml++`` config yields the identical jaxpr (trace-only:
    no compile cost in tier-1)."""
    jaxprs = []
    for cfg in (_tiny(), _tiny(meta_algorithm="maml++")):
        init, apply = make_model(cfg)
        state = init_train_state(cfg, init, jax.random.PRNGKey(0))
        step = functools.partial(make_train_step(cfg, apply),
                                 second_order=True, use_msl=True)
        batch = _synthetic_batch(jax.random.PRNGKey(100), cfg, 4)
        text = str(jax.make_jaxpr(step)(state, batch, jnp.float32(0)))
        # Embedded callable reprs carry id()-dependent addresses; the
        # program structure is everything else.
        jaxprs.append(re.sub(r"0x[0-9a-f]+", "0x", text))
    assert jaxprs[0] == jaxprs[1]
    # And the maml++ spec literally gates nothing.
    cfg = _tiny()
    assert cfg.use_second_order(5) == bool(
        cfg.second_order and 5 > cfg.first_order_to_second_order_epoch)
    assert cfg.use_msl(0) == bool(cfg.use_multi_step_loss_optimization)
    assert (cfg.effective_learnable_lslr ==
            cfg.learnable_per_layer_per_step_inner_loop_learning_rate)


# ---------------------------------------------------------------------------
# ANIL: head-only fast set shrinks everything downstream
# ---------------------------------------------------------------------------

def test_anil_split_is_head_only():
    cfg = _tiny(meta_algorithm="anil")
    init, _ = make_model(cfg)
    params, _ = init(jax.random.PRNGKey(0))
    fast, slow = split_fast_slow(cfg, params)
    assert set(fast) == set(HEAD_PARAM_KEYS) == {"linear"}
    assert set(slow) == set(params) - {"linear"}
    # Default algorithm: the head is fast AND the body is fast.
    d_fast, _ = split_fast_slow(_tiny(), params)
    assert "linear" in d_fast and len(d_fast) > 1


def test_anil_adapted_footprint_smaller():
    """The quantity serving caches per support set (the adapted fast
    params) shrinks under ANIL — byte-for-byte, same checkpoint
    geometry. This is the tier-1 (no-engine) half of the serve claim."""
    cfg_anil, cfg_maml = _tiny(meta_algorithm="anil"), _tiny()
    init, _ = make_model(cfg_maml)
    params, _ = init(jax.random.PRNGKey(0))

    def entry_bytes(cfg):
        fast, _ = split_fast_slow(cfg, params)
        return sum(int(x.nbytes) for x in jax.tree.leaves(fast))

    adapted_a, total_a = adapted_param_counts(cfg_anil, params)
    adapted_m, total_m = adapted_param_counts(cfg_maml, params)
    assert total_a == total_m
    assert adapted_a < adapted_m
    assert entry_bytes(cfg_anil) < entry_bytes(cfg_maml)


# ---------------------------------------------------------------------------
# regression losses: zero-weight padding exactness
# ---------------------------------------------------------------------------

def test_mse_and_weighted_mse_padding_exact():
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.normal(size=(6, 1)), jnp.float32)
    targets = jnp.asarray(rng.normal(size=(6,)), jnp.float32)
    ones = jnp.ones((6,), jnp.float32)
    # all-ones weights == plain mse, bit-for-bit
    assert float(losses.weighted_mse(preds, targets, ones)) == \
        float(losses.mse(preds, targets))
    # zero-weight padding rows are INVISIBLE: garbage in the padded
    # slots cannot move the loss (the serve batcher's exactness
    # contract, regression edition).
    pad_preds = jnp.concatenate(
        [preds, jnp.full((3, 1), 1e9, jnp.float32)])
    pad_targets = jnp.concatenate(
        [targets, jnp.full((3,), -1e9, jnp.float32)])
    w = jnp.concatenate([ones, jnp.zeros((3,), jnp.float32)])
    np.testing.assert_allclose(
        float(losses.weighted_mse(pad_preds, pad_targets, w)),
        float(losses.mse(preds, targets)), rtol=1e-6)
    # regression "accuracy" is the negative MSE (higher = better).
    assert float(losses.regression_score(preds, targets)) == \
        -float(losses.mse(preds, targets))


# ---------------------------------------------------------------------------
# sinusoid workload
# ---------------------------------------------------------------------------

def _sin_cfg():
    return _tiny(task_type="regression", backbone="mlp",
                 dataset_name="sinusoid_synthetic",
                 num_classes_per_set=1, num_samples_per_class=5,
                 num_target_samples=10, image_height=1, image_width=1,
                 image_channels=1, transfer_images_uint8=False,
                 augment_images=False)


def test_sinusoid_source_truthful_and_deterministic():
    from howtotrainyourmamlpytorch_tpu.data.sources import SinusoidSource
    s1 = SinusoidSource(num_tasks=6, points_per_task=20, seed=(1, 7))
    s2 = SinusoidSource(num_tasks=6, points_per_task=20, seed=(1, 7))
    assert s1.class_names == s2.class_names and len(s1.class_names) == 6
    picks = np.array([0, 3, 19])
    for name in s1.class_names:
        x1, y1 = s1.get_images(name, picks), s1.get_targets(name, picks)
        np.testing.assert_array_equal(x1, s2.get_images(name, picks))
        np.testing.assert_array_equal(y1, s2.get_targets(name, picks))
        assert x1.shape == (3, 1, 1, 1) and x1.dtype == np.float32
        assert y1.shape == (3,) and y1.dtype == np.float32
        lo, hi = SinusoidSource.X_RANGE
        assert (x1 >= lo).all() and (x1 <= hi).all()
        assert (np.abs(y1) <= SinusoidSource.AMP_RANGE[1]).all()
    # No uint8 wire for real-valued x.
    assert not hasattr(s1, "get_images_raw")


def test_sinusoid_sampler_float_labels_match_source():
    from howtotrainyourmamlpytorch_tpu.data.sampler import EpisodeSampler
    from howtotrainyourmamlpytorch_tpu.data.sources import SinusoidSource
    cfg = _sin_cfg()
    src = SinusoidSource(num_tasks=8, points_per_task=30, seed=(0, 5))
    ep = EpisodeSampler(src, cfg, split_seed=2).sample(11)
    ep2 = EpisodeSampler(src, cfg, split_seed=2).sample(11)
    for a, b in zip(ep, ep2):
        np.testing.assert_array_equal(a, b)
    assert ep.support_y.dtype == np.float32
    assert ep.target_y.dtype == np.float32
    assert ep.support_x.shape == (5, 1, 1, 1)
    assert ep.target_y.shape == (10,)
    # Every (x, y) row must co-occur in SOME task's pool: y really is
    # A*sin(x - phi) for the task the sampler drew, not a relabeling.
    pool = {}
    for name in src.class_names:
        idx = np.arange(src.num_images(name))
        xs = src.get_images(name, idx).reshape(-1)
        ys = src.get_targets(name, idx)
        pool.update(zip(xs.tolist(), ys.tolist()))
    for x, y in zip(ep.support_x.reshape(-1), ep.support_y):
        assert pool[float(x)] == float(y)


def test_sinusoid_classification_sampler_rejects_sources_without_targets():
    from howtotrainyourmamlpytorch_tpu.data.sampler import EpisodeSampler
    from howtotrainyourmamlpytorch_tpu.data.sources import SyntheticSource
    src = SyntheticSource(num_classes=4, images_per_class=8,
                          image_size=(1, 1, 1), seed=0)
    with pytest.raises(ValueError, match="get_targets"):
        EpisodeSampler(src, _sin_cfg(), split_seed=0)


# ---------------------------------------------------------------------------
# AOT structural fingerprint
# ---------------------------------------------------------------------------

def test_fingerprint_distinct_per_algorithm_and_task_type():
    from howtotrainyourmamlpytorch_tpu.parallel import aot, make_mesh
    cfg = _tiny()
    mesh = make_mesh(cfg, jax.devices()[:1])
    fps = {name: aot.store_fingerprint(cfg.replace(meta_algorithm=name),
                                       mesh)
           for name in ZOO}
    assert len(set(fps.values())) == len(ZOO)
    assert fps["maml++"] == aot.store_fingerprint(cfg, mesh)  # default
    reg = _sin_cfg()
    assert aot.store_fingerprint(reg, make_mesh(reg, jax.devices()[:1])) \
        not in set(fps.values())


# ---------------------------------------------------------------------------
# reptile mechanics
# ---------------------------------------------------------------------------

def test_reptile_step_moves_fast_leaves_only():
    """One Reptile outer step: fast params move along the interpolation
    delta; slow (norm) leaves and the frozen LSLR tree must not move —
    their 'gradient' is identically zero by construction."""
    cfg = _tiny(meta_algorithm="reptile")
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                     second_order=False, use_msl=False))
    batch = _synthetic_batch(jax.random.PRNGKey(100), cfg, 4)
    new_state, metrics = step(state, batch, jnp.float32(0))
    assert np.isfinite(float(metrics.loss))
    jax.tree.map(np.testing.assert_array_equal,
                 jax.device_get(state.lslr),
                 jax.device_get(new_state.lslr))
    _, slow0 = split_fast_slow(cfg, jax.device_get(state.params))
    fast0, _ = split_fast_slow(cfg, jax.device_get(state.params))
    fast1, slow1 = split_fast_slow(cfg, jax.device_get(new_state.params))
    jax.tree.map(np.testing.assert_array_equal, slow0, slow1)
    moved = [bool(np.any(a != b)) for a, b in zip(
        jax.tree.leaves(fast0), jax.tree.leaves(fast1))]
    assert all(moved), moved


# ---------------------------------------------------------------------------
# slow: the bitwise default-path pin (satellite 4) + ANIL serve claim
# ---------------------------------------------------------------------------

# sha256 over the sorted (path, bytes) flattening of {params, lslr}
# after 3 flagship train steps, recorded on the PRE-REGISTRY tree
# (jax 0.4.37, float32, 8-device virtual CPU — the pinned test env).
# If this moves, the flagship trajectory moved: that is a bug in
# whatever PR moved it, not a constant to refresh casually.
_GOLDEN_DIGEST = \
    "3a1c8152cdf3ef206eae6e28a04f2805e9e821bf6847300bdf6f0e18e86cf009"


def _train3_digest(cfg):
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    step = jax.jit(functools.partial(make_train_step(cfg, apply),
                                     second_order=True, use_msl=True))
    for i in range(3):
        batch = _synthetic_batch(jax.random.PRNGKey(100 + i), cfg, 4)
        state, _ = step(state, batch, jnp.float32(0))
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(
        {"params": state.params, "lslr": state.lslr})[0]
    for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
        h.update(str(path).encode())
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    cache = getattr(step, "_cache_size", lambda: 1)()
    return h.hexdigest(), cache


@pytest.mark.slow  # two full compiles of the flagship train step
def test_default_path_bitwise_pin():
    """meta_algorithm absent AND explicit 'maml++' both reproduce the
    pre-registry 3-step weight digest bit-for-bit, with equal
    cache-warm compile counts (one executable each, reused across all
    three steps)."""
    d_absent, c_absent = _train3_digest(OUTER_CFG)
    d_explicit, c_explicit = _train3_digest(
        OUTER_CFG.replace(meta_algorithm="maml++"))
    assert d_absent == d_explicit == _GOLDEN_DIGEST
    assert c_absent == c_explicit == 1


@pytest.mark.slow  # ~60s: 5k outer steps of the shipped sinusoid config
def test_sinusoid_regression_learns_below_pinned_mse():
    """The regression path LEARNS: 5k outer steps of the shipped
    sinusoid config (batch 25, the paper's sinusoid meta-batch) must
    push held-out post-adaptation MSE under the pinned bar. Recorded
    trajectory of this exact fixed-seed run (docs/PERF.md §
    Meta-algorithm zoo): 2.92 at step 0, 2.68 at 5k, 1.19 at 50k —
    the bar (2.80) sits above the 5k point with margin, far below the
    step-0 value and the ~4.25 zero-predictor baseline."""
    from howtotrainyourmamlpytorch_tpu.data.sampler import EpisodeSampler
    from howtotrainyourmamlpytorch_tpu.data.sources import SinusoidSource
    from howtotrainyourmamlpytorch_tpu.meta.outer import make_eval_step

    cfg = MAMLConfig.from_json_file(
        "experiment_config/sinusoid_maml_5-shot.json").replace(
        batch_size=25, total_epochs=2, total_iter_per_epoch=2)
    src = SinusoidSource(num_tasks=20000, points_per_task=50,
                         seed=(0, 104))
    sampler = EpisodeSampler(src, cfg, split_seed=0)
    init, apply = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    step = jax.jit(functools.partial(
        make_train_step(cfg, apply),
        second_order=cfg.use_second_order(1_000_001),
        use_msl=cfg.use_msl(0)))
    eval_step = jax.jit(make_eval_step(cfg, apply))
    eval_batch = jax.tree.map(
        jnp.asarray, sampler.sample_batch(range(10**6, 10**6 + 25)))

    def eval_mse(s):
        return -float(np.mean(np.asarray(
            eval_step(s, eval_batch).accuracy)))

    before = eval_mse(state)
    for i in range(5000):
        batch = jax.tree.map(jnp.asarray, sampler.sample_batch(
            range(25 * i, 25 * i + 25)))
        state, metrics = step(state, batch, jnp.float32(0))
        assert np.isfinite(float(metrics.loss)), i
    after = eval_mse(state)
    assert after < 2.80, (before, after)
    assert after < before - 0.1, (before, after)


@pytest.mark.slow  # two serving engines, adapt+predict compiles each
def test_anil_serves_smaller_entries_and_faster_adapt():
    """The ANIL serve claim, on one checkpoint geometry: cache entries
    are byte-smaller AND adapt p50 is faster than MAML++ (the body's
    inner-loop backward disappears). Same quantities serve_bench
    reports (cache_entry_bytes_mean, adapt_seconds_p50)."""
    from howtotrainyourmamlpytorch_tpu.serve import (
        FewShotRequest, ServingEngine)

    def run(algorithm):
        cfg = MAMLConfig(
            dataset_name="synthetic_serve", image_height=12,
            image_width=12, image_channels=1, num_classes_per_set=3,
            num_samples_per_class=1, num_target_samples=2, batch_size=2,
            cnn_num_filters=16, num_stages=3,
            number_of_training_steps_per_iter=3,
            number_of_evaluation_steps_per_iter=3, second_order=False,
            use_multi_step_loss_optimization=False,
            serve_buckets=((3, 4),), serve_batch_tasks=2,
            serve_default_deadline_ms=0.0, serve_cache_capacity=32,
            meta_algorithm=algorithm, compute_dtype="float32")
        init, _ = make_model(cfg)
        state = init_train_state(cfg, init, jax.random.PRNGKey(0))
        eng = ServingEngine(cfg, state, devices=jax.devices()[:1])
        try:
            eng.warmup()
            rng = np.random.RandomState(7)
            for seed in range(10):  # 10 distinct supports -> 10 adapts
                eng.submit(FewShotRequest(
                    support_x=rng.randint(
                        0, 256, (3, 12, 12, 1)).astype(np.uint8),
                    support_y=np.arange(3, dtype=np.int32),
                    query_x=rng.randint(
                        0, 256, (2, 12, 12, 1)).astype(np.uint8)))
                (resp,) = eng.drain()
                assert resp.error is None, resp.error
            cache = eng.cache
            bytes_mean = cache.approx_bytes / max(len(cache), 1)
            p50 = eng.registry.histogram(
                "serve/adapt_seconds").quantile(0.5)
            gauges = (eng.registry.gauge("algo/adapted_params").value,
                      eng.registry.gauge("algo/total_params").value)
        finally:
            eng.close()
        return bytes_mean, p50, gauges

    anil_bytes, anil_p50, (anil_adapted, anil_total) = run("anil")
    maml_bytes, maml_p50, (maml_adapted, maml_total) = run("maml++")
    assert anil_total == maml_total
    assert anil_adapted < maml_adapted
    assert anil_bytes < maml_bytes, (anil_bytes, maml_bytes)
    assert anil_p50 is not None and maml_p50 is not None
    assert anil_p50 < maml_p50, (anil_p50, maml_p50)

"""The one-command real-data accuracy gate (VERDICT r4 next #2).

The gate's job is to make "paper number" vs "synthetic protocol
evidence" a mechanical distinction: it must REFUSE synthetic sources and
missing datasets, and — against a real on-disk image tree — drive the
full schedule plus the 600-episode top-5-ensemble protocol and emit one
machine-readable verdict vs the BASELINE.md table. The end-to-end test
here runs the real thing against a small PNG tree (tests/helpers.py
fixtures, the reference `<dataset>/<split>/<class>/*.png` layout), so
the day Mini-ImageNet bytes exist the only new variable is the data.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import accuracy_gate  # noqa: E402

FLAGSHIP = os.path.join(
    REPO, "experiment_config", "mini-imagenet_maml++_5-way_5-shot_DA.json")


def _run_gate(argv, capsys):
    rc = accuracy_gate.main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1])


def test_gate_refuses_synthetic(capsys):
    rc, verdict = _run_gate(
        ["--config", FLAGSHIP, "--dataset_name", "synthetic_mini"],
        capsys)
    assert rc == 1
    assert verdict["pass"] is False
    assert "synthetic" in verdict["error"]


def test_gate_requires_real_dataset(tmp_path, capsys):
    """A missing dataset directory must fail onto maybe_unzip_dataset's
    provisioning instructions, never fall back to synthetic data."""
    rc, verdict = _run_gate(
        ["--config", FLAGSHIP,
         "--dataset_path", str(tmp_path / "nonexistent")],
        capsys)
    assert rc == 1
    assert verdict["pass"] is False
    assert "no real dataset" in verdict["error"]
    # The message carries the provisioning instructions.
    assert "zip" in verdict["error"]


def test_gate_requires_threshold_for_unknown_workload(capsys):
    """Configs with no BASELINE.md paper row (tiered-imagenet pod) must
    demand an explicit --min-accuracy instead of inventing a gate."""
    pod = os.path.join(
        REPO, "experiment_config",
        "tiered-imagenet_maml++_5-way_5-shot_resnet12_pod.json")
    rc, verdict = _run_gate(["--config", pod], capsys)
    assert rc == 1
    assert "min-accuracy" in verdict["error"]


def test_gate_usage_errors_exit_1_not_2(capsys):
    """argparse's native exit status is 2, which would collide with the
    gate's exit-2 = 'ran but below the accuracy gate' contract; every
    parse failure must remap to the error contract (exit 1 + JSON)."""
    rc, verdict = _run_gate(
        ["--config", FLAGSHIP, "--min-accuracy", "abc"], capsys)
    assert rc == 1
    assert verdict["pass"] is False
    rc2, verdict2 = _run_gate([], capsys)  # missing required --config
    assert rc2 == 1
    assert verdict2["pass"] is False
    # A bad override surfaces through the trainer-CLI parser: same remap.
    rc3, verdict3 = _run_gate(
        ["--config", FLAGSHIP, "--no_such_field", "1"], capsys)
    assert rc3 == 1
    assert verdict3["pass"] is False


def test_gate_paper_table_matches_baseline_md():
    """The (mean, CI) rows hardcoded in the gate are BASELINE.md's."""
    md = open(os.path.join(REPO, "BASELINE.md")).read()
    for (family, way, shot), (acc, ci) in accuracy_gate.PAPER_GATES.items():
        # Omniglot rows read "99.47%", imagenet rows "68.32 ± 0.44%".
        assert f"{100 * acc:.2f}" in md, (family, way, shot)
        if ci:
            # A non-zero margin must be the PUBLISHED CI, not invented.
            assert f"± {100 * ci:.2f}" in md, (family, way, shot)


def test_gate_threshold_is_mean_minus_ci():
    """ADVICE r5: the pass gate is paper mean minus its published CI —
    an at-parity run passes deterministically; rows without a published
    CI keep the strict mean."""
    class _C:
        dataset_name = "mini_imagenet_full_size"
        num_classes_per_set = 5
        num_samples_per_class = 5
        meta_algorithm = "maml++"
    mean, ci = accuracy_gate.paper_gate(_C)
    assert (mean, ci) == (0.6832, 0.0044)
    _C.num_samples_per_class = 1
    assert accuracy_gate.paper_gate(_C) == (0.5215, 0.0026)
    _C.dataset_name = "omniglot_dataset"
    assert accuracy_gate.paper_gate(_C) == (0.9947, 0.0)
    # The algorithm picks the table: fomaml rows come from the MAML
    # paper's first-order entries (BASELINE.md § FOMAML), and the
    # no-paper-row algorithms resolve None (the gate then demands an
    # explicit --min-accuracy).
    _C.meta_algorithm = "fomaml"
    assert accuracy_gate.paper_gate(_C) == (0.987, 0.004)
    _C.dataset_name = "mini_imagenet_full_size"
    assert accuracy_gate.paper_gate(_C) == (0.4807, 0.0175)
    _C.meta_algorithm = "reptile"
    assert accuracy_gate.paper_gate(_C) is None


@pytest.mark.slow
def test_gate_end_to_end_on_real_png_tree(tmp_path, capsys):
    """Full wiring against a REAL on-disk image tree: flagship config,
    schedule shrunk via the trainer-CLI override mechanism, verdict line
    carries the ensemble-protocol evidence. --min-accuracy 0.0 makes the
    gate pass at chance accuracy (the PNGs are random noise — this test
    proves the pipeline, not the science)."""
    from helpers import make_png_split_tree
    import numpy as np
    rng = np.random.default_rng(0)
    data = tmp_path / "pngset"
    make_png_split_tree(
        data, {"train": 6, "val": 5, "test": 5}, rng, size=(12, 12),
        images_per_class=8)
    rc, verdict = _run_gate(
        ["--config", FLAGSHIP, "--min-accuracy", "0.0",
         "--dataset_path", str(data),
         "--experiment_root", str(tmp_path / "exp"),
         "--image_height", "12", "--image_width", "12",
         "--cnn_num_filters", "4", "--num_stages", "2",
         "--batch_size", "4", "--task_microbatches", "1",
         "--number_of_training_steps_per_iter", "2",
         "--number_of_evaluation_steps_per_iter", "2",
         "--total_epochs", "2", "--total_iter_per_epoch", "4",
         "--num_evaluation_tasks", "16", "--eval_batch_size", "8",
         "--precompile_phases", "false",
         "--multi_step_loss_num_epochs", "1"],
        capsys)
    assert rc == 0, verdict
    assert verdict["pass"] is True
    assert verdict["threshold_source"] == "--min-accuracy"
    assert verdict["dataset_path"] == str(data)
    assert verdict["num_episodes"] == 16
    assert verdict["num_models"] == 2          # top-k of the 2 epochs
    assert 0.0 <= verdict["test_accuracy_mean"] <= 1.0
    # The same invocation against the PAPER threshold must FAIL on
    # noise data with exit code 2 (below-gate, not error) — the verdict
    # distinguishes "ran and missed" from "could not run".
    rc2, verdict2 = _run_gate(
        ["--config", FLAGSHIP,
         "--dataset_path", str(data),
         "--experiment_root", str(tmp_path / "exp2"),
         "--image_height", "12", "--image_width", "12",
         "--cnn_num_filters", "4", "--num_stages", "2",
         "--batch_size", "4", "--task_microbatches", "1",
         "--number_of_training_steps_per_iter", "2",
         "--number_of_evaluation_steps_per_iter", "2",
         "--total_epochs", "1", "--total_iter_per_epoch", "2",
         "--num_evaluation_tasks", "8", "--eval_batch_size", "8",
         "--precompile_phases", "false",
         "--multi_step_loss_num_epochs", "1"],
        capsys)
    assert rc2 == 2
    assert verdict2["pass"] is False
    # Gate = paper mean minus its published CI (ADVICE r5); the strict
    # mean and the granted margin are reported fields.
    assert verdict2["threshold"] == pytest.approx(0.6832 - 0.0044)
    assert verdict2["paper_mean"] == pytest.approx(0.6832)
    assert verdict2["margin"] == pytest.approx(0.0044)
    assert verdict2["strict_pass"] is False
    assert verdict2["threshold_source"] == \
        "BASELINE.md MAML++ paper table, mean - CI"

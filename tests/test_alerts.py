"""Ops-plane tests (ISSUE 20): alert rules engine, fleet log
collection, rotation, and the status console.

Tier-1 units pin the whole alerting contract clock-in and
process-free: rule parsing (config.py-grade did-you-mean errors),
every rule type's condition math (threshold ops, reset-aware counter
rates with the first-observation-is-baseline rule, absence over
present-signals-only ages incl. the ``inf`` vanished-lease case,
per-tenant burn rates), ``for_s`` hysteresis with blink reset, dedup
by (rule, labels), the firing -> resolved lifecycle (event rows,
``maml_alert_firing`` gauge, atomic ALERTS.json), the supervisor
integration (rate + absence rules over real fake-proc ticks, decision
rows annotated with the firing set), JsonlLogger size-capped rotation
+ the rotated readers, the fleet events collector, and the
ops_console CLI (real subprocess under the jax-import booby trap —
the artifact schema pin the console docstring promises).

The structural zero-cost pin (``alert_rules_path`` unset installs
NOTHING on the serving engine) is tier-1; the bitwise
alerts-on-vs-off serving parity proof compiles two engines and rides
the ``slow`` profile.
"""

import json
import math
import os
import random
import subprocess
import sys
import time

import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.serve.fleet import (
    supervisor as fsup)
from howtotrainyourmamlpytorch_tpu.serve.fleet.router import ReplicaLease
from howtotrainyourmamlpytorch_tpu.serve.fleet.supervisor import (
    ReplicaSupervisor)
from howtotrainyourmamlpytorch_tpu.telemetry import aggregate, alerts
from howtotrainyourmamlpytorch_tpu.utils.tracing import (
    JsonlLogger, read_jsonl, read_jsonl_rotated, rotated_path)
from test_fleet_supervisor import FakeProc, _touch_lease

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OPS_CONSOLE = os.path.join(REPO, "scripts", "ops_console.py")
DEFAULT_RULES = os.path.join(REPO, "configs", "alerts_default.json")


# ---------------------------------------------------------------------------
# test doubles
# ---------------------------------------------------------------------------

class _Sink:
    """JsonlLogger-shaped capture sink (the evaluator only needs
    ``.log(event, **payload)``)."""

    def __init__(self):
        self.rows = []

    def log(self, event, **payload):
        row = {"event": event, **payload}
        self.rows.append(row)
        return row


class _Counter:
    def __init__(self):
        self.value = 0.0

    def inc(self, amount=1.0):
        self.value += amount


class _Gauge:
    def __init__(self):
        self.value = 0.0

    def set(self, v):
        self.value = float(v)


class _SnapReg:
    """Duck-typed MetricsRegistry WITH ``snapshot()`` — the
    supervisor's alert pass reads its counters through it
    (test_fleet_supervisor's ``_Reg`` deliberately lacks snapshot;
    alerting is exactly the consumer that needs one)."""

    def __init__(self):
        self.counters = {}
        self.gauges = {}

    def counter(self, name):
        return self.counters.setdefault(name, _Counter())

    def gauge(self, name):
        return self.gauges.setdefault(name, _Gauge())

    def snapshot(self):
        out = {n: c.value for n, c in self.counters.items()}
        out.update({n: g.value for n, g in self.gauges.items()})
        return out


def _ev(*rule_dicts, **kw):
    return alerts.AlertEvaluator(
        alerts.parse_rules({"rules": list(rule_dicts)}), **kw)


# ---------------------------------------------------------------------------
# rule parsing / validation
# ---------------------------------------------------------------------------

def test_shipped_default_rules_parse_and_round_trip():
    rules = alerts.load_rules(DEFAULT_RULES)
    names = {r.name for r in rules}
    assert {"heartbeat_stale", "replica_lease_stale", "slo_burn_high",
            "replica_restarts", "replica_crash_loop",
            "admission_shedding"} <= names
    # as_dict() is a valid rules document again (the snapshot format).
    redo = alerts.parse_rules({"rules": [r.as_dict() for r in rules]})
    assert [r.as_dict() for r in redo] == [r.as_dict() for r in rules]


@pytest.mark.parametrize("doc,match", [
    ("not a dict", r"'rules' list"),
    ({"rules": [{"type": "threshold"}]}, r"non-empty 'name'"),
    ({"rules": [{"name": "a", "type": "treshold"}]},
     r"did you mean 'threshold'"),
    ({"rules": [{"name": "a", "type": "threshold", "metrik": "m",
                 "op": ">", "value": 1, "metric": "m"}]},
     r"unknown field 'metrik'.*did you mean 'metric'"),
    ({"rules": [{"name": "a", "type": "threshold", "op": ">",
                 "value": 1}]}, r"requires field 'metric'"),
    ({"rules": [{"name": "a", "type": "threshold", "metric": "m",
                 "op": ">", "value": 1, "severity": "warning"}]},
     r"did you mean 'warn'"),
    ({"rules": [{"name": "a", "type": "threshold", "metric": "m",
                 "op": "=>", "value": 1}]}, r"unknown op '=>'"),
    ({"rules": [{"name": "a", "type": "rate", "metric": "m", "op": ">",
                 "value": 0, "for_s": -1}]}, r"for_s must be >= 0"),
    ({"rules": [{"name": "a", "type": "absence", "signal": "hb"}]},
     r"max_age_s"),
    ({"rules": [{"name": "a", "type": "absence", "max_age_s": 5}]},
     r"'signal'\s+or 'signal_prefix'"),
    ({"rules": [{"name": "a", "type": "burn_rate", "max_burn": 1},
                {"name": "a", "type": "burn_rate", "max_burn": 2}]},
     r"duplicate rule name"),
])
def test_parse_rules_rejections_name_the_problem(doc, match):
    with pytest.raises(ValueError, match=match):
        alerts.parse_rules(doc)


def test_load_rules_errors_name_the_file(tmp_path):
    bad = tmp_path / "rules.json"
    bad.write_text("{not json")
    with pytest.raises(ValueError, match=r"rules\.json.*not valid"):
        alerts.load_rules(str(bad))
    bad.write_text(json.dumps(
        {"rules": [{"name": "a", "type": "nope"}]}))
    with pytest.raises(ValueError, match=r"rules\.json.*unknown type"):
        alerts.load_rules(str(bad))
    # A config-named file that does not exist is a deployment error.
    with pytest.raises(OSError):
        alerts.load_rules(str(tmp_path / "missing.json"))


def test_severity_helpers():
    assert [alerts.severity_rank(s) for s in alerts.SEVERITIES] \
        == [0, 1, 2]
    assert alerts.max_severity(["info", "critical", "warn"]) \
        == "critical"
    assert alerts.max_severity(["info"]) == "info"
    assert alerts.max_severity([]) is None


# ---------------------------------------------------------------------------
# condition math per rule type
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op,value,fires", [
    (">", 2.0, True), (">", 3.0, False),
    (">=", 3.0, True), (">=", 3.1, False),
    ("<", 4.0, True), ("<", 3.0, False),
    ("<=", 3.0, True), ("<=", 2.9, False),
    ("==", 3.0, True), ("==", 2.0, False),
])
def test_threshold_ops(op, value, fires):
    ev = _ev({"name": "t", "type": "threshold", "metric": "m",
              "op": op, "value": value})
    t = ev.evaluate(0.0, snapshot={"m": 3.0})
    assert bool(t) is fires
    if fires:
        assert t[0]["state"] == "firing" and t[0]["value"] == 3.0


def test_threshold_ignores_missing_and_non_finite_metrics():
    ev = _ev({"name": "t", "type": "threshold", "metric": "m",
              "op": ">", "value": 0.0})
    assert ev.evaluate(0.0, snapshot={}) == []
    assert ev.evaluate(1.0, snapshot={"m": float("nan")}) == []
    assert ev.evaluate(2.0, snapshot={"m": "not a number"}) == []
    assert ev.active() == []


def test_rate_first_observation_is_baseline_then_fires_then_resolves():
    ev = _ev({"name": "r", "type": "rate", "metric": "c",
              "op": ">", "value": 0.0})
    # A huge first value is a baseline, never a rate — a fresh process
    # attaching to a long-lived counter must not page.
    assert ev.evaluate(0.0, snapshot={"c": 1000.0}) == []
    t = ev.evaluate(2.0, snapshot={"c": 1006.0})
    assert t[0]["state"] == "firing"
    assert t[0]["value"] == pytest.approx(3.0)  # 6 over 2s
    # Steady counter -> rate 0 -> resolved.
    t = ev.evaluate(3.0, snapshot={"c": 1006.0})
    assert [r["state"] for r in t] == ["resolved"]
    assert ev.fired_total == 1 and ev.resolved_total == 1


def test_rate_is_reset_aware():
    ev = _ev({"name": "r", "type": "rate", "metric": "c",
              "op": ">", "value": 0.0})
    ev.evaluate(0.0, snapshot={"c": 100.0})
    # Counter below its predecessor = restarted process: the new value
    # contributes whole over the interval, never a negative rate.
    t = ev.evaluate(2.0, snapshot={"c": 4.0})
    assert t[0]["state"] == "firing"
    assert t[0]["value"] == pytest.approx(2.0)


def test_absence_judges_only_present_signals():
    ev = _ev({"name": "hb", "type": "absence", "signal": "heartbeat",
              "max_age_s": 10.0, "severity": "critical"})
    # Not this process's signal to watch: a shared rules file must not
    # make a process page about a heartbeat it does not emit.
    assert ev.evaluate(0.0, ages={}) == []
    assert ev.evaluate(1.0, ages={"heartbeat": 5.0}) == []
    t = ev.evaluate(2.0, ages={"heartbeat": 11.0})
    assert t[0]["state"] == "firing"
    assert t[0]["labels"] == {"signal": "heartbeat"}
    assert t[0]["value"] == 11.0
    t = ev.evaluate(3.0, ages={"heartbeat": 0.1})
    assert [r["state"] for r in t] == ["resolved"]


def test_absence_prefix_instances_and_vanished_lease_inf():
    ev = _ev({"name": "lease_stale", "type": "absence",
              "signal_prefix": "lease:", "max_age_s": 1.0})
    t = ev.evaluate(0.0, ages={"lease:0": 2.0,
                               "lease:1": float("inf"),
                               "lease:2": 0.2})
    fired = {r["labels"]["signal"]: r["value"] for r in t}
    assert fired == {"lease:0": 2.0, "lease:1": None}  # inf -> null
    # One instance resolves while the other keeps firing silently.
    t = ev.evaluate(1.0, ages={"lease:0": 0.0,
                               "lease:1": float("inf")})
    assert [(r["state"], r["labels"]["signal"]) for r in t] \
        == [("resolved", "lease:0")]
    assert ev.firing_summary() == {"count": 1, "max_severity": "warn"}


def test_burn_rate_per_tenant_instances():
    ev = _ev({"name": "burn", "type": "burn_rate", "max_burn": 2.0,
              "severity": "critical"})
    t = ev.evaluate(0.0, burn_rates={"acme": 3.5, "bbco": 1.0})
    assert [(r["labels"], r["value"]) for r in t] \
        == [({"tenant": "acme"}, 3.5)]
    # The other tenant crossing later is a SECOND instance, deduped
    # independently of the first.
    t = ev.evaluate(1.0, burn_rates={"acme": 3.5, "bbco": 4.0})
    assert [(r["state"], r["labels"]) for r in t] \
        == [("firing", {"tenant": "bbco"})]
    assert ev.firing_summary() == {"count": 2,
                                   "max_severity": "critical"}


# ---------------------------------------------------------------------------
# hysteresis, dedup, lifecycle
# ---------------------------------------------------------------------------

def test_for_s_hysteresis_with_blink_reset():
    ev = _ev({"name": "q", "type": "threshold", "metric": "m",
              "op": ">", "value": 1.0, "for_s": 5.0})
    assert ev.evaluate(0.0, snapshot={"m": 9.0}) == []  # pending
    assert ev.evaluate(3.0, snapshot={"m": 9.0}) == []  # still pending
    # The condition blinks false: pending drops SILENTLY (that is the
    # hysteresis working — a noisy sample never pages, never logs).
    assert ev.evaluate(4.0, snapshot={"m": 0.0}) == []
    assert ev.evaluate(5.0, snapshot={"m": 9.0}) == []  # clock restarts
    assert ev.evaluate(9.0, snapshot={"m": 9.0}) == []  # 4s < for_s
    t = ev.evaluate(10.0, snapshot={"m": 9.0})
    assert t[0]["state"] == "firing"
    assert t[0]["since_ts"] == 5.0 and t[0]["fired_ts"] == 10.0
    assert ev.fired_total == 1


def test_firing_dedup_no_refire_while_active():
    ev = _ev({"name": "hot", "type": "threshold", "metric": "m",
              "op": ">", "value": 1.0})
    t = ev.evaluate(0.0, snapshot={"m": 5.0})
    assert [r["state"] for r in t] == ["firing"]
    # Re-observed true: silent, but the tracked value stays current.
    assert ev.evaluate(1.0, snapshot={"m": 6.0}) == []
    assert ev.fired_total == 1
    (act,) = ev.active()
    assert act["value"] == 6.0


def test_lifecycle_rows_gauge_and_atomic_snapshot(tmp_path):
    snap_path = tmp_path / "ALERTS.json"
    reg, sink = _SnapReg(), _Sink()
    ev = _ev({"name": "hot", "type": "threshold", "metric": "m",
              "op": ">", "value": 1.0},
             source="unit", snapshot_path=str(snap_path))
    ev.evaluate(0.0, snapshot={"m": 5.0}, jsonl=sink, registry=reg)
    assert reg.gauges[alerts.FIRING_GAUGE].value == 1.0
    doc = json.loads(snap_path.read_text())
    assert len(doc["firing"]) == 1
    assert doc["counts"] == {"info": 0, "warn": 1, "critical": 0}
    assert doc["source"] == "unit"
    ev.evaluate(1.0, snapshot={"m": 0.0}, jsonl=sink, registry=reg)
    assert reg.gauges[alerts.FIRING_GAUGE].value == 0.0
    assert ev.active() == []
    doc = json.loads(snap_path.read_text())
    assert doc["firing"] == []
    assert doc["fired_total"] == 1 and doc["resolved_total"] == 1
    rows = [r for r in sink.rows if r["event"] == alerts.ALERT_EVENT]
    assert [r["state"] for r in rows] == ["firing", "resolved"]
    assert all(r["source"] == "unit" and r["rule"] == "hot"
               for r in rows)
    assert set(rows[0]) >= {"rule", "type", "severity", "state",
                            "labels", "value", "since_ts", "fired_ts",
                            "at_ts", "source"}
    # Atomic replace leaves no tmp litter behind.
    assert [p for p in os.listdir(tmp_path) if ".tmp." in p] == []


def test_active_orders_critical_first():
    ev = _ev({"name": "warned", "type": "threshold", "metric": "a",
              "op": ">", "value": 0.0, "severity": "warn"},
             {"name": "paged", "type": "threshold", "metric": "b",
              "op": ">", "value": 0.0, "severity": "critical"})
    ev.evaluate(0.0, snapshot={"a": 1.0, "b": 1.0})
    assert [r["rule"] for r in ev.active()] == ["paged", "warned"]
    assert ev.firing_summary() == {"count": 2,
                                   "max_severity": "critical"}


def test_read_snapshots_fail_soft(tmp_path):
    good = tmp_path / "ALERTS.json"
    good.write_text(json.dumps({"updated_ts": 1.0, "source": "x",
                                "firing": [{"rule": "r",
                                            "severity": "warn"}],
                                "counts": {}}))
    (tmp_path / "torn.json").write_text("{torn")
    (tmp_path / "shape.json").write_text(json.dumps({"firing": "no"}))
    docs = alerts.read_snapshots([str(good), str(tmp_path / "torn.json"),
                                  str(tmp_path / "shape.json"),
                                  str(tmp_path / "missing.json")])
    assert len(docs) == 1 and docs[0]["source"] == "x"


# ---------------------------------------------------------------------------
# supervisor integration (rate + absence over real ticks; satellite 3)
# ---------------------------------------------------------------------------

def _mk_sup_with_alerts(fleet_dir, spawned, reg, events, ev, **kw):
    def spawn(slot):
        proc = FakeProc()
        spawned.setdefault(slot, []).append(proc)
        return proc
    kw.setdefault("rng", random.Random(0))
    return ReplicaSupervisor(str(fleet_dir), spawn, registry=reg,
                             events_path=str(events),
                             alert_evaluator=ev, **kw)


def test_supervisor_restart_rate_alert_annotates_decisions(tmp_path):
    spawned, reg = {}, _SnapReg()
    events = tmp_path / "events_supervisor.jsonl"
    ev = _ev({"name": "replica_restarts", "type": "rate",
              "metric": fsup.RESTARTS_COUNTER, "op": ">", "value": 0.0,
              "severity": "warn"}, source="supervisor")
    sup = _mk_sup_with_alerts(tmp_path / "fleet", spawned, reg, events,
                              ev, desired=1, scale_max=2,
                              backoff_base_s=0.05, backoff_cap_s=2.0)
    t0 = time.time()
    sup.tick(t0)                        # spawn; rate baseline (c=0)
    _touch_lease(tmp_path / "fleet", 0)
    sup.tick(t0 + 0.1)                  # RUNNING; steady -> no fire
    assert ev.active() == []
    spawned[0][0].exit(1)
    sup.tick(t0 + 0.2)                  # crash -> restarts=1 -> fires
    assert ev.firing_summary() == {"count": 1, "max_severity": "warn"}
    # A decision made WHILE firing carries the firing set — and the
    # counter going quiet resolves the alert at this tick's end.
    sup.tick(t0 + 0.3, advice="scale_up")
    assert ev.active() == []
    assert reg.gauges[alerts.FIRING_GAUGE].value == 0.0
    rows = read_jsonl(str(events))
    alert_rows = [r for r in rows if r.get("event") == alerts.ALERT_EVENT]
    assert [r["state"] for r in alert_rows] == ["firing", "resolved"]
    assert all(r["rule"] == "replica_restarts"
               and r["source"] == "supervisor" for r in alert_rows)
    scale = [r for r in rows if r.get("event") == "fleet_supervisor"
             and r.get("kind") == "scale_up"]
    assert scale and scale[0]["alerts_firing"] == ["replica_restarts"]


def test_supervisor_absence_alert_on_stale_lease(tmp_path):
    spawned, reg = {}, _SnapReg()
    events = tmp_path / "events_supervisor.jsonl"
    ev = _ev({"name": "lease_stale", "type": "absence",
              "signal_prefix": "lease:", "max_age_s": 1.0,
              "severity": "critical"}, source="supervisor")
    # Wide stalled/dead thresholds: the aged lease must trip the ALERT,
    # not the supervisor's own kill path.
    sup = _mk_sup_with_alerts(tmp_path / "fleet", spawned, reg, events,
                              ev, desired=1, scale_max=1,
                              stalled_after_s=10.0, dead_after_s=30.0)
    t0 = time.time()
    sup.tick(t0)
    _touch_lease(tmp_path / "fleet", 0)
    sup.tick(t0 + 0.1)
    assert ev.active() == []            # fresh lease, nothing fires
    _touch_lease(tmp_path / "fleet", 0, age_s=2.0)
    sup.tick(t0 + 0.2)
    (act,) = ev.active()
    assert act["rule"] == "lease_stale"
    assert act["labels"] == {"signal": "lease:0"}
    _touch_lease(tmp_path / "fleet", 0)  # proof of life returns
    sup.tick(t0 + 0.3)
    assert ev.active() == []
    assert ev.fired_total == 1 and ev.resolved_total == 1


# ---------------------------------------------------------------------------
# JsonlLogger rotation + rotated readers (satellite 1)
# ---------------------------------------------------------------------------

def test_jsonl_logger_rotates_one_spare(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = JsonlLogger(path, max_bytes=150)
    for seq in range(12):
        log.log("tick", seq=seq)
    assert os.path.exists(rotated_path(path))
    rows = read_jsonl_rotated(path)
    seqs = [r["seq"] for r in rows]
    # Every row lands in exactly one segment and the two segments are
    # contiguous in write order; the oldest rows (beyond one spare)
    # are legitimately gone.
    assert seqs == list(range(seqs[0], 12))
    assert 0 < len(seqs) < 12
    # Only the one spare exists — no .2 ladder.
    assert not os.path.exists(path + ".2")
    assert [r["seq"] for r in read_jsonl_rotated(path, tail=2)] \
        == [10, 11]


def test_read_jsonl_rotated_survives_missing_live_segment(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(rotated_path(path), "w") as f:
        f.write(json.dumps({"ts": 1.0, "event": "tick", "seq": 0})
                + "\n")
    # Right after a rotation the live file does not exist yet.
    assert [r["seq"] for r in read_jsonl_rotated(path)] == [0]


def test_jsonl_logger_uncapped_and_disabled_behavior(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = JsonlLogger(path)  # max_bytes=0: never rotates
    for seq in range(50):
        log.log("tick", seq=seq, pad="x" * 64)
    assert not os.path.exists(rotated_path(path))
    assert len(read_jsonl(path)) == 50
    off = JsonlLogger(str(tmp_path / "never.jsonl"), enabled=False,
                      max_bytes=10)
    off.log("tick", seq=0)
    assert not os.path.exists(str(tmp_path / "never.jsonl"))


# ---------------------------------------------------------------------------
# fleet events collector (satellite 2)
# ---------------------------------------------------------------------------

def _write_rows(path, rows):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def test_collect_fleet_events_merges_sources_in_time_order(tmp_path):
    out = tmp_path / "out"
    _write_rows(str(out / "events_driver.jsonl"),
                [{"ts": 3.0, "event": "metrics"},
                 {"ts": 1.0, "event": "metrics"}])
    _write_rows(str(out / "logs" / "events_replica_0.jsonl"),
                [{"ts": 2.0, "event": "metrics"},
                 {"ts": 4.0, "event": "metrics",
                  "replica": "supervisor"},
                 {"event": "half_written"}])
    # A rotated spare folds into its live segment's stream.
    _write_rows(str(out / "events_driver.jsonl.1"),
                [{"ts": 0.5, "event": "metrics"}])
    # Unreadable files contribute nothing (render the half-dead fleet).
    (out / "bad.jsonl").write_text("{torn")
    rows = aggregate.collect_fleet_events([str(out)])
    assert [r.get("ts") for r in rows] == [None, 0.5, 1.0, 2.0, 3.0, 4.0]
    by_ts = {r.get("ts"): r["source"] for r in rows}
    assert by_ts[0.5] == "events_driver"       # spare keeps its stem
    assert by_ts[2.0] == "events_replica_0"
    assert by_ts[4.0] == "supervisor"          # row's own identity wins
    assert by_ts[None] == "events_replica_0"   # no-ts rows still render
    # The spare is folded per live segment, never listed as a file.
    files = aggregate.resolve_fleet_files([str(out)])
    assert not any(f.endswith(".jsonl.1") for f in files)


def test_fleet_counter_totals_reset_aware_per_source():
    rows = [
        {"event": "metrics", "source": "a",
         "metrics": {"fleet/restarts": 2.0, "other/x": 9.0}},
        {"event": "metrics", "source": "b",
         "metrics": {"fleet/restarts": 4.0}},
        {"event": "metrics", "source": "a",
         "metrics": {"fleet/restarts": 5.0}},
        # Source a restarts: value below predecessor contributes whole.
        {"event": "metrics", "source": "a",
         "metrics": {"fleet/restarts": 1.0, "serve/shed_total": 3.0}},
        {"event": "not_metrics", "source": "a",
         "metrics": {"fleet/restarts": 99.0}},
    ]
    totals = aggregate.fleet_counter_totals(rows)
    assert totals["fleet/restarts"] == pytest.approx(10.0)  # 2+3+1 + 4
    assert totals["serve/shed_total"] == pytest.approx(3.0)
    assert "other/x" not in totals


def test_latest_gauges_last_write_wins():
    rows = [
        {"event": "metrics",
         "metrics": {"fleet/canary_weight": 0.1}},
        {"event": "metrics",
         "metrics": {"fleet/canary_weight": 0.5, "junk": "str"}},
    ]
    out = aggregate.latest_gauges(rows, ["fleet/canary_weight",
                                         "fleet/never_written"])
    assert out == {"fleet/canary_weight": 0.5,
                   "fleet/never_written": None}


# ---------------------------------------------------------------------------
# ops_console CLI (real subprocess, jax-import booby trap)
# ---------------------------------------------------------------------------

def _console(args, trap):
    proc = subprocess.run(
        [sys.executable, OPS_CONSOLE] + args, capture_output=True,
        text=True, env=dict(os.environ, PYTHONPATH=str(trap)),
        timeout=120)
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    artifact = json.loads(lines[-1]) if lines else {}
    return proc, artifact


@pytest.fixture
def trap(tmp_path):
    """PYTHONPATH booby trap (the reqtrace idiom): the console must run
    on a login node — any jax import explodes."""
    trap = tmp_path / "trap"
    trap.mkdir()
    (trap / "jax.py").write_text(
        "raise ImportError('ops_console must not import jax')\n")
    return trap


def test_ops_console_renders_fleet_and_alerts(tmp_path, trap):
    out = tmp_path / "out"
    _write_rows(str(out / "logs" / "events.jsonl"), [
        {"ts": 1.0, "event": "heartbeat", "epoch": 0, "iter": 10,
         "process_index": 0},
        {"ts": 2.0, "event": "metrics",
         "metrics": {"fleet/canary_weight": 0.25,
                     "serve/shed_total": 3.0}},
        # replica_restarts fired then resolved: replay must NOT count
        # it (last transition per (source, rule, labels) wins).
        {"ts": 3.0, "event": "alert", "rule": "replica_restarts",
         "severity": "warn", "state": "firing", "labels": {}},
        {"ts": 4.0, "event": "alert", "rule": "replica_restarts",
         "severity": "warn", "state": "resolved", "labels": {}},
        {"ts": 5.0, "event": "alert", "rule": "slo_burn_high",
         "severity": "critical", "state": "firing",
         "labels": {"tenant": "acme"}, "value": 3.5},
    ])
    fleet = out / "fleet"
    fleet.mkdir()
    lease = ReplicaLease(str(fleet), 0, 0.0)
    assert lease.touch(payload={
        "port": 7001, "pid": 1234, "version": "ckpt_v1",
        "stats": {"queue_depth": 1, "p95_ms": 12.5},
        "alerts_firing": {"count": 2, "max_severity": "warn"}},
        force=True)

    proc, art = _console([str(out)], trap)
    assert proc.returncode == 0, proc.stderr
    assert "ALERTS FIRING (1)" in proc.stdout  # human render
    assert art["metric"] == "ops_console"
    assert art["events_rows"] == 5 and art["sources"] == ["events"]
    assert art["replicas_live"] == 1
    (rep,) = art["replicas"]
    assert rep["verdict"] == "live" and rep["version"] == "ckpt_v1"
    # The peer's own firing summary rides the lease payload (sat. 3).
    assert rep["alerts_firing"] == 2
    assert rep["alerts_max_severity"] == "warn"
    assert art["canary_weight"] == 0.25
    assert art["counters"] == {"serve/shed_total": 3.0}
    assert art["alerts_firing"] == 1
    assert art["alerts_by_severity"] == {"info": 0, "warn": 0,
                                         "critical": 1}
    assert art["alerts"][0]["rule"] == "slo_burn_high"

    # An ALERTS.json snapshot is the evaluator's own word and WINS over
    # row replay: all-clear snapshot -> zero firing.
    (out / "ALERTS.json").write_text(json.dumps(
        {"updated_ts": 6.0, "source": "supervisor", "firing": [],
         "counts": {}, "fired_total": 2, "resolved_total": 2}))
    proc, art = _console([str(out), "--json"], trap)
    assert proc.returncode == 0, proc.stderr
    assert art["alerts_firing"] == 0
    assert "alerts: none firing" not in proc.stdout  # --json is quiet


def test_ops_console_exit_codes(tmp_path, trap):
    empty = tmp_path / "empty"
    empty.mkdir()
    proc, art = _console([str(empty), "--json"], trap)
    assert proc.returncode == 1 and "error" in art
    proc, art = _console([str(empty), "--watch", "-1"], trap)
    assert proc.returncode == 2 and "error" in art


# ---------------------------------------------------------------------------
# config contract + serving-engine zero-cost pin (satellite 4)
# ---------------------------------------------------------------------------

def _tiny_serve_cfg(**kw):
    kw.setdefault("serve_buckets", ((3, 4),))
    kw.setdefault("serve_batch_tasks", 2)
    return MAMLConfig(
        dataset_name="synthetic_serve", image_height=10, image_width=10,
        image_channels=1, num_classes_per_set=3, num_samples_per_class=1,
        num_target_samples=2, batch_size=2, cnn_num_filters=4,
        num_stages=2, number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2, second_order=False,
        use_multi_step_loss_optimization=False,
        serve_default_deadline_ms=0.0,
        serve_cache_capacity=8, **kw)


def test_alert_rules_path_is_runtime_only_and_defaults_off():
    assert MAMLConfig().alert_rules_path == ""
    from howtotrainyourmamlpytorch_tpu.parallel import aot
    # Pointing a run at a rules file must not invalidate its AOT
    # compile cache — alerting never touches the computation.
    assert "alert_rules_path" in aot._RUNTIME_ONLY_KEYS


def test_engine_alerting_is_structurally_zero_cost_when_off():
    import jax

    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.serve import ServingEngine

    cfg = _tiny_serve_cfg()
    init, _ = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, state, devices=jax.devices()[:1])
    try:
        # The knob at default installs NOTHING: no evaluator object, no
        # gauge series — the _perf/_watchdog structural discipline.
        assert eng._alerts is None
        assert eng.alerts_firing_summary() is None
        assert alerts.FIRING_GAUGE not in eng.registry.snapshot()
    finally:
        eng.close()
    # alert_rules_path is runtime-only, so the same state serves both.
    eng = ServingEngine(_tiny_serve_cfg(alert_rules_path=DEFAULT_RULES),
                        state, devices=jax.devices()[:1])
    try:
        assert eng._alerts is not None
        # Eager registration: an alerting engine's first flush shows 0
        # firing, not an absent series.
        assert eng.registry.snapshot()[alerts.FIRING_GAUGE] == 0.0
        assert eng.alerts_firing_summary() == {"count": 0,
                                               "max_severity": None}
    finally:
        eng.close()


@pytest.mark.slow
def test_serving_bitwise_parity_alerts_on_vs_off(tmp_path):
    """Alerting observes; it must never perturb the computation. Same
    state, same request, alerts on vs off: bitwise-identical logits,
    and only the alerting engine's flush carries the firing gauge."""
    import jax
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.serve import (
        FewShotRequest, ServingEngine)

    cfg_off = _tiny_serve_cfg()
    init, _ = make_model(cfg_off)
    state = init_train_state(cfg_off, init, jax.random.PRNGKey(0))
    rng = np.random.RandomState(7)
    sx = rng.randint(0, 256, (3, 10, 10, 1)).astype(np.uint8)
    sy = (np.arange(3) % 3).astype(np.int32)
    qx = rng.randint(0, 256, (2, 10, 10, 1)).astype(np.uint8)

    logits, flushed = {}, {}
    for key, cfg in (("off", cfg_off),
                     ("on", _tiny_serve_cfg(
                         alert_rules_path=DEFAULT_RULES))):
        eng = ServingEngine(cfg, state, devices=jax.devices()[:1])
        try:
            eng.warmup()
            eng.submit(FewShotRequest(support_x=sx, support_y=sy,
                                      query_x=qx))
            (resp,) = eng.drain()
            assert resp.error is None
            logits[key] = np.asarray(resp.logits)
            jl = JsonlLogger(str(tmp_path / f"events_{key}.jsonl"))
            eng.flush_metrics(jl)
        finally:
            eng.close()
        (row,) = [r for r in read_jsonl(
            str(tmp_path / f"events_{key}.jsonl"))
            if r.get("event") == "metrics"]
        flushed[key] = row["metrics"]
    assert np.array_equal(logits["on"], logits["off"])
    assert flushed["on"].get(alerts.FIRING_GAUGE) == 0.0
    assert alerts.FIRING_GAUGE not in flushed["off"]

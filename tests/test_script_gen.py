"""Tests for launch-script + config-variant generation (utils/script_gen.py).

Reference behavior: one launch .sh per experiment config, executable, using
the ``cd ..; python train_maml_system.py --name_of_args_json_file`` contract;
the generator also stamps config JSONs from a template + grid.
"""

import json
import os
import subprocess

from howtotrainyourmamlpytorch_tpu.utils.script_gen import (
    generate_config_variants, generate_launch_scripts)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_cfg(d, name, **kv):
    with open(os.path.join(d, name + ".json"), "w") as f:
        json.dump({"experiment_name": name, **kv}, f)


def test_generates_one_executable_script_per_config(tmp_path):
    cfg_dir = tmp_path / "experiment_config"
    cfg_dir.mkdir()
    _write_cfg(str(cfg_dir), "exp_a")
    _write_cfg(str(cfg_dir), "exp_b")
    (cfg_dir / "notes.txt").write_text("ignored")

    out = generate_launch_scripts(str(cfg_dir), str(tmp_path / "scripts"))
    names = [os.path.basename(p) for p in out]
    assert names == ["exp_a.sh", "exp_b.sh"]
    for p in out:
        assert os.access(p, os.X_OK)
        text = open(p).read()
        assert "train_maml_system.py" in text
        assert "experiment_config/" in text
        assert '"$@"' in text  # CLI overrides pass through


def test_cluster_variant_resumes_from_latest(tmp_path):
    cfg_dir = tmp_path / "experiment_config"
    cfg_dir.mkdir()
    _write_cfg(str(cfg_dir), "exp_a")
    out = generate_launch_scripts(str(cfg_dir), str(tmp_path / "scripts"),
                                  cluster=True)
    text = open(out[0]).read()
    assert "continue_from_epoch latest" in text
    assert out[0].endswith("_cluster.sh")


def test_config_variant_grid(tmp_path):
    base = {"dataset_name": "omniglot_dataset", "batch_size": 16}
    written = generate_config_variants(
        base,
        grid={"num_classes_per_set": [5, 20],
              "num_samples_per_class": [1, 5]},
        name_template=("omniglot_{num_classes_per_set}-way_"
                       "{num_samples_per_class}-shot"),
        config_dir=str(tmp_path / "cfgs"))
    assert len(written) == 4
    cfg = json.load(open(os.path.join(
        str(tmp_path / "cfgs"), "omniglot_20-way_1-shot.json")))
    assert cfg["num_classes_per_set"] == 20
    assert cfg["num_samples_per_class"] == 1
    assert cfg["batch_size"] == 16
    assert cfg["experiment_name"] == "omniglot_20-way_1-shot"


def test_shipped_scripts_match_shipped_configs():
    """The repo ships experiment_scripts/ regenerated from
    experiment_config/; drift fails here."""
    cfg_dir = os.path.join(REPO_ROOT, "experiment_config")
    scripts_dir = os.path.join(REPO_ROOT, "experiment_scripts")
    expected = {f[:-5] + ".sh" for f in os.listdir(cfg_dir)
                if f.endswith(".json")}
    actual = {f for f in os.listdir(scripts_dir) if f.endswith(".sh")
              and not f.endswith("_cluster.sh")}
    assert expected == actual


def test_shipped_smoke_script_dry_runs():
    """`bash -n` parses every shipped script (no exec)."""
    scripts_dir = os.path.join(REPO_ROOT, "experiment_scripts")
    for f in sorted(os.listdir(scripts_dir)):
        if f.endswith(".sh"):
            subprocess.run(["bash", "-n", os.path.join(scripts_dir, f)],
                           check=True)

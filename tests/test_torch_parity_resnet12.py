"""ResNet-12 numerical parity against a freshly-written PyTorch oracle.

tests/test_torch_parity.py pins the VGG backbone; this file extends the
same oracle methodology to the second backbone (the tiered-imagenet
pod flagship, models/resnet12.py): forward parity and the defining
MAML meta-gradient (both derivative orders) through the residual
blocks' per-step BN + LeakyReLU(0.1) + 1x1-projection-skip structure.
Small geometry, float32, CPU — tolerances reflect f32 conv
reassociation across backends, looser than VGG's because the net is 3x
deeper (13 convs vs 5 layers).
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.meta.inner import (
    Episode, lslr_init, split_fast_slow, task_forward)
from howtotrainyourmamlpytorch_tpu.models import make_model
from test_torch_parity import _to_torch_conv, _to_torch_linear


CFG = MAMLConfig(
    dataset_name="synthetic", image_height=16, image_width=16,
    image_channels=3, num_classes_per_set=3, num_samples_per_class=2,
    num_target_samples=2, batch_size=1, cnn_num_filters=4,
    backbone="resnet12",
    number_of_training_steps_per_iter=2,
    number_of_evaluation_steps_per_iter=2,
    task_learning_rate=0.1, compute_dtype="float32",
    learnable_per_layer_per_step_inner_loop_learning_rate=True,
    per_step_bn_statistics=True)

_BLOCKS, _CONVS = 4, 3
FAST_KEYS = ([f"block{b}_conv{j}" for b in range(_BLOCKS)
              for j in range(_CONVS)]
             + [f"block{b}_skip_conv" for b in range(_BLOCKS)]
             + ["linear"])


def resnet_params_to_torch(params, requires_grad=False):
    out = {}
    for b in range(_BLOCKS):
        for j in range(_CONVS):
            out[f"block{b}_conv{j}"] = _to_torch_conv(
                params[f"block{b}_conv{j}"])
            for leaf in ("gamma", "beta"):
                out[f"block{b}_norm{j}_{leaf}"] = torch.tensor(
                    np.asarray(params[f"block{b}_norm{j}"][leaf]))
        out[f"block{b}_skip_conv"] = _to_torch_conv(
            params[f"block{b}_skip_conv"])
        for leaf in ("gamma", "beta"):
            out[f"block{b}_skip_norm_{leaf}"] = torch.tensor(
                np.asarray(params[f"block{b}_skip_norm"][leaf]))
    out["linear"] = _to_torch_linear(params["linear"])
    if requires_grad:
        for key, val in out.items():
            if isinstance(val, tuple):
                out[key] = tuple(v.requires_grad_() for v in val)
            else:
                val.requires_grad_()
    return out


def _bn(x, params, name, step, cfg):
    return F.batch_norm(
        x, None, None, weight=params[f"{name}_gamma"][step],
        bias=params[f"{name}_beta"][step], training=True,
        momentum=cfg.batch_norm_momentum, eps=cfg.batch_norm_eps)


def torch_resnet_forward(params, x_nhwc, step, cfg=CFG):
    """Oracle: 4 blocks of 3x(3x3 conv pad1 -> per-step BN ->
    LeakyReLU(0.1), last conv's BN un-activated) + 1x1-conv+BN skip,
    LeakyReLU after the add, 2x2 maxpool per block; GAP; linear."""
    x = torch.tensor(np.asarray(x_nhwc).transpose(0, 3, 1, 2)) \
        if not torch.is_tensor(x_nhwc) else x_nhwc
    for b in range(_BLOCKS):
        residual = x
        for j in range(_CONVS):
            w, bias = params[f"block{b}_conv{j}"]
            x = F.conv2d(x, w, bias, padding=1)
            x = _bn(x, params, f"block{b}_norm{j}", step, cfg)
            if j < _CONVS - 1:
                x = F.leaky_relu(x, 0.1)
        w, bias = params[f"block{b}_skip_conv"]
        residual = F.conv2d(residual, w, bias)  # 1x1, no padding
        residual = _bn(residual, params, f"block{b}_skip_norm", step, cfg)
        x = F.leaky_relu(x + residual, 0.1)
        x = F.max_pool2d(x, 2)
    feats = x.mean((2, 3))  # global average pool
    w, bias = params["linear"]
    return F.linear(feats, w, bias)


def _episode(key=0):
    rng = np.random.default_rng(key)
    n, k, t = (CFG.num_classes_per_set, CFG.num_samples_per_class,
               CFG.num_target_samples)
    h, w, c = CFG.image_shape
    return Episode(
        support_x=rng.standard_normal((n * k, h, w, c)).astype(np.float32),
        support_y=np.repeat(np.arange(n, dtype=np.int32), k),
        target_x=rng.standard_normal((n * t, h, w, c)).astype(np.float32),
        target_y=np.repeat(np.arange(n, dtype=np.int32), t))


@pytest.fixture(scope="module")
def model():
    init, apply = make_model(CFG)
    params, bn_state = init(jax.random.PRNGKey(3))
    return apply, params, bn_state


def test_resnet12_forward_parity(model):
    apply, params, bn_state = model
    ep = _episode()
    logits_jax, _ = apply(params, bn_state, jnp.asarray(ep.support_x),
                          jnp.int32(0), True)
    logits_torch = torch_resnet_forward(resnet_params_to_torch(params),
                                        ep.support_x, step=0)
    np.testing.assert_allclose(np.asarray(logits_jax),
                               logits_torch.detach().numpy(),
                               rtol=5e-4, atol=5e-4)


def test_resnet12_fast_slow_partition(model):
    """All 13 convs + linear adapt; all 16 norms are slow (the 'norm' in
    name rule the flat naming was designed for)."""
    _, params, _ = model
    fast, slow = split_fast_slow(CFG, params)
    assert sorted(fast) == sorted(FAST_KEYS)
    assert all("norm" in k for k in slow)
    assert len(slow) == _BLOCKS * (_CONVS + 1)


def _torch_meta_grad(params, ep, second_order):
    tp = resnet_params_to_torch(params, requires_grad=True)
    sx = torch.tensor(np.asarray(ep.support_x).transpose(0, 3, 1, 2))
    tx = torch.tensor(np.asarray(ep.target_x).transpose(0, 3, 1, 2))
    sy = torch.tensor(np.asarray(ep.support_y), dtype=torch.long)
    ty = torch.tensor(np.asarray(ep.target_y), dtype=torch.long)
    fast = {k: tp[k] for k in FAST_KEYS}
    for step in range(CFG.number_of_training_steps_per_iter):
        loss = F.cross_entropy(
            torch_resnet_forward({**tp, **fast}, sx, step), sy)
        leaves = [v for pair in fast.values() for v in pair]
        grads = torch.autograd.grad(loss, leaves,
                                    create_graph=second_order)
        it = iter(grads)
        fast = {k: (w - CFG.task_learning_rate * next(it),
                    b - CFG.task_learning_rate * next(it))
                for k, (w, b) in fast.items()}
    final = CFG.number_of_training_steps_per_iter - 1
    t_loss = F.cross_entropy(
        torch_resnet_forward({**tp, **fast}, tx, final), ty)
    t_loss.backward()
    return float(t_loss.detach()), tp


@pytest.mark.slow  # deep-backbone compile x2 orders (~60s, 1 core)
@pytest.mark.parametrize("second_order", [False, True])
def test_resnet12_meta_gradient_parity(model, second_order):
    """d(target loss after K adapted steps)/dθ0 through the residual
    topology must match torch.autograd with create_graph=second_order."""
    apply, params, bn_state = model
    ep = _episode(7)
    lslr = lslr_init(CFG, split_fast_slow(CFG, params)[0])

    def loss_fn(p):
        return task_forward(
            CFG, apply, p, lslr, bn_state,
            Episode(*(jnp.asarray(f) for f in ep)),
            num_steps=CFG.number_of_training_steps_per_iter,
            second_order=second_order, use_msl=False,
            msl_weights=None).loss

    loss_jax, grads_jax = jax.value_and_grad(loss_fn)(params)
    loss_torch, tp = _torch_meta_grad(params, ep, second_order)
    assert abs(float(loss_jax) - loss_torch) < 5e-4

    checks = [("block0_conv0", "w"), ("block1_conv2", "w"),
              ("block3_skip_conv", "w"), ("linear", "w")]
    for key, leaf in checks:
        got = np.asarray(grads_jax[key][leaf])
        want = tp[key][0].grad.numpy()
        if key != "linear":
            want = want.transpose(2, 3, 1, 0)
        else:
            want = want.T
        np.testing.assert_allclose(
            got, want, rtol=5e-3, atol=5e-4,
            err_msg=f"{key}.{leaf} meta-grad (so={second_order})")
    # Slow-parameter (BN affine) meta-grads flow through adaptation too.
    np.testing.assert_allclose(
        np.asarray(grads_jax["block0_norm0"]["gamma"]),
        tp["block0_norm0_gamma"].grad.numpy(),
        rtol=5e-3, atol=5e-4, err_msg="block0_norm0 gamma meta-grad")

"""Serving-fleet tests (ISSUE 13): router ring, bounded load, shared
L2 tier, rolling-swap controller, engine wiring, subprocess smoke.

Tier-1 keeps to pure/host-side units plus ONE tiny-compile engine
fixture (L2 probe/publish through a real ServingEngine) and ONE
2-replica subprocess smoke through the real ``fleet_bench.py``
entrypoint (budgeted ~15s wall; the N=3 load + rolling hot-swap proof
rides the ``slow`` marker — tier-1 sits at ~660s of the 870s driver
budget and must not grow past it).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.serve.fleet import (
    FleetController, FleetRouter, HashRing, L2AdaptedParamsCache,
    ReplicaLease, advise, read_members, routing_key)
from howtotrainyourmamlpytorch_tpu.serve.fleet import controller as fc
from howtotrainyourmamlpytorch_tpu.serve.fleet import l2cache
from howtotrainyourmamlpytorch_tpu.telemetry import MetricsRegistry
from helpers import _can_bind_localhost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLEET_BENCH = os.path.join(REPO, "scripts", "fleet_bench.py")


def _keys(n=400):
    rng = np.random.RandomState(0)
    out = []
    for i in range(n):
        sx = rng.randint(0, 256, (3, 4, 4, 1)).astype(np.uint8)
        sy = (np.arange(3) % 3).astype(np.int32)
        out.append(routing_key(sx, sy))
    return out


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------

def test_ring_routing_is_deterministic_and_covers_members():
    ring = HashRing([0, 1, 2], vnodes=64)
    keys = _keys(300)
    owners = [ring.primary(k) for k in keys]
    assert owners == [ring.primary(k) for k in keys]  # deterministic
    # Every member owns a nontrivial share (vnodes spread the ring).
    for m in (0, 1, 2):
        assert owners.count(m) > len(keys) * 0.15
    # candidates() lists each member exactly once, primary first.
    for k in keys[:20]:
        c = ring.candidates(k)
        assert sorted(c) == [0, 1, 2] and c[0] == ring.primary(k)


def test_ring_membership_churn_moves_bounded_key_fraction():
    """THE consistent-hashing property: removing (draining) one of N
    replicas re-routes only that replica's keys (~1/N); the survivors'
    keys keep their owner — the L1 working sets the router exists to
    preserve. Adding it back restores the original assignment
    exactly."""
    keys = _keys(400)
    full = HashRing([0, 1, 2, 3], vnodes=64)
    drained = HashRing([0, 1, 2], vnodes=64)
    before = {k: full.primary(k) for k in keys}
    after = {k: drained.primary(k) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    lost_share = sum(1 for k in keys if before[k] == 3)
    # ONLY the drained replica's keys moved...
    assert moved == lost_share
    # ...and that share is ~1/4 of the space (generous tolerance: 400
    # keys over 64 vnodes is a small sample).
    assert 0.10 <= moved / len(keys) <= 0.45
    # Survivors' keys did not reshuffle among themselves.
    for k in keys:
        if before[k] != 3:
            assert after[k] == before[k]
    # Rejoin: bitwise the original assignment.
    rejoined = HashRing([0, 1, 2, 3], vnodes=64)
    assert {k: rejoined.primary(k) for k in keys} == before


# ---------------------------------------------------------------------------
# membership + bounded-load routing
# ---------------------------------------------------------------------------

def _announce(fleet_dir, rid, port=9000, **extra):
    lease = ReplicaLease(str(fleet_dir), rid, interval_s=0.0)
    assert lease.touch({"port": port + rid, **extra}, force=True)
    return lease


def test_membership_from_leases_and_tombstones(tmp_path):
    for rid in (0, 1, 2):
        _announce(tmp_path, rid)
    # Replica 2 is draining: lease alive, tombstone present.
    with open(os.path.join(str(tmp_path), "replica_2.drain"), "w") as f:
        f.write("{}")
    # Replica 1's lease is ancient (dead).
    old = time.time() - 3600
    os.utime(os.path.join(str(tmp_path), "replica_1.lease"), (old, old))
    reg = MetricsRegistry()
    router = FleetRouter(str(tmp_path), stalled_after_s=1.0,
                         dead_after_s=5.0, registry=reg)
    members = router.refresh()
    assert members[0]["state"] == "live" and not members[0]["draining"]
    assert members[1]["state"] == "dead"
    assert members[2]["state"] == "live" and members[2]["draining"]
    # Only replica 0 is routable: live AND not draining.
    assert router.routable == [0]
    assert reg.gauge("fleet/replicas_live").value == 1
    assert reg.gauge("fleet/replicas_draining").value == 1
    # Payloads survive the round trip (the port the router dials).
    assert members[0]["payload"]["port"] == 9000


def test_torn_lease_payload_degrades_to_age_only(tmp_path):
    _announce(tmp_path, 0)
    path = os.path.join(str(tmp_path), "replica_0.lease")
    with open(path, "w") as f:
        f.write('{"port": 90')  # torn JSON
    members = read_members(str(tmp_path))
    # Still a member (mtime is fresh) — payload just absent.
    assert members[0]["payload"] is None
    assert members[0]["age"] < 60


def test_bounded_load_spills_hot_key_and_complete_releases(tmp_path):
    for rid in (0, 1, 2):
        _announce(tmp_path, rid)
    reg = MetricsRegistry()
    router = FleetRouter(str(tmp_path), load_factor=1.25,
                         stalled_after_s=60.0, dead_after_s=120.0,
                         registry=reg)
    router.refresh()
    key = _keys(1)[0]
    primary = router.ring.primary(key)
    # One hot tenant: repeated routes without completions must NOT all
    # land on the primary — bounded load caps it and spills to the
    # next ring position.
    picks = [router.route(key) for _ in range(12)]
    assert picks[0] == primary
    assert len(set(picks)) >= 2
    assert reg.counter("fleet/router_spills").value > 0
    assert max(router.in_flight(r) for r in (0, 1, 2)) < 12
    # Completions release capacity: the key goes back to its primary.
    for r in picks:
        router.complete(r)
    assert router.route(key) == primary
    router.complete(primary)


def test_route_with_no_live_replica_counts_and_returns_none(tmp_path):
    reg = MetricsRegistry()
    router = FleetRouter(str(tmp_path), registry=reg)
    router.refresh()
    assert router.route("deadbeef") is None
    assert reg.counter("fleet/router_no_replica").value == 1


# ---------------------------------------------------------------------------
# L2 adapted-params tier
# ---------------------------------------------------------------------------

def _tree():
    return ({"conv0": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                       "b": np.zeros(4, np.float32)},
             "head": [np.float32(1.5), np.ones((2, 2), np.float16)]},
            {"bn": {"mean": np.linspace(0, 1, 5).astype(np.float64),
                    "tuple": (np.int32(7),)}})


def test_l2_round_trip_preserves_trees_and_dtypes(tmp_path):
    reg = MetricsRegistry()
    l2 = L2AdaptedParamsCache(str(tmp_path), registry=reg)
    fast, bn = _tree()
    assert l2.put("a" * 64, fast, bn)
    entry = l2.get("a" * 64)
    assert entry is not None
    got_fast, got_bn = entry["fast"], entry["bn_state"]
    np.testing.assert_array_equal(got_fast["conv0"]["w"],
                                  fast["conv0"]["w"])
    assert got_fast["conv0"]["w"].dtype == np.float32
    assert got_fast["head"][1].dtype == np.float16
    assert got_bn["bn"]["mean"].dtype == np.float64
    assert isinstance(got_bn["bn"]["tuple"], tuple)
    assert (l2.hits, l2.misses, l2.errors) == (1, 0, 0)
    assert reg.counter(l2cache.PUBLISHES).value == 1
    # A plain absent key is a counted MISS, not an error.
    assert l2.get("b" * 64) is None
    assert (l2.misses, l2.errors) == (1, 0)


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "magic"])
def test_l2_damage_is_counted_fail_soft_miss(tmp_path, damage):
    """The PR 3 cache_errors discipline, tier 2: truncation, a flipped
    payload bit, or a foreign file all read as a counted miss — never
    a wrong answer, never an exception — and the damaged file is
    quarantined so repeats don't re-pay the verify-and-fail."""
    reg = MetricsRegistry()
    l2 = L2AdaptedParamsCache(str(tmp_path), registry=reg)
    fast, bn = _tree()
    key = "c" * 64
    assert l2.put(key, fast, bn)
    path = l2.path(key)
    blob = open(path, "rb").read()
    if damage == "truncate":
        open(path, "wb").write(blob[:len(blob) // 2])
    elif damage == "bitflip":
        flipped = bytearray(blob)
        flipped[len(flipped) - 8] ^= 0x10  # payload byte, not header
        open(path, "wb").write(bytes(flipped))
    else:
        open(path, "wb").write(b"NOTL2AAA" + blob[8:])
    assert l2.get(key) is None
    assert l2.errors == 1 and l2.misses == 1
    assert reg.counter(l2cache.ERRORS).value == 1
    assert not os.path.exists(path)  # quarantined
    # The tier keeps working after damage.
    assert l2.put(key, fast, bn) and l2.get(key) is not None


def test_l2_gc_by_recency_and_stale_tmp_sweep(tmp_path):
    l2 = L2AdaptedParamsCache(str(tmp_path), max_entries=100)
    fast, bn = _tree()
    keys = [f"{i:064d}" for i in range(5)]
    now = time.time()
    for i, k in enumerate(keys):
        assert l2.put(k, fast, bn)
        # Distinct mtimes (filesystem mtime granularity beats a sleep).
        os.utime(l2.path(k), (now + i, now + i))
    assert l2.gc(max_entries=3) == 2
    survivors = {k for k, _ in l2.entries()}
    assert survivors == set(keys[2:])  # oldest-recency entries died
    assert l2.evictions == 2
    # A GET refreshes recency (mtime bump), so a later GC keeps the
    # recently-USED entry over a recently-WRITTEN-but-idle one.
    assert l2.get(keys[2]) is not None
    os.utime(l2.path(keys[2]), (now + 10, now + 10))
    assert l2.gc(max_entries=2) == 1
    assert keys[2] in {k for k, _ in l2.entries()}
    assert keys[3] not in {k for k, _ in l2.entries()}
    # Stale tmp sweep: old tmps die, fresh ones (a publish in flight
    # on another replica) survive.
    stale = os.path.join(str(tmp_path), "x.l2.tmp.999")
    fresh = os.path.join(str(tmp_path), "y.l2.tmp.998")
    open(stale, "wb").write(b"x")
    open(fresh, "wb").write(b"y")
    os.utime(stale, (now - 7200, now - 7200))
    assert l2.sweep() == 1
    assert not os.path.exists(stale) and os.path.exists(fresh)


# ---------------------------------------------------------------------------
# rolling-swap controller
# ---------------------------------------------------------------------------

class _FakeFleet:
    """Membership snapshot the controller reads; tests mutate payloads
    to play the replica side of the protocol."""

    def __init__(self, rids):
        self.members = {r: {"state": "live", "age": 0.0, "draining": False,
                            "payload": {"version": 1, "stats": {}}}
                        for r in rids}

    def __call__(self):
        return {r: dict(rec) for r, rec in self.members.items()}


def test_rolling_swap_happy_path(tmp_path):
    reg = MetricsRegistry()
    fleet = _FakeFleet([0, 1, 2])
    ctl = FleetController(str(tmp_path), fleet, registry=reg)
    doc = ctl.start_rollout(2)
    assert doc["state"] == fc.ROLLING and doc["replicas"] == [0, 1, 2]
    # Replica 0 is tombstoned; nobody else is.
    assert os.path.exists(ctl._drain_path(0))
    assert not os.path.exists(ctl._drain_path(1))
    # Not acked yet -> still draining, still tombstoned.
    assert ctl.tick()["index"] == 0
    # Replica 0 acks by reporting the target version in its lease.
    fleet.members[0]["payload"] = {"version": 2}
    doc = ctl.tick()
    assert doc["index"] == 1
    assert not os.path.exists(ctl._drain_path(0))  # rejoined
    assert os.path.exists(ctl._drain_path(1))      # next in line
    fleet.members[1]["payload"] = {"version": 2}
    fleet.members[2]["payload"] = {"version": 2}
    assert ctl.tick()["index"] == 2
    doc = ctl.tick()
    assert doc["state"] == fc.DONE
    assert not any(os.path.exists(ctl._drain_path(r)) for r in (0, 1, 2))
    assert reg.counter(fc.SWAPS_COUNTER).value == 1
    assert reg.counter(fc.SWAP_STEPS_COUNTER).value == 3
    assert reg.counter(fc.HALTS_COUNTER).value == 0


def test_rolling_swap_halts_on_canary_fail_and_pins_fleet_wide(tmp_path):
    """THE safety property: one replica's canary rejection stops the
    rollout for the WHOLE fleet — the version is pinned in the rollout
    record (replicas poll it and refuse locally), the tombstone is
    lifted so the replica rejoins on its live version, and a restarted
    rollout of the same version is refused outright."""
    reg = MetricsRegistry()
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet, registry=reg)
    ctl.start_rollout(2)
    fleet.members[0]["payload"] = {"version": 1, "swap_failed": 2}
    doc = ctl.tick()
    assert doc["state"] == fc.HALTED
    assert doc["halt_replica"] == 0 and 2 in doc["rejected"]
    assert not os.path.exists(ctl._drain_path(0))  # rejoined, un-swapped
    assert not os.path.exists(ctl._drain_path(1))  # never touched
    assert reg.counter(fc.HALTS_COUNTER).value == 1
    assert reg.counter(fc.SWAPS_COUNTER).value == 0
    # The pin is durable: the same version never rolls again.
    assert ctl.start_rollout(2)["state"] == fc.HALTED
    # A NEW version starts a fresh rollout, pin list intact.
    doc = ctl.start_rollout(3)
    assert doc["state"] == fc.ROLLING and doc["rejected"] == [2]


def test_rolling_swap_halts_when_replica_dies_mid_swap(tmp_path):
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet)
    ctl.start_rollout(2)
    fleet.members[0]["state"] = "dead"
    doc = ctl.tick()
    assert doc["state"] == fc.HALTED
    assert doc["halt_reason"] == "replica died mid-swap"


def test_rolling_swap_stall_halts_without_pinning(tmp_path):
    """A LIVE replica that can never decide (target retired from the
    registry mid-rollout) must not hold the fleet at N-1 forever: the
    stall backstop halts — WITHOUT pinning the version (a stall is not
    a canary verdict), so the same rollout can be retried."""
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet, step_stall_timeout_s=30)
    ctl.start_rollout(2)
    # Backdate the rollout record: 40s of no decision.
    doc = ctl.read_rollout()
    doc["updated_ts"] = time.time() - 40.0
    fc._atomic_write_json(ctl.rollout_path, doc)
    doc = ctl.tick()
    assert doc["state"] == fc.HALTED
    assert doc["halt_reason"] == "rollout step stalled"
    assert 2 not in doc["rejected"]                 # not pinned
    assert not os.path.exists(ctl._drain_path(0))  # rejoined
    # Retry is allowed (unlike a canary-fail pin).
    assert ctl.start_rollout(2)["state"] == fc.ROLLING


def test_rolling_swap_tick_heals_missing_tombstone(tmp_path):
    """Crash-recovery contract: the rollout record is the truth; a
    missing drain tombstone (controller died between the record write
    and the drain, or stray cleanup) is re-written by tick()."""
    fleet = _FakeFleet([0, 1])
    ctl = FleetController(str(tmp_path), fleet)
    ctl.start_rollout(2)
    os.remove(ctl._drain_path(0))
    ctl.tick()
    assert os.path.exists(ctl._drain_path(0))


def test_router_forgets_in_flight_across_replica_restart(tmp_path):
    """A replica SIGKILLed with requests in flight and restarted
    BEFORE any refresh observed it dead must not keep its phantom
    in-flight counts (the restart shows up as a changed lease pid) —
    they would skew the bounded-load cap forever."""
    leases = {rid: _announce(tmp_path, rid) for rid in (0, 1)}
    router = FleetRouter(str(tmp_path), stalled_after_s=60.0,
                         dead_after_s=120.0)
    router.refresh()
    key = _keys(1)[0]
    rid = router.route(key)
    assert router.in_flight(rid) == 1
    # "Restart": same replica id announces with a different pid.
    path = os.path.join(str(tmp_path), f"replica_{rid}.lease")
    doc = json.load(open(path))
    doc["pid"] = doc["pid"] + 1
    with open(path, "w") as f:
        json.dump(doc, f)
    router.refresh()
    assert router.in_flight(rid) == 0
    assert leases  # keep lease objects alive (no tmp cleanup races)


def test_avoid_fleet_rejected_rolls_back_at_startup(tmp_path):
    """A replica that BOOTS on a fleet-rejected version (restart after
    a halted rollout: LATEST is the banned checkpoint) must pin the
    rejected list and roll back to the newest non-rejected live
    version — without a canary (it is the previously-serving
    known-good)."""
    from howtotrainyourmamlpytorch_tpu.ckpt.registry import ModelRegistry
    from howtotrainyourmamlpytorch_tpu.serve.fleet.replica import (
        avoid_fleet_rejected)

    reg_dir = str(tmp_path / "ckpt")
    registry = ModelRegistry(reg_dir)
    registry.publish(tag="0", epoch=0, val_acc=0.5, fingerprint=111)
    registry.publish(tag="1", epoch=1, val_acc=0.6, fingerprint=222)
    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(fleet_dir)
    with open(os.path.join(fleet_dir, "ROLLOUT.json"), "w") as f:
        json.dump({"state": "halted", "version": 2, "rejected": [2]}, f)

    class _StubEngine:
        def __init__(self):
            self._model_version = 2      # booted on the banned bytes
            self._registry_dir = reg_dir
            self.pinned = set()
            self.adopted = None

        def pin_rejected(self, v):
            self.pinned.add(v)

        def load_registry_version(self, rec):
            return {"loaded": rec["tag"]}

        def adopt_version(self, rec, state):
            self.adopted = (rec["version"], state)
            self._model_version = rec["version"]

    eng = _StubEngine()
    assert avoid_fleet_rejected(eng, fleet_dir) == 1
    assert eng.pinned == {2}
    assert eng.adopted == (1, {"loaded": "0"})
    # Booted on a GOOD version: pins only, no rollback.
    eng2 = _StubEngine()
    eng2._model_version = 1
    assert avoid_fleet_rejected(eng2, fleet_dir) is None
    assert eng2.adopted is None and eng2.pinned == {2}


def test_controller_signals_and_advise(tmp_path):
    reg = MetricsRegistry()
    fleet = _FakeFleet([0, 1])
    fleet.members[0]["payload"] = {"stats": {
        "queue_depth": 70, "p95_ms": 250.0, "cache_hit_frac": 0.9,
        "l2_hits": 5, "l2_misses": 2, "l2_errors": 0, "responses": 10}}
    fleet.members[1]["payload"] = {"stats": {
        "queue_depth": 10, "p95_ms": 900.0, "cache_hit_frac": 0.4,
        "l2_hits": 1, "l2_misses": 1, "l2_errors": 1, "responses": 4}}
    ctl = FleetController(str(tmp_path), fleet, registry=reg)
    sig = ctl.publish_signals()
    assert sig["queue_depth_total"] == 80
    assert sig["p95_ms_max"] == 900.0
    assert sig["cache_hit_frac_min"] == 0.4
    # Aggregates publish under DISTINCT agg_* names so a log carrying
    # both replica flushes and controller flushes never double-counts.
    assert reg.counter("fleet/agg_l2_hits").value == 6
    assert reg.counter("fleet/agg_l2_errors").value == 1
    # Replica 0 restarts (its counters reset): only growth contributes.
    fleet.members[0]["payload"]["stats"].update(l2_hits=2)
    ctl.publish_signals()
    assert reg.counter("fleet/agg_l2_hits").value == 8  # + reset seg 2
    # 40 queued per live replica -> scale up; idle fleet -> scale down.
    assert advise(sig, live=2) == "scale_up"
    assert advise({"queue_depth_total": 0, "p95_ms_max": 50.0},
                  live=2) == "scale_down"
    assert advise({"queue_depth_total": 0, "p95_ms_max": 50.0},
                  live=1) == "hold"  # never below the floor


def test_fleet_config_knobs_validate_and_derive():
    """The fleet_* knobs' contract: validation rejects nonsense, and
    the effective_* thresholds derive from the lease cadence with the
    cluster rules (3x/6x; dead never below stalled) — the same
    derivation the jax-free bench driver mirrors."""
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    cfg = MAMLConfig(dataset_name="fleet_cfg",
                     fleet_lease_interval_s=0.5)
    assert cfg.effective_fleet_stalled_s == pytest.approx(1.5)
    assert cfg.effective_fleet_dead_s == pytest.approx(3.0)
    explicit = cfg.replace(fleet_replica_stalled_s=4.0,
                           fleet_replica_dead_s=2.0)
    assert explicit.effective_fleet_dead_s == 4.0  # never below stalled
    for bad in (dict(fleet_load_factor=0.9), dict(fleet_vnodes=0),
                dict(serve_l2_max_entries=0),
                dict(fleet_lease_interval_s=0.0),
                dict(fleet_replica_dead_s=-1.0)):
        with pytest.raises(ValueError):
            MAMLConfig(dataset_name="fleet_cfg", **bad)


# ---------------------------------------------------------------------------
# engine wiring: L2 probe on L1 miss, publish on adapt
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path, **kw):
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    kw.setdefault("serve_buckets", ((3, 4),))
    kw.setdefault("serve_batch_tasks", 2)
    return MAMLConfig(
        dataset_name="synthetic_fleet_engine", image_height=10,
        image_width=10, image_channels=1, num_classes_per_set=3,
        num_samples_per_class=1, num_target_samples=2, batch_size=2,
        cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2, second_order=False,
        use_multi_step_loss_optimization=False,
        serve_default_deadline_ms=0.0, serve_cache_capacity=8,
        serve_l2_dir=os.path.join(str(tmp_path), "l2"), **kw)


def _req(s=3, q=2, seed=0):
    from howtotrainyourmamlpytorch_tpu.serve import FewShotRequest
    rng = np.random.RandomState(seed)
    return FewShotRequest(
        support_x=rng.randint(0, 256, (s, 10, 10, 1)).astype(np.uint8),
        support_y=(np.arange(s) % 3).astype(np.int32),
        query_x=rng.randint(0, 256, (q, 10, 10, 1)).astype(np.uint8))


@pytest.fixture(scope="module")
def l2_engine(tmp_path_factory):
    import jax
    from howtotrainyourmamlpytorch_tpu.meta.outer import init_train_state
    from howtotrainyourmamlpytorch_tpu.models import make_model
    from howtotrainyourmamlpytorch_tpu.serve import ServingEngine
    tmp = tmp_path_factory.mktemp("fleet_engine")
    cfg = _tiny_cfg(tmp)
    init, _ = make_model(cfg)
    state = init_train_state(cfg, init, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, state, devices=jax.devices()[:1])
    eng.warmup()
    yield eng
    eng.close()


def test_engine_l2_probe_publish_and_tiers(l2_engine):
    """The cross-replica guarantee, single-process form: an adapt
    publishes to L2; with the L1 entry gone (a restart, an eviction, a
    DIFFERENT replica), the repeat is an L2 hit — cache_tier says so,
    and the adapt executable is NOT dispatched."""
    eng = l2_engine
    r1 = _req(seed=50)
    eng.submit(r1)
    (resp1,) = eng.drain()
    assert resp1.cache_tier is None and not resp1.cache_hit
    # Publishes ride the background writer thread (off the response
    # path); flush gives the test visibility.
    assert eng.l2_flush()
    assert eng.l2.publishes >= 1  # the adapt published fleet-wide
    # L1 hit: tier says l1.
    eng.submit(_req(seed=50))
    (resp2,) = eng.drain()
    assert resp2.cache_tier == "l1" and resp2.cache_hit
    # Simulate "another replica": clear the L1; the L2 absorbs the
    # repeat without an adapt dispatch.
    eng.cache.clear()
    adapt_before = eng.adapt_invocations
    eng.submit(_req(seed=50))
    (resp3,) = eng.drain()
    assert resp3.cache_tier == "l2" and resp3.cache_hit
    assert eng.adapt_invocations == adapt_before
    assert resp3.predictions.shape == resp1.predictions.shape
    # The L2 hit back-filled the L1: the next repeat never leaves the
    # process.
    eng.submit(_req(seed=50))
    (resp4,) = eng.drain()
    assert resp4.cache_tier == "l1"


def test_engine_l2_damage_degrades_to_adapt(l2_engine):
    """A damaged L2 entry must degrade the request to the adapt path
    (counted), never to a wrong answer or a crash."""
    eng = l2_engine
    r = _req(seed=60)
    eng.submit(r)
    (first,) = eng.drain()
    assert first.cache_tier is None
    assert eng.l2_flush()  # async publish must land before we damage it
    eng.cache.clear()
    # Corrupt every L2 entry on disk.
    for key, _ in eng.l2.entries():
        with open(eng.l2.path(key), "r+b") as f:
            f.seek(20)
            f.write(b"\xff\xff\xff\xff")
    errors_before = eng.l2.errors
    adapt_before = eng.adapt_invocations
    eng.submit(_req(seed=60))
    (resp,) = eng.drain()
    assert resp.error is None
    assert resp.cache_tier is None           # re-adapted
    assert eng.adapt_invocations == adapt_before + 1
    assert eng.l2.errors > errors_before     # counted fail-soft


def test_l1_cache_bytes_gauge_and_eviction_counter(l2_engine):
    """Satellite: the L1 tracks approximate resident bytes and the
    engine mirrors them (serve/cache_bytes) next to the eviction
    counter — the autoscale signal pair."""
    eng = l2_engine
    assert len(eng.cache) > 0
    assert eng.cache.approx_bytes > 0
    eng._mirror_cache_counters()
    assert eng.registry.gauge("serve/cache_bytes").value == \
        eng.cache.approx_bytes
    assert eng.registry.counter("serve/cache_evictions").value >= 0


def test_lru_approx_bytes_tracks_put_evict_clear():
    from howtotrainyourmamlpytorch_tpu.serve.cache import (
        AdaptedParamsLRU, entry_nbytes)
    lru = AdaptedParamsLRU(capacity=2)
    a = {"w": np.zeros((4, 4), np.float32)}          # 64 bytes
    b = [np.zeros(8, np.float64), (np.zeros(2, np.int32),)]  # 72 bytes
    assert entry_nbytes(a) == 64 and entry_nbytes(b) == 72
    lru.put("a", a)
    lru.put("b", b)
    assert lru.approx_bytes == 136
    lru.put("c", a)  # evicts "a"
    assert lru.approx_bytes == 136 - 64 + 64
    assert lru.evictions == 1
    lru.clear()
    assert lru.approx_bytes == 0


# ---------------------------------------------------------------------------
# subprocess smoke + slow proof (the real fleet_bench.py entrypoint)
# ---------------------------------------------------------------------------

needs_sockets = pytest.mark.skipif(
    not _can_bind_localhost(),
    reason="fleet replicas serve over localhost sockets, which this "
           "sandbox cannot bind (the fleet_bench skip-artifact path "
           "covers the CLI side)")


def _run_fleet_bench(args, timeout):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, FLEET_BENCH] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert lines, f"no artifact line\n{proc.stdout}\n{proc.stderr}"
    return proc.returncode, json.loads(lines[-1])


@needs_sockets
def test_fleet_bench_quick_smoke_two_replicas(tmp_path):
    """Tier-1 acceptance smoke: 2 real replica subprocesses + the
    jax-free router through the REAL fleet_bench.py entrypoint — zero
    dropped requests, the L2 migration verdict, and the artifact
    schema the BENCH rounds consume."""
    rc, art = _run_fleet_bench(
        ["--quick", "--out", str(tmp_path / "fb")], timeout=300)
    assert art["metric"] == "fleet_bench"
    assert art["status"] == "ok", art
    assert rc == 0
    assert art["replicas"] == 2
    assert art["zero_dropped"] is True
    assert art["fleet"]["responses_ok"] == art["requests"] > 0
    assert art["fleet"]["dropped"] == 0
    # The migration leg proved the shared tier: tenant re-served from
    # L2 on the OTHER replica with zero adapt dispatches there.
    assert art["migration"]["ok"] is True
    assert art["migration"]["second_tier"] == "l2"
    assert art["migration"]["target_adapt_delta"] == 0
    assert art["migration"]["from_replica"] != art["migration"][
        "to_replica"]
    # Schema stability with serve_bench's single-engine artifact.
    for key in ("fleet_qps", "fleet_l2_hit_frac", "fleet_rolling_swaps",
                "fleet_rolling_swap_halts", "fleet_router_spills"):
        assert key in art


@pytest.mark.slow
@needs_sockets
def test_fleet_bench_full_proof_three_replicas(tmp_path):
    """The ISSUE 13 acceptance leg (slow: ~6 min on this box): 3
    replicas sustain >= 3x single-engine QPS with ZERO dropped
    requests through a mid-load rolling hot-swap, and the drained
    tenant is an L2 hit on its new replica — all asserted from the
    artifact."""
    rc, art = _run_fleet_bench(
        ["--out", str(tmp_path / "fb"), "--requests", "300"],
        timeout=560)
    assert art["status"] == "ok", art
    assert rc == 0
    assert art["zero_dropped"] is True
    assert art["fleet"]["dropped"] == 0 and art["single"]["dropped"] == 0
    assert art["fleet_speedup_vs_single"] >= 3.0
    assert art["rollout"]["state"] == "done"
    assert art["fleet_rolling_swaps"] == 1
    assert art["fleet_rolling_swap_halts"] == 0
    assert art["migration"]["ok"] is True


def test_serve_bench_exposes_fleet_keys_as_null():
    """Satellite: the single-engine artifact carries every fleet_* key
    (null) so BENCH comparisons stay schema-stable across PRs. Pinned
    at the source level (running serve_bench is compile-heavy; the
    keys live in one dict literal)."""
    import ast
    src = open(os.path.join(REPO, "scripts", "serve_bench.py")).read()
    tree = ast.parse(src)
    keys = {getattr(k, "value", None)
            for node in ast.walk(tree) if isinstance(node, ast.Dict)
            for k in node.keys}
    for key in ("fleet_replicas", "fleet_qps", "fleet_speedup_vs_single",
                "fleet_l2_hit_frac", "fleet_rolling_swaps",
                "fleet_rolling_swap_halts", "fleet_router_spills",
                "fleet_trace_count", "fleet_trace_linked_frac",
                "fleet_trace_dominant_tier", "fleet_trace_tier_seconds",
                "fleet_slo_burn_rate", "fleet_slo_tenants",
                "fleet_shed_count", "fleet_failover_count",
                "fleet_restarts",
                # ISSUE 19 traffic-lab keys (traffic_replay.py fills
                # them; both bench artifacts carry them as null).
                "traffic_p95_ms", "traffic_slo_held",
                "traffic_canary_weight_final", "traffic_cb_groups",
                # ISSUE 20 alert keys (chaos_fleet.py fills them; the
                # benches carry them as honestly-null).
                "alerts_fired", "alerts_resolved",
                "alerts_active_final"):
        assert key in keys, f"serve_bench artifact lost {key}"

    fleet_src = open(os.path.join(REPO, "scripts", "fleet_bench.py")).read()
    fleet_keys = {getattr(k, "value", None)
                  for node in ast.walk(ast.parse(fleet_src))
                  if isinstance(node, ast.Dict) for k in node.keys}
    for key in ("traffic_p95_ms", "traffic_slo_held",
                "traffic_canary_weight_final", "traffic_cb_groups",
                "alerts_fired", "alerts_resolved",
                "alerts_active_final"):
        assert key in fleet_keys, f"fleet_bench artifact lost {key}"
